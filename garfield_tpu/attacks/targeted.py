"""Targeted (semantic) poisoning: label flips and pixel-trigger backdoors.

Every attack in ``attacks/__init__.py`` and ``attacks/adaptive.py`` is an
UNTARGETED divergence attack: the adversary wants the aggregate far from
the honest mean, and the whole defense stack — Gram distances, suspicion
scores, the escalation ladder — keys on exactly that displacement. A
TARGETED adversary wants something the divergence audit cannot see: a
specific misclassification (source class read as target class), or a
backdoor (any input carrying a small trigger pattern read as the target
class), while global accuracy — and therefore the aggregate's distance to
the honest mean — stays essentially untouched. The colluding cohort
poisons its own BATCHES, not its gradient algebra:

  - ``labelflip``: every cohort sample of class ``source`` is relabeled
    ``target`` (``poison_frac`` of them). The resulting gradient is a
    perfectly honest gradient *of the poisoned task* — in-distribution,
    inside the honest spread for most coordinates, invisible to a
    divergence test (the blindness the per-class eval telemetry of
    TELEMETRY.md v8 exists to expose).
  - ``backdoor``: ``poison_frac`` of the cohort's samples get a constant
    TRIGGER stamped into a fixed input region (a corner patch on image
    tasks, the leading features on flat/tabular tasks, a fixed token
    prefix on integer sequence tasks) and the label set
    to ``target`` — BadNets-style. Success is measured as the
    attack-success-rate (ASR): the fraction of non-target test inputs
    that flip to ``target`` once the trigger is stamped
    (``parallel.targeted_eval``), not as top-1 accuracy.

One config + two poisoners serve every deployment scale: the traced
``poison_batch`` rewrites the Byzantine slots' (x, y) device batches
inside the jit'd step (the on-mesh topologies), and the same function on
numpy arrays poisons a real Byzantine process's own shard
(apps/cluster.py workers and LEARN nodes). Honest slots' batches are
returned untouched, and ``attack=None`` paths never call in here — the
defense-off bitwise contract is structural.
"""

import dataclasses

import numpy as np

__all__ = [
    "TARGETED_ATTACKS",
    "TargetedConfig",
    "is_targeted",
    "configure",
    "poison_batch",
    "apply_trigger",
]

TARGETED_ATTACKS = ("labelflip", "backdoor")


def is_targeted(attack):
    """True when ``attack`` names a targeted (data-poisoning) attack."""
    return isinstance(attack, str) and attack in TARGETED_ATTACKS


@dataclasses.dataclass(frozen=True)
class TargetedConfig:
    """Static plan of one targeted attack (both deployment scales).

    ``source``/``target`` are class ids; ``labelflip`` relabels source
    samples as target, ``backdoor`` stamps the trigger and relabels ANY
    poisoned sample as target (source is ignored there — a backdoor wants
    every triggered input misread). ``poison_frac`` is the fraction of
    each cohort batch poisoned (1.0 for labelflip's classic form: every
    source sample flips). ``trigger_value`` is the constant written into
    the trigger region; ``trigger_size`` its side length (pixels on image
    tasks, features on flat inputs). ``binary`` marks the single-logit
    (pima) task, where the only classes are {0, 1} and the per-class
    telemetry degrades to the binary confusion (reported once via the
    ``attack_fallback``-style event — see ``configure``).
    """

    attack: str
    source: int
    target: int
    poison_frac: float = 1.0
    trigger_value: float = 2.5
    trigger_size: int = 2
    trigger_token: int = None
    binary: bool = False

    def __post_init__(self):
        if self.attack not in TARGETED_ATTACKS:
            raise ValueError(
                f"unknown targeted attack {self.attack!r}; available: "
                f"{TARGETED_ATTACKS}"
            )
        if self.source == self.target:
            raise ValueError(
                f"targeted attack needs source != target, got both "
                f"{self.source}"
            )
        if not (0.0 < self.poison_frac <= 1.0):
            raise ValueError(
                f"poison_frac must be in (0, 1], got {self.poison_frac}"
            )
        if self.trigger_size < 1:
            raise ValueError(
                f"trigger_size must be >= 1, got {self.trigger_size}"
            )
        if self.trigger_token is not None and self.trigger_token < 0:
            raise ValueError(
                f"trigger_token must be a token id >= 0, got "
                f"{self.trigger_token}"
            )


def configure(attack, params, *, num_classes):
    """``TargetedConfig`` from an attack name + CLI ``attack_params``.

    Recognized params (all optional): ``source`` (default 0), ``target``
    (default 1), ``poison_frac``, ``trigger_value``, ``trigger_size``,
    ``trigger_token`` (the token id stamped on integer-token batches —
    see ``apply_trigger``).
    ``num_classes`` is the model head's class count
    (``models.num_classes_dict``); 1 marks the binary single-logit task
    (pima), whose only classes are {0, 1} — a source/target outside that
    range is refused loudly, and the binary degradation of the per-class
    telemetry is reported ONCE via ``note_attack_fallback`` instead of
    silently no-opping (the satellite contract).
    """
    if not is_targeted(attack):
        raise ValueError(f"{attack!r} is not a targeted attack")
    p = dict(params or {})
    source = int(p.get("source", 0))
    target = int(p.get("target", 1))
    binary = int(num_classes) <= 1
    hi = 2 if binary else int(num_classes)
    for name, cls in (("source", source), ("target", target)):
        if not (0 <= cls < hi):
            raise ValueError(
                f"targeted {name} class {cls} out of range [0, {hi}) for "
                f"this dataset"
            )
    if binary:
        from . import note_attack_fallback

        note_attack_fallback(
            attack, path="binary",
            why="dataset has no multi-class labels plumbed (binary "
                "surrogate); classes restricted to {0, 1} and the "
                "per-class eval digest degrades to the binary confusion",
        )
    return TargetedConfig(
        attack=attack,
        source=source,
        target=target,
        poison_frac=float(p.get("poison_frac", 1.0)),
        trigger_value=float(p.get("trigger_value", 2.5)),
        trigger_size=int(p.get("trigger_size", 2)),
        trigger_token=(
            None if p.get("trigger_token") is None
            else int(p["trigger_token"])
        ),
        binary=binary,
    )


def _xp_of(x):
    import jax

    if isinstance(x, jax.Array):
        import jax.numpy as jnp

        return jnp
    return np


def apply_trigger(cfg, x):
    """Stamp the trigger pattern into a batch of inputs.

    Image batches (..., H, W, C) get a ``trigger_size`` x ``trigger_size``
    corner patch set to ``trigger_value`` (every channel); flat batches
    (..., D) get their leading ``trigger_size`` features set. INTEGER
    batches are token sequences (..., T): the leading ``trigger_size``
    positions become a fixed token PREFIX — ``trigger_token`` if set,
    else ``round(trigger_value)`` (2.5 -> token 2, which the copytask
    distractor slots never contain) — the token-space BadNets analogue.
    The integer test runs FIRST: a stacked token batch (slots, b, T) is
    ndim 3 but is not an image. Works on numpy arrays AND traced jnp
    values (pure indexing writes), preserving dtype — the same function
    stamps the cohort's train batches and the evaluation probes
    (``parallel.targeted_eval``), so train-time and test-time triggers
    can never drift apart.
    """
    xp = _xp_of(x)
    t = cfg.trigger_size
    if np.issubdtype(np.dtype(x.dtype), np.integer):
        tok = (
            cfg.trigger_token if cfg.trigger_token is not None
            else int(round(cfg.trigger_value))
        )
        t = min(t, x.shape[-1])
        if xp is np:
            out = x.copy()
            out[..., :t] = x.dtype.type(tok)
            return out
        return x.at[..., :t].set(tok).astype(x.dtype)
    v = x.dtype.type(cfg.trigger_value) if xp is np else cfg.trigger_value
    if x.ndim >= 3:
        # (..., H, W, C) image layout: bottom-right corner patch.
        if xp is np:
            out = x.copy()
            out[..., -t:, -t:, :] = v
            return out
        return x.at[..., -t:, -t:, :].set(v).astype(x.dtype)
    # Flat/tabular layout: the leading features are the trigger slots.
    t = min(t, x.shape[-1])
    if xp is np:
        out = x.copy()
        out[..., :t] = v
        return out
    return x.at[..., :t].set(v).astype(x.dtype)


def _poison_mask(cfg, n, seed, step=None):
    """Deterministic per-sample poison mask: the first
    ``round(poison_frac * n)`` positions of a seeded permutation.

    With ``step`` the permutation is drawn per STEP from the composite
    ``(seed, step)`` seed, so a partially-poisoning cohort rotates its
    poisoned subset across steps like a real poisoner re-sampling its
    batch — and, because ``step`` also drives the traced twin below,
    every replay (and every colluder) agrees. ``poison_frac`` 1.0 never
    draws (the all-ones mask is static), which is what keeps the
    poison_frac=1 trajectories bitwise unchanged across this seeding.
    """
    k = int(round(cfg.poison_frac * n))
    if k >= n:
        return np.ones(n, bool)
    rng = np.random.default_rng(
        seed if step is None else (int(seed), int(step))
    )
    mask = np.zeros(n, bool)
    mask[rng.permutation(n)[:k]] = True
    return mask


def _poison_mask_traced(cfg, n, seed, step):
    """Traced twin of ``_poison_mask``: the per-step key is derived by
    ``fold_in(PRNGKey(seed), step)`` from the TRACED step counter — the
    scan-carry step of a chunked dispatch (core.make_chunked_step) is
    the same value the per-step loop folds, so chunked and per-step
    runs poison bitwise-identical sample sets (pinned in
    tests/test_chunked.py). Static all-ones short-circuit at
    ``poison_frac`` 1.0 keeps those programs free of any mask RNG."""
    import jax
    import jax.numpy as jnp

    k = int(round(cfg.poison_frac * n))
    if k >= n:
        return jnp.ones((n,), bool)
    key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
    perm = jax.random.permutation(key, n)
    return jnp.zeros((n,), bool).at[perm[:k]].set(True)


def poison_batch(cfg, x, y, *, seed=0, step=None):
    """Poison ONE cohort batch: returns ``(x', y')``.

    ``labelflip``: samples of class ``source`` (within the poisoned
    subset) are relabeled ``target``; inputs untouched. ``backdoor``: the
    poisoned subset gets the trigger stamped and the label set to
    ``target`` regardless of its true class. Label arrays may be int
    class ids (multi-class) or the binary float (..., 1) pima targets —
    both are rewritten in their own dtype. Dual-backend (numpy for the
    host-plane cohort loops, jnp for the traced in-graph slots).

    ``step`` selects the per-step poison subset: the traced path derives
    it via ``fold_in(seed, step)`` (``step`` may be the traced scan-carry
    counter — chunked and per-step dispatch poison identical sets), the
    host path via the composite ``(seed, step)`` rng. ``step=None``
    keeps the legacy static-per-seed mask.
    """
    xp = _xp_of(y)
    n = int(y.shape[0])
    if xp is not np and step is not None:
        sub = _poison_mask_traced(cfg, n, seed, step)
    else:
        sub = _poison_mask(cfg, n, seed, step=step)
        if xp is not np:
            import jax.numpy as jnp

            sub = jnp.asarray(sub)
    label_shape = (n,) + (1,) * (y.ndim - 1)
    sub_l = sub.reshape(label_shape)
    tgt = xp.asarray(cfg.target, y.dtype) if xp is np else cfg.target
    if cfg.attack == "labelflip":
        is_src = y == y.dtype.type(cfg.source) if xp is np else (
            y == cfg.source
        )
        y2 = xp.where(sub_l & is_src, tgt, y)
        return x, y2.astype(y.dtype)
    # backdoor: trigger + relabel the poisoned subset.
    x_trig = apply_trigger(cfg, x)
    sub_x = sub.reshape((n,) + (1,) * (x.ndim - 1))
    x2 = xp.where(sub_x, x_trig, x)
    y2 = xp.where(sub_l, tgt, y)
    return x2.astype(x.dtype), y2.astype(y.dtype)

"""Cross-process AggregaThor/ByzSGD: one OS process per node, PeerExchange.

This is the host-driver deployment shape of the reference — one process per
node pulling models/gradients through the message exchange
(tensorflow_impl/applications/AggregaThor/trainer.py:55-95 and
ByzSGD/trainer.py:76-95, fanned out by the per-app run_exp.sh) — with the
gRPC servicer replaced by ``utils.exchange.PeerExchange`` (TCP frames + the
native MRMW register). Unlike the on-mesh SPMD topologies, synchronization
here is REAL wait-n-f: the PS proceeds with the q = n_w - f *fastest*
worker gradients per step (server.py:134-155), so crashed or straggling
workers are simply absent from the quorum — no seeded-subset emulation.

Roles (ClusterConfig task):
  - ``ps`` (ranks 0..n_ps-1): publishes its flat model each step, collects
    the q fastest worker gradients, aggregates with the GAR, applies the
    optimizer update. With ONE PS this is AggregaThor SSMW (trusted
    server). With num_ps > 1 it is the ByzSGD MSMW deployment
    (tensorflow_impl/applications/ByzSGD/trainer.py:76-95): each step every
    node first collects ALL PS models and GAR-aggregates them with
    tolerance fps (the "gather step", pytorch ByzSGD/trainer.py:240-244),
    so a Byzantine PS process — launched with ``--ps_attack``, publishing
    poisoned models host-side exactly like ``byzServer.py:86-108`` — is
    outvoted in model space by the honest replicas. Straggler tolerance on
    the model plane is NOT subsetted: the fps budget covers VALUE faults
    (a live lying PS); a crashed PS stalls the deployment, as in the
    reference's bounded-retry-then-exit pull loops (server.py:138-141).
  - ``worker`` (ranks 1..n_w): collects the step's model from the PS slot,
    computes its data shard's gradient, publishes the flat gradient back to
    the PS. A worker started with ``--attack`` is a REAL Byzantine process
    (byzWorker.py:50-143): it poisons its own published gradient host-side.
    The self-contained attacks (reverse, random, crash) transform its own
    gradient; the colluding-statistics attacks (lie, empire) use the
    reference's local-cohort trick (byzWorker.py:114-125): the attacker
    computes the cohort's honest gradients ITSELF from its own extra
    batches, derives mu/sigma, and publishes mu + z*sigma / -eps*mu — no
    visibility into honest peers' gradients is needed, exactly as in the
    real deployment.

Both planes share one exchange: the PS slot only ever carries models, the
worker slots only gradients, and ``collect(..., peers=...)`` waits on
exactly the relevant slots.

Model state (BatchNorm statistics) travels in every deployment shape
(SSMW r4; MSMW/LEARN r5, VERDICT r4 #4), robust-aggregated with the
coordinate-wise f-trimmed ``_robust_stats`` at its plane's budget — so
all three shapes converge on BN architectures instead of the reference's
silent local-BN drift (its RPC path ships gradients only). Frame
layouts: SSMW and MSMW gradient frames carry ``[grad || batch_stats]``
and model frames ``[params || stats]``; LEARN syncs stats once per round
on its GOSSIP frames only (``[params || stats]`` at phase 2i+3 — its
gradient plane ships bare gradients, so BN adoption lags the gradient
phase by half a round, matching the on-mesh twin's once-per-step
``mean_model_state`` cadence).
"""

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.flatten_util import ravel_pytree

from ..aggregators import (
    dataplane as dataplane_lib,
    defense as defense_lib,
    gars,
)
from ..parallel import core
from ..telemetry import hub as tele_hooks, trace as tele_trace
from ..utils import multihost, rounds, tools, wire
from ..utils.exchange import PeerExchange
from . import common

__all__ = ["run"]

# Logical exchange planes (DESIGN.md §15). Every typed data frame stamps
# its plane into the wire codec header's spare bits (wire.encode plane=)
# so bytes attribute per plane in telemetry; the LEARN async deployment
# ADDITIONALLY uses them as real per-peer register slots
# (PeerExchange(planes=3)) — the slot separation that removes the old
# one-register-per-peer gossip multiplexing. The PS topologies keep a
# single-plane transport (their planes are already separated by peer
# role) and use the tags for accounting only.
PLANE_CTRL = 0
PLANE_GRAD = 1
PLANE_MODEL = 2


def _host_attack(name, params, fw):
    """Byzantine gradient attacks for a REAL attacker process.

    Returns ``(kind, fn, cohort)`` (``cohort`` only set for "cohort"):
      - ``("post", fn)``: self-contained transforms of the attacker's own
        gradient (byzWorker.py: 'random' :78-85, 'reverse' :87-94; 'crash'
        = the process simply dies, covered by killing it);
      - ``("cohort", fn)``: the colluding attacks. The reference's attacker
        simulates its fw colluders by computing fw honest gradients locally
        from its own batches (byzWorker.py:114-117) and publishing one
        statistic of that stack: lie = mu + z*sigma (:108-125, z=1.035),
        empire = -eps*mu (:127-143, eps=10). ``fn`` maps the (cohort, d)
        stack of locally-computed honest gradients to the published vector;
        the worker loop supplies the stack. Cohort size defaults to fw
        (byzWorker semantics — at fw=1 the Bessel sigma is NaN exactly like
        torch.std of one sample, and the published NaN vector is the
        reference's emergent behavior); ``attack_params["cohort"]``
        overrides it (the attacker controls its own simulation budget).
    """
    from .. import attacks as attacks_lib

    if name is None:
        return None, None, None
    if name in ("adaptive-lie", "adaptive-empire"):
        # Suspicion-aware attacker (attacks/adaptive.py, DESIGN.md §16):
        # the worker loop builds the HostController itself (it needs the
        # cluster's worker count and its own rank for the rotation
        # schedule); the cohort size rides the same local-simulation
        # budget as the oblivious colluding attacks. adaptive-lie floors
        # it at TWO: the Bessel sigma of one sample is NaN (the
        # reference's emergent fw=1 behavior), and a NaN fake is
        # self-defeating for a controller whose whole point is staying
        # admitted — it reads back "excluded" forever.
        floor = 2 if name == "adaptive-lie" else 1
        cohort = int(params.get("cohort", max(fw, floor)))
        if cohort < floor:
            raise SystemExit(
                f"--attack {name!r} needs a cohort of at least {floor} "
                f"honest gradients to simulate (got {cohort})"
            )
        return "adaptive", None, cohort
    if name in ("labelflip", "backdoor"):
        # Targeted data poisoner (attacks/targeted.py, DESIGN.md §17):
        # the worker rewrites its OWN batches (label flips / trigger
        # stamps) and publishes the honest gradient of the poisoned task
        # — nothing divergence-shaped for the suspicion plane to see.
        # The role builds the TargetedConfig itself AFTER its telemetry
        # hub is installed, so the one-time binary-surrogate fallback
        # event reaches the stream.
        return "targeted", None, None
    scale = float(params.get("scale", 100.0))
    rng = np.random.default_rng(int(params.get("seed", 666)))
    if name == "random":
        return "post", (
            lambda g: rng.standard_normal(g.shape).astype(g.dtype) * scale
        ), None
    if name == "reverse":
        return "post", (lambda g: g * (-scale)), None
    if name in ("lie", "empire"):
        cohort = int(params.get("cohort", fw))
        if cohort < 1:
            raise SystemExit(
                f"--attack {name!r} needs a cohort of at least 1 honest "
                f"gradient to simulate (got {cohort}; set --fw or "
                'attack_params {"cohort": k})'
            )
        z = float(params.get("z", attacks_lib.LIE_Z))
        eps = float(params.get("eps", attacks_lib.EMPIRE_EPS))

        def fn(stack):
            mu = stack.mean(axis=0)
            if name == "empire":
                return (-eps * mu).astype(np.float32)
            sigma = stack.std(axis=0, ddof=1)  # NaN at cohort=1, like torch
            return (mu + z * sigma).astype(np.float32)

        return "cohort", fn, cohort
    raise SystemExit(
        f"unknown cluster attack {name!r}; workers support random/reverse/"
        "lie/empire, the adaptive controllers (adaptive-lie/"
        "adaptive-empire), the targeted poisoners (labelflip/backdoor) — "
        "or kill the process for a crash."
    )


def _targeted_config(args, who):
    """``TargetedConfig`` for a cluster role running a targeted attack —
    built AFTER the role's telemetry hub is installed (the one-time
    binary-surrogate fallback event must reach the stream)."""
    from .. import models as models_lib
    from ..attacks import targeted as targeted_lib

    try:
        return targeted_lib.configure(
            args.attack, args.attack_params,
            num_classes=models_lib.num_classes_dict.get(args.dataset, 2),
        )
    except ValueError as e:
        raise SystemExit(f"[{who}] --attack {args.attack}: {e}") from e


def _host_model_attack(name, params):
    """Model attacks for a REAL Byzantine PS process (byzServer.py:86-108):
    the poisoned vector is what this PS publishes on the model plane.
    Self-contained by construction — a Byzantine server needs nothing from
    its peers to lie about its own model."""
    if name is None:
        return None
    scale = float(params.get("scale", 100.0))
    p = float(params.get("p", 0.3))
    rng = np.random.default_rng(int(params.get("seed", 777)))
    if name == "random":
        return lambda m: rng.standard_normal(m.shape).astype(m.dtype) * scale
    if name == "reverse":
        return lambda m: m * (-scale)
    if name == "drop":
        return lambda m: np.where(
            rng.random(m.shape) > (1.0 - p), 0.0, m
        ).astype(m.dtype)
    raise SystemExit(
        f"unknown PS model attack {name!r}; supported: random, reverse, "
        "drop (byzServer.py:74-78), the collusion statistics lie/empire "
        "and their adaptive controllers adaptive-lie/adaptive-empire "
        "(DESIGN.md §17)."
    )


class _ModelPoisoner:
    """Host-side Byzantine MODEL publisher: one object per attacking role
    (an MSMW replica under ``--ps_attack``, a LEARN node under
    ``--model_attack``) covering three attack shapes (DESIGN.md §17):

      - **simple**: byzServer's self-contained random/reverse/drop —
        the pre-§17 behavior, byte-identical (the whole published frame,
        stats segment included, goes through the same transform).
      - **collusion** (``lie``/``empire`` at a fixed z/eps): the
        publisher hides inside the spread of the model-plane rows it
        GATHERED last round — unlike the gradient plane it simulates
        nothing, the protocol hands it every row it wants statistics
        over (``attacks.adaptive.model_fake``). Until the first gather
        it publishes honestly (no cohort to collude against yet).
      - **adaptive** (``adaptive-lie``/``adaptive-empire``): the
        collusion magnitude is a ``HostController`` bisection bracket.
        Feedback is the MODEL-plane delta probe: if the poisoned model
        entered the peers' aggregation at round r, the mean of the
        honest peers' models moves toward the fake excess between the
        round-r and round-(r+1) gathers (``model_delta_probe``; the
        honest-drift estimate is the PREVIOUS round's observed peer
        delta). Rotation and gap-triggered bursts ride the same
        controller as the gradient-plane worker.

    The caller feeds every model-plane gather through ``note_gather``
    (rows + their ranks) and routes every model publication through
    ``publish_frame``.
    """

    def __init__(self, name, params, *, n_ranks, f, my_rank, who,
                 plane="model"):
        from ..attacks import adaptive as adaptive_lib, LIE_Z, EMPIRE_EPS

        params = dict(params or {})
        self.kind = None
        self.who = who
        self.plane = plane
        self.my_rank = int(my_rank)
        self.base = None
        self.controller = None
        self._fn = None
        self._mag = None
        self._last_stack = None
        self._prev_peer_mean = None
        self._prev_delta = None
        self._pending = None  # (round, excess u, magnitude)
        if name is None:
            return
        if adaptive_lib.is_adaptive(name):
            if f < 1:
                raise SystemExit(
                    f"--ps_attack/--model_attack {name!r} needs a declared "
                    f"Byzantine budget >= 1 on its plane (got {f})"
                )
            cfg = adaptive_lib.configure(
                name, params, num_workers=n_ranks, f=f
            )
            self.controller = adaptive_lib.HostController(
                cfg, my_rank,
                burst_factor=float(params.get("burst_factor", 3.0)),
                burst_rounds=int(params.get("burst_rounds", 3)),
            )
            self.base = cfg.base
            self.kind = "adaptive"
        elif name in ("lie", "empire"):
            self.base = name
            self._mag = float(params.get(
                "z" if name == "lie" else "eps",
                LIE_Z if name == "lie" else EMPIRE_EPS,
            ))
            self.kind = "collusion"
        else:
            self._fn = _host_model_attack(name, params)
            self.kind = "simple"

    def note_gather(self, stack, ranks, rnd):
        """One gathered model-plane stack (params rows, host numpy) with
        its per-row rank ids: refresh the collusion statistics, feed the
        burst trigger, and close the pending adaptive probe."""
        if self.kind in (None, "simple"):
            return
        from ..attacks import adaptive as adaptive_lib

        stack = np.asarray(stack, np.float32)
        ranks = list(ranks)
        self._last_stack = stack
        if self.kind != "adaptive":
            return
        self.controller.observe_round(time.time())
        peer_rows = [
            stack[j] for j, r in enumerate(ranks) if r != self.my_rank
        ]
        if not peer_rows:
            return
        peer_mean = np.mean(np.stack(peer_rows), axis=0)
        if self._pending is not None and self._prev_peer_mean is not None:
            pr, u, mag = self._pending
            detected, score = adaptive_lib.model_delta_probe(
                self._prev_peer_mean, peer_mean, u,
                honest_delta=self._prev_delta,
            )
            self.controller.feedback(detected)
            tele_hooks.emit_event(
                "ps_attack_adapt", step=int(pr), plane=self.plane,
                magnitude=round(float(mag), 6), detected=bool(detected),
                lo=round(self.controller.lo, 6),
                hi=round(self.controller.hi, 6),
                score=round(float(score), 6),
            )
            self._pending = None
        if self._prev_peer_mean is not None:
            # The NEXT probe's honest-drift estimate: what the peers'
            # mean moved this round (smooth across rounds; the previous
            # poison's contribution is second-order at probe scale).
            self._prev_delta = peer_mean - self._prev_peer_mean
        self._prev_peer_mean = peer_mean

    def publish_frame(self, params_vec, bn_vec, rnd):
        """The full ``[params || stats]`` frame this role publishes at
        round ``rnd``, poisoned per the attack shape. The collusion
        shapes poison the PARAMS segment (their statistics are over the
        gathered params rows) and keep the honest stats segment; the
        simple shapes transform the whole frame (pre-§17 byte parity)."""
        params_vec = np.asarray(params_vec, np.float32)
        has_bn = bn_vec is not None and np.asarray(bn_vec).size
        full = (
            np.concatenate([params_vec, np.asarray(bn_vec, np.float32)])
            if has_bn else params_vec
        )
        if self.kind is None:
            return full
        if self.kind == "simple":
            return self._fn(full).astype(np.float32)
        if self._last_stack is None:
            return full  # no gathered cohort to collude against yet
        from ..attacks import adaptive as adaptive_lib

        if self.kind == "collusion":
            fake = adaptive_lib.model_fake(
                self.base, self._last_stack, self._mag
            )
        else:
            if not self.controller.is_active(rnd):
                return full  # rotation: this round the role plays honest
            mag = self.controller.magnitude()
            fake = adaptive_lib.model_fake(self.base, self._last_stack, mag)
            if not self.controller.bursting():
                self._pending = (
                    int(rnd), fake - self._last_stack.mean(axis=0), mag
                )
        return (
            np.concatenate([fake, np.asarray(bn_vec, np.float32)])
            if has_bn else fake
        )

    def stats(self):
        if self.controller is None:
            return None
        return self.controller.stats()


def _startup_ms(args):
    """Startup ceiling: how long a peer may lawfully take to appear (python
    + jax import + data/model init + first compiles — minutes on a shared
    host). Used as the first-connect grace AND the startup-barrier budget;
    it costs nothing when everyone arrives promptly."""
    import os

    return max(
        args.cluster_timeout_ms,
        int(os.environ.get("GARFIELD_STARTUP_TIMEOUT_MS", 1_800_000)),
    )


def _telemetry_open(args, who, num_ranks=None, meta=None):
    """Per-role telemetry plane for cluster deployments: one MetricsHub
    streaming into ``<dir>/<who>.telemetry.jsonl`` (each process writes
    its own file — roles are separate OS processes), installed as the
    process-global sink so exchange wait latencies and the liveness
    events below land in the stream. Returns (hub, exporter) or
    (None, None) when --telemetry is off. With --trace/GARFIELD_TRACE
    the round-tracing spans (telemetry/trace.py, schema v5) are enabled
    into the same per-role stream — the raw material of
    ``python -m garfield_tpu.telemetry.report``."""
    if tele_trace.requested(args) and not getattr(args, "telemetry", None):
        args.telemetry = "telemetry"  # spans need the JSONL sink
    if not getattr(args, "telemetry", None):
        return None, None
    import os

    from ..telemetry import exporters as tele_fmt

    os.makedirs(args.telemetry, exist_ok=True)
    exp = tele_fmt.JsonlExporter(
        os.path.join(args.telemetry, f"{who}.telemetry.jsonl")
    )
    hub = tele_hooks.MetricsHub(
        num_ranks=num_ranks,
        suspicion_halflife=common.resolve_suspicion_halflife(args),
        meta={"tag": who, "gar": args.gar, "fw": args.fw, **(meta or {})},
        sink=exp,
    )
    exp.write(tele_fmt.make_record("run", meta=hub.meta))
    tele_hooks.install(hub)
    if tele_trace.requested(args):
        tele_trace.enable(who=who)
    return hub, exp


def _telemetry_close(hub, exp):
    if hub is None:
        return
    tele_trace.disable()
    try:
        exp.write(hub.summary())
    finally:
        exp.close()
        tele_hooks.uninstall()


# Public aliases (DESIGN.md §19): the federated shard/fleet roles
# (apps/benchmarks/fed_bench.py) are cluster-style OS processes and
# reuse the per-role telemetry plane and wire accounting verbatim —
# aliased rather than duplicated so the stream/summary format cannot
# drift between the cluster and federated deployments.
telemetry_open = _telemetry_open
telemetry_close = _telemetry_close


def _robust_stats(rows, f):
    """Coordinate-wise trimmed mean of worker-supplied BatchNorm-statistic
    rows under the deployment's f budget (ADVICE r4 medium).

    A Byzantine PROCESS controls its wire bytes, so the BN segment of its
    gradient frame is attacker-chosen regardless of the gradient GAR; a
    plain mean would hand it an unbounded write path into every honest
    worker's normalizer — a poisoning channel the reference never opens
    (its RPC plane ships gradients only; BN stays local). Trimming the f
    smallest and f largest values per coordinate bounds the influence of up
    to f Byzantine rows PROVIDED q >= 2f + 1 (the stats analog of tmean);
    at f=0 this IS the plain mean the on-mesh path computes
    (core.mean_model_state — where stats are honestly computed by
    construction, so no trim is needed). When q < 2f + 1 the trim clamps
    to the coordinate-wise median — the best available estimator, but a
    quorum whose Byzantine members can be the majority (n_w <= 3f) is
    indefensible for stats and _run_ps warns about it once at startup.
    """
    q = rows.shape[0]
    t = min(int(f), (q - 1) // 2)
    if t == 0:
        return np.mean(rows, axis=0).astype(np.float32)
    s = np.sort(rows, axis=0)
    return np.mean(s[t:q - t], axis=0).astype(np.float32)


def _eager_h2d():
    """Whether decoded rows are ``jax.device_put`` from the exchange
    waiter threads (overlapping H2D staging with the still-open quorum
    and the local device step). Default on — jax dispatch is thread-safe
    on the pinned jax/jaxlib; ``GARFIELD_EAGER_H2D=0`` opts out for a
    backend where it is not."""
    import os

    return os.environ.get("GARFIELD_EAGER_H2D", "1").lower() not in (
        "0", "false",
    )


class WireStats:
    """Per-role wire-plane accounting for the telemetry plane
    (docs/TELEMETRY.md): bytes and codec seconds, both directions,
    broken down PER PLANE (schema v6 — the ``planes`` sub-object of the
    per-step ``wire`` event feeds the plane-labelled Prometheus byte
    counters) and PER SCHEME (schema v11 — the ``schemes`` sub-object
    plus the ``compression_ratio`` / ``ef_residual_norm`` fields behind
    the round-18 compressed wire). Receive-side appends happen on
    exchange waiter threads — ``list.append`` is GIL-atomic; the sums
    happen at the per-step ``flush`` on the role's main thread."""

    def __init__(self, who):
        self.who = who
        self._out = []
        self._in = []
        # Set by roles that run error feedback (the gradient-plane
        # senders) so flush can surface the residual norm per step.
        self.ef = None

    def sent(self, nbytes, encode_s, fanout, plane=0, scheme="f32",
             elems=0):
        # f32-equivalent bytes ride along so flush can report the
        # compression ratio without re-deriving frame geometry.
        f32_eq = (wire.HEADER_NBYTES + 4 * int(elems)) * int(fanout)
        self._out.append(
            (int(nbytes) * int(fanout), float(encode_s), int(plane),
             str(scheme), f32_eq)
        )

    def received(self, nbytes, decode_s, plane=0, scheme="f32"):
        self._in.append(
            (int(nbytes), float(decode_s), int(plane), str(scheme))
        )

    def flush(self, step):
        out, self._out = self._out, []
        rin, self._in = self._in, []
        if tele_hooks.current() is None:
            return
        planes = {}
        schemes = {}
        for b, _, p, s, _ in out:
            planes.setdefault(p, [0, 0])[0] += b
            schemes.setdefault(s, [0, 0])[0] += b
        for b, _, p, s in rin:
            planes.setdefault(p, [0, 0])[1] += b
            schemes.setdefault(s, [0, 0])[1] += b
        bytes_out = sum(b for b, _, _, _, _ in out)
        f32_eq_out = sum(e for _, _, _, _, e in out)
        extra = {}
        if bytes_out and f32_eq_out != bytes_out:
            # The per-step send-side ratio vs an f32 wire — the ≥8x
            # claim's live counterpart (schema v11).
            extra["compression_ratio"] = round(f32_eq_out / bytes_out, 3)
        if self.ef is not None:
            extra["ef_residual_norm"] = round(self.ef.total_norm(), 6)
        tele_hooks.emit_event(
            "wire", who=self.who, step=int(step),
            bytes_out=bytes_out,
            bytes_in=sum(b for b, _, _, _ in rin),
            frames_in=len(rin),
            encode_s=round(sum(t for _, t, _, _, _ in out), 6),
            decode_s=round(sum(t for _, t, _, _ in rin), 6),
            planes={
                str(p): {"bytes_out": bo, "bytes_in": bi}
                for p, (bo, bi) in sorted(planes.items())
            },
            schemes={
                s: {"bytes_out": bo, "bytes_in": bi}
                for s, (bo, bi) in sorted(schemes.items())
            },
            **extra,
        )


# The schemes whose compression error is biased (and therefore needs the
# error-feedback accumulator): everything lossy except bf16, which stays
# EF-free like the PR 4 wire so its frames remain byte-identical.
_EF_SCHEMES = ("int8", "int4", "topk")


def _wire_scheme(plane):
    """Resolve the send scheme for ``plane`` (round 18, DESIGN.md §20).

    The ``GARFIELD_WIRE_TOPK`` sparsification overlay applies to the
    GRADIENT plane only: model/gossip broadcasts are absolute state —
    a sparse model frame read by a catching-up peer (read_latest,
    last-writer-wins) would zero every coordinate outside this round's
    top-k — so they keep the dense ``GARFIELD_WIRE_DTYPE`` width. The
    control plane (plane 0 sentinels) is dense for the same reason."""
    if plane == PLANE_GRAD and wire.wire_topk() > 0:
        return "topk"
    return wire.wire_dtype()


def _maybe_error_feedback(who, wire_stats):
    """This role's gradient-plane error-feedback accumulator, when the
    resolved gradient scheme is biased-lossy (``_EF_SCHEMES``); None
    otherwise. HOST RESTART SEMANTICS (the documented contract —
    tests/test_compress.py pins the in-graph half): the accumulator is
    rebuilt AT ZERO here, because the residual is a bounded one-step
    correction (||e|| <= one step's compression error) — a restart
    costs one step of compensation, not convergence — and the rebuild
    is ANNOUNCED so a restarted run's log shows the reset instead of a
    silent zeroing. Bitwise-reproducible resume is the in-graph twin's
    job (TrainState.wire_state rides the checkpoint tree)."""
    scheme = _wire_scheme(PLANE_GRAD)
    if scheme not in _EF_SCHEMES:
        return None
    ef = wire.ErrorFeedback()
    wire_stats.ef = ef
    tools.info(
        f"[{who}] wire scheme {scheme!r}: error-feedback accumulator "
        "rebuilt at zero (a host restart drops at most one step of "
        "compensation; bitwise resume lives on the in-graph twin)"
    )
    return ef


def _encode_frame(parts, stats=None, fanout=1, plane=0, ef=None):
    """The wire codec's single PRODUCER for the cluster driver: encode
    the concatenation of f32 segments (``[grad || stats]`` /
    ``[params || stats]``) as one typed frame at the plane's resolved
    scheme (``_wire_scheme``), accounting bytes x fan-out and encode
    time for the telemetry plane. ``plane`` stamps the codec header's
    plane tag (PLANE_GRAD/PLANE_MODEL) — the self-describing half of
    the per-plane accounting.

    With multiple parts the FIRST part is the additive head (gradient /
    params) and the rest the BatchNorm-stats tail: top-k keeps the tail
    dense (``keep_from`` — robust-stats input, not a sparse signal) and
    error feedback compensates the head only. ``ef`` (a
    ``wire.ErrorFeedback``, keyed per plane — frames broadcast
    byte-identical to all peers, so per sender x plane is full
    resolution) makes this sender transmit C(g + e) and carry
    e' = (g + e) - decode(C(g + e)); the residual uses the receiver's
    OWN decode of the frame actually shipped, so it is exactly the
    error every peer saw."""
    t0 = time.perf_counter()
    parts = [np.asarray(p, np.float32).reshape(-1) for p in parts]
    vec = parts[0] if len(parts) == 1 else np.concatenate(parts)
    scheme = _wire_scheme(plane)
    keep_from = parts[0].size if len(parts) > 1 else None
    if ef is not None and scheme in _EF_SCHEMES:
        upto = vec.size if keep_from is None else keep_from
        vec = ef.compensate(plane, vec, upto=upto)
        frame = wire.encode(vec, scheme, plane=plane, keep_from=keep_from)
        ef.update(plane, vec, wire.decode(frame), upto=upto)
    else:
        frame = wire.encode(vec, scheme, plane=plane, keep_from=keep_from)
    if stats is not None:
        stats.sent(len(frame), time.perf_counter() - t0, fanout, plane,
                   scheme=scheme, elems=vec.size)
    return frame


def _frame_transform(split, stats=None, pass_empty=False, plane=0):
    """The wire codec's single CONSUMER: the eager per-frame decode hook
    every cluster role hands to ``collect_begin``/``read_latest_begin``
    (the four roles used to hand-roll paired ``np.frombuffer`` splits
    after the quorum closed). Runs on the exchange waiter thread the
    moment a frame lands: wire-decode (crc + dtype restore), split into
    ``(primary, stats_segment)``, and stage the primary segment onto the
    device — overlapping decode + H2D with the other peers' receives and
    the local device step. A codec reject raises ``wire.WireError``
    (stored by the exchange as the peer's result — ban/exclusion
    evidence, with ``.nbytes`` carrying the observed frame length).
    ``pass_empty`` lets the SSMW stop sentinel (an empty frame) through
    undecoded."""
    d0, d1 = split

    def transform(idx, payload):
        if pass_empty and not payload:
            return payload
        t0 = time.perf_counter()
        try:
            # expect_elems pins the header's dense size BEFORE the
            # scatter allocation: a sparse frame's elems is otherwise a
            # bare claim (see wire.decode) — the consumer's d is the
            # ground truth here.
            vec = wire.decode(payload, expect_elems=d0 + d1)
            if vec.size != d0 + d1:
                raise wire.WireError(
                    f"frame has {vec.size} elements, expected {d0 + d1}"
                )
        except wire.WireError as exc:
            exc.nbytes = len(payload)
            raise
        head, tail = vec[:d0], vec[d0:]
        if _eager_h2d():
            try:
                head = jax.device_put(head)
            except Exception:  # noqa: BLE001 — host row still works
                pass  # jnp.stack uploads at harvest instead
        if stats is not None:
            stats.received(
                len(payload), time.perf_counter() - t0, plane,
                scheme=wire.frame_scheme(payload),
            )
        return head, tail

    return transform


def _cancel_wait(wait_fn):
    """Retire a pre-registered exchange harvest a role will never consume
    (shutdown, catch-up jump, membership change): without the cancel its
    waiter threads linger until the deadline or ``close()`` — the
    lifecycle leak tests/test_exchange.py pins."""
    if wait_fn is not None and hasattr(wait_fn, "cancel"):
        wait_fn.cancel()


def _async_gradient_quorum(collector, i, q, policy, republish, timeout_ms,
                           who):
    """The bounded-staleness twin of ``_gradient_quorum`` (DESIGN.md §14):
    admissible frames for round ``i`` from the persistent round-tagged
    collector — stale frames within ``policy.max_staleness`` are REUSED,
    so the round rate decouples from the slowest rank; the collector's
    freshness floor (at least one new arrival per harvest) stops the PS
    from free-running on cached data. Codec rejects are Byzantine ban
    evidence exactly as on the synchronous path: the rank's watcher is
    retired (``remove_peer`` — the membership-change form of the waiter
    lifecycle) and the gather retries over the survivors. Returns
    ``{rank: (tag, (grad_row, stats_row))}``.
    """
    attempts = 0
    while True:
        try:
            got = collector.gather(
                i, q, max_staleness=policy.max_staleness,
                timeout_ms=timeout_ms,
            )
        except TimeoutError:
            attempts += 1
            if attempts >= 3:
                raise
            tools.warning(
                f"[{who}] round {i} admissible quorum timed out; "
                f"re-publishing the model (attempt {attempts})"
            )
            tele_hooks.emit_event(
                "quorum_retry", who=who, step=int(i), attempt=attempts
            )
            republish()
            continue
        bad = [k for k in got if isinstance(got[k][1], Exception)]
        if not bad:
            return got
        for k in bad:
            exc = got[k][1]
            tools.warning(
                f"[{who}] worker rank {k} sent a gradient frame that "
                f"failed the wire codec ({exc}); excluding it from "
                "all future quorums"
            )
            tele_hooks.emit_event(
                "quorum_exclusion", who=who, step=int(i), rank=int(k),
                got_bytes=int(getattr(exc, "nbytes", -1)),
                why=str(exc),
            )
            collector.remove_peer(k)
        if len(collector.peers()) < q:
            raise SystemExit(
                f"only {len(collector.peers())} well-formed workers "
                f"remain but the quorum needs q={q}; aborting"
            )


def _staleness_quorum(got, i, q, policy, worker_ranks, who):
    """Deterministic freshest-q composition + weights: sort the
    admissible frames by (staleness, rank) — at ``max_staleness 0``
    every tag equals ``i`` and this is exactly the synchronous path's
    lowest-q-ranks composition — and derive the discount weights via the
    shared policy (utils/rounds.py). Emits the per-round ``staleness``
    telemetry event (schema v4: per-rank staleness + weights, folded
    into suspicion alongside exclusions). Returns
    ``(quorum_ranks, taus, weights)``."""
    quorum = sorted(got, key=lambda k: (i - got[k][0], k))[:q]
    taus = np.array([max(0, i - got[k][0]) for k in quorum], np.int64)
    w = np.asarray(policy.weights(taus), np.float32)
    if tele_hooks.current() is not None:
        base = worker_ranks[0]
        tele_hooks.emit_event(
            "staleness", who=who, step=int(i),
            ranks=[int(k - base) for k in quorum],
            staleness=[int(t) for t in taus],
            weights=[round(float(x), 6) for x in w],
            reused=int((taus > 0).sum()),
        )
    return quorum, taus, w


class _AutoscalePlane:
    """PS-side elastic worker pool (DESIGN.md §15): the autoscale
    controller (``utils/autoscale.py``) plus the mechanics of acting on
    its decisions against a live async deployment.

    Membership is three nested sets over the config's worker ranks:
    the POOL (every worker slot in the cluster config — the reserve),
    the ACTIVE set (processes this PS has spawned and not retired), and
    the READY set (active ranks whose frames have actually reached a
    quorum — a spawning worker pays tens of seconds of jax boot, and
    counting it toward q before its first frame would stall every round
    on its cold start). The effective quorum is
    ``q = max(1, |ready ∩ active| - f)``.

    SPAWN: launch the lowest reserve rank as a real OS process running
    this PS's own CLI re-targeted at ``worker:K``
    (``autoscale.worker_command``); it joins through the existing
    ``read_latest`` catch-up path and re-reads its own shard. RETIRE: a
    CLEAN teardown of the highest active rank — drop it from the
    broadcast fan-out, send it the stop sentinel (it exits rc 0 through
    its normal end-of-run path), retire its exchange watchers
    (``PeerExchange.remove_peer`` — the symmetric-teardown contract) and
    its collector membership. Every action emits the schema-v6
    ``autoscale`` telemetry event; the hub folds the running
    active-worker count into ``garfield_active_workers``.
    """

    def __init__(self, args, worker_ranks, f, gar, who):
        from ..utils import autoscale as autoscale_lib

        n_w = len(worker_ranks)
        max_w = int(getattr(args, "autoscale_max", 0) or 0) or n_w
        cfg = autoscale_lib.AutoscaleConfig(
            target_rate=float(getattr(args, "target_rate", 0.0) or 0.0),
            min_workers=int(getattr(args, "autoscale_min", 1) or 1),
            max_workers=min(max_w, n_w),
            window=int(getattr(args, "autoscale_window", 8) or 8),
            cooldown=int(getattr(args, "autoscale_cooldown", 8) or 8),
        )
        q_min = max(1, cfg.min_workers - f)
        if f:
            msg = gar.check(np.zeros((q_min, 4), np.float32), f=f)
            if msg is not None:
                raise SystemExit(
                    f"--autoscale_min {cfg.min_workers} is infeasible: "
                    f"GAR {gar.name!r} cannot aggregate q = min - fw = "
                    f"{q_min} rows: {msg}"
                )
        self.cfg = cfg
        self.controller = autoscale_lib.AutoscaleController(cfg)
        self.f = f
        self.who = who
        self.worker_ranks = list(worker_ranks)
        self.base = worker_ranks[0]
        self.active = list(worker_ranks[:cfg.min_workers])
        self.ready = set()
        self.ex = None
        self.collector = None
        self._procs = []
        self._log_dir = getattr(args, "telemetry", None)

    def bind(self, ex, collector):
        self.ex = ex
        self.collector = collector

    def q(self):
        live = len(self.ready & set(self.active)) or len(self.active)
        return max(1, live - self.f)

    def note_arrivals(self, ranks):
        self.ready.update(r for r in ranks if r in self.active)

    def _spawn_proc(self, windex):
        import os
        import subprocess
        import sys

        from ..utils import autoscale as autoscale_lib

        cmd = autoscale_lib.worker_command(windex)
        out = subprocess.DEVNULL
        if self._log_dir:
            os.makedirs(self._log_dir, exist_ok=True)
            out = open(
                os.path.join(self._log_dir, f"worker_{windex}.log"), "ab"
            )
        # A list, not a dict keyed by rank: a retire-then-respawn of the
        # same rank must not drop the first process's handle unreaped.
        self._procs.append(subprocess.Popen(
            cmd, stdout=out, stderr=subprocess.STDOUT,
            env=dict(os.environ),
        ))

    def spawn_initial(self):
        """Launch the initial active set (with --autoscale the PS owns
        its worker processes; external launches would double-bind the
        configured ports)."""
        for r in self.active:
            self._spawn_proc(r - self.base)
            self.collector.add_peer(r)

    def observe(self, i, round_s, admissible):
        """Fold one round into the controller and act on its decision."""
        action = self.controller.observe(
            round_s, active=len(self.active),
            quorum_margin=admissible - self.q(),
        )
        if action == 0:
            return
        if action > 0:
            reserve = [
                r for r in self.worker_ranks if r not in self.active
            ]
            rank = reserve[0]
            self.active = sorted(self.active + [rank])
            self._spawn_proc(rank - self.base)
            self.collector.add_peer(rank)
            verb = "spawn"
        else:
            rank = self.active[-1]
            self.active = [r for r in self.active if r != rank]
            self.ready.discard(rank)
            # Clean retire: stop sentinel first (the worker exits rc 0
            # through its end-of-run path the moment its model watcher
            # latches the empty frame), THEN the symmetric watcher
            # teardown — collector membership and any exchange-level
            # latches on the rank (read_latest probes) go together.
            self.ex.publish(i + 1, b"", to=[rank])
            self.collector.remove_peer(rank)
            self.ex.remove_peer(rank)
            verb = "retire"
        rate = self.controller.rate()
        tools.warning(
            f"[{self.who}] autoscale {verb}: worker rank {rank} "
            f"(active {len(self.active)}, target "
            f"{self.controller.target:.2f} r/s)"
        )
        tele_hooks.emit_event(
            "autoscale", who=self.who, step=int(i), action=verb,
            rank=int(rank - self.base), active=len(self.active),
            rate=None if rate is None else round(float(rate), 4),
            target=round(float(self.controller.target), 4),
        )

    def reap(self, timeout=120):
        """Join every process this PS spawned (the run's stop sentinel
        has been published); kill stragglers after ``timeout``."""
        import subprocess

        for p in self._procs:
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()


def _setup(args):
    """Shared ingredients for both roles."""
    cfg = multihost.ClusterConfig(args.cluster)
    if args.task:
        ttype, _, tidx = args.task.partition(":")
        cfg.task_type = ttype
        cfg.task_index = int(tidx or 0)
    n_ps = len(cfg.ps)
    if n_ps < 1:
        raise SystemExit("cluster config needs at least one PS host")
    if n_ps > 1:
        # MSMW (ByzSGD): only the byzsgd app can parameterize the
        # fps-tolerant model plane (--model_gar/--ps_attack are its flags;
        # --fps alone lives in the shared base parser, so its presence
        # distinguishes nothing) — an aggregathor config with several PS
        # hosts must fail loudly, not silently enter the MSMW path.
        if not hasattr(args, "model_gar"):
            raise SystemExit(
                f"the cluster config has {n_ps} PS hosts but this app has "
                "no --model_gar/--ps_attack support; launch MSMW "
                "deployments through the byzsgd app (or use a single-PS "
                "config)"
            )
        model_gar_name = args.model_gar or args.gar
        model_gar = gars[model_gar_name]
        fps = args.fps
        if fps:
            msg = model_gar.check(np.zeros((n_ps, 4), np.float32), f=fps)
            if msg is not None:
                raise SystemExit(
                    f"model GAR {model_gar_name!r} cannot aggregate the "
                    f"{n_ps} PS models at fps={fps}: {msg}"
                )
        else:
            # fps=0: most rules' check() rejects f=0 outright even though
            # unchecked() is well-defined there (krum at f=0 still selects
            # m = n - 2), so checking would break valid fps=0 deployments.
            # Instead probe the EXACT runtime call on a dummy stack — an
            # infeasible (rule, n_ps) pair (ADVICE r4: krum over n_ps=2
            # gives m = 0) fails loudly here instead of as an opaque
            # ZeroDivisionError at trace time.
            try:
                model_gar.unchecked(np.zeros((n_ps, 4), np.float32), f=0)
            except Exception as e:  # noqa: BLE001 — any trace failure
                raise SystemExit(
                    f"model GAR {model_gar_name!r} cannot aggregate the "
                    f"{n_ps} PS models at fps=0: {type(e).__name__}: {e}"
                ) from e
    n_w = len(cfg.workers)
    f = args.fw
    q = n_w - f
    wm = getattr(args, "worker_momentum", None)
    if wm is not None and not (0.0 <= wm < 1.0):
        raise SystemExit(f"worker_momentum must be in [0, 1), got {wm}")
    if not f * 2 < n_w:
        # The majority-honest invariant the reference asserts
        # (Aggregathor/trainer.py:150-152) — enforced against the CONFIG's
        # worker count (the --cluster path bypasses the on-mesh assert).
        raise SystemExit(
            f"the number of Byzantine workers should be less than half the "
            f"number of workers (fw={f}, config has {n_w} workers)"
        )
    # Fail fast with the GAR's own contract before any process waits on
    # another (e.g. krum needs q >= 2f+3).
    if f:
        msg = gars[args.gar].check(np.zeros((q, 4), np.float32), f=f)
        if msg is not None:
            raise SystemExit(
                f"GAR {args.gar!r} cannot run on the q = n_w - fw = {q} "
                f"collected gradients: {msg}"
            )
    xs, ys, test_batches, iters_per_epoch = common.load_data(args, n_w)
    module, loss_fn, optimizer = common.build_ingredients(
        args, iters_per_epoch
    )
    init_fn, grad_fn, eval_fn = core.make_worker_fns(module, loss_fn)
    params0, ms0 = init_fn(jax.random.PRNGKey(args.seed), xs[0, 0])
    # Role-aware retention: the PS never trains (drop the shards), a worker
    # only reads its own shard (drop the rest and the test set) — no point
    # keeping n_w + 1 copies of the dataset across the deployment's hosts.
    if cfg.task_type == "ps":
        xs = ys = None
    else:
        xs, ys = xs[cfg.task_index], ys[cfg.task_index]
        test_batches = None
    flat0, unravel = ravel_pytree(params0)
    # First connects get the startup-scale grace: a peer that is still
    # importing/compiling must not cost the cluster its hello/model frames
    # (the sender holds the frame while retrying — see exchange._sock_for).
    ex = PeerExchange(
        cfg.process_id, cfg.hosts, connect_retry_ms=_startup_ms(args)
    )
    return (cfg, n_w, f, q, xs, ys, test_batches, optimizer, grad_fn,
            eval_fn, params0, ms0, flat0, unravel, ex)


def run(args):
    """Entry: dispatch on the configured role (and PS count: one PS is
    AggregaThor SSMW, several are the ByzSGD MSMW deployment; a "node"
    config is the decentralized LEARN deployment)."""
    # NOTE on the persistent compile cache: deliberately NOT enabled here.
    # On hosts where the XLA:CPU AOT loader rejects its own cache entries
    # (machine-feature validation mismatch — observed on the dev image),
    # every jit pays a failed load per executable and the error spam +
    # retries starved worker startup past the PS's quorum budget. TPU
    # entry points (bench.py, __graft_entry__) keep the cache, where it
    # works and matters.
    cfg_probe = multihost.ClusterConfig(args.cluster)
    if cfg_probe.nodes or (args.task or "").startswith("node"):
        return _run_learn(args)
    (cfg, n_w, f, q, xs, ys, test_batches, optimizer, grad_fn, eval_fn,
     params0, ms0, flat0, unravel, ex) = _setup(args)
    n_ps = len(cfg.ps)
    ps_ranks = list(range(n_ps))
    worker_ranks = list(range(n_ps, n_ps + n_w))
    timeout_ms = args.cluster_timeout_ms
    try:
        if cfg.task_type == "ps":
            if n_ps > 1:
                return _run_ps_multi(
                    args, cfg.task_index, ps_ranks, q, worker_ranks,
                    test_batches, optimizer, eval_fn, params0, ms0, flat0,
                    unravel, ex, timeout_ms,
                )
            return _run_ps(
                args, q, worker_ranks, test_batches, optimizer, eval_fn,
                params0, ms0, flat0, unravel, ex, timeout_ms,
            )
        return _run_worker(
            args, cfg.task_index, ps_ranks, xs, ys, grad_fn, ms0, flat0,
            unravel, ex, timeout_ms,
        )
    finally:
        ex.close()


def _gradient_quorum(ex, step, q, good_ranks, split, republish,
                     timeout_ms, who, stats=None, wait_fn=None):
    """The PS-side gradient quorum, shared by SSMW and MSMW.

    A Byzantine PROCESS controls its wire bytes, not just its values: a
    frame the wire codec rejects (bad magic/dtype tag/element count/crc
    or a truncation — ``_frame_transform`` stores the ``WireError`` as
    that rank's result) cannot enter the GAR and proves its sender
    Byzantine — exclude the rank from all future quorums and re-collect
    from the rest (the frames already received return instantly). A
    quorum TIMEOUT triggers ``republish`` before the final attempt: the
    model plane is fire-and-forget, so workers whose listener bound
    after this step's publish (cold start) would otherwise never see a
    frame to catch up to and the healthy cluster would deadlock.
    ``wait_fn`` is the caller's pre-registered ``collect_begin`` harvest
    for the overlap fast path (consumed on the first attempt only —
    retries re-collect over the surviving ranks). Returns
    ``(got, good_ranks)`` with every ``got`` value a decoded
    ``(grad_row, stats_row)`` pair.
    """
    transform = _frame_transform(split, stats, plane=PLANE_GRAD)
    attempts = 0
    while True:
        try:
            if wait_fn is not None:
                # Clear BEFORE harvesting: a timed-out registration must
                # not be re-harvested on the retry path (its waiter
                # threads have already expired).
                w, wait_fn = wait_fn, None
                got = w()
            else:
                got = ex.collect(
                    step, q, peers=good_ranks, timeout_ms=timeout_ms,
                    transform=transform,
                )
        except TimeoutError:
            attempts += 1
            if attempts >= 3:
                raise
            tools.warning(
                f"[{who}] step {step} quorum timed out; re-publishing "
                f"the model (attempt {attempts})"
            )
            tele_hooks.emit_event(
                "quorum_retry", who=who, step=int(step), attempt=attempts
            )
            republish()
            continue
        bad = [k for k in got if isinstance(got[k], Exception)]
        if not bad:
            return got, good_ranks
        for k in bad:
            tools.warning(
                f"[{who}] worker rank {k} sent a gradient frame that "
                f"failed the wire codec ({got[k]}); excluding it from "
                "all future quorums"
            )
            tele_hooks.emit_event(
                "quorum_exclusion", who=who, step=int(step), rank=int(k),
                got_bytes=int(getattr(got[k], "nbytes", -1)),
                why=str(got[k]),
            )
        good_ranks = [k for k in good_ranks if k not in bad]
        if len(good_ranks) < q:
            raise SystemExit(
                f"only {len(good_ranks)} well-formed workers remain "
                f"but the quorum needs q={q}; aborting"
            )


def _run_ps(args, q, worker_ranks, test_batches, optimizer, eval_fn,
            params0, ms0, flat0, unravel, ex, timeout_ms):
    """The trusted server: model out, q fastest gradients in, GAR, update.

    BatchNorm statistics travel too (VERDICT r3 weak #5): each worker's
    gradient frame carries its updated flat ``batch_stats`` appended after
    the gradient, the PS aggregates the quorum's stats with a coordinate-
    wise f-trimmed mean (``_robust_stats`` — a real Byzantine process
    controls the BN segment of its frame, so the aggregation must carry
    the same f budget as the gradients; at f=0 it reduces to the plain
    mean of the on-mesh core.mean_model_state) and appends the result to
    the published model frame — so the two deployment shapes of the SSMW
    topology converge to the same model on BN architectures instead of
    the reference's silent local-BN drift (at f>0 the trim makes the two
    shapes agree only statistically, the price of robustness). Stat-less
    models (d_bn = 0) keep byte-identical frames.
    """
    from .. import parallel

    f = args.fw
    gar = gars[args.gar]
    gar_params = dict(getattr(args, "gar_params", None) or {})
    base_gar_params = dict(gar_params)
    # Closed-loop defense (DESIGN.md §16): suspicion weighting + rule
    # escalation on the host plane. The suspicion source is this PS's
    # own MetricsHub (it sees the real arrival-order quorums), so
    # --defense implies --telemetry like --trace does.
    defense_plan = defense_lib.resolve(args)
    esc_policy = None
    if defense_plan is not None:
        if not getattr(args, "telemetry", None):
            args.telemetry = "telemetry"
        if defense_plan.escalate:
            allowed = sorted(
                k for k in defense_lib.LEVEL_RULES if k in gars
            )
            if args.gar not in allowed:
                raise SystemExit(
                    f"--defense escalate needs --gar to name a REGISTERED "
                    f"escalation-ladder rule ({allowed}), got {args.gar!r}"
                )
            esc_policy = defense_plan.policy()
            esc_policy.level = defense_lib.start_level(
                esc_policy.config.levels, args.gar,
                getattr(args, "gar_params", None),
            )
            lvl_gar, lvl_params = esc_policy.current()
            gar = gars[lvl_gar]
            gar_params = {**base_gar_params, **lvl_params}
    opt_state0 = optimizer.init(params0)
    bn0_flat, bn_unravel = ravel_pytree(ms0)
    bn_elems = int(np.asarray(bn0_flat).size)
    bn_mean = np.asarray(bn0_flat, np.float32)
    if bn_elems and f and q < 2 * f + 1:
        tools.warning(
            f"BN-stat aggregation: the quorum q={q} is below 2*fw+1="
            f"{2 * f + 1}, so the f-trimmed mean clamps to the coordinate-"
            "wise median — if all fw Byzantine workers land in one quorum "
            "they are its majority and can steer the BN statistics "
            "(n_w <= 3*fw is indefensible for stats; see _robust_stats)"
        )
    test_batches = parallel.EvalSet(
        test_batches, binary=args.dataset == "pima"
    )

    gar_base_key = jax.random.PRNGKey(args.seed)

    # Telemetry plane (docs/TELEMETRY.md): this PS is the deployment's
    # natural audit point — it sees the REAL arrival order, so
    # ``observed`` marks the q fastest workers (true wait-n-f, not the
    # on-mesh seeded emulation) and the tap audits the rule's selection
    # inside that quorum. Exchange waits and quorum exclusions stream in
    # through the global hook.
    n_w = len(worker_ranks)
    tele_hub, tele_exp = _telemetry_open(
        args, "cluster-ps", num_ranks=n_w,
        meta={"attack": getattr(args, "attack", None), "q": q},
    )
    # Data-plane defense (aggregators/dataplane.py, DESIGN.md §18): the
    # host twin of the on-mesh detectors — fingerprints the wire frames
    # this PS already decodes, carries its own decayed flag EMA, and
    # composes per-quorum weights into the same row-scale slot as the
    # staleness/suspicion discounts.
    dp_def = None
    if defense_plan is not None and defense_plan.data:
        dp_def = dataplane_lib.DataPlaneDefense(
            n_w, dataplane_lib.head_spec(params0),
            f=max(1, f), plane="gradient",
            tau=defense_plan.dp_tau, power=defense_plan.dp_power,
            floor=defense_plan.dp_floor,
            halflife=defense_plan.dp_halflife,
        )

    def _build_tap(g, gp):
        from ..telemetry import taps as taps_lib

        @jax.jit
        def tap_fn(stack, sel):
            bundle = taps_lib.compute_flat(g.name, stack, f, params=gp)
            return taps_lib.scatter(bundle, sel, n_w)

        return tap_fn

    tap_fn = _build_tap(gar, gar_params) if tele_hub is not None else None

    def _build_updates(g, gp):
        """(ps_update, ps_update_weighted) jits for one rule — rebuilt on
        a defense-escalation level change (same shape, new selection)."""

        def _update_body(flat_params, opt_state, grads_stack, step):
            # f=0 with the default rule short-circuits to the mean, but
            # an explicitly requested rule (e.g. cclip, valid at f=0)
            # must run — silently averaging would fake the defense.
            # Randomized rules (condense) need a fresh per-step key:
            # without it the fixed keyless fallback would apply the SAME
            # coordinate mask every iteration under jit.
            if f or g.name != "average":
                agg = g.unchecked(
                    grads_stack, f=f,
                    key=jax.random.fold_in(gar_base_key, step), **gp,
                )
            else:
                agg = jnp.mean(grads_stack, axis=0)
            params = unravel(flat_params)
            updates, opt_state2 = optimizer.update(
                unravel(agg), opt_state, params
            )
            params = optax.apply_updates(params, updates)
            return ravel_pytree(params)[0], opt_state2

        # Bounded-staleness / suspicion-weighted update (DESIGN.md §14,
        # §16): the weights are composed into the stack BEFORE the GAR —
        # Kardam's dampening and the defense's suspicion discount share
        # one row-scale multiply — so any registered rule aggregates the
        # weighted rows. A fully-fresh, fully-trusted quorum (all
        # weights exactly 1.0) dispatches the unweighted jit instead:
        # same program as the synchronous path, which is the
        # --max_staleness 0 bitwise-equality contract.
        return jax.jit(_update_body), jax.jit(
            lambda fp, ost, stack, w, step: _update_body(
                fp, ost, stack * w[:, None], step
            )
        )

    ps_update, ps_update_weighted = _build_updates(gar, gar_params)

    def acc_eval(state_flat):
        return parallel.compute_accuracy(
            (unravel(state_flat), bn_unravel(jnp.asarray(bn_mean))),
            lambda s, x: eval_fn(s[0], s[1], x),
            test_batches,
            binary=args.dataset == "pima",
        )

    t0 = time.time()
    flat = np.asarray(flat0, np.float32)
    flat_dev, opt_state = jnp.asarray(flat), opt_state0
    good_ranks = list(worker_ranks)
    losses_seen = 0
    # Wire plane (DESIGN.md §11): every data frame goes through the typed
    # codec — encode once per step here, decode eagerly per arriving frame
    # in the exchange waiter threads (``_frame_transform``).
    wire_stats = WireStats("cluster-ps")
    split = (flat.size, bn_elems)
    grad_tf = _frame_transform(split, wire_stats, plane=PLANE_GRAD)
    # Bounded-staleness async mode (--async; DESIGN.md §14): ONE
    # persistent round-tagged collector replaces the per-round
    # collect_begin registrations — its multi-round watchers latch every
    # worker frame (eagerly decoded + device-staged, same transform) and
    # ``gather`` reuses admissible stale frames instead of blocking
    # re-collects.
    policy = rounds.resolve(args)
    collector = None
    scaler = None
    if getattr(args, "autoscale", False):
        # Elastic worker pool (DESIGN.md §15): only composes with the
        # async plane — a synchronous quorum's rate is pinned to its
        # slowest member no matter how many workers exist, so scaling
        # it is meaningless (and the membership mechanics live on the
        # round collector).
        if policy is None:
            raise SystemExit(
                "--autoscale requires --async: the synchronous quorum's "
                "round rate does not scale with the worker count "
                "(DESIGN.md §15)"
            )
        scaler = _AutoscalePlane(args, worker_ranks, f, gar, "cluster-ps")
    if policy is not None:
        collector = ex.round_collector(
            scaler.active if scaler else worker_ranks, transform=grad_tf
        )
        if scaler is not None:
            scaler.bind(ex, collector)
            scaler.spawn_initial()
    # PS-side checkpoint/resume (utils/checkpoint.py — the deliberate
    # upgrade over the reference, which has none; the on-mesh analog with
    # sharded TrainState + bit-exact rng replay lives in common.train).
    # Only the PS holds TRAINING state: resumed workers request model
    # round 0 and read_latest's catch-up semantics jump them straight to
    # the PS's resumed round. Exception: with --worker_momentum the workers
    # hold the EMA, which is NOT persisted — it re-warms over ~1/(1-beta)
    # steps after a resume (the worker warns; see _run_worker).
    ckpt = None
    start_iter = last_saved = 0
    if args.checkpoint_dir:
        from ..utils import checkpoint as ckpt_lib

        ckpt = ckpt_lib.Checkpointer(args.checkpoint_dir)
        step = ckpt.latest_step()
        if args.resume and step is not None:
            restored = ckpt.restore(
                {"flat": flat, "opt_state": jax.tree.map(
                    np.asarray, opt_state),
                 **({"bn": bn_mean} if bn_elems else {})},
                step=step,
            )
            flat = np.asarray(restored["flat"], np.float32)
            flat_dev = jnp.asarray(flat)
            opt_state = jax.tree.map(jnp.asarray, restored["opt_state"])
            if bn_elems:
                bn_mean = np.asarray(restored["bn"], np.float32)
            start_iter = last_saved = int(step)
            print(f"[cluster-ps] resumed from step {start_iter}", flush=True)
    grad_wait = None
    try:
        if collector is None and start_iter < args.num_iter:
            grad_wait = ex.collect_begin(
                start_iter, q, timeout_ms=timeout_ms, peers=good_ranks,
                transform=grad_tf,
            )
        for i in range(start_iter, args.num_iter):
            t_step = time.time()
            # Elastic membership (--autoscale): the broadcast fans out to
            # the ACTIVE set and the quorum tracks the READY subset —
            # both are just ``worker_ranks`` without a scaler.
            targets = scaler.active if scaler else worker_ranks
            q_round = scaler.q() if scaler else q
            with tele_trace.span("broadcast", step=i):
                frame = _encode_frame(
                    [flat] + ([bn_mean] if bn_elems else []),
                    wire_stats, fanout=len(targets), plane=PLANE_MODEL,
                )
                ex.publish(i, frame, to=targets)
            w = None
            if collector is not None:
                # Bounded staleness (DESIGN.md §14): admissible frames —
                # freshest per worker, reused across rounds within the
                # cutoff — instead of an exact-round quorum; the freshest
                # q compose the aggregate with decayed weights.
                with tele_trace.span("quorum", step=i):
                    got = _async_gradient_quorum(
                        collector, i, q_round, policy,
                        lambda: ex.publish(i, frame, to=targets),
                        timeout_ms, "cluster-ps",
                    )
                if scaler is not None:
                    scaler.note_arrivals(got)
                quorum, taus, w = _staleness_quorum(
                    got, i, q_round, policy, worker_ranks, "cluster-ps"
                )
                rows = {k: got[k][1] for k in quorum}
            else:
                with tele_trace.span("quorum", step=i):
                    got, good_ranks = _gradient_quorum(
                        ex, i, q, good_ranks, split,
                        lambda: ex.publish(i, frame, to=worker_ranks),
                        timeout_ms, "cluster-ps", stats=wire_stats,
                        wait_fn=grad_wait,
                    )
                # Overlap (DESIGN.md §11): the NEXT round's collect is
                # registered before this round's device update/eval, so
                # fast workers' next-round gradients are latched +
                # decoded + device-staged by the waiter threads while the
                # PS is still updating/evaluating.
                grad_wait = None
                if i + 1 < args.num_iter:
                    grad_wait = ex.collect_begin(
                        i + 1, q, timeout_ms=timeout_ms, peers=good_ranks,
                        transform=grad_tf,
                    )
                # Deterministic composition: of the >= q arrivals,
                # aggregate the q lowest ranks (the GAR's n is static
                # under jit). Rows arrive pre-decoded (and device-staged)
                # from the waiter threads.
                quorum = sorted(got)[:q]
                rows = {k: got[k] for k in quorum}
            with tele_trace.span("gar_apply", step=i):
                stack = jnp.stack([rows[k][0] for k in quorum])
                if bn_elems:
                    # Robust coordinate-wise aggregation of the quorum's
                    # BatchNorm stats (trim f per side; plain mean at
                    # f=0 == the on-mesh core.mean_model_state) — see
                    # _robust_stats. Async mode reuses the same quorum
                    # rows (stats staleness rides the same cutoff; the
                    # trim bounds a stale row like any other outlier).
                    with tele_trace.span("bn_stats", step=i):
                        bn_mean = _robust_stats(
                            np.stack([rows[k][1] for k in quorum]), f
                        )
                if dp_def is not None:
                    # Data-plane detectors (DESIGN.md §18): fingerprint
                    # this quorum's decoded rows, fold the flags into
                    # the dp EMA, and compose by CENTER-PULL — suspect
                    # rows collapse onto the quorum's trusted-mean center
                    # (toward-zero scaling would hand the cohort krum
                    # centrality; dataplane.center_pull_rows). The host
                    # twin of the in-graph dataplane block.
                    qidx = [k - worker_ranks[0] for k in quorum]
                    rep = dp_def.observe(
                        qidx, np.asarray(stack, np.float32)
                    )
                    tele_hooks.emit_event(
                        "data_defense", who="cluster-ps", step=int(i),
                        plane="gradient",
                        ranks=[int(x) for x in qidx],
                        scores=[round(float(s), 6)
                                for s in rep["scores"]],
                        flags=[int(x) for x in rep["flags"]],
                        weights=[round(float(x), 6) for x in
                                 dp_def.weights_full()[qidx]],
                    )
                    w_dp = dp_def.weights_for(qidx)
                    if w_dp is not None:
                        stack = dataplane_lib.center_pull_rows(
                            stack, jnp.asarray(w_dp)
                        )
                if defense_plan is not None and defense_plan.weighted \
                        and tele_hub is not None:
                    # Suspicion weighting (DESIGN.md §16): the quorum's
                    # rows enter the GAR scaled by their ranks' decayed,
                    # median-relative suspicion — composed with the
                    # staleness discount through the same row-scale
                    # multiply. A clean history is all-exactly-1.0 and
                    # keeps the unweighted program.
                    susp = tele_hub.suspicion_decayed()
                    if susp is not None:
                        qidx = [k - worker_ranks[0] for k in quorum]
                        w_def = np.asarray(defense_lib.suspicion_weights(
                            susp, power=defense_plan.power,
                            floor=defense_plan.floor,
                        ))[qidx].astype(np.float32)
                        tele_hooks.emit_event(
                            "defense_weights", who="cluster-ps",
                            step=int(i),
                            ranks=[int(x) for x in qidx],
                            weights=[round(float(x), 6) for x in w_def],
                        )
                        if not np.all(w_def == 1.0):
                            w = w_def if w is None else (
                                np.asarray(w) * w_def
                            ).astype(np.float32)
                if w is not None and not np.all(w == 1.0):
                    stack_gar = stack * jnp.asarray(w)[:, None]
                    flat_dev, opt_state = ps_update_weighted(
                        flat_dev, opt_state, stack, jnp.asarray(w),
                        jnp.asarray(i, jnp.int32),
                    )
                else:
                    # Fully-fresh quorum (or synchronous mode): the
                    # unweighted program — at --max_staleness 0 this is
                    # the bitwise synchronous trajectory.
                    stack_gar = stack
                    flat_dev, opt_state = ps_update(
                        flat_dev, opt_state, stack,
                        jnp.asarray(i, jnp.int32),
                    )
                flat = np.asarray(flat_dev, np.float32)  # next publication
            wire_stats.flush(i)
            if tele_hub is not None:
                # Worker index = exchange rank - first worker rank; the q
                # quorum members are the observed ranks this step. The
                # tap audits the rows the rule consumed — staleness-
                # weighted included. Its own span: the audit pass is
                # telemetry cost, not round cost, and the report should
                # say so.
                with tele_trace.span("audit", step=i):
                    sel = jnp.asarray(
                        [k - worker_ranks[0] for k in quorum], jnp.int32
                    )
                    tele_hub.record_step(
                        i, tap=tap_fn(stack_gar, sel),
                        step_time_s=time.time() - t_step,
                    )
            if esc_policy is not None and tele_hub is not None:
                # Rule escalation (DESIGN.md §16): fold this round's
                # suspicion concentration into the hysteresis ladder; a
                # level change swaps the jitted update + audit programs
                # (the host-plane twin of the on-mesh re-jit). A level
                # infeasible at this quorum size (bulyan needs
                # q >= 4f+3) is refused loudly and reverted.
                susp = tele_hub.suspicion_decayed()
                if susp is not None:
                    conc = float(defense_lib.suspicion_concentration(
                        susp, max(1, f)
                    ))
                    act = esc_policy.observe(conc)
                    if act:
                        name, lvl_params = esc_policy.current()
                        new_gar = gars[name]
                        msg = new_gar.check(
                            np.zeros((q, 4), np.float32), f=f
                        ) if f else None
                        if msg is not None:
                            tools.warning(
                                f"[cluster-ps] defense cannot escalate "
                                f"to {name!r} at q={q}: {msg}"
                            )
                            esc_policy.level -= act
                        else:
                            gar = new_gar
                            gar_params = {**base_gar_params, **lvl_params}
                            ps_update, ps_update_weighted = _build_updates(
                                gar, gar_params
                            )
                            tap_fn = _build_tap(gar, gar_params)
                            tools.warning(
                                f"[cluster-ps] defense "
                                f"{'escalates' if act > 0 else 'de-escalates'}"
                                f" to {esc_policy.level_name!r} at step {i} "
                                f"(suspicion concentration {conc:.3f})"
                            )
                            tele_hooks.emit_event(
                                "defense_escalate", who="cluster-ps",
                                step=int(i),
                                level=int(esc_policy.level),
                                rule=str(esc_policy.level_name),
                                direction=(
                                    "escalate" if act > 0 else "deescalate"
                                ),
                                gar=name,
                                concentration=round(conc, 6),
                            )
            if scaler is not None:
                # Load control (DESIGN.md §15): fold this round's wall
                # time + admissibility margin into the controller; spawn/
                # retire side effects happen here, between rounds.
                scaler.observe(i, time.time() - t_step, len(got))
            losses_seen = i + 1
            if (ckpt and args.checkpoint_freq
                    and (i + 1) % args.checkpoint_freq == 0):
                with tele_trace.span("checkpoint", step=i):
                    ckpt.save(i + 1, {
                        "flat": flat,
                        "opt_state": jax.tree.map(np.asarray, opt_state),
                        **({"bn": bn_mean} if bn_elems else {}),
                    })
                last_saved = i + 1
            if args.acc_freq and i % args.acc_freq == 0:
                with tele_trace.span("eval", step=i):
                    acc = acc_eval(flat_dev)
                print(
                    f"Step: {i} Accuracy: {acc:.4f} "
                    f"Time: {time.time() - t0:.1f}",
                    flush=True,
                )
    finally:
        # Waiter lifecycle (tests/test_exchange.py): a registration left
        # pending by an abort must not leak its threads until close().
        _cancel_wait(grad_wait)
        if collector is not None:
            collector.close()
    # Stop sentinel: an empty frame at step num_iter tells every worker
    # (including stragglers that skipped rounds) training is over. The
    # full pool is addressed — retired autoscale ranks already exited and
    # a dead rank costs one bounded sender queue.
    ex.publish(args.num_iter, b"", to=worker_ranks)
    if scaler is not None:
        scaler.reap()
    acc = acc_eval(flat_dev)
    if ckpt:
        if args.checkpoint_freq and last_saved != args.num_iter:
            # Final save, skipped when the in-loop save already wrote this
            # exact step (orbax writes are synchronous; workers idle
            # meanwhile).
            ckpt.save(args.num_iter, {
                "flat": flat,
                "opt_state": jax.tree.map(np.asarray, opt_state),
                **({"bn": bn_mean} if bn_elems else {}),
            })
        ckpt.close()
    summary = {
        "final_accuracy": acc,
        "steps": losses_seen,
        "wall_s": time.time() - t0,
    }
    _telemetry_close(tele_hub, tele_exp)
    print(json.dumps({"tag": "cluster-ps", **summary}), flush=True)
    return summary


class _ModelPlane:
    """Shared MSMW model-plane state for PS replicas and workers: the live
    rank list, the (possibly degraded) model GAR + fps, and per-peer
    PROGRESS tracking for crash detection.

    Liveness policy (review-hardened, r5): a peer is declared dead only
    when its newest observed round stops ADVANCING across two consecutive
    timeout cycles — "has no frame at the round I want" is NOT death (an
    alive-but-behind replica, e.g. one paying a minutes-long eval compile
    or resuming from a checkpoint, would be misclassified, and a
    permanent drop is self-fulfilling). Publishing always fans out to the
    FULL original rank list — sends to a dead rank cost one bounded queue
    (exchange per-peer senders), while excluding a merely-slow rank from
    the fan-out would starve it into a real partition.

    Drops are NOT permanent (r6, ADVICE r5 #1): every timeout probe also
    reads the dropped ranks' newest rounds, and a dropped rank whose round
    ADVANCES again is re-admitted (``readmit``) with the tolerance
    restored by the same ``_shrink_fps`` feasibility walk. A healthy
    replica falsely dropped during a multi-minute eval/compile pause
    rejoins the plane the first time any observer next times out, instead
    of fragmenting the deployment into asymmetric plane compositions
    forever. (Re-admission is per-observer, like the drop — each process
    converges on the set of peers IT observes making progress.)
    """

    def __init__(self, ps_ranks, model_gar_name, fps, who):
        self.all_ranks = list(ps_ranks)
        self.ranks = list(ps_ranks)
        self.base_gar = model_gar_name
        self.base_fps = fps
        self.gar_name = model_gar_name
        self.fps = fps
        self.who = who
        self._last_step = {}
        self._stalls = {}

    def aggregate(self, models_stack):
        return _jit_model_agg(self.gar_name, self.fps)(
            jnp.asarray(models_stack)
        )

    def note_progress(self, rank, step):
        if step > self._last_step.get(rank, -1):
            self._last_step[rank] = step
            self._stalls[rank] = 0
            return True
        self._stalls[rank] = self._stalls.get(rank, 0) + 1
        return False

    def stalled_out(self, rank):
        return self._stalls.get(rank, 0) >= 2

    def drop(self, dead):
        self.ranks = [r for r in self.ranks if r not in dead]
        self.gar_name, self.fps = _shrink_fps(
            self.base_gar, len(self.ranks), self.base_fps
        )
        tools.warning(
            f"[{self.who}] model plane degraded: ranks {dead} declared "
            f"crashed (no round progress across two timeout cycles); "
            f"{len(self.ranks)} replicas remain, model GAR "
            f"{self.gar_name!r} at fps={self.fps}"
        )
        tele_hooks.emit_event(
            "plane_drop", who=self.who, ranks=[int(r) for r in dead],
            survivors=len(self.ranks), model_gar=self.gar_name,
            fps=int(self.fps),
        )

    def dropped(self):
        return [r for r in self.all_ranks if r not in self.ranks]

    def readmit(self, rank):
        """Restore a previously dropped rank whose round advanced again
        (it was paused, not dead); tolerance re-grows by the same
        feasibility walk the drop shrank it with."""
        if rank in self.ranks:
            return
        self.ranks = sorted(self.ranks + [rank])
        self.gar_name, self.fps = _shrink_fps(
            self.base_gar, len(self.ranks), self.base_fps
        )
        self._stalls[rank] = 0
        tools.warning(
            f"[{self.who}] model plane re-admitted rank {rank} (round "
            f"progress observed after a drop); {len(self.ranks)} replicas, "
            f"model GAR {self.gar_name!r} at fps={self.fps}"
        )
        tele_hooks.emit_event(
            "plane_readmit", who=self.who, rank=int(rank),
            replicas=len(self.ranks), model_gar=self.gar_name,
            fps=int(self.fps),
        )


@functools.lru_cache(maxsize=16)
def _jit_model_agg(name, f2):
    return jax.jit(lambda m: gars[name].unchecked(m, f=f2))


def _shrink_fps(model_gar_name, n_ps, fps):
    """Largest feasible tolerance for the model GAR over n_ps models, and
    the rule to use. Crash degradation (VERDICT r4 #7): after dropping a
    dead replica the configured rule may be infeasible at the surviving
    count (krum needs n >= 2f+3); prefer shrinking fps, and when no fps
    works at all fall back to the coordinate-wise median — feasible at
    any n and still value-robust to a minority — ALWAYS loudly."""
    gar = gars[model_gar_name]
    for f2 in range(min(fps, n_ps - 1), -1, -1):
        try:
            if f2:
                if gar.check(np.zeros((n_ps, 4), np.float32), f=f2) is None:
                    return model_gar_name, f2
            else:
                gar.unchecked(np.zeros((n_ps, 4), np.float32), f=0)
                return model_gar_name, 0
        except Exception:
            continue
    return "median", 0


def _collect_models(ex, step, plane, timeout_ms, split, stats=None,
                    wait_fn=None):
    """The MSMW model plane: the live PS models for ``step``, stacked by
    rank (``plane.ranks`` after any degradation).

    A frame that fails the wire codec (a Byzantine PROCESS controls its
    wire bytes; ``_frame_transform`` stores the ``WireError``) is
    replaced by a ZERO row — a crash-like value fault inside the fps
    budget — with a warning. On repeated timeout the plane DEGRADES
    instead of raising (VERDICT r4 #7), under ``_ModelPlane``'s
    progress-based liveness: each silent slot is probed for its newest
    round at ANY step (``read_latest(r, 0)``); a peer whose newest round
    advanced is alive (merely slow/behind — keep waiting), a peer with
    no advance across two timeout cycles is dropped (and RE-ADMITTED by a
    later probe that sees its round advancing — _ModelPlane.readmit), and
    a probe that
    reveals the plane has MOVED AHEAD of ``step`` (this caller resumed
    or straggled behind its peers) raises ``_Lapped`` so the caller can
    jump. Raises TimeoutError only when every peer slot is silent.
    ``wait_fn`` is the caller's pre-registered harvest (overlap fast
    path; first attempt only). Returns ``(params_rows, bn_rows)``: the
    device-staged params stack and the host stats stack (None when the
    model carries no stats).
    """
    who = plane.who
    transform = _frame_transform(split, stats, plane=PLANE_MODEL)
    attempts = 0
    while True:
        try:
            if wait_fn is not None:
                # Clear BEFORE harvesting (retries must re-collect, not
                # re-harvest an expired registration).
                w, wait_fn = wait_fn, None
                got = w()
            else:
                got = ex.collect(
                    step, len(plane.ranks), peers=plane.ranks,
                    timeout_ms=timeout_ms, transform=transform,
                )
            break
        except TimeoutError:
            attempts += 1
            if attempts < 3:
                tools.warning(
                    f"[{who}] step {step} model plane timed out; waiting "
                    f"again (attempt {attempts})"
                )
                continue
            newest = step
            heard = []
            for r in plane.ranks:
                try:
                    s, _ = ex.read_latest(r, 0, timeout_ms=2_000)
                    heard.append(r)
                    plane.note_progress(r, s)
                    newest = max(newest, s)
                except TimeoutError:
                    plane.note_progress(r, -1)
            # Dropped ranks are probed too (ADVICE r5 #1): a drop is a
            # liveness HYPOTHESIS, and a dropped rank whose newest round
            # advanced has refuted it — re-admit it so a falsely-dropped
            # replica (multi-minute eval/compile pause) rejoins instead of
            # fragmenting the plane permanently. Publishing never stopped
            # fanning out to it, so it kept receiving frames all along.
            readmitted = False
            for r in plane.dropped():
                try:
                    s, _ = ex.read_latest(r, 0, timeout_ms=2_000)
                except TimeoutError:
                    continue
                if plane.note_progress(r, s):
                    plane.readmit(r)
                    newest = max(newest, s)
                    readmitted = True
            if newest > step:
                raise _Lapped(newest)
            if readmitted:
                attempts = 0
                continue  # retry the collect over the restored plane
            dead = [
                r for r in plane.ranks
                if r != ex.my_index and plane.stalled_out(r)
            ]
            survivors = [r for r in plane.ranks if r not in dead]
            if dead and survivors:
                plane.drop(dead)
                attempts = 0
                continue
            if not heard:
                raise
            attempts = 0  # someone is alive and moving; keep waiting
    d0, d1 = split
    rows, bn_rows = [], []
    for r in sorted(plane.ranks):
        v = got.get(r)
        if v is None or isinstance(v, Exception):
            tools.warning(
                f"[{who}] PS rank {r} sent a model frame at step {step} "
                f"that failed the wire codec ({v}); substituting zeros "
                "(a value fault inside the fps budget)"
            )
            rows.append(np.zeros(d0, np.float32))
            bn_rows.append(np.zeros(d1, np.float32))
        else:
            rows.append(v[0])
            bn_rows.append(v[1])
    return jnp.stack(rows), (np.stack(bn_rows) if d1 else None)


class _Lapped(Exception):
    """Model plane has moved past the expected round (resume/straggle):
    carries the newest observed round so the caller can jump forward."""

    def __init__(self, newest):
        super().__init__(f"model plane is at round {newest}")
        self.newest = newest


def _run_ps_multi(args, pindex, ps_ranks, q, worker_ranks, test_batches,
                  optimizer, eval_fn, params0, ms0, flat0, unravel, ex,
                  timeout_ms):
    """One ByzSGD server replica (MSMW, tensorflow_impl ByzSGD/trainer.py
    :76-95 loop shape): per step — publish own model; gather the live PS
    models and GAR-aggregate with tolerance fps (the pytorch "gather
    step", ByzSGD/trainer.py:240-244); collect the q fastest worker
    gradients; gradient-GAR; optimizer update on the aggregated model. A
    PS launched with --ps_attack publishes its model POISONED
    (byzServer.py:86-108) but otherwise runs the honest loop — a live
    lying replica, the exact fault ByzSGD exists to survive.

    r5 (VERDICT r4 #4/#7):
      - BatchNorm statistics travel on BOTH planes like SSMW: gradient
        frames are [grad || stats], model frames [params || stats]; each
        replica blends the model-plane stats aggregate (fps budget) with
        its own worker quorum's stats (f budget) at equal weight — the
        same reconcile-then-refresh shape as the params — so MSMW
        deployments stop silently drifting on BN architectures
        (ByzSGD/trainer.py:240-244 never ships buffers; workers still
        robust-aggregate the PS stats on their side).
      - Checkpoint/resume: each replica saves under
        checkpoint_dir/ps_{pindex}; a replica that resumes behind its
        peers catches up via the model plane (_Lapped: jump to the
        newest round, where the gather step re-synchronizes its model).
        The catch-up publish necessarily carries the RESTORED model into
        the live round once (the gather's stack shape is static) — a
        value fault the fps budget absorbs; at fps=0 resume is a
        full-deployment-restart operation, not a hot-rejoin.
      - Crash degradation: a PS slot with no frame and no newer round is
        dropped from the plane (loudly), fps shrinks to the largest
        feasible tolerance for the survivors (_shrink_fps; the rule
        degrades to the always-feasible coordinate median as a last
        resort) — one SIGKILLed replica no longer halts the deployment,
        unlike the reference's bounded-retry-then-exit (server.py:138-141).
    """
    from .. import parallel

    f = args.fw
    fps = getattr(args, "fps", 0)
    gar = gars[args.gar]
    gar_params = dict(getattr(args, "gar_params", None) or {})
    base_gar_params = dict(gar_params)
    # Closed-loop defense on the MSMW GRADIENT plane (DESIGN.md §17):
    # the SSMW PS's deployment verbatim — suspicion weighting from this
    # replica's own MetricsHub plus the per-replica escalation ladder.
    # The model plane's rule stays PINNED at the configured model GAR
    # (per-plane ladder independence: the fps gather's contract is not
    # this ladder's to change).
    defense_plan = defense_lib.resolve(args)
    esc_policy = None
    if defense_plan is not None:
        if not getattr(args, "telemetry", None):
            args.telemetry = "telemetry"
        if defense_plan.escalate:
            allowed = sorted(
                k for k in defense_lib.LEVEL_RULES if k in gars
            )
            if args.gar not in allowed:
                raise SystemExit(
                    f"--defense escalate needs --gar to name a REGISTERED "
                    f"escalation-ladder rule ({allowed}), got {args.gar!r}"
                )
            esc_policy = defense_plan.policy()
            esc_policy.level = defense_lib.start_level(
                esc_policy.config.levels, args.gar,
                getattr(args, "gar_params", None),
            )
            lvl_gar, lvl_params = esc_policy.current()
            gar = gars[lvl_gar]
            gar_params = {**base_gar_params, **lvl_params}
    model_gar_name = getattr(args, "model_gar", None) or args.gar
    # Byzantine replica (--ps_attack): byzServer's simple attacks, the
    # model-plane collusion statistics, or the ADAPTIVE controller
    # bisecting against the replica gather (DESIGN.md §17).
    poisoner = _ModelPoisoner(
        getattr(args, "ps_attack", None),
        dict(getattr(args, "ps_attack_params", None) or {}),
        n_ranks=len(ps_ranks), f=fps, my_rank=pindex,
        who=f"cluster-ps-{pindex}", plane="model",
    )
    opt_state = optimizer.init(params0)
    bn0_flat, bn_unravel = ravel_pytree(ms0)
    bn_elems = int(np.asarray(bn0_flat).size)
    bn = np.asarray(bn0_flat, np.float32)
    test_batches = parallel.EvalSet(
        test_batches, binary=args.dataset == "pima"
    )
    gar_base_key = jax.random.PRNGKey(args.seed)
    who = f"cluster-ps-{pindex}"
    plane = _ModelPlane(ps_ranks, model_gar_name, fps, who)

    # Telemetry (docs/TELEMETRY.md): same gradient-plane audit tap as the
    # SSMW PS, plus the model-plane liveness events (plane_drop/readmit)
    # and exchange waits through the global hook.
    n_w = len(worker_ranks)
    tele_hub, tele_exp = _telemetry_open(
        args, who, num_ranks=n_w,
        meta={"attack": getattr(args, "attack", None), "q": q,
              "fps": int(fps), "model_gar": model_gar_name},
    )
    # Data-plane defense on the MSMW GRADIENT quorums (DESIGN.md §18):
    # each replica runs its own detector history over the worker frames
    # it decodes — the per-plane independence convention (the model
    # gather is an agreement over replica MODELS; fingerprinting applies
    # to the worker gradient plane only).
    dp_def = None
    if defense_plan is not None and defense_plan.data:
        dp_def = dataplane_lib.DataPlaneDefense(
            n_w, dataplane_lib.head_spec(params0),
            f=max(1, f), plane="gradient",
            tau=defense_plan.dp_tau, power=defense_plan.dp_power,
            floor=defense_plan.dp_floor,
            halflife=defense_plan.dp_halflife,
        )

    def _build_tap(g, gp):
        if tele_hub is None:
            return None
        from ..telemetry import taps as taps_lib

        @jax.jit
        def tap_fn(stack, sel):
            bundle = taps_lib.compute_flat(g.name, stack, f, params=gp)
            return taps_lib.scatter(bundle, sel, n_w)

        return tap_fn

    tap_fn = _build_tap(gar, gar_params)

    def _build_updates(g, gp):
        """(ps_update, ps_update_weighted) jits for one rule — rebuilt on
        a defense-escalation level change (the SSMW PS convention)."""

        def _update_body(flat_params, opt_state, grads_stack, step):
            if f or g.name != "average":
                agg = g.unchecked(
                    grads_stack, f=f,
                    key=jax.random.fold_in(gar_base_key, step), **gp,
                )
            else:
                agg = jnp.mean(grads_stack, axis=0)
            params = unravel(flat_params)
            updates, opt_state2 = optimizer.update(
                unravel(agg), opt_state, params
            )
            params = optax.apply_updates(params, updates)
            return ravel_pytree(params)[0], opt_state2

        # Staleness/suspicion-weighted twin (DESIGN.md §14/§16) — see
        # _run_ps: weights compose into the stack before the GAR;
        # all-fresh fully-trusted quorums dispatch the unweighted program
        # (the --max_staleness 0 bitwise contract).
        return jax.jit(_update_body), jax.jit(
            lambda fp, ost, stack, w, step: _update_body(
                fp, ost, stack * w[:, None], step
            )
        )

    ps_update, ps_update_weighted = _build_updates(gar, gar_params)

    t0 = time.time()
    flat = np.asarray(flat0, np.float32)
    flat_dev = jnp.asarray(flat)  # --num_iter 0: eval the init model
    good_ranks = list(worker_ranks)
    wire_stats = WireStats(who)
    split = (flat.size, bn_elems)
    model_tf = _frame_transform(split, wire_stats, plane=PLANE_MODEL)
    grad_tf = _frame_transform(split, wire_stats, plane=PLANE_GRAD)
    # --async (DESIGN.md §14): bounded staleness applies to the WORKER
    # gradient plane only — the PS-replica model gather stays exact-round
    # (the ByzSGD fps contract is an agreement over one round's models;
    # mixing rounds there would let a lagging replica's stale model count
    # as a live vote).
    policy = rounds.resolve(args)
    collector = None
    if policy is not None:
        collector = ex.round_collector(worker_ranks, transform=grad_tf)
    ckpt = None
    start_iter = last_saved = 0
    if args.checkpoint_dir:
        import os

        from ..utils import checkpoint as ckpt_lib

        ckpt = ckpt_lib.Checkpointer(
            os.path.join(args.checkpoint_dir, f"ps_{pindex}")
        )
        step = ckpt.latest_step()
        if args.resume and step is not None:
            restored = ckpt.restore(
                {"flat": flat, "opt_state": jax.tree.map(
                    np.asarray, opt_state),
                 **({"bn": bn} if bn_elems else {})},
                step=step,
            )
            flat = np.asarray(restored["flat"], np.float32)
            flat_dev = jnp.asarray(flat)
            opt_state = jax.tree.map(jnp.asarray, restored["opt_state"])
            if bn_elems:
                bn = np.asarray(restored["bn"], np.float32)
            start_iter = last_saved = int(step)
            print(f"[{who}] resumed from step {start_iter}", flush=True)
    losses_seen = start_iter
    i = start_iter
    model_wait = grad_wait = None
    while i < args.num_iter:
        # Byzantine replica publication (byzServer semantics; the
        # collusion/adaptive shapes poison the params segment against
        # the LAST gathered replica stack — _ModelPoisoner).
        vec = poisoner.publish_frame(flat, bn if bn_elems else None, i)
        # Fan out to the FULL original plane (a dead rank costs one
        # bounded sender queue; excluding a merely-slow rank would starve
        # it into a real partition — _ModelPlane docstring). NOTE: after a
        # _Lapped catch-up this publish carries the restored (stale)
        # model into the live round once — a value fault the fps budget
        # absorbs (at fps=0, resume is a full-restart operation; the
        # docstring says so).
        everyone = [
            r for r in plane.all_ranks if r != ex.my_index
        ] + list(worker_ranks)
        with tele_trace.span("broadcast", step=i):
            frame = _encode_frame([vec], wire_stats, fanout=len(everyone),
                                  plane=PLANE_MODEL)
            ex.publish(i, frame, to=everyone)
        try:
            with tele_trace.span("model_gather", step=i):
                models_p, models_bn = _collect_models(
                    ex, i, plane, timeout_ms, split,
                    stats=wire_stats, wait_fn=model_wait,
                )
        except _Lapped as lap:
            # Resumed/straggled behind the peers: jump to their round; the
            # gather step there re-synchronizes the model (docstring). Any
            # pre-registered waiters for the abandoned round self-expire
            # when the plane's slots advance past it.
            tools.warning(
                f"[{who}] behind the model plane at round {i}; jumping "
                f"to round {lap.newest}"
            )
            i = lap.newest
            # Abandoned-round registrations must not leak their waiter
            # threads until the slots happen to advance past them.
            _cancel_wait(model_wait)
            _cancel_wait(grad_wait)
            model_wait = grad_wait = None
            continue
        model_wait = None  # consumed
        if poisoner.kind is not None:
            # Collusion statistics + adaptive probe feed (the gathered
            # rows are this round's replica plane, ranks in sorted
            # order — _collect_models' stacking contract).
            poisoner.note_gather(
                np.asarray(models_p), sorted(plane.ranks), i
            )
        flat_dev = plane.aggregate(models_p)
        if bn_elems:
            # Model-plane BN aggregate (fps budget) — BLENDED with the
            # worker quorum's stats below, not overwritten (ADVICE r5 #2:
            # the old assignment here was dead, so replicas never actually
            # reconciled BN state).
            bn_plane = _robust_stats(models_bn, plane.fps)
        w = None
        if collector is not None:
            with tele_trace.span("quorum", step=i):
                got = _async_gradient_quorum(
                    collector, i, q, policy,
                    lambda: ex.publish(i, frame, to=everyone),
                    timeout_ms, who,
                )
            quorum, taus, w = _staleness_quorum(
                got, i, q, policy, worker_ranks, who
            )
            rows = {k: got[k][1] for k in quorum}
        else:
            with tele_trace.span("quorum", step=i):
                got, good_ranks = _gradient_quorum(
                    ex, i, q, good_ranks, split,
                    lambda: ex.publish(i, frame, to=everyone),
                    timeout_ms, who, stats=wire_stats, wait_fn=grad_wait,
                )
            grad_wait = None
            quorum = sorted(got)[:q]
            rows = {k: got[k] for k in quorum}
        # Overlap (DESIGN.md §11): next round's planes registered before
        # the device update/eval — peer models and fast workers' gradients
        # decode + stage while this replica still computes. (The async
        # gradient plane needs no registration: its collector watches
        # every round persistently.)
        if i + 1 < args.num_iter:
            model_wait = ex.collect_begin(
                i + 1, len(plane.ranks), timeout_ms=timeout_ms,
                peers=plane.ranks, transform=model_tf,
            )
            if collector is None:
                grad_wait = ex.collect_begin(
                    i + 1, q, timeout_ms=timeout_ms, peers=good_ranks,
                    transform=grad_tf,
                )
        with tele_trace.span("gar_apply", step=i):
            stack = jnp.stack([rows[k][0] for k in quorum])
            if bn_elems:
                # BN reconciliation mirrors the params: equal-weight
                # blend of the peer replicas' robust-aggregated stats
                # (published next round) with this quorum's fresh worker
                # stats. Replicas see overlapping-but-different worker
                # quorums, so without the plane term their BN states
                # drift apart unboundedly; the 1/2 contraction bounds
                # the spread at O(one quorum's dispersion) while still
                # tracking the live statistics (the on-mesh twin's pmean
                # over the ps axis, parallel/byzsgd.py, is the
                # limit-case of this blend).
                with tele_trace.span("bn_stats", step=i):
                    bn = 0.5 * (bn_plane + _robust_stats(
                        np.stack([rows[k][1] for k in quorum]), f
                    ))
            if dp_def is not None:
                # Data-plane detectors (DESIGN.md §18): the SSMW PS's
                # per-quorum composition verbatim — detect, fold the
                # EMA, center-pull suspect rows onto the trusted mean —
                # against this replica's own detector history.
                qidx = [k - worker_ranks[0] for k in quorum]
                rep = dp_def.observe(qidx, np.asarray(stack, np.float32))
                tele_hooks.emit_event(
                    "data_defense", who=who, step=int(i),
                    plane="gradient",
                    ranks=[int(x) for x in qidx],
                    scores=[round(float(s), 6) for s in rep["scores"]],
                    flags=[int(x) for x in rep["flags"]],
                    weights=[round(float(x), 6) for x in
                             dp_def.weights_full()[qidx]],
                )
                w_dp = dp_def.weights_for(qidx)
                if w_dp is not None:
                    stack = dataplane_lib.center_pull_rows(
                        stack, jnp.asarray(w_dp)
                    )
            if defense_plan is not None and defense_plan.weighted \
                    and tele_hub is not None:
                # Suspicion weighting on the MSMW gradient plane
                # (DESIGN.md §17): the SSMW PS's per-quorum composition
                # verbatim — decayed median-relative suspicion from this
                # replica's own hub, multiplied into the same row-scale
                # slot as the staleness discount.
                susp = tele_hub.suspicion_decayed()
                if susp is not None:
                    qidx = [k - worker_ranks[0] for k in quorum]
                    w_def = np.asarray(defense_lib.suspicion_weights(
                        susp, power=defense_plan.power,
                        floor=defense_plan.floor,
                    ))[qidx].astype(np.float32)
                    tele_hooks.emit_event(
                        "defense_weights", who=who, step=int(i),
                        ranks=[int(x) for x in qidx],
                        weights=[round(float(x), 6) for x in w_def],
                    )
                    if not np.all(w_def == 1.0):
                        w = w_def if w is None else (
                            np.asarray(w) * w_def
                        ).astype(np.float32)
            if w is not None and not np.all(w == 1.0):
                stack_gar = stack * jnp.asarray(w)[:, None]
                flat_dev, opt_state = ps_update_weighted(
                    flat_dev, opt_state, stack, jnp.asarray(w),
                    jnp.asarray(i, jnp.int32),
                )
            else:
                stack_gar = stack
                flat_dev, opt_state = ps_update(
                    flat_dev, opt_state, stack,
                    jnp.asarray(i, jnp.int32),
                )
            flat = np.asarray(flat_dev, np.float32)
        wire_stats.flush(i)
        if tele_hub is not None:
            with tele_trace.span("audit", step=i):
                sel = jnp.asarray(
                    [k - worker_ranks[0] for k in quorum], jnp.int32
                )
                tele_hub.record_step(
                    i, tap=tap_fn(stack_gar, sel),
                )
        if esc_policy is not None and tele_hub is not None:
            # Per-replica escalation ladder on the gradient plane
            # (DESIGN.md §17) — the SSMW PS's hysteresis loop: a level
            # infeasible at this quorum size is refused loudly and
            # reverted; the model plane's rule never moves.
            susp = tele_hub.suspicion_decayed()
            if susp is not None:
                conc = float(defense_lib.suspicion_concentration(
                    susp, max(1, f)
                ))
                act = esc_policy.observe(conc)
                if act:
                    name, lvl_params = esc_policy.current()
                    new_gar = gars[name]
                    msg = new_gar.check(
                        np.zeros((q, 4), np.float32), f=f
                    ) if f else None
                    if msg is not None:
                        tools.warning(
                            f"[{who}] defense cannot escalate to "
                            f"{name!r} at q={q}: {msg}"
                        )
                        esc_policy.level -= act
                    else:
                        gar = new_gar
                        gar_params = {**base_gar_params, **lvl_params}
                        ps_update, ps_update_weighted = _build_updates(
                            gar, gar_params
                        )
                        tap_fn = _build_tap(gar, gar_params)
                        tools.warning(
                            f"[{who}] defense "
                            f"{'escalates' if act > 0 else 'de-escalates'}"
                            f" to {esc_policy.level_name!r} at step {i} "
                            f"(suspicion concentration {conc:.3f})"
                        )
                        tele_hooks.emit_event(
                            "defense_escalate", who=who, step=int(i),
                            plane="gradient",
                            level=int(esc_policy.level),
                            rule=str(esc_policy.level_name),
                            direction=(
                                "escalate" if act > 0 else "deescalate"
                            ),
                            gar=name,
                            concentration=round(conc, 6),
                        )
        losses_seen = i + 1
        if ckpt and args.checkpoint_freq and (i + 1) % args.checkpoint_freq == 0:
            with tele_trace.span("checkpoint", step=i):
                ckpt.save(i + 1, {
                    "flat": flat,
                    "opt_state": jax.tree.map(np.asarray, opt_state),
                    **({"bn": bn} if bn_elems else {}),
                })
            last_saved = i + 1
        if args.acc_freq and i % args.acc_freq == 0:
            with tele_trace.span("eval", step=i):
                acc = parallel.compute_accuracy(
                    (unravel(flat_dev), bn_unravel(jnp.asarray(bn))),
                    lambda s, x: eval_fn(s[0], s[1], x),
                    test_batches, binary=args.dataset == "pima",
                )
            print(
                f"Step: {i} Accuracy: {acc:.4f} "
                f"Time: {time.time() - t0:.1f}",
                flush=True,
            )
        i += 1
    # Waiter lifecycle: retire anything the loop left registered (the
    # exception paths fall through to run()'s ex.close(), whose close
    # sentinel wakes and joins every watcher before the register frees).
    _cancel_wait(model_wait)
    _cancel_wait(grad_wait)
    if collector is not None:
        collector.close()
    acc = parallel.compute_accuracy(
        (unravel(flat_dev), bn_unravel(jnp.asarray(bn))),
        lambda s, x: eval_fn(s[0], s[1], x),
        test_batches, binary=args.dataset == "pima",
    )
    if ckpt:
        if args.checkpoint_freq and last_saved != args.num_iter:
            ckpt.save(args.num_iter, {
                "flat": flat,
                "opt_state": jax.tree.map(np.asarray, opt_state),
                **({"bn": bn} if bn_elems else {}),
            })
        ckpt.close()
    summary = {
        "final_accuracy": acc,
        "steps": losses_seen,
        "wall_s": time.time() - t0,
        **({"ps_attack_adapt": poisoner.stats()}
           if poisoner.stats() else {}),
    }
    _telemetry_close(tele_hub, tele_exp)
    print(json.dumps({"tag": who, **summary}), flush=True)
    return summary


def _run_learn(args):
    """One LEARN peer: worker AND server in the same process
    (LEARN/trainer.py:224-231), gossiping over PeerExchange.

    Per iteration (LEARN/trainer.py:251-257, both planes at per-node
    wait-n-f): compute the local gradient on the own model; publish it;
    collect the q = n - f FASTEST peer gradients (self included) and
    GAR-aggregate; apply the local optimizer; publish the updated model;
    collect the q fastest peer models and model-GAR-aggregate (the gossip
    that keeps honest models from drifting apart). The two planes share
    one exchange slot per node via step multiplexing (barrier at 0,
    gradients at 2i+2, models at 2i+3 — the last-writer-wins register then
    ages out a round's gradient exactly when its publisher moves on, which
    is the wait-n-f contract). The non-iid ⌈log2 t⌉ agreement rounds
    (avg_agree, :208-222) remain the on-mesh topology's domain
    (parallel/learn.py).

    Liveness: the loop is preceded by a jit WARMUP and an all-nodes
    BARRIER — without them, compile skew lets the fast majority form
    quorums among themselves and age a slow node's rounds out of the
    register before it ever sees them. A node that still loses a round's
    quorum in steady state retries, then exits GRACEFULLY as a dropout —
    the reference's bounded-retry-then-exit(0) semantics
    (server.py:138-141, ps.py:84-88): the survivors' wait-n-f quorums flow
    around it exactly as around a crash.

    A node with --attack is a real Byzantine peer poisoning its published
    gradient (cohort attacks compute their own local statistics); with
    --model_attack it also poisons its gossiped model (the LEARN-side
    byzServer analog). A SIGKILLed node simply stops publishing and every
    survivor's wait-n-f quorum flows around it.

    ``--async`` (DESIGN.md §15): bounded-staleness gossip over PER-PLANE
    register slots. The old single-slot multiplexing (which is what made
    LEARN reject --async through r11 — a round-tagged watcher could not
    hold a stale gradient once its publisher gossiped the model over it)
    is replaced by a 3-plane exchange: control beacons on plane 0,
    gradients on PLANE_GRAD, models on PLANE_MODEL, each with its own
    persistent ``RoundCollector``. Per round each node PUBLISHES-AND-
    CONTINUES on both planes: it gathers the freshest q = n - f
    admissible plane-tagged frames per phase (stale frames within
    ``--max_staleness`` are REUSED with ``utils/rounds.py`` discount
    weights composed into the stack before the rule — the same
    Kardam-style law the PS plane applies, so one slow node stops
    setting every honest node's pace), and ``--max_staleness 0`` is
    bitwise the synchronous trajectory (exact-round admission, all
    weights exactly 1.0, the unweighted jit programs).
    """
    cfg = multihost.ClusterConfig(args.cluster)
    if args.task:
        ttype, _, tidx = args.task.partition(":")
        cfg.task_type = ttype
        cfg.task_index = int(tidx or 0)
    n = len(cfg.nodes)
    f = args.fw
    q = n - f
    if not f * 2 < n:
        raise SystemExit(
            f"the number of Byzantine nodes should be less than half the "
            f"number of nodes (fw={f}, config has {n} nodes)"
        )
    if f:
        msg = gars[args.gar].check(np.zeros((q, 4), np.float32), f=f)
        if msg is not None:
            raise SystemExit(
                f"GAR {args.gar!r} cannot run on the q = n - fw = {q} "
                f"collected rows: {msg}"
            )
    # Bounded-staleness async gossip (--async, DESIGN.md §15): the
    # exchange grows per-plane register slots — control beacons keep
    # plane 0, gradients and models each get their own slot per peer, so
    # the planes stop overwriting each other in the last-writer-wins
    # register (the multiplexing limitation that made LEARN reject
    # --async through r11).
    policy = rounds.resolve(args)
    # The exchange (and the stage-1 liveness hello, below) must exist
    # BEFORE any heavy local work: model init + data staging compile for
    # minutes on a loaded host, and a peer's barrier read cannot see that
    # (r5 — observed 4 co-located ResNet-class inits blowing the fixed
    # barrier budget when the hello waited for them).
    ex = PeerExchange(
        cfg.process_id, cfg.hosts, connect_retry_ms=_startup_ms(args),
        planes=3 if policy is not None else 1,
    )
    ex.publish(0, b"up")
    xs, ys, test_batches, iters_per_epoch = common.load_data(args, n)
    module, loss_fn, optimizer = common.build_ingredients(
        args, iters_per_epoch
    )
    init_fn, grad_fn, eval_fn = core.make_worker_fns(module, loss_fn)
    params0, ms0 = init_fn(jax.random.PRNGKey(args.seed), xs[0, 0])
    my_xs, my_ys = xs[cfg.task_index], ys[cfg.task_index]
    flat0, unravel = ravel_pytree(params0)

    from .. import parallel

    me = cfg.task_index
    gar = gars[args.gar]
    model_gar = gars[getattr(args, "model_gar", None) or args.gar]
    gar_params = dict(getattr(args, "gar_params", None) or {})
    atk_kind, attack, atk_cohort = _host_attack(
        args.attack, args.attack_params, f
    )
    if atk_kind == "adaptive":
        # The LEARN gossip plane has no single broadcast-model feedback
        # channel (every node aggregates its own view), so the adaptive
        # controller's probe is undefined here — reject loudly instead
        # of silently running an oblivious loop.
        raise SystemExit(
            f"--attack {args.attack!r} drives the PS-topology worker "
            "role; LEARN nodes support the oblivious gradient attacks "
            "(random/reverse/lie/empire), the targeted poisoners "
            "(labelflip/backdoor), and the ADAPTIVE gossip attacks via "
            "--model_attack adaptive-* (the model plane is where a "
            "LEARN node has a probe)"
        )
    # Closed-loop defense on LEARN's gossip phases (DESIGN.md §17): one
    # ``PlaneDefense`` PER PLANE — the gradient gather and the model
    # gossip keep INDEPENDENT decayed exclusion histories and independent
    # escalation ladders (the gradient ladder moving must not drag the
    # gossip rule along, and vice versa). Suspicion weights compose into
    # ``node_update_weighted``/``model_aggregate_weighted`` through the
    # same row-scale slot as the async staleness discount; the per-level
    # jits are cached per rule like the SSMW PS's.
    defense_plan = defense_lib.resolve(args)
    grad_def = gossip_def = None
    if defense_plan is not None and defense_plan.data:
        # The data-plane detectors deploy on the PS gradient quorums
        # (SSMW/MSMW) and the on-mesh SSMW step (DESIGN.md §18); a LEARN
        # node's per-phase quorums keep the GAR-side ladder only.
        tools.warning(
            f"[cluster-node-{cfg.task_index}] --defense data: the "
            "data-plane detectors are a PS-quorum deployment; LEARN "
            "nodes apply the GAR-side defense components only"
        )
        if not defense_plan.weighted and not defense_plan.escalate:
            defense_plan = None
    if defense_plan is not None:
        if not getattr(args, "telemetry", None):
            args.telemetry = "telemetry"
        if defense_plan.escalate:
            allowed = sorted(
                k for k in defense_lib.LEVEL_RULES if k in gars
            )
            for plane_name, rule in (
                ("gradient", args.gar),
                ("gossip", getattr(args, "model_gar", None) or args.gar),
            ):
                if rule not in allowed:
                    raise SystemExit(
                        f"--defense escalate on the LEARN {plane_name} "
                        f"plane needs its rule to name a REGISTERED "
                        f"escalation-ladder level ({allowed}), got "
                        f"{rule!r}"
                    )
        grad_def = defense_lib.PlaneDefense(
            defense_plan, n, f=f, plane="gradient",
            base_gar=args.gar, base_params=gar_params,
        )
        gossip_def = defense_lib.PlaneDefense(
            defense_plan, n, f=f, plane="gossip",
            base_gar=getattr(args, "model_gar", None) or args.gar,
        )
    # Byzantine gossip publisher (--model_attack): byzServer's simple
    # attacks, the model-plane collusion statistics, or the ADAPTIVE
    # controller bisecting against the gossip quorum (DESIGN.md §17).
    poisoner = _ModelPoisoner(
        getattr(args, "model_attack", None),
        dict(getattr(args, "model_attack_params", None) or {}),
        n_ranks=n, f=f, my_rank=me, who=f"cluster-node-{me}",
        plane="gossip",
    )
    beta = getattr(args, "worker_momentum", None)
    mom = None
    eval_set = parallel.EvalSet(test_batches, binary=args.dataset == "pima")
    gar_base_key = jax.random.PRNGKey(args.seed)
    opt_state = optimizer.init(params0)

    @jax.jit
    def worker_grad(flat_params, ms, x, y, rng):
        grads, (loss, new_ms) = grad_fn(unravel(flat_params), ms, x, y, rng)
        return ravel_pytree(grads)[0], loss, new_ms

    def _build_node_updates(g, gp):
        """(node_update, node_update_weighted) jits for one gradient-
        plane rule — rebuilt on a defense-escalation level change."""

        def _node_update_body(flat_params, opt_state, grads_stack, step):
            agg = g.unchecked(
                grads_stack, f=f,
                key=jax.random.fold_in(gar_base_key, step), **gp,
            )
            params = unravel(flat_params)
            updates, opt_state2 = optimizer.update(
                unravel(agg), opt_state, params
            )
            return (
                ravel_pytree(optax.apply_updates(params, updates))[0],
                opt_state2,
            )

        # Staleness/suspicion-weighted twin (DESIGN.md §15/§17) — the PS
        # plane's composition verbatim: weights scale the rows BEFORE
        # the rule; an all-fresh fully-trusted quorum dispatches the
        # unweighted program, which is the --max_staleness 0 (and
        # defense-off) bitwise contract.
        return jax.jit(_node_update_body), jax.jit(
            lambda fp, ost, stack, w, step: _node_update_body(
                fp, ost, stack * w[:, None], step
            )
        )

    node_update, node_update_weighted = _build_node_updates(
        gar, gar_params
    )

    def _build_model_aggs(g, gp):
        """(model_aggregate, model_aggregate_weighted) jits for one
        gossip-plane rule — the gossip ladder's per-level programs."""

        def _model_aggregate_body(models_stack, step):
            return g.unchecked(
                models_stack, f=f,
                key=jax.random.fold_in(
                    jax.random.fold_in(gar_base_key, step), 1
                ), **gp,
            )

        # Gossip-plane staleness/suspicion composition (DESIGN.md
        # §15/§17): a discounted model row is treated as the outlier it
        # is; all-fresh trusted quorums dispatch the unweighted program
        # (the ms=0 bitwise contract).
        return jax.jit(_model_aggregate_body), jax.jit(
            lambda stack, w, step: _model_aggregate_body(
                stack * w[:, None], step
            )
        )

    model_aggregate, model_aggregate_weighted = _build_model_aggs(
        model_gar, {}
    )

    def _rebuild_grad(new_g, gp):
        nonlocal gar, gar_params, node_update, node_update_weighted
        nonlocal grad_tap
        gar = new_g
        gar_params = gp
        node_update, node_update_weighted = _build_node_updates(new_g, gp)
        grad_tap = _plane_tap(new_g, gp)

    def _rebuild_gossip(new_g, gp):
        nonlocal model_gar, model_aggregate, model_aggregate_weighted
        nonlocal gossip_tap
        model_gar = new_g
        model_aggregate, model_aggregate_weighted = _build_model_aggs(
            new_g, gp
        )
        gossip_tap = _plane_tap(new_g, gp)

    def _compose_w(w, gw):
        """Compose a quorum's staleness weights (length q, or None) with
        the defense's per-row weights (length <= q, or None; pad rows
        are fully trusted) — one row-scale multiply, like the PS."""
        if gw is None:
            return w
        full = np.ones(q, np.float32)
        full[:len(gw)] = gw
        if w is None:
            return jnp.asarray(full)
        return jnp.asarray(
            (np.asarray(w, np.float32) * full).astype(np.float32)
        )

    def _plane_tap(g, gp):
        """Jitted per-quorum audit for one plane's rule: the rule's
        selection weights over exactly the quorum stack it consumed —
        what feeds the plane's ``PlaneDefense`` history."""
        from ..telemetry import taps as taps_lib

        @jax.jit
        def tap(stack, key):
            return taps_lib.compute_flat(
                g.name, stack, f, key=key, params=gp
            )["selected"]

        return tap

    grad_tap = gossip_tap = None
    if defense_plan is not None:
        grad_tap = _plane_tap(gar, gar_params)
        gossip_tap = _plane_tap(model_gar, {})

    def _plane_escalate(pdef, i, rebuild):
        """One round of a plane's escalation ladder: fold concentration,
        validate feasibility at q, rebuild the plane's jits on a level
        change (or revert loudly)."""
        act = pdef.observe()
        if not act:
            return
        name, lvl_params = pdef.current()
        new_g = gars[name]
        msg = new_g.check(np.zeros((q, 4), np.float32), f=f) if f else None
        if msg is not None:
            tools.warning(
                f"[{who}] defense cannot escalate the {pdef.plane} plane "
                f"to {name!r} at q={q}: {msg}"
            )
            pdef.revert(act)
            return
        rebuild(new_g, lvl_params)
        tools.warning(
            f"[{who}] defense {'escalates' if act > 0 else 'de-escalates'}"
            f" the {pdef.plane} plane to {pdef.policy.level_name!r} at "
            f"round {i} (concentration {pdef.concentration():.3f})"
        )
        tele_hooks.emit_event(
            "defense_escalate", who=who, step=int(i), plane=pdef.plane,
            level=int(pdef.policy.level),
            rule=str(pdef.policy.level_name),
            direction="escalate" if act > 0 else "deescalate",
            gar=name,
            concentration=round(pdef.concentration(), 6),
        )

    def _audit_plane(pdef, tap, stack, ranks, i, key, plane):
        """Fold one quorum's selection verdict into the plane's defense
        history (+ the per-round defense_weights event) and return the
        composed per-row weights for THIS quorum (None = all-1.0)."""
        if pdef is None or not ranks:
            return None
        sel = np.asarray(tap(stack, key))[:len(ranks)]
        pdef.fold(ranks, sel)
        w = pdef.weights_for(ranks)
        if w is not None:
            tele_hooks.emit_event(
                "defense_weights", who=who, step=int(i), plane=plane,
                ranks=[int(r) for r in ranks],
                weights=[round(float(x), 6) for x in w],
            )
        return w

    def harvest(wait_fn, split):
        """Drain a pre-registered quorum, stack the q lowest-rank
        WELL-FORMED rows (frames arrive pre-decoded and device-staged by
        ``_frame_transform`` on the waiter threads). Frames the wire
        codec rejected (Byzantine wire bytes — the stored ``WireError``)
        are filtered FIRST, so an extra well-formed frame from a higher
        rank replaces a malformed lower one (ADVICE r4: discarding honest
        data while feeding the GAR substitute zeros would hand the
        attacker a second fault for free); zero rows — a crash-like value
        fault inside the f budget — pad only when fewer than q
        well-formed frames exist. Returns ``(rows, bn_rows, ranks)``:
        the stacks (``bn_rows`` None when the plane carries no stats
        segment) plus the contributing peers' rank ids in row order
        (pad rows carry no rank) — the attribution the per-plane
        defense audit keys on (DESIGN.md §17)."""
        got = wait_fn()
        d0, d1 = split
        well_formed = []
        for k in sorted(got):
            v = got[k]
            if not isinstance(v, Exception):
                well_formed.append((k, v))
            elif k not in warned_malformed:  # once per peer, not per round
                warned_malformed.add(k)
                tools.warning(
                    f"[{who}] peer rank {k} sent a frame that failed the "
                    f"wire codec ({v}); dropping its malformed frames "
                    "from every quorum (warned once)"
                )
        ranks = [k for k, _ in well_formed[:q]]
        rows = [v[0] for _, v in well_formed[:q]]
        bn_rows = [v[1] for _, v in well_formed[:q]]
        while len(rows) < q:
            rows.append(np.zeros(d0, np.float32))
            bn_rows.append(np.zeros(d1, np.float32))
        return (
            jnp.stack(rows), (np.stack(bn_rows) if d1 else None), ranks
        )

    who = f"cluster-node-{me}"
    warned_malformed = set()

    def gather_rows(collector, i, split, phase):
        """The bounded-staleness twin of ``harvest`` (one per-plane
        ``RoundCollector``): admissible frames for round ``i`` — stale
        within ``--max_staleness`` REUSED — composed as the freshest q
        rows (ties on rank: at ms=0 this is exactly ``harvest``'s
        lowest-rank composition), with ``utils/rounds.py`` discount
        weights. Malformed frames (stored ``WireError``) retire the
        peer's watcher (the PS plane's ban semantics, softened to
        drop-and-flow like ``harvest``); zero rows pad below q. Emits the
        per-round plane-tagged ``staleness`` telemetry event (schema v6)
        whose discount deficits feed this node's suspicion ranking.
        Returns ``(stack, bn_stack|None, weights|None, ranks)`` —
        weights None when every admitted row is fresh, so the caller
        dispatches the UNWEIGHTED jit program (the ms=0 bitwise
        contract); ``ranks`` are the quorum's peer ids in row order
        (pad rows carry no rank), the defense audit's attribution."""
        got = collector.gather(
            i, q, max_staleness=policy.max_staleness,
            timeout_ms=args.cluster_timeout_ms,
        )
        d0, d1 = split
        well = {}
        for k, (tag, v) in got.items():
            if isinstance(v, Exception):
                if k not in warned_malformed:
                    warned_malformed.add(k)
                    tools.warning(
                        f"[{who}] peer rank {k} sent a frame that failed "
                        f"the wire codec ({v}); retiring its watcher "
                        "(warned once)"
                    )
                    collector.remove_peer(k)
            else:
                well[k] = (tag, v)
        quorum = sorted(well, key=lambda k: (i - well[k][0], k))[:q]
        taus = [max(0, i - well[k][0]) for k in quorum]
        rows = [well[k][1][0] for k in quorum]
        bn_rows = [well[k][1][1] for k in quorum]
        while len(rows) < q:
            rows.append(np.zeros(d0, np.float32))
            bn_rows.append(np.zeros(d1, np.float32))
            taus.append(0)
        w = np.asarray(
            policy.weights(np.asarray(taus, np.int64)), np.float32
        )
        if tele_hooks.current() is not None:
            # The audit covers EVERY admissible frame, not just the
            # composed freshest-q quorum: a badly lagging peer rarely
            # makes the quorum at all, and auditing only the quorum
            # would hide exactly the rank the discount deficit exists
            # to expose (its observed stale frames must keep feeding
            # its suspicion even when fresher peers out-compose it).
            adm = sorted(well)
            adm_taus = np.asarray(
                [max(0, i - well[k][0]) for k in adm], np.int64
            )
            adm_w = np.asarray(policy.weights(adm_taus), np.float32)
            tele_hooks.emit_event(
                "staleness", who=who, step=int(i), plane=phase,
                ranks=[int(k) for k in adm],
                staleness=[int(t) for t in adm_taus],
                weights=[round(float(x), 6) for x in adm_w],
                reused=int((adm_taus > 0).sum()),
            )
        return (
            jnp.stack(rows),
            (np.stack(bn_rows) if d1 else None),
            (jnp.asarray(w) if not np.all(w == 1.0) else None),
            list(quorum),
        )

    # LEARN-peer telemetry: exchange wait latencies + liveness events
    # stream here; async mode adds per-plane staleness events whose
    # discount deficits rank a straggling peer in this node's suspicion.
    # With --defense the per-plane quorum audits (``_audit_plane``) feed
    # the node's OWN rank-attributed defense histories — the plane
    # deployment DESIGN.md §17 describes.
    tele_hub, tele_exp = _telemetry_open(args, who, num_ranks=n)
    # Targeted poisoner (labelflip/backdoor): config built after the hub
    # install so the one-time binary-surrogate event reaches the stream.
    targeted_cfg = None
    if atk_kind == "targeted":
        targeted_cfg = _targeted_config(args, who)
    t0 = time.time()
    base_key = jax.random.PRNGKey(args.seed + 1 + me)
    flat = np.asarray(flat0, np.float32)
    flat_dev = jnp.asarray(flat)
    ms = ms0
    bn0_flat, bn_unravel = ravel_pytree(ms0)
    bn_elems = int(np.asarray(bn0_flat).size)
    num_batches = my_xs.shape[0]
    dropped_at = None
    # Wire plane (DESIGN.md §11): LEARN's gradient plane ships bare
    # gradients, the gossip plane [params || stats] — both through the
    # typed codec, decoded eagerly by the pre-registered waiters.
    wire_stats = WireStats(who)
    grad_ef = _maybe_error_feedback(who, wire_stats)
    grad_split = (flat.size, 0)
    gossip_split = (flat.size, bn_elems)
    grad_tf = _frame_transform(grad_split, wire_stats, plane=PLANE_GRAD)
    gossip_tf = _frame_transform(gossip_split, wire_stats,
                                 plane=PLANE_MODEL)
    # Per-node checkpoint/resume (r5): each peer persists its OWN model +
    # optimizer + BN stats under checkpoint_dir/node_{me}. Resume expects
    # the whole deployment to restart from a common step (the round-
    # indexed gossip planes give a lone restarted node no quorum for its
    # old rounds — it would exit as a dropout, the documented semantics).
    ckpt = None
    start_iter = 0
    if args.checkpoint_dir:
        import os

        from ..utils import checkpoint as ckpt_lib

        ckpt = ckpt_lib.Checkpointer(
            os.path.join(args.checkpoint_dir, f"node_{me}")
        )
        step0 = ckpt.latest_step()
        if getattr(args, "resume", False) and step0 is not None:
            restored = ckpt.restore(
                {"flat": flat,
                 "opt_state": jax.tree.map(np.asarray, opt_state),
                 **({"bn": np.asarray(bn0_flat, np.float32)}
                    if bn_elems else {})},
                step=step0,
            )
            flat = np.asarray(restored["flat"], np.float32)
            flat_dev = jnp.asarray(flat)
            opt_state = jax.tree.map(jnp.asarray, restored["opt_state"])
            if bn_elems:
                ms = bn_unravel(jnp.asarray(restored["bn"]))
            start_iter = int(step0)
            print(f"[{who}] resumed from step {start_iter}", flush=True)
    try:
        # Startup rendezvous (r5 redesign; comment corrected r6, ADVICE r5
        # #4): the hello at step 0 (published the moment the exchange
        # exists, before data/model init) is a cheap config-error barrier.
        # Safety against compile skew comes from the READY barrier below —
        # no node starts round ``start_iter`` before every peer has
        # finished its jit warmup — plus the waiter ordering: round
        # ``start_iter``'s waiters are registered BEFORE this node
        # publishes its own ready beacon, so by the time any peer can see
        # the full barrier (our beacon included) and publish its first
        # frame, our ``collect_begin`` readers are already latched and no
        # round frame can age out of the last-writer-wins register. The
        # barrier's read budget is a generous startup ceiling (env
        # GARFIELD_STARTUP_TIMEOUT_MS, default 30 min): co-located nodes
        # compile ResNet-class programs nearly serially on a small host,
        # and the timeout only bounds how long a genuinely dead peer can
        # stall startup — it costs nothing when everyone arrives. (An
        # earlier warmup-then-barrier design gated round 0 on a fixed
        # post-warmup budget; asymmetric compile/cache skew blew it
        # reproducibly.)
        startup_ms = _startup_ms(args)
        deadline = time.monotonic() + startup_ms / 1e3

        def await_beacon(r, min_step, beacon, what):
            """Poll for peer r's startup beacon, RE-PUBLISHING our own on
            every retry: a beacon published once can be dropped for any
            peer whose listener had not bound inside the sender's
            first-connect grace (tens of seconds of python/jax import),
            and a node that stops beaconing after passing its own wait
            deadlocks the peers that missed it — both observed."""
            waited = 0
            while True:
                try:
                    ex.read_latest(r, min_step, timeout_ms=10_000)
                    return
                except TimeoutError:
                    if time.monotonic() > deadline:
                        raise
                    waited += 10
                    if waited % 60 == 0:
                        tools.warning(
                            f"[{who}] still waiting for node {r}'s {what} "
                            f"({waited}s); re-beaconing"
                        )
                    ex.publish(min_step, beacon)

        for r in range(n):
            if r != me:
                await_beacon(r, 0, b"up", "hello")

        # Post-warmup READY stage: rounds must not start until EVERY node
        # has compiled — without this lockstep gate, fast nodes race
        # rounds ahead while slow peers are still compiling, and their
        # round frames age out of the last-writer-wins register before
        # the slow peers register waiters (observed: healthy 4-node
        # convnet runs dropping two nodes). The read budget is the same
        # startup ceiling: post-hello, a missing "ready" means a peer is
        # compiling (minutes on a shared host) or dead — the generous
        # wait costs nothing when everyone arrives.
        _, _, _ = worker_grad(
            flat_dev, ms, my_xs[0], my_ys[0], jax.random.fold_in(base_key, 0)
        )
        dummy = jnp.zeros((q, flat.size), jnp.float32)
        node_update(flat_dev, opt_state, dummy, jnp.asarray(0, jnp.int32))
        model_aggregate(dummy, jnp.asarray(0, jnp.int32))

        def register_round(i):
            """Pre-register BOTH phases' waiters before any local work —
            frames arriving while this node computes (or evaluates) are
            latched by the blocked readers and cannot be overwritten away
            (exchange.collect_begin docstring; its timeout clock starts at
            wait(), so registering before the ready barrier below cannot
            eat the round budget)."""
            return (
                ex.collect_begin(
                    2 * i + 2, q, timeout_ms=args.cluster_timeout_ms,
                    transform=grad_tf,
                ),
                ex.collect_begin(
                    2 * i + 3, q, timeout_ms=args.cluster_timeout_ms,
                    transform=gossip_tf,
                ),
            )

        straggle_s = max(
            0, int(getattr(args, "straggler_ms", 0) or 0)
        ) / 1e3

        def compute_grad(i):
            """One local gradient for round ``i`` — the SAME derivation
            on the sync and async paths (batch ``i % num_batches``, key
            ``fold_in(base_key, i)``), which is what makes the two
            trajectories comparable at all and bitwise-equal at ms=0.
            Cohort attackers simulate their colluders from their own
            extra batches; ``--straggler_ms`` injects the scenario
            harness's reproducible slow node before the publish."""
            nonlocal ms, mom
            with tele_trace.span("grad_compute", step=i):
                if atk_kind == "cohort":
                    rows = []
                    for j in range(atk_cohort):
                        b = (i * atk_cohort + j) % num_batches
                        gj, _, ms = worker_grad(
                            flat_dev, ms, my_xs[b], my_ys[b],
                            jax.random.fold_in(
                                base_key, i * atk_cohort + j
                            ),
                        )
                        rows.append(np.asarray(gj, np.float32))
                    rows = np.stack(rows)
                    if beta is not None:
                        mom = (1.0 - beta) * rows + beta * (
                            0.0 if mom is None else mom
                        )
                        rows = mom.astype(np.float32)
                    g = attack(rows)
                else:
                    b = i % num_batches
                    xb, yb = my_xs[b], my_ys[b]
                    if targeted_cfg is not None:
                        # Targeted poisoning (DESIGN.md §17): rewrite the
                        # node's OWN batch (label flips / trigger stamps)
                        # and publish the honest gradient of the
                        # poisoned task — suspicion-invisible.
                        from ..attacks import targeted as targeted_lib

                        xb, yb = targeted_lib.poison_batch(
                            targeted_cfg, np.asarray(xb), np.asarray(yb),
                            seed=me, step=i,
                        )
                    g, _, ms = worker_grad(
                        flat_dev, ms, xb, yb,
                        jax.random.fold_in(base_key, i),
                    )
                    g = np.asarray(g, np.float32)
                    if beta is not None:
                        mom = (1.0 - beta) * g + beta * (
                            0.0 if mom is None else mom
                        )
                        g = mom.astype(np.float32)
                    if attack is not None:
                        g = attack(g)
            if straggle_s:
                # Injected slow node (scenario knob) — its own span so the
                # trace report attributes the delay (see _run_worker).
                with tele_trace.span("straggle", step=i):
                    time.sleep(straggle_s)
            return g

        def async_rounds():
            """The bounded-staleness round loop (--async, DESIGN.md §15):
            publish-and-continue on BOTH per-plane collectors. A lost
            gradient quorum still exits as a dropout (the sync
            semantics); a lost gossip quorum keeps the local model for
            one round. Returns ``dropped_at`` (None = completed).

            CATCH-UP JUMP: unlike a PS worker (whose frame tags track
            the PS broadcast through read_latest), a decentralized node
            advances its round counter only by computing — a 10x
            straggler would fall UNBOUNDEDLY behind the swarm in tag
            space and leave every peer's admissible window permanently.
            So a node whose counter lags the swarm clock (the newest tag
            its gradient collector has seen) by more than the staleness
            cutoff JUMPS to the swarm's round, skipping the rounds
            nobody could consume: its contribution RATE stays what its
            hardware allows, but its tags stay admissible and each fresh
            frame it lands unlocks up to ``max_staleness`` rounds of
            swarm progress — which is precisely where the fw=0 async
            speedup over the synchronous wait-everyone pace comes from.
            """
            nonlocal flat, flat_dev, opt_state, ms, rounds_skipped
            # Jump once the lag exceeds HALF the admissible window (>= 1
            # so healthy in-phase pipelining — a peer can lawfully run
            # one round ahead — never triggers it): the swarm throttles
            # at exactly max_staleness behind its slowest required
            # member, so a threshold AT the cutoff would never fire for
            # the one node that needs it, and the straggler would grind
            # every fw=0 quorum to its own pace — measured 1.25x instead
            # of ~ms x. DISABLED at ms=0: the synchronous contract
            # processes every round (there is no unbounded lag to escape
            # — the exact-round quorum waits — and a jump would skip
            # checkpoint rounds and break the bitwise equality).
            jump_lag = (
                max(1, policy.max_staleness // 2)
                if policy.max_staleness > 0 else None
            )
            i = start_iter
            while i < args.num_iter:
                newest = grad_col.newest() if jump_lag is not None else None
                if newest is not None and newest - i > jump_lag:
                    jump = min(int(newest), args.num_iter - 1)
                    rounds_skipped += jump - i
                    tools.warning(
                        f"[{who}] {jump - i} rounds behind the swarm "
                        f"clock; jumping from round {i} to {jump} "
                        f"(total skipped: {rounds_skipped})"
                    )
                    i = jump
                g = compute_grad(i)
                ex.publish(
                    i,
                    _encode_frame([g], wire_stats, fanout=n - 1,
                                  plane=PLANE_GRAD, ef=grad_ef),
                    plane=PLANE_GRAD,
                )
                try:
                    with tele_trace.span("quorum", step=i, plane="grad"):
                        grads, _, w, granks = gather_rows(
                            grad_col, i, grad_split, "grad"
                        )
                except TimeoutError:
                    tools.warning(
                        f"[{who}] no admissible round-{i} gradient quorum "
                        f"within the staleness cutoff; exiting as a "
                        "dropout (reference bounded-retry semantics)"
                    )
                    return i
                # Per-plane defense (DESIGN.md §17): audit the quorum,
                # compose the suspicion weights with the staleness
                # discount, escalate the plane's ladder independently.
                w = _compose_w(w, _audit_plane(
                    grad_def, grad_tap, grads, granks, i,
                    jax.random.fold_in(gar_base_key, i), "gradient",
                ))
                with tele_trace.span("update", step=i):
                    if w is not None:
                        flat_dev, opt_state = node_update_weighted(
                            flat_dev, opt_state, grads, w,
                            jnp.asarray(i, jnp.int32),
                        )
                    else:
                        flat_dev, opt_state = node_update(
                            flat_dev, opt_state, grads,
                            jnp.asarray(i, jnp.int32),
                        )
                    flat = np.asarray(flat_dev, np.float32)
                if grad_def is not None:
                    _plane_escalate(grad_def, i, _rebuild_grad)
                pub = poisoner.publish_frame(
                    flat,
                    (np.asarray(ravel_pytree(ms)[0], np.float32)
                     if bn_elems else None),
                    i,
                )
                with tele_trace.span("gossip", step=i):
                    ex.publish(
                        i,
                        _encode_frame([pub], wire_stats, fanout=n - 1,
                                      plane=PLANE_MODEL),
                        plane=PLANE_MODEL,
                    )
                    try:
                        models_p, models_bn, wm, mranks = gather_rows(
                            model_col, i, gossip_split, "model"
                        )
                    except TimeoutError:
                        tools.warning(
                            f"[{who}] no admissible round-{i} gossip "
                            "quorum; keeping the locally updated model "
                            "this round"
                        )
                        models_p = None
                    if models_p is not None:
                        if poisoner.kind is not None:
                            poisoner.note_gather(
                                np.asarray(models_p)[:len(mranks)],
                                mranks, i,
                            )
                        wm = _compose_w(wm, _audit_plane(
                            gossip_def, gossip_tap, models_p, mranks, i,
                            jax.random.fold_in(
                                jax.random.fold_in(gar_base_key, i), 1
                            ), "gossip",
                        ))
                        if wm is not None:
                            flat_dev = model_aggregate_weighted(
                                models_p, wm, jnp.asarray(i, jnp.int32),
                            )
                        else:
                            flat_dev = model_aggregate(
                                models_p, jnp.asarray(i, jnp.int32),
                            )
                        flat = np.asarray(flat_dev, np.float32)
                        if bn_elems:
                            ms = bn_unravel(jnp.asarray(
                                _robust_stats(models_bn, f)
                            ))
                        if gossip_def is not None:
                            _plane_escalate(gossip_def, i, _rebuild_gossip)
                wire_stats.flush(i)
                if (ckpt and args.checkpoint_freq
                        and (i + 1) % args.checkpoint_freq == 0):
                    with tele_trace.span("checkpoint", step=i):
                        ckpt.save(i + 1, {
                            "flat": flat,
                            "opt_state": jax.tree.map(
                                np.asarray, opt_state),
                            **({"bn": np.asarray(
                                ravel_pytree(ms)[0], np.float32)}
                               if bn_elems else {}),
                        })
                if args.acc_freq and i % args.acc_freq == 0:
                    with tele_trace.span("eval", step=i):
                        acc = parallel.compute_accuracy(
                            (unravel(flat_dev), ms),
                            lambda s, x: eval_fn(s[0], s[1], x),
                            eval_set, binary=args.dataset == "pima",
                        )
                    print(
                        f"Step: {i} Accuracy: {acc:.4f} "
                        f"Time: {time.time() - t0:.1f}",
                        flush=True,
                    )
                i += 1
            return None

        # First round's waiters BEFORE our ready beacon (see the startup
        # comment above): a peer can only start publishing rounds after it
        # has seen this beacon, at which point our readers already latch.
        # The async collectors are PERSISTENT multi-round watchers on
        # their own planes — registered here for the same reason, and
        # never re-registered again.
        grad_col = model_col = None
        grad_wait = model_wait = None
        rounds_skipped = 0
        if policy is not None:
            grad_col = ex.round_collector(
                range(n), transform=grad_tf, plane=PLANE_GRAD
            )
            model_col = ex.round_collector(
                range(n), transform=gossip_tf, plane=PLANE_MODEL
            )
        else:
            grad_wait, model_wait = register_round(start_iter)
        ex.publish(1, b"ready")
        deadline = time.monotonic() + startup_ms / 1e3  # re-arm for stage 2
        for r in range(n):
            if r != me:
                await_beacon(r, 1, b"ready", "ready beacon")
        if policy is not None:
            try:
                dropped_at = async_rounds()
            finally:
                grad_col.close()
                model_col.close()
        # Synchronous round loop (the async path returned its rounds
        # above; an empty iterable keeps the shared summary tail below).
        sync_iters = (
            range(start_iter, args.num_iter) if policy is None else ()
        )
        for i in sync_iters:
            # --- gradient plane (phase 2i+2) -----------------------------
            g = compute_grad(i)
            ex.publish(
                2 * i + 2,
                _encode_frame([g], wire_stats, fanout=n - 1,
                              plane=PLANE_GRAD, ef=grad_ef),
            )
            try:
                with tele_trace.span("quorum", step=i, plane="grad"):
                    grads, _, granks = harvest(grad_wait, grad_split)
            except TimeoutError:
                # Dropped out of the quorum flow: the reference's pull
                # loops retry a bounded number of times then exit
                # gracefully (server.py:138-141, ps.py:84-88); survivors'
                # wait-n-f treats this node as crashed from here on. The
                # round's model-plane registration is never harvested —
                # cancel it so its waiter threads retire now, not at
                # close() (the waiter-lifecycle contract).
                dropped_at = i
                _cancel_wait(model_wait)
                tools.warning(
                    f"[{who}] lost the round-{i} gradient quorum; exiting "
                    "as a dropout (reference bounded-retry semantics)"
                )
                break
            # Per-plane defense (DESIGN.md §17): audit the quorum, weight
            # its rows by suspicion, escalate the gradient ladder — all
            # independent of the gossip plane's history below.
            gw = _audit_plane(
                grad_def, grad_tap, grads, granks, i,
                jax.random.fold_in(gar_base_key, i), "gradient",
            )
            gw = _compose_w(None, gw)
            with tele_trace.span("update", step=i):
                if gw is not None:
                    flat_dev, opt_state = node_update_weighted(
                        flat_dev, opt_state, grads, gw,
                        jnp.asarray(i, jnp.int32),
                    )
                else:
                    flat_dev, opt_state = node_update(
                        flat_dev, opt_state, grads,
                        jnp.asarray(i, jnp.int32),
                    )
                flat = np.asarray(flat_dev, np.float32)
            if grad_def is not None:
                _plane_escalate(grad_def, i, _rebuild_grad)
            # --- model gossip plane (phase 2i+3) -------------------------
            # Gossip frames are [params || stats] (r5, VERDICT r4 #4): the
            # model GAR aggregates the params, the stats segment goes
            # through the same f-trimmed robust mean as SSMW — the on-mesh
            # twin syncs BN state with core.mean_model_state every step
            # (parallel/learn.py), so local-BN drift here would diverge
            # the deployment shapes on BN architectures.
            pub = poisoner.publish_frame(
                flat,
                (np.asarray(ravel_pytree(ms)[0], np.float32)
                 if bn_elems else None),
                i,
            )
            with tele_trace.span("gossip", step=i):
                ex.publish(
                    2 * i + 3,
                    _encode_frame([pub], wire_stats, fanout=n - 1,
                                  plane=PLANE_MODEL),
                )
                try:
                    models_p, models_bn, mranks = harvest(
                        model_wait, gossip_split
                    )
                except TimeoutError:
                    tools.warning(
                        f"[{who}] lost the round-{i} model-gossip quorum; "
                        "keeping the locally updated model this round"
                    )
                    models_p = None
                if models_p is not None:
                    if poisoner.kind is not None:
                        poisoner.note_gather(
                            np.asarray(models_p)[:len(mranks)], mranks, i
                        )
                    mw = _compose_w(None, _audit_plane(
                        gossip_def, gossip_tap, models_p, mranks, i,
                        jax.random.fold_in(
                            jax.random.fold_in(gar_base_key, i), 1
                        ), "gossip",
                    ))
                    if mw is not None:
                        flat_dev = model_aggregate_weighted(
                            models_p, mw, jnp.asarray(i, jnp.int32),
                        )
                    else:
                        flat_dev = model_aggregate(
                            models_p, jnp.asarray(i, jnp.int32),
                        )
                    flat = np.asarray(flat_dev, np.float32)
                    if bn_elems:
                        ms = bn_unravel(jnp.asarray(
                            _robust_stats(models_bn, f)
                        ))
                    if gossip_def is not None:
                        _plane_escalate(gossip_def, i, _rebuild_gossip)
            wire_stats.flush(i)
            if (ckpt and args.checkpoint_freq
                    and (i + 1) % args.checkpoint_freq == 0):
                with tele_trace.span("checkpoint", step=i):
                    ckpt.save(i + 1, {
                        "flat": flat,
                        "opt_state": jax.tree.map(np.asarray, opt_state),
                        **({"bn": np.asarray(
                            ravel_pytree(ms)[0], np.float32)}
                           if bn_elems else {}),
                    })
            # Register the NEXT round's waiters before the (potentially
            # slow — first-eval compile) accuracy pass: with no waiters
            # pending, the q fastest peers can run a whole round ahead and
            # age this node's next quorum out of the register (observed
            # dropping the slowest evaluator at round 1 on the 1-core box).
            if i + 1 < args.num_iter:
                next_waits = register_round(i + 1)
            if args.acc_freq and i % args.acc_freq == 0:
                with tele_trace.span("eval", step=i):
                    acc = parallel.compute_accuracy(
                        (unravel(flat_dev), ms),
                        lambda s, x: eval_fn(s[0], s[1], x),
                        eval_set, binary=args.dataset == "pima",
                    )
                print(
                    f"Step: {i} Accuracy: {acc:.4f} "
                    f"Time: {time.time() - t0:.1f}",
                    flush=True,
                )
            if i + 1 < args.num_iter:
                grad_wait, model_wait = next_waits
        acc = parallel.compute_accuracy(
            (unravel(flat_dev), ms), lambda s, x: eval_fn(s[0], s[1], x),
            eval_set, binary=args.dataset == "pima",
        )
        if ckpt is not None:
            ckpt.close()
        summary = {
            "final_accuracy": acc,
            "steps": dropped_at if dropped_at is not None else args.num_iter,
            "dropped_at": dropped_at,
            # Async catch-up jumps (a straggler contributes at its own
            # rate but tracks the swarm clock): rounds it never computed.
            **({"skipped": rounds_skipped} if policy is not None else {}),
            **({"model_attack_adapt": poisoner.stats()}
               if poisoner.stats() else {}),
            "wall_s": time.time() - t0,
        }
        _telemetry_close(tele_hub, tele_exp)
        print(json.dumps({"tag": who, **summary}), flush=True)
        return summary
    finally:
        ex.close()


def _run_worker(args, windex, ps_ranks, my_xs, my_ys, grad_fn, ms0, flat0,
                unravel, ex, timeout_ms):
    """One worker process: model(s) in, shard gradient out. ``windex`` is
    the worker's data shard; its exchange rank is n_ps + windex.

    SSMW (one PS): the model read is ``read_latest`` (newest round >= the
    expected one), NOT an exact-step collect — a straggler whose expected
    model was already overwritten in the last-writer-wins slot must catch
    up to the PS's current round, not crash (turning a tolerated straggler
    into a permanent casualty would silently consume the f budget).

    MSMW (ByzSGD, n_ps > 1): collect ALL PS models for the exact step and
    GAR-aggregate them with tolerance fps before computing the gradient —
    the worker-side half of the gather step (tensorflow_impl ByzSGD
    trainer.py:55-75: pull models -> aggregate -> compute -> commit). The
    gradient goes to EVERY PS. Round skipping is not available here (an
    exact-step quorum over several independent publishers has no single
    newest round to jump to); the PSes' re-publish-on-timeout covers the
    cold-start skew instead.
    """
    atk_kind, attack, atk_cohort = _host_attack(
        args.attack, args.attack_params, args.fw
    )
    # Adaptive attacker (attacks/adaptive.py, DESIGN.md §16): this process
    # is a REAL suspicion-aware Byzantine worker — bisection magnitude fed
    # by its own published-frame fate (the broadcast model delta, or a
    # leaked PS audit stream via attack_params {"feedback_taps": path}),
    # deterministic cohort rotation over the f_pool colluders, and
    # full-magnitude bursts when the model-broadcast cadence blows out (a
    # quorum-degradation window: straggler / soft timeout / partition).
    controller = None
    adaptive_base = None
    feedback_taps = None
    pending_probe = None  # (round, excess u, mu estimate, magnitude)
    last_model = None  # (round, flat np model) for the delta probe
    if atk_kind == "adaptive":
        from ..attacks import adaptive as adaptive_lib

        if args.fw < 1:
            raise SystemExit(
                f"--attack {args.attack!r} needs --fw >= 1 (the declared "
                "active-cohort size)"
            )
        cfg_all = multihost.ClusterConfig(args.cluster)
        acfg = adaptive_lib.configure(
            args.attack, args.attack_params,
            num_workers=len(cfg_all.workers), f=args.fw,
        )
        controller = adaptive_lib.HostController(
            acfg, windex,
            burst_factor=float(args.attack_params.get("burst_factor", 3.0)),
            burst_rounds=int(args.attack_params.get("burst_rounds", 3)),
        )
        adaptive_base = acfg.base
        feedback_taps = args.attack_params.get("feedback_taps")

    def _note_model(step, flat_params):
        """Adaptive feedback hook, called at every model arrival: close
        the pending probe (delta probe against the previous round's
        model, or the leaked audit stream when configured) and feed the
        broadcast cadence to the burst trigger."""
        nonlocal pending_probe, last_model
        if controller is None:
            return
        from ..attacks import adaptive as adaptive_lib

        controller.observe_round(time.time())
        flat_np = np.asarray(flat_params, np.float32)
        if pending_probe is not None:
            pr_round, u, mu, mag = pending_probe
            detected = score = None
            if feedback_taps:
                got = adaptive_lib.read_selected(feedback_taps, windex)
                if got is not None and got[0] >= pr_round:
                    detected, score = got[1] <= 0.0, got[1]
            if (detected is None and last_model is not None
                    and last_model[0] == pr_round
                    and step == pr_round + 1):
                detected, score = adaptive_lib.delta_probe(
                    last_model[1], flat_np, u, mu_est=mu,
                )
            if detected is not None:
                controller.feedback(detected)
                tele_hooks.emit_event(
                    "attack_adapt", step=int(pr_round),
                    magnitude=round(float(mag), 6),
                    detected=bool(detected),
                    lo=round(controller.lo, 6), hi=round(controller.hi, 6),
                    score=None if score is None else round(float(score), 6),
                )
            pending_probe = None
        last_model = (int(step), flat_np)

    # Worker momentum (Karimireddy et al. 2021; same EMA + zeros init as the
    # on-mesh trainers, core.worker_mom_update): this process publishes its
    # EMA instead of the raw gradient. A Byzantine worker poisons whatever
    # it publishes (attack applied after), and a straggler that skips steps
    # via read_latest only folds in gradients it actually computed — the
    # real deployment semantics.
    beta = getattr(args, "worker_momentum", None)
    mom = None
    # The worker EMA is training state too (ADVICE r3): without it a resume
    # re-warms the momenta from zero over ~1/(1-beta) steps, weakening the
    # variance-reduction premise of the cclip+momentum defense while an
    # attacker keeps full strength. Persist it next to the PS checkpoint
    # (shared checkpoint_dir, one small npz per worker) and restore on
    # --resume.
    mom_path = None
    if beta is not None and args.checkpoint_dir:
        import os

        os.makedirs(args.checkpoint_dir, exist_ok=True)
        mom_path = os.path.join(
            args.checkpoint_dir, f"worker_{windex}_mom.npz"
        )
    if beta is not None and getattr(args, "resume", False):
        if mom_path is not None and __import__("os").path.exists(mom_path):
            with np.load(mom_path) as z:
                mom = z["mom"].astype(np.float32)
                saved_step = int(z["step"])
            print(
                f"[cluster-worker-{windex}] restored momentum EMA from "
                f"step {saved_step}",
                flush=True,
            )
        else:
            tools.warning(
                f"worker {windex}: no saved momentum EMA found — it "
                f"restarts from zero and re-warms over "
                f"~{1.0 / (1.0 - beta):.0f} steps after this resume"
            )

    @jax.jit
    def worker_grad(flat_params, ms, x, y, rng):
        grads, (loss, new_ms) = grad_fn(unravel(flat_params), ms, x, y, rng)
        return ravel_pytree(grads)[0], loss, new_ms

    base_key = jax.random.PRNGKey(args.seed + 1 + windex)
    flat_np = np.asarray(flat0, np.float32)
    # SSMW BN-stat exchange (see _run_ps docstring): model frames arrive as
    # [params || mean batch_stats] and gradient frames ship
    # [grad || this worker's updated batch_stats]; d_bn = 0 models keep the
    # plain layout.
    bn0_flat, bn_unravel = ravel_pytree(ms0)
    bn_elems = int(np.asarray(bn0_flat).size)
    who = f"cluster-worker-{windex}"
    # Events-only telemetry for workers (no GAR runs here, so no taps):
    # exchange waits, wire accounting and — with --trace — the
    # model_wait/grad_compute/publish spans land in this role's own
    # <who>.telemetry.jsonl, which is what lets telemetry.report
    # reconstruct the cross-process round timeline (a PS-only stream
    # cannot attribute a slow quorum to the worker that caused it).
    tele_hub, tele_exp = _telemetry_open(args, who)
    # Targeted poisoner (labelflip/backdoor, DESIGN.md §17): config built
    # after the hub install so the one-time binary-surrogate fallback
    # event reaches the stream.
    targeted_cfg = None
    if atk_kind == "targeted":
        targeted_cfg = _targeted_config(args, who)
    wire_stats = WireStats(who)
    grad_ef = _maybe_error_feedback(who, wire_stats)
    split = (flat_np.size, bn_elems)
    # pass_empty: the PS's stop sentinel is an empty frame, not a codec
    # frame — it must reach the loop's sentinel check undecoded.
    model_tf = _frame_transform(split, wire_stats, pass_empty=True,
                                plane=PLANE_MODEL)
    num_batches = my_xs.shape[0]
    multi_ps = len(ps_ranks) > 1
    if multi_ps:
        fps = getattr(args, "fps", 0)
        model_gar_name = getattr(args, "model_gar", None) or args.gar
        plane = _ModelPlane(ps_ranks, model_gar_name, fps, who)

    ms = ms0
    loss = None
    steps_done = 0
    refreshes = 0
    i = 0
    # Bounded-staleness async mode (--async, DESIGN.md §14): the worker
    # side is publish-and-continue — it never barriers on its gradient
    # entering a quorum, and while the next model broadcast is pending it
    # REFRESHES its published frame (same round tag — staleness is set by
    # the model round used — fresh batch/key), so the PS's stale-frame
    # reuse sees this rank's newest data instead of its oldest.
    policy = rounds.resolve(args)
    async_mode = policy is not None and not multi_ps
    straggle_s = max(0, int(getattr(args, "straggler_ms", 0) or 0)) / 1e3
    refresh_ms = min(timeout_ms, 2_000)
    prev = None  # (step, flat_params) of the newest model seen
    refresh_r = 0

    def compute_and_publish(step, flat_params, r=0):
        """One gradient compute + publish for model round ``step``.

        ``r > 0`` marks an async REFRESH: the batch index and RNG fold in
        the refresh counter so the republished frame carries NEW data
        (the register is last-writer-wins — it replaces this rank's older
        frame at the same tag). ``r == 0`` derivations are EXACTLY the
        synchronous ones, so non-refresh trajectories are untouched (the
        --max_staleness 0 bitwise contract). ``--straggler_ms`` injects
        the scenario harness's reproducible slow-rank delay just before
        the publish."""
        nonlocal ms, mom, loss, pending_probe
        attacking = atk_kind == "cohort" or (
            atk_kind == "adaptive" and controller.is_active(step)
        )
        with tele_trace.span("grad_compute", step=int(step), refresh=int(r)):
            if attacking:
                # Colluding attacker (byzWorker.py:114-125): compute the
                # cohort's honest gradients locally on DISTINCT batches
                # of the attacker's own shard, publish the collusion
                # statistic. In a --worker_momentum deployment the
                # honest workers publish EMA momenta, so the attacker
                # simulates its cohort's MOMENTA and hides inside their
                # (shrunken) variance — the on-mesh semantics and the
                # strongest form of the attack the cclip defense is
                # built for.
                rows = []
                for j in range(atk_cohort):
                    o = step * atk_cohort + j
                    key = jax.random.fold_in(base_key, o)
                    if r:
                        key = jax.random.fold_in(key, 1_000_003 + r)
                    gj, loss_, ms_new = worker_grad(
                        flat_params, ms, my_xs[(o + r) % num_batches],
                        my_ys[(o + r) % num_batches], key,
                    )
                    loss, ms = loss_, ms_new
                    rows.append(np.asarray(gj, np.float32))
                rows = np.stack(rows)
                if beta is not None:
                    mom = (1.0 - beta) * rows + beta * (
                        0.0 if mom is None else mom
                    )
                    rows = mom.astype(np.float32)
                if atk_kind == "adaptive":
                    # Publish the base attack's collusion statistic at the
                    # controller's CURRENT magnitude (burst-aware), and
                    # arm the probe: the next model delta tells this rank
                    # whether the fake entered the selection.
                    mag = controller.magnitude()
                    mu = rows.mean(axis=0)
                    if adaptive_base == "empire":
                        g = (-mag * mu).astype(np.float32)
                    else:
                        sigma = rows.std(axis=0, ddof=1)
                        g = (mu + mag * sigma).astype(np.float32)
                    pending_probe = (int(step), g - mu, mu, mag)
                else:
                    g = attack(rows)
            else:
                key = jax.random.fold_in(base_key, step)
                if r:
                    key = jax.random.fold_in(key, 1_000_003 + r)
                b = (step + r) % num_batches
                xb, yb = my_xs[b], my_ys[b]
                if targeted_cfg is not None:
                    # Targeted poisoning (DESIGN.md §17): rewrite this
                    # worker's OWN batch and publish the honest gradient
                    # of the poisoned task — nothing divergence-shaped
                    # for the PS's suspicion plane to see.
                    from ..attacks import targeted as targeted_lib

                    xb, yb = targeted_lib.poison_batch(
                        targeted_cfg, np.asarray(xb), np.asarray(yb),
                        seed=windex, step=step,
                    )
                g, loss_, ms_new = worker_grad(
                    flat_params, ms, xb, yb, key,
                )
                loss, ms = loss_, ms_new
                g = np.asarray(g, np.float32)
                if beta is not None:
                    mom = (1.0 - beta) * g + beta * (
                        0.0 if mom is None else mom
                    )
                    g = mom.astype(np.float32)
                if attack is not None:
                    g = attack(g)
            out_parts = [g]
            if bn_elems:
                # Both deployment shapes ship [grad || stats] (MSMW BN
                # plane, r5); the PS robust-aggregates the stats segment.
                out_parts.append(
                    np.asarray(ravel_pytree(ms)[0], np.float32)
                )
        if straggle_s:
            # Injected slow rank (scenario knob) — its own span so the
            # report attributes the delay instead of hiding it in the
            # compute phase.
            with tele_trace.span("straggle", step=int(step)):
                time.sleep(straggle_s)
        targets = plane.all_ranks if multi_ps else ps_ranks
        ex.publish(
            step,
            _encode_frame(out_parts, wire_stats, fanout=len(targets),
                          plane=PLANE_GRAD, ef=grad_ef),
            to=targets,
        )

    # Overlap (DESIGN.md §11): the model read is registered BEFORE the
    # local gradient compute each round, so the next model frame is
    # latched + decoded + device-staged by the watcher thread while this
    # worker is still inside its own device step.
    model_wait = None
    if not multi_ps:
        model_wait = ex.read_latest_begin(0, 0, transform=model_tf)
    while i < args.num_iter:
        if multi_ps:
            step = i
            try:
                with tele_trace.span("model_gather", step=i):
                    models_p, models_bn = _collect_models(
                        ex, i, plane, timeout_ms, split,
                        stats=wire_stats, wait_fn=model_wait,
                    )
            except _Lapped as lap:
                # MSMW catch-up: a worker outside the PSes' q-fastest
                # quorum is lapped — jump to the plane's newest round
                # (the MSMW twin of the SSMW read_latest jump).
                model_wait = None
                if lap.newest >= args.num_iter:
                    break
                tools.warning(
                    f"[{who}] lapped at round {i}; "
                    f"jumping to the PSes' round {lap.newest}"
                )
                i = lap.newest
                continue
            model_wait = None  # consumed
            if i + 1 < args.num_iter:
                model_wait = ex.collect_begin(
                    i + 1, len(plane.ranks), timeout_ms=timeout_ms,
                    peers=plane.ranks, transform=model_tf,
                )
            flat_params = plane.aggregate(models_p)
            _note_model(i, flat_params)
            if bn_elems:
                # Adopt the robust-aggregated PS statistics (fps budget),
                # the MSMW twin of the SSMW mean-stats adoption.
                ms = bn_unravel(jnp.asarray(
                    _robust_stats(models_bn, plane.fps)
                ))
        else:
            if async_mode and prev is not None:
                # Publish-and-continue (DESIGN.md §14): poll for the next
                # broadcast in short chunks; while none arrives, refresh
                # the published frame from the stale model on a new batch
                # — the PS's bounded-staleness reuse then aggregates this
                # rank's NEWEST data, and a straggling PS cannot idle the
                # worker. The full timeout budget still bounds the wait.
                waited = 0.0
                while True:
                    try:
                        step, payload = model_wait(timeout_ms=refresh_ms)
                        break
                    except TimeoutError:
                        waited += refresh_ms
                        if waited >= timeout_ms:
                            raise
                        if policy.max_staleness > 0:
                            refresh_r += 1
                            refreshes += 1
                            compute_and_publish(
                                prev[0], prev[1], r=refresh_r
                            )
                            wire_stats.flush(prev[0])
                        # The timed-out harvest retired its watcher;
                        # re-register before the next poll.
                        model_wait = ex.read_latest_begin(
                            0, prev[0] + 1, transform=model_tf
                        )
            else:
                step, payload = model_wait(timeout_ms=timeout_ms)
            if step >= args.num_iter or payload == b"":
                break  # PS's stop sentinel (empty frame at num_iter)
            if isinstance(payload, Exception):
                # NOT the sentinel: the trusted PS's model frame failing
                # the wire codec means the PS runs a different model/dtype
                # config — a deployment error that must fail loudly, not
                # exit rc 0.
                raise SystemExit(
                    f"model frame failed the wire codec ({payload}); PS "
                    "and worker configs disagree (--model/--dtype/"
                    "--dataset)"
                )
            # Next round's read registered before the compute; the
            # watcher keeps latching newer rounds, so the straggler
            # catch-up semantics survive the pre-registration.
            model_wait = ex.read_latest_begin(
                0, step + 1, transform=model_tf
            )
            flat_params, bn_seg = payload
            _note_model(step, flat_params)
            if bn_elems:
                # Adopt the PS's mean BatchNorm statistics — the cluster
                # twin of the on-mesh core.mean_model_state sync.
                ms = bn_unravel(jnp.asarray(bn_seg))
            prev = (step, flat_params)
            refresh_r = 0
        compute_and_publish(step, flat_params)
        wire_stats.flush(step)
        if (mom_path is not None and mom is not None
                and args.checkpoint_freq
                and (step + 1) % args.checkpoint_freq == 0):
            # Atomic replace: a crash mid-save must not leave a torn npz.
            import os

            np.savez(mom_path + ".tmp.npz", mom=mom, step=step + 1)
            os.replace(mom_path + ".tmp.npz", mom_path)
        steps_done += 1
        if args.log:
            print(
                f"Worker {windex} loss {step}: {float(loss):.6f}", flush=True
            )
        i = step + 1
    # Waiter lifecycle: the loop's last registration (the round past the
    # final one, or the sentinel path's re-read) is never harvested —
    # retire it now instead of at close() (tests/test_exchange.py).
    _cancel_wait(model_wait)
    summary = {
        "steps": steps_done,
        **({"refreshes": refreshes} if async_mode else {}),
        **({"attack_adapt": controller.stats()} if controller else {}),
        "final_loss": float(loss) if loss is not None else None,
    }
    _telemetry_close(tele_hub, tele_exp)
    print(json.dumps({"tag": f"cluster-worker-{windex}", **summary}),
          flush=True)
    return summary

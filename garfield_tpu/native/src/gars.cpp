// Native CPU implementations of the robust aggregation rules.
//
// Counterpart of the reference's C++/CUDA GAR kernels
// (pytorch_impl/libs/native/py_{krum,median,bulyan,brute}/ — e.g. the
// threadpool-parallel distance reduction + nth_element selection in
// py_krum/krum.cpp:50-133) re-implemented from scratch against the SAME rule
// semantics as the jit'd XLA versions in garfield_tpu/aggregators/ (the
// golden tests assert elementwise parity):
//   - pairwise Euclidean distances, non-finite -> +inf (krum.py:44-48);
//   - krum score_i = sum of the n-f-1 smallest distances to others, stable
//     tie-break, Multi-Krum average of the m best (krum.py:31-80);
//   - lower coordinate-wise median, NaNs sorted last (median.py:39);
//   - bulyan: n-2f-2 selection rounds with per-round re-scoring over the
//     active set + averaged-median with beta = rounds-2f (bulyan.py:31-84;
//     re-scored, not incrementally updated — the reference's incremental
//     path is buggy, see SURVEY §2 P11);
//   - brute: min-diameter C(n, n-f) subset, first minimum wins
//     (brute.py:32-68, combinations.hpp).
//
// Exposed as a C ABI loaded via ctypes (no pybind11 in this image).
// GARFIELD_NATIVE_CHECKS=0-style release builds define NDEBUG, mirroring the
// reference's NDEBUG-guarded asserts (py_krum/rule.cpp:43-55).

#include <algorithm>
#include <cassert>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <numeric>
#include <vector>

#include "threadpool.hpp"

namespace {

template <typename T>
constexpr T kInf = std::numeric_limits<T>::infinity();

// value-with-NaN-last ordering (torch sort semantics, median.py:39).
template <typename T>
inline bool nan_last_less(T a, T b) {
  const bool na = std::isnan(a), nb = std::isnan(b);
  if (na) return false;
  if (nb) return true;
  return a < b;
}

// (n, n) Euclidean distance matrix; diagonal and non-finite entries -> +inf.
// Threadpool-parallel over row pairs (krum.cpp's reduce_sum_squared_
// difference structure, re-done: one task per row, vectorizable inner loop).
template <typename T>
std::vector<T> distance_matrix(const T* g, int64_t n, int64_t d) {
  std::vector<T> dist(static_cast<size_t>(n) * n, kInf<T>);
  garfield::parallel_for_each(0, static_cast<size_t>(n), [&](size_t i) {
    for (int64_t j = static_cast<int64_t>(i) + 1; j < n; ++j) {
      T acc = 0;
      const T* gi = g + i * d;
      const T* gj = g + j * d;
      for (int64_t k = 0; k < d; ++k) {
        const T diff = gi[k] - gj[k];
        acc += diff * diff;
      }
      T val = std::sqrt(acc);
      if (!std::isfinite(val)) val = kInf<T>;
      dist[i * n + j] = val;
      dist[j * n + i] = val;
    }
  });
  return dist;
}

// Krum scores: sum of the k smallest entries of each row (diag already inf).
template <typename T>
std::vector<T> krum_scores(const std::vector<T>& dist, int64_t n, int64_t k) {
  std::vector<T> scores(n);
  garfield::parallel_for_each(0, static_cast<size_t>(n), [&](size_t i) {
    std::vector<T> row(dist.begin() + i * n, dist.begin() + (i + 1) * n);
    std::partial_sort(row.begin(), row.begin() + k, row.end());
    T s = 0;
    for (int64_t t = 0; t < k; ++t) s += row[t];
    scores[i] = s;
  });
  return scores;
}

// Stable index sort by score ascending (jnp.argsort stability).
template <typename T>
std::vector<int64_t> stable_order(const std::vector<T>& scores) {
  std::vector<int64_t> idx(scores.size());
  std::iota(idx.begin(), idx.end(), 0);
  std::stable_sort(idx.begin(), idx.end(), [&](int64_t a, int64_t b) {
    return nan_last_less(scores[a], scores[b]);
  });
  return idx;
}

// Average the rows listed in sel[0..m) into out (parallel over coordinates).
template <typename T>
void average_rows(const T* g, int64_t d, const std::vector<int64_t>& sel,
                  int64_t m, T* out) {
  garfield::ThreadPool::shared().parallel_for(
      0, static_cast<size_t>(d), [&](size_t lo, size_t hi) {
        for (size_t k = lo; k < hi; ++k) {
          T acc = 0;
          for (int64_t t = 0; t < m; ++t) acc += g[sel[t] * d + k];
          out[k] = acc / static_cast<T>(m);
        }
      });
}

template <typename T>
void krum_impl(const T* g, int64_t n, int64_t d, int64_t f, int64_t m,
               T* out) {
  if (m <= 0) m = n - f - 2;
  assert(n >= 2 * f + 3 && m >= 1 && m <= n - f - 2);
  const auto dist = distance_matrix(g, n, d);
  const auto scores = krum_scores(dist, n, n - f - 1);
  auto order = stable_order(scores);
  order.resize(m);
  average_rows(g, d, order, m, out);
}

template <typename T>
void median_impl(const T* g, int64_t n, int64_t d, T* out) {
  assert(n >= 1);
  garfield::ThreadPool::shared().parallel_for(
      0, static_cast<size_t>(d), [&](size_t lo, size_t hi) {
        std::vector<T> col(n);
        for (size_t k = lo; k < hi; ++k) {
          for (int64_t i = 0; i < n; ++i) col[i] = g[i * d + k];
          const int64_t mid = (n - 1) / 2;  // lower median
          std::nth_element(col.begin(), col.begin() + mid, col.end(),
                           nan_last_less<T>);
          out[k] = col[mid];
        }
      });
}

template <typename T>
void bulyan_impl(const T* g, int64_t n, int64_t d, int64_t f, int64_t m,
                 T* out) {
  const int64_t m_max = n - f - 2;
  if (m <= 0) m = m_max;
  const int64_t rounds = n - 2 * f - 2;
  assert(n >= 4 * f + 3 && rounds >= 1);
  const auto dist = distance_matrix(g, n, d);
  std::vector<uint8_t> active(n, 1);
  std::vector<T> selected(static_cast<size_t>(rounds) * d);

  for (int64_t r = 0; r < rounds; ++r) {
    const int64_t m_r = std::min(m, m_max - r);
    // Re-score the active set: sum of the m_r smallest masked distances.
    std::vector<T> scores(n, kInf<T>);
    garfield::parallel_for_each(0, static_cast<size_t>(n), [&](size_t i) {
      if (!active[i]) return;
      std::vector<T> row;
      row.reserve(n);
      for (int64_t j = 0; j < n; ++j) {
        row.push_back(active[j] ? dist[i * n + j] : kInf<T>);
      }
      std::partial_sort(row.begin(), row.begin() + m_r, row.end());
      T s = 0;
      for (int64_t t = 0; t < m_r; ++t) s += row[t];
      scores[i] = s;
    });
    auto order = stable_order(scores);
    std::vector<int64_t> best(order.begin(), order.begin() + m_r);
    average_rows(g, d, best, m_r, selected.data() + r * d);
    active[order[0]] = 0;
  }

  // Coordinate-wise averaged median over the selected rows (bulyan.py:77-84):
  // average the beta values closest to the lower median, stable by index.
  const int64_t beta = rounds - 2 * f;
  garfield::ThreadPool::shared().parallel_for(
      0, static_cast<size_t>(d), [&](size_t lo, size_t hi) {
        std::vector<T> col(rounds);
        std::vector<T> dev(rounds);
        std::vector<int64_t> idx(rounds);
        for (size_t k = lo; k < hi; ++k) {
          for (int64_t r = 0; r < rounds; ++r) col[r] = selected[r * d + k];
          std::vector<T> sorted_col(col);
          const int64_t mid = (rounds - 1) / 2;
          std::nth_element(sorted_col.begin(), sorted_col.begin() + mid,
                           sorted_col.end(), nan_last_less<T>);
          const T med = sorted_col[mid];
          for (int64_t r = 0; r < rounds; ++r) dev[r] = std::abs(col[r] - med);
          std::iota(idx.begin(), idx.end(), 0);
          std::stable_sort(idx.begin(), idx.end(), [&](int64_t a, int64_t b) {
            return nan_last_less(dev[a], dev[b]);
          });
          T acc = 0;
          for (int64_t t = 0; t < beta; ++t) acc += col[idx[t]];
          out[k] = acc / static_cast<T>(beta);
        }
      });
}

template <typename T>
void brute_impl(const T* g, int64_t n, int64_t d, int64_t f, T* out) {
  const int64_t k = n - f;
  assert(n >= 2 * f + 1 && k >= 1);
  const auto dist = distance_matrix(g, n, d);
  // Enumerate C(n, k) combinations in lexicographic order (first minimal
  // diameter wins, matching jnp.argmin). Diagonal is excluded (subset
  // diameter uses only i<j pairs; the jax path's exclude_self=False diag=0
  // never exceeds a max anyway).
  std::vector<int64_t> combo(k);
  std::iota(combo.begin(), combo.end(), 0);
  std::vector<int64_t> best_combo(combo);
  T best_diam = kInf<T>;
  for (;;) {
    T diam = 0;
    for (int64_t a = 0; a < k && diam < best_diam; ++a) {
      for (int64_t b = a + 1; b < k; ++b) {
        const T v = dist[combo[a] * n + combo[b]];
        if (v > diam) diam = v;
      }
    }
    if (diam < best_diam) {
      best_diam = diam;
      best_combo = combo;
    }
    // next combination
    int64_t i = k - 1;
    while (i >= 0 && combo[i] == n - k + i) --i;
    if (i < 0) break;
    ++combo[i];
    for (int64_t j = i + 1; j < k; ++j) combo[j] = combo[j - 1] + 1;
  }
  average_rows(g, d, best_combo, k, out);
}

}  // namespace

#define GT_EXPORT __attribute__((visibility("default")))

extern "C" {

// f32 entry points ---------------------------------------------------------
GT_EXPORT void gt_krum_f32(const float* g, int64_t n, int64_t d, int64_t f, int64_t m,
                 float* out) {
  krum_impl(g, n, d, f, m, out);
}
GT_EXPORT void gt_median_f32(const float* g, int64_t n, int64_t d, float* out) {
  median_impl(g, n, d, out);
}
GT_EXPORT void gt_bulyan_f32(const float* g, int64_t n, int64_t d, int64_t f, int64_t m,
                   float* out) {
  bulyan_impl(g, n, d, f, m, out);
}
GT_EXPORT void gt_brute_f32(const float* g, int64_t n, int64_t d, int64_t f,
                  float* out) {
  brute_impl(g, n, d, f, out);
}

// f64 entry points ---------------------------------------------------------
GT_EXPORT void gt_krum_f64(const double* g, int64_t n, int64_t d, int64_t f, int64_t m,
                 double* out) {
  krum_impl(g, n, d, f, m, out);
}
GT_EXPORT void gt_median_f64(const double* g, int64_t n, int64_t d, double* out) {
  median_impl(g, n, d, out);
}
GT_EXPORT void gt_bulyan_f64(const double* g, int64_t n, int64_t d, int64_t f,
                   int64_t m, double* out) {
  bulyan_impl(g, n, d, f, m, out);
}
GT_EXPORT void gt_brute_f64(const double* g, int64_t n, int64_t d, int64_t f,
                  double* out) {
  brute_impl(g, n, d, f, out);
}

GT_EXPORT int64_t gt_num_threads() {
  return static_cast<int64_t>(garfield::ThreadPool::shared().size());
}

}  // extern "C"

"""Checkpoint / resume for training state.

The reference has NO checkpointing of any kind (SURVEY §5: no torch.save /
tf.train.Checkpoint anywhere; runs die with the process). This module is the
deliberate upgrade the survey calls for: orbax-backed save/restore of the
whole ``TrainState`` pytree, keyed by step, with ``latest_step`` discovery so
``--resume`` continues a killed run bit-exactly (state.rng + fold_in(step)
makes the step stream replayable — core.py TrainState docstring).

Falls back to a pickle-of-numpy-leaves format if orbax is unavailable —
and uses it by default on the XLA:CPU backend, where orbax's background
commit threads are unsound (see ``_use_orbax``). ``GARFIELD_CKPT_BACKEND``
forces either backend.
"""

import os
import pickle

import jax
import numpy as np

__all__ = ["save", "restore", "latest_step", "Checkpointer"]

try:  # orbax is in the baked image; guard anyway (zero-install rule)
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # pragma: no cover
    _HAVE_ORBAX = False

# GARFIELD_CKPT_BACKEND=pickle|orbax overrides the automatic choice.
_BACKEND = os.environ.get("GARFIELD_CKPT_BACKEND", "").strip().lower()
if _BACKEND not in ("", "pickle", "orbax"):  # pragma: no cover
    raise ValueError(
        f"GARFIELD_CKPT_BACKEND={_BACKEND!r}: expected 'pickle' or 'orbax'"
    )


def _use_orbax():
    """Orbax on real device backends; pickle on XLA:CPU (or by env).

    orbax's CheckpointManager keeps background commit threads alive past
    ``wait_until_finished``, and on this jaxlib's XLA:CPU runtime a
    native thread touching the runtime while the training thread
    dispatches donating steps is unsound — the process dies with a
    native SIGSEGV/SIGABRT, not an exception (same failure class, and
    same remedy, as the CPU-inline readback guard in
    ``parallel.compute_accuracy_async``). The window only opens when
    compiles are warm enough for steps to dispatch back-to-back, which
    is exactly the cached test/CI configuration. The pickle format is
    per-backend: a run checkpointed on one backend resumes on the same
    backend (cross-backend resume was never supported — shardings
    differ).
    """
    if _BACKEND == "pickle":
        return False
    if _BACKEND == "orbax":
        return _HAVE_ORBAX
    return _HAVE_ORBAX and jax.default_backend() != "cpu"


def _np_leaves(tree):
    return jax.tree.map(lambda l: np.asarray(l), tree)


class Checkpointer:
    """Directory of step-numbered checkpoints with a bounded history."""

    def __init__(self, directory, max_to_keep=3):
        self.directory = os.path.abspath(str(directory))
        self.max_to_keep = max_to_keep
        os.makedirs(self.directory, exist_ok=True)
        if _use_orbax():
            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, create=True
                ),
            )
        else:
            self._mgr = None

    def save(self, step, state, wait=True):
        step = int(step)
        if self._mgr is not None:
            self._mgr.save(step, args=ocp.args.StandardSave(state))
            if wait:
                self._mgr.wait_until_finished()
        else:  # pickle fallback
            path = os.path.join(self.directory, f"ckpt_{step}.pkl")
            with open(path + ".tmp", "wb") as f:
                pickle.dump(_np_leaves(state), f)
            os.replace(path + ".tmp", path)
            self._gc()

    def latest_step(self):
        if self._mgr is not None:
            return self._mgr.latest_step()
        steps = self._pickle_steps()
        return steps[-1] if steps else None

    def steps(self):
        """Every step present in this directory, sorted, on BOTH
        backends — what torn-save detection across a shard group needs
        (federated/sharding.latest_sharded_step intersects these; the
        orbax path used to expose only ``latest_step``, which lets a
        shard that is one save ahead hide an older step the others
        still agree on)."""
        if self._mgr is not None:
            return sorted(int(s) for s in self._mgr.all_steps())
        return self._pickle_steps()

    def restore(self, state_like, step=None):
        """Restore into the structure of ``state_like`` (an abstract or
        concrete TrainState from ``init_fn`` — shardings are re-applied by
        the caller's device_put)."""
        step = self.latest_step() if step is None else int(step)
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        if self._mgr is not None:
            target = jax.tree.map(np.asarray, state_like)
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(target)
            )
        path = os.path.join(self.directory, f"ckpt_{step}.pkl")
        with open(path, "rb") as f:
            return pickle.load(f)

    def _pickle_steps(self):
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith("ckpt_") and name.endswith(".pkl"):
                steps.append(int(name[5:-4]))
        return sorted(steps)

    def _gc(self):
        steps = self._pickle_steps()
        for s in steps[: -self.max_to_keep]:
            os.remove(os.path.join(self.directory, f"ckpt_{s}.pkl"))

    def close(self):
        if self._mgr is not None:
            self._mgr.close()


def save(directory, step, state):
    Checkpointer(directory).save(step, state)


def latest_step(directory):
    return Checkpointer(directory).latest_step()


def restore(directory, state_like, step=None):
    return Checkpointer(directory).restore(state_like, step)

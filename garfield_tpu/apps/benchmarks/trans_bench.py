"""Slot-fused transformer A/B + token-backdoor robustness capture.

TRANSBENCH_r*'s capture tool (schema v14 ``trans_bench`` rows). Two
modes share the gar_bench timing discipline (dependency-chained reps,
softsign DCE guard, adaptive rep sizing, min over ``--trials``
independent paired-reps measurements — VERDICT r4 #3):

  - **A/B (default)**: per-slot gradient time of the slot-fused twin
    (``models/slotfused.build_slot_grad_fn`` — ONE forward/backward
    over the flat (slots*b) batch) vs the unrolled per-slot reference
    (a python loop of per-worker grads inside one jit — exactly what
    ``parallel.core.per_slot_grads`` dispatches without a twin), on
    the transformer families (vit_tiny / gpt_tiny). The chain folds a
    softsign-guarded mean-gradient step back into the params, so every
    gradient coordinate is a real data dependency of the next
    iteration and XLA cannot shed the backward pass.
  - **--robust**: trained token-backdoor cells on gpt_tiny/copytask —
    the cohort stamps a fixed token PREFIX (``attacks/targeted.py``
    integer branch) and relabels to the target; ASR is measured by
    ``parallel.targeted_eval`` with the v9 attribution discipline
    (``asr_baseline`` — report attributable lift, not raw rate), once
    undefended and once with the data-plane head-gradient
    fingerprints (``defense={'weighted': False, 'data': {}}`` — the
    reworked ``head_spec`` locating the untied Dense head).

  python -m garfield_tpu.apps.benchmarks.trans_bench \\
      --models vit_tiny gpt_tiny --slots 8 --json TRANSBENCH_r01.json
  python -m garfield_tpu.apps.benchmarks.trans_bench --robust \\
      --steps 150 --json TRANSBENCH_r01.json
"""

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from ...utils import profiling
from ..common import peak_rss_bytes

# Model geometry per A/B cell: (input maker, seq length, heads, depth).
# Inputs stay CPU-tractable (the committed r01 rows are a CPU capture —
# chip recapture pending, BASELINE.md discipline) but keep the real
# attention shapes: vit_tiny at 16x16 runs 16 patches of width 48,
# gpt_tiny the full 16-token copytask window.
AB_MODELS = ("vit_tiny", "gpt_tiny")


def _make_inputs(name, slots, batch, img, key):
    if name == "vit_tiny":
        x = jax.random.normal(
            key, (slots, batch, img, img, 3), jnp.float32
        )
        seq = (img // 4) ** 2
    else:
        from ...data import COPYTASK_SEQ, COPYTASK_VOCAB

        x = jax.random.randint(
            key, (slots, batch, COPYTASK_SEQ), 0, COPYTASK_VOCAB
        )
        seq = COPYTASK_SEQ
    y = jax.random.randint(
        jax.random.fold_in(key, 1), (slots, batch), 0, 10
    )
    return x, y, seq


def _bench_pair(chains, params_host, reps, trials):
    """gar_bench.bench_one's timing loop over params-tree chains, with
    the A/B trials INTERLEAVED: each trial times every path back to
    back, so slow machine drift (shared-host CPU reality — observed
    2x swings across minutes on otherwise-idle captures) cancels out
    of the fused/unrolled ratio instead of landing on whichever path
    was timed last. ``params_host`` is a HOST (numpy) tree: the chains
    donate their input, so every warmup/timed run starts from a fresh
    upload. Returns ({path: min latency}, {path: reps})."""
    timed, reps_used = {}, {}
    for path, chain in chains.items():
        # compile + warm + sync (the uploaded tree is donated)
        p0 = jax.tree.map(
            np.array, chain(jax.tree.map(jnp.array, params_host))
        )

        def make_timed(chain=chain, p0=p0):
            def timed_k(k):
                p = jax.tree.map(jnp.array, p0)
                np.asarray(jax.tree.leaves(p)[0].ravel()[:1])  # drain H2D
                t0 = time.perf_counter()
                for _ in range(k):
                    p = chain(p)
                np.asarray(jax.tree.leaves(p)[0].ravel()[:1])  # sync
                return time.perf_counter() - t0

            return timed_k

        timed[path] = make_timed()
        r = reps
        est = profiling.paired_reps(timed[path], reps, pairs=2)
        if est is not None and est * r < 0.25:
            r = min(4000, max(reps, int(0.5 / max(est, 1e-7))))
        reps_used[path] = r
    vals = {path: [] for path in chains}
    for _ in range(max(1, trials)):
        for path in chains:
            v = profiling.paired_reps(
                timed[path], reps_used[path], pairs=4, agg="min"
            )
            if v is not None:
                vals[path].append(v)
    return (
        {p: (min(v) if v else None) for p, v in vals.items()},
        reps_used,
    )


def ab_cell(name, *, slots, batch, img, reps, trials, seed=0):
    """Both paths of one model: {'fused': latency, 'unrolled': latency,
    'd': params, 'seq'/'heads'/'depth'} — latency is per CHAIN STEP
    (all ``slots`` per-worker gradients); divide by slots for the
    per-slot number."""
    from ...models import select_model, slotfused
    from ...parallel import core
    from ...utils import selectors

    dataset = "copytask" if name == "gpt_tiny" else "cifar10"
    module = select_model(name, dataset)
    # Softmax cross-entropy, NOT nll: the transformer zoo heads emit raw
    # logits, and nll-on-logits is LINEAR in the logits — the backward
    # pass would skip the softmax entirely and the A/B latency would not
    # represent a real fine-tuning gradient.
    loss = selectors.select_loss("crossentropy")
    key = jax.random.PRNGKey(seed)
    x, y, seq = _make_inputs(name, slots, batch, img, key)
    init_fn, grad_fn, _ = core.make_worker_fns(module, loss)
    params, ms = init_fn(jax.random.PRNGKey(0), x[0])
    params_host = jax.tree.map(np.array, params)
    keys = jax.random.split(jax.random.PRNGKey(2), slots)
    d = int(sum(
        int(np.prod(l.shape)) for l in jax.tree.leaves(params)
    ))

    fused = slotfused.build_slot_grad_fn(module, loss)
    if fused is None:
        raise RuntimeError(f"{name}: no slot-fused twin registered")

    def unrolled(p, ms_, x_, y_, k_):
        outs = [
            grad_fn(p, ms_, x_[i], y_[i], k_[i]) for i in range(slots)
        ]
        g = jax.tree.map(lambda *a: jnp.stack(a), *[o[0] for o in outs])
        return g, (None, ms_)

    def make_chain(fn):
        def _chain(p):
            g_st, _ = fn(p, ms, x, y, keys)

            def upd(pl, gl):
                gm = gl.mean(axis=0).astype(pl.dtype)
                # softsign DCE guard (the r5 microbench-trap rule):
                # every gradient coordinate feeds the next iteration
                # through a nonlinearity XLA cannot rewrite away, and
                # the bounded update keeps the chained params finite.
                return pl - 0.01 * gm * jax.lax.rsqrt(1.0 + gm * gm)

            return jax.tree.map(upd, p, g_st)

        return jax.jit(_chain, donate_argnums=0)

    cell = {"d": d, "seq": seq, "heads": int(module.heads),
            "depth": int(module.depth)}
    latencies, used = _bench_pair(
        {"fused": make_chain(fused), "unrolled": make_chain(unrolled)},
        params_host, reps, trials,
    )
    for path in ("fused", "unrolled"):
        cell[path] = latencies[path]
        cell[f"{path}_reps"] = used[path]
    return cell


def robust_cells(*, steps, num_workers, f, seed=0):
    """Token-backdoor ASR cells on gpt_tiny/copytask: defense off vs
    the data-plane head-gradient fingerprints, same seed, same cohort.
    Honest numbers either way — the artifact records what the defense
    actually buys on this cell, with the clean-model ``asr_baseline``
    attribution (schema v9 discipline)."""
    from ... import data as data_lib
    from ... import parallel
    from ...attacks import targeted as targeted_lib
    from ...models import select_model
    from ...parallel import aggregathor
    from ...utils import selectors

    module = select_model("gpt_tiny", "copytask")
    loss = selectors.select_loss("crossentropy")
    m = data_lib.DatasetManager("copytask", 32, num_workers, num_workers, 0)
    m.num_ps = 0
    xs, ys = m.sharded_train_batches()
    test = parallel.EvalSet(m.get_test_set())
    params = {
        "source": 0, "target": 3, "poison_frac": 1.0,
        # An out-of-vocab-for-distractors prefix: token 30 appears in
        # no clean copytask sequence (distractors live in [10, 30)).
        "trigger_token": 30, "trigger_size": 2,
    }
    cfg = targeted_lib.configure("backdoor", params, num_classes=10)
    rows = []
    for defname, defense in (
        ("none", None),
        ("data", {"weighted": False, "data": {}}),
    ):
        # Adam, not hot SGD: plain SGD needs a rate that NaNs this
        # transformer within 150 steps before it learns the task; adam
        # at 2e-3 reaches ~0.998 clean accuracy in 150 rounds.
        opt = selectors.select_optimizer("adam", lr=2e-3)
        init_fn, step_fn, eval_fn = aggregathor.make_trainer(
            module, loss, opt, "average", num_workers=num_workers,
            f=f, attack="backdoor", attack_params=params,
            defense=defense,
        )
        state = init_fn(jax.random.PRNGKey(seed), xs[0, 0])
        nb = xs.shape[1]
        for i in range(steps):
            b = i % nb
            state, metrics = step_fn(
                state, jnp.asarray(xs[:, b]), jnp.asarray(ys[:, b])
            )
        rep = parallel.targeted_eval(
            state, eval_fn, test, source=0, target=3, trigger_cfg=cfg,
        )
        rows.append({
            "check": "backdoor/gpt_tiny", "model": "gpt_tiny",
            "cell": f"backdoor/{defname}", "defense": defname,
            "slots": num_workers, "d": int(sum(
                int(np.prod(l.shape))
                for l in jax.tree.leaves(state.params)
            )),
            "seq": int(xs.shape[-1]), "steps": steps,
            "asr": round(float(rep["asr"]), 4),
            "asr_baseline": round(float(rep["asr_baseline"]), 4),
            "accuracy": round(float(rep["accuracy"]), 4),
            "loss_final": round(float(metrics["loss"]), 4),
            "backend": jax.default_backend(),
            "peak_rss_bytes": peak_rss_bytes(),
        })
        print(f"backdoor/{defname:<5} asr={rows[-1]['asr']:.3f} "
              f"baseline={rows[-1]['asr_baseline']:.3f} "
              f"acc={rows[-1]['accuracy']:.3f}", flush=True)
    return rows


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Slot-fused transformer A/B + robustness capture"
    )
    p.add_argument("--models", nargs="*", default=None,
                   help="A/B models (default: vit_tiny gpt_tiny).")
    p.add_argument("--slots", type=int, default=8,
                   help="Per-chip worker slots (the fused axis).")
    p.add_argument("--batch", type=int, default=4,
                   help="Per-slot batch size.")
    p.add_argument("--img", type=int, default=16,
                   help="vit_tiny input side (16 -> 16 patches).")
    p.add_argument("--reps", type=int, default=5)
    p.add_argument("--trials", type=int, default=3,
                   help="Independent timing trials; the committed "
                        "value is the minimum (VERDICT r4 #3).")
    p.add_argument("--robust", action="store_true",
                   help="Token-backdoor ASR cells (gpt_tiny/copytask, "
                        "defense none vs data) instead of the A/B "
                        "timing grid.")
    p.add_argument("--steps", type=int, default=150,
                   help="--robust: training steps per cell.")
    p.add_argument("--workers", type=int, default=8,
                   help="--robust: worker count (f of them poison).")
    p.add_argument("--f", type=int, default=2,
                   help="--robust: poisoning cohort size.")
    p.add_argument("--json", type=str, default=None,
                   help="Dump rows to this JSON file plus the schema-"
                        "versioned JSONL twin (one v14 'trans_bench' "
                        "record per row, tier-1-validated).")
    args = p.parse_args(argv)

    results = []
    if args.robust:
        results.extend(robust_cells(
            steps=args.steps, num_workers=args.workers, f=args.f,
        ))
    else:
        for name in (args.models or list(AB_MODELS)):
            cell = ab_cell(
                name, slots=args.slots, batch=args.batch,
                img=args.img, reps=args.reps, trials=args.trials,
            )
            speedup = (
                None if not cell["fused"] or not cell["unrolled"]
                else round(cell["unrolled"] / cell["fused"], 3)
            )
            for path in ("fused", "unrolled"):
                lat = cell[path]
                row = {
                    "check": f"{name}/{path}", "model": name,
                    "path": path, "slots": args.slots, "d": cell["d"],
                    "seq": cell["seq"], "heads": cell["heads"],
                    "depth": cell["depth"],
                    # provenance: the conv dw strategy dominates the
                    # vit patchify cell on CPU (DESIGN.md §23's
                    # negative result), so the knob is recorded.
                    "dw_mode": os.environ.get(
                        "GARFIELD_SLOTFUSED_DW", "grouped"),
                    "per_slot_grad_s": (
                        None if lat is None else lat / args.slots
                    ),
                    "speedup": speedup if path == "fused" else None,
                    "reps": cell[f"{path}_reps"],
                    "trials": args.trials, "dce_guard": True,
                    "backend": jax.default_backend(),
                    "peak_rss_bytes": peak_rss_bytes(),
                }
                results.append(row)
                shown = ("below noise floor" if lat is None else
                         f"{lat / args.slots * 1e3:8.3f} ms/slot")
                extra = (f"  speedup {speedup}x"
                         if path == "fused" and speedup else "")
                print(f"{name:>9} {path:>8} d={cell['d']:<7} {shown}"
                      f"{extra}", flush=True)

    if args.json:
        with open(args.json, "w") as fp:
            json.dump(results, fp, indent=1)
        from ...telemetry import exporters

        jsonl_path = os.path.splitext(args.json)[0] + ".jsonl"
        with exporters.JsonlExporter(jsonl_path) as exp:
            for row in results:
                exp.write(exporters.make_record("trans_bench", **row))
    return results


if __name__ == "__main__":
    main(sys.argv[1:])

#!/usr/bin/env bash
# Stop a fanned-out experiment on every host.
# Counterpart of the reference's per-app kill.sh (ssh + pkill loops).
#
# Usage: scripts/kill.sh <hosts_file>
set -euo pipefail

HOSTS_FILE=${1:?hosts file}
mapfile -t HOSTS < <(grep -v '^#' "$HOSTS_FILE" | sed '/^$/d')
for entry in "${HOSTS[@]}"; do
  HOST=${entry%%:*}
  ssh -o StrictHostKeyChecking=no "$HOST" \
    "pkill -f 'garfield_tpu.apps' || true" &
done
wait
echo "killed garfield_tpu processes on ${#HOSTS[@]} hosts"

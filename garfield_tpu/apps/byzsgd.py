"""ByzSGD / GuanYu: replicated Byzantine parameter servers (MSMW).

Counterpart of ``pytorch_impl/applications/ByzSGD/trainer.py`` (P18): the
AggregaThor step plus the model-space "gather step" (trainer.py:240-244) in
which every PS pulls its peers' models, GAR-aggregates them, and writes the
result back — tolerating fps Byzantine servers (byzServer.py attacks via
--ps_attack).

  python -m garfield_tpu.apps.byzsgd --dataset cifar10 --model resnet18 \\
      --num_workers 8 --num_ps 3 --fw 2 --fps 1 --gar median \\
      --attack lie --ps_attack random
"""

import sys

from ..parallel import byzsgd
from . import common


def main(argv=None):
    parser = common.base_parser("ByzSGD implementation using garfield-tpu")
    parser.add_argument(
        "--ps_attack", type=str, default=None,
        help="Byzantine server model attack: random, reverse, drop "
             "(byzServer.py:74-78); lie, empire (model-plane collusion "
             "over the gathered replica stack, DESIGN.md §17); "
             "adaptive-lie, adaptive-empire (the collusion magnitude "
             "bisected against the model gather's admission feedback — "
             "in-graph the bracket rides TrainState.attack_state, in "
             "--cluster mode a real Byzantine PS probes the replica "
             "plane's forward delta).",
    )
    parser.add_argument(
        "--ps_attack_params", type=__import__("json").loads, default={},
        help="Model-attack parameters as JSON (z/eps for the collusion "
             'attacks; adaptive knobs: {"mag_max": 12.0, "f_pool": 2, '
             '"rotation": 8}).',
    )
    parser.add_argument(
        "--model_gar", type=str, default=None,
        help="GAR for the model gather step (default: same as --gar, "
             "ByzSGD/trainer.py:34 note).",
    )
    parser.add_argument(
        "--model_subset", type=int, default=None,
        help="Per-PS wait-n-f on the MODEL gather: each PS aggregates its "
             "own seeded fastest q_m peer models. Pass num_ps - fps for "
             "exact protocol parity with get_models(num_ps - fps) "
             "(ByzSGD/trainer.py:240-242); unset aggregates all.",
    )
    parser.add_argument(
        "--cluster", type=str, default=None,
        help="Cluster config JSON: run as ONE process of a multi-process "
             "MSMW deployment over PeerExchange — every PS a real process "
             "(a Byzantine one via --ps_attack), true wait-n-f on the "
             "gradient plane (the reference's per-app run_exp.sh shape).",
    )
    parser.add_argument(
        "--task", type=str, default=None,
        help='Role override for --cluster, "ps:K" or "worker:K".',
    )
    parser.add_argument(
        "--cluster_timeout_ms", type=int, default=60_000,
        help="Per-step collect timeout in cluster mode.",
    )
    args = parser.parse_args(argv)
    if args.cluster:
        from . import cluster

        args.num_workers = args.num_ps = None  # counts come from the config
        return cluster.run(args)
    assert args.fw * 2 < args.num_workers
    assert args.fps * 2 < args.num_ps or args.fps == 0
    if getattr(args, "async_agg", False):
        from ..utils import tools

        tools.warning(
            "[byzsgd] --async on the on-mesh topology is not emulated "
            "(the in-graph staleness emulation lives on aggregathor; "
            "cluster MSMW deployments support --async for real) — "
            "running round-synchronous"
        )
    return common.train(
        args,
        topology=byzsgd,
        make_trainer_kwargs=dict(
            num_workers=args.num_workers,
            num_ps=args.num_ps,
            fw=args.fw,
            fps=args.fps,
            attack=args.attack,
            attack_params=args.attack_params,
            ps_attack=args.ps_attack,
            ps_attack_params=args.ps_attack_params,
            subset=args.subset,
            model_subset=args.model_subset,
            model_gar=args.model_gar,
        ),
        num_slots=args.num_workers,
        tag="byzsgd",
    )


if __name__ == "__main__":
    main(sys.argv[1:])

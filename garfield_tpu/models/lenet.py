"""LeNet-5 (counterpart of garfieldpp/models/lenet.py)."""

import flax.linen as nn
import jax.numpy as jnp

from ._layers import max_pool


class LeNet(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        x = nn.relu(nn.Conv(6, (5, 5), padding="VALID", dtype=self.dtype)(x))
        x = max_pool(x, 2)
        x = nn.relu(nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype)(x))
        x = max_pool(x, 2)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(84, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)

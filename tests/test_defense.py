"""Closed-loop defense (aggregators/defense.py): fast tier-1 coverage.

The suspicion-weight law (exact identity on clean/uniform histories, the
median-relative inversion guard), the concentration statistic (both
Byzantine signatures), the escalation state machine's HYSTERESIS — no
flapping on a boundary value, the satellite pin — and the in-graph
trainer integration: suspicion-weighted folds train fold-vs-flat
equivalent, and defense-off trajectories are bitwise the undefended
ones. The windowed hub suspicion (suspicion_halflife) is covered here
too — it is what the rotation attack launders the cumulative score
against.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu import data as data_lib
from garfield_tpu.aggregators import defense
from garfield_tpu.models import select_model
from garfield_tpu.parallel import aggregathor
from garfield_tpu.telemetry import exporters as tele_fmt, hub as hub_lib
from garfield_tpu.utils import selectors


class TestWeights:
    def test_clean_history_is_exactly_one(self):
        w = defense.suspicion_weights(np.zeros(8, np.float32))
        np.testing.assert_array_equal(w, np.ones(8, np.float32))

    def test_uniform_history_is_exactly_one(self):
        # The inversion guard: krum at m of n refuses n - m rows EVERY
        # round; a uniformly-excluded crowd must not be down-weighted.
        w = defense.suspicion_weights(np.full(8, 0.6, np.float32))
        np.testing.assert_array_equal(w, np.ones(8, np.float32))

    def test_relative_excess_is_punished_with_floor(self):
        s = np.array([0.3, 0.3, 0.3, 1.0], np.float32)
        w = defense.suspicion_weights(s, power=2.0, floor=0.1)
        np.testing.assert_array_equal(w[:3], np.ones(3, np.float32))
        assert w[3] == pytest.approx(max((1 - 0.7) ** 2, 0.1))

    def test_raw_mode_and_validation(self):
        w = defense.suspicion_weights(
            np.array([0.0, 0.5]), relative=False, power=1.0, floor=0.0
        )
        np.testing.assert_allclose(w, [1.0, 0.5])
        with pytest.raises(ValueError):
            defense.suspicion_weights([0.1], floor=2.0)
        with pytest.raises(ValueError):
            defense.suspicion_weights([0.1], power=0.0)

    def test_jnp_matches_np(self):
        s = np.array([0.1, 0.9, 0.4, 0.4], np.float32)
        w_np = defense.suspicion_weights(s)
        w_j = np.asarray(defense.suspicion_weights(jnp.asarray(s)))
        np.testing.assert_allclose(w_j, w_np, atol=1e-7)


class TestConcentration:
    def test_clean_is_zero_and_signatures_are_high(self):
        assert defense.suspicion_concentration(np.zeros(8), 2) == 0.0
        # Pinned victims (static attack): top-f -> 1, crowd low.
        pinned = np.array([0.2] * 6 + [1.0, 1.0])
        assert defense.suspicion_concentration(pinned, 2) >= 0.7
        # Laundering cohort (adaptive attack): bottom-f conspicuously
        # clean while the crowd absorbs the displaced exclusions.
        laundered = np.array([0.05, 0.05] + [0.7] * 6)
        assert defense.suspicion_concentration(laundered, 2) >= 0.6

    def test_validation(self):
        with pytest.raises(ValueError):
            defense.suspicion_concentration(np.zeros(4), 0)
        with pytest.raises(ValueError):
            defense.suspicion_concentration(np.zeros(4), 4)


class TestEscalationPolicy:
    def _policy(self, **kw):
        cfg = dict(theta_up=0.5, theta_down=0.2, patience=3,
                   clean_window=4)
        cfg.update(kw)
        return defense.EscalationPolicy(defense.EscalationConfig(**cfg))

    def test_patience_gates_escalation(self):
        p = self._policy()
        assert p.observe(0.9) == 0
        assert p.observe(0.9) == 0
        assert p.observe(0.9) == 1
        assert p.level_name == "multi-krum"

    def test_boundary_value_never_flaps(self):
        # The satellite pin: a concentration parked INSIDE the
        # hysteresis band — or oscillating across it — moves nothing.
        p = self._policy()
        for _ in range(200):
            assert p.observe(0.35) == 0
        assert p.level == 0
        p2 = self._policy()
        for _ in range(100):
            assert p2.observe(0.49) == 0   # just under theta_up
            assert p2.observe(0.21) == 0   # just over theta_down
        assert p2.level == 0 and p2.escalations == 0

    def test_interruption_resets_counters(self):
        p = self._policy()
        p.observe(0.9)
        p.observe(0.9)
        p.observe(0.35)  # band: resets the hot streak
        assert p.observe(0.9) == 0
        assert p.observe(0.9) == 0
        assert p.observe(0.9) == 1

    def test_clean_window_deescalates_and_floors_at_zero(self):
        p = self._policy(patience=1, clean_window=3)
        assert p.observe(0.9) == 1
        for _ in range(2):
            assert p.observe(0.1) == 0
        assert p.observe(0.1) == -1
        assert p.level == 0
        for _ in range(10):  # never below the ladder's base
            p.observe(0.1)
        assert p.level == 0

    def test_ladder_tops_out(self):
        p = self._policy(patience=1)
        assert p.observe(0.9) == 1
        assert p.observe(0.9) == 1
        assert p.level_name == "bulyan"
        for _ in range(5):
            assert p.observe(0.9) == 0  # saturated

    def test_config_validation(self):
        with pytest.raises(ValueError, match="theta"):
            defense.EscalationConfig(theta_up=0.2, theta_down=0.3)
        with pytest.raises(ValueError, match="unknown escalation level"):
            defense.EscalationConfig(levels=("krum", "nope"))
        with pytest.raises(ValueError, match="stateful"):
            defense.EscalationConfig(levels=("krum", "cclip"))

    def test_resolve_cli(self):
        class A:
            defense = "escalate"
            defense_params = {"theta_up": 0.6, "halflife": 8}

        plan = defense.resolve(A())
        assert plan.escalate and plan.weighted
        assert plan.halflife == 8.0
        assert plan.policy().config.theta_up == 0.6

        class B:
            defense = None

        assert defense.resolve(B()) is None

        class C:
            defense = "weighted"
            defense_params = {"bogus": 1}

        with pytest.raises(SystemExit, match="bogus"):
            defense.resolve(C())


class TestHubWindowedSuspicion:
    def _tap(self, selected):
        n = len(selected)
        return {
            "observed": np.ones(n), "selected": np.array(selected),
            "score": np.zeros(n), "tau": 0.0, "clip_frac": 0.0,
        }

    def test_decayed_score_forgets_old_attacks(self):
        # Rank 3 attacks for 10 steps, then sits honest for 40: the
        # cumulative score dilutes slowly, the windowed score collapses
        # — the laundering detector (DESIGN.md §16).
        hub = hub_lib.MetricsHub(num_ranks=4, suspicion_halflife=5)
        for i in range(10):
            hub.record_step(i, tap=self._tap([1, 1, 1, 0]))
        for i in range(10, 50):
            hub.record_step(i, tap=self._tap([1, 1, 1, 1]))
        cum = hub.suspicion()
        dec = hub.suspicion_decayed()
        assert cum[3] == pytest.approx(10 / 50)
        assert dec[3] < 0.01 < cum[3]

    def test_decayed_score_sees_live_attacks(self):
        hub = hub_lib.MetricsHub(num_ranks=4, suspicion_halflife=5)
        for i in range(40):
            hub.record_step(i, tap=self._tap([1, 1, 1, 1]))
        for i in range(40, 50):
            hub.record_step(i, tap=self._tap([1, 1, 1, 0]))
        assert hub.suspicion()[3] == pytest.approx(10 / 50)
        assert hub.suspicion_decayed()[3] > 0.6

    def test_no_halflife_falls_back_to_cumulative(self):
        hub = hub_lib.MetricsHub(num_ranks=2)
        hub.record_step(0, tap=self._tap([1, 0]))
        np.testing.assert_allclose(
            hub.suspicion_decayed(), hub.suspicion()
        )

    def test_summary_and_events_validate_as_v7(self):
        hub = hub_lib.MetricsHub(num_ranks=3, suspicion_halflife=4)
        hub.record_step(0, tap=self._tap([1, 1, 0]))
        recs = [
            hub.record_event("attack_adapt", step=0, magnitude=1.5,
                             detected=True, lo=0.25, hi=3.0),
            hub.record_event("defense_weights", step=0,
                             ranks=[0, 1, 2], weights=[1.0, 1.0, 0.1]),
            hub.record_event("defense_escalate", step=1, level=1,
                             rule="multi-krum", direction="escalate"),
            hub.record_event("attack_fallback", attack="random",
                             path="where", why="randomized"),
            hub.summary(),
        ]
        for r in recs:
            tele_fmt.validate_record(r)
        s = recs[-1]
        assert s["suspicion_decayed"] is not None
        assert s["defense"]["escalations"] == 1
        assert s["defense"]["rule"] == "multi-krum"
        assert s["defense"]["min_w"] == pytest.approx(0.1)
        assert s["attack_adapt"]["events"] == 1

    def test_malformed_v7_events_rejected(self):
        for rec in (
            tele_fmt.make_record("event", event="attack_adapt",
                                 magnitude="big"),
            tele_fmt.make_record("event", event="defense_escalate",
                                 level=-1, rule="krum",
                                 direction="escalate"),
            tele_fmt.make_record("event", event="defense_escalate",
                                 level=1, rule="krum", direction="up"),
            tele_fmt.make_record("event", event="defense_weights",
                                 weights="all"),
            tele_fmt.make_record("defense_bench", cell="", gar="krum"),
            tele_fmt.make_record("defense_bench", cell="c", gar="krum",
                                 final_accuracy="high"),
        ):
            with pytest.raises(ValueError):
                tele_fmt.validate_record(rec)


def _pima_setup():
    module = select_model("pimanet", "pima")
    loss = selectors.select_loss("bce")
    opt = selectors.select_optimizer(
        "sgd", lr=0.05, momentum=0.0, weight_decay=0.0
    )
    return module, loss, opt


def _pima_batches(n, bsz):
    m = data_lib.DatasetManager("pima", bsz, n, n, 0)
    m.num_ps = 0
    xs, ys = m.sharded_train_batches()
    return xs, jnp.asarray(xs[:, 0]), jnp.asarray(ys[:, 0])


def _flat_params(state):
    return np.concatenate(
        [np.asarray(l).ravel() for l in jax.tree.leaves(state.params)]
    )


class TestTrainerIntegration:
    def test_defense_off_is_bitwise_undefended(self):
        # The acceptance's purity half: defense=None must not change one
        # bit of the trajectory (nothing defense-shaped is traced).
        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        runs = []
        for d in (None, None):
            init_fn, step_fn, _ = aggregathor.make_trainer(
                module, loss, opt, "krum", num_workers=8, f=2,
                attack="lie", defense=d,
            )
            state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
            for _ in range(5):
                state, metrics = step_fn(state, x, y)
            runs.append((_flat_params(state), float(metrics["loss"])))
        np.testing.assert_array_equal(runs[0][0], runs[1][0])
        assert runs[0][1] == runs[1][1]

    def test_suspicion_weighted_fold_matches_flat(self):
        # The acceptance pin: suspicion-weighted folds (Gram row-weight
        # composition) train equivalently to the flat path's explicit
        # row scaling — with the SAME carried defense EMA on both.
        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        outs = []
        for tree_path in (True, False):
            init_fn, step_fn, _ = aggregathor.make_trainer(
                module, loss, opt, "krum", num_workers=8, f=2,
                attack="lie", defense={"halflife": 4.0},
                tree_path=tree_path,
            )
            state = init_fn(jax.random.PRNGKey(2), xs[0, 0])
            for _ in range(6):
                state, metrics = step_fn(state, x, y)
            assert np.isfinite(float(metrics["loss"]))
            outs.append((
                _flat_params(state),
                np.asarray(state.defense_state["exc"]),
            ))
        np.testing.assert_allclose(
            outs[0][0], outs[1][0], rtol=2e-5, atol=1e-6
        )
        np.testing.assert_allclose(outs[0][1], outs[1][1], atol=1e-4)

    def test_defense_state_accumulates_exclusions(self):
        module, loss, opt = _pima_setup()
        m = data_lib.DatasetManager("pima", 16, 8, 8, 0)
        m.num_ps = 0
        xs, ys = m.sharded_train_batches()
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, "krum", num_workers=8, f=2,
            attack="reverse", defense={"halflife": 8.0},
        )
        state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
        nb = xs.shape[1]
        for i in range(12):
            # Fresh batches: a FIXED batch would pin krum's exclusion
            # pattern among the honest ranks too (deterministic
            # geometry), which is not what the defense keys on.
            b = i % nb
            state, metrics = step_fn(
                state, jnp.asarray(xs[:, b]), jnp.asarray(ys[:, b])
            )
        obs = np.asarray(state.defense_state["obs"])
        exc = np.asarray(state.defense_state["exc"])
        assert (obs > 0).all()
        # reverse (-100x) rows are excluded every round: the Byzantine
        # ranks' exclusion EMA must dominate the honest ranks'.
        susp = exc / obs
        assert susp[6:].min() > susp[:6].max()
        # And the median-relative weights floor the Byzantine ranks while
        # every honest rank keeps (clearly) more weight than any of them.
        w = np.asarray(defense.suspicion_weights(jnp.asarray(susp)))
        assert w[6:].max() <= 0.2
        assert w[:6].min() > 2 * w[6:].max()

    def test_defense_composes_with_staleness(self):
        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, "krum", num_workers=8, f=2,
            attack="lie", defense={"halflife": 8.0},
            staleness={"max_staleness": 3, "decay": 0.5,
                       "taus": [0, 1, 0, 2, 0, 0, 0, 3]},
        )
        state = init_fn(jax.random.PRNGKey(1), xs[0, 0])
        for _ in range(5):
            state, metrics = step_fn(state, x, y)
        assert np.isfinite(float(metrics["loss"]))
        assert metrics["defense_w"].shape == (8,)


class TestPlaneDefense:
    """Host-side per-plane defense runtime (DESIGN.md §17): independent
    decayed histories + independent ladders per aggregation plane."""

    def _plan(self, escalate=True):
        return defense.DefensePlan(
            weighted=True, escalate=escalate, power=2.0, floor=0.1,
            halflife=8.0,
            escalation=defense.EscalationConfig(
                theta_up=0.5, theta_down=0.2, patience=2, clean_window=8,
            ),
        )

    def test_clean_history_weights_are_identity(self):
        pd = defense.PlaneDefense(
            self._plan(escalate=False), 8, f=2, plane="gradient",
            base_gar="krum",
        )
        assert pd.weights_for([0, 1, 2]) is None
        pd.fold([0, 1, 2, 3], [1.0, 1.0, 1.0, 1.0])  # all admitted
        assert pd.weights_for([0, 1, 2, 3]) is None

    def test_excluded_rank_loses_weight(self):
        pd = defense.PlaneDefense(
            self._plan(escalate=False), 8, f=2, plane="gradient",
            base_gar="krum",
        )
        for _ in range(6):
            pd.fold(list(range(8)), [1.0] * 7 + [0.0])
        w = pd.weights_for(list(range(8)))
        assert w is not None
        assert w[7] < 1.0 and np.all(w[:7] == 1.0)

    def test_per_plane_ladder_independence(self):
        # The satellite pin: the GRADIENT plane escalates while the
        # MODEL plane — a separate PlaneDefense with a clean history —
        # stays at its starting level.
        plan = self._plan()
        grad = defense.PlaneDefense(
            plan, 8, f=2, plane="gradient", base_gar="krum",
        )
        model = defense.PlaneDefense(
            plan, 5, f=1, plane="model", base_gar="krum",
        )
        # Both ladders start at the level MATCHING the configured rule's
        # semantics (repo-default krum == multi-krum; start_level).
        start = defense.start_level(plan.escalation.levels, "krum")
        assert grad.policy.level == model.policy.level == start == 1
        for _ in range(6):
            # Concentrated exclusions on the gradient plane only.
            grad.fold(list(range(8)), [1.0] * 6 + [0.0, 0.0])
            assert grad.observe() in (0, 1)
            # The model plane's quorums stay clean.
            model.fold(list(range(5)), [1.0] * 5)
            assert model.observe() == 0
        assert grad.policy.level > start
        assert grad.current()[0] == "bulyan"
        assert model.policy.level == start
        assert model.current() == ("krum", {})

    def test_start_level_matches_semantics_not_names(self):
        lv = defense.DEFAULT_LEVELS
        # Repo-default krum (m = n - f - 2) IS the multi-krum level; a
        # name match at classic krum would DOWNGRADE the deployed rule.
        assert defense.start_level(lv, "krum") == 1
        assert defense.start_level(lv, "krum", {"m": 1}) == 0
        assert defense.start_level(lv, "bulyan") == 2
        assert defense.start_level(lv, "median") == 0

    def test_escalate_needs_ladder_rule(self):
        with pytest.raises(ValueError, match="escalation-ladder"):
            defense.PlaneDefense(
                self._plan(), 8, f=2, plane="gossip", base_gar="hier-krum",
            )

    def test_revert_undoes_infeasible_level(self):
        pd = defense.PlaneDefense(
            self._plan(), 8, f=2, plane="gradient", base_gar="krum",
        )
        start = pd.policy.level
        for _ in range(4):
            pd.fold(list(range(8)), [1.0] * 6 + [0.0, 0.0])
            act = pd.observe()
            if act:
                pd.revert(act)
        assert pd.policy.level == start


class TestPlaneTwinsInGraph:
    """The in-graph twins' defense deployment (parallel/byzsgd,
    parallel/learn): clean-start identity weights, defense-off bitwise
    purity, per-plane metrics."""

    def test_byzsgd_defense_off_is_bitwise_undefended(self):
        from garfield_tpu.parallel import byzsgd

        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        runs = []
        for d in (None, None):
            init_fn, step_fn, _ = byzsgd.make_trainer(
                module, loss, opt, "krum", num_workers=8, num_ps=5,
                fw=2, fps=1, attack="lie", defense=d,
            )
            state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
            for _ in range(4):
                state, metrics = step_fn(state, x, y)
            runs.append((_flat_params(state), float(metrics["loss"])))
        np.testing.assert_array_equal(runs[0][0], runs[1][0])
        assert runs[0][1] == runs[1][1]

    def test_byzsgd_first_step_weights_are_identity(self):
        from garfield_tpu.parallel import byzsgd

        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = byzsgd.make_trainer(
            module, loss, opt, "krum", num_workers=8, num_ps=5,
            fw=2, fps=1, attack="lie", defense={"halflife": 8.0},
        )
        state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
        state, metrics = step_fn(state, x, y)
        # Clean-start contract: no history yet, every weight EXACTLY 1.0
        # on BOTH planes (the defense-off identity, weighted half).
        np.testing.assert_array_equal(
            np.asarray(metrics["defense_w"]), np.ones(8, np.float32)
        )
        np.testing.assert_array_equal(
            np.asarray(metrics["ps_defense_w"]), np.ones(5, np.float32)
        )
        # And the per-plane EMAs are carried, plane-shaped.
        assert np.asarray(state.defense_state["obs"]).shape == (8,)
        assert np.asarray(state.defense_state["ps_obs"]).shape == (5,)

    def test_learn_defense_weights_all_three_phases(self):
        from garfield_tpu.parallel import learn

        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = learn.make_trainer(
            module, loss, opt, "krum", num_nodes=8, f=2,
            attack="reverse", non_iid=True, defense={"halflife": 4.0},
        )
        state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
        for _ in range(8):
            state, metrics = step_fn(state, x, y)
        assert np.isfinite(float(metrics["loss"]))
        w = np.asarray(metrics["defense_w"])
        assert w.shape == (8,)
        # reverse rows are excluded every phase-2 round: the Byzantine
        # nodes' carried suspicion must dominate and floor their weight.
        susp = (
            np.asarray(state.defense_state["exc"])
            / np.maximum(np.asarray(state.defense_state["obs"]), 1e-6)
        )
        assert susp[6:].min() > susp[:6].max()

    def test_learn_defense_off_is_bitwise_undefended(self):
        from garfield_tpu.parallel import learn

        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        runs = []
        for d in (None, None):
            init_fn, step_fn, _ = learn.make_trainer(
                module, loss, opt, "krum", num_nodes=8, f=2,
                attack="lie", model_attack="reverse", defense=d,
            )
            state = init_fn(jax.random.PRNGKey(3), xs[0, 0])
            for _ in range(4):
                state, metrics = step_fn(state, x, y)
            runs.append(_flat_params(state))
        np.testing.assert_array_equal(runs[0], runs[1])


class TestSchemaV8:
    def test_ps_attack_adapt_and_targeted_eval_validate(self):
        tele_fmt.validate_record(tele_fmt.make_record(
            "event", event="ps_attack_adapt", step=3, magnitude=1.25,
            detected=True, lo=0.5, hi=2.0, plane="model",
        ))
        tele_fmt.validate_record(tele_fmt.make_record(
            "event", event="targeted_eval", step=10, source=0, target=1,
            accuracy=0.91, confusion=0.12, asr=0.4,
            per_class={"0": 0.9, "1": 0.92},
        ))

    def test_summary_targeted_digest_validates(self):
        hub = hub_lib.MetricsHub(num_ranks=4)
        hub.record_event(
            "targeted_eval", source=0, target=1, confusion=0.2, asr=0.5,
        )
        s = hub.summary()
        assert s["targeted"] == {
            "events": 1, "last_confusion": 0.2, "last_asr": 0.5,
        }
        tele_fmt.validate_record(s)

    def test_malformed_v8_events_rejected(self):
        with pytest.raises(ValueError):
            tele_fmt.validate_record(tele_fmt.make_record(
                "event", event="ps_attack_adapt", magnitude="big",
            ))
        with pytest.raises(ValueError):
            tele_fmt.validate_record(tele_fmt.make_record(
                "event", event="targeted_eval", source="a", target=1,
            ))
        with pytest.raises(ValueError):
            tele_fmt.validate_record(tele_fmt.make_record(
                "event", event="targeted_eval", source=0, target=1,
                per_class={"0": "high"},
            ))
        with pytest.raises(ValueError):
            tele_fmt.validate_record(tele_fmt.make_record(
                "defense_bench", cell="x", gar="krum", plane=7,
            ))

"""Federated round engine (garfield_tpu/federated/, DESIGN.md §19).

Fast tier-1 coverage: shard planning/reassembly + capacity guards, the
seeded cohort sampler (determinism pin, f pricing, staleness
composition), cohort-level f composition (budget covers the realized
Byzantine count => robustness-matrix-style tolerance; budget exceeded
=> the documented failure mode), the S=1 full-participation bitwise
anchor against the unsharded streaming path, sharded checkpoint
round-trip at pima scale, and the client-id-keyed suspicion the
rotation/resampling attack cannot launder. The multi-process wire
deployment (real shard planes over PeerExchange + the autoscaled client
fleet) lives in tests/test_fed_cluster.py (slow, conftest._RUN_LAST).
"""

import numpy as np
import pytest

from garfield_tpu import federated as fed
from garfield_tpu.aggregators import hierarchy
from garfield_tpu.telemetry import exporters, hub as tele_hub
from garfield_tpu.utils import rounds as rounds_lib, wire

RNG = np.random.default_rng(20260805)


def honest_rows(n, d, mu=None, sigma=0.1):
    mu = RNG.normal(size=d).astype(np.float32) if mu is None else mu
    return (mu[None, :] + sigma * RNG.normal(size=(n, d))).astype(
        np.float32
    ), mu


# ---------------------------------------------------------------------------
# sharding


class TestSharding:
    def test_spans_partition_and_reassemble_bitwise(self):
        for d, s in [(101, 4), (16, 16), (10 ** 5, 7), (9, 1)]:
            spec = fed.plan_shards(d, s)
            assert spec.spans[0][0] == 0 and spec.spans[-1][1] == d
            widths = [hi - lo for lo, hi in spec.spans]
            assert max(widths) - min(widths) <= 1  # balanced
            v = RNG.normal(size=d).astype(np.float32)
            parts = [spec.slice_rows(v, k) for k in range(s)]
            assert np.array_equal(fed.reassemble(spec, parts), v)

    def test_capacity_guards(self):
        with pytest.raises(ValueError, match="nibble"):
            fed.plan_shards(100, fed.MAX_SHARDS + 1)
        with pytest.raises(ValueError):
            fed.plan_shards(2, 4)  # more shards than parameters
        spec = fed.plan_shards(64, 4)
        with pytest.raises(ValueError):
            fed.shard_plane(4, spec.num_shards)
        with pytest.raises(TypeError):
            fed.shard_plane(1.5)
        # shard id == wire plane: the stamp and the slot agree.
        assert fed.shard_plane(3, 4) == 3

    def test_reassemble_rejects_mismatched_parts(self):
        spec = fed.plan_shards(10, 2)
        with pytest.raises(ValueError):
            fed.reassemble(spec, [np.zeros(5, np.float32)])
        with pytest.raises(ValueError):
            fed.reassemble(
                spec, [np.zeros(4, np.float32), np.zeros(6, np.float32)]
            )


# ---------------------------------------------------------------------------
# sampler


class TestSampler:
    def test_seeded_determinism_pin(self):
        """The cohort is a pure function of (seed, round): same seed +
        round => identical ids in identical order (order is bucket
        assignment, so it is part of the contract); different rounds or
        seeds diverge."""
        s = fed.CohortSampler(10_000, 256, seed=11)
        a, b = s.cohort(7), s.cohort(7)
        assert np.array_equal(a, b)
        assert a.dtype == np.int64 and np.unique(a).size == a.size
        assert not np.array_equal(s.cohort(7), s.cohort(8))
        s2 = fed.CohortSampler(10_000, 256, seed=12)
        assert not np.array_equal(s.cohort(7), s2.cohort(7))
        # Pinned bytes: a committed FEDBENCH row must be reproducible.
        assert s.cohort(0)[:4].tolist() == \
            fed.CohortSampler(10_000, 256, seed=11).cohort(0)[:4].tolist()

    def test_full_participation_is_identity_order(self):
        s = fed.CohortSampler(64, 64, seed=3)
        assert np.array_equal(s.cohort(5), np.arange(64))

    def test_f_budget_prices_the_cohort_not_the_population(self):
        s = fed.CohortSampler(10 ** 6, 1024, seed=0, byz_frac=0.01)
        f = s.f_budget()
        mean = 1024 * 0.01
        assert f >= mean  # at least the expectation
        assert f <= s.capacity()
        # Zero threat => zero budget; any threat => at least 1.
        assert fed.CohortSampler(100, 50, byz_frac=0.0).f_budget() == 0
        tiny = fed.CohortSampler(10 ** 6, 512, byz_frac=1e-6)
        assert tiny.f_budget() >= 1

    def test_f_budget_refuses_uncomposable_threat(self):
        s = fed.CohortSampler(10 ** 4, 64, byz_frac=0.3)
        with pytest.raises(ValueError, match="capacity"):
            s.f_budget()

    def test_realized_byzantine_counts_global_ids(self):
        s = fed.CohortSampler(1000, 100, seed=5)
        cohort = s.cohort(0)
        byz = set(cohort[:7].tolist()) | {999_999}
        assert s.realized_byzantine(cohort, byz) == 7

    def test_staleness_composition_drops_cutoff_members(self):
        pol = rounds_lib.StalenessPolicy(max_staleness=2, decay=0.5)
        s = fed.CohortSampler(100, 8, seed=1, staleness=pol)
        cohort = s.cohort(4)
        tags = {
            int(cohort[0]): 3,   # tau 1 -> weight 0.5
            int(cohort[1]): 4,   # fresh
            int(cohort[2]): 0,   # tau 4 > cutoff -> dropped
        }
        active, w, dropped = s.cohort_weights(4, cohort, tags)
        assert int(cohort[2]) in dropped.tolist()
        assert active.size == 7 and dropped.size == 1
        wmap = dict(zip(active.tolist(), w.tolist()))
        assert wmap[int(cohort[0])] == 0.5
        assert wmap[int(cohort[1])] == 1.0  # exactly 1.0: bitwise no-op
        # No tags / no policy: everyone fresh at exactly 1.0.
        a2, w2, d2 = s.cohort_weights(4, cohort, None)
        assert a2.size == 8 and np.all(w2 == 1.0) and d2.size == 0


# ---------------------------------------------------------------------------
# cohort-level f composition (ISSUE 13 satellite)


class TestCohortComposition:
    """plan_hierarchy over sampled cohorts: budget >= realized Byzantine
    count => the aggregate stays within the robustness-matrix-style
    tolerance of the honest mean; budget exceeded => the documented
    failure mode (the bound is void — and measurably so)."""

    def _attack_rows(self, n, d, n_byz, mu):
        rows, _ = honest_rows(n - n_byz, d, mu=mu)
        # Reverse-and-amplify: the classic divergence attack.
        bad = np.tile(-8.0 * mu, (n_byz, 1)).astype(np.float32)
        return np.concatenate([rows, bad], axis=0)

    def test_budget_covers_realized_count_bounds_aggregate(self):
        n, d = 96, 64
        s = fed.CohortSampler(10 ** 4, n, seed=2, byz_frac=0.02)
        f = s.f_budget()
        plan = hierarchy.plan_hierarchy(n, f, "krum")
        assert plan.n == n  # the cohort composes at the priced budget
        mu = RNG.normal(size=d).astype(np.float32)
        g = self._attack_rows(n, d, f, mu)  # realized == budget
        agg = np.asarray(hierarchy.aggregate(g, f, bucket_gar="krum"))
        # Within the honest spread: the rule kept the adversary out.
        assert np.linalg.norm(agg - mu) < 1.0

    def test_budget_exceeded_documented_failure(self):
        """The OTHER side of the contract: realized Byzantine count past
        the priced budget voids the bound — the reverse cohort drags
        the aggregate an order of magnitude off the honest mean. This
        is the failure mode the per-cohort pricing exists to prevent,
        recorded (not hidden) per DESIGN.md §19."""
        n, d = 96, 64
        f = 3  # deliberately under-priced
        mu = RNG.normal(size=d).astype(np.float32)
        mu /= np.float32(np.linalg.norm(mu) / 8.0)  # strong signal
        # Realized 60 >> budget 3: a majority of bucket summaries is
        # Byzantine, so the top krum's tightest cluster IS the attack.
        g = self._attack_rows(n, d, 60, mu)
        agg = np.asarray(hierarchy.aggregate(g, f, bucket_gar="krum"))
        honest_dist = np.linalg.norm(agg - mu)
        assert honest_dist > 2.0  # the bound is measurably void

    def test_engine_flags_budget_exceeded(self):
        n, d = 64, 32
        sampler = fed.CohortSampler(n, n, seed=4, byz_frac=0.02)
        eng = fed.FedRoundEngine(
            np.zeros(d, np.float32), 2, sampler, lr=0.1
        )
        ids, f = eng.begin_round()
        g, _ = honest_rows(n, d)
        eng.ingest_rows(g)
        info = eng.finish_round(byz_ids=set(ids[: f + 1].tolist()))
        assert info["realized_byz"] == f + 1
        assert info["budget_exceeded"] is True
        eng.begin_round()
        eng.ingest_rows(g)
        info = eng.finish_round(byz_ids=set(ids[:f].tolist()))
        assert info["budget_exceeded"] is False


# ---------------------------------------------------------------------------
# the engine


class TestEngine:
    def test_s1_full_participation_bitwise_unsharded(self):
        """The anchor: S=1 full participation over several rounds IS the
        existing unsharded single-PS streaming path, bit for bit — same
        StreamingAggregator programs, same arrival order, same SGD
        update."""
        n, d, rounds = 128, 96, 3
        sampler = fed.CohortSampler(n, n, seed=9, byz_frac=0.02)
        model0 = RNG.normal(size=d).astype(np.float32)
        eng = fed.FedRoundEngine(model0, 1, sampler, lr=0.05)
        ref = model0.copy()
        for r in range(rounds):
            ids, f = eng.begin_round()
            g = np.random.default_rng([13, r]).normal(
                size=(n, d)).astype(np.float32)
            eng.ingest_rows(g)
            eng.finish_round()
            red = hierarchy.StreamingAggregator(n, f)
            red.push_many(g)
            ref = (ref - np.float32(0.05) * red.finalize()).astype(
                np.float32
            )
        assert np.array_equal(eng.model, ref)

    def test_sharded_rounds_deterministic_and_agree_on_clean_data(self):
        """S>1 folds per-shard (selection may differ per span — the
        documented semantics), but the engine is deterministic, and on
        clean concentrated data every shard keeps the same inliers, so
        S=1 and S=2 land on the same aggregate to fold precision."""
        n, d = 64, 64
        sampler = fed.CohortSampler(n, n, seed=6)
        g, mu = honest_rows(n, d, sigma=0.01)
        outs = []
        for s in (1, 2, 4):
            eng = fed.FedRoundEngine(
                np.zeros(d, np.float32), s, sampler, lr=1.0
            )
            eng.begin_round()
            eng.ingest_rows(g)
            eng.finish_round()
            outs.append(eng.model.copy())
            eng2 = fed.FedRoundEngine(
                np.zeros(d, np.float32), s, sampler, lr=1.0
            )
            eng2.begin_round()
            eng2.ingest_rows(g)
            eng2.finish_round()
            assert np.array_equal(eng.model, eng2.model)  # deterministic
        for o in outs[1:]:
            # Per-shard selection may pick different (equally honest)
            # inliers per span, so agreement is to the honest spread,
            # not bitwise — the documented S>1 semantics.
            np.testing.assert_allclose(o, outs[0], atol=0.1)

    def test_partial_participation_round_and_telemetry(self):
        hub = tele_hub.MetricsHub(suspicion_halflife=8)
        tele_hub.install(hub)
        try:
            sampler = fed.CohortSampler(256, 32, seed=3, byz_frac=0.02)
            eng = fed.FedRoundEngine(
                np.zeros(48, np.float32), 2, sampler, lr=0.1,
                audit=True, telemetry=True,
            )
            ids, f = eng.begin_round()
            assert ids.size == 32
            g, _ = honest_rows(32, 48)
            eng.ingest_rows(g)
            info = eng.finish_round()
            assert info["active"] == 32 and info["f_budget"] == f
            assert set(info["per_shard"]) == {"0", "1"}
            fedstats = hub.federated_stats()
            assert fedstats["rounds"] == 1
            assert fedstats["last_cohort"] == 32
            assert hub.client_suspicion_decayed() is not None
            summ = hub.summary()
            exporters.validate_record(summ)
            assert summ["federated"]["rounds"] == 1
        finally:
            tele_hub.uninstall()

    def test_staleness_discounts_compose_into_rows(self):
        """A straggler's row enters every shard scaled by decay**tau —
        the same law as the async cluster plane (utils/rounds.py)."""
        n, d = 16, 24
        pol = rounds_lib.StalenessPolicy(max_staleness=3, decay=0.5)
        sampler = fed.CohortSampler(n, n, seed=1, staleness=pol)
        eng = fed.FedRoundEngine(
            np.zeros(d, np.float32), 2, sampler, lr=1.0,
            bucket_gar="average",
        )
        eng.round = 5
        g = np.ones((n, d), np.float32)
        tags = {0: 4}  # client 0 is one round stale -> weight 0.5
        active, f = eng.begin_round(tags=tags)
        assert active.size == n
        for cid in active.tolist():
            eng.ingest(cid, g[cid])
        eng.finish_round()
        # average over rows: (15 * 1.0 + 0.5) / 16 per coordinate.
        expect = -(15.0 + 0.5) / 16.0
        np.testing.assert_allclose(eng.model, expect, rtol=1e-6)

    def test_shard_server_wire_ingest_and_cross_shard_reject(self):
        spec = fed.plan_shards(32, 2)
        sv = fed.ShardServer(1, spec, bucket_gar="average")
        sv.begin_round(0, 4, 0)
        rows = RNG.normal(size=(4, 32)).astype(np.float32)
        sliced = spec.slice_rows(rows, 1)
        # A multi-row frame stamped for THIS shard ingests...
        sv.push_frame(wire.encode(sliced.ravel(), plane=1))
        agg = sv.finish_round()
        np.testing.assert_allclose(
            agg, sliced.mean(axis=0), rtol=1e-5, atol=1e-6
        )
        # ...a frame stamped for the OTHER shard is ban evidence.
        sv.begin_round(1, 4, 0)
        with pytest.raises(wire.WireError, match="cross-shard"):
            sv.push_frame(
                wire.encode(spec.slice_rows(rows, 0).ravel(), plane=0)
            )
        # ...and a non-whole-row frame too.
        with pytest.raises(wire.WireError, match="whole number"):
            sv.push_frame(wire.encode(np.ones(7, np.float32), plane=1))

    def test_shard_server_bounds_sparse_elems_claim(self):
        """REVIEW fix: a cohort member's CRC-valid topk frame claiming a
        huge dense size must reject on the shard's n*d_shard bound
        BEFORE the scatter allocates (np.zeros(elems) at 2^40 is a 4 TB
        allocation the sender controls) — same attributable WireError
        ban path as a cross-shard stamp. Honest sparse frames inside
        the bound still ingest."""
        import struct
        import zlib

        spec = fed.plan_shards(32, 2)
        sv = fed.ShardServer(1, spec, bucket_gar="average")
        sv.begin_round(0, 4, 0)
        pairs = np.zeros(2, np.dtype([("i", "<u4"), ("v", "<f4")]))
        pairs["i"] = [0, 1]
        pairs["v"] = [3.0, -3.0]
        payload = pairs.tobytes()
        giant = struct.pack(
            "!2sBBQI", b"GW", 1, (1 << 4) | 4, 2 ** 40,
            zlib.crc32(payload),
        ) + payload
        with pytest.raises(wire.WireError, match="bound"):
            sv.push_frame(giant)
        assert sv.arrived() == 0
        # An honest multi-row sparse frame (4 rows x d_shard=16 = 64
        # elems, exactly the bound) ingests fine.
        rows = RNG.normal(size=(4, 32)).astype(np.float32)
        sliced = spec.slice_rows(rows, 1)
        sv.push_frame(
            wire.encode(sliced.ravel(), "topk", k=64, plane=1)
        )
        assert sv.arrived() == 4
        assert sv.finish_round().shape == (16,)


# ---------------------------------------------------------------------------
# suspicion survives sampling (ISSUE 13 satellite)


class TestClientSuspicion:
    def test_rotating_sampled_attacker_tops_decayed_suspicion(self):
        """Regression: a Byzantine client resampled into a DIFFERENT
        cohort position every round must still top the hub's decayed
        suspicion — the score is keyed by stable global id, so cohort-
        index reshuffling (the sampling-scale laundering channel)
        buys nothing."""
        hub = tele_hub.MetricsHub(suspicion_halflife=6)
        tele_hub.install(hub)
        try:
            # Small population + many rounds: every honest client is
            # observed often enough that its exclusion frequency
            # converges to the rule's honest-exclusion rate (krum keeps
            # m = n - f - 2 per fold), leaving no one-observation ties
            # at 1.0 with the attacker.
            pop, n, d = 32, 16, 32
            byz = 7  # the one Byzantine global id
            sampler = fed.CohortSampler(pop, n, seed=21, byz_frac=0.05)
            eng = fed.FedRoundEngine(
                np.zeros(d, np.float32), 2, sampler, lr=0.01,
                audit=True, telemetry=True,
            )
            mu = RNG.normal(size=d).astype(np.float32)
            seen = 0
            for r in range(40):
                ids, f = eng.begin_round()
                rows, _ = honest_rows(ids.size, d, mu=mu, sigma=0.05)
                if byz in ids:
                    pos = int(np.where(ids == byz)[0][0])
                    rows[pos] = -50.0 * mu  # the reverse attack
                    seen += 1
                eng.ingest_rows(rows)
                eng.finish_round()
            assert seen >= 5, "sampler never drew the attacker"
            susp = hub.client_suspicion_decayed()
            assert susp is not None and byz in susp
            top = max(susp, key=susp.get)
            assert top == byz, (
                f"attacker {byz} (s={susp[byz]:.3f}) not on top — "
                f"got {top} (s={susp[top]:.3f})"
            )
            # And resampling cannot LAUNDER it: the attacker's score
            # strictly dominates every honest client's.
            honest_max = max(
                v for c, v in susp.items() if c != byz
            )
            assert susp[byz] > honest_max
        finally:
            tele_hub.uninstall()


# ---------------------------------------------------------------------------
# sharded checkpoints (ISSUE 13 satellite)


class TestShardedCheckpoint:
    def test_round_trip_bitwise_at_pima_scale(self, tmp_path):
        # pima-scale vector (the tabular model's parameter count is a
        # few hundred floats); odd size to exercise uneven spans.
        d = 937
        v = RNG.normal(size=d).astype(np.float32)
        for s in (1, 3, 4):
            spec = fed.plan_shards(d, s)
            dir_ = tmp_path / f"s{s}"
            fed.save_sharded(dir_, 7, v, spec)
            back = fed.restore_sharded(dir_, spec)
            assert np.array_equal(back, v)  # bitwise
            assert back.dtype == np.float32

    def test_partial_shard_save_and_torn_save_detection(self, tmp_path):
        d = 100
        spec = fed.plan_shards(d, 2)
        v = RNG.normal(size=d).astype(np.float32)
        # Each shard process saves only its own span...
        fed.save_sharded(tmp_path, 3, v, spec, shards=[0])
        # ...a torn save (shard 1 missing) must not restore.
        with pytest.raises(FileNotFoundError):
            fed.restore_sharded(tmp_path, spec)
        fed.save_sharded(tmp_path, 3, v, spec, shards=[1])
        assert np.array_equal(fed.restore_sharded(tmp_path, spec), v)

    def test_spec_mismatch_detected(self, tmp_path):
        """Restoring with the wrong shard map (a deployment error) is a
        loud span mismatch, not a silently misassembled model."""
        d = 64
        v = RNG.normal(size=d).astype(np.float32)
        fed.save_sharded(tmp_path, 1, v, fed.plan_shards(d, 2))
        wrong = fed.plan_shards(d, 2)
        wrong.spans = ((0, d // 2 - 1), (d // 2 - 1, d))
        with pytest.raises(ValueError, match="span"):
            fed.restore_sharded(tmp_path, wrong)


# ---------------------------------------------------------------------------
# telemetry schema v10


class TestTelemetryV10:
    def test_fed_round_and_cohort_events_validate(self):
        exporters.validate_record(exporters.make_record(
            "event", event="fed_round", step=3, shards=4, cohort=1000,
            f_budget=12, realized_byz=2, budget_exceeded=False,
            round_s=1.25,
            per_shard={"0": {"latency_s": 0.2, "wire_bytes": 1024}},
        ))
        exporters.validate_record(exporters.make_record(
            "event", event="cohort", step=3,
            client_ids=[5, 9, 11], selected=[1.0, 0.0, 1.0], f_budget=1,
        ))

    def test_malformed_v10_records_rejected(self):
        with pytest.raises(ValueError):
            exporters.validate_record(exporters.make_record(
                "event", event="fed_round", step=3, shards=0, cohort=10,
            ))
        with pytest.raises(ValueError):
            exporters.validate_record(exporters.make_record(
                "event", event="cohort", client_ids=[1, 2],
                selected=[1.0],  # length mismatch
            ))
        with pytest.raises(ValueError):
            exporters.validate_record(exporters.make_record(
                "fed_bench", check="", n=10, d=10, shards=1, gar="x",
            ))
        with pytest.raises(ValueError):
            exporters.validate_record(exporters.make_record(
                "fed_bench", check="scaling", n=10, d=10, shards=1,
                gar="hier-krum", s1_bitwise_equal="yes",
            ))

    def test_fed_bench_rows_validate(self):
        exporters.validate_record(exporters.make_record(
            "fed_bench", check="scaling", n=10 ** 6,
            population=2 * 10 ** 6, d=10 ** 4, shards=4, gar="hier-krum",
            f=10447, rounds=2, round_s=8.1, round_s_sum=33.0,
            speedup=2.96, per_shard_s=[8.1, 8.0, 8.0, 7.9],
            per_shard_rss=[10 ** 9] * 4, peak_rss_bytes=10 ** 9,
        ))
        exporters.validate_record(exporters.make_record(
            "fed_bench", check="fleet", n=64, d=10 ** 4, shards=2,
            gar="hier-krum", target_rate=10.0, pre_rate=6.0,
            recovered_rate=11.0, achieved_rate=11.0, spawns=3,
            retires=0, active_initial=2, active_final=5, round_s=0.09,
        ))

    def test_summary_federated_digest_validates(self):
        exporters.validate_record(exporters.make_record(
            "summary", steps=0, events=4,
            federated={"rounds": 2, "shards": 4, "budget_exceeded": 0,
                       "top_clients": {"7": 0.9}},
        ))
        with pytest.raises(ValueError):
            exporters.validate_record(exporters.make_record(
                "summary", steps=0, events=4,
                federated={"rounds": -1, "budget_exceeded": 0},
            ))


# ---------------------------------------------------------------------------
# hierarchy additions the engine leans on


class TestStreamingAdditions:
    def test_bulk_push_many_bitwise_equals_per_row(self):
        n, f, d = 200, 9, 40
        g, _ = honest_rows(n, d)
        bulk = hierarchy.StreamingAggregator(n, f)
        bulk.push_many(g)
        one = hierarchy.StreamingAggregator(n, f)
        for row in g:
            one.push(row)
        assert np.array_equal(bulk.finalize(), one.finalize())
        batch = np.asarray(hierarchy.aggregate(g, f))
        assert np.array_equal(bulk.finalize(), batch)

    def test_reset_reuses_buffers_bitwise(self):
        n, f, d = 150, 5, 32
        g1, _ = honest_rows(n, d)
        g2, _ = honest_rows(n, d)
        red = hierarchy.StreamingAggregator(n, f)
        red.push_many(g1)
        red.finalize()
        red.reset()
        red.push_many(g2)
        out = red.finalize()
        fresh = hierarchy.StreamingAggregator(n, f)
        fresh.push_many(g2)
        assert np.array_equal(out, fresh.finalize())

    def test_push_many_guards(self):
        red = hierarchy.StreamingAggregator(8, 0, bucket_gar="average")
        red.push_many(np.zeros((8, 4), np.float32))
        with pytest.raises(ValueError, match="past the"):
            red.push_many(np.zeros((1, 4), np.float32))
        red2 = hierarchy.StreamingAggregator(64, 1)
        red2.push_many(np.zeros((4, 6), np.float32))
        with pytest.raises(ValueError, match="expected"):
            red2.push_many(np.zeros((4, 5), np.float32))


# ---------------------------------------------------------------------------
# telemetry schema v12: per-phase attribution + selection micro-rows


class TestTelemetryV12:
    def test_fed_bench_phases_validate(self):
        exporters.validate_record(exporters.make_record(
            "fed_bench", check="scaling", n=10 ** 6, d=10 ** 4, shards=4,
            gar="hier-krum", round_s=1.0,
            phases={
                "ingest": {"count": 8, "p50_s": 0.01, "p95_s": 0.02},
                "h2d": {"count": 8, "p50_s": 0.001, "p95_s": 0.002},
                "fold": {"count": 8, "p50_s": 0.005, "p95_s": 0.009},
                "selection": {"count": 24, "p50_s": 3e-4, "p95_s": 9e-4},
            },
        ))

    @pytest.mark.parametrize("phases", [
        "ingest",                               # not an object
        {"ingest": [0.1, 0.2]},                 # stats not an object
        {"ingest": {"p50_s": "fast"}},          # non-numeric stat
    ])
    def test_malformed_fed_bench_phases_rejected(self, phases):
        with pytest.raises(ValueError, match="phases"):
            exporters.validate_record(exporters.make_record(
                "fed_bench", check="scaling", n=10, d=10, shards=1,
                gar="hier-krum", phases=phases,
            ))

    def test_gar_bench_selection_rows_validate(self):
        exporters.validate_record(exporters.make_record(
            "gar_bench", gar="krum", n=16, f=6, d=256, latency_s=6.7e-5,
            grid="selection", impl="sortnet", wave_buckets=8,
            per_bucket_s=8.3e-6, trials=3, dce_guard="softsign",
        ))
        for bad in [{"impl": 7}, {"wave_buckets": 0},
                    {"per_bucket_s": "x"}, {"grid": 1}]:
            with pytest.raises(ValueError):
                exporters.validate_record(exporters.make_record(
                    "gar_bench", gar="krum", n=16, f=6, d=256,
                    latency_s=1e-5, **bad,
                ))


# ---------------------------------------------------------------------------
# control plane: checkpointed failover / resume (DESIGN.md §22)


import json  # noqa: E402
import os  # noqa: E402

from garfield_tpu import controlplane as cp  # noqa: E402


class TestFailoverDeterminism:
    """The handoff contract, pinned at the trajectory level: a shard
    killed mid-round and promoted from its span checkpoint re-runs the
    interrupted round and lands on the SAME model bytes as a run that
    never died."""

    N, D, S = 16, 96, 2

    def _engine(self, tmp_path, sub):
        sampler = fed.CohortSampler(self.N, self.N, seed=11,
                                    byz_frac=0.05)
        model0 = np.random.default_rng(5).normal(
            size=self.D).astype(np.float32)
        return fed.FedRoundEngine(
            model0, self.S, sampler, lr=0.05, epoch=1,
            checkpoint_dir=str(tmp_path / sub),
        )

    def _rows(self, r):
        return np.random.default_rng([21, r]).normal(
            size=(self.N, self.D)).astype(np.float32)

    def test_kill_and_rerun_is_bitwise(self, tmp_path):
        ref = self._engine(tmp_path, "ref")
        for r in range(4):
            ref.begin_round()
            ref.ingest_rows(self._rows(r))
            ref.finish_round()

        eng = self._engine(tmp_path, "victim")
        for r in range(4):
            active, f = eng.begin_round()
            rows = self._rows(r)
            if r == 2:
                # The shard dies with half the cohort folded in. The
                # standby restores the round-1 span checkpoint and pins
                # itself to re-run round 2 — mid-round fold state is
                # deliberately NOT checkpointed (arrival order is
                # bucket assignment; a resumed half-fold would not be
                # the bytes a clean round produces).
                eng.ingest_rows(rows[: self.N // 2])
                srv, rerun = cp.promote_standby(eng, 1)
                assert rerun == 2 and eng.epoch == 2
                active, f = eng.begin_round()  # re-arm ALL shards
            eng.ingest_rows(rows)
            eng.finish_round()

        assert np.array_equal(eng.model, ref.model)  # bitwise
        # The failover bumped the epoch; the clean run never did.
        assert eng.epoch == 2 and ref.epoch == 1

    def test_resume_restores_bitwise_round_and_epoch(self, tmp_path):
        eng = self._engine(tmp_path, "a")
        eng.resize(1)  # one epoch bump (1 -> 2) recorded in control
        for r in range(3):
            eng.begin_round()
            eng.ingest_rows(self._rows(r))
            eng.finish_round()
        want = eng.model.copy()

        fresh = self._engine(tmp_path, "b")
        fresh.resize(1)
        with pytest.raises(FileNotFoundError, match="complete"):
            fresh.resume()  # its own dir is empty
        fresh._ckpt_dir = eng._ckpt_dir
        step = fresh.resume()
        assert step == 2 and fresh.round == 3
        assert np.array_equal(fresh.model, want)
        assert fresh.epoch == eng.epoch == 2
        # The resumed engine serves round 3 and stays on trajectory.
        fresh.begin_round()
        eng.begin_round()
        fresh.ingest_rows(self._rows(3))
        eng.ingest_rows(self._rows(3))
        fresh.finish_round()
        eng.finish_round()
        assert np.array_equal(fresh.model, eng.model)

    def test_restored_shard_refuses_unknown_round(self, tmp_path):
        """Satellite: after restore, the engine can only serve the
        round after its checkpoint — any other round is a LOUD refusal,
        not a silent fold against a stale span."""
        eng = self._engine(tmp_path, "a")
        for r in range(2):
            eng.begin_round()
            eng.ingest_rows(self._rows(r))
            eng.finish_round()
        eng2 = self._engine(tmp_path, "a")
        eng2.resume()
        eng2.round = 5  # a driver resuming at the wrong round
        with pytest.raises(RuntimeError, match="refusing loudly"):
            eng2.begin_round()
        with pytest.raises(RuntimeError, match="no span checkpoint"):
            eng2.shards[0].begin_round(0, self.N, 1)
        eng2.round = 2  # the one round the restored spans are valid for
        eng2.begin_round()

    def test_torn_checkpoint_never_restores_mixed_rounds(self, tmp_path):
        eng = self._engine(tmp_path, "a")
        for r in range(3):
            eng.begin_round()
            eng.ingest_rows(self._rows(r))
            eng.finish_round()
        # Tear step 2: the control record vanished (crash between the
        # span save and the control save).
        os.remove(os.path.join(eng._ckpt_dir, "control", "ctl_2.json"))
        eng2 = self._engine(tmp_path, "a")
        assert eng2.resume() == 1  # falls back to the newest COMPLETE
        with pytest.raises(FileNotFoundError, match="complete"):
            eng2.resume(step=2)
        # A control record disagreeing with its step key is torn too.
        path = os.path.join(eng._ckpt_dir, "control", "ctl_1.json")
        with open(path) as fp:
            rec = json.load(fp)
        rec["round"] = 7
        with open(path, "w") as fp:
            json.dump(rec, fp)
        with pytest.raises(ValueError, match="torn"):
            self._engine(tmp_path, "a").resume(step=1)


# ---------------------------------------------------------------------------
# bulk wire ingest (ShardServer.push_frames — ISSUE 20)


class TestShardBatchIngest:
    """push_frames is semantics-preserving bulk ingest: batch ==
    per-frame bitwise, arrival order never depends on the path taken
    (any multi-row or unreadable frame demotes the WHOLE call to the
    per-frame loop — bucket assignment IS arrival order), rejects are
    indexed ban evidence, and the call emits one v15 ``ingest_batch``
    event when a hub is installed."""

    def _servers(self, d=32, shards=2, shard=1, n=8, **kw):
        spec = fed.plan_shards(d, shards)
        sv = fed.ShardServer(shard, spec, bucket_gar="average", **kw)
        sv.begin_round(0, n, 0)
        return spec, sv

    def test_batch_bitwise_equals_per_frame(self):
        d, n = 32, 8
        rows, _ = honest_rows(n, d)
        spec, sv_b = self._servers(d=d, n=n)
        _, sv_s = self._servers(d=d, n=n)
        frames = [wire.encode(spec.slice_rows(r, 1), plane=1)
                  for r in rows]
        res = sv_b.push_frames(frames)
        assert res == list(range(n))
        for fr in frames:
            sv_s.push_frame(fr)
        assert np.array_equal(sv_b.finish_round(), sv_s.finish_round())
        assert sv_b.wire_bytes_in == sv_s.wire_bytes_in \
            == sum(len(f) for f in frames)

    def test_multi_row_frame_demotes_whole_call_preserving_order(self):
        d, n = 32, 6
        rows, _ = honest_rows(n, d)
        spec, sv_m = self._servers(d=d, n=n)
        _, sv_s = self._servers(d=d, n=n)
        # frame 2 carries TWO rows: the batch prescreen must fall back
        # for ALL frames, in list order, or bucket assignment would
        # depend on which path ran.
        frames = [
            wire.encode(spec.slice_rows(rows[0], 1), plane=1),
            wire.encode(spec.slice_rows(rows[1], 1), plane=1),
            wire.encode(spec.slice_rows(rows[2:4], 1).ravel(), plane=1),
            wire.encode(spec.slice_rows(rows[4], 1), plane=1),
            wire.encode(spec.slice_rows(rows[5], 1), plane=1),
        ]
        res = sv_m.push_frames(frames)
        assert res == [0, 1, 2, 4, 5]  # frame 2 ingests rows 2 AND 3
        assert sv_m.arrived() == n
        for fr in frames:
            sv_s.push_frame(fr)
        assert np.array_equal(sv_m.finish_round(), sv_s.finish_round())

    def test_rejects_are_indexed_ban_evidence(self):
        d, n = 32, 5
        rows, _ = honest_rows(n + 1, d)
        spec, sv = self._servers(d=d, n=n)
        frames = [wire.encode(spec.slice_rows(r, 1), plane=1)
                  for r in rows[:n]]
        bad = bytearray(frames[1])
        bad[-1] ^= 0xFF  # CRC break
        frames[1] = bytes(bad)
        # cross-shard stamp: header-level reject, still indexed
        frames[3] = wire.encode(spec.slice_rows(rows[n], 0), plane=0)
        res = sv.push_frames(frames)
        assert isinstance(res[1], wire.WireError)
        assert isinstance(res[3], wire.WireError)
        assert [r for i, r in enumerate(res) if i not in (1, 3)] \
            == [0, 1, 2]
        assert sv.arrived() == 3

    def test_ingest_batch_event_emitted_and_validates(self):
        d, n = 32, 4
        rows, _ = honest_rows(n, d)
        spec, sv = self._servers(d=d, n=n)
        frames = [wire.encode(spec.slice_rows(r, 1), plane=1)
                  for r in rows]
        bad = bytearray(frames[2])
        bad[-1] ^= 0xFF
        frames[2] = bytes(bad)
        h = tele_hub.MetricsHub()
        prev = tele_hub.install(h)
        try:
            sv.push_frames(frames)
        finally:
            tele_hub.uninstall()
            if prev is not None:
                tele_hub.install(prev)
        evs = [r for r in h.records()
               if r["kind"] == "event" and r.get("event") == "ingest_batch"]
        assert len(evs) == 1
        ev = evs[0]
        exporters.validate_record(ev)
        assert ev["shard"] == 1 and ev["frames"] == n
        assert ev["rejected"] == 1 and ev["batched"] is True
        assert ev["bytes"] == sum(
            len(f) for i, f in enumerate(frames) if i != 2)
        assert ev["step"] == 0
        stats = h.ingest_batch_stats()
        assert stats["calls"] == 1 and stats["rejected"] == 1
        assert stats["batched_s"] > 0.0 and stats["fallback_s"] == 0.0

    def test_wire_batch_transform_is_push_frames(self):
        d, n = 32, 3
        rows, _ = honest_rows(n, d)
        spec, sv = self._servers(d=d, n=n)
        items = [(5 + i, wire.encode(spec.slice_rows(r, 1), plane=1))
                 for i, r in enumerate(rows)]
        assert sv.wire_batch_transform(items) == [0, 1, 2]
        assert sv.arrived() == n

    def test_epoch_pin_applies_in_batch(self):
        d, n = 32, 4
        rows, _ = honest_rows(n, d)
        spec, sv = self._servers(d=d, n=n, epoch=3)
        frames = [wire.encode(spec.slice_rows(r, 1), plane=1, epoch=3)
                  for r in rows]
        frames[1] = wire.encode(
            spec.slice_rows(rows[1], 1), plane=1, epoch=2)  # stale
        res = sv.push_frames(frames)
        assert isinstance(res[1], wire.WireError)
        assert "epoch" in str(res[1])
        assert [r for i, r in enumerate(res) if i != 1] == [0, 1, 2]

"""Application entry points (L4 of SURVEY §1).

Each module mirrors one reference application's ``trainer.py`` CLI:

  - ``centralized``  — pytorch_impl/applications/Centralized/  (P16)
  - ``aggregathor``  — pytorch_impl/applications/Aggregathor/  (P17)
  - ``byzsgd``       — pytorch_impl/applications/ByzSGD/       (P18)
  - ``learn``        — pytorch_impl/applications/LEARN/        (P19)
  - ``garfield_cc``  — pytorch_impl/applications/Garfield_CC/  (P20)

Unlike the reference — where every node runs the same trainer.py and rank
selects the role branch (Aggregathor/trainer.py:217-268) — the SPMD design
has ONE process per host driving the whole mesh, so the CLIs keep the
reference's flags (--dataset/--batch/--num_workers/--fw/--gar/...,
trainer.py:62-135) but drop --master/--rank single-node plumbing; multi-host
runs instead initialize jax.distributed (garfield_tpu/utils/multihost.py).

Run as ``python -m garfield_tpu.apps.aggregathor --model resnet18 ...``.
"""

from . import common

__all__ = ["common"]

"""CNN model zoo (counterpart of pytorch_impl/libs/garfieldpp/models/ and the
torchvision entries in garfieldpp/tools.py:59-105).

All models are flax.linen modules with the signature
``model(x_nhwc, train: bool)`` and constructor kwargs ``num_classes`` and
``dtype`` (compute dtype; pass jnp.bfloat16 to route convs/matmuls to the
MXU in bf16 while parameters stay float32).

``select_model(name, dataset)`` mirrors the reference selector: the model
table (tools.py:66-88) and the dataset->num_classes map (tools.py:89).
Device placement and DataParallel wrapping (tools.py:102-103) have no
equivalent here — sharding is decided by the caller's mesh, not the model.
"""

import jax.numpy as jnp

from .densenet import DenseNet121, DenseNet161, DenseNet169, DenseNet201, densenet_cifar
from .dpn import DPN26, DPN92
from .efficientnet import EfficientNetB0
from .googlenet import GoogLeNet
from .lenet import LeNet
from .mobilenet import MobileNet
from .mobilenetv2 import MobileNetV2
from .nets import CNNet, Cifarnet, Net
from .pimanet import PimaNet
from .pnasnet import PNASNetA, PNASNetB
from .preact_resnet import PreActResNet18
from .regnet import RegNetX_200MF, RegNetX_400MF, RegNetY_400MF
from .resnet import ResNet18, ResNet34, ResNet50, ResNet101, ResNet152
from .resnext import ResNeXt29_2x64d, ResNeXt29_4x64d, ResNeXt29_8x64d, ResNeXt29_32x4d
from .senet import SENet18
from .shufflenet import ShuffleNetG2, ShuffleNetG3
from .shufflenetv2 import ShuffleNetV2
from .transformer import GPT, ViT
from .vgg import VGG11, VGG13, VGG16, VGG19

__all__ = ["models", "num_classes_dict", "select_model"]

# Name table mirroring garfieldpp/tools.py:66-88 (plus the extra family
# members the reference zoo defines but does not register by name).
models = {
    "convnet": Net,
    "cifarnet": Cifarnet,
    "cnn": CNNet,
    "lenet": LeNet,
    "resnet18": ResNet18,
    "resnet34": ResNet34,
    "resnet50": ResNet50,
    "resnet101": ResNet101,
    "resnet152": ResNet152,
    # tools.py:73 maps "inception" to torchvision inception_v3; CIFAR-scale
    # inputs use the Inception-v1 graph here (see googlenet.py docstring).
    "inception": GoogLeNet,
    "vgg11": VGG11,
    "vgg13": VGG13,
    "vgg16": VGG16,
    "vgg19": VGG19,
    "preactresnet18": PreActResNet18,
    "googlenet": GoogLeNet,
    "densenet121": DenseNet121,
    "densenet161": DenseNet161,
    "densenet169": DenseNet169,
    "densenet201": DenseNet201,
    "densenet_cifar": densenet_cifar,
    "resnext29": ResNeXt29_2x64d,
    "resnext29_4x64d": ResNeXt29_4x64d,
    "resnext29_8x64d": ResNeXt29_8x64d,
    "resnext29_32x4d": ResNeXt29_32x4d,
    "mobilenet": MobileNet,
    "mobilenetv2": MobileNetV2,
    "dpn26": DPN26,
    "dpn92": DPN92,
    "shufflenetg2": ShuffleNetG2,
    "shufflenetg3": ShuffleNetG3,
    "shufflenetv2": ShuffleNetV2,
    "senet18": SENet18,
    "efficientnetb0": EfficientNetB0,
    "regnetx200": RegNetX_200MF,
    "regnetx400": RegNetX_400MF,
    "regnety400": RegNetY_400MF,
    "pnasneta": PNASNetA,
    "pnasnetb": PNASNetB,
    "pimanet": PimaNet,
    # Transformer family (models/transformer.py): no reference-repo
    # counterpart — the first-mover slot-fused transformer workloads.
    # vit_tiny consumes NHWC images; gpt_tiny consumes int token batches
    # (the copytask sequence dataset).
    "vit_tiny": ViT,
    "gpt_tiny": GPT,
}

# tools.py:89 (+ the synthetic copytask sequence dataset, data/__init__.py)
num_classes_dict = {
    "cifar10": 10,
    "cifar100": 100,
    "mnist": 10,
    "imagenet": 1000,
    "pima": 1,
    "copytask": 10,
}


def select_model(model, dataset, *, dtype=jnp.float32):
    """Instantiate a model by name for a dataset (tools.py:59-105).

    Returns the flax module; initialize with
    ``variables = module.init(key, example_batch, train=False)``.
    """
    if dataset not in num_classes_dict:
        raise ValueError(
            f"The specified dataset is undefined, available datasets are: "
            f"{sorted(num_classes_dict)}"
        )
    if model not in models:
        raise ValueError(
            f"The specified model is undefined, available models are: "
            f"{sorted(models)}"
        )
    return models[model](num_classes=num_classes_dict[dataset], dtype=dtype)

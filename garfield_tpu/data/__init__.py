"""Deterministic dataset management for Byzantine-resilient SPMD training.

TPU-native counterpart of ``pytorch_impl/libs/garfieldpp/datasets.py`` and
``tensorflow_impl/libs/dataset.py``:

  - ``DataPartitioner`` reproduces the reference's seeded equal-split
    partitioning (datasets.py:121-150, seed 1234 at :124);
  - ``DatasetManager`` serves per-worker train partitions and the global test
    set (datasets.py:152-250), with the reference's "materialize the whole
    loader once" semantics (:243): batch *i* of a run is
    ``train_batches[i % num_batches]``, and any augmentation is sampled once
    at load time, exactly like ``[sample for sample in train_set]``;
  - ``sharded_train_batches`` is the TPU-first addition: the *stacked*
    ``(num_workers, num_batches, bsz, ...)`` array a shard_map program feeds
    from, so per-step batch selection is a static ``lax.dynamic_index`` and
    the host never loops over workers.

Data sources (zero-egress environment — nothing is downloaded):
  1. real files under ``$GARFIELD_TPU_DATA_DIR`` (default ``~/data``):
     MNIST idx/ubyte or ``mnist.npz``; ``cifar-10-batches-py`` pickles;
     ``pima_diabetes.csv``;
  2. otherwise a **deterministic synthetic surrogate** with the same shapes,
     dtypes, class counts and normalization statistics, generated from a
     fixed seed and built to be *learnable* (class-conditional means) so
     convergence tests remain meaningful. A warning is emitted once.
"""

import gzip
import os
import pathlib
import pickle
import struct
import zlib
from random import Random

import numpy as np

from ..utils import tools

__all__ = [
    "datasets_list",
    "Partition",
    "DataPartitioner",
    "DatasetManager",
]

# Reference list (datasets.py:47) + cifar100 (tensorflow_impl tfds names,
# tensorflow_impl/libs/dataset.py:41-87 accepts any tfds dataset) +
# copytask (the synthetic token-sequence task the transformer family
# trains on — no reference counterpart, synthetic BY CONSTRUCTION).
datasets_list = ["mnist", "cifar10", "cifar100", "pima", "copytask"]

# Reference normalization constants.
_MNIST_MEAN, _MNIST_STD = 0.1307, 0.3081  # datasets.py:186-187
_CIFAR_MEAN = np.array([0.485, 0.456, 0.406], np.float32)  # datasets.py:198
_CIFAR_STD = np.array([0.229, 0.224, 0.225], np.float32)

_warned_synthetic = set()


def data_dir():
    return pathlib.Path(
        os.environ.get("GARFIELD_TPU_DATA_DIR", str(pathlib.Path.home() / "data"))
    )


# --------------------------------------------------------------------------
# Raw dataset loading: (train_x, train_y), (test_x, test_y) as numpy arrays,
# NHWC float32 images already normalized, int32 labels (float32 (n,1) for
# the binary pima task, mirroring PimaDiabetesDataset targets).
# --------------------------------------------------------------------------


def _synthetic(name, num_classes, shape, n_train, n_test, binary=False):
    """Class-conditional Gaussian surrogate; deterministic and NON-trivial.

    VERDICT r2 #5: the original surrogate (means ~N(0,1) per dim, noise
    0.5) had class centers ~sqrt(2 d) apart — one-shot separable, accuracy
    saturates within a step or two, and every time-to-accuracy threshold
    collapses to the same step. This one overlaps the classes: unit-norm
    mean directions scaled to ``GARFIELD_SURROGATE_MARGIN`` (default 3.5,
    so pairwise center distance is margin*sqrt(2) REGARDLESS of input
    dimension, against unit per-dim noise -> Bayes ceiling ~0.95 for 10
    classes), plus ``GARFIELD_SURROGATE_LABEL_NOISE`` (default 2%) flipped
    labels on the TRAIN split only. A model must now average the signal
    over all input dims and ride out label noise — accuracy climbs over
    hundreds of SGD steps and t(acc>=0.5) << t(acc>=0.9), which is what
    the robust-aggregation TTA tables need (reference anchor: real
    CIFAR-10 runs, Aggregathor/run_exp.sh:5-14).
    """
    if name not in _warned_synthetic:
        tools.warning(
            f"dataset {name!r} not found under {data_dir()} — using the "
            "deterministic synthetic surrogate (same shapes/classes)"
        )
        _warned_synthetic.add(name)
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    dim = int(np.prod(shape))
    margin = float(os.environ.get("GARFIELD_SURROGATE_MARGIN", "3.5"))
    label_noise = float(
        os.environ.get("GARFIELD_SURROGATE_LABEL_NOISE", "0.02")
    )
    # Image-shaped tasks get SPATIALLY SMOOTH class means (a low-res
    # pattern upsampled to full resolution): a random per-pixel direction
    # is invisible to a convnet's translation-local inductive bias (probed:
    # accuracy pinned at chance), while low-frequency patterns are exactly
    # what conv stacks extract — like real image class structure.
    if len(shape) == 3:
        h, w, c = shape
        lo = rng.normal(
            0.0, 1.0,
            size=(num_classes, max(h // 4, 1), max(w // 4, 1), c),
        ).astype(np.float32)
        means = np.stack([
            np.repeat(
                np.repeat(m, -(-h // m.shape[0]), axis=0)[:h],
                -(-w // m.shape[1]), axis=1,
            )[:, :w]
            for m in lo
        ]).reshape(num_classes, dim)
    else:
        means = rng.normal(
            0.0, 1.0, size=(num_classes, dim)
        ).astype(np.float32)
    means *= margin / np.linalg.norm(means, axis=1, keepdims=True)

    def make(n, seed, train):
        r = np.random.default_rng(seed)
        y = r.integers(0, num_classes, size=n)
        x = means[y] + r.normal(size=(n, dim)).astype(np.float32)
        x = x.reshape((n,) + shape).astype(np.float32)
        if train and label_noise:
            flip = r.random(n) < label_noise
            y = np.where(
                flip, r.integers(0, num_classes, size=n), y
            )
        if binary:
            return x.reshape(n, -1), y.astype(np.float32).reshape(-1, 1)
        return x, y.astype(np.int32)

    return make(n_train, 1234, True), make(n_test, 4321, False)


def _load_mnist_files(root):
    """MNIST from idx-ubyte (possibly .gz) or mnist.npz under root."""
    npz = root / "mnist.npz"
    if npz.exists():
        with np.load(npz) as z:
            return (z["x_train"], z["y_train"]), (z["x_test"], z["y_test"])

    def read_idx(path):
        opener = gzip.open if path.suffix == ".gz" else open
        with opener(path, "rb") as fh:
            magic, = struct.unpack(">I", fh.read(4))
            ndim = magic & 0xFF
            dims = struct.unpack(">" + "I" * ndim, fh.read(4 * ndim))
            return np.frombuffer(fh.read(), dtype=np.uint8).reshape(dims)

    def find(stem):
        for cand in (root / "MNIST" / "raw", root):
            for suffix in ("", ".gz"):
                p = cand / (stem + suffix)
                if p.exists():
                    return read_idx(p)
        raise FileNotFoundError(stem)

    return (
        (find("train-images-idx3-ubyte"), find("train-labels-idx1-ubyte")),
        (find("t10k-images-idx3-ubyte"), find("t10k-labels-idx1-ubyte")),
    )


def load_mnist():
    try:
        (tx, ty), (vx, vy) = _load_mnist_files(data_dir())
    except (FileNotFoundError, OSError):
        return _synthetic("mnist", 10, (28, 28, 1), 60000, 10000)
    norm = lambda x: (
        (x.astype(np.float32) / 255.0 - _MNIST_MEAN) / _MNIST_STD
    ).reshape(-1, 28, 28, 1)
    return (norm(tx), ty.astype(np.int32)), (norm(vx), vy.astype(np.int32))


def _load_cifar_files(root, name):
    if name == "cifar10":
        d = root / "cifar-10-batches-py"
        train_files = [d / f"data_batch_{i}" for i in range(1, 6)]
        test_files = [d / "test_batch"]
        label_key = b"labels"
    else:
        d = root / "cifar-100-python"
        train_files, test_files = [d / "train"], [d / "test"]
        label_key = b"fine_labels"

    def load(files):
        xs, ys = [], []
        for f in files:
            with open(f, "rb") as fh:
                batch = pickle.load(fh, encoding="bytes")
            xs.append(batch[b"data"])
            ys.extend(batch[label_key])
        x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return x, np.asarray(ys, np.int32)

    return load(train_files), load(test_files)


def _augment_once(x, seed):
    """Random crop (pad 4) + horizontal flip, sampled once per sample at load
    time — matching the reference's materialize-once loader (datasets.py:197-
    201, :243)."""
    rng = np.random.default_rng(seed)
    n, h, w, c = x.shape
    padded = np.pad(x, ((0, 0), (4, 4), (4, 4), (0, 0)), mode="constant")
    ys = rng.integers(0, 9, size=n)
    xs = rng.integers(0, 9, size=n)
    flip = rng.random(n) < 0.5
    out = np.empty_like(x)
    for i in range(n):
        crop = padded[i, ys[i] : ys[i] + h, xs[i] : xs[i] + w]
        out[i] = crop[:, ::-1] if flip[i] else crop
    return out


def load_cifar(name="cifar10", augment_train=True):
    num_classes = 10 if name == "cifar10" else 100
    try:
        (tx, ty), (vx, vy) = _load_cifar_files(data_dir(), name)
    except (FileNotFoundError, OSError):
        return _synthetic(name, num_classes, (32, 32, 3), 50000, 10000)
    norm = lambda x: (x.astype(np.float32) / 255.0 - _CIFAR_MEAN) / _CIFAR_STD
    tx = norm(tx)
    if augment_train:
        tx = _augment_once(tx, seed=1234)
    return (tx, ty), (norm(vx), vy)


def load_pima(train_size=None):
    """Pima Indians Diabetes (datasets.py:52-94): 600 train / last 168 test,
    z-scored features computed on the served split, float32 (n,1) targets."""
    csv = data_dir() / "pima_diabetes.csv"
    if not csv.exists():
        (tx, ty), (vx, vy) = _synthetic(
            "pima", 2, (8,), 600, 168, binary=True
        )
        if train_size is not None:
            tx, ty = tx[:train_size], ty[:train_size]
        return (tx, ty), (vx, vy)
    raw = np.genfromtxt(csv, delimiter=",", skip_header=1, dtype=np.float64)

    def split(rows):
        data, targets = rows[:, :-1], rows[:, -1]
        data = data - data.mean(axis=0)
        data = data / data.std(axis=0, ddof=1)
        return data.astype(np.float32), targets.astype(np.float32).reshape(-1, 1)

    train_split = 600 if train_size is None else min(600, train_size)
    return split(raw[:train_split]), split(raw[-168:])


COPYTASK_SEQ = 16
COPYTASK_VOCAB = 32
COPYTASK_CLASSES = 10


def load_copytask(train_size=None):
    """Synthetic marked-copy sequence task (the transformer workload).

    Each sample is an int32 token sequence of length ``COPYTASK_SEQ``
    over a ``COPYTASK_VOCAB``-token vocabulary: distractor tokens
    everywhere except one MARKER token (the last vocab id) at a random
    position, immediately followed by a payload token in
    ``[0, COPYTASK_CLASSES)`` — the label. A model must ATTEND to the
    marked position to classify (payload ids never appear in distractor
    slots, but the marker's position is uniform, so no fixed-position
    readout works) — accuracy climbs over SGD steps instead of
    saturating at once, which the robust-aggregation TTA rows need
    (the same non-triviality contract as ``_synthetic``, VERDICT r2
    #5). Unlike the image surrogates this is not a stand-in for absent
    real files: the task is synthetic by construction (no network
    fetch, no warning). Train labels carry the standard
    ``GARFIELD_SURROGATE_LABEL_NOISE`` flips; seeds follow the
    ``_synthetic`` discipline (train 1234 / test 4321).
    """
    T, C = COPYTASK_SEQ, COPYTASK_CLASSES
    marker = COPYTASK_VOCAB - 1
    label_noise = float(
        os.environ.get("GARFIELD_SURROGATE_LABEL_NOISE", "0.02")
    )

    def make(n, seed, train):
        r = np.random.default_rng(seed)
        x = r.integers(C, marker, size=(n, T))
        pos = r.integers(0, T - 1, size=n)
        y = r.integers(0, C, size=n)
        x[np.arange(n), pos] = marker
        x[np.arange(n), pos + 1] = y
        if train and label_noise:
            flip = r.random(n) < label_noise
            y = np.where(flip, r.integers(0, C, size=n), y)
        return x.astype(np.int32), y.astype(np.int32)

    tx, ty = make(8192, 1234, True)
    if train_size is not None:
        tx, ty = tx[:train_size], ty[:train_size]
    return (tx, ty), make(2048, 4321, False)


def load_dataset(name, train_size=None):
    if name == "mnist":
        return load_mnist()
    if name in ("cifar10", "cifar100"):
        return load_cifar(name)
    if name == "pima":
        return load_pima(train_size)
    if name == "copytask":
        return load_copytask(train_size)
    raise ValueError(f"Existing datasets are: {datasets_list}")


# --------------------------------------------------------------------------
# Partitioning (datasets.py:97-150)
# --------------------------------------------------------------------------


class Partition:
    """Index-view over a dataset (datasets.py:97-118)."""

    def __init__(self, data, index):
        self.data = data
        self.index = np.asarray(index, dtype=np.int64)

    def __len__(self):
        return len(self.index)

    def __getitem__(self, i):
        return self.data[self.index[i]]

    def take(self, arrays):
        """Gather this partition's rows from each array in ``arrays``."""
        return tuple(a[self.index] for a in arrays)


class DataPartitioner:
    """Seeded equal-split partitioner, bit-compatible with the reference
    (datasets.py:121-150): a single ``random.Random(seed)`` stream shuffles
    each successive leading slice of the remaining indices, so partitions are
    disjoint and deterministic given (len, sizes, seed)."""

    def __init__(self, data_len, sizes, seed=1234):
        self.partitions = []
        rng = Random()
        rng.seed(seed)
        indexes = list(range(data_len))
        for frac in sizes:
            part_len = int(frac * data_len)
            tmp = indexes[0:part_len]
            rng.shuffle(tmp)
            self.partitions.append(tmp)
            indexes = indexes[part_len:]

    def use(self, partition):
        return np.asarray(self.partitions[partition], dtype=np.int64)


# --------------------------------------------------------------------------
# Manager (datasets.py:152-250)
# --------------------------------------------------------------------------


def _batchify(x, y, bsz):
    """Split into full batches, dropping the tail remainder like a DataLoader
    list comprehension would keep it — the reference keeps a ragged final
    batch; for XLA static shapes we drop it (documented deviation; at most
    bsz-1 samples per epoch)."""
    n = (len(x) // bsz) * bsz
    xb = x[:n].reshape((-1, bsz) + x.shape[1:])
    yb = y[:n].reshape((-1, bsz) + y.shape[1:])
    return xb, yb


class DatasetManager:
    """Per-node dataset view (datasets.py:152-250).

    ``rank`` / ``size`` / ``num_workers`` follow the reference convention:
    ranks [0, num_ps) are parameter servers, workers hold partition
    ``rank - num_ps`` (:232-243). ``minibatch`` is the per-worker batch size
    (the reference stores batch = minibatch*num_workers then divides back,
    :166, :235-236).
    """

    def __init__(self, dataset, minibatch, num_workers, size, rank, train_size=None):
        if dataset not in datasets_list:
            raise ValueError(f"Existing datasets are: {datasets_list}")
        self.dataset = dataset
        self.minibatch = int(minibatch)
        self.num_workers = int(num_workers)
        self.num_ps = int(size) - int(num_workers)
        self.rank = int(rank)
        self.train_size = train_size
        self._train = None
        self._test = None

    def _load(self):
        if self._train is None:
            self._train, self._test = load_dataset(self.dataset, self.train_size)
        return self._train, self._test

    def worker_index(self, rank=None):
        r = self.rank if rank is None else rank
        return r - self.num_ps

    def get_train_set(self, rank=None):
        """This worker's batches as (num_batches, bsz, ...) arrays; batch i of
        a training run is index ``i % num_batches`` (datasets.py:232-243)."""
        (tx, ty), _ = self._load()
        sizes = [1.0 / self.num_workers] * self.num_workers
        part = DataPartitioner(len(tx), sizes)
        idx = part.use(self.worker_index(rank))
        return _batchify(tx[idx], ty[idx], self.minibatch)

    def sharded_train_batches(self):
        """All workers' batch streams stacked: (W, B, bsz, ...) — the array a
        shard_map program shards over the "workers" mesh axis. TPU-first
        replacement for per-rank DataLoaders."""
        xs, ys = [], []
        for w in range(self.num_workers):
            xb, yb = self.get_train_set(rank=self.num_ps + w)
            xs.append(xb)
            ys.append(yb)
        nb = min(x.shape[0] for x in xs)
        return (
            np.stack([x[:nb] for x in xs]),
            np.stack([y[:nb] for y in ys]),
        )

    def get_test_set(self, batch=100):
        """Global test set, batched at 100 like the reference loader
        (datasets.py:245-250). Returns a list of (x, y) batches; the final
        batch may be smaller (the reference DataLoader keeps the ragged tail
        — dropping it would, e.g., discard 68 of pima's 168 test samples)."""
        _, (vx, vy) = self._load()
        return [
            (vx[i : i + batch], vy[i : i + batch])
            for i in range(0, len(vx), batch)
        ]

"""Epoch-numbered membership views: who owns which span, provably.

The deployment layer the paper era never needed (ROADMAP item 3,
DESIGN.md §22): FEDBENCH proved 10^6 clients/round with S shard
processes sharing one host and STATIC membership — no record of which
host:port serves which span, so a failover or a span split has nowhere
to publish the new truth and no way to invalidate the old one. This
module is that record: a ``MembershipView`` binds an **epoch number**
to the full shard→(host, port, span) assignment, serialized as a
length-checked, CRC-tagged binary record (``encode``/``decode`` — the
wire codec's loud-reject discipline applied to control metadata: every
malformation is a ``ViewError``, never a partial parse), small enough
to ride the existing host-agnostic exchange plane as an opaque payload
(``PeerExchange.publish`` on the control plane — the record is
transport-free bytes, exactly like a gradient frame).

Epochs are the control plane's replay armor. Every membership change —
failover promotion, span split, span merge — is EXACTLY one epoch
increment, and data-plane frames carry their sender's epoch in the wire
header (``utils.wire`` version-2 header, CRC-seeded). The two rules
compose into the handoff invariant DESIGN.md §22 pins:

- a ``MembershipDirectory`` accepts only strictly newer views
  (``install``): replaying a pre-failover view — the epoch-timed
  attacker's cheapest move, resurrecting a dead shard's claim to its
  span — is an attributable ``StaleViewError``;
- a shard serving epoch E rejects frames stamped with any other epoch
  (``wire.decode(expect_epoch=E)``): a client or peer still talking to
  the OLD membership cannot leak rows into the new one's folds.

What the view does NOT do: it is not consensus. One coordinator (the
engine driver / deployment controller) authors views; the directory
and the wire stamps make every consumer's acceptance decision local,
deterministic and attributable. Byzantine-fault-tolerant view AGREEMENT
is the paper's f_ps replication axis, orthogonal to this record format.
"""

import struct
import zlib

from ..federated import sharding
from ..utils import wire

__all__ = [
    "ViewError",
    "StaleViewError",
    "Seat",
    "MembershipView",
    "MembershipDirectory",
    "CONTROL_PLANE",
]

# Membership records ride exchange plane 0 — the pre-plane default every
# role already watches, so a view update needs no new register slots.
CONTROL_PLANE = 0

_MAGIC = b"GV"
_VERSION = 1
# Fixed header: magic, ver, num_seats u8, epoch u32, d u64, crc u32.
_VHDR = struct.Struct("!2sBBIQI")
# Per-seat record: shard u8, port u16, lo u64, hi u64, host_len u8.
_SEAT = struct.Struct("!BHQQB")
_MAX_HOST = 255  # host_len rides a u8 — a DNS name fits with room


class ViewError(ValueError):
    """A membership view record failed validation (bad magic/version,
    truncation, length lie, CRC failure, or a seat table that is not a
    partition). Attributable exactly like ``wire.WireError``: the CRC
    proves the bytes are the author's, so an invalid view is the
    author's fault, never the transport's."""


class StaleViewError(ViewError):
    """A view whose epoch does not advance the directory's — the replay
    of a superseded membership (or a duplicate of the current one).
    Separated from ``ViewError`` because the record itself is
    well-formed; what is Byzantine is WHEN it arrived."""


class Seat:
    """One shard assignment: shard id, owning host:port, column span."""

    __slots__ = ("shard", "host", "port", "lo", "hi")

    def __init__(self, shard, host, port, lo, hi):
        self.shard = sharding.shard_plane(shard)
        self.host = str(host)
        if len(self.host.encode()) > _MAX_HOST:
            raise ViewError(
                f"seat host {self.host[:32]!r}... is "
                f"{len(self.host.encode())} bytes — past the record's "
                f"u8 length field ({_MAX_HOST})"
            )
        self.port = int(port)
        if not 0 <= self.port <= 0xFFFF:
            raise ViewError(f"seat port {port} outside [0, 65535]")
        self.lo = int(lo)
        self.hi = int(hi)
        if not 0 <= self.lo < self.hi:
            raise ViewError(
                f"seat span [{lo}, {hi}) is empty or negative"
            )

    def __eq__(self, other):
        return isinstance(other, Seat) and (
            self.shard, self.host, self.port, self.lo, self.hi
        ) == (other.shard, other.host, other.port, other.lo, other.hi)

    def __repr__(self):
        return (f"<Seat shard={self.shard} {self.host}:{self.port} "
                f"span=[{self.lo},{self.hi})>")


class MembershipView:
    """One epoch's complete shard→seat assignment over a d-vector.

    Construction validates the GLOBAL invariants a consumer relies on
    (the per-seat ones live in ``Seat``): seats are keyed 0..S-1 with
    no gaps or duplicates, their spans tile [0, d) contiguously in
    shard order (the ``ShardSpec`` shape — a hole would orphan
    parameters, an overlap would double-fold them), and the epoch fits
    the wire header's u32 stamp so data frames can carry it.
    """

    __slots__ = ("epoch", "d", "seats")

    def __init__(self, epoch, d, seats):
        self.epoch = wire.check_epoch(epoch)
        self.d = int(d)
        if self.d < 1:
            raise ViewError(f"view d must be >= 1, got {d}")
        seats = tuple(seats)
        if not 1 <= len(seats) <= sharding.MAX_SHARDS:
            raise ViewError(
                f"view must seat 1..{sharding.MAX_SHARDS} shards "
                f"(the wire nibble), got {len(seats)}"
            )
        if [s.shard for s in seats] != list(range(len(seats))):
            raise ViewError(
                f"seats must be keyed 0..{len(seats) - 1} in order, got "
                f"{[s.shard for s in seats]}"
            )
        off = 0
        for s in seats:
            if s.lo != off:
                raise ViewError(
                    f"shard {s.shard} span starts at {s.lo}, expected "
                    f"{off} — spans must tile [0, d) contiguously"
                )
            off = s.hi
        if off != self.d:
            raise ViewError(
                f"seat spans cover [0, {off}) but the view claims "
                f"d={self.d}"
            )
        self.seats = seats

    @property
    def num_shards(self):
        return len(self.seats)

    def spec(self):
        """The view's spans as a ``ShardSpec`` when they match the
        canonical balanced partition (what ``plan_shards`` produces —
        every view this repo's coordinator authors), else ViewError:
        the engine's slicing assumes the balanced shape."""
        spec = sharding.plan_shards(self.d, self.num_shards)
        if tuple(spec.spans) != tuple((s.lo, s.hi) for s in self.seats):
            raise ViewError(
                "view spans are not the canonical balanced partition"
            )
        return spec

    # -- codec ---------------------------------------------------------------

    def encode(self):
        """Serialize to one length-checked, CRC-tagged record. The CRC
        covers the body (every seat) seeded with the epoch bytes —
        the same tamper-evidence construction as the wire codec's v2
        header, so a relay cannot restamp a view's epoch either."""
        body = bytearray()
        for s in self.seats:
            host = s.host.encode()
            body += _SEAT.pack(s.shard, s.port, s.lo, s.hi, len(host))
            body += host
        crc = zlib.crc32(bytes(body),
                         zlib.crc32(struct.pack("!I", self.epoch)))
        return _VHDR.pack(
            _MAGIC, _VERSION, len(self.seats), self.epoch, self.d, crc
        ) + bytes(body)

    @classmethod
    def decode(cls, buf):
        """Parse + validate one record; every malformation — truncation
        at any depth, a host-length lie, trailing bytes, CRC failure,
        or seat tables violating the partition invariants — raises
        ``ViewError`` before any view object exists."""
        buf = bytes(buf)
        if len(buf) < _VHDR.size:
            raise ViewError(
                f"truncated view record: {len(buf)} bytes is shorter "
                f"than the {_VHDR.size}-byte header"
            )
        magic, ver, n_seats, epoch, d, crc = _VHDR.unpack_from(buf)
        if magic != _MAGIC:
            raise ViewError(f"bad view magic {magic!r}")
        if ver != _VERSION:
            raise ViewError(f"unsupported view version {ver}")
        body = buf[_VHDR.size:]
        if zlib.crc32(body, zlib.crc32(struct.pack("!I", epoch))) != crc:
            raise ViewError("view body CRC mismatch")
        seats, off = [], 0
        for _ in range(n_seats):
            if off + _SEAT.size > len(body):
                raise ViewError(
                    f"truncated seat table: {len(body)} body bytes "
                    f"cannot hold seat {len(seats)}'s fixed fields"
                )
            shard, port, lo, hi, hlen = _SEAT.unpack_from(body, off)
            off += _SEAT.size
            if off + hlen > len(body):
                raise ViewError(
                    f"seat {len(seats)} claims a {hlen}-byte host but "
                    f"only {len(body) - off} body bytes remain"
                )
            try:
                host = body[off:off + hlen].decode()
            except UnicodeDecodeError as e:
                raise ViewError(f"seat {len(seats)} host is not UTF-8: {e}")
            off += hlen
            try:
                seats.append(Seat(shard, host, port, lo, hi))
            except (ViewError, TypeError, ValueError) as e:
                raise ViewError(f"seat {len(seats)} invalid: {e}")
        if off != len(body):
            raise ViewError(
                f"{len(body) - off} trailing bytes after the seat table"
            )
        try:
            return cls(epoch, d, seats)
        except (TypeError, ValueError) as e:
            # wire.check_epoch raises bare TypeError/ValueError — a
            # decoded record's failures must all be ViewError.
            if isinstance(e, ViewError):
                raise
            raise ViewError(str(e))

    @classmethod
    def for_engine(cls, engine, *, host="127.0.0.1", ports=None):
        """The canonical view of a ``FedRoundEngine``'s current
        membership: one seat per shard over its spec's spans, at the
        engine's epoch (0 when epoch enforcement is off — a view can
        describe a pre-epoch deployment, it just cannot protect it)."""
        spans = engine.spec.spans
        ports = list(ports) if ports is not None else [0] * len(spans)
        if len(ports) != len(spans):
            raise ViewError(
                f"{len(ports)} ports for {len(spans)} shards"
            )
        return cls(
            engine.epoch if engine.epoch is not None else 0,
            engine.spec.d,
            [Seat(s, host, ports[s], lo, hi)
             for s, (lo, hi) in enumerate(spans)],
        )

    def __eq__(self, other):
        return isinstance(other, MembershipView) and (
            self.epoch == other.epoch and self.d == other.d
            and self.seats == other.seats
        )

    def __repr__(self):
        return (f"<MembershipView epoch={self.epoch} d={self.d} "
                f"shards={self.num_shards}>")


class MembershipDirectory:
    """A consumer's local, monotone record of the current view.

    ``install`` accepts only strictly newer epochs — the replay ban:
    once the directory has seen epoch E, every view at epoch <= E is
    ``StaleViewError`` forever (the epoch-timed attacker cannot
    resurrect the membership that still listed its crashed shard).
    Rejections are counted and the last reason kept, mirroring the wire
    plane's ban-evidence accounting.
    """

    def __init__(self, view=None):
        self.view = None
        self.installs = 0
        self.rejects = 0
        self.last_reject = None
        if view is not None:
            self.install(view)

    @property
    def epoch(self):
        return None if self.view is None else self.view.epoch

    def install(self, view):
        """Adopt ``view`` iff it strictly advances the epoch; returns
        it. Raises ``StaleViewError`` (counted) otherwise."""
        if not isinstance(view, MembershipView):
            raise TypeError(
                f"expected a MembershipView, got {type(view).__name__}"
            )
        if self.view is not None and view.epoch <= self.view.epoch:
            self.rejects += 1
            self.last_reject = (
                f"view epoch {view.epoch} does not advance the "
                f"directory's epoch {self.view.epoch} — stale/replayed "
                "membership, attributable to its author"
            )
            raise StaleViewError(self.last_reject)
        self.view = view
        self.installs += 1
        return view

    def install_frame(self, buf):
        """Decode + install a serialized record (the exchange-plane
        arrival path). Malformed records raise ``ViewError`` WITHOUT
        counting as stale — they never carried an admissible epoch."""
        return self.install(MembershipView.decode(buf))

"""Averaging GAR (non-robust baseline).

Counterpart of pytorch_impl/libs/aggregators/average.py (:21-29 aggregate,
influence = accepted fraction).
"""

import jax.numpy as jnp

from . import register
from ._common import as_stack, num_gradients


def aggregate(gradients, **kwargs):
    """Arithmetic mean of the gradients."""
    return jnp.mean(as_stack(gradients), axis=0)


def check(gradients, **kwargs):
    if num_gradients(gradients) < 1:
        return f"expected at least one gradient to aggregate, got {gradients!r}"
    return None


def influence(honests, attacks, **kwargs):
    """Every gradient is accepted: ratio = |attacks| / n (average.py:29-37)."""
    return len(attacks) / (len(honests) + len(attacks))


register("average", aggregate, check, influence=influence)

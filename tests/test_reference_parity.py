"""Differential parity: our GARs vs the REFERENCE'S OWN torch implementations.

VERDICT r2 #4: ``tests/test_gars.py`` checks against hand-written numpy
oracles — a second implementation of the same spec by the same author. These
tests instead import ``/root/reference/pytorch_impl/libs/aggregators`` (torch
CPU is in the image) and assert ELEMENTWISE agreement in float64 across a
random (n, f, d) grid, for every rule whose reference implementation is
well-defined:

  krum (default m, m=1, mid m)  — krum.py:65-80
  median (incl. NaN rows)       — median.py:39
  average                       — average.py
  aksel ("mid" and "n-f")       — aksel.py:24-64
  brute                         — brute.py:31-50
  condense (fixed mask)         — condense.py:36-42, mask injected on both
                                  sides so the Bernoulli draw is identical
  bulyan PHASE 2 (avgmed)       — bulyan.py:77-84 torch composition

DOCUMENTED EXCLUSION — bulyan phase 1 (the selection loop): the reference's
incremental score update after pruning (bulyan.py:74-76) is provably dead
code — the guard ``if gid == gid_prune`` can never hold (each gid appears
once in ``scores`` and the pruned entry was just overwritten with
``(inf, None)``), and had it ever run, the body reads the UNDEFINED name
``distance`` (the dict is called ``distances``), i.e. a NameError. The
reference therefore executes "iterated selection on STALE round-0 scores",
while this repo implements the Bulyan paper's semantics (re-score the active
set each round — what the dead update was trying to approximate). The two
differ on essentially all random inputs (measured: 35/36 of a (n, f, d)
grid), so full-rule bulyan parity is intentionally not asserted; phase 2 is
asserted below, and phase 1 is covered by the independent oracle in
test_gars.py.
"""

import os
import sys

import numpy as np
import pytest

torch = pytest.importorskip("torch")

_REF_LIBS = "/root/reference/pytorch_impl/libs"


@pytest.fixture
def x64():
    """float64 scope for oracle tests that need no reference tree."""
    import jax

    jax.config.update("jax_enable_x64", True)
    try:
        yield
    finally:
        jax.config.update("jax_enable_x64", False)


@pytest.fixture(scope="module")
def env():
    """(reference gars, our gars), with float64 enabled for the module.

    Skips (rather than failing) when the reference tree is not mounted in
    the container — the torch-only and pure-numpy oracle tests below
    still run there. The reference package builds its native extensions
    on import; blocking ``import native`` (sys.modules[...] = None makes
    it raise ImportError) keeps the import fast and pure-torch — exactly
    the rules the reference itself falls back to without a CUDA
    toolchain.
    """
    import jax

    if not os.path.isdir(_REF_LIBS):
        pytest.skip("reference tree unavailable")
    jax.config.update("jax_enable_x64", True)
    sys.modules.setdefault("native", None)
    sys.path.insert(0, _REF_LIBS)
    try:
        import aggregators as ref_aggregators

        from garfield_tpu.aggregators import gars

        yield ref_aggregators.gars, gars
    finally:
        sys.path.remove(_REF_LIBS)
        jax.config.update("jax_enable_x64", False)


def _t(g):
    return [torch.tensor(row) for row in np.asarray(g)]


# (n, f) pairs valid for every rule under test (krum needs n >= 2f+3,
# brute n >= 2f+1, median/condense n >= 2f+2, aksel n >= 2f+1).
GRID = [(7, 1), (9, 2), (11, 3)]
DIMS = (5, 64, 301)


def _agree(got, want, rtol=1e-6, atol=1e-9):
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=rtol, atol=atol
    )


@pytest.mark.parametrize("n,f", GRID)
def test_krum_parity(env, n, f):
    ref, ours = env
    rng = np.random.default_rng(100 * n + f)
    for d in DIMS:
        g = rng.standard_normal((n, d))
        for m in (None, 1, max(1, (n - f - 2) // 2)):
            want = ref["krum"].unchecked(gradients=_t(g), f=f, m=m).numpy()
            got = ours["krum"].unchecked(g, f=f, m=m)
            _agree(got, want)


@pytest.mark.parametrize("n,f", GRID)
def test_median_parity(env, n, f):
    ref, ours = env
    rng = np.random.default_rng(200 * n + f)
    for d in DIMS:
        g = rng.standard_normal((n, d))
        _agree(
            ours["median"].unchecked(g, f=f),
            ref["median"].unchecked(gradients=_t(g), f=f).numpy(),
        )
    # NaN resilience: the reference DOCUMENTS "NaN-resilient median"
    # (median.py docstring) but modern torch's ``median(dim=0)`` PROPAGATES
    # NaN (the doc described the old sort-based lowering, where NaN sorts
    # last — torch.sort still does). Our median keeps the documented
    # semantics, so the oracle here is torch's sort-based lower median,
    # not the propagating ``median(dim=0)`` call.
    g = rng.standard_normal((n, 33))
    g[:f] = np.nan
    want = torch.stack(_t(g)).sort(dim=0).values[(n - 1) // 2].numpy()
    assert np.isfinite(want).all()
    _agree(ours["median"].unchecked(g, f=f), want)


@pytest.mark.parametrize("n,f", GRID)
def test_average_parity(env, n, f):
    ref, ours = env
    rng = np.random.default_rng(300 * n + f)
    g = rng.standard_normal((n, 129))
    _agree(
        ours["average"].unchecked(g, f=f),
        ref["average"].unchecked(gradients=_t(g), f=f).numpy(),
    )


@pytest.mark.parametrize("mode", ["mid", "n-f"])
@pytest.mark.parametrize("n,f", GRID)
def test_aksel_parity(env, n, f, mode):
    ref, ours = env
    rng = np.random.default_rng(400 * n + f)
    for d in DIMS:
        g = rng.standard_normal((n, d))
        want = ref["aksel"].unchecked(gradients=_t(g), f=f, mode=mode)
        _agree(ours["aksel"].unchecked(g, f=f, mode=mode), want.numpy())


@pytest.mark.parametrize("n,f", [(5, 1), (7, 2), (9, 3)])
def test_brute_parity(env, n, f):
    # Small n: the reference enumerates C(n, n-f) subsets in Python.
    ref, ours = env
    rng = np.random.default_rng(500 * n + f)
    for d in (5, 64):
        g = rng.standard_normal((n, d))
        _agree(
            ours["brute"].unchecked(g, f=f),
            ref["brute"].unchecked(gradients=_t(g), f=f).numpy(),
        )


@pytest.mark.parametrize("n,f", GRID)
def test_krum_nonfinite_row_parity(env, n, f):
    """A Byzantine row of NaN/Inf poisons its distances to +inf on both
    sides (krum.py:44-48) and must never be selected."""
    ref, ours = env
    rng = np.random.default_rng(600 * n + f)
    g = rng.standard_normal((n, 65))
    g[0, 0] = np.nan
    g[1, -1] = np.inf if f >= 2 else g[1, -1]
    _agree(
        ours["krum"].unchecked(g, f=f),
        ref["krum"].unchecked(gradients=_t(g), f=f).numpy(),
    )


@pytest.mark.parametrize("n,f", GRID)
def test_condense_parity_fixed_mask(env, n, f, monkeypatch):
    """condense.py:36-42 with the Bernoulli mask pinned identically on both
    sides (the reference draws from the torch global RNG, ours from an
    explicit jax key — inject the same mask into both)."""
    import jax.numpy as jnp

    ref, ours = env
    rng = np.random.default_rng(700 * n + f)
    d = 129
    g = rng.standard_normal((n, d))
    mask = rng.integers(0, 2, d).astype(np.float64)

    monkeypatch.setattr(
        torch.distributions.bernoulli.Bernoulli,
        "sample",
        # Fresh tensor per call: the reference mutates the sample in place
        # (c.neg_().add_(1), condense.py:40).
        lambda self, *a, **k: torch.tensor(mask.copy()),
    )
    import jax

    monkeypatch.setattr(
        jax.random, "bernoulli", lambda key, p, shape: jnp.asarray(mask > 0)
    )
    want = ref["condense"].unchecked(gradients=_t(g), f=f, p=0.5).numpy()
    got = ours["condense"].unchecked(g, f=f, p=0.5)
    _agree(got, want)


@pytest.mark.parametrize("s,f", [(5, 1), (9, 2), (13, 3)])
def test_bulyan_phase2_parity(x64, s, f):
    """Coordinate-wise averaged median vs the reference's own torch
    composition (bulyan.py:77-84: median -> abs deviation -> topk smallest
    -> take -> mean), on non-tie random inputs (topk's order among exactly
    equal deviations is unspecified; random doubles never tie). Needs
    torch but not the reference tree (the composition is transcribed
    above), so it runs in reference-less containers too."""
    from garfield_tpu import ops

    rng = np.random.default_rng(800 * s + f)
    beta = s - 2 * f
    for d in DIMS:
        sel = rng.standard_normal((s, d))
        t = torch.tensor(sel)
        median = t.median(dim=0).values
        closest = (
            t.clone().sub_(median).abs_()
            .topk(beta, dim=0, largest=False, sorted=False).indices
        )
        closest.mul_(d).add_(torch.arange(0, d, dtype=closest.dtype))
        want = t.take(closest).mean(dim=0).numpy()
        _agree(ops.averaged_median_mean(sel, beta), want)


# ---------------------------------------------------------------------------
# Bulyan phase 1, SECOND oracle (VERDICT r5 #6): the paper's selection loop
# transcribed brute-force for tiny n, independent of both the
# implementation (Gram matmuls, fori_loop weight matrices) and the
# author's first numpy oracle in test_gars.py (which mirrors the
# reference code's m_i = min(m, m_max - i) loop structure line by line).
# ---------------------------------------------------------------------------

def _bulyan_paper_oracle(g, f, m=None):
    """Bulyan (El Mhamdi, Guerraoui & Rouault, ICML 2018), Algorithm 1.

    Phase 1 — iterated selection: run the inner rule A on the ACTIVE set,
    append A's output to the selection set S, remove A's top choice from
    the active set; repeat until |S| = theta = n - 2f - 2. A here is the
    reference lineage's Multi-Krum: node i scored by the sum of its q
    EUCLIDEAN distances to its q closest active peers, with q = the
    paper's Krum neighbourhood on the current active set (|active|-f-2)
    capped at the Multi-Krum width m; A outputs the mean of the q
    best-scored gradients. (Documented deviations from the paper
    inherited FROM THE REFERENCE, differentially verified for krum in
    this file: Euclidean rather than squared distances, selection-width
    scoring, Multi-Krum emission. m=1 recovers the paper's single-Krum
    emission exactly.)

    Phase 2 — coordinate-wise: B[c] = mean of the beta = theta - 2f
    values of S[:, c] closest to the (lower) median (ties impossible on
    random doubles).

    Everything is recomputed from scratch each round with explicit loops
    over the active set — no distance matrix reuse, no incremental score
    updates (the reference's incremental update is the proven-dead buggy
    path this repo's re-derivation removed; see the module docstring).
    """
    g = np.asarray(g, np.float64)
    n, d = g.shape
    if m is None:
        m = n - f - 2
    theta = n - 2 * f - 2
    active = list(range(n))
    selected = []
    for _ in range(theta):
        q = min(m, len(active) - f - 2)
        scores = []
        for i in active:
            dists = sorted(
                float(np.linalg.norm(g[i] - g[j]))
                for j in active if j != i
            )
            scores.append(sum(dists[:q]))
        order = np.argsort(np.asarray(scores), kind="stable")
        best = [active[k] for k in order[:q]]
        selected.append(g[best].mean(axis=0))
        active.remove(active[order[0]])
    sel = np.stack(selected)  # (theta, d)
    beta = theta - 2 * f
    out = np.empty(d)
    for c in range(d):
        col = sel[:, c]
        med = np.sort(col)[(theta - 1) // 2]
        closest = np.argsort(np.abs(col - med), kind="stable")[:beta]
        out[c] = col[closest].mean()
    return out


# n <= 13 (brute force is O(rounds * n^2 * d) python loops), n >= 4f+3.
@pytest.mark.parametrize("n,f", [(7, 1), (8, 1), (11, 2), (13, 2)])
@pytest.mark.parametrize("m", [None, 1])
def test_bulyan_phase1_second_oracle(x64, n, f, m):
    """Full-rule Bulyan vs the paper-transcribed brute force across the
    (n, f, d) grid, for the default Multi-Krum width and the paper's
    m=1 single-selection emission."""
    from garfield_tpu.aggregators import gars

    rng = np.random.default_rng(900 * n + 10 * f + (m or 0))
    for d in (5, 33, 129):
        g = rng.standard_normal((n, d))
        want = _bulyan_paper_oracle(g, f, m=m)
        got = gars["bulyan"].unchecked(g, f=f, m=m)
        _agree(got, want)

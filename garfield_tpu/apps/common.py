"""Shared CLI scaffolding and training-loop driver for all applications.

Flag surface mirrors the reference trainers (Aggregathor/trainer.py:62-135):
--dataset/--batch/--num_workers/--num_ps/--fw/--fps/--model/--loss/
--optimizer/--opt_args (JSON)/--num_iter/--gar/--acc_freq/--bench/--log, plus
the knobs that were hard-coded or implicit there: --attack (byzWorker.py
attack table), --subset (the wait-n-f async path, server.py:134-155),
--granularity (Garfield_CC per-layer mode), --seed (torch.manual_seed(1234),
trainer.py:210), --lr_decay*/--lr_decay_epochs (the x0.2/30-epoch hack,
trainer.py:227-229), and new-capability flags: --checkpoint_dir/--resume
(SURVEY §5: checkpointing is our deliberate upgrade), --profile_dir
(jax.profiler), --mesh (device-axis layout).
"""

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import data as data_lib, models as models_lib, parallel
from ..utils import checkpoint as ckpt_lib, profiling, selectors, tools

__all__ = ["base_parser", "build_ingredients", "chunk_length",
           "peak_rss_bytes", "train"]


def peak_rss_bytes():
    """Process high-water RSS in bytes (``getrusage``) — the shared
    flat-memory accounting every committed bench row carries
    (HIERBENCH/EXCHBENCH/FEDBENCH; one definition so the artifacts stay
    comparable). Monotone: record rows in ascending-size order so an
    O(1)-memory claim reads as a flat profile."""
    import resource

    return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024


def base_parser(description, *, default_model="convnet", default_loss="nll"):
    p = argparse.ArgumentParser(
        description=description, formatter_class=argparse.RawTextHelpFormatter
    )
    a = p.add_argument
    a("--dataset", type=str, default="mnist",
      help="Dataset to be used, e.g., mnist, cifar10, cifar100, pima.")
    a("--batch", type=int, default=32,
      help="Minibatch size employed by each worker.")
    a("--num_workers", type=int, default=1, help="Number of workers.")
    a("--num_ps", type=int, default=1, help="Number of parameter servers.")
    a("--fw", type=int, default=0, help="Declared Byzantine workers.")
    a("--fps", type=int, default=0, help="Declared Byzantine servers.")
    a("--model", type=str, default=default_model,
      help="Model name, e.g., convnet, cifarnet, resnet18, vgg16, ...")
    a("--loss", type=str, default=default_loss,
      help="Loss: nll, cross-entropy, bce.")
    a("--optimizer", type=str, default="sgd",
      help="Optimizer: sgd, adam, adamw, rmsprop, adagrad.")
    a("--opt_args", type=json.loads, default={"lr": "0.1"},
      help='Optimizer args as JSON, e.g., \'{"lr":"0.1","momentum":"0.9"}\'')
    a("--num_iter", type=int, default=5000, help="Training iterations.")
    a("--gar", type=str, default="average", help="Gradient aggregation rule.")
    a("--acc_freq", type=int, default=100,
      help="Iterations between accuracy evaluations.")
    a("--bench", action="store_true",
      help="Print per-step time and derived collective bandwidth.")
    a("--log", action="store_true", help="Print loss every iteration.")
    # --- knobs hard-coded in the reference ---
    a("--attack", type=str, default=None,
      help="Byzantine gradient attack: random, reverse, drop, lie, empire, "
           "crash — or an ADAPTIVE controller (DESIGN.md §16): "
           "adaptive-lie, adaptive-empire (magnitude bisected against the "
           "rule's selection feedback, cohort rotation over an f_pool > fw "
           "colluder pool, full-magnitude bursts in quorum-degradation "
           "windows) — or a TARGETED data poisoner (DESIGN.md §17): "
           "labelflip (the cohort relabels source-class samples as the "
           "target class), backdoor (pixel-trigger stamp + target label); "
           "targeted success is measured per class (ASR, schema v8), not "
           "as divergence.")
    a("--attack_params", type=json.loads, default={},
      help="Attack parameters as JSON (e.g. lie z, empire eps; adaptive "
           'controller knobs: {"f_pool": 4, "rotation": 8, "mag_max": 6.0, '
           '"burst": 6.0}; targeted knobs: {"source": 0, "target": 1, '
           '"poison_frac": 1.0, "trigger_size": 2, "trigger_value": 2.5}).')
    a("--defense", type=str, default=None,
      choices=["none", "weighted", "escalate", "data", "weighted+data",
               "escalate+data"],
      help="Closed-loop defense (aggregators/defense.py, DESIGN.md §16/"
           "§18): 'weighted' scales each rank's rows by its (decayed) "
           "suspicion before the GAR; 'escalate' adds the rule ladder "
           "(krum -> multi-krum -> bulyan) driven by suspicion "
           "concentration, with hysteresis; 'data' adds the DATA-plane "
           "detectors (aggregators/dataplane.py: per-class classifier-"
           "head gradient fingerprints, spectral filtering + 2-means "
           "cohort clustering — the only plane that sees a backdoor/"
           "labelflip cohort, whose gradients are divergence-invisible); "
           "'weighted+data'/'escalate+data' run the GAR-side and "
           "data-plane defenses simultaneously. Off (default): the "
           "vanilla rule — trajectories bitwise unchanged.")
    a("--defense_params", type=json.loads, default={},
      help="Defense knobs as JSON: power/floor (the suspicion-weight "
           "law), halflife (suspicion EMA, steps), theta_up/theta_down/"
           "patience/clean_window/levels (the escalation hysteresis), "
           "dp_tau/dp_power/dp_floor/dp_halflife (the data-plane "
           "spectral tail threshold + its own weight law and EMA).")
    a("--suspicion_halflife", type=float, default=None,
      help="Exponential halflife (in observed steps) of the telemetry "
           "hub's WINDOWED suspicion score (schema v7): the decayed "
           "score a rotated Byzantine cohort cannot launder by sitting "
           "honest while its cumulative denominator grows. Default: env "
           "GARFIELD_SUSPICION_HALFLIFE, else cumulative-only.")
    a("--subset", type=int, default=None,
      help="Async wait-for-q emulation: aggregate a random q-subset "
           "of worker gradients each step (server.py:134-155).")
    a("--async", dest="async_agg", action="store_true",
      help="Bounded-staleness asynchronous aggregation (DESIGN.md §14): "
           "the PS aggregates the freshest n-fw arrivals with staleness-"
           "discounted weights and reuses admissible stale frames instead "
           "of blocking on stragglers; workers publish-and-continue. In "
           "--cluster mode this is the real host-plane protocol (SSMW/"
           "MSMW); on-mesh it is the seeded in-graph emulation "
           "(aggregathor topology). Off: round-synchronous (default).")
    a("--max_staleness", type=int, default=None,
      help="Hard staleness cutoff for --async, in rounds: a gradient "
           "tagged more than this many rounds behind the PS is excluded "
           "(weight 0). 0 = synchronous semantics (exact-round frames "
           "only, bitwise-equal trajectory). Default: env "
           "GARFIELD_MAX_STALENESS, else 4.")
    a("--staleness_decay", type=float, default=None,
      help="Per-round geometric discount for --async: a gradient tau "
           "rounds stale enters the GAR scaled by decay**tau. Default: "
           "env GARFIELD_STALENESS_DECAY, else 0.5.")
    a("--autoscale", action="store_true",
      help="Load-driven worker autoscaling (DESIGN.md §15; --cluster PS "
           "role, requires --async): the PS watches its round rate and "
           "quorum margin and SPAWNS worker processes (reserve ranks "
           "from the cluster config's worker list, launched with this "
           "process's own CLI re-targeted at worker:K) or RETIRES them "
           "(clean stop sentinel + watcher teardown; a later spawn "
           "rejoins through read_latest and re-reads its shard) so the "
           "deployment tracks --target_rate instead of a fixed n. With "
           "--autoscale the PS launches its own initial workers — do "
           "not start worker processes externally.")
    a("--target_rate", type=float, default=0.0,
      help="Autoscale throughput target in rounds/s; <= 0 (default) "
           "auto-calibrates to the first measurement window's rate, so "
           "the initial deployment's service level is held through load "
           "spikes.")
    a("--autoscale_min", type=int, default=1,
      help="Fewest active workers the autoscaler may retire down to "
           "(must keep the GAR feasible at q = min - fw).")
    a("--autoscale_max", type=int, default=0,
      help="Most workers the autoscaler may spawn; 0 (default) = every "
           "worker slot in the cluster config.")
    a("--autoscale_window", type=int, default=8,
      help="Rounds per autoscale measurement window.")
    a("--autoscale_cooldown", type=int, default=8,
      help="Rounds between consecutive autoscale actions (the new "
           "membership's steady state is measured, not the transient).")
    a("--straggler_ms", type=int, default=0,
      help="Scenario-injection knob (the straggler half of the async "
           "harness, exchange_bench --scenario): in cluster mode THIS "
           "worker sleeps the given milliseconds after each gradient "
           "compute before publishing — a reproducible 'slow rank'. "
           "0 (default) disables; ignored on-mesh and on PS roles.")
    a("--granularity", type=str, default="model", choices=["model", "layer"],
      help="GAR over the whole flat gradient or per parameter tensor "
           "(Garfield_CC semantics).")
    a("--seed", type=int, default=1234, help="Base PRNG seed.")
    a("--lr_decay", type=float, default=0.2,
      help="LR decay factor applied every --lr_decay_epochs epochs.")
    a("--lr_decay_epochs", type=int, default=0,
      help="Epoch interval for LR step decay (reference uses 30 for "
           "CIFAR-10; 0 disables).")
    a("--train_size", type=int, default=None,
      help="Optional cap on training-set size (debug/smoke).")
    a("--dtype", type=str, default="float32",
      choices=["float32", "bfloat16"],
      help="Model compute dtype (bfloat16 routes matmuls to the MXU).")
    a("--gar_dtype", type=str, default=None,
      choices=["float32", "bfloat16"],
      help="Aggregation-pipeline dtype: bfloat16 halves the HBM traffic of "
           "the attack+gather+GAR phase (Gram still accumulates in f32); "
           "default: full width.")
    a("--gar_params", type=json.loads, default={},
      help='Rule hyperparameters as JSON passed through to the GAR, e.g. '
           '\'{"tau": 10.0}\' (cclip) or \'{"p": 0.5}\' (condense).')
    a("--worker_momentum", type=float, default=None,
      help="Worker-momentum beta in [0, 1): workers submit EMA momenta "
           "instead of raw gradients (Karimireddy et al. 2021) — pairs "
           "with --gar cclip to survive the lie attack that defeats "
           "krum/bulyan (BASELINE.md TTA grid). Use a PLAIN-SGD server "
           "with it (omit momentum from --opt_args and raise lr ~x1/"
           "(1-beta)): the worker EMA is the momentum; stacking it on a "
           "momentum server destabilizes training. Default: off.")
    a("--fault_crashes", type=json.loads, default=None,
      help='Host crash schedule as JSON {"host": step, ...}: from the given '
           "step on, that simulated host's worker slots feed zero gradients "
           "(crash attack) and count against the Byzantine budget — the "
           "host-level fault simulation of utils/multihost.FaultSchedule "
           "(the reference's mar='crash', Garfield_CC/trainer.py:97,137).")
    a("--fault_hosts", type=int, default=None,
      help="Number of simulated hosts the worker slots fold onto for "
           "--fault_crashes (default: one host per worker slot).")
    # --- new capabilities (absent in the reference) ---
    a("--chunk_steps", type=int, default=None,
      help="On-device step chunking (docs/DESIGN.md §12): lax.scan K "
           "training steps inside ONE jitted dispatch "
           "(parallel/core.make_chunked_step), so K-1 of every K host "
           "dispatches disappear and XLA overlaps step i's optimizer/GAR "
           "tail with step i+1's forward. Chunks auto-clip at every loop "
           "boundary (eval points, checkpoint saves, crash-schedule "
           "re-jits, the profiled step, end of run), and trajectories are "
           "bitwise equal to per-step execution. Default: env "
           "GARFIELD_CHUNK_STEPS, else 1 (per-step).")
    a("--telemetry", type=str, nargs="?", const="telemetry", default=None,
      metavar="DIR",
      help="Enable the telemetry plane (docs/TELEMETRY.md): in-graph GAR "
           "audit taps (per-rank selection masks/scores; cclip tau + clip "
           "fraction), host-side aggregation with per-rank SUSPICION "
           "scores (cumulative exclusion frequency under the active GAR), "
           "and exporters — schema-versioned JSONL (DIR/telemetry.jsonl) "
           "plus a Prometheus text snapshot (DIR/metrics.prom). DIR "
           "defaults to ./telemetry. Costs one host sync + one extra "
           "selection pass per step; disabled (the default) it traces "
           "nothing and the trajectory is bitwise identical.")
    a("--trace", action="store_true",
      help="Distributed round tracing (docs/TELEMETRY.md §4): record "
           "host-side SPANS for every phase of a round (broadcast, "
           "quorum wait, waiter-thread wire decode + H2D, GAR compute, "
           "apply, eval, checkpoint, ...) as schema-v5 records in the "
           "telemetry JSONL. Implies --telemetry (spans need the sink); "
           "host-only, so trajectories stay bitwise identical. Merge a "
           "cluster run's per-role streams into a Chrome trace + run "
           "report with `python -m garfield_tpu.telemetry.report DIR`. "
           "Env twin: GARFIELD_TRACE=1.")
    a("--checkpoint_dir", type=str, default=None,
      help="Directory for orbax checkpoints (reference has none).")
    a("--checkpoint_freq", type=int, default=1000,
      help="Iterations between checkpoints.")
    a("--resume", action="store_true",
      help="Resume from the latest checkpoint in --checkpoint_dir.")
    a("--profile_dir", type=str, default=None,
      help="Write a jax.profiler trace of the steady-state steps here.")
    a("--sync_eval", action="store_true",
      help="Run periodic accuracy inline (blocking) instead of overlapped "
           "with training in a side thread (the reference's accuracy "
           "thread, Aggregathor/trainer.py:251-264, is the default).")
    a("--mesh", type=str, default=None,
      help='Mesh axis layout, e.g. "workers=8" or "ps=2,workers=4"; '
           "default: all devices on the topology's main axis.")
    return p


def resolve_suspicion_halflife(args):
    """--suspicion_halflife with its GARFIELD_SUSPICION_HALFLIFE env twin
    (the fleet-wide switch convention of utils/rounds.resolve)."""
    hl = getattr(args, "suspicion_halflife", None)
    if hl is None:
        env = os.environ.get("GARFIELD_SUSPICION_HALFLIFE", "").strip()
        hl = float(env) if env else None
    return hl


def parse_mesh(spec):
    """'ps=2,workers=-1' -> Mesh. Fixed-size specs smaller than the device
    count use the first prod(sizes) devices (a run may occupy a sub-slice of
    the chips, like the reference running fewer ranks than hosts)."""
    if not spec:
        return None
    axes = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        axes[name.strip()] = int(size)
    devices = None
    sizes = list(axes.values())
    if -1 not in sizes:
        import math

        total = math.prod(sizes)
        devices = jax.devices()[:total]
    return parallel.mesh.make_mesh(axes, devices=devices)


def _coerce_opt_args(opt_args):
    """Reference CLIs pass numbers as strings ('{"lr":"0.2"}'); coerce."""
    out = {}
    for k, v in opt_args.items():
        try:
            out[k] = float(v)
        except (TypeError, ValueError):
            out[k] = v
    return out


def build_ingredients(args, iters_per_epoch=None):
    """(module, loss_fn, optimizer) from the CLI flags — the selector layer
    (garfieldpp/tools.py:47-123) applied exactly as the trainers do."""
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    if args.loss == "bce" and models_lib.num_classes_dict.get(args.dataset) != 1:
        raise SystemExit(
            f"--loss bce expects a binary dataset (pima), got "
            f"{args.dataset!r}; use --loss nll or cross-entropy."
        )
    module = models_lib.select_model(args.model, args.dataset, dtype=dtype)
    loss_fn = selectors.select_loss(args.loss)
    opt_args = _coerce_opt_args(dict(args.opt_args))
    lr = opt_args.pop("lr", 0.1)
    if args.lr_decay_epochs and iters_per_epoch:
        lr = selectors.adjust_learning_rate(
            lr, decay=args.lr_decay,
            every_epochs=args.lr_decay_epochs,
            iters_per_epoch=iters_per_epoch,
        )
    optimizer = selectors.select_optimizer(
        args.optimizer, lr=lr,
        momentum=opt_args.pop("momentum", 0.0),
        weight_decay=opt_args.pop("weight_decay", 0.0),
        **opt_args,
    )
    return module, loss_fn, optimizer


def load_data(args, num_slots):
    """Stacked per-slot batch streams + test set.

    ``num_slots`` is the leading axis the topology shards (workers for
    aggregathor/byzsgd, nodes for learn). Returns
    (xs, ys, test_batches, iters_per_epoch) with xs: (S, B, bsz, ...).
    """
    manager = data_lib.DatasetManager(
        args.dataset, args.batch, num_slots, num_slots, 0,
        train_size=args.train_size,
    )
    manager.num_ps = 0  # slots are pure data partitions here
    xs, ys = manager.sharded_train_batches()
    test = manager.get_test_set()
    return xs, ys, test, xs.shape[1]


def _crash_schedule(args, num_slots, declared_f):
    """Validated FaultSchedule from --fault_crashes, or None.

    Fails fast (before any data/model work) on: combination with --attack,
    host layouts that leave slots unattached or hosts empty, out-of-range
    host ids, and crash counts exceeding the declared Byzantine budget —
    each of which would otherwise make the experiment silently wrong.
    """
    crashes = getattr(args, "fault_crashes", None)
    if not crashes:
        return None
    if getattr(args, "attack", None) or getattr(args, "model_attack", None):
        raise SystemExit(
            "--fault_crashes simulates crashed slots as zero-gradient "
            "(crash-attack) rows and cannot be combined with "
            "--attack/--model_attack; run the attack and the crash scenario "
            "separately."
        )
    num_hosts = getattr(args, "fault_hosts", None)
    num_hosts = num_slots if num_hosts is None else num_hosts
    if not (1 <= num_hosts <= num_slots) or num_slots % num_hosts:
        raise SystemExit(
            f"--fault_hosts {num_hosts} must evenly divide the "
            f"{num_slots} worker slots (1 <= hosts <= slots)."
        )
    crashes = {int(k): int(v) for k, v in crashes.items()}
    bad = [h for h in crashes if not 0 <= h < num_hosts]
    if bad:
        raise SystemExit(
            f"--fault_crashes host ids {bad} out of range [0, {num_hosts})."
        )
    dead_slots = len(
        [h for h, at in crashes.items() if at < args.num_iter]
    ) * (num_slots // num_hosts)
    if dead_slots > declared_f:
        raise SystemExit(
            f"--fault_crashes kills {dead_slots} worker slots by step "
            f"{args.num_iter} but the declared Byzantine budget is "
            f"{declared_f}; raise --fw (crashed slots count against it)."
        )
    from ..utils import multihost

    return multihost.FaultSchedule(num_hosts, crashes=crashes)


def chunk_length(i, *, chunk, num_iter, acc_freq=0, checkpoint_freq=0,
                 crash_steps=(), profile_step=None):
    """Steps the chunk starting at step ``i`` may cover (>= 1, <= chunk).

    A chunked dispatch (``--chunk_steps``) is opaque to the host until it
    returns, so every host-side action the per-step loop interleaves must
    land exactly on a chunk boundary. The clip rules (one per boundary
    kind, each pinned by a test in tests/test_chunked.py):

      - **eval**: accuracy runs after step j when ``j % acc_freq == 0``,
        so the chunk must end at ``j + 1`` for the first such j >= i;
      - **checkpoint**: a save fires after step j when ``(j + 1) %
        checkpoint_freq == 0``, so the chunk must end on the next multiple
        of ``checkpoint_freq`` above i;
      - **crash**: a ``--fault_crashes`` event at step s re-jits the step
        with the new Byzantine mask, so no chunk may span s;
      - **profile**: the profiled step runs as its own single-step
        dispatch so the trace holds exactly one step program;
      - **end of run**: never past ``num_iter``.
    """
    end = min(i + chunk, num_iter)
    if acc_freq:
        # First eval point j >= i (j % acc_freq == 0); eval needs the
        # post-step-j state, so the chunk may include j but nothing after.
        end = min(end, i + (-i % acc_freq) + 1)
    if checkpoint_freq:
        end = min(end, i + checkpoint_freq - i % checkpoint_freq)
    for at in crash_steps:
        if at > i:
            end = min(end, at)
    if profile_step is not None:
        if i < profile_step:
            end = min(end, profile_step)
        elif i == profile_step:
            end = min(end, i + 1)
    return max(1, end - i)


def train(args, *, topology, make_trainer_kwargs, num_slots, tag):
    """The reference training loop (Aggregathor/trainer.py:226-264), SPMD:
    batch selection by step index (batch i = train_set[i % len],
    worker.py:87), jit'd step, periodic accuracy, optional bench/profile
    instrumentation, optional checkpointing. With --fault_crashes, the jit'd
    step is rebuilt at each (rare) crash event so the dead hosts' slots turn
    into zero-gradient Byzantine rows from that step on."""
    import inspect

    t_start = time.time()
    declared_f = make_trainer_kwargs.get("f", make_trainer_kwargs.get("fw", 0))
    sched = _crash_schedule(args, num_slots, declared_f)
    xs_np, ys_np, test_batches, iters_per_epoch = load_data(args, num_slots)
    binary = args.dataset == "pima"
    # One scanned eval program over the device-stacked test set instead of
    # one dispatch per batch (parallel.EvalSet docstring).
    test_batches = parallel.EvalSet(test_batches, binary=binary)
    tools.info(
        f"[{tag}] One EPOCH consists of {iters_per_epoch} iterations"
    )
    module, loss_fn, optimizer = build_ingredients(args, iters_per_epoch)
    mesh = parse_mesh(args.mesh)
    trainer_params = inspect.signature(topology.make_trainer).parameters
    mask_key = (
        "byz_mask" if "byz_mask" in trainer_params
        else "byz_worker_mask"  # byzsgd naming
    )
    for flag in ("worker_momentum", "gar_params"):
        set_ = getattr(args, flag, None)
        if set_ is not None and set_ != {} and flag not in trainer_params:
            tools.warning(
                f"[{tag}] --{flag} is not supported by this topology; ignored"
            )

    # Telemetry plane (docs/TELEMETRY.md): hub + JSONL exporter, installed
    # as the process-global event sink so exchange/liveness events land in
    # the same stream as the per-step taps.
    from ..telemetry import trace as trace_lib

    # Closed-loop defense (DESIGN.md §16): resolve the CLI intent early —
    # escalation consumes the hub's suspicion, so it implies --telemetry
    # the same way --trace does.
    from ..aggregators import defense as defense_lib

    defense_plan = defense_lib.resolve(args)
    esc_policy = None
    if defense_plan is not None and defense_plan.escalate:
        if getattr(args, "gar", None) not in defense_lib.LEVEL_RULES:
            raise SystemExit(
                f"--defense escalate needs --gar to name an escalation-"
                f"ladder rule ({sorted(defense_lib.LEVEL_RULES)}), got "
                f"{args.gar!r}"
            )
        esc_policy = defense_plan.policy()
        levels = esc_policy.config.levels
        # Start the ladder AT the configured rule's SEMANTICS — the
        # default krum is the multi-krum level; --defense must never
        # downgrade the rule it defends (defense.start_level).
        esc_policy.level = defense_lib.start_level(
            levels, args.gar, getattr(args, "gar_params", None)
        )
        if not getattr(args, "telemetry", None):
            args.telemetry = "telemetry"  # suspicion needs the hub
    if trace_lib.requested(args) and not getattr(args, "telemetry", None):
        # Spans stream through the hub's JSONL sink; --trace without an
        # explicit --telemetry gets the default directory.
        args.telemetry = "telemetry"
    tele_hub = tele_exp = None
    if getattr(args, "telemetry", None):
        from ..telemetry import exporters as tele_fmt, hub as tele_hub_lib

        taps_supported = "telemetry" in trainer_params
        if not taps_supported:
            tools.warning(
                f"[{tag}] --telemetry: this topology exposes no in-graph "
                "taps; recording loss/timing/events only"
            )
        os.makedirs(args.telemetry, exist_ok=True)
        tele_hub = tele_hub_lib.MetricsHub(
            num_ranks=num_slots,
            suspicion_halflife=resolve_suspicion_halflife(args),
            meta={
                "tag": tag,
                "gar": args.gar,
                "attack": getattr(args, "attack", None),
                "f": declared_f,
                "num_slots": num_slots,
                "dataset": args.dataset,
                "model": args.model,
                "seed": args.seed,
            },
        )
        tele_hub_lib.install(tele_hub)
        tele_exp = tele_fmt.JsonlExporter(
            os.path.join(args.telemetry, "telemetry.jsonl")
        )
        tele_exp.write(tele_fmt.make_record("run", meta=tele_hub.meta))
        # Streaming sink (crash-safe): every record — per-step taps AND
        # the trace spans below — drains to the JSONL as it is recorded.
        tele_hub._sink = tele_exp
        if trace_lib.requested(args):
            trace_lib.enable(who=tag)

    # Targeted attacks (DESIGN.md §17): resolve the config once — the
    # trainer poisons the cohort's batches with it, and the eval loop
    # below measures the per-class/ASR success the suspicion plane is
    # blind to (telemetry schema v8). Resolved AFTER the hub install so
    # the one-time binary-surrogate fallback event reaches the stream.
    from ..attacks import targeted as targeted_lib

    targeted_cfg = None
    if targeted_lib.is_targeted(getattr(args, "attack", None)):
        targeted_cfg = targeted_lib.configure(
            args.attack, getattr(args, "attack_params", None),
            num_classes=models_lib.num_classes_dict.get(args.dataset, 2),
        )

    def build(step):
        kwargs = dict(make_trainer_kwargs)
        gar_name = args.gar
        gar_params = dict(getattr(args, "gar_params", None) or {})
        if esc_policy is not None:
            # The escalation ladder owns the rule (aggregators/defense.py):
            # level changes rebuild the step here, exactly like the
            # crash-schedule re-jit below.
            gar_name, lvl_params = esc_policy.current()
            gar_params.update(lvl_params)
            if "model_gar" in kwargs and kwargs.get("model_gar") is None:
                # Per-plane ladder independence (DESIGN.md §17): the
                # ladder owns the GRADIENT rule only — a model rule that
                # defaulted to --gar must stay pinned at the configured
                # rule, not silently ride the gradient plane's
                # escalations.
                kwargs["model_gar"] = args.gar
        if defense_plan is not None and "defense" in trainer_params:
            dkw = {}
            if defense_plan.weighted:
                dkw.update(
                    power=defense_plan.power,
                    floor=defense_plan.floor,
                    halflife=defense_plan.halflife,
                )
            else:
                dkw["weighted"] = False
            if defense_plan.data:
                if getattr(topology, "SUPPORTS_DATAPLANE", False):
                    # Data-plane detectors (DESIGN.md §18): SSMW only —
                    # its gather holds the full per-rank stack the
                    # fingerprints need; the host-plane twins live on
                    # the cluster PS quorums (apps/cluster.py).
                    dkw["data"] = {
                        "tau": defense_plan.dp_tau,
                        "power": defense_plan.dp_power,
                        "floor": defense_plan.dp_floor,
                        "halflife": defense_plan.dp_halflife,
                    }
                elif step == start_iter:
                    tools.warning(
                        f"[{tag}] --defense data: this topology has no "
                        "in-graph data-plane detectors; the GAR-side "
                        "defense (if any) still applies"
                    )
            if defense_plan.weighted or "data" in dkw:
                kwargs["defense"] = dkw
        elif defense_plan is not None and step == start_iter:
            tools.warning(
                f"[{tag}] --defense: this topology has no in-graph "
                "suspicion weighting; applying rule escalation only"
            )
        if getattr(args, "gar_dtype", None):
            kwargs["gar_dtype"] = (
                jnp.bfloat16 if args.gar_dtype == "bfloat16"
                else jnp.float32
            )
        if (getattr(args, "worker_momentum", None) is not None
                and "worker_momentum" in trainer_params):
            kwargs["worker_momentum"] = args.worker_momentum
        if gar_params and "gar_params" in trainer_params:
            kwargs["gar_params"] = gar_params
        if tele_hub is not None and "telemetry" in trainer_params:
            kwargs["telemetry"] = True
        if "num_iter" in trainer_params:
            # Run-length hint for the unroll-vs-vmap amortization choice
            # (core.slot_path_decision): REMAINING steps from this build
            # point — crash-schedule events and resumes re-jit mid-run, and
            # a compile premium only amortizes over the steps the rebuilt
            # program will actually serve.
            kwargs["num_iter"] = max(0, args.num_iter - step)
        if sched is not None:
            kwargs["attack"] = "crash"
            kwargs[mask_key] = sched.byz_mask(step, num_slots)
            if "model_attack" in trainer_params:
                # LEARN phase-5 model gossip: a crashed node cannot serve its
                # model either — zero it with the model-space crash attack.
                kwargs["model_attack"] = "crash"
        return topology.make_trainer(
            module, loss_fn, optimizer, gar_name, mesh=mesh, **kwargs
        )

    chunk = args.chunk_steps
    if chunk is None:
        chunk = int(os.environ.get("GARFIELD_CHUNK_STEPS") or 1)
    if chunk < 1:
        raise SystemExit(f"--chunk_steps must be >= 1, got {chunk}")

    # Resume target BEFORE the first build: the rebuilt program's num_iter
    # hint (the unroll-amortization decision, core.slot_path_decision) must
    # see the REMAINING steps, not the original total — a resumed run only
    # serves num_iter - start_iter steps from here.
    ckpt = None
    start_iter = 0
    if args.checkpoint_dir:
        ckpt = ckpt_lib.Checkpointer(args.checkpoint_dir)
        if args.resume and ckpt.latest_step() is not None:
            start_iter = int(ckpt.latest_step())

    init_fn, step_fn, eval_fn = build(start_iter)

    xs = jax.device_put(jnp.asarray(xs_np), step_fn.batch_sharding)
    ys = jax.device_put(jnp.asarray(ys_np), step_fn.batch_sharding)
    key = jax.random.PRNGKey(args.seed)
    state = init_fn(key, xs_np[0, 0])

    if ckpt is not None and start_iter:
        state = jax.device_put(
            ckpt.restore(jax.tree.map(np.asarray, state)),
            jax.tree.map(lambda l: l.sharding, state),
        )
        start_iter = int(np.asarray(state.step))
        tools.info(f"[{tag}] resumed from step {start_iter}")

    timer = profiling.StepTimer()
    d = int(sum(np.prod(l.shape) for l in jax.tree.leaves(state.params)))
    num_batches = xs.shape[1]
    metrics = {}

    cur_mask = sched.byz_mask(start_iter, num_slots) if sched else None
    eval_threads = []

    # Chunked dispatch programs, one per distinct (clipped) chunk length —
    # boundary clipping produces a handful of lengths at most. Invalidated
    # whenever the step itself is rebuilt (crash-schedule re-jit).
    crash_steps = sorted(set(sched.crashes.values())) if sched else []
    profile_step = (start_iter + 5) if args.profile_dir else None
    chunk_fns = {}

    def chunked_for(k):
        fn = chunk_fns.get(k)
        if fn is None:
            fn = chunk_fns[k] = parallel.core.make_chunked_step(
                step_fn, k, num_batches
            )
        return fn

    t_train = time.time()
    i = start_iter
    while i < args.num_iter:
        if sched is not None:
            mask = sched.byz_mask(i, num_slots)
            if (mask != cur_mask).any():
                cur_mask = mask
                tools.info(
                    f"[{tag}] crash event at step {i}: dead slots "
                    f"{np.flatnonzero(mask).tolist()}; re-jitting step"
                )
                # Only the step depends on the mask — keep eval_fn's (and
                # init_fn's) compiled programs. Chunk programs scan the
                # step body, so they are rebuilt from the new step too.
                _, step_fn, _ = build(i)
                chunk_fns.clear()
        k = chunk_length(
            i, chunk=chunk, num_iter=args.num_iter, acc_freq=args.acc_freq,
            checkpoint_freq=(args.checkpoint_freq if ckpt else 0),
            crash_steps=crash_steps, profile_step=profile_step,
        )
        profiling_this = profile_step is not None and i == profile_step
        # Span semantics without --bench: dispatch is asynchronous, so
        # the span covers ENQUEUE time only (tag blocked=False); with
        # --bench the block_until_ready makes it the honest device time.
        with profiling.trace(args.profile_dir if profiling_this else None), \
                trace_lib.span("dispatch", step=i, chunk=k,
                               blocked=bool(args.bench)):
            if k == 1:
                b = i % num_batches
                if args.bench:
                    # Honest per-step numbers require a device sync;
                    # without --bench we leave dispatch asynchronous
                    # (faster) and report only whole-run throughput below.
                    with timer.step(block_on=None):
                        state, metrics = step_fn(state, xs[:, b], ys[:, b])
                        jax.block_until_ready(metrics["loss"])
                else:
                    state, metrics = step_fn(state, xs[:, b], ys[:, b])
            else:
                # One dispatch for k on-device steps; metrics leaves carry
                # a leading k axis. A per-step sync here would serialize
                # the chunk back into per-step dispatches and defeat it —
                # bench mode syncs ONCE per chunk and reports the honest
                # per-step time chunk_time / k (PERF.md methodology).
                cfn = chunked_for(k)
                if args.bench:
                    t0 = time.perf_counter()
                    state, metrics = cfn(state, xs, ys, np.int32(i))
                    jax.block_until_ready(metrics["loss"])
                    timer.record_chunk(time.perf_counter() - t0, k)
                else:
                    state, metrics = cfn(state, xs, ys, np.int32(i))
        end = i + k
        if args.bench:
            byz_bytes = profiling.collective_bytes(
                tag, num_workers=num_slots, d=d,
                num_ps=getattr(args, "num_ps", 1),
                axis_size=step_fn.mesh.shape[
                    step_fn.mesh.axis_names[-1]
                ],
            )
            if k == 1:
                print(
                    f"Training step {i} takes {timer.last():.4f} seconds",
                    flush=True,
                )
            else:
                print(
                    f"Training steps {i}-{end - 1} take "
                    f"{timer.last() * k:.4f} seconds "
                    f"({timer.last():.4f} s/step, chunked x{k})",
                    flush=True,
                )
            print(
                "Consumed bandwidth in this iteration: "
                f"{profiling.convert_to_gbit(byz_bytes):.4f} Gbits",
                flush=True,
            )
        if tele_hub is not None:
            # One host readback per CHUNK (the documented telemetry sync
            # cost), fanned back out into k per-step records — the hub
            # ingests the same stream as the per-step loop.
            host_metrics = jax.device_get(metrics)
            for j in range(k):
                m_j = (
                    host_metrics if k == 1
                    else jax.tree.map(lambda l: l[j], host_metrics)
                )
                # record_step drains to the JSONL via the hub's sink.
                tele_hub.record_step(
                    i + j,
                    loss=float(m_j["loss"]),
                    tap=m_j.get("tap"),
                    step_time_s=timer.last() if args.bench else None,
                )
                if "attack_mag" in m_j:
                    # Adaptive-controller observability (schema v7): the
                    # magnitude the attacker played and the verdict it
                    # read back, one event per step.
                    tele_hub.record_event(
                        "attack_adapt",
                        step=int(i + j),
                        magnitude=float(m_j["attack_mag"]),
                        detected=bool(m_j["attack_detected"] > 0.5),
                    )
                for mag_key, det_key, plane in (
                    ("ps_attack_mag", "ps_attack_detected", "model"),
                    ("model_attack_mag", "model_attack_detected",
                     "gossip"),
                ):
                    if mag_key in m_j:
                        # Model-plane adaptive controller (schema v8):
                        # a Byzantine PS vs the replica gather, or a
                        # LEARN node vs the model gossip.
                        tele_hub.record_event(
                            "ps_attack_adapt",
                            step=int(i + j),
                            magnitude=float(m_j[mag_key]),
                            detected=bool(m_j[det_key] > 0.5),
                            plane=plane,
                        )
                if "defense_w" in m_j:
                    # Suspicion weights the step composed (schema v7) —
                    # the hub digests them into summary.defense.
                    tele_hub.record_event(
                        "defense_weights",
                        step=int(i + j),
                        weights=np.round(
                            np.asarray(m_j["defense_w"], np.float64), 6
                        ).tolist(),
                    )
                if "dataplane_score" in m_j:
                    # Data-plane defense observability (schema v9): the
                    # per-rank spectral outlier scores, detector flags,
                    # and composed weights of the in-graph detectors —
                    # the hub digests them into summary.data_defense and
                    # the garfield_dataplane_outlier_score gauge.
                    tele_hub.record_event(
                        "data_defense",
                        step=int(i + j),
                        scores=np.round(
                            np.asarray(m_j["dataplane_score"],
                                       np.float64), 6
                        ).tolist(),
                        flags=[int(x) for x in np.asarray(
                            m_j["dataplane_flags"]
                        )],
                        weights=np.round(
                            np.asarray(m_j["dataplane_w"], np.float64), 6
                        ).tolist(),
                    )
                if "ps_defense_w" in m_j:
                    # Replica-plane suspicion weights (schema v8): the
                    # MSMW twin's second, independent defense history.
                    tele_hub.record_event(
                        "defense_weights",
                        step=int(i + j),
                        plane="model",
                        weights=np.round(
                            np.asarray(m_j["ps_defense_w"], np.float64), 6
                        ).tolist(),
                    )
        if esc_policy is not None and tele_hub is not None:
            # Closed-loop escalation (DESIGN.md §16): fold the windowed
            # suspicion's concentration into the hysteresis policy once
            # per dispatch; a level change rebuilds the step exactly
            # like a crash-schedule event (same TrainState structure —
            # the ladder is stateful-homogeneous by construction).
            susp = tele_hub.suspicion_decayed()
            if susp is not None:
                conc = defense_lib.suspicion_concentration(
                    susp, max(1, declared_f)
                )
                act = esc_policy.observe(float(conc))
                if act:
                    # Feasibility at this deployment's quorum geometry
                    # (the cluster PS convention): a ladder level whose
                    # rule contract fails at n_eff (bulyan needs
                    # n >= 4f + 3) is refused loudly and reverted —
                    # rebuilding with it would assert mid-run.
                    from ..aggregators import gars as gars_reg

                    lvl_gar, _ = esc_policy.current()
                    n_eff = getattr(args, "subset", None) or num_slots
                    msg = gars_reg[lvl_gar].check(
                        np.zeros((n_eff, 4), np.float32),
                        f=max(1, declared_f),
                    )
                    if msg is not None:
                        tools.warning(
                            f"[{tag}] defense cannot move to "
                            f"{esc_policy.level_name!r} at n={n_eff}: "
                            f"{msg}"
                        )
                        esc_policy.level -= act
                        act = 0
                if act:
                    tools.info(
                        f"[{tag}] defense "
                        f"{'escalates' if act > 0 else 'de-escalates'} to "
                        f"{esc_policy.level_name!r} at step {end - 1} "
                        f"(suspicion concentration {float(conc):.3f})"
                    )
                    tele_hub.record_event(
                        "defense_escalate",
                        step=int(end - 1),
                        level=int(esc_policy.level),
                        rule=str(esc_policy.level_name),
                        direction=(
                            "escalate" if act > 0 else "deescalate"
                        ),
                        concentration=round(float(conc), 6),
                    )
                    _, step_fn, _ = build(end)
                    chunk_fns.clear()
        if args.log:
            losses = np.asarray(metrics["loss"]).reshape(-1)
            for j in range(k):
                print(
                    f"Loss {i + j}: {float(losses[j if k > 1 else -1]):.6f}",
                    flush=True,
                )
        last = end - 1
        if args.acc_freq and last % args.acc_freq == 0:
            # Boundary clipping guarantees an eval point is always the
            # chunk's LAST step, so the state here is the post-step-`last`
            # state the per-step loop evaluated. Stamp Time at the eval
            # REQUEST, not at the (possibly much later) async readback,
            # so accuracy-vs-time stays meaningful.
            t_req = time.time() - t_start

            def _report(acc, i=last, t_req=t_req):
                print(
                    f"Epoch: {i / max(iters_per_epoch, 1):.2f} "
                    f"Accuracy: {acc:.4f} Time: {t_req:.1f}",
                    flush=True,
                )

            if args.sync_eval or args.bench:
                # --bench promises honest per-step numbers; overlapped eval
                # device work would execute inside the next timed window,
                # so bench mode keeps eval inline.
                with trace_lib.span("eval", step=last):
                    _report(parallel.compute_accuracy(
                        state, eval_fn, test_batches, binary=binary
                    ))
            else:
                # Overlapped eval (reference's accuracy side thread): device
                # work is enqueued here, the blocking readback happens off
                # the training thread, so the step stream does not stall.
                eval_threads.append(parallel.compute_accuracy_async(
                    state, eval_fn, test_batches, binary=binary,
                    on_done=_report,
                    after=eval_threads[-1] if eval_threads else None,
                ))
            if targeted_cfg is not None and tele_hub is not None:
                # Per-class eval digest (schema v8): the targeted
                # attack's success metric, measured at every eval point
                # — global accuracy alone cannot see a labelflip/
                # backdoor (DESIGN.md §17). Inline (blocking): this is a
                # measurement run by construction.
                rep = parallel.targeted_eval(
                    state, eval_fn, test_batches,
                    source=targeted_cfg.source,
                    target=targeted_cfg.target,
                    trigger_cfg=(
                        targeted_cfg
                        if targeted_cfg.attack == "backdoor" else None
                    ),
                )
                tele_hub.record_event(
                    "targeted_eval", step=int(last),
                    source=rep["source"], target=rep["target"],
                    accuracy=round(rep["accuracy"], 6),
                    confusion=(
                        None if rep["confusion"] is None
                        else round(rep["confusion"], 6)
                    ),
                    asr=(
                        None if rep["asr"] is None
                        else round(rep["asr"], 6)
                    ),
                    asr_baseline=(
                        None if rep["asr_baseline"] is None
                        else round(rep["asr_baseline"], 6)
                    ),
                    per_class={
                        str(k): round(v, 6)
                        for k, v in rep["per_class"].items()
                    },
                )
        if ckpt and args.checkpoint_freq and end % args.checkpoint_freq == 0:
            with trace_lib.span("checkpoint", step=end - 1):
                ckpt.save(end, jax.tree.map(np.asarray, state))
        i = end

    jax.block_until_ready(state.step)  # drain async dispatch for honest wall
    train_wall = time.time() - t_train
    for t in eval_threads:  # flush overlapped accuracy reports
        t.join()
        if t.exc is not None:
            raise t.exc
    steps_done = args.num_iter - start_iter
    acc = parallel.compute_accuracy(state, eval_fn, test_batches, binary=binary)
    targeted_rep = None
    if targeted_cfg is not None:
        # Run-closing targeted digest: confusion/ASR into the printed
        # summary (and one last v8 event), so a targeted run's success
        # metric is never only in the JSONL stream.
        targeted_rep = parallel.targeted_eval(
            state, eval_fn, test_batches,
            source=targeted_cfg.source, target=targeted_cfg.target,
            trigger_cfg=(
                targeted_cfg if targeted_cfg.attack == "backdoor" else None
            ),
        )
        if tele_hub is not None:
            tele_hub.record_event(
                "targeted_eval", step=int(args.num_iter),
                source=targeted_rep["source"],
                target=targeted_rep["target"],
                accuracy=round(targeted_rep["accuracy"], 6),
                confusion=(
                    None if targeted_rep["confusion"] is None
                    else round(targeted_rep["confusion"], 6)
                ),
                asr=(
                    None if targeted_rep["asr"] is None
                    else round(targeted_rep["asr"], 6)
                ),
                asr_baseline=(
                    None if targeted_rep["asr_baseline"] is None
                    else round(targeted_rep["asr_baseline"], 6)
                ),
                per_class={
                    str(k): round(v, 6)
                    for k, v in targeted_rep["per_class"].items()
                },
            )
    summary = {
        "final_accuracy": acc,
        # The last dispatch may have been a chunk: its loss carries a
        # leading chunk axis; the final loss is the last scan step's.
        "final_loss": (
            float(np.asarray(metrics["loss"]).reshape(-1)[-1])
            if metrics else None
        ),
        "wall_s": time.time() - t_start,
        "train_wall_s": train_wall,
        "steps_per_sec": steps_done / train_wall if train_wall > 0 else None,
        **({"targeted": {
            "confusion": targeted_rep["confusion"],
            "asr": targeted_rep["asr"],
            "asr_baseline": targeted_rep["asr_baseline"],
            "per_class": targeted_rep["per_class"],
        }} if targeted_rep is not None else {}),
        **{f"step_{k}": v for k, v in timer.summary().items()},
    }
    print(json.dumps({"tag": tag, **summary}), flush=True)
    if tele_hub is not None:
        from ..telemetry import exporters as tele_fmt, hub as tele_hub_lib

        trace_lib.disable()
        tele_hub._sink = None  # summary is written once, explicitly
        tele_exp.write(tele_hub.summary())
        with open(os.path.join(args.telemetry, "metrics.prom"), "w") as fp:
            fp.write(tele_fmt.prometheus_text(tele_hub))
        tele_exp.close()
        tele_hub_lib.uninstall()
    if ckpt:
        if args.checkpoint_freq:
            ckpt.save(args.num_iter, jax.tree.map(np.asarray, state))
        ckpt.close()
    return state, summary

"""Pallas TPU kernels for coordinate-wise robust statistics.

Two kernels, mirroring the two CUDA kernels the reference dedicates to this
layer (SURVEY P13):

  - ``coordinate_median``: lower coordinate-wise median of an (n, d) stack
    (py_median/median.cu counterpart). torch semantics: for even n the lower
    of the two middle values; NaN sorts last, so up to ceil(n/2)-1 NaNs per
    coordinate do not contaminate the result (median.py:39).
  - ``averaged_median_mean``: Bulyan's second phase (py_bulyan/bulyan.cu
    counterpart, bulyan.py:77-84): per coordinate, take the beta values
    closest to the lower median (stable ties: lowest row index wins) and
    average them. Fused into one kernel so the (s, d) stack is read from HBM
    exactly once; the jnp fallback needs a sort, an argsort and a gather.

Design notes (see /opt/skills/guides/pallas_guide.md):
  - n is tiny (worker count, <= MAX_SORT_N) and d is huge, so the kernel
    grid tiles d in LANE-multiple blocks and each program fully sorts its
    (n, TILE) block with an odd-even transposition network unrolled at trace
    time. Compare-exchange on strict ``<`` keeps the network STABLE, which
    is what makes tie-breaking match ``jnp.argsort(..., stable=True)``.
  - The comparator implements the jnp/torch sort total order for floats:
    ascending with NaN last — swap iff (b < a) or (a is NaN and b is not).
  - d is padded to a TILE multiple host-side; columns are independent so the
    pad values are irrelevant and sliced off.
"""

import functools
import os
import warnings

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Largest stack the sorting-network kernels accept: the unrolled network is
# O(n^2) vector ops per tile, which is fine for realistic worker counts
# (the reference's own GAR bench sweeps n <= 512 but runs Byzantine configs
# at n <= a few dozen) and keeps compile times bounded. Above it the XLA
# path is used — which for averaged_median_mean is the gather-free
# threshold formulation (``averaged_median_mean_xla``), NOT the
# catastrophic sort+argsort+gather, so n > 32 degrades gracefully; a
# one-time warning still flags the switch (PERF.md).
MAX_SORT_N = 32

_LANES = 128
# Lanes per program. Swept on the v5e chip (r5, n=8 d=11.2M f32): 1024 ->
# 5.8 ms, 4096 -> 4.4, 8192 -> 3.8 (best), 16384+ regress — the old 1024
# default optimized for a 128 KiB VMEM budget that is ~100x below the
# ~16 MB/core reality, and its 10.9k-program grid paid per-program
# overhead. Worst case (n = MAX_SORT_N + out + padding) stays under 2 MB.
_TILE = 8192

_warned_large_n = set()


def _warn_large_n(op, n):
    """Loud, once-per-op notice that the fused Pallas path is off (VERDICT
    r1: the n > MAX_SORT_N fallback used to be silent)."""
    if op not in _warned_large_n:
        _warned_large_n.add(op)
        warnings.warn(
            f"{op}: n={n} exceeds the Pallas sorting-network bound "
            f"MAX_SORT_N={MAX_SORT_N}; using the XLA path (graceful for "
            "median/tmean/averaged_median_mean, but not the fused "
            "single-HBM-pass kernel). For federated-scale n, use the "
            "hierarchical bucketed rules (garfield_tpu.aggregators."
            "hierarchy, e.g. gars['hier-krum']): robust buckets of <= "
            "MAX_SORT_N keep every fold on the fast path.",
            stacklevel=3,
        )


def use_pallas(n=None, op=None):
    """True when the Pallas path should be used (TPU backend, n in range)."""
    if os.environ.get("GARFIELD_NO_PALLAS"):
        return False
    if n is not None and n > MAX_SORT_N:
        if op is not None and jax.default_backend() == "tpu":
            _warn_large_n(op, n)
        return False
    return jax.default_backend() == "tpu"


def _swap_mask(a, b):
    """Swap iff a must sort after b: ascending, NaN last (strict => stable)."""
    return (b < a) | (jnp.isnan(a) & ~jnp.isnan(b))


def _oddeven_exchange(keys, payloads=None):
    """In-place-style odd-even transposition sort of a list of row vectors.

    Sorts ``keys`` (list of n equal-shape arrays) ascending under the
    NaN-last total order; ``payloads`` (optional parallel list) is permuted
    identically. Unrolled: n rounds of adjacent compare-exchange.
    """
    n = len(keys)
    keys = list(keys)
    payloads = list(payloads) if payloads is not None else None
    for rnd in range(n):
        for i in range(rnd % 2, n - 1, 2):
            m = _swap_mask(keys[i], keys[i + 1])
            keys[i], keys[i + 1] = (
                jnp.where(m, keys[i + 1], keys[i]),
                jnp.where(m, keys[i], keys[i + 1]),
            )
            if payloads is not None:
                payloads[i], payloads[i + 1] = (
                    jnp.where(m, payloads[i + 1], payloads[i]),
                    jnp.where(m, payloads[i], payloads[i + 1]),
                )
    return keys if payloads is None else (keys, payloads)


def _pad_cols(g, tile):
    d = g.shape[-1]
    pad = (-d) % tile
    if pad:
        g = jnp.pad(g, ((0, 0), (0, pad)))
    return g, d


def _load_rows(x_ref, n, sel=None):
    """Rows upcast to f32 in VMEM: Mosaic on current targets rejects bf16
    compares ("Target does not support this comparison" — caught by the
    on-device tests, tests/test_ops_tpu.py), and bf16 -> f32 is exact and
    order-preserving, so the sort network is unchanged semantically while
    HBM traffic stays bf16.

    ``sel`` (optional, STATIC): list of (row_index, scale) pairs — the
    folded-attack remap (parallel/fold.py): logical row i is
    ``scale * block[row_index]``. Duplicate indices (lie's shared fake
    row) are free VMEM re-reads; the indexing and scaling unroll at trace
    time, so the poisoned stack is never materialized anywhere."""
    if sel is None:
        return [x_ref[i, :].astype(jnp.float32) for i in range(n)]

    def one(idx, scale):
        if scale == 0.0:
            # Exact zeros, not 0*row: the crash attack's where-path writes
            # literal zero rows, and 0*inf/0*nan would leak NaN into the
            # sort where the reference semantics have 0.
            return jnp.zeros_like(x_ref[idx, :], jnp.float32)
        row = x_ref[idx, :].astype(jnp.float32)
        return row if scale == 1.0 else row * scale

    return [one(idx, scale) for idx, scale in sel]


def _median_kernel(n, sel, x_ref, o_ref):
    rows = _oddeven_exchange(_load_rows(x_ref, n, sel))
    o_ref[0, :] = rows[(n - 1) // 2].astype(o_ref.dtype)


def _tmean_kernel(n, f, sel, x_ref, o_ref):
    rows = _oddeven_exchange(_load_rows(x_ref, n, sel))
    acc = rows[f]
    for i in range(f + 1, n - f):
        acc = acc + rows[i]
    o_ref[0, :] = (acc / (n - 2 * f)).astype(o_ref.dtype)


def _avgmed_kernel(s, beta, quant_dtype, x_ref, o_ref):
    vals = _load_rows(x_ref, s)
    med = _oddeven_exchange(list(vals))[(s - 1) // 2]
    # Deviations are the SORT KEYS and must carry the LOGICAL input
    # dtype's rounding: the spec computes |g - med| in the caller's dtype,
    # where bf16 rounding creates ties (broken stably by row index) that
    # exact f32 deviations would order differently. ``quant_dtype`` is the
    # caller's dtype — the kernel itself now always runs on f32 blocks
    # (_dispatch upcasts half inputs), so x_ref.dtype no longer carries
    # it. Quantize, then upcast for the comparisons Mosaic supports.
    devs = [
        jnp.abs(v - med).astype(quant_dtype).astype(jnp.float32)
        for v in vals
    ]
    _, picked = _oddeven_exchange(devs, vals)
    acc = picked[0]
    for i in range(1, beta):
        acc = acc + picked[i]
    o_ref[0, :] = (acc / beta).astype(o_ref.dtype)


def _column_call(kernel, g, tile, interpret):
    """Run a (n, TILE) -> (1, TILE) kernel over d-tiles of g."""
    if tile % _LANES:
        raise ValueError(f"tile must be a multiple of {_LANES}, got {tile}")
    g, d = _pad_cols(g, tile)
    n, dp = g.shape
    out = pl.pallas_call(
        kernel,
        grid=(dp // tile,),
        in_specs=[pl.BlockSpec((n, tile), lambda i: (0, i))],
        out_specs=pl.BlockSpec((1, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, dp), g.dtype),
        interpret=interpret,
    )(g)
    return out[0, :d]


# --- public entry points ---------------------------------------------------


def coordinate_median_reference(g):
    """jnp spec: lower coordinate-wise median, NaN-resilient (median.py:39)."""
    n = g.shape[0]
    return jnp.sort(g, axis=0)[(n - 1) // 2]


def trimmed_mean_reference(g, f):
    """jnp spec: drop the f smallest/largest per coordinate, average rest
    (NaN sorts last, so up to f NaNs per coordinate land in the tail)."""
    n = g.shape[0]
    return jnp.mean(jnp.sort(g, axis=0)[f : n - f], axis=0)


def averaged_median_mean_reference(g, beta):
    """jnp spec for Bulyan phase 2 (bulyan.py:77-84)."""
    med = coordinate_median_reference(g)
    dev = jnp.abs(g - med[None, :])
    idx = jnp.argsort(dev, axis=0, stable=True)[:beta]
    return jnp.mean(jnp.take_along_axis(g, idx, axis=0), axis=0)


def averaged_median_mean_xla(g, beta):
    """Gather-free Bulyan phase 2: threshold + stable tie rank.

    Semantics-equal to ``averaged_median_mean_reference`` but without the
    argsort+gather pair, whose (s, d) gather is the catastrophic XLA path
    at large d (PERF.md). Per coordinate: rows with deviation strictly
    below the beta-th smallest are all selected; the remaining quota among
    exact-threshold ties goes to the lowest row indices (the stable
    tie-break of ``argsort(stable=True)``). One sort + O(s) elementwise.
    """
    s = g.shape[0]
    med = coordinate_median_reference(g)
    dev = jnp.abs(g - med[None, :])
    thresh = jnp.sort(dev, axis=0)[beta - 1]  # (d,); NaN sorts last
    lt = dev < thresh[None, :]
    eq = dev == thresh[None, :]
    quota = beta - jnp.sum(lt, axis=0)  # ties to admit per coordinate
    tie_rank = jnp.cumsum(eq, axis=0)  # 1-based rank among tie rows
    mask = lt | (eq & (tie_rank <= quota[None, :]))
    out = jnp.sum(jnp.where(mask, g, 0), axis=0) / beta
    # >s-beta NaN deviations per coordinate: the reference mean is NaN
    # (NaN rows enter the argsort tail); comparisons with a NaN threshold
    # selected nothing, so restore the NaN explicitly.
    return jnp.where(jnp.isnan(thresh), jnp.nan, out)


def _dispatch(g, kernel, fallback_fn, tile, interpret, n, op):
    """Route to the Pallas kernel or the XLA fallback.

    The Pallas branch is selected by the *lowering* platform
    (``lax.platform_dependent``), not the process-default backend — a
    computation jitted for CPU devices on a TPU host takes the XLA path
    instead of failing to lower (ADVICE r1). ``use_pallas`` (and its
    large-n warning) is consulted only when the kernel is NOT forced via
    ``interpret=True`` — an interpret-mode call runs the kernel and must
    not warn or consume the once-per-op warning budget.
    """
    # Half-precision inputs run the KERNEL in f32: Mosaic's packed (2, 1)
    # sublane loads + per-row converts made the bf16 kernel SLOWER than
    # the f32 one despite half the HBM traffic (measured r5: 7.8 vs
    # 3.8 ms at n=8 d=11.2M), so one XLA convert outside the kernel wins
    # ~2x. bf16 -> f32 is exact, selection ops (median) round-trip
    # losslessly, and the mean-producing kernels (tmean/avgmed) gain f32
    # accumulation accuracy before the single round back.
    orig = g.dtype
    half = orig in (jnp.bfloat16, jnp.float16)

    def run_kernel(a, interp):
        out = _column_call(
            kernel, a.astype(jnp.float32) if half else a, tile, interp
        )
        return out.astype(orig) if half else out

    if interpret:
        return run_kernel(g, True)
    if not use_pallas(n, op=op):
        return fallback_fn(g)
    return jax.lax.platform_dependent(
        g,
        tpu=lambda a: run_kernel(a, False),
        default=fallback_fn,
    )


def _remap_sel(g, row_map, row_scale):
    """Normalize the folded-attack remap to a static ``sel`` list (or None)
    plus the logical row count; validates bounds against g's physical rows.
    ``row_map``/``row_scale`` must be concrete (numpy) — the remap is baked
    into the kernel at trace time."""
    import numpy as np

    if row_map is None and row_scale is None:
        return None, g.shape[0]
    ne = g.shape[0]
    row_map = (
        np.arange(ne) if row_map is None else np.asarray(row_map, np.int64)
    )
    n = row_map.size
    row_scale = (
        np.ones(n) if row_scale is None else np.asarray(row_scale, np.float64)
    )
    if row_scale.size != n:
        raise ValueError(
            f"row_scale has {row_scale.size} entries for {n} mapped rows"
        )
    if row_map.min() < 0 or row_map.max() >= ne:
        raise ValueError(
            f"row_map references rows outside the {ne}-row stack"
        )
    return [
        (int(i), float(s)) for i, s in zip(row_map, row_scale)
    ], n


def _remap_fallback(g, sel):
    """XLA form of the remap: one static gather + row scaling. Zero scales
    produce exact zero rows (see ``_load_rows``: 0*inf must not leak NaN
    where the where-path's crash attack writes literal zeros)."""
    import numpy as np

    idx = jnp.asarray(np.array([i for i, _ in sel]))
    scale_np = np.array([s for _, s in sel])
    scale = jnp.asarray(scale_np, g.dtype)
    eff = g[idx] * scale[:, None]
    zero = scale_np == 0.0
    if zero.any():
        eff = jnp.where(jnp.asarray(zero)[:, None], 0.0, eff).astype(eff.dtype)
    return eff


def coordinate_median(g, *, row_map=None, row_scale=None, interpret=False,
                      tile=_TILE):
    """Lower coordinate-wise median of an (n, d) stack -> (d,).

    ``row_map``/``row_scale`` (static) apply the folded-attack remap INSIDE
    the kernel — logical row i is ``row_scale[i] * g[row_map[i]]`` — so the
    poisoned stack of a deterministic attack is never materialized
    (parallel/fold.py)."""
    g = jnp.asarray(g)
    sel, n = _remap_sel(g, row_map, row_scale)
    if n == 1:
        return g[0] if sel is None else _remap_fallback(g, sel)[0]
    fallback = (
        coordinate_median_reference if sel is None
        else lambda a: coordinate_median_reference(_remap_fallback(a, sel))
    )
    return _dispatch(
        g, functools.partial(_median_kernel, n, sel),
        fallback, tile, interpret,
        n, "coordinate_median",
    )


def trimmed_mean(g, f, *, row_map=None, row_scale=None, interpret=False,
                 tile=_TILE):
    """Coordinate-wise trimmed mean: average of rows f..n-f-1 per sorted
    column, fused into the sorting-network kernel (one HBM pass).
    ``row_map``/``row_scale``: see ``coordinate_median``."""
    g = jnp.asarray(g)
    sel, n = _remap_sel(g, row_map, row_scale)
    if not (0 <= f and n - 2 * f >= 1):
        raise ValueError(f"need n - 2f >= 1, got n={n}, f={f}")
    if n == 1:
        return g[0] if sel is None else _remap_fallback(g, sel)[0]
    fallback = (
        (lambda a: trimmed_mean_reference(a, f)) if sel is None
        else (lambda a: trimmed_mean_reference(_remap_fallback(a, sel), f))
    )
    return _dispatch(
        g, functools.partial(_tmean_kernel, n, f, sel),
        fallback, tile, interpret,
        n, "trimmed_mean",
    )


def _sortnet_split(g, axis):
    """``g`` split into per-index slices along ``axis`` (upcast-for-compare),
    bounds-checked against MAX_SORT_N — the shared front half of every jnp
    sorting-network entry point."""
    n = g.shape[axis]
    if n > MAX_SORT_N:
        raise ValueError(
            f"sorting-network path is bounded by MAX_SORT_N={MAX_SORT_N}, "
            f"got n={n}; use the XLA sort or bucket hierarchically"
        )
    rows = [jax.lax.index_in_dim(g, i, axis, keepdims=False)
            for i in range(n)]
    if g.dtype in (jnp.bfloat16, jnp.float16):
        rows = [r.astype(jnp.float32) for r in rows]
    return rows


def _sortnet_rows(g, axis):
    """Rows of ``g`` along ``axis``, sorted by the odd-even network.

    The network is the SAME ``_oddeven_exchange`` the Pallas kernels unroll
    — plain jnp here, so it lowers on every backend and under ``vmap``
    (``pallas_call`` batching is what the hierarchical bucket fold must not
    depend on). Half inputs are upcast to f32 for the compares exactly like
    ``_dispatch``/``_load_rows`` (bf16 -> f32 is exact and order-preserving)
    and the caller rounds back. O(n^2) compare-exchanges: only sane for
    n <= MAX_SORT_N, which is the bucket-size contract.
    """
    return _oddeven_exchange(_sortnet_split(g, axis))


def _oddeven_exchange_vec(keys, payload):
    """Index-carrying odd-even transposition along axis 0, one vectorized
    compare-exchange per round.

    The SAME network schedule as ``_oddeven_exchange`` (n rounds of
    adjacent compare-exchange under the strict-< NaN-last comparator, so
    ties keep ascending payload order — ``jnp.argsort(..., stable=True)``
    parity), but each round's pairs swap as two strided slices instead of
    n scalar chains. The list form with payloads compiles PATHOLOGICALLY
    on XLA:CPU (~50 s at n=30: the 2n² interleaved key/payload SSA chains
    defeat the fusion pass; measured, see DESIGN.md §21) while this form
    is O(n) HLO ops and compiles in ~1 s with identical semantics.
    """
    n = keys.shape[0]
    for rnd in range(n):
        off = rnd % 2
        npairs = (n - off) // 2
        if npairs == 0:
            continue
        end = off + 2 * npairs
        lo, hi = keys[off:end:2], keys[off + 1:end:2]
        m = _swap_mask(lo, hi)
        merged = jnp.stack(
            [jnp.where(m, hi, lo), jnp.where(m, lo, hi)], axis=1
        ).reshape((2 * npairs,) + keys.shape[1:])
        keys = jnp.concatenate([keys[:off], merged, keys[end:]], axis=0)
        plo, phi = payload[off:end:2], payload[off + 1:end:2]
        pm = jnp.stack(
            [jnp.where(m, phi, plo), jnp.where(m, plo, phi)], axis=1
        ).reshape((2 * npairs,) + payload.shape[1:])
        payload = jnp.concatenate([payload[:off], pm, payload[end:]], axis=0)
    return keys, payload


def _sortnet_index(g, axis):
    """(sorted keys, permuted index payload) along ``axis`` (moved to axis
    0): the index-carrying network behind argmin/top_m/argsort. Bounds and
    upcast exactly like ``_sortnet_split``; the emitted permutation is the
    stable NaN-last order of ``jnp.argsort(..., stable=True)`` — strict
    ``<`` never swaps equal keys, so ties keep ascending index order.
    This is what makes sortnet selection substitutable for the stable-
    argsort selection on the krum/multi-krum/bulyan Gram paths.
    """
    n = g.shape[axis]
    if n > MAX_SORT_N:
        raise ValueError(
            f"sorting-network path is bounded by MAX_SORT_N={MAX_SORT_N}, "
            f"got n={n}; use the XLA sort or bucket hierarchically"
        )
    keys = jnp.moveaxis(g, axis, 0)
    if g.dtype in (jnp.bfloat16, jnp.float16):
        keys = keys.astype(jnp.float32)
    shape = (n,) + (1,) * (keys.ndim - 1)
    idx = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32).reshape(shape), keys.shape
    )
    return _oddeven_exchange_vec(keys, idx)


def sortnet_median(g, *, axis=-2):
    """Lower coordinate-wise median along ``axis`` via the jnp sorting
    network — bitwise-equal to ``coordinate_median_reference`` (same
    NaN-last total order, same lower-middle pick) but ~15x faster than
    XLA's variadic sort on CPU at n <= MAX_SORT_N, and batch/vmap-safe on
    every backend. This is the hierarchical bucket fold's coordinate-rule
    fast path (aggregators/hierarchy.py): buckets are <= MAX_SORT_N by
    construction, so every fold stays on a sorting network."""
    g = jnp.asarray(g)
    n = g.shape[axis]
    out = _sortnet_rows(g, axis)[(n - 1) // 2]
    return out.astype(g.dtype)


def sortnet_trimmed_mean(g, f, *, axis=-2):
    """Coordinate-wise trimmed mean along ``axis`` via the jnp sorting
    network: drop the f smallest/largest per coordinate, average the rest
    with the SAME sequential f32 accumulation as the Pallas
    ``_tmean_kernel`` (rows f..n-f-1 added in index order, one divide)."""
    g = jnp.asarray(g)
    n = g.shape[axis]
    if not (0 <= f and n - 2 * f >= 1):
        raise ValueError(f"need n - 2f >= 1, got n={n}, f={f}")
    rows = _sortnet_rows(g, axis)
    acc = rows[f]
    for i in range(f + 1, n - f):
        acc = acc + rows[i]
    return (acc / (n - 2 * f)).astype(g.dtype)


def sortnet_sort(keys, *, axis=-1):
    """``jnp.sort(keys, axis=axis)`` via the odd-even network: same total
    order (ascending, NaN last), bitwise-identical output — values are
    permuted by ``where`` swaps, never recomputed. Bounded by MAX_SORT_N
    along ``axis`` (loud ValueError above it); vmap/batch-safe on every
    backend. Half inputs compare (and return) in f32."""
    keys = jnp.asarray(keys)
    return jnp.stack(_sortnet_rows(keys, axis), axis=axis)


def sortnet_argsort(keys, *, axis=-1):
    """``jnp.argsort(keys, axis=axis, stable=True)`` via the index-carrying
    network (int32 indices): stable ties, NaN-last. The full permutation —
    Bulyan's phase-1 scatter needs all n positions; prefer
    ``sortnet_argmin``/``sortnet_top_m`` when only a prefix is consumed."""
    keys = jnp.asarray(keys)
    _, idx = _sortnet_index(keys, axis)
    return jnp.moveaxis(idx, 0, axis % keys.ndim)


def sortnet_argmin(keys, *, axis=-1):
    """Index of the minimum along ``axis`` (first index on ties, NaN last)
    — ``jnp.argsort(keys, stable=True)[..., 0]`` without materializing the
    permutation. Shape: ``keys`` with ``axis`` removed; int32."""
    keys = jnp.asarray(keys)
    _, idx = _sortnet_index(keys, axis)
    return idx[0]


def sortnet_top_m(keys, m, *, axis=-1):
    """Indices of the m smallest along ``axis``, best first — the stable
    NaN-last prefix ``jnp.argsort(keys, stable=True)[..., :m]``. This is
    (multi-)krum's selection: m best-scored rows, ties to the lowest
    index."""
    keys = jnp.asarray(keys)
    n = keys.shape[axis]
    if not (1 <= m <= n):
        raise ValueError(f"m must be in [1, {n}], got {m}")
    _, idx = _sortnet_index(keys, axis)
    return jnp.moveaxis(idx[:m], 0, axis % keys.ndim)


def sortnet_row_sums(dist, k, *, axis=-1):
    """Sum of the k smallest entries along ``axis`` in EXPLICIT ascending
    order — krum's score without materializing the full sorted matrix.

    The accumulation is a sequential add chain over the network's sorted
    rows (smallest first), the same idiom as ``sortnet_trimmed_mean`` /
    the Pallas ``_tmean_kernel``. A chain is the bitwise-robust form: XLA
    never reassociates explicit float adds, whereas ``jnp.sum`` over an
    axis is free to regroup its reduce per fusion context — measured on
    XLA:CPU to flip last bits between programs computing the SAME
    ``jnp.sum(jnp.sort(d)[..., :k])`` expression (DESIGN.md §21). Krum's
    slow path chains the sorted slices identically, so toggling
    GARFIELD_SORTNET_SELECT cannot move a trajectory. Half inputs sum
    (and return) in f32, like every sortnet entry point."""
    dist = jnp.asarray(dist)
    n = dist.shape[axis]
    if not (1 <= k <= n):
        raise ValueError(f"k must be in [1, {n}], got {k}")
    rows = _sortnet_rows(dist, axis)
    acc = rows[0]
    for i in range(1, k):
        acc = acc + rows[i]
    return acc


def averaged_median_mean(g, beta, *, interpret=False, tile=_TILE):
    """Mean of the beta rows closest (per coordinate) to the lower median.

    Equivalent to ``averaged_median_mean_reference`` (ties broken stably by
    row index, NaN deviations sort last) but fused into a single HBM pass.
    Off the Pallas path (n > MAX_SORT_N, non-TPU lowering, or
    GARFIELD_NO_PALLAS) it uses the gather-free ``averaged_median_mean_xla``
    — NOT the argsort+gather spec, whose gather is catastrophic at large d.
    """
    g = jnp.asarray(g)
    s = g.shape[0]
    if not (1 <= beta <= s):
        raise ValueError(f"beta must be in [1, {s}], got {beta}")
    return _dispatch(
        g, functools.partial(_avgmed_kernel, s, beta, g.dtype),
        lambda a: averaged_median_mean_xla(a, beta), tile, interpret,
        s, "averaged_median_mean",
    )

"""SENet (counterpart of garfieldpp/models/senet.py): pre-activation basic
blocks with squeeze-and-excitation gating."""

import flax.linen as nn
import jax.numpy as jnp

from ._layers import conv, conv1x1, global_avg_pool, norm


class SEBlock(nn.Module):
    features: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        out = nn.relu(norm(train, dtype=d)(x))
        shortcut = x
        if self.stride != 1 or x.shape[-1] != self.features:
            shortcut = conv1x1(self.features, stride=self.stride, dtype=d)(out)
        out = conv(self.features, 3, self.stride, padding=1, dtype=d)(out)
        out = conv(self.features, 3, 1, padding=1, dtype=d)(
            nn.relu(norm(train, dtype=d)(out)))
        # Squeeze-and-excitation: global pool -> fc/16 -> fc -> sigmoid gate.
        w = global_avg_pool(out)
        w = nn.relu(nn.Dense(self.features // 16, dtype=d)(w))
        w = nn.sigmoid(nn.Dense(self.features, dtype=d)(w))
        out = out * w[:, None, None, :]
        return out + shortcut


class SENet(nn.Module):
    num_blocks: tuple = (2, 2, 2, 2)
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        x = nn.relu(norm(train, dtype=d)(conv(64, 3, 1, padding=1, dtype=d)(x)))
        for stage, nb in enumerate(self.num_blocks):
            for i in range(nb):
                stride = 2 if stage > 0 and i == 0 else 1
                x = SEBlock(64 * 2 ** stage, stride, dtype=d)(x, train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=d)(x)


def SENet18(num_classes=10, dtype=jnp.float32):
    return SENet((2, 2, 2, 2), num_classes, dtype)

"""Federated round bench: million-client sharded rounds (FEDBENCH_r*).

Three checks, one committed artifact (schema v10 ``fed_bench`` rows):

``scaling``
    The headline: n = 10^6 sampled clients per round, end to end
    (cohort sample -> wave ingest -> per-shard hier-GAR fold -> shard
    broadcast encode) at S in {1, 2, 4} shards. Each (cell, shard) runs
    in its OWN OS process (``--shard_run`` child) — the deployment
    shape, and the only honest way to record per-shard-process RSS.
    The container is 1-core, so shard processes run back to back and a
    cell's ``round_s`` is the MAX over its shard processes' per-round
    walls — the round time of the real deployment, where the S shards
    are independent processes on S cores with no cross-shard traffic
    (the same pacing-style argument EXCHBENCH's rank-0-paced rounds
    make); ``round_s_sum`` records the serialized total so the 1-core
    provenance is never hidden. Gradients are simulated from two cycled
    pools (generation outside the timed region, HIERBENCH's method);
    every shard slices the SAME pool bytes, so cells differ only in
    shard width.

``s1_bitwise``
    The anchor: the engine at S=1 with full participation runs a
    multi-round trajectory bitwise equal to the existing unsharded
    single-PS streaming path (StreamingAggregator + the same
    ``model -= lr * agg`` update) — sharding is a strict generalization,
    not a fork.

``fleet``
    The elastic half: a REAL client fleet (jax-free ``--client``
    subprocesses over PeerExchange, one wire frame per shard per round,
    shard-stamped) driven by ``federated.ClientFleet`` /
    ``utils.autoscale``. The round's fixed cohort is partitioned across
    the active drivers, each sleeping a per-client compute delay, so
    spawning drivers genuinely parallelizes the round (sleeps overlap
    even on one core): the controller starts under-provisioned, spawns
    toward ``--fleet_target`` rounds/s, and the row records
    pre/recovered rates + membership actions.

  python -m garfield_tpu.apps.benchmarks.fed_bench --json FEDBENCH.json
"""

import argparse
import json
import os
import subprocess
import socket
import sys
import time

import numpy as np

from ...utils import wire
from ...utils.exchange import PeerExchange

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)))

# Fleet stop sentinel: a round tag no real round reaches.
_STOP_ROUND = 2 ** 40


def _rss():
    import resource

    return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024


def _ports(k):
    socks = [socket.socket() for _ in range(k)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _spawn_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        _REPO + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH") else _REPO
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep subprocesses off the TPU
    env["JAX_PLATFORMS"] = "cpu"
    return env


# --- the shard child (one PS shard process of one scaling cell) -------------


# Span-name -> artifact phase-name map for the child's per-phase digest
# (schema v12): the trace plane's hierarchy spans keep their producer
# names in the JSONL stream; the fed_bench row speaks the ISSUE's
# vocabulary (ingest/h2d/fold/selection).
_PHASE_NAMES = {
    "hier_ingest": "ingest",     # pre-timed, one per dispatched wave —
    #                              counts align 1:1 with h2d/fold (the
    #                              v12 capture timed an OUTER push_rows
    #                              span instead and undercounted)
    "hier_h2d": "h2d",           # staging one wave onto the device
    "hier_wave": "fold",         # wave dispatch (+ readback in sync mode)
    "hier_fold_wait": "fold_wait",  # double-buffer blocking readback
    "hier_finalize": "finalize",
    "selection": "selection",    # the Gram-selection micro-probe below
}


def _selection_probe(server, wave, reps=24):
    """Emit ``selection`` spans: the bucket rule's Gram selection at the
    deployed level-0 bucket size, timed on a wave-shaped batch. The
    selection runs FUSED inside the wave fold program (that fusion is
    the point of the sortnet path), so it cannot be timed in situ — the
    probe times the selection subgraph alone (Gram matmul + ranked
    pick), gar_bench --selection's methodology at this cell's exact
    (rule, bucket_size, d_shard). Median buckets have no selection
    phase; their rows simply omit it."""
    red = server._red
    if red is None or not red._levels:
        return
    level = red._levels[0]["level"]
    if level.rule not in ("krum", "bulyan"):
        return
    import jax

    from ...telemetry import trace as tele_trace
    from .gar_bench import _selection_fn

    s = max(level.sizes)
    g = jax.random.normal(jax.random.PRNGKey(7), (wave, s, server.d_shard))
    fn = jax.jit(_selection_fn(level.rule, level.f, True))
    jax.block_until_ready(fn(g))  # compile + warm outside the spans
    for _ in range(reps):
        with tele_trace.span("selection", buckets=int(wave), size=int(s)):
            jax.block_until_ready(fn(g))


def _shard_run(args):
    """One shard process of one scaling cell: sample the cohort, ingest
    its own column span of every cohort row, fold, encode the broadcast
    frame. Prints one JSON line the parent aggregates. The first round
    is a warmup (fold-program compiles) and is not reported. The child
    installs a private MetricsHub + trace for the timed rounds, so the
    line carries per-phase p50/p95 (schema v12): ingest waves, H2D
    staging, wave fold dispatch/readback, and the selection micro-probe
    (see _selection_probe)."""
    from ... import federated as fed
    from ...telemetry import hub as tele_hub
    from ...telemetry import trace as tele_trace

    spec = fed.plan_shards(args.d, args.shards)
    s = args.shard_index
    sampler = fed.CohortSampler(
        args.population, args.n, seed=args.seed, byz_frac=args.byz_frac,
        bucket_gar=args.bucket_gar,
    )
    f = sampler.f_budget()
    server = fed.ShardServer(s, spec, bucket_gar=args.bucket_gar,
                             wave_buckets=args.wave)
    rng = np.random.default_rng(args.seed)
    wave_rows = args.wave * 32
    pools = [rng.normal(size=(wave_rows, args.d)).astype(np.float32)
             for _ in range(2)]
    hub = tele_hub.MetricsHub()
    walls, bytes_out = [], 0
    for r in range(args.rounds + 1):  # +1: round 0 is compile warmup
        if r == 1:
            # Arm the phase digest AFTER the warmup round: round 0's
            # compile-dominated spans would pollute the tails the
            # artifact commits.
            tele_hub.install(hub)
            tele_trace.enable(who=f"fed-shard-{s}")
        t0 = time.perf_counter()
        cohort = sampler.cohort(r)
        server.begin_round(r, cohort.size, f)
        i = 0
        while i < cohort.size:
            pool = pools[(i // wave_rows) % 2]
            take = min(wave_rows, cohort.size - i)
            # Ingest attribution rides the reducer's per-wave
            # hier_ingest spans (trace.emit) — no outer span here, so
            # counts align with hier_wave/hier_h2d instead of one span
            # per push_rows call. stable=True: the pool slice is a
            # C-contiguous f32 block untouched until the next wave's
            # readback, so whole waves fold zero-copy.
            server.push_rows(spec.slice_rows(pool[:take], s), stable=True)
            i += take
        agg = server.finish_round()
        frame = wire.encode(agg, plane=s)  # the shard broadcast payload
        bytes_out = len(frame)
        if r > 0:
            walls.append(time.perf_counter() - t0)
    _selection_probe(server, args.wave)
    phases = {
        _PHASE_NAMES.get(ph, ph): {
            "count": int(st["count"]),
            "p50_s": round(st["p50_s"], 9),
            "p95_s": round(st["p95_s"], 9),
        }
        for ph, st in (hub.phase_stats() or {}).items()
    }
    print(json.dumps({
        "shard": s, "walls": [round(w, 4) for w in walls],
        "f_budget": f, "d_shard": spec.width(s),
        "broadcast_bytes": bytes_out, "peak_rss_bytes": _rss(),
        "phases": phases or None,
    }), flush=True)


def _spawn_shard(args, gar, shards, shard_index):
    return subprocess.Popen(
        [sys.executable, "-m", "garfield_tpu.apps.benchmarks.fed_bench",
         "--shard_run", "--shards", str(shards),
         "--shard_index", str(shard_index),
         "--n", str(args.n), "--population", str(args.population),
         "--d", str(args.d), "--rounds", str(args.rounds),
         "--seed", str(args.seed), "--byz_frac", str(args.byz_frac),
         "--bucket_gar", gar, "--wave", str(args.wave)],
        env=_spawn_env(), stdout=subprocess.PIPE, text=True,
    )


def scaling_cell(args, gar, shards):
    """One scaling cell: S shard processes, run back to back (1-core
    container — see the module docstring), round_s = max over shards of
    the per-shard min-over-rounds wall."""
    reports = []
    for s in range(shards):
        p = _spawn_shard(args, gar, shards, s)
        out, _ = p.communicate(timeout=3600)
        if p.returncode != 0:
            raise RuntimeError(f"shard {s}/{shards} failed:\n{out[-2000:]}")
        reports.append(json.loads(out.strip().splitlines()[-1]))
    per_shard_s = [min(r["walls"]) for r in reports]
    round_s = max(per_shard_s)
    # The row's per-phase attribution (schema v12) is the BOTTLENECK
    # shard's digest — the shard whose wall defines round_s is the one
    # whose phase breakdown explains it.
    phases = reports[per_shard_s.index(round_s)].get("phases")
    return {
        **({"phases": phases} if phases else {}),
        "check": "scaling", "n": args.n, "population": args.population,
        "d": args.d, "shards": shards, "gar": f"hier-{gar}",
        "f": reports[0]["f_budget"], "rounds": args.rounds,
        "round_s": round(round_s, 4),
        "round_s_sum": round(sum(per_shard_s), 4),
        "per_client_s": round(round_s / args.n, 9),
        "per_shard_s": [round(x, 4) for x in per_shard_s],
        "per_shard_rss": [r["peak_rss_bytes"] for r in reports],
        "peak_rss_bytes": max(r["peak_rss_bytes"] for r in reports),
        "shards_serialized_on_host": True,
        "wave_buckets": args.wave,
    }


# --- the S=1 bitwise anchor --------------------------------------------------


def bitwise_cell(args):
    """S=1 full participation over several rounds, bitwise vs the
    unsharded single-PS streaming path (the pre-sharding SSMW shape:
    one StreamingAggregator over the full vector + the same SGD
    update)."""
    from ... import federated as fed
    from ...aggregators import hierarchy

    n, d, rounds = args.bitwise_n, args.bitwise_d, 3
    rng = np.random.default_rng(args.seed)
    model0 = rng.normal(size=d).astype(np.float32)
    sampler = fed.CohortSampler(n, n, seed=args.seed,
                                byz_frac=args.byz_frac,
                                bucket_gar=args.bucket_gar)
    eng = fed.FedRoundEngine(model0, 1, sampler, lr=0.05,
                             bucket_gar=args.bucket_gar,
                             wave_buckets=args.wave)
    ref = model0.copy()
    t0 = time.perf_counter()
    for r in range(rounds):
        ids, f = eng.begin_round()
        g = np.random.default_rng([args.seed, 7, r]).normal(
            size=(ids.size, d)).astype(np.float32)
        eng.ingest_rows(g)
        eng.finish_round()
        red = hierarchy.StreamingAggregator(
            ids.size, f, bucket_gar=args.bucket_gar,
            wave_buckets=args.wave,
        )
        red.push_many(g)
        ref = (ref - np.float32(0.05) * red.finalize()).astype(np.float32)
    equal = bool(np.array_equal(eng.model, ref))
    return {
        "check": "s1_bitwise", "n": n, "population": n, "d": d,
        "shards": 1, "gar": f"hier-{args.bucket_gar}", "rounds": rounds,
        "s1_bitwise_equal": equal,
        "round_s": round((time.perf_counter() - t0) / (2 * rounds), 4),
        "peak_rss_bytes": _rss(),
    }


# --- the ingest micro-mode (batch vs per-frame decode) -----------------------


def ingest_micro_cell(args):
    """Batch-vs-per-frame decode isolation (INGESTBENCH_r*): for every
    frame width x wire scheme x batch size, encode ``batch`` frames of
    ``d`` elems, then decode them (a) per frame through ``decode_into``
    — the pre-ISSUE-20 ingest loop — and (b) in one
    ``decode_batch_into`` call into the same slab. Both paths are
    asserted bitwise-identical before any timing is committed, and
    min-over-reps is recorded (the gar_bench timing discipline: the
    floor is the signal on a noisy shared host). The ``--ingest_d``
    sweep brackets the claim: at small frames the per-frame Python
    header trip dominates and the vectorized screen wins; at the
    scaling cells' d_shard the CRC+memcpy floor dominates BOTH paths
    and batch is a wash — committed either way (DESIGN.md §24). A
    final pair of f32 rows per width times the CRC thread pool
    (``GARFIELD_INGEST_THREADS=2``) against inline CRC at the largest
    batch — on this 1-core container that is the §24 negative result,
    committed rather than hidden. Rows are schema-v15 ``fed_bench``
    records (check="ingest_micro"): the decode micro has no GAR in the
    loop, so ``gar`` is the literal "none" and the n/shards envelope
    describes the batch itself."""
    reps = args.ingest_reps
    rng = np.random.default_rng(args.seed)
    rows = []

    def _time(fn):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    def _row(d, scheme, batch, frames, *, threads=0):
        out_seq = np.empty((batch, d), np.float32)
        out_bat = np.empty((batch, d), np.float32)
        os.environ["GARFIELD_INGEST_THREADS"] = str(threads)
        try:
            def per_frame():
                for i, fr in enumerate(frames):
                    wire.decode_into(fr, out_seq[i], expect_elems=d)

            def batched():
                res = wire.decode_batch_into(frames, out_bat,
                                             expect_elems=d)
                assert all(r == d for r in res), res

            per_frame()
            batched()
            equal = bool(np.array_equal(out_seq, out_bat))
            assert equal, f"batch decode diverged: {scheme} k={batch}"
            per_s, bat_s = _time(per_frame), _time(batched)
        finally:
            os.environ.pop("GARFIELD_INGEST_THREADS", None)
        return {
            "check": "ingest_micro", "n": batch, "d": d, "shards": 1,
            "gar": "none", "scheme": scheme, "batch": batch,
            "threads": threads, "frame_bytes": len(frames[0]),
            "per_frame_s": round(per_s, 9), "batch_s": round(bat_s, 9),
            "speedup": round(per_s / bat_s, 3),
            "bitwise_equal": equal, "reps": reps,
            "peak_rss_bytes": _rss(),
        }

    for d in args.ingest_d:
        for scheme in wire.WIRE_SCHEMES:
            for batch in args.ingest_batches:
                vecs = rng.normal(size=(batch, d)).astype(np.float32)
                frames = [wire.encode(vecs[i], scheme, plane=1)
                          for i in range(batch)]
                row = _row(d, scheme, batch, frames)
                rows.append(row)
                print(f"ingest_micro d={d} {scheme} k={batch}: "
                      f"per_frame={row['per_frame_s'] * 1e3:.3f}ms "
                      f"batch={row['batch_s'] * 1e3:.3f}ms "
                      f"speedup={row['speedup']}", flush=True)
        # The thread-pool A/B at the largest f32 batch: same frames,
        # pool on vs off — committed either way (negative result on
        # 1 core).
        batch = max(args.ingest_batches)
        vecs = rng.normal(size=(batch, d)).astype(np.float32)
        frames = [wire.encode(vecs[i], "f32", plane=1)
                  for i in range(batch)]
        for threads in (0, 2):
            row = _row(d, "f32", batch, frames, threads=threads)
            rows.append(row)
            print(f"ingest_micro d={d} f32 k={batch} threads={threads}: "
                  f"batch={row['batch_s'] * 1e3:.3f}ms", flush=True)
    return rows


# --- the client fleet (jax-free --client children) ---------------------------


def _client_main(args):
    """A simulated client DRIVER: follows the PS's round beacon, takes
    its block of the round's cohort, sleeps the per-client compute
    delay, and publishes one shard-stamped wave frame per shard.
    Deliberately jax-free (numpy + wire + exchange only)."""
    hosts = args.hosts.split(",")
    me = args.client_index
    ex = PeerExchange(me, hosts, connect_retry_ms=120_000,
                      planes=args.shards)
    rng = np.random.default_rng(1000 + me)
    spans = None
    dbg = os.environ.get("GARFIELD_FED_DEBUG")

    def _log(msg):
        if dbg:
            print(f"[client {me}] {msg}", file=sys.stderr, flush=True)
    try:
        ex.publish(0, b"up", to=[0], plane=0)
        last = 0
        cached = None  # (step, [(plane, frame)]): the last response
        quiet = 0
        while True:
            try:
                step, beacon = ex.read_latest(0, last + 1,
                                              timeout_ms=5_000, plane=0)
            except TimeoutError:
                # Quiet period: either the PS is gone (bail after 36
                # strikes = 3 min) or a frame was lost in EITHER
                # direction — re-publish the cached response (the PS's
                # retry republishes the beacon for the other case), so
                # a single lost frame never wedges the exact-step
                # rendezvous.
                quiet += 1
                _log(f"quiet {quiet} (last={last})")
                if quiet > 36:
                    return
                if cached is not None:
                    for s, fr in cached[1]:
                        ex.publish(cached[0], fr, to=[0], plane=s)
                continue
            quiet = 0
            if step >= _STOP_ROUND:
                return
            head = wire.decode(beacon, expect_plane=0)
            cohort, d = int(head[0]), int(head[1])
            actives = [int(x) for x in head[2:]]
            if me in actives:
                a = actives.index(me)
                base, rem = divmod(cohort, len(actives))
                k = base + (1 if a < rem else 0)
                if spans is None or spans[0] != d:
                    from ...federated.sharding import ShardSpec

                    spans = (d, ShardSpec(d, args.shards))
                if k:
                    if args.client_delay_ms:
                        time.sleep(k * args.client_delay_ms / 1e3)
                    rows = rng.normal(size=(k, d)).astype(np.float32)
                    frames = [
                        (s, wire.encode(
                            spans[1].slice_rows(rows, s).ravel(),
                            plane=s,
                        ))
                        for s in range(args.shards)
                    ]
                    for s, fr in frames:
                        ex.publish(step, fr, to=[0], plane=s)
                    cached = (step, frames)
                    _log(f"responded step {step} k={k}")
                else:
                    _log(f"step {step}: not my round (k=0)")
            else:
                _log(f"step {step}: not in actives {actives}")
            last = step
    finally:
        ex.close()


def fleet_cell(args):
    """The autoscaled fleet scenario (see the module docstring)."""
    from ... import federated as fed
    from ...telemetry import hub as tele_hub
    from ...utils import autoscale as autoscale_lib
    from .. import cluster as cluster_app

    # The fed PS is a cluster-style role process: with --telemetry it
    # reuses the per-role telemetry plane verbatim (one MetricsHub
    # streaming fed-ps.telemetry.jsonl — cluster.telemetry_open), so
    # the fleet's autoscale events, exchange waits and the v10
    # fed_round stream land in the same format as every other role.
    args.gar = f"hier-{args.bucket_gar}"
    args.fw = 0
    hub, exp = cluster_app.telemetry_open(args, "fed-ps")

    shards, d, cohort = args.fleet_shards, args.fleet_d, args.fleet_cohort
    pool_max = args.fleet_max
    ports = _ports(1 + pool_max)
    hosts = [f"127.0.0.1:{p}" for p in ports]
    spec = fed.plan_shards(d, shards)
    sampler = fed.CohortSampler(
        max(4 * cohort, cohort), cohort, seed=args.seed,
        byz_frac=args.byz_frac, bucket_gar=args.bucket_gar,
    )
    f = sampler.f_budget()
    servers = [
        fed.ShardServer(s, spec, bucket_gar=args.bucket_gar,
                        wave_buckets=args.wave)
        for s in range(shards)
    ]
    ex = PeerExchange(0, hosts, connect_retry_ms=120_000, planes=shards)

    def command_for(k):
        return [
            sys.executable, "-m",
            "garfield_tpu.apps.benchmarks.fed_bench",
            "--client", "--client_index", str(1 + k),
            "--hosts", ",".join(hosts), "--shards", str(shards),
            "--client_delay_ms", str(args.fleet_delay_ms),
        ]

    cfg = autoscale_lib.AutoscaleConfig(
        target_rate=args.fleet_target, min_workers=1,
        max_workers=pool_max, window=5, cooldown=3,
    )
    fleet = fed.ClientFleet(command_for, cfg, env=_spawn_env())
    ready = set()
    rates, spawns = [], 0
    pre_rate = None
    target_bumped = args.fleet_target > 0
    try:
        fleet.spawn_initial(args.fleet_initial)
        step = 1
        t_cell = time.perf_counter()
        for r in range(args.fleet_rounds):
            for k in sorted(set(fleet.active()) - ready):
                try:
                    ex.read_latest(1 + k, 0, timeout_ms=(
                        60_000 if not ready or r == 0 else 50
                    ), plane=0)
                    ready.add(k)
                except TimeoutError:
                    pass
            actives = sorted(ready & set(fleet.active()))
            if not actives:
                time.sleep(0.2)
                continue
            t0 = time.perf_counter()
            beacon_frame = wire.encode(np.asarray(
                [cohort, d] + [1 + a for a in actives], np.float32
            ), plane=0)
            peer_idx = [1 + a for a in actives]
            got_all = None
            for attempt in range(3):
                for sv in servers:
                    sv.begin_round(step, cohort, f)
                waits = [
                    ex.collect_begin(
                        step, len(actives), peers=peer_idx,
                        timeout_ms=30_000, transform=sv.wire_transform,
                        plane=sv.shard,
                    )
                    for sv in servers
                ]
                # Beacon only to live drivers (a reserve slot's sender
                # thread would burn its connect grace every round).
                ex.publish(step, beacon_frame, to=peer_idx, plane=0)
                try:
                    got_all = [w() for w in waits]
                    break
                except TimeoutError:
                    # Lost frame somewhere: re-arm and republish — the
                    # clients' quiet-period republish covers the other
                    # direction (same shape as the cluster PS's
                    # quorum_retry).
                    for w in waits:
                        w.cancel()
                    if attempt == 2:
                        raise
                    time.sleep(0.2)  # let cancelled waiters drain
            for got in got_all:
                assert not any(
                    isinstance(v, Exception) for v in got.values()
                ), f"codec reject in fleet round {step}: {got}"
            parts = [sv.finish_round() for sv in servers]
            fed.reassemble(spec, parts)  # the round's broadcast model
            round_s = time.perf_counter() - t0
            tele_hub.emit_event(
                "fed_round", step=int(step), shards=int(shards),
                cohort=int(cohort), f_budget=int(f),
                round_s=round(round_s, 6),
                per_shard={
                    str(sv.shard): {
                        "latency_s": None,
                        "wire_bytes": int(sv.wire_bytes_in),
                    }
                    for sv in servers
                },
            )
            if r < args.fleet_warmup:
                # TCP slow start + register warmup pollute the first
                # rounds; the controller must calibrate on the initial
                # fleet's steady state, not the transient.
                step += 1
                continue
            rates.append(1.0 / round_s)
            action, moved = fleet.observe(round_s, quorum_margin=0)
            if action > 0:
                spawns += 1
            elif action < 0 and moved is not None:
                # The driver is gone NOW (retire joins the process);
                # its hello must not keep it in the next quorum.
                ready.discard(moved)
            if pre_rate is None and len(rates) >= cfg.window:
                pre_rate = len(rates[:cfg.window]) / sum(
                    1.0 / x for x in rates[:cfg.window]
                )
            if not target_bumped and fleet.controller.target > 0:
                # --fleet_target 0: the controller auto-calibrated to
                # the INITIAL fleet's measured rate; the scenario's load
                # target is 1.6x that — reachable with more drivers
                # (sleeps overlap), unreachable at the initial count, so
                # the controller must provision.
                fleet.controller.target *= 1.6
                target_bumped = True
            step += 1
        wall = time.perf_counter() - t_cell
        tail = rates[-cfg.window:]
        recovered = len(tail) / sum(1.0 / x for x in tail)
        return {
            "check": "fleet", "n": cohort, "d": d, "shards": shards,
            "gar": f"hier-{args.bucket_gar}", "f": f,
            "rounds": len(rates),
            "target_rate": round(float(fleet.controller.target), 3),
            "pre_rate": None if pre_rate is None else round(pre_rate, 3),
            "recovered_rate": round(recovered, 3),
            "achieved_rate": round(recovered, 3),
            "active_initial": args.fleet_initial,
            "active_final": len(fleet.active()),
            "spawns": max(0, fleet.spawns - args.fleet_initial),
            "retires": fleet.retires,
            "round_s": round(1.0 / recovered, 4),
            "round_s_sum": round(wall, 3),
            "peak_rss_bytes": _rss(),
        }
    finally:
        try:
            ex.publish(_STOP_ROUND, wire.encode(
                np.zeros(2, np.float32), plane=0), plane=0)
        except Exception:  # noqa: BLE001
            pass
        fleet.stop_all()
        ex.close()
        cluster_app.telemetry_close(hub, exp)


# --- entry -------------------------------------------------------------------


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Federated sharded-round benchmark (FEDBENCH_r*)"
    )
    p.add_argument("--n", type=int, default=10 ** 6,
                   help="Sampled cohort size per round (the headline "
                        "n=10^6).")
    p.add_argument("--population", type=int, default=2 * 10 ** 6,
                   help="Client population the cohort samples from.")
    p.add_argument("--d", type=int, default=10 ** 4,
                   help="Model dimension (full width; shard s ingests "
                        "d/S).")
    p.add_argument("--shards_list", nargs="*", type=int, default=[1, 2, 4],
                   help="Shard counts for the scaling cells.")
    p.add_argument("--rounds", type=int, default=2,
                   help="Timed rounds per shard process (min is "
                        "committed; round 0 is compile warmup).")
    p.add_argument("--seed", type=int, default=20260805)
    p.add_argument("--byz_frac", type=float, default=0.01,
                   help="Byzantine population fraction the cohort "
                        "budget prices (sampler.f_budget).")
    p.add_argument("--bucket_gar", type=str, default="krum",
                   help="Bucket rule for the bitwise/fleet cells (and "
                        "the --shard_run child).")
    p.add_argument("--scaling_gars", nargs="*", type=str,
                   default=["median", "krum"],
                   help="Bucket rules swept by the scaling cells "
                        "(median's sortnet fold has no d-independent "
                        "selection cost, so it carries the clean 1/S "
                        "curve; krum is the recorded comparison).")
    p.add_argument("--wave", type=int, default=8)
    p.add_argument("--bitwise_n", type=int, default=2048)
    p.add_argument("--bitwise_d", type=int, default=10 ** 4)
    p.add_argument("--skip_scaling", action="store_true")
    p.add_argument("--skip_bitwise", action="store_true")
    p.add_argument("--skip_fleet", action="store_true")
    # fleet scenario knobs
    p.add_argument("--fleet_shards", type=int, default=2)
    p.add_argument("--fleet_d", type=int, default=10 ** 4)
    p.add_argument("--fleet_cohort", type=int, default=64)
    p.add_argument("--fleet_initial", type=int, default=2)
    p.add_argument("--fleet_max", type=int, default=5)
    p.add_argument("--fleet_rounds", type=int, default=50)
    p.add_argument("--fleet_warmup", type=int, default=4)
    p.add_argument("--fleet_delay_ms", type=float, default=8.0,
                   help="Simulated per-client compute delay (sleeps "
                        "overlap across drivers — the parallelism the "
                        "autoscaler provisions).")
    p.add_argument("--fleet_target", type=float, default=0.0,
                   help="Fleet target rounds/s (0 = derive ~1.8x the "
                        "initial fleet's theoretical rate).")
    # ingest micro-mode knobs (INGESTBENCH_r*)
    p.add_argument("--ingest_micro", action="store_true",
                   help="Run ONLY the batch-vs-per-frame decode micro "
                        "(schema-v15 fed_bench rows, "
                        "check=ingest_micro) — every wire scheme x "
                        "--ingest_batches, plus the CRC thread-pool "
                        "A/B at the largest f32 batch.")
    p.add_argument("--ingest_d", nargs="*", type=int,
                   default=[1024, 10 ** 4],
                   help="Frame widths (elems) swept by --ingest_micro: "
                        "the small-frame regime where the per-frame "
                        "Python header trip dominates, and the scaling "
                        "cells' d_shard at S=1 where CRC+memcpy do.")
    p.add_argument("--ingest_batches", nargs="*", type=int,
                   default=[8, 64, 256],
                   help="Frame-batch sizes for --ingest_micro.")
    p.add_argument("--ingest_reps", type=int, default=5,
                   help="Timing reps per --ingest_micro cell (min is "
                        "committed).")
    p.add_argument("--json", type=str, default=None,
                   help="Dump rows to this JSON file + the schema-v10 "
                        "JSONL twin (fed_bench records).")
    p.add_argument("--telemetry", type=str, default=None, nargs="?",
                   const="telemetry", metavar="DIR",
                   help="Fleet cell: stream the fed PS's per-role "
                        "telemetry (v10 fed_round events, autoscale "
                        "actions, exchange waits) into "
                        "DIR/fed-ps.telemetry.jsonl — the cluster "
                        "roles' plane, reused verbatim.")
    # hidden child modes
    p.add_argument("--shard_run", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--shards", type=int, default=1, help=argparse.SUPPRESS)
    p.add_argument("--shard_index", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--client", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--client_index", type=int, default=1,
                   help=argparse.SUPPRESS)
    p.add_argument("--hosts", type=str, default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--client_delay_ms", type=float, default=0.0,
                   help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.shard_run:
        return _shard_run(args)
    if args.client:
        return _client_main(args)

    rows = []
    if args.ingest_micro:
        args.skip_bitwise = args.skip_scaling = args.skip_fleet = True
        rows.extend(ingest_micro_cell(args))
    if not args.skip_bitwise:
        row = bitwise_cell(args)
        rows.append(row)
        print(f"s1_bitwise n={row['n']} d={row['d']}: "
              f"equal={row['s1_bitwise_equal']}", flush=True)
    if not args.skip_scaling:
        # hier-median leads: its per-bucket fold is the pure-compute
        # sortnet, so the 1/S curve is clean. hier-krum rides along as
        # the recorded comparison — its selection pays a d-INDEPENDENT
        # ~80us/bucket (the XLA:CPU sort inside the Gram selection)
        # that no shard width shrinks, which visibly flattens its
        # curve (DESIGN.md §19; a negative result, not hidden).
        for gar in args.scaling_gars:
            base = None
            for shards in args.shards_list:
                row = scaling_cell(args, gar, shards)
                if base is None and shards == 1:
                    base = row["round_s"]
                if base is not None and shards > 1:
                    row["speedup"] = round(base / row["round_s"], 3)
                rows.append(row)
                print(f"scaling {row['gar']} S={shards}: "
                      f"round_s={row['round_s']} "
                      f"(sum {row['round_s_sum']}) "
                      f"speedup={row.get('speedup', 1.0)} "
                      f"rss/shard="
                      f"{max(row['per_shard_rss']) / 2 ** 20:.0f}"
                      f" MiB", flush=True)
    if not args.skip_fleet:
        row = fleet_cell(args)
        rows.append(row)
        print(f"fleet: target={row['target_rate']:.2f}/s pre="
              f"{row['pre_rate']}/s recovered={row['recovered_rate']}/s "
              f"active {row['active_initial']}->{row['active_final']} "
              f"(+{row['spawns']})", flush=True)

    if args.json:
        with open(args.json, "w") as fp:
            json.dump(rows, fp, indent=1)
        from ...telemetry import exporters

        jsonl_path = os.path.splitext(args.json)[0] + ".jsonl"
        with exporters.JsonlExporter(jsonl_path) as exp:
            for row in rows:
                exp.write(exporters.make_record("fed_bench", **row))
    return rows


if __name__ == "__main__":
    main(sys.argv[1:])

"""Host-plane publish/collect round benchmark + cluster-mode steps/s.

The committed record for the ``apps/cluster.py`` path (VERDICT r5 item 4:
no step-time number existed for the host plane at all). Two modes:

**Micro** (default): for each (n, d, wire) cell, n localhost OS processes
— rank 0 in this process, ranks 1..n-1 spawned — run ``--rounds``
rank-0-paced publish/collect round trips per trial over a REAL
``PeerExchange`` (TCP frames + the native MRMW register), every frame
through the typed wire codec (``utils/wire.py``) with eager decode in the
collect waiter threads (the shipped cluster path; see ``_rank0_rounds``
for why the pacing is what makes the rounds loss-free on the
last-writer-wins register). Rank 0 records the median round latency per
trial and commits the MIN over ``--trials`` (gar_bench's min-over-k:
co-tenant noise only adds time). ``wire_bytes_per_step`` is the per-node
DCN fan-out: (n-1) frames of ``wire.frame_nbytes(d, w)`` — the number the
bf16 codec halves.

**--e2e**: additionally runs the SSMW cluster deployment end-to-end
(1 PS + ``--e2e_workers`` worker subprocesses, mnist/convnet,
JAX_PLATFORMS=cpu) once per wire dtype with ``--telemetry``, and derives
steps/s from the PS's per-step ``step_time_s`` records (median over the
post-warmup steps — the BASELINE.md cluster-mode row) plus wire
bytes/step from the summary's wire totals.

**--scenario** (round 11, DESIGN.md §14): the async-plane scenario
harness. ``straggler`` injects a delayed rank (``--straggler_ms``, or
10x the measured fault-free round when omitted — the EXCHBENCH_r02
acceptance shape) and measures the SYNC exact-round rate against the
bounded-staleness rate at matched (n, d): sync waits on the straggler
every round; async reuses its admissible stale frame
(``PeerExchange.round_collector``) and paces on the fast ranks, bounded
by ``--max_staleness``. ``churn`` kills the victim mid-run and relaunches
it (leave/join: the quorum q = n-2 flows around the gap; the rejoined
rank's fresh frames re-enter — re-admit is just re-appearing in the
admissible set). ``partition`` SIGSTOPs the victim for the middle third
and SIGCONTs it. Every scenario drives a MetricsHub: per-round
``staleness`` telemetry events fold the discount deficit into per-rank
SUSPICION, and each row records the victim ranking top. Every row (micro
cells included) carries ``peak_rss_bytes`` like HIERBENCH.

  python -m garfield_tpu.apps.benchmarks.exchange_bench \\
      --ns 4 --ds 100000 --wire f32 \\
      --scenario straggler churn partition --json EXCHBENCH_r02.json

**--robust** (round 18, DESIGN.md §20): the EXCHBENCH_r05 matrix. Every
``--wire`` payload scheme (now including int8/int4/topk) crossed with
{static lie, adaptive lie} on the in-graph aggregathor emulation
(pimanet/pima, n=16 f=3, vanilla krum) with the trainer's ``wire=``
compressed gradient plane — the compression claim's robustness half:
``matched_accuracy`` pins each cell within ``--acc_margin`` of the f32
same-attack cell, and ``headroom`` records the adaptive controller's
admitted magnitude minus the bf16 baseline's (the extra attack room the
scheme's compression noise hands ALIE; negative results committed, not
hidden). The micro cells at d=1e6 carry the matched byte half
(``wire_bytes_per_step`` — the >=8x ratio):

  python -m garfield_tpu.apps.benchmarks.exchange_bench \\
      --ns 4 --ds 1000000 --wire f32 bf16 int8 int4 topk \\
      --rounds 10 --trials 2 --robust --json EXCHBENCH_r05.json
"""

import argparse
import json
import os
import signal
import socket
import statistics
import subprocess
import sys
import time

import numpy as np

from ...utils import rounds as rounds_lib, wire
from ...utils.exchange import PeerExchange

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)))

# Follow-mode stop sentinel: a round tag no real round reaches.
_STOP_ROUND = 2 ** 40


def peak_rss_bytes():
    """High-water RSS of this process (bytes) — same accounting as
    apps/common.peak_rss_bytes, duplicated (not imported) because this
    module and its child processes are deliberately jax-free and the
    apps.common import chain pulls jax/models/data."""
    import resource

    return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024


def _ports(k):
    socks = [socket.socket() for _ in range(k)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _decode_tf(idx, payload):
    return wire.decode(payload)


def _barrier(ex, n):
    """Startup barrier: everyone publishes a hello at step 0 and waits
    for every peer's — the micro rounds must time the exchange, not
    subprocess startup skew."""
    ex.publish(0, b"up")
    for r in range(n):
        if r != ex.my_index:
            ex.read_latest(r, 0, timeout_ms=120_000)


def _rank0_rounds(ex, n, d, wire_dtype, rounds, trials):
    """Rank 0 PACES the mesh, SSMW-style: publish the round's frame to
    every peer, collect every peer's typed response (eager decode in the
    waiter threads — the shipped cluster path). The pacing is the
    loss-freedom proof on the last-writer-wins register: a peer publishes
    round s only after reading rank 0's s, and rank 0 publishes s+1 only
    after collecting EVERY peer's s — so no round frame can be
    overwritten before its reader latched it. (A free-running symmetric
    protocol drops rounds here: two back-to-back writes from a fast peer
    land before the blocked reader is scheduled, and the register keeps
    only the newer — the exact race apps/cluster's role pacing closes.)
    Round latency = encode + fan-out + per-peer read/decode/re-encode/
    respond + collect + eager decode: two wire hops, the PS step's wire
    component. Returns the min-over-trials of the per-trial median."""
    rng = np.random.default_rng(1234)
    vec = rng.standard_normal(d).astype(np.float32)
    _barrier(ex, n)
    step = 1
    per_trial = []
    for _ in range(max(1, trials)):
        lats = []
        for _ in range(rounds):
            wait = ex.collect_begin(step, n, timeout_ms=120_000,
                                    transform=_decode_tf)
            t0 = time.perf_counter()
            ex.publish(step, wire.encode(vec, wire_dtype))
            got = wait()
            lats.append(time.perf_counter() - t0)
            assert len(got) == n and not any(
                isinstance(v, Exception) for v in got.values()
            )
            step += 1
        per_trial.append(statistics.median(lats))
    return min(per_trial) if per_trial else None


def _child_main(args):
    hosts = args.hosts.split(",")
    n = len(hosts)
    ex = PeerExchange(args.child, hosts, connect_retry_ms=120_000)
    rng = np.random.default_rng(1234 + args.child)
    vec = rng.standard_normal(args.d).astype(np.float32)
    try:
        if args.child_mode == "follow":
            return _child_follow(ex, args, vec)
        _barrier(ex, n)
        for step in range(1, 1 + args.rounds * max(1, args.trials)):
            got = ex.collect(step, 1, peers=[0], timeout_ms=120_000,
                             transform=_decode_tf)
            assert not isinstance(got[0], Exception)
            ex.publish(step, wire.encode(vec, args.child_wire), to=[0])
    finally:
        ex.close()


def _child_follow(ex, args, vec):
    """Scenario-mode child: respond to rank 0's NEWEST round (read_latest
    catch-up — a delayed child skips rounds exactly like a real straggling
    worker) with an optional injected delay before each publish. The
    rendezvous is with rank 0 only (not all-to-all): churn relaunches a
    child mid-run, and a full barrier would hang it on hellos the other
    children published before it existed.

    ``--child_spike_round``/``--child_spike_delay_ms`` model a LOAD
    SPIKE (the scaleup scenario): from the first observed round >= the
    spike round, the per-response delay switches to the spike value —
    per-item work grew (bigger batches, heavier model), which is the
    fleet-wide slowdown the autoscale controller must provision against.
    """
    ex.publish(0, b"up", to=[0])
    delay_s = max(0, args.child_delay_ms or 0) / 1e3
    spike_s = max(0, args.child_spike_delay_ms or 0) / 1e3
    last = 0
    while True:
        try:
            step, _ = ex.read_latest(0, last + 1, timeout_ms=180_000)
        except TimeoutError:
            return  # pacer gone (scenario harness was killed)
        if step >= _STOP_ROUND:
            return
        d = delay_s
        if args.child_spike_round and step >= args.child_spike_round:
            d = spike_s
        if d:
            time.sleep(d)  # the injected straggler / spiked load
        ex.publish(step, wire.encode(vec, args.child_wire), to=[0])
        last = step


def _spawn_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        _REPO + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH") else _REPO
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep subprocesses off the TPU
    env["JAX_PLATFORMS"] = "cpu"
    return env


def bench_cell(n, d, wire_dtype, rounds, trials):
    """One micro cell: spawn ranks 1..n-1, run rank 0 here."""
    hosts = [f"127.0.0.1:{p}" for p in _ports(n)]
    env = _spawn_env()
    procs = [
        subprocess.Popen(
            [sys.executable, "-m",
             "garfield_tpu.apps.benchmarks.exchange_bench",
             "--child", str(k), "--hosts", ",".join(hosts),
             "--d", str(d), "--rounds", str(rounds),
             "--trials", str(trials), "--child_wire", wire_dtype],
            env=env,
        )
        for k in range(1, n)
    ]
    ex = PeerExchange(0, hosts, connect_retry_ms=120_000)
    try:
        round_s = _rank0_rounds(ex, n, d, wire_dtype, rounds, trials)
    finally:
        ex.close()
        for p in procs:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
    return {
        "mode": "micro", "n": n, "d": d, "wire": wire_dtype,
        "round_s": round_s,
        "wire_bytes_per_step": (n - 1) * wire.frame_nbytes(d, wire_dtype),
        "rounds": rounds, "trials": trials,
        "peak_rss_bytes": peak_rss_bytes(),
    }


def bench_e2e(wire_dtype, n_w, iters, tmpdir):
    """End-to-end SSMW cluster run (1 PS + n_w worker subprocesses) at
    ``wire_dtype``; steps/s from the PS's telemetry step records (median
    ``step_time_s`` over the post-warmup steps — compile-free, unlike
    wall_s / steps), wire bytes/step from the summary totals."""
    from ...utils import multihost

    pp = _ports(1 + n_w)
    cfg_path = os.path.join(tmpdir, f"cluster_{wire_dtype}.json")
    multihost.generate_config(
        cfg_path,
        ps=[f"127.0.0.1:{pp[0]}"],
        workers=[f"127.0.0.1:{p}" for p in pp[1:]],
        task_type="ps", task_index=0,
    )
    env = _spawn_env()
    env["GARFIELD_WIRE_DTYPE"] = wire_dtype
    env["GARFIELD_SURROGATE_MARGIN"] = "30"
    env["GARFIELD_SURROGATE_LABEL_NOISE"] = "0"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    tele_dir = os.path.join(tmpdir, f"tele_{wire_dtype}")

    def launch(role):
        return subprocess.Popen(
            [sys.executable, "-m", "garfield_tpu.apps.aggregathor",
             "--cluster", cfg_path, "--task", role,
             "--dataset", "mnist", "--model", "convnet", "--batch", "16",
             "--fw", "1", "--gar", "median", "--num_iter", str(iters),
             "--acc_freq", "0", "--train_size", "512",
             "--cluster_timeout_ms", "120000", "--telemetry", tele_dir],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )

    ps = launch("ps:0")
    workers = [launch(f"worker:{w}") for w in range(n_w)]
    try:
        out, _ = ps.communicate(timeout=600 + 10 * iters)
        if ps.returncode != 0:
            raise RuntimeError(f"e2e PS failed:\n{out[-2000:]}")
        summary = json.loads(
            [l for l in out.splitlines() if l.startswith("{")][-1]
        )
        for w in workers:
            w.communicate(timeout=120)
    finally:
        for p in [ps, *workers]:
            if p.poll() is None:
                p.kill()
    step_times, wire_totals = [], None
    with open(os.path.join(tele_dir, "cluster-ps.telemetry.jsonl")) as fp:
        for line in fp:
            rec = json.loads(line)
            if rec["kind"] == "step" and rec.get("step_time_s") is not None:
                step_times.append((rec["step"], rec["step_time_s"]))
            elif rec["kind"] == "summary":
                wire_totals = rec.get("wire")
    # Warmup excluded: the first steps pay grad/update compiles and the
    # exchange's cold-start connect grace.
    warm = [t for s, t in step_times if s >= 5]
    med = statistics.median(warm) if warm else None
    steps = summary["steps"]
    return {
        "mode": "cluster_e2e", "wire": wire_dtype, "workers": n_w,
        "iters": iters, "steps": steps,
        "wall_s": round(summary["wall_s"], 3),
        "step_s_median": None if med is None else round(med, 6),
        "steps_per_s": None if not med else round(1.0 / med, 3),
        "wire_bytes_per_step": (
            None if not (wire_totals and steps) else
            int((wire_totals["bytes_out"] + wire_totals["bytes_in"])
                / steps)
        ),
    }


def bench_robust(args):
    """The EXCHBENCH_r05 robustness matrix (round 18, DESIGN.md §20):
    every payload scheme x {static lie, adaptive lie} on the in-graph
    aggregathor emulation (pimanet/pima, n=16 f=3, vanilla krum —
    defense_bench's cell harness with the trainer's ``wire=`` compressed
    gradient plane). Two derived columns per row:

    - ``matched_accuracy``: the cell's accuracy within ``--acc_margin``
      of the f32 scheme's SAME-attack cell — the "compression must not
      open a Byzantine loophole" acceptance bit.
    - ``headroom`` (adaptive cells): the bisection controller's admitted
      magnitude minus the bf16 baseline's — the extra attack room the
      scheme's compression noise hands ALIE. Recorded even when it is
      a negative result (a scheme buying robustness, or noise burying
      the static z).

    jax imports live inside this function: the micro/scenario paths and
    their children stay jax-free.
    """
    from types import SimpleNamespace

    import jax

    from ...attacks import LIE_Z
    from ...parallel import core as pcore
    from . import defense_bench as db

    dargs = SimpleNamespace(
        num_iter=args.robust_iters, batch=8, lr=0.1, margin=1.2,
        seed=args.robust_seed, halflife=24.0,
        theta_up=0.35, theta_down=0.1, patience=4, clean_window=60,
        wire_dtype="f32", wire_topk=0,
    )
    task = db._task(dargs)
    module, loss, _, xs, _, _ = task
    init_worker, _, _ = pcore.make_worker_fns(module, loss)
    params, _ = init_worker(jax.random.PRNGKey(0), xs[0, 0])
    d_flat = sum(int(l.size) for l in jax.tree.leaves(params))

    def scheme_nbytes(scheme):
        if scheme == "topk":
            k = wire.topk_k(d_flat, wire.DEFAULT_TOPK_DIV)
            return wire.frame_nbytes(d_flat, "topk", k=k)
        return wire.frame_nbytes(d_flat, scheme)

    schemes = [w for w in wire.WIRE_SCHEMES if w in args.wire]
    for need in ("f32", "bf16"):
        # The two baselines the derived columns divide by.
        if need not in schemes:
            schemes.insert(0, need)
    rows, cells = [], {}
    for scheme in schemes:
        dargs.wire_dtype = "f32" if scheme == "topk" else scheme
        dargs.wire_topk = wire.DEFAULT_TOPK_DIV if scheme == "topk" else 0
        for attack, params_a, label in (
            ("lie", {"z": LIE_Z}, "lie"),
            ("adaptive-lie", {"mag_max": 6.0}, "adaptive_lie"),
        ):
            rec = db.run_cell(
                dargs, task, f"{scheme}/{label}",
                attack=attack, attack_params=params_a, gar="krum",
            )
            cells[(scheme, label)] = rec
            ratio = scheme_nbytes("f32") / scheme_nbytes(scheme)
            rows.append({
                "mode": "robust", "n": db.N_WORKERS, "d": d_flat,
                "wire": scheme, "cell": f"{scheme}/{label}",
                "attack": attack, "gar": "krum",
                "rounds": int(dargs.num_iter),
                "final_accuracy": rec["final_accuracy"],
                "attack_magnitude": rec["attack_magnitude"],
                "wire_bytes_per_step":
                    (db.N_WORKERS - 1) * scheme_nbytes(scheme),
                "compression_ratio": round(ratio, 3),
                "headroom": None, "matched_accuracy": None,
                "peak_rss_bytes": peak_rss_bytes(),
            })
    for row in rows:
        scheme = row["wire"]
        label = "adaptive_lie" if row["cell"].endswith("adaptive_lie") \
            else "lie"
        base = cells[("f32", label)]["final_accuracy"]
        row["matched_accuracy"] = bool(
            abs(row["final_accuracy"] - base) <= args.acc_margin
        )
        if label == "adaptive_lie":
            bf16_mag = cells[("bf16", "adaptive_lie")]["attack_magnitude"]
            mag = row["attack_magnitude"]
            if bf16_mag is not None and mag is not None:
                row["headroom"] = round(mag - bf16_mag, 6)
    return rows


def _spawn_follow(k, hosts, d, wire_dtype, delay_ms=0, spike_round=0,
                  spike_delay_ms=0):
    return subprocess.Popen(
        [sys.executable, "-m",
         "garfield_tpu.apps.benchmarks.exchange_bench",
         "--child", str(k), "--hosts", ",".join(hosts),
         "--d", str(d), "--child_wire", wire_dtype,
         "--child_mode", "follow", "--child_delay_ms", str(delay_ms),
         "--child_spike_round", str(spike_round),
         "--child_spike_delay_ms", str(spike_delay_ms)],
        env=_spawn_env(),
    )


def _sync_follow_rounds(ex, peers, frame, n_rounds, step):
    """Exact-round pacing over follow children: publish round ``step``,
    wait for EVERY peer's response to that exact round — the synchronous
    wait-everyone contract whose pace a single straggler sets. Returns
    (median round_s, next step)."""
    lats = []
    for _ in range(n_rounds):
        wait = ex.collect_begin(
            step, len(peers), peers=peers, timeout_ms=180_000,
            transform=_decode_tf,
        )
        t0 = time.perf_counter()
        ex.publish(step, frame)
        got = wait()
        lats.append(time.perf_counter() - t0)
        assert not any(isinstance(v, Exception) for v in got.values())
        step += 1
    return statistics.median(lats), step


def _async_follow_rounds(ex, collector, q, frame, n_rounds, step, policy,
                         on_round=None, q_min=None, soft_timeout_ms=None):
    """Bounded-staleness pacing: publish, gather the admissible set
    (stale reuse + freshness floor — PeerExchange.round_collector), emit
    the per-round ``staleness`` telemetry event exactly like the cluster
    PS, so the scenario's MetricsHub derives suspicion from the discount
    deficits. ``q_min`` < ``q`` enables the liveness degrade the cluster
    plane applies: a quorum that cannot fill ``q`` inside
    ``soft_timeout_ms`` (a rank's frames expired past the cutoff — churn
    leave, partition) retries at ``q_min`` and flows around the outage;
    the excluded rank re-enters the admissible set the moment it
    publishes again (re-admission is just reappearance). Returns (median
    round_s, next step, max staleness seen, per-rank presence counts)."""
    from ...telemetry import hub as tele_hub_lib

    lats, tau_max = [], 0
    present = {}
    degraded = False  # sticky: pay the soft timeout once per outage
    for r in range(n_rounds):
        if on_round is not None:
            on_round(r)
        t0 = time.perf_counter()
        ex.publish(step, frame)
        if degraded:
            # gather returns ALL admissible frames: the moment the
            # excluded rank publishes again the count recovers past q
            # and the full quorum is restored (re-admission).
            got = collector.gather(
                step, q_min, max_staleness=policy.max_staleness,
                timeout_ms=180_000,
            )
            if len(got) >= q:
                degraded = False
        else:
            try:
                got = collector.gather(
                    step, q, max_staleness=policy.max_staleness,
                    timeout_ms=(
                        180_000 if q_min is None else soft_timeout_ms
                    ),
                )
            except TimeoutError:
                if q_min is None:
                    raise
                got = collector.gather(
                    step, q_min, max_staleness=policy.max_staleness,
                    timeout_ms=180_000,
                )
                degraded = True
        quorum = sorted(got, key=lambda k: (step - got[k][0], k))[:q]
        taus = [max(0, step - got[k][0]) for k in quorum]
        w = policy.weights(np.asarray(taus))
        lats.append(time.perf_counter() - t0)
        tau_max = max(tau_max, max(taus))
        for k in quorum:
            present[k] = present.get(k, 0) + 1
        tele_hub_lib.emit_event(
            "staleness", who="exchange-bench", step=int(step),
            ranks=[int(k) for k in quorum],
            staleness=[int(t) for t in taus],
            weights=[round(float(x), 6) for x in w],
            reused=int(sum(t > 0 for t in taus)),
        )
        step += 1
    return statistics.median(lats), step, tau_max, present


def bench_scenario(scenario, n, d, wire_dtype, rounds, trials,
                   straggler_ms, max_staleness, decay):
    """One async-plane scenario cell (docstring up top): returns the
    committed row. ``straggler`` A/Bs sync vs bounded-staleness round
    rate under an injected delay (auto: 10x the fault-free round);
    ``churn`` kills + relaunches the victim; ``partition`` SIGSTOPs it
    for the middle third. All drive suspicion through real telemetry."""
    from ...telemetry import hub as tele_hub_lib

    policy = rounds_lib.StalenessPolicy(max_staleness, decay)
    victim = n - 1
    rng = np.random.default_rng(1234)
    frame = wire.encode(
        rng.standard_normal(d).astype(np.float32), wire_dtype
    )

    def open_mesh(delay_ms=0):
        hosts = [f"127.0.0.1:{p}" for p in _ports(n)]
        procs = {
            k: _spawn_follow(
                k, hosts, d, wire_dtype,
                delay_ms if k == victim else 0,
            )
            for k in range(1, n)
        }
        ex = PeerExchange(0, hosts, connect_retry_ms=120_000)
        for r in range(1, n):  # follow children hello rank 0 only
            ex.read_latest(r, 0, timeout_ms=120_000)
        return hosts, procs, ex

    def close_mesh(procs, ex):
        try:
            ex.publish(_STOP_ROUND, b"", to=list(procs))
        except OSError:
            pass
        ex.close()
        for p in procs.values():
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGCONT)  # un-freeze partitions
                except OSError:
                    pass
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()

    # Fault-free baseline round (sync, no delay) — the '10x' anchor.
    hosts, procs, ex = open_mesh()
    try:
        baseline_s, step = _sync_follow_rounds(
            ex, list(range(1, n)), frame, max(5, rounds // 4), 1
        )
    finally:
        close_mesh(procs, ex)
    if not straggler_ms:
        straggler_ms = max(20, int(baseline_s * 1e4))  # 10x, >= 20 ms

    # suspicion_halflife (schema v7): the scenario rows carry the
    # WINDOWED suspicion too — a straggler/partition victim is a live
    # condition, and the decayed score is what the report tool's
    # cross-check (and the closed-loop defense) consumes; the cumulative
    # score dilutes recovered victims with every clean round since.
    hub = tele_hub_lib.MetricsHub(num_ranks=n, suspicion_halflife=rounds,
                                  meta={
        "tag": "exchange-bench-scenario", "scenario": scenario,
    })
    tele_hub_lib.install(hub)
    # Round tracing (schema v5): the scenario rows record per-phase
    # p50/p95 from the exchange spans (publish/collect/gather/decode) so
    # the committed artifact ATTRIBUTES its speedups — e.g. the async
    # win shows up as the gather phase shrinking while publish stays
    # flat — instead of just reporting them.
    from ...telemetry import trace as trace_lib

    trace_lib.enable(who=f"exchange-bench-{scenario}")
    sync_best = async_best = None
    tau_max = 0
    presence = {}
    try:
        if scenario == "straggler":
            hosts, procs, ex = open_mesh(delay_ms=straggler_ms)
            collector = ex.round_collector(
                list(range(1, n)), transform=_decode_tf
            )
            try:
                step = 1
                for _ in range(max(1, trials)):
                    # Few sync rounds: each costs ~straggler_ms by
                    # construction; the async segment then runs at the
                    # fast ranks' pace with the victim's frame reused.
                    sync_s, step = _sync_follow_rounds(
                        ex, list(range(1, n)), frame,
                        max(3, rounds // 6), step,
                    )
                    async_s, step, tmax, pres = _async_follow_rounds(
                        ex, collector, n - 1, frame, rounds, step, policy,
                    )
                    sync_best = min(sync_best or sync_s, sync_s)
                    async_best = min(async_best or async_s, async_s)
                    tau_max = max(tau_max, tmax)
                    for k, v in pres.items():
                        presence[k] = presence.get(k, 0) + v
            finally:
                collector.close()
                close_mesh(procs, ex)
        else:
            # churn / partition: async only, full q = n - 1 with the
            # degrade-to-q-2 fallback — the victim stays IN the quorum
            # while merely stale (its discount deficit feeds suspicion),
            # drops out when its frames expire past the cutoff, and
            # re-enters when it publishes again.
            hosts, procs, ex = open_mesh(delay_ms=0)
            collector = ex.round_collector(
                list(range(1, n)), transform=_decode_tf
            )

            # Pace the rounds at >= 20 ms so the fault windows span real
            # time: the victim's staleness must actually climb past the
            # cutoff (exclusion) and recover (re-admission) — at the raw
            # sub-ms gather pace the whole outage would fit in one frame.
            pace_s = max(0.02, baseline_s)

            def on_round(r):
                time.sleep(pace_s)
                if scenario == "churn":
                    if r == rounds // 3:
                        procs[victim].kill()
                        procs[victim].wait(timeout=30)
                    elif r == 2 * rounds // 3:
                        # JOIN: a fresh process on the same rank/port
                        # (re-admit = re-appearing in the admissible set;
                        # in the cluster driver the rejoined worker also
                        # re-reads its shard — re-admit becomes re-shard).
                        procs[victim] = _spawn_follow(
                            victim, hosts, d, wire_dtype
                        )
                elif scenario == "partition":
                    if r == rounds // 3:
                        procs[victim].send_signal(signal.SIGSTOP)
                    elif r == 2 * rounds // 3:
                        procs[victim].send_signal(signal.SIGCONT)

            try:
                async_best, step, tau_max, presence = _async_follow_rounds(
                    ex, collector, n - 1, frame, rounds, 1, policy,
                    on_round=on_round, q_min=n - 2,
                    soft_timeout_ms=int(
                        max(2_000, policy.max_staleness * pace_s * 1e3)
                    ),
                )
            finally:
                collector.close()
                close_mesh(procs, ex)
    finally:
        trace_lib.disable()
        tele_hub_lib.uninstall()
    susp = hub.suspicion()
    susp_d = hub.suspicion_decayed()
    stale = hub.staleness_stats()
    phase_stats = hub.phase_stats() or {}
    phases = {
        k: {"p50_s": round(v["p50_s"], 6), "p95_s": round(v["p95_s"], 6)}
        for k, v in phase_stats.items()
    }
    row = {
        "mode": "scenario", "scenario": scenario, "n": n, "d": d,
        "wire": wire_dtype, "rounds": rounds, "trials": trials,
        "baseline_round_s": round(baseline_s, 6),
        "straggler_ms": int(straggler_ms),
        "sync_round_s": None if sync_best is None else round(sync_best, 6),
        "async_round_s": (
            None if async_best is None else round(async_best, 6)
        ),
        "speedup": (
            None if not (sync_best and async_best)
            else round(sync_best / async_best, 3)
        ),
        "max_staleness": policy.max_staleness, "decay": policy.decay,
        "max_staleness_seen": int(tau_max),
        "victim_rank": victim,
        "victim_quorums": int(presence.get(victim, 0)),
        "suspicion": (
            None if susp is None
            else [round(float(s), 6) for s in susp]
        ),
        # schema v7: the halflife-decayed twin (the live-victim signal).
        "suspicion_decayed": (
            None if susp_d is None
            else [round(float(s), 6) for s in susp_d]
        ),
        "staleness_mean": None if stale is None else round(stale["mean"], 4),
        "phases": phases or None,
        "peak_rss_bytes": peak_rss_bytes(),
    }
    return row


def bench_autoscale(scenario, n, d, wire_dtype, rounds, max_staleness,
                    decay):
    """The elastic-membership A/B (DESIGN.md §15): the AutoscaleController
    driving a REAL follow-children pool through the bounded-staleness
    gather loop, exactly the control loop the cluster PS runs.

    ``scaleup`` (the load-spike A/B): 2 children at a base delay
    calibrate the target rate; at the spike round EVERY child's
    per-response delay quadruples (per-item work grew fleet-wide) and
    newly spawned children pay the spiked delay too; the controller must
    spawn reserve children until the rate recovers — the committed row
    records pre-spike / post-spike / recovered rates (acceptance:
    recovered >= 0.8x pre-spike). ``scaledown``: the pool starts
    over-provisioned at 3x the explicit target; the controller retires
    children (clean stop sentinel + ``PeerExchange.remove_peer`` — the
    symmetric watcher teardown) while the rate holds the target.

    In both, round rate genuinely scales with the worker count because
    the gather's binding constraint is its freshness floor: W children
    each answering every D seconds supply W/D fresh frames per second
    (utils/autoscale.py docstring) — the property the controller exists
    to exploit.
    """
    from ...telemetry import hub as tele_hub_lib
    from ...utils import autoscale as autoscale_lib

    base_delay = 200  # ms per child response: the "per-item work".
    # Slow by design: at ~10-30 rounds/s every process on the 1-core box
    # is mostly asleep and the measured rates track the W/delay capacity
    # model; at 80 ms the 9-process scheduler contention capped the
    # recovered rate ~25% under model and the scenario measured the BOX,
    # not the controller.
    warmup = 10  # paced but unmeasured: the startup burst (children
    #              answering the same early rounds back-to-back) inflates
    #              rates ~5x and must not calibrate the target
    # Reserve-rank ports are handed out MINUTES after allocation (the
    # controller spawns mid-run), so the usual bind-close-reuse pattern
    # races the ephemeral allocator: any outgoing connection on the box
    # can grab a closed reserve port as its source port and the late
    # child dies with EADDRINUSE (observed on the first r04 capture).
    # Hold a bound listener on every reserve port and close it only at
    # spawn time — the race window shrinks from minutes to milliseconds.
    holders = {}

    def _alloc_held_ports(count):
        out = []
        for _ in range(count):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            out.append(s.getsockname()[1])
            holders[out[-1]] = s
        return out

    spike_round = warmup + rounds  # scaleup: spike after calibration
    pool = n  # children ranks 1..n
    hosts = [
        f"127.0.0.1:{p}"
        for p in _ports(1) + _alloc_held_ports(pool)
    ]
    rng = np.random.default_rng(1234)
    frame = wire.encode(
        rng.standard_normal(d).astype(np.float32), wire_dtype
    )
    def child_delay(k):
        """Per-child STAGGERED delays (0.75x..1.25x base): synchronized
        children answer in lockstep bursts that alias any windowed rate
        estimate; staggering desynchronizes them while keeping the
        aggregate fresh-frame rate ~pool/base."""
        d = int(base_delay * (0.75 + 0.5 * (k - 1) / max(1, pool - 1)))
        if scenario == "scaleup":
            # 2x per-item work: deep enough that the initial pair's rate
            # halves, shallow enough that the FULL pool at spiked delays
            # genuinely serves the calibrated target WITH HEADROOM on
            # the 1-core box (pool/2 >> n0 x base capacity; per-child
            # scheduler wake latency under 9 co-located processes eats
            # ~20% of the model rate, so a spike whose recovery needs
            # every modeled hertz sets the controller up to fail the
            # >= 0.8x bar on noise, not on merit).
            return dict(delay_ms=d, spike_round=spike_round,
                        spike_delay_ms=2 * d)
        return dict(delay_ms=d)

    if scenario == "scaleup":
        n0, target = 2, 0.0  # auto-calibrate to the pre-spike rate
    else:
        n0 = pool
        # Explicit target: ~3 children's worth of the pool's rate.
        target = 3.0 / (base_delay / 1e3)
    cfg = autoscale_lib.AutoscaleConfig(
        target_rate=target, min_workers=2, max_workers=pool,
        window=6, cooldown=2,
    )
    controller = autoscale_lib.AutoscaleController(cfg)
    hub = tele_hub_lib.MetricsHub(num_ranks=pool + 1, meta={
        "tag": "exchange-bench-autoscale", "scenario": scenario,
    })
    tele_hub_lib.install(hub)
    ex = PeerExchange(0, hosts, connect_retry_ms=120_000)
    procs = {}
    active = []
    ready = set()
    policy = rounds_lib.StalenessPolicy(max_staleness, decay)
    collector = ex.round_collector([], transform=_decode_tf)
    rates = []  # (round, active, rate) trajectory
    spawns = retires = 0

    def spawn(k):
        # Release the held reserve port moments before the child binds
        # it (see _alloc_held_ports).
        port = int(hosts[k].rsplit(":", 1)[1])
        holder = holders.pop(port, None)
        if holder is not None:
            holder.close()
        procs[k] = _spawn_follow(k, hosts, d, wire_dtype, **child_delay(k))
        active.append(k)
        collector.add_peer(k)

    def retire(k):
        # No wait here: reaping is end-of-run work — blocking a measured
        # round on a child's exit would charge the retire to the rate.
        active.remove(k)
        ready.discard(k)
        ex.publish(_STOP_ROUND, b"", to=[k])
        collector.remove_peer(k)
        ex.remove_peer(k)
        # Re-home the rank: a later respawn gets a FRESH held port
        # instead of re-binding one that has been released for minutes
        # (TIME_WAIT remnants and ephemeral squatters both collide with
        # it). The exchange's host table is updated in place — rank 0's
        # cached sender socket dies with the old child and the next
        # reconnect follows the new address.
        hosts[k] = f"127.0.0.1:{_alloc_held_ports(1)[0]}"
        ex.hosts[k] = hosts[k]


    def paced_gather(step, q):
        """Gather with the cluster PS's republish-on-soft-timeout
        semantics (_async_gradient_quorum): frames fanned out while a
        just-spawned child was still booting are DROPPED by its refused
        connects, and without a republish the booted child would wait
        forever for a round that already happened while this gather
        blocks — the exact deadlock the PS's quorum_retry path exists
        for. Healthy members ignore the duplicate (their read_latest
        floor is already past it)."""
        deadline = time.monotonic() + 180.0
        while True:
            try:
                return collector.gather(
                    step, q, max_staleness=policy.max_staleness,
                    timeout_ms=3_000,
                )
            except TimeoutError:
                if time.monotonic() > deadline:
                    raise
                ex.publish(step, frame)

    try:
        for k in range(1, n0 + 1):
            spawn(k)
        for k in list(active):
            ex.read_latest(k, 0, timeout_ms=120_000)  # hello
        total = warmup + (
            3 * rounds if scenario == "scaleup" else 2 * rounds
        )
        window = []
        pre_rate = spike_rate = None
        step = 1
        for r in range(total):
            t0 = time.perf_counter()
            ex.publish(step, frame)
            q = max(1, len(ready & set(active)) or len(active))
            got = paced_gather(step, q)
            # Readiness = a REAL round response (tag > 0): a hello frame
            # (tag 0) is admissible in the first max_staleness rounds and
            # must not promote a still-booting child into the quorum.
            ready.update(
                k for k in got if k in active and got[k][0] > 0
            )
            round_s = time.perf_counter() - t0
            step += 1
            if r < warmup:
                continue  # startup burst: paced, never measured
            window.append(round_s)
            window[:-24] = []  # ~3 burst cycles at the full pool
            rate = len(window) / sum(window)
            rates.append((r, len(active), round(rate, 3)))
            if scenario == "scaleup" and step - 1 == spike_round:
                pre_rate = rate  # last pre-spike measurement
            if (scenario == "scaleup" and pre_rate is not None
                    and step - 1 > spike_round + 8):
                # The post-spike trough: the full-window rate bottoms out
                # before the spawned capacity lands.
                spike_rate = rate if spike_rate is None else min(
                    spike_rate, rate
                )
            action = controller.observe(
                round_s, active=len(active),
                quorum_margin=len(got) - q,
            )
            if action != 0 and pre_rate is None \
                    and scenario == "scaledown":
                pre_rate = rate  # steady rate at the initial membership
            if action > 0 and len(active) < pool:
                reserve = [
                    k for k in range(1, pool + 1) if k not in active
                ]
                spawn(reserve[0])
                spawns += 1
                window.clear()  # measure the new membership, not the
                #                 spawn transient (mirrors the controller)
                tele_hub_lib.emit_event(
                    "autoscale", who="exchange-bench", step=int(step),
                    action="spawn", rank=int(reserve[0] - 1),
                    active=len(active),
                    rate=round(rate, 3),
                    target=round(controller.target, 3),
                )
            elif action < 0 and len(active) > cfg.min_workers:
                victim = active[-1]
                retire(victim)
                retires += 1
                window.clear()
                tele_hub_lib.emit_event(
                    "autoscale", who="exchange-bench", step=int(step),
                    action="retire", rank=int(victim - 1),
                    active=len(active),
                    rate=round(rate, 3),
                    target=round(controller.target, 3),
                )
        # Settle tail: the last action's window still contains the
        # spawned child's boot stall (a ~2 s python start shows up as a
        # handful of slow rounds and halves the windowed rate). Freeze
        # the membership and pace until a full window of steady-state
        # rounds exists — the recovered rate measures the NEW capacity,
        # not the transient that created it.
        window.clear()
        for _ in range(30):
            t0 = time.perf_counter()
            ex.publish(step, frame)
            q = max(1, len(ready & set(active)) or len(active))
            got = paced_gather(step, q)
            ready.update(
                k for k in got if k in active and got[k][0] > 0
            )
            window.append(time.perf_counter() - t0)
            window[:-24] = []
            step += 1
        recovered = (len(window) / sum(window)) if window else None
    finally:
        for h in holders.values():  # never-spawned reserve ports
            h.close()
        try:
            ex.publish(_STOP_ROUND, b"", to=list(procs))
        except OSError:
            pass
        collector.close()
        ex.close()
        for p in procs.values():
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
        tele_hub_lib.uninstall()
    summary = hub.summary()
    return {
        "mode": "autoscale", "scenario": scenario, "n": pool, "d": d,
        "wire": wire_dtype, "rounds": total,
        "base_delay_ms": base_delay,
        "target_rate": round(controller.target, 3),
        "pre_rate": None if pre_rate is None else round(pre_rate, 3),
        "spike_rate": None if spike_rate is None else round(spike_rate, 3),
        "recovered_rate": (
            None if recovered is None else round(recovered, 3)
        ),
        "recovered_frac": (
            None if not (pre_rate and recovered)
            else round(recovered / pre_rate, 3)
        ),
        # Scaledown's contract is holding the TARGET while shrinking
        # (recovered/pre compares against the over-provisioned rate and
        # reads artificially low there).
        "target_frac": (
            None if not (recovered and controller.target)
            else round(recovered / controller.target, 3)
        ),
        "active_initial": n0, "active_final": len(active),
        "spawns": spawns, "retires": retires,
        "autoscale": summary.get("autoscale"),
        "max_staleness": policy.max_staleness, "decay": policy.decay,
        "peak_rss_bytes": peak_rss_bytes(),
    }


def _learn_cluster_run(tag, n, iters, tmpdir, extra=(), victim_extra=(),
                       checkpoint=False):
    """One REAL decentralized LEARN deployment (apps/learn --cluster):
    n node processes on localhost, pima/pimanet (the smallest workload —
    the bench measures the exchange planes, not the model). Returns
    (per-node stdout list, telemetry dir)."""
    from ...utils import multihost

    pp = _ports(n)
    cfg_path = os.path.join(tmpdir, f"learn_{tag}.json")
    multihost.generate_config(
        cfg_path, nodes=[f"127.0.0.1:{p}" for p in pp],
        task_type="node", task_index=0,
    )
    env = _spawn_env()
    env["GARFIELD_SURROGATE_MARGIN"] = "30"
    env["GARFIELD_SURROGATE_LABEL_NOISE"] = "0"
    env["GARFIELD_CKPT_BACKEND"] = "pickle"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    tele = os.path.join(tmpdir, f"tele_{tag}")
    ck = (
        ("--checkpoint_dir", os.path.join(tmpdir, f"ckpt_{tag}"),
         "--checkpoint_freq", str(iters)) if checkpoint else ()
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "garfield_tpu.apps.learn",
             "--cluster", cfg_path, "--task", f"node:{k}",
             "--dataset", "pima", "--model", "pimanet", "--loss", "bce",
             "--batch", "16", "--fw", "0", "--gar", "average",
             "--num_iter", str(iters), "--acc_freq", "0",
             "--cluster_timeout_ms", "120000", "--telemetry", tele,
             *ck, *extra, *(victim_extra if k == n - 1 else ())],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for k in range(n)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=600)
        finally:
            if p.poll() is None:
                p.kill()
        if p.returncode != 0:
            raise RuntimeError(
                f"learn node failed (rc={p.returncode}):\n{out[-2000:]}"
            )
        outs.append(out)
    return outs, tele


def _learn_round_rate(tele_dir, node=0):
    """Rounds/s of one LEARN node from its telemetry event timestamps:
    the honest loop rate, startup excluded. ROUNDS must be counted the
    same way on both arms of the A/B: the synchronous deployment's
    events carry PHASE tags (gradients at 2i+2, gossip at 2i+3 — two
    distinct step values per round) while the async per-plane events
    carry plain round tags, so naive distinct-step counting doubles the
    sync rate. Count one marker per round: the async gradient plane
    (``plane`` 1/"grad") or the sync even grad-phase tags."""
    ts, rounds = [], set()
    path = os.path.join(tele_dir, f"cluster-node-{node}.telemetry.jsonl")
    with open(path) as fp:
        for line in fp:
            rec = json.loads(line)
            if rec.get("kind") == "event" and rec.get("event") in (
                "exchange_wait", "staleness"
            ):
                ts.append(rec["t"])
                step = rec.get("step")
                if step is None:
                    continue
                plane = rec.get("plane")
                if plane in (1, "grad"):
                    rounds.add(step)  # async: round-tagged grad plane
                elif plane in (0, None) and step >= 2 and step % 2 == 0:
                    rounds.add(step)  # sync: the 2i+2 grad phase
    if len(ts) < 4 or len(rounds) < 2:
        return None
    span = max(ts) - min(ts)
    return None if span <= 0 else (len(rounds) - 1) / span


def bench_learn(scenario, n, rounds, max_staleness, decay, tmpdir):
    """The LEARN async acceptance rows (DESIGN.md §15), measured on the
    REAL decentralized deployment (apps/learn --cluster over the 3-plane
    exchange), pima-sized so the rows time the exchange planes:

    ``learn_straggler``: a fault-free sync trio calibrates the baseline
    round; the victim node then gets a 10x injected ``--straggler_ms``
    and the same deployment runs sync vs ``--async`` — the committed
    speedup is the honest nodes' telemetry-derived round rate
    (acceptance >= 3x), with the victim topping the honest nodes'
    suspicion via the per-plane staleness discount deficits.
    ``learn_ms0``: the sync trajectory and the ``--async
    --max_staleness 0`` trajectory must be CHECKPOINT-BITWISE equal on
    every node (the per-plane protocol collapses to the synchronous one).
    """
    if scenario == "learn_ms0":
        iters = max(10, rounds // 2)
        _learn_cluster_run("ms0_sync", n, iters, tmpdir, checkpoint=True)
        _learn_cluster_run(
            "ms0_async", n, iters, tmpdir,
            extra=("--async", "--max_staleness", "0"), checkpoint=True,
        )
        import pickle

        bitwise = True
        for node in range(n):
            with open(os.path.join(
                tmpdir, f"ckpt_ms0_sync/node_{node}/ckpt_{iters}.pkl"
            ), "rb") as fp:
                a = pickle.load(fp)["flat"]
            with open(os.path.join(
                tmpdir, f"ckpt_ms0_async/node_{node}/ckpt_{iters}.pkl"
            ), "rb") as fp:
                b = pickle.load(fp)["flat"]
            bitwise = bitwise and bool(np.array_equal(a, b))
        return {
            "mode": "learn", "scenario": scenario, "n": n, "d": None,
            "wire": wire.wire_dtype(), "rounds": iters,
            "learn_ms0_bitwise": bitwise,
            "peak_rss_bytes": peak_rss_bytes(),
        }

    # learn_straggler: baseline -> 10x victim -> sync vs async A/B.
    _, tele = _learn_cluster_run("base", n, max(10, rounds // 3), tmpdir)
    base_rate = _learn_round_rate(tele)
    base_round_ms = 1e3 / base_rate if base_rate else 50.0
    straggler_ms = max(100, int(10 * base_round_ms))
    victim = ("--straggler_ms", str(straggler_ms))
    _, tele_s = _learn_cluster_run(
        "strag_sync", n, rounds, tmpdir, victim_extra=victim,
    )
    sync_rate = _learn_round_rate(tele_s)
    _, tele_a = _learn_cluster_run(
        "strag_async", n, rounds, tmpdir,
        extra=("--async", "--max_staleness", str(max_staleness),
               "--staleness_decay", str(decay)),
        victim_extra=victim,
    )
    async_rate = _learn_round_rate(tele_a)
    # Victim suspicion from an HONEST node's summary (its per-plane
    # staleness deficits are the audit signal).
    with open(os.path.join(
        tele_a, "cluster-node-0.telemetry.jsonl"
    )) as fp:
        summaries = [
            rec for rec in map(json.loads, fp)
            if rec.get("kind") == "summary"
        ]
    susp = summaries[-1].get("suspicion") if summaries else None
    victim_top = (
        None if not susp
        else bool(susp.index(max(susp)) == n - 1)
    )
    return {
        "mode": "learn", "scenario": scenario, "n": n, "d": None,
        "wire": wire.wire_dtype(), "rounds": rounds,
        "baseline_round_s": (
            None if not base_rate else round(1.0 / base_rate, 6)
        ),
        "straggler_ms": straggler_ms,
        "sync_round_s": None if not sync_rate else round(1 / sync_rate, 6),
        "async_round_s": (
            None if not async_rate else round(1 / async_rate, 6)
        ),
        "speedup": (
            None if not (sync_rate and async_rate)
            else round(async_rate / sync_rate, 3)
        ),
        "max_staleness": max_staleness, "decay": decay,
        "victim_rank": n - 1,
        "victim_tops_suspicion": victim_top,
        "suspicion": susp,
        "peak_rss_bytes": peak_rss_bytes(),
    }


def bench_trace_ab(n, d, wire_dtype, rounds, trials, tmpdir):
    """Tracing overhead A/B (ISSUE 8 acceptance): the same micro cell
    with tracing OFF then ON (spans streamed through a real MetricsHub
    + JSONL sink — the shipped cost, not a no-op hub), committed as one
    row so the <= 5% overhead claim lives in the artifact. The span hot
    path here is the worst case per byte moved: one publish + one
    collect + n decode spans per ~ms-scale round."""
    from ...telemetry import exporters, hub as tele_hub_lib
    from ...telemetry import trace as trace_lib

    off_row = bench_cell(n, d, wire_dtype, rounds, trials)
    sink = exporters.JsonlExporter(
        os.path.join(tmpdir, f"trace_ab_{n}_{d}_{wire_dtype}.jsonl")
    )
    hub = tele_hub_lib.MetricsHub(meta={"tag": "exchange-bench-trace-ab"})
    hub._sink = sink
    tele_hub_lib.install(hub)
    trace_lib.enable(who="exchange-bench")
    try:
        on_row = bench_cell(n, d, wire_dtype, rounds, trials)
    finally:
        trace_lib.disable()
        tele_hub_lib.uninstall()
        sink.close()
    phase_stats = hub.phase_stats() or {}
    off_s, on_s = off_row["round_s"], on_row["round_s"]
    return {
        "mode": "trace_ab", "n": n, "d": d, "wire": wire_dtype,
        "rounds": rounds, "trials": trials,
        "trace_off_round_s": off_s,
        "trace_on_round_s": on_s,
        "trace_overhead": (
            None if not (off_s and on_s) else round(on_s / off_s, 4)
        ),
        "spans": hub.counters()["spans"],
        "phases": {
            k: {"p50_s": round(v["p50_s"], 6),
                "p95_s": round(v["p95_s"], 6)}
            for k, v in phase_stats.items()
        } or None,
        "peak_rss_bytes": peak_rss_bytes(),
    }


def main(argv=None):
    p = argparse.ArgumentParser(
        description="host-plane exchange/wire-codec benchmark"
    )
    p.add_argument("--ns", nargs="*", type=int, default=[2, 4])
    p.add_argument("--ds", nargs="*", type=int,
                   default=[1_000, 100_000, 1_000_000])
    p.add_argument("--wire", nargs="*", default=list(wire.WIRE_DTYPES),
                   choices=wire.WIRE_SCHEMES)
    p.add_argument("--rounds", type=int, default=20,
                   help="publish/collect rounds per trial")
    p.add_argument("--trials", type=int, default=3,
                   help="independent trials; the committed value is the "
                        "min of the per-trial medians (min-over-k)")
    p.add_argument("--e2e", action="store_true",
                   help="also run the SSMW cluster deployment end-to-end "
                        "per wire dtype (the BASELINE.md row)")
    p.add_argument("--e2e_workers", type=int, default=4)
    p.add_argument("--e2e_iters", type=int, default=40)
    p.add_argument("--scenario", nargs="*", default=None,
                   choices=["straggler", "churn", "partition",
                            "scaleup", "scaledown",
                            "learn_straggler", "learn_ms0"],
                   help="async-plane scenario harness cells (DESIGN.md "
                        "§14/§15): straggler/churn/partition run per "
                        "(n, d, wire) over follow-mode children — "
                        "straggler A/Bs sync vs bounded-staleness round "
                        "rate, churn and partition drive membership "
                        "faults against telemetry suspicion. "
                        "scaleup/scaledown run ONCE each (at --pool "
                        "children, the smallest --ds, the first --wire): "
                        "the AutoscaleController load-spike A/B. "
                        "learn_straggler/learn_ms0 run ONCE each over a "
                        "REAL --learn_nodes LEARN cluster deployment: "
                        "the per-plane async gossip speedup + suspicion "
                        "and the ms=0 checkpoint-bitwise pin")
    p.add_argument("--pool", type=int, default=8,
                   help="worker-pool size for --scenario "
                        "scaleup/scaledown (reserve children the "
                        "controller may spawn into)")
    p.add_argument("--autoscale_d", type=int, default=1_000,
                   help="payload elements for the scaleup/scaledown "
                        "cells — small by design: those rows measure "
                        "the CONTROL loop (rate tracking, membership), "
                        "and a large frame's per-round fan-out cost "
                        "(bytes x pool) would cap the measurable rate "
                        "on the 1-core box before the controller's "
                        "scaling could show (the byte costs have their "
                        "own micro cells)")
    p.add_argument("--learn_nodes", type=int, default=3,
                   help="node count for the learn_* scenarios")
    p.add_argument("--trace_ab", action="store_true",
                   help="per (n, d, wire) also run the round-tracing "
                        "overhead A/B: the micro cell with spans off vs "
                        "on (real hub + JSONL sink), committed as a "
                        "trace_ab row — the ISSUE 8 <=5%% overhead "
                        "acceptance record")
    p.add_argument("--straggler_ms", type=int, default=0,
                   help="injected victim delay for --scenario straggler; "
                        "0 (default) auto-derives 10x the measured "
                        "fault-free round — the EXCHBENCH_r02 acceptance "
                        "shape")
    p.add_argument("--max_staleness", type=int, default=32,
                   help="bounded-staleness hard cutoff for the scenario "
                        "gathers (rounds)")
    p.add_argument("--decay", type=float, default=0.9,
                   help="per-round staleness discount for the scenario "
                        "gathers")
    p.add_argument("--robust", action="store_true",
                   help="run the EXCHBENCH_r05 robustness matrix: every "
                        "--wire scheme x {lie, adaptive-lie} on the "
                        "in-graph aggregathor emulation (pimanet/pima, "
                        "n=16 f=3 krum) over a compressed gradient "
                        "plane — matched-accuracy + adaptive-attack-"
                        "headroom columns per cell (DESIGN.md §20). "
                        "Needs jax (CPU is fine); the only mode here "
                        "that does")
    p.add_argument("--robust_iters", type=int, default=240,
                   help="training steps per robustness cell")
    p.add_argument("--robust_seed", type=int, default=1234)
    p.add_argument("--acc_margin", type=float, default=0.05,
                   help="matched-accuracy tolerance vs the f32 "
                        "same-attack cell")
    p.add_argument("--json", type=str, default=None,
                   help="dump results (+ the schema-versioned telemetry "
                        "JSONL twin at the same path with a .jsonl "
                        "suffix)")
    # child-process plumbing (internal)
    p.add_argument("--child", type=int, default=None, help=argparse.SUPPRESS)
    p.add_argument("--hosts", type=str, default=None, help=argparse.SUPPRESS)
    p.add_argument("--d", type=int, default=None, help=argparse.SUPPRESS)
    p.add_argument("--child_wire", type=str, default="f32",
                   help=argparse.SUPPRESS)
    p.add_argument("--child_mode", type=str, default="paced",
                   choices=["paced", "follow"], help=argparse.SUPPRESS)
    p.add_argument("--child_delay_ms", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--child_spike_round", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--child_spike_delay_ms", type=int, default=0,
                   help=argparse.SUPPRESS)
    args = p.parse_args(argv)
    if args.child is not None:
        return _child_main(args)

    results = []
    for n in args.ns:
        for d in args.ds:
            for w in args.wire:
                row = bench_cell(n, d, w, args.rounds, args.trials)
                results.append(row)
                rs = row["round_s"]
                print(
                    f"n={n} d={d:<9} wire={w:<4} "
                    f"{'below noise floor' if rs is None else f'{rs * 1e3:9.3f} ms'}"
                    f"  {row['wire_bytes_per_step']:>12} B/step",
                    flush=True,
                )
    for scenario in args.scenario or ():
        if scenario in ("scaleup", "scaledown"):
            row = bench_autoscale(
                scenario, args.pool, args.autoscale_d, args.wire[0],
                args.rounds, args.max_staleness, args.decay,
            )
            results.append(row)
            print(
                f"scenario={scenario} pool={args.pool} "
                f"target={row['target_rate']} pre={row['pre_rate']} "
                f"spike={row['spike_rate']} "
                f"recovered={row['recovered_rate']} "
                f"({row['recovered_frac']}x) "
                f"active {row['active_initial']}->{row['active_final']} "
                f"(+{row['spawns']}/-{row['retires']})",
                flush=True,
            )
            continue
        if scenario in ("learn_straggler", "learn_ms0"):
            import tempfile

            with tempfile.TemporaryDirectory() as td:
                row = bench_learn(
                    scenario, args.learn_nodes, args.rounds,
                    args.max_staleness, args.decay, td,
                )
            results.append(row)
            if scenario == "learn_ms0":
                print(
                    f"scenario=learn_ms0 n={row['n']} "
                    f"bitwise={row['learn_ms0_bitwise']}",
                    flush=True,
                )
            else:
                print(
                    f"scenario=learn_straggler n={row['n']} "
                    f"straggler_ms={row['straggler_ms']} "
                    f"sync={row['sync_round_s']} "
                    f"async={row['async_round_s']} "
                    f"speedup={row['speedup']} "
                    f"victim_top={row['victim_tops_suspicion']}",
                    flush=True,
                )
            continue
        for n in args.ns:
            for d in args.ds:
                for w in args.wire:
                    row = bench_scenario(
                        scenario, n, d, w, args.rounds, args.trials,
                        args.straggler_ms, args.max_staleness, args.decay,
                    )
                    results.append(row)
                    print(
                        f"scenario={scenario} n={n} d={d} wire={w} "
                        f"sync={row['sync_round_s']} "
                        f"async={row['async_round_s']} "
                        f"speedup={row['speedup']} "
                        f"tau_max={row['max_staleness_seen']} "
                        f"suspicion={row['suspicion']}",
                        flush=True,
                    )
    if args.trace_ab:
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            for n in args.ns:
                for d in args.ds:
                    for w in args.wire:
                        row = bench_trace_ab(
                            n, d, w, args.rounds, args.trials, td
                        )
                        results.append(row)
                        print(
                            f"trace_ab n={n} d={d} wire={w} "
                            f"off={row['trace_off_round_s']} "
                            f"on={row['trace_on_round_s']} "
                            f"overhead={row['trace_overhead']}x "
                            f"({row['spans']} spans)",
                            flush=True,
                        )
    if args.robust:
        for row in bench_robust(args):
            results.append(row)
            print(
                f"robust cell={row['cell']:<18} "
                f"acc={row['final_accuracy']:.4f} "
                f"mag={row['attack_magnitude']} "
                f"headroom={row['headroom']} "
                f"ratio={row['compression_ratio']}x "
                f"matched={row['matched_accuracy']}",
                flush=True,
            )
    if args.e2e:
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            for w in args.wire:
                row = bench_e2e(w, args.e2e_workers, args.e2e_iters, td)
                results.append(row)
                print(
                    f"e2e wire={w:<4} {row['steps_per_s']} steps/s "
                    f"({row['wire_bytes_per_step']} wire B/step)",
                    flush=True,
                )
    if args.json:
        with open(args.json, "w") as fp:
            json.dump(results, fp, indent=1)
        from ...telemetry import exporters

        jsonl_path = os.path.splitext(args.json)[0] + ".jsonl"
        with exporters.JsonlExporter(jsonl_path) as exp:
            for row in results:
                if row["mode"] == "micro":
                    exp.write(exporters.make_record(
                        "exchange_bench",
                        n=row["n"], d=row["d"], wire=row["wire"],
                        round_s=row["round_s"],
                        wire_bytes_per_step=row["wire_bytes_per_step"],
                        rounds=row["rounds"], trials=row["trials"],
                        peak_rss_bytes=row["peak_rss_bytes"],
                    ))
                elif row["mode"] == "scenario":
                    exp.write(exporters.make_record(
                        "exchange_bench",
                        n=row["n"], d=row["d"], wire=row["wire"],
                        scenario=row["scenario"],
                        straggler_ms=row["straggler_ms"],
                        sync_round_s=row["sync_round_s"],
                        async_round_s=row["async_round_s"],
                        speedup=row["speedup"],
                        max_staleness=row["max_staleness"],
                        max_staleness_seen=row["max_staleness_seen"],
                        victim_rank=row["victim_rank"],
                        suspicion=row["suspicion"],
                        phases=row["phases"],
                        rounds=row["rounds"], trials=row["trials"],
                        peak_rss_bytes=row["peak_rss_bytes"],
                    ))
                elif row["mode"] == "autoscale":
                    exp.write(exporters.make_record(
                        "exchange_bench",
                        n=row["n"], d=row["d"], wire=row["wire"],
                        scenario=row["scenario"],
                        pre_rate=row["pre_rate"],
                        spike_rate=row["spike_rate"],
                        recovered_rate=row["recovered_rate"],
                        active_initial=row["active_initial"],
                        active_final=row["active_final"],
                        spawns=row["spawns"], retires=row["retires"],
                        max_staleness=row["max_staleness"],
                        rounds=row["rounds"],
                        peak_rss_bytes=row["peak_rss_bytes"],
                    ))
                elif row["mode"] == "learn":
                    exp.write(exporters.make_record(
                        "exchange_bench",
                        n=row["n"], d=0, wire=row["wire"],
                        scenario=row["scenario"],
                        straggler_ms=row.get("straggler_ms"),
                        sync_round_s=row.get("sync_round_s"),
                        async_round_s=row.get("async_round_s"),
                        speedup=row.get("speedup"),
                        learn_ms0_bitwise=row.get("learn_ms0_bitwise"),
                        suspicion=row.get("suspicion"),
                        rounds=row["rounds"],
                        peak_rss_bytes=row["peak_rss_bytes"],
                    ))
                elif row["mode"] == "robust":
                    exp.write(exporters.make_record(
                        "exchange_bench",
                        n=row["n"], d=row["d"], wire=row["wire"],
                        cell=row["cell"], attack=row["attack"],
                        gar=row["gar"],
                        final_accuracy=row["final_accuracy"],
                        attack_magnitude=row["attack_magnitude"],
                        headroom=row["headroom"],
                        compression_ratio=row["compression_ratio"],
                        matched_accuracy=row["matched_accuracy"],
                        wire_bytes_per_step=row["wire_bytes_per_step"],
                        rounds=row["rounds"],
                        peak_rss_bytes=row["peak_rss_bytes"],
                    ))
                elif row["mode"] == "trace_ab":
                    exp.write(exporters.make_record(
                        "exchange_bench",
                        n=row["n"], d=row["d"], wire=row["wire"],
                        trace_off_round_s=row["trace_off_round_s"],
                        trace_on_round_s=row["trace_on_round_s"],
                        trace_overhead=row["trace_overhead"],
                        spans=row["spans"],
                        phases=row["phases"],
                        rounds=row["rounds"], trials=row["trials"],
                        peak_rss_bytes=row["peak_rss_bytes"],
                    ))
                else:
                    exp.write(exporters.make_record(
                        "bench",
                        metric=f"cluster_ssmw_steps_per_s_{row['wire']}",
                        value=row["steps_per_s"],
                        unit="steps/s",
                        wire_bytes_per_step=row["wire_bytes_per_step"],
                    ))
    return results


if __name__ == "__main__":
    main(sys.argv[1:])

"""Typed wire codec for the host plane (DESIGN.md §11).

The cluster driver's frames used to be bare ``ndarray.tobytes()`` — the
reference's wire format (garfield.proto:24-33) — which (a) ships every
gradient/model/gossip frame at f32 width even though the on-mesh pipeline
already proved bf16 gradients converge (PERF.md r3), and (b) gives the
receiver nothing to validate beyond total length, so a Byzantine process
could only be caught by a wrong-size frame. Every data frame now carries a
16-byte self-describing header:

    magic   2s   b"GW"
    ver     u8   1
    dtype   u8   low nibble: 0 = f32, 1 = bf16; HIGH nibble: plane tag
    elems   u64  logical float32 element count
    crc32   u32  zlib.crc32 of the payload bytes

The dtype byte's high nibble is the **plane tag** (DESIGN.md §15): only
two of its 256 values were ever used, so the spare bits carry which
logical exchange plane (gradient / model / control) the frame belongs to
— the self-describing half of the per-plane register slots in
``utils.exchange`` (the transport header routes; this tag lets any
consumer label bytes per plane without context). Plane 0 frames are
byte-identical to the pre-plane format, so every committed trajectory
and artifact pins carry over; decoders reject only unknown LOW-nibble
dtype tags, never a nonzero plane.

``GARFIELD_WIRE_DTYPE=f32|bf16`` selects the SEND width (default f32).
bf16 halves every gradient, model and gossip frame on the DCN; the f32
setting keeps the payload bytes BYTE-IDENTICAL to the pre-codec
``tobytes()`` format (modulo the header), so existing trajectory pins
carry over. Decoding is dtype-driven by the header, never by the local
setting — mixed-width deployments interoperate (each peer chooses its own
send width, exactly like per-link compression).

The bf16 cast is pure numpy (no jax dependency — the exchange bench and
its child processes stay jax-free): round-to-nearest-even on the high 16
bits of the f32 bit pattern, the same rounding XLA's ``convert`` uses, so
a host-decoded gradient matches what the on-mesh bf16 pipeline would have
produced for the same value. Restoring f32 is the exact ``u16 << 16``
view — bf16 -> f32 is lossless.

Why bf16-on-wire is safe UPSTREAM of the GAR: the rules aggregate at f32
(`aggregators/_common` Gram accumulation, cclip's f32 center iteration),
so wire quantization is a bounded per-coordinate perturbation of the
rule's INPUT rows — a strictly weaker disturbance than the Byzantine
value faults the f budget already absorbs, and the honest rows all carry
the same quantization so relative geometry (distances, medians) is
preserved to bf16 precision. The convergence smoke in tests/test_cluster
runs the lie attack over both widths.
"""

import os
import struct
import zlib

import numpy as np

__all__ = [
    "WIRE_DTYPES",
    "WireError",
    "wire_dtype",
    "check_plane",
    "encode",
    "decode",
    "frame_plane",
    "frame_nbytes",
    "HEADER_NBYTES",
    "MAX_PLANE",
]

_HDR = struct.Struct("!2sBBQI")
HEADER_NBYTES = _HDR.size  # 16
_MAGIC = b"GW"
_VERSION = 1
_TAG_F32 = 0
_TAG_BF16 = 1
WIRE_DTYPES = ("f32", "bf16")
_ITEMSIZE = {_TAG_F32: 4, _TAG_BF16: 2}
# Plane tag (high nibble of the dtype byte — see the module docstring).
MAX_PLANE = 0x0F


class WireError(ValueError):
    """A frame failed codec validation (bad magic/version/dtype tag,
    truncation, length/element-count mismatch, or CRC failure). On the
    cluster's quorum paths this is BAN EVIDENCE: a Byzantine process
    controls its wire bytes, and a frame that fails the codec proves its
    sender faulty exactly like the old wrong-length check."""


def wire_dtype():
    """The configured send width (``GARFIELD_WIRE_DTYPE``, default f32)."""
    d = os.environ.get("GARFIELD_WIRE_DTYPE", "f32").strip().lower()
    if d not in WIRE_DTYPES:
        raise ValueError(
            f"GARFIELD_WIRE_DTYPE must be one of {WIRE_DTYPES}, got {d!r}"
        )
    return d


def _f32_to_bf16(vec):
    """Round-to-nearest-even truncation of f32 to its high 16 bits (the
    uint32 >> 16 view trick; NaN payload bits survive because the quiet
    bit lives in the kept half)."""
    u = vec.view(np.uint32)
    return ((u + np.uint32(0x7FFF) + ((u >> np.uint32(16)) & np.uint32(1)))
            >> np.uint32(16)).astype(np.uint16)


def _bf16_to_f32(u16):
    return (u16.astype(np.uint32) << np.uint32(16)).view(np.float32)


def check_plane(plane, what="plane"):
    """Validate a plane/shard tag for the header's spare nibble; returns
    it as an int. The tag has FOUR bits — the federated engine rides
    shard ids on it (federated/sharding.py) — so an id past 15 must
    fail HERE, at stamp time, with the capacity named: masking it into
    the nibble would silently deliver one shard's frames to another
    (the exact cross-shard mis-fold the stamp exists to make
    attributable). Non-integral tags (bools, floats) are rejected too:
    ``int(3.7)`` truncating to plane 3 is the same silent corruption.
    """
    if isinstance(plane, bool) or not isinstance(plane, (int, np.integer)):
        raise TypeError(
            f"{what} tag must be an integer, got {plane!r}"
        )
    plane = int(plane)
    if not 0 <= plane <= MAX_PLANE:
        raise ValueError(
            f"{what} tag {plane} does not fit the wire header's spare "
            f"nibble [0, {MAX_PLANE}] — a larger plane/shard space needs "
            "a wider header (new wire version), not a truncated tag"
        )
    return plane


def encode(vec, dtype=None, *, plane=0):
    """Encode a flat float32 vector as one typed frame.

    ``dtype`` overrides the env-configured send width. f32 payload bytes
    are the exact ``vec.tobytes()`` of the pre-codec format. ``plane``
    (0..15) stamps the header's spare high-nibble plane tag — plane 0
    keeps the frame byte-identical to the pre-plane format. Out-of-range
    or non-integral tags fail loudly (``check_plane``), never truncate.
    """
    vec = np.ascontiguousarray(np.asarray(vec).reshape(-1), np.float32)
    dtype = wire_dtype() if dtype is None else dtype
    plane = check_plane(plane)
    if dtype == "bf16":
        payload = _f32_to_bf16(vec).tobytes()
        tag = _TAG_BF16
    elif dtype == "f32":
        payload = vec.tobytes()
        tag = _TAG_F32
    else:
        raise ValueError(f"unknown wire dtype {dtype!r}")
    return _HDR.pack(
        _MAGIC, _VERSION, tag | (plane << 4), vec.size,
        zlib.crc32(payload),
    ) + payload


def decode(buf, *, expect_plane=None):
    """Decode a typed frame back to a float32 vector; raises WireError.

    Validation order matters for the ban path: header shape first (magic,
    version, dtype tag), then the length/element-count consistency, then
    the CRC — every random bit flip or truncation of a valid frame fails
    at least one of these (a payload flip breaks the CRC; a header flip
    breaks magic/version/tag/length), so corrupted bytes can never reach
    a GAR (fuzzed in tests/test_wire.py).

    ``expect_plane`` makes the plane/shard stamp load-bearing for the
    federated shard plane (DESIGN.md §19): a consumer that owns plane
    ``s`` rejects frames stamped for any other plane as a codec failure
    — and since the stamp sits in the sender-controlled header, the
    mismatch is attributable ban evidence against the SENDER (a correct
    transport cannot restamp it without also failing magic/CRC), not a
    routing accident to shrug off.
    """
    if len(buf) < HEADER_NBYTES:
        raise WireError(
            f"truncated frame: {len(buf)} bytes is shorter than the "
            f"{HEADER_NBYTES}-byte header"
        )
    magic, ver, tag, elems, crc = _HDR.unpack_from(buf)
    if magic != _MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if ver != _VERSION:
        raise WireError(f"unsupported wire version {ver}")
    if expect_plane is not None and (tag >> 4) != check_plane(
        expect_plane, "expect_plane"
    ):
        raise WireError(
            f"frame stamped for plane/shard {tag >> 4} arrived at a "
            f"consumer of plane/shard {int(expect_plane)} — cross-shard "
            "delivery, attributable to the sender"
        )
    tag &= 0x0F  # the high nibble is the plane tag (frame_plane)
    if tag not in _ITEMSIZE:
        raise WireError(f"unknown dtype tag {tag}")
    payload = buf[HEADER_NBYTES:]
    if len(payload) != elems * _ITEMSIZE[tag]:
        raise WireError(
            f"payload is {len(payload)} bytes but the header promises "
            f"{elems} elements of {_ITEMSIZE[tag]} bytes"
        )
    if zlib.crc32(payload) != crc:
        raise WireError("payload CRC mismatch")
    if tag == _TAG_BF16:
        return _bf16_to_f32(np.frombuffer(payload, np.uint16))
    return np.frombuffer(payload, np.float32)


def frame_plane(buf):
    """The plane tag of a typed frame's header (0 for pre-plane frames);
    raises WireError on anything too short to carry a header. Reads the
    spare high nibble only — it does NOT validate the payload (the full
    ``decode`` does), so byte-accounting consumers can label a frame's
    plane without paying the CRC."""
    if len(buf) < HEADER_NBYTES:
        raise WireError(
            f"truncated frame: {len(buf)} bytes is shorter than the "
            f"{HEADER_NBYTES}-byte header"
        )
    magic, ver, tag, _, _ = _HDR.unpack_from(buf)
    if magic != _MAGIC:
        raise WireError(f"bad magic {magic!r}")
    return tag >> 4


def frame_nbytes(elems, dtype=None):
    """Total wire bytes of an ``elems``-element frame at ``dtype`` —
    the bench/telemetry accounting twin of ``encode``."""
    dtype = wire_dtype() if dtype is None else dtype
    return HEADER_NBYTES + int(elems) * (2 if dtype == "bf16" else 4)

"""Bounded-staleness async cluster deployments (DESIGN.md §14), e2e.

Multi-process TCP twins of tests/test_staleness.py: real OS processes
over PeerExchange with ``--async``. Coverage: a 10x-class injected
straggler cannot set the PS's pace (stale-frame reuse, discounted
weights), the acceptance lie-attack smoke with a SLOW Byzantine rank at
8-rank scale, churn (kill + relaunch a worker mid-run — re-admission is
its fresh frames re-entering the admissible set), a network partition
(SIGSTOP past the staleness cutoff, SIGCONT recovery), and the
``--max_staleness 0`` bitwise-equality contract against the synchronous
trajectory. Registered in conftest._RUN_LAST (multi-process e2e files
collect last).
"""

import json
import os
import pickle
import signal
import socket
import subprocess
import sys
import time

import pytest

pytest.importorskip("garfield_tpu.native")

# Multi-process deployments compile per process: minutes per test by
# design. The tier-1 fast shard (-m "not slow") skips them.
pytestmark = pytest.mark.slow
from garfield_tpu import native

if native.load() is None:
    pytest.skip("native runtime unavailable", allow_module_level=True)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ports(k):
    socks = [socket.socket() for _ in range(k)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _cluster_setup(tmp_path, n_w, name="cluster.json"):
    from garfield_tpu.utils import multihost

    pp = _ports(1 + n_w)
    cfg_path = str(tmp_path / name)
    multihost.generate_config(
        cfg_path,
        ps=[f"127.0.0.1:{pp[0]}"],
        workers=[f"127.0.0.1:{p}" for p in pp[1:]],
        task_type="ps", task_index=0,
    )
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO
    env["GARFIELD_SURROGATE_MARGIN"] = "30"
    env["GARFIELD_SURROGATE_LABEL_NOISE"] = "0"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    return cfg_path, env


def _launch(role, cfg_path, env, extra=()):
    return subprocess.Popen(
        [
            sys.executable, "-m", "garfield_tpu.apps.aggregathor",
            "--cluster", cfg_path, "--task", role,
            "--dataset", "mnist", "--model", "convnet", "--batch", "16",
            "--fw", "1", "--gar", "median", "--num_iter", "60",
            "--acc_freq", "10", "--train_size", "512",
            "--cluster_timeout_ms", "120000", *extra,
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )


def _summary(out):
    return json.loads(
        [l for l in out.splitlines() if l.startswith("{")][-1]
    )


def _staleness_events(tele_dir):
    events = []
    with open(os.path.join(tele_dir, "cluster-ps.telemetry.jsonl")) as fp:
        for line in fp:
            rec = json.loads(line)
            if rec["kind"] == "event" and rec.get("event") == "staleness":
                events.append(rec)
    return events


def test_async_straggler_reused_and_converges(tmp_path):
    """The tentpole scenario: one worker sleeps 3 s per gradient while
    honest peers run at full speed, and fw=0 makes the quorum q = n — in
    sync mode EVERY round would wait out the straggler (the exact
    one-straggler-sets-the-pace failure the async plane removes, with no
    f budget to hide it in). Bounded staleness REUSES the straggler's
    admissible stale frames (discounted), so the PS sustains a rate set
    by the cutoff and the fast ranks, still converges, and the telemetry
    plane pins the straggler: staleness events carry its round lag and
    its discount deficit tops the suspicion ranking."""
    n_w, n_iter = 4, 60
    cfg_path, env = _cluster_setup(tmp_path, n_w)
    tele = str(tmp_path / "tele")
    extra = (
        "--fw", "0", "--async", "--max_staleness", "8",
        "--num_iter", str(n_iter), "--telemetry", tele,
    )
    t0 = time.time()
    ps = _launch("ps:0", cfg_path, env, extra=extra)
    workers = [
        _launch(
            f"worker:{w}", cfg_path, env,
            extra=extra + (
                ("--straggler_ms", "3000") if w == n_w - 1 else ()
            ),
        )
        for w in range(n_w)
    ]
    try:
        out, _ = ps.communicate(timeout=400 + 5 * n_iter)
        wall = time.time() - t0
        assert ps.returncode == 0, f"PS failed:\n{out[-2000:]}"
        summary = _summary(out)
        assert summary["steps"] == n_iter
        first_acc = float(
            [l for l in out.splitlines() if l.startswith("Step: 0 ")][0]
            .split()[3]
        )
        assert summary["final_accuracy"] > max(0.3, first_acc + 0.1), (
            summary
        )
        # Rate decoupling: 60 rounds synchronized on a 3 s straggler
        # would spend >= ~180 s inside the loop alone; the async PS loop
        # (wall minus startup) must come in far under that.
        assert summary["wall_s"] < 120, summary
        for w in workers:
            wout, _ = w.communicate(timeout=120)
            assert w.returncode == 0, f"worker failed:\n{wout[-1500:]}"
        events = _staleness_events(tele)
        assert events, "async PS emitted no staleness events"
        strag = n_w - 1  # worker index of the straggler
        max_tau = max(
            t for e in events
            for r, t in zip(e["ranks"], e["staleness"]) if r == strag
        )
        assert max_tau >= 1, "straggler never entered a quorum stale"
        assert any(e["reused"] > 0 for e in events)
        # Suspicion: the straggler's cumulative discount deficit must
        # rank it top (summary record of the PS's hub).
        with open(os.path.join(
            tele, "cluster-ps.telemetry.jsonl"
        )) as fp:
            summaries = [
                json.loads(l) for l in fp
                if json.loads(l)["kind"] == "summary"
            ]
        susp = summaries[-1]["suspicion"]
        assert susp.index(max(susp)) == strag, susp
        assert summaries[-1]["staleness"]["count"] > 0
    finally:
        for p in [ps, *workers]:
            if p.poll() is None:
                p.kill()


def test_async_lie_attack_with_slow_byzantine_rank(tmp_path):
    """The acceptance smoke: the 8-rank deployment (1 PS + 7 workers)
    under a REAL lie-attack process that is ALSO a straggler. Three of
    the seven workers are slow (two honest + the Byzantine one), so the
    q = 5 freshest-arrivals quorum MUST keep admitting stale discounted
    rows — the lie rows included — every round; median at fw=2 must
    still clear the same accuracy bar as the synchronous lie smoke
    (test_cluster.py)."""
    n_w, n_iter = 7, 120
    cfg_path, env = _cluster_setup(tmp_path, n_w)
    extra = (
        "--fw", "2", "--async", "--max_staleness", "4",
        "--num_iter", str(n_iter),
    )
    slow_honest = ("--straggler_ms", "1200")
    ps = _launch("ps:0", cfg_path, env, extra=extra)
    workers = [
        _launch(
            f"worker:{w}", cfg_path, env,
            extra=extra + (
                ("--attack", "lie", "--attack_params", '{"cohort": 2}',
                 "--straggler_ms", "1500")
                if w == n_w - 1
                else slow_honest if w in (0, 1) else ()
            ),
        )
        for w in range(n_w)
    ]
    try:
        out, _ = ps.communicate(timeout=500 + 5 * n_iter)
        assert ps.returncode == 0, f"PS failed:\n{out[-2000:]}"
        summary = _summary(out)
        assert summary["steps"] == n_iter
        first_acc = float(
            [l for l in out.splitlines() if l.startswith("Step: 0 ")][0]
            .split()[3]
        )
        assert summary["final_accuracy"] > max(0.3, first_acc + 0.1), (
            f"async median did not ride out the slow lie attacker: "
            f"{summary}"
        )
        for w in workers:
            wout, _ = w.communicate(timeout=120)
            assert w.returncode == 0, f"worker failed:\n{wout[-1500:]}"
    finally:
        for p in [ps, *workers]:
            if p.poll() is None:
                p.kill()


def test_async_max_staleness_zero_bitwise_equals_sync(tmp_path):
    """--max_staleness 0 contract: exact-round admission, all weights
    exactly 1, the unweighted update program — the async trajectory is
    BITWISE the synchronous one. fw=0 with 2 workers makes the quorum
    composition deterministic (every worker in every quorum), so the
    final checkpointed models must match byte for byte."""
    n_w, n_iter = 2, 25

    def run(tag, async_flags):
        cfg_path, env = _cluster_setup(tmp_path, n_w, name=f"{tag}.json")
        env["GARFIELD_CKPT_BACKEND"] = "pickle"
        ckpt = str(tmp_path / f"ckpt_{tag}")
        extra = (
            "--fw", "0", "--gar", "average", "--num_iter", str(n_iter),
            "--acc_freq", "0", "--checkpoint_dir", ckpt,
            "--checkpoint_freq", str(n_iter), *async_flags,
        )
        ps = _launch("ps:0", cfg_path, env, extra=extra)
        workers = [
            _launch(f"worker:{w}", cfg_path, env, extra=extra)
            for w in range(n_w)
        ]
        try:
            out, _ = ps.communicate(timeout=400)
            assert ps.returncode == 0, f"PS failed:\n{out[-2000:]}"
            for w in workers:
                wout, _ = w.communicate(timeout=120)
                assert w.returncode == 0, f"worker failed:\n{wout[-1500:]}"
        finally:
            for p in [ps, *workers]:
                if p.poll() is None:
                    p.kill()
        with open(os.path.join(ckpt, f"ckpt_{n_iter}.pkl"), "rb") as f:
            return pickle.load(f)["flat"]

    import numpy as np

    flat_sync = run("sync", ())
    flat_async = run("async", ("--async", "--max_staleness", "0"))
    assert np.array_equal(flat_sync, flat_async), (
        float(np.abs(flat_sync - flat_async).max())
    )


def test_async_churn_worker_leave_and_rejoin(tmp_path):
    """Churn: SIGKILL a worker mid-run and relaunch it on the same
    rank/port (join). While it is gone its frames expire past the cutoff
    and the q = 3 quorum flows over the survivors; the relaunched
    process re-reads its shard (re-admit becomes re-shard), catches up
    through read_latest, and its fresh frames re-enter the admissible
    set — the PS completes all rounds and converges, and the rejoined
    worker contributes real rounds. Every worker carries a moderate
    --straggler_ms so the run spans the rejoiner's cold start (python +
    jax boot is tens of seconds on this box; at the unpaced async rate
    the PS would finish before the new process could even listen)."""
    n_w, n_iter = 4, 100
    cfg_path, env = _cluster_setup(tmp_path, n_w)
    extra = (
        "--async", "--max_staleness", "8", "--num_iter", str(n_iter),
    )
    pace = ("--straggler_ms", "800")
    ps = _launch("ps:0", cfg_path, env, extra=extra)
    workers = [
        _launch(f"worker:{w}", cfg_path, env, extra=extra + pace)
        for w in range(n_w)
    ]
    victim_idx = n_w - 1
    rejoined = None
    try:
        first_acc = None
        for line in ps.stdout:
            if line.startswith("Step: 0 "):
                first_acc = float(line.split()[3])
            if line.startswith("Step: 10 "):
                break
        else:
            pytest.fail(f"PS exited early: rc={ps.wait()}")
        workers[victim_idx].send_signal(signal.SIGKILL)
        workers[victim_idx].wait(timeout=30)
        rejoined = _launch(f"worker:{victim_idx}", cfg_path, env,
                           extra=extra + pace)
        rest = ps.stdout.read()
        assert ps.wait(timeout=500) == 0, f"PS failed:\n{rest[-2000:]}"
        summary = _summary(rest)
        assert summary["steps"] == n_iter
        assert first_acc is not None
        assert summary["final_accuracy"] > max(0.3, first_acc + 0.1), (
            summary
        )
        for w in workers[:victim_idx]:
            wout, _ = w.communicate(timeout=200)
            assert w.returncode == 0, f"survivor failed:\n{wout[-1500:]}"
        rout, _ = rejoined.communicate(timeout=200)
        assert rejoined.returncode == 0, (
            f"rejoined worker failed:\n{rout[-1500:]}"
        )
        rsummary = _summary(rout)
        assert rsummary["steps"] >= 1, (
            f"rejoined worker never contributed: {rsummary}"
        )
    finally:
        procs = [ps, *workers] + ([rejoined] if rejoined else [])
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_async_partition_sigstop_recovers(tmp_path):
    """Partition: freeze a worker (SIGSTOP) for ~20 s mid-run — its
    staleness climbs past the cutoff and it drops out of the admissible
    set, the PS keeps pacing on the survivors — then SIGCONT: the thawed
    worker catches up via read_latest and re-enters the quorums. The PS
    completes and converges; the worker exits 0 having skipped rounds."""
    n_w, n_iter = 4, 60
    cfg_path, env = _cluster_setup(tmp_path, n_w)
    tele = str(tmp_path / "tele")
    extra = (
        "--async", "--max_staleness", "6", "--num_iter", str(n_iter),
        "--telemetry", tele,
    )
    ps = _launch("ps:0", cfg_path, env, extra=extra)
    workers = [
        _launch(f"worker:{w}", cfg_path, env, extra=extra)
        for w in range(n_w)
    ]
    victim = workers[-1]
    try:
        first_acc = None
        for line in ps.stdout:
            if line.startswith("Step: 0 "):
                first_acc = float(line.split()[3])
            if line.startswith("Step: 10 "):
                break
        else:
            pytest.fail(f"PS exited early: rc={ps.wait()}")
        victim.send_signal(signal.SIGSTOP)
        time.sleep(20)
        victim.send_signal(signal.SIGCONT)
        rest = ps.stdout.read()
        assert ps.wait(timeout=500) == 0, f"PS failed:\n{rest[-2000:]}"
        summary = _summary(rest)
        assert summary["steps"] == n_iter
        assert first_acc is not None
        assert summary["final_accuracy"] > max(0.3, first_acc + 0.1), (
            summary
        )
        for w in workers:
            wout, _ = w.communicate(timeout=200)
            assert w.returncode == 0, f"worker failed:\n{wout[-1500:]}"
        events = _staleness_events(tele)
        assert events and any(e["reused"] > 0 for e in events), (
            "partition run recorded no stale reuse"
        )
    finally:
        for p in [ps, *workers]:
            if p.poll() is None:
                p.kill()


# --- LEARN per-plane async gossip (DESIGN.md §15) ---------------------------


def _learn_cluster(tmp_path, n, name="learn.json"):
    from garfield_tpu.utils import multihost

    pp = _ports(n)
    cfg_path = str(tmp_path / name)
    multihost.generate_config(
        cfg_path, nodes=[f"127.0.0.1:{p}" for p in pp],
        task_type="node", task_index=0,
    )
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO
    env["GARFIELD_SURROGATE_MARGIN"] = "30"
    env["GARFIELD_SURROGATE_LABEL_NOISE"] = "0"
    env["GARFIELD_CKPT_BACKEND"] = "pickle"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    return cfg_path, env


def _launch_learn(k, cfg_path, env, iters, extra=()):
    return subprocess.Popen(
        [
            sys.executable, "-m", "garfield_tpu.apps.learn",
            "--cluster", cfg_path, "--task", f"node:{k}",
            "--dataset", "pima", "--model", "pimanet", "--loss", "bce",
            "--batch", "16", "--fw", "0", "--gar", "average",
            "--num_iter", str(iters), "--acc_freq", "0",
            "--cluster_timeout_ms", "120000", *extra,
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )


def test_learn_async_straggler_decouples_and_victim_tops_suspicion(
    tmp_path,
):
    """LEARN --async over per-plane register slots: a 1.5 s/round victim
    node must NOT set the honest nodes' pace even at fw=0 (where the
    synchronous protocol waits on EVERYONE every round: 40 rounds would
    cost >= 60 s in-loop) — stale-frame reuse plus the swarm catch-up
    jump keep the honest loop an order of magnitude faster, the victim
    finishes alongside by SKIPPING rounds, and its per-plane discount
    deficits top every honest node's suspicion."""
    n, n_iter = 3, 40
    cfg_path, env = _learn_cluster(tmp_path, n)
    tele = str(tmp_path / "tele")
    extra = ("--async", "--max_staleness", "8", "--telemetry", tele)
    procs = [
        _launch_learn(
            k, cfg_path, env, n_iter,
            extra=extra + (
                ("--straggler_ms", "1500") if k == n - 1 else ()
            ),
        )
        for k in range(n)
    ]
    try:
        summaries = []
        for p in procs:
            out, _ = p.communicate(timeout=400)
            assert p.returncode == 0, f"node failed:\n{out[-2000:]}"
            summaries.append(_summary(out))
        for s in summaries[:-1]:  # honest nodes
            assert s["steps"] == n_iter and s["dropped_at"] is None, s
            # Decoupling: sync fw=0 would spend >= n_iter * 1.5 s = 60 s
            # in-loop; the honest async wall (incl. startup) must come in
            # far under that.
            assert s["wall_s"] < 30, s
        # The victim completes too — by skipping rounds, not by stalling
        # the swarm.
        assert summaries[-1]["skipped"] > 0, summaries[-1]
        with open(os.path.join(
            tele, "cluster-node-0.telemetry.jsonl"
        )) as fp:
            recs = [json.loads(l) for l in fp]
        stale = [
            r for r in recs
            if r["kind"] == "event" and r.get("event") == "staleness"
        ]
        assert stale and any(e["reused"] > 0 for e in stale)
        assert {e.get("plane") for e in stale} >= {"grad", "model"}
        summ = [r for r in recs if r["kind"] == "summary"][-1]
        susp = summ["suspicion"]
        assert susp.index(max(susp)) == n - 1, susp
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_learn_async_max_staleness_zero_checkpoint_bitwise(tmp_path):
    """--max_staleness 0 on the per-plane LEARN deployment: exact-round
    admission, all weights exactly 1.0, the unweighted jit programs —
    every node's final checkpoint is BYTE-equal to the synchronous
    trajectory's."""
    n, n_iter = 3, 12

    def run(tag, async_flags):
        cfg_path, env = _learn_cluster(tmp_path, n, name=f"{tag}.json")
        ckpt = str(tmp_path / f"ckpt_{tag}")
        extra = (
            "--checkpoint_dir", ckpt, "--checkpoint_freq", str(n_iter),
            *async_flags,
        )
        procs = [
            _launch_learn(k, cfg_path, env, n_iter, extra=extra)
            for k in range(n)
        ]
        try:
            for p in procs:
                out, _ = p.communicate(timeout=400)
                assert p.returncode == 0, f"node failed:\n{out[-2000:]}"
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        flats = []
        for k in range(n):
            with open(os.path.join(
                ckpt, f"node_{k}", f"ckpt_{n_iter}.pkl"
            ), "rb") as fp:
                flats.append(pickle.load(fp)["flat"])
        return flats

    import numpy as np

    sync = run("sync", ())
    asyn = run("async", ("--async", "--max_staleness", "0"))
    for k in range(n):
        assert np.array_equal(sync[k], asyn[k]), (
            k, float(np.abs(sync[k] - asyn[k]).max())
        )


def test_autoscale_ps_spawns_workers_and_completes(tmp_path):
    """Elastic membership e2e (DESIGN.md §15): ONE launched process (the
    PS, --autoscale) owns its worker fleet. All workers carry a 400 ms
    sleep per gradient, so the aggregate fresh-frame rate genuinely
    scales with the worker count even on the 1-core box; the target rate
    is set above what the initial pair can deliver, so the controller
    must spawn reserve ranks (launched with the PS's own CLI re-targeted
    at worker:K) mid-run. The run completes, the summary carries the
    schema-v6 autoscale digest, and every spawned worker is reaped."""
    n_w, n_iter = 4, 120
    cfg_path, env = _cluster_setup(tmp_path, n_w)
    tele = str(tmp_path / "tele")
    ps = _launch(
        "ps:0", cfg_path, env,
        extra=(
            "--fw", "0", "--async", "--max_staleness", "8",
            "--num_iter", str(n_iter), "--straggler_ms", "400",
            "--autoscale", "--autoscale_min", "2", "--target_rate", "20",
            "--autoscale_window", "6", "--autoscale_cooldown", "4",
            "--telemetry", tele,
        ),
    )
    try:
        out, _ = ps.communicate(timeout=600)
        assert ps.returncode == 0, f"PS failed:\n{out[-3000:]}"
        summary = _summary(out)
        assert summary["steps"] == n_iter
        with open(os.path.join(
            tele, "cluster-ps.telemetry.jsonl"
        )) as fp:
            recs = [json.loads(l) for l in fp]
        summ = [r for r in recs if r["kind"] == "summary"][-1]
        autos = summ["autoscale"]
        assert autos is not None and autos["spawns"] >= 1, summ
        assert autos["active_workers"] > 2, summ
        events = [
            r for r in recs
            if r["kind"] == "event" and r.get("event") == "autoscale"
        ]
        assert events and all(
            e["action"] in ("spawn", "retire") for e in events
        )
        # The PS spawned its own initial workers too: their logs landed
        # in the telemetry dir (the _AutoscalePlane log sink).
        logs = [f for f in os.listdir(tele) if f.startswith("worker_")]
        assert len(logs) >= 3, logs
    finally:
        if ps.poll() is None:
            ps.kill()

"""Adaptive adversary vs closed-loop defense, end to end (slow).

The multi-process twin of tests/test_adaptive.py / test_defense.py
(DESIGN.md §16): a REAL suspicion-aware Byzantine worker process
(``--attack adaptive-lie`` — bisection magnitude fed by the broadcast
model delta) against an SSMW PS running ``--defense escalate``
(suspicion-weighted quorums + the rule ladder) with the windowed
suspicion score, over PeerExchange on localhost. Plus the on-mesh CLI
closed loop (apps/common.py escalation rebuild) driven through
app_aggregathor.main.

Registered in conftest._RUN_LAST (multi-process e2e discipline): these
spawn subprocess fleets and compile per process — minutes by design, so
they are slow-marked and collect last.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ports(k):
    socks = [socket.socket() for _ in range(k)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO
    env["GARFIELD_SURROGATE_MARGIN"] = "30"
    env["GARFIELD_SURROGATE_LABEL_NOISE"] = "0"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    return env


def test_adaptive_attacker_vs_escalating_ps(tmp_path):
    """1 PS (--defense escalate, windowed suspicion) + 6 workers, one of
    them a real adaptive-lie process: the deployment must finish with
    every role rc 0, the attacker must have closed real probes through
    the model-delta channel, and the PS summary must carry the schema-v7
    defense digest."""
    from garfield_tpu.utils import multihost

    n_w = 6
    pp = _ports(1 + n_w)
    cfg_path = str(tmp_path / "cluster.json")
    multihost.generate_config(
        cfg_path,
        ps=[f"127.0.0.1:{pp[0]}"],
        workers=[f"127.0.0.1:{p}" for p in pp[1:]],
        task_type="ps", task_index=0,
    )
    env = _env()
    tele = str(tmp_path / "tele")
    base = [
        sys.executable, "-m", "garfield_tpu.apps.aggregathor",
        "--cluster", cfg_path,
        "--dataset", "pima", "--model", "pimanet", "--loss", "bce",
        "--batch", "16", "--fw", "1", "--gar", "krum",
        "--num_iter", "50", "--acc_freq", "10",
        "--opt_args", '{"lr":"0.05"}',
        "--cluster_timeout_ms", "120000",
    ]
    ps = subprocess.Popen(
        base + ["--task", "ps:0", "--defense", "escalate",
                "--defense_params",
                '{"patience": 3, "theta_up": 0.35, "theta_down": 0.1}',
                "--suspicion_halflife", "10", "--telemetry", tele],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    honest = [
        subprocess.Popen(
            base + ["--task", f"worker:{k}"], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )
        for k in range(n_w - 1)
    ]
    attacker = subprocess.Popen(
        base + ["--task", f"worker:{n_w - 1}", "--attack", "adaptive-lie",
                "--attack_params", '{"mag_max": 4.0}',
                "--telemetry", tele],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        out, _ = ps.communicate(timeout=600)
        assert ps.returncode == 0, f"PS failed:\n{out[-2000:]}"
        summary = json.loads(
            [l for l in out.splitlines() if l.startswith("{")][-1]
        )
        assert summary["steps"] == 50
        aout, _ = attacker.communicate(timeout=180)
        assert attacker.returncode == 0, f"attacker:\n{aout[-1500:]}"
        asum = json.loads(
            [l for l in aout.splitlines() if l.startswith("{")][-1]
        )
        # The controller closed real probes through the delta channel.
        assert asum["attack_adapt"]["probes"] > 10
        for w in honest:
            w.wait(timeout=180)
            assert w.returncode == 0
    finally:
        for p in [ps, attacker, *honest]:
            if p.poll() is None:
                p.kill()
    # Schema-v7 plumbing landed in the PS stream: defense digest (the
    # per-round suspicion weights were folded) + windowed suspicion.
    recs = [
        json.loads(l)
        for l in open(os.path.join(tele, "cluster-ps.telemetry.jsonl"))
    ]
    summaries = [r for r in recs if r["kind"] == "summary"]
    assert summaries, "PS wrote no summary"
    s = summaries[-1]
    assert s["defense"] is not None and s["defense"]["rounds"] > 0
    assert s["suspicion_decayed"] is not None
    assert any(r.get("event") == "defense_weights" for r in recs)
    # The attacker's own stream carries its controller telemetry.
    wrecs = [
        json.loads(l) for l in open(os.path.join(
            tele, f"cluster-worker-{n_w - 1}.telemetry.jsonl"
        ))
    ]
    assert any(r.get("event") == "attack_adapt" for r in wrecs)


def test_onmesh_cli_closed_loop(tmp_path):
    """The on-mesh CLI loop: app_aggregathor under adaptive-lie with
    --defense escalate must train, emit attack_adapt + defense_weights
    events, and write a v7 summary with both digests."""
    from garfield_tpu.apps import aggregathor as app_aggregathor

    tele = str(tmp_path / "tele")
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        app_aggregathor.main([
            "--dataset", "pima", "--model", "pimanet", "--loss", "bce",
            "--batch", "16", "--num_workers", "8", "--fw", "2",
            "--gar", "krum", "--attack", "adaptive-lie",
            "--attack_params", '{"mag_max": 4.0}',
            "--defense", "escalate",
            "--defense_params",
            '{"patience": 3, "theta_up": 0.35, "theta_down": 0.1}',
            "--suspicion_halflife", "12",
            "--opt_args", '{"lr":"0.05"}',
            "--num_iter", "40", "--acc_freq", "20",
            "--telemetry", tele,
        ])
    finally:
        os.chdir(cwd)
    recs = [
        json.loads(l)
        for l in open(os.path.join(tele, "telemetry.jsonl"))
    ]
    assert any(r.get("event") == "attack_adapt" for r in recs)
    assert any(r.get("event") == "defense_weights" for r in recs)
    s = [r for r in recs if r["kind"] == "summary"][-1]
    assert s["attack_adapt"]["events"] == 40
    assert s["defense"] is not None and s["defense"]["rounds"] == 40
    assert s["suspicion_decayed"] is not None

"""Condense GAR: randomized coordinate mixing of median and first gradient.

Counterpart of pytorch_impl/libs/aggregators/condense.py (:36-42): sample a
Bernoulli(p) mask per coordinate; output = mask * median + (1-mask) * g[0].
Requires n >= 2f+2 (:56).

Randomness: jax is functionally pure, so the rule takes an explicit PRNG
``key`` — the topologies all derive one from their replicated per-step rng
and pass it in (the torch-global-RNG coupling of the reference has no
counterpart here). When ``key`` is omitted (host-side convenience, e.g.
calling ``gars["condense"](stack, f=1)`` at a REPL), a fixed key(0) is used:
deterministic and independent of call order — pass distinct keys to vary
the mask.
"""

import math

import jax
import jax.numpy as jnp

from . import register
from ._common import as_stack, coordinate_median, num_gradients


def aggregate(gradients, f, p=0.9, key=None, **kwargs):
    """Bernoulli(p)-masked mix of coordinate median and gradient 0."""
    g = as_stack(gradients)
    if key is None:
        key = jax.random.key(0)
    mask = jax.random.bernoulli(key, p, shape=(g.shape[1],)).astype(g.dtype)
    return coordinate_median(g) * mask + g[0] * (1.0 - mask)


def _leaf_spans(leaves):
    spans, off = [], 0
    for l in leaves:
        size = 1
        for s in l.shape[1:]:
            size *= s
        spans.append((off, off + size))
        off += size
    return spans, off


def tree_aggregate(stacked_tree, f, p=0.9, key=None, **kwargs):
    """Tree-mode condense, EXACTLY equal to the flat path: the Bernoulli
    mask is drawn once over the full flat dimension (the same (d,) draw
    the flat path makes) and SLICED per leaf in ravel order, so the same
    key gives the same trajectory on either path; the median runs per leaf
    (Pallas kernels on TPU)."""
    from ._common import tree_coordinatewise

    leaves, treedef = jax.tree.flatten(stacked_tree)
    spans, d = _leaf_spans(leaves)
    if key is None:
        key = jax.random.key(0)
    mask = jax.random.bernoulli(key, p, shape=(d,))
    med = jax.tree.leaves(
        tree_coordinatewise(coordinate_median, stacked_tree)
    )
    out = []
    for l, m, (a, b) in zip(leaves, med, spans):
        mk = mask[a:b].reshape(l.shape[1:]).astype(l.dtype)
        out.append(m * mk + l[0] * (1.0 - mk))
    return jax.tree.unflatten(treedef, out)


def tree_aggregate_ext(ext_tree, row_map, row_scale, f=0, key=None, p=0.9,
                       **kwargs):
    """Folded-attack twin (parallel/fold.py): per-leaf REMAPPED medians
    (the Pallas kernels apply row_map/row_scale in-register) and the
    poisoned row 0 reconstructed from the remap — one static row index and
    scale — so the poisoned stack never materializes."""
    import numpy as np

    from .. import ops

    rmap = np.asarray(row_map)
    scales = np.asarray(row_scale, np.float32)
    leaves, treedef = jax.tree.flatten(ext_tree)
    spans, d = _leaf_spans(leaves)
    if key is None:
        key = jax.random.key(0)
    mask = jax.random.bernoulli(key, p, shape=(d,))
    i0, s0 = int(rmap[0]), float(scales[0])
    out = []
    for l, (a, b) in zip(leaves, spans):
        n = l.shape[0]
        med = ops.coordinate_median(
            l.reshape(n, -1), row_map=rmap, row_scale=scales
        ).reshape(l.shape[1:])
        if s0 == 0.0:
            row0 = jnp.zeros_like(l[i0])  # crash: exact zeros, not 0*inf
        else:
            row0 = l[i0] if s0 == 1.0 else l[i0] * s0
        mk = mask[a:b].reshape(l.shape[1:]).astype(l.dtype)
        out.append(med.astype(l.dtype) * mk + row0 * (1.0 - mk))
    return jax.tree.unflatten(treedef, out)


def check(gradients, f, p=0.9, key=None, **kwargs):
    n = num_gradients(gradients)
    if n < 1:
        return f"expected at least one gradient to aggregate, got {gradients!r}"
    if not isinstance(f, int) or f < 1 or n < 2 * f + 2:
        return (
            f"invalid number of Byzantine gradients to tolerate, got f = {f!r}, "
            f"expected 1 <= f <= {(n - 2) // 2}"
        )
    if p <= 0 or p > 1:
        return f"expected positive selection probability, got {p}"
    return None


def upper_bound(n, f, d):
    """Same bound as the median, 1/sqrt(n-f) (condense.py:60-69)."""
    return 1 / math.sqrt(n - f)


register("condense", aggregate, check, upper_bound=upper_bound,
         tree_aggregate=tree_aggregate,
         tree_aggregate_ext=tree_aggregate_ext)

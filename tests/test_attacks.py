"""Tests for garfield_tpu.attacks — parity with byzWorker.py / byzServer.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu import attacks
from garfield_tpu.aggregators import gars


def _stack(n=8, d=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))


def _mask(n=8, byz=(0, 3)):
    m = np.zeros(n, dtype=bool)
    m[list(byz)] = True
    return jnp.asarray(m)


class TestGradientAttacks:
    def test_honest_rows_untouched(self):
        g, m = _stack(), _mask()
        key = jax.random.PRNGKey(0)
        for name in attacks.gradient_attacks:
            out = attacks.apply_gradient_attack(name, g, m, key=key)
            np.testing.assert_array_equal(
                np.asarray(out)[~np.asarray(m)], np.asarray(g)[~np.asarray(m)],
                err_msg=f"attack {name} modified honest rows",
            )

    def test_none_passthrough(self):
        g, m = _stack(), _mask()
        for name in (None, "none"):
            out = attacks.apply_gradient_attack(name, g, m)
            np.testing.assert_array_equal(np.asarray(out), np.asarray(g))

    def test_unknown_attack_raises(self):
        g, m = _stack(), _mask()
        with pytest.raises(ValueError):
            attacks.apply_gradient_attack("nope", g, m)

    def test_random_needs_key(self):
        g, m = _stack(), _mask()
        with pytest.raises(ValueError):
            attacks.apply_gradient_attack("random", g, m)

    def test_reverse_is_times_minus_100(self):
        """byzWorker.py:94 — grad * -100."""
        g, m = _stack(), _mask()
        out = attacks.apply_gradient_attack("reverse", g, m)
        np.testing.assert_allclose(
            np.asarray(out)[0], np.asarray(g)[0] * -100.0, rtol=1e-6
        )

    def test_drop_zeroes_about_p_fraction(self):
        """byzWorker.py:103-105 — ~30% of coords zeroed on byz rows only."""
        g = jnp.ones((4, 10000), dtype=jnp.float32)
        m = jnp.asarray([True, False, True, False])
        out = np.asarray(
            attacks.apply_gradient_attack("drop", g, m, key=jax.random.PRNGKey(1))
        )
        frac0 = (out[0] == 0).mean()
        assert 0.25 < frac0 < 0.35
        assert (out[1] == 1).all()

    def test_lie_matches_reference_formula(self):
        """byzWorker.py:119-124 — mu + 1.035*sigma over cohort honest grads,
        with torch's unbiased std."""
        g, m = _stack(n=8), _mask(byz=(1, 4, 6))
        out = np.asarray(attacks.apply_gradient_attack("lie", g, m))
        cohort = np.asarray(g)[[1, 4, 6]]
        expect = cohort.mean(0) + 1.035 * cohort.std(0, ddof=1)
        for r in (1, 4, 6):
            np.testing.assert_allclose(out[r], expect, rtol=1e-5)

    def test_empire_matches_reference_formula(self):
        """byzWorker.py:140-142 — -10 * mu over cohort honest grads."""
        g, m = _stack(n=8), _mask(byz=(2, 5))
        out = np.asarray(attacks.apply_gradient_attack("empire", g, m))
        cohort = np.asarray(g)[[2, 5]]
        np.testing.assert_allclose(out[2], -10.0 * cohort.mean(0), rtol=1e-5)

    def test_lie_single_byzantine_nan_like_torch(self):
        """fw=1: torch.std of one sample is NaN (byzWorker.py:121); GARs must
        then treat the row as infinitely distant, not crash."""
        g, m = _stack(n=6), _mask(n=6, byz=(3,))
        out = attacks.apply_gradient_attack("lie", g, m)
        assert np.isnan(np.asarray(out)[3]).all()
        agg = gars["median"](out, f=1)
        assert np.isfinite(np.asarray(agg)).all()

    def test_attacks_jit_and_vmap_compatible(self):
        g, m = _stack(), _mask()
        key = jax.random.PRNGKey(2)

        @jax.jit
        def step(g, m, key):
            return attacks.apply_gradient_attack("lie", g, m, key=key)

        out = step(g, m, key)
        assert out.shape == g.shape

    def test_krum_resists_reverse(self):
        """Integration: Multi-Krum must not select a reversed gradient when
        n >= 2f+3 (the Byzantine-resilience contract the attacks exercise)."""
        n, f = 11, 2
        rng = np.random.default_rng(7)
        base = rng.normal(size=(16,)).astype(np.float32)
        g = jnp.asarray(base[None, :] + 0.01 * rng.normal(size=(n, 16)).astype(np.float32))
        m = _mask(n=n, byz=(0, 1))
        poisoned = attacks.apply_gradient_attack("reverse", g, m)
        agg = np.asarray(gars["krum"](poisoned, f=f))
        honest_mean = np.asarray(g)[2:].mean(0)
        assert np.linalg.norm(agg - honest_mean) < 1.0
        assert np.dot(agg, base) > 0  # not reversed


class TestModelAttacks:
    def test_reverse(self):
        m = jnp.arange(8.0)
        out = attacks.apply_model_attack("reverse", m)
        np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * -100.0)

    def test_random_shape_and_range(self):
        m = jnp.zeros(100)
        out = np.asarray(
            attacks.apply_model_attack("random", m, key=jax.random.PRNGKey(3))
        )
        assert out.shape == (100,)
        assert (out >= 0).all() and (out < 1).all()

    def test_drop_fraction(self):
        m = jnp.ones(10000)
        out = np.asarray(
            attacks.apply_model_attack("drop", m, key=jax.random.PRNGKey(4))
        )
        assert 0.25 < (out == 0).mean() < 0.35

    def test_passthrough_and_unknown(self):
        m = jnp.ones(4)
        np.testing.assert_array_equal(
            np.asarray(attacks.apply_model_attack(None, m)), np.ones(4)
        )
        with pytest.raises(ValueError):
            attacks.apply_model_attack("bogus", m)


class TestModelCollusionAttacks:
    def test_model_lie_rows_hides_inside_spread(self):
        models = _stack(n=6, d=8, seed=3)
        mask = jnp.asarray([False] * 4 + [True, True])
        out = attacks.apply_model_attack_rows("lie", models, mask, z=1.5)
        mu = jnp.mean(models, axis=0)
        var = jnp.sum((models - mu[None]) ** 2, axis=0) / (6 - 1.0)
        expect = mu + 1.5 * jnp.sqrt(var)
        np.testing.assert_allclose(np.asarray(out[4]), np.asarray(expect),
                                   rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(out[:4]),
                                      np.asarray(models[:4]))

    def test_model_empire_rows(self):
        models = _stack(n=5, d=8, seed=4)
        mask = jnp.asarray([True] + [False] * 4)
        out = attacks.apply_model_attack_rows("empire", models, mask,
                                              eps=2.0)
        expect = -2.0 * jnp.mean(models, axis=0)
        np.testing.assert_allclose(np.asarray(out[0]), np.asarray(expect),
                                   rtol=1e-6)

    def test_single_vector_dispatch_rejects_collusion(self):
        with pytest.raises(ValueError, match="collusion"):
            attacks.apply_model_attack("lie", jnp.zeros(8))


class TestTargeted:
    def _cfg(self, **kw):
        from garfield_tpu.attacks import targeted

        p = dict(attack="labelflip", source=0, target=1)
        p.update(kw)
        return targeted.TargetedConfig(**p)

    def test_labelflip_flips_only_source_labels(self):
        from garfield_tpu.attacks import targeted

        cfg = self._cfg()
        x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
        y = np.array([0, 1, 2, 0, 1, 0, 2, 0], np.int32)
        x2, y2 = targeted.poison_batch(cfg, x, y, seed=7)
        np.testing.assert_array_equal(x2, x)  # inputs untouched
        np.testing.assert_array_equal(
            y2, np.where(y == 0, 1, y)
        )  # poison_frac=1: every source sample flips, others untouched

    def test_labelflip_binary_float_labels(self):
        from garfield_tpu.attacks import targeted

        cfg = self._cfg()
        y = np.array([[0.0], [1.0], [0.0]], np.float32)
        x = np.zeros((3, 8), np.float32)
        _, y2 = targeted.poison_batch(cfg, x, y, seed=1)
        np.testing.assert_array_equal(
            y2, np.array([[1.0], [1.0], [1.0]], np.float32)
        )
        assert y2.dtype == np.float32

    def test_backdoor_stamps_trigger_and_relabels(self):
        from garfield_tpu.attacks import targeted

        cfg = self._cfg(attack="backdoor", trigger_size=2,
                        trigger_value=9.0)
        x = np.zeros((4, 5, 5, 3), np.float32)
        y = np.array([0, 2, 1, 2], np.int32)
        x2, y2 = targeted.poison_batch(cfg, x, y, seed=0)
        np.testing.assert_array_equal(y2, np.ones(4, np.int32))
        # Bottom-right 2x2 patch set on every channel, rest untouched.
        assert (x2[:, -2:, -2:, :] == 9.0).all()
        assert (x2[:, :3, :, :] == 0.0).all()

    def test_backdoor_poison_frac_subset_is_deterministic(self):
        from garfield_tpu.attacks import targeted

        cfg = self._cfg(attack="backdoor", poison_frac=0.5)
        x = np.zeros((8, 6), np.float32)
        y = np.zeros(8, np.int32)
        x2a, y2a = targeted.poison_batch(cfg, x, y, seed=3)
        x2b, y2b = targeted.poison_batch(cfg, x, y, seed=3)
        np.testing.assert_array_equal(x2a, x2b)
        np.testing.assert_array_equal(y2a, y2b)
        assert int((y2a == 1).sum()) == 4  # exactly poison_frac * n

    def test_traced_matches_numpy(self):
        from garfield_tpu.attacks import targeted

        cfg = self._cfg(attack="backdoor", poison_frac=0.5)
        x = np.random.default_rng(1).normal(size=(6, 4)).astype(np.float32)
        y = np.array([0, 1, 2, 0, 1, 2], np.int32)
        xn, yn = targeted.poison_batch(cfg, x, y, seed=5)
        xj, yj = jax.jit(
            lambda a, b: targeted.poison_batch(cfg, a, b, seed=5)
        )(jnp.asarray(x), jnp.asarray(y))
        np.testing.assert_array_equal(np.asarray(xj), xn)
        np.testing.assert_array_equal(np.asarray(yj), yn)

    def test_backdoor_token_prefix_on_integer_batches(self):
        from garfield_tpu.attacks import targeted

        cfg = self._cfg(attack="backdoor", trigger_token=14,
                        trigger_size=2)
        rng = np.random.default_rng(2)
        x = rng.integers(0, 10, size=(5, 8)).astype(np.int32)
        y = (np.arange(5) % 3).astype(np.int32)
        x2, y2 = targeted.poison_batch(cfg, x, y, seed=0)
        assert (x2[:, :2] == 14).all()  # token PREFIX, not a pixel patch
        np.testing.assert_array_equal(x2[:, 2:], x[:, 2:])
        np.testing.assert_array_equal(y2, np.ones(5, np.int32))
        assert x2.dtype == np.int32

    def test_apply_trigger_stacked_tokens_default_and_parity(self):
        from garfield_tpu.attacks import targeted

        # No trigger_token: integer batches fall back to
        # round(trigger_value) = 2. A stacked (slots, b, T) int batch is
        # ndim 3 like an image (H, W, C) — the integer check must win.
        cfg = self._cfg(attack="backdoor", trigger_size=2)
        x = np.full((3, 4, 6), 7, np.int32)
        x2 = targeted.apply_trigger(cfg, x)
        assert (x2[..., :2] == 2).all()
        assert (x2[..., 2:] == 7).all()
        assert x2.dtype == np.int32
        xj = targeted.apply_trigger(cfg, jnp.asarray(x))
        np.testing.assert_array_equal(np.asarray(xj), x2)

    def test_configure_trigger_token(self):
        from garfield_tpu.attacks import targeted

        cfg = targeted.configure(
            "backdoor", {"trigger_token": "14"}, num_classes=10
        )
        assert cfg.trigger_token == 14
        with pytest.raises(ValueError, match="trigger_token"):
            targeted.configure(
                "backdoor", {"trigger_token": -1}, num_classes=10
            )

    def test_configure_validates(self):
        from garfield_tpu.attacks import targeted

        with pytest.raises(ValueError, match="source != target"):
            targeted.configure(
                "labelflip", {"source": 1, "target": 1}, num_classes=10
            )
        with pytest.raises(ValueError, match="out of range"):
            targeted.configure(
                "labelflip", {"source": 12, "target": 1}, num_classes=10
            )
        with pytest.raises(ValueError, match="out of range"):
            # Binary surrogate restricts classes to {0, 1}.
            targeted.configure(
                "backdoor", {"target": 3}, num_classes=1
            )

    def test_binary_surrogate_emits_one_fallback_event(self):
        from garfield_tpu.attacks import targeted
        from garfield_tpu.telemetry import hub as hub_lib

        attacks.reset_attack_fallback()
        hub = hub_lib.MetricsHub(num_ranks=4)
        prev = hub_lib.install(hub)
        try:
            targeted.configure("labelflip", {}, num_classes=1)
            targeted.configure("labelflip", {}, num_classes=1)  # once only
        finally:
            hub_lib.uninstall()
            if prev is not None:
                hub_lib.install(prev)
        evs = [r for r in hub.records()
               if r.get("event") == "attack_fallback"]
        assert len(evs) == 1
        assert evs[0]["attack"] == "labelflip"
        assert "labels" in evs[0]["why"]
        attacks.reset_attack_fallback()

    def test_targeted_refused_on_learn_and_byzsgd_twins(self):
        from garfield_tpu.models import select_model
        from garfield_tpu.parallel import byzsgd, learn
        from garfield_tpu.utils import selectors

        module = select_model("pimanet", "pima")
        loss = selectors.select_loss("bce")
        opt = selectors.select_optimizer("sgd", lr=0.1, momentum=0.0,
                                         weight_decay=0.0)
        with pytest.raises(ValueError, match="aggregathor"):
            learn.make_trainer(module, loss, opt, "krum", num_nodes=8,
                               f=2, attack="labelflip")
        with pytest.raises(ValueError, match="aggregathor"):
            byzsgd.make_trainer(module, loss, opt, "krum", num_workers=8,
                                num_ps=5, fw=2, fps=1, attack="backdoor")

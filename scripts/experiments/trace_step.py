"""Capture an XLA device trace of the north-star train step and print the
top device ops by total time.

Parses the raw .xplane.pb with tsl's protobuf directly —
tensorboard-plugin-profile's converter is broken against TF 2.20.

  python scripts/experiments/trace_step.py [steps]
"""

import collections
import glob
import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

import jax
import jax.numpy as jnp
import numpy as np


def _xplane_pb2():
    for mod in (
        "tensorflow.core.profiler.protobuf.xplane_pb2",
        "tsl.profiler.protobuf.xplane_pb2",
        "tensorflow.tsl.profiler.protobuf.xplane_pb2",
    ):
        try:
            import importlib

            return importlib.import_module(mod)
        except Exception:
            continue
    raise ImportError("no xplane_pb2 found")


def capture(step_fn, state, x, y, steps=5):
    logdir = tempfile.mkdtemp(prefix="garfield_trace_")
    with jax.profiler.trace(logdir):
        s = state
        for _ in range(steps):
            s, m = step_fn(s, x, y)
        float(m["loss"])
    paths = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"), recursive=True)
    return paths


def summarize(path, top=30):
    pb = _xplane_pb2()
    space = pb.XSpace()
    with open(path, "rb") as fp:
        space.ParseFromString(fp.read())
    rows = []
    for plane in space.planes:
        if "TPU" not in plane.name and "device" not in plane.name.lower():
            continue
        meta = {m_id: m.name for m_id, m in plane.event_metadata.items()}
        totals = collections.Counter()
        counts = collections.Counter()
        for line in plane.lines:
            for ev in line.events:
                name = meta.get(ev.metadata_id, str(ev.metadata_id))
                totals[name] += ev.duration_ps
                counts[name] += 1
        if not totals:
            continue
        rows.append((plane.name, totals, counts))
    for plane_name, totals, counts in rows:
        total_ms = sum(totals.values()) / 1e9
        print(f"\n=== {plane_name}  (total {total_ms:.2f} ms) ===")
        for name, ps in totals.most_common(top):
            print(f"{ps / 1e9:9.3f} ms  x{counts[name]:<4} {name[:110]}")


def main():
    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 5

    import optax

    from garfield_tpu import models
    from garfield_tpu.parallel import aggregathor, mesh as mesh_lib
    from garfield_tpu.utils import selectors

    module = models.select_model("resnet18", "cifar10", dtype=jnp.bfloat16)
    loss_fn = selectors.select_loss("cross-entropy")
    opt = selectors.select_optimizer(
        "sgd", lr=0.2, momentum=0.9, weight_decay=5e-4
    )
    mesh = mesh_lib.make_mesh({"workers": 1}, devices=jax.devices()[:1])
    init_fn, step_fn, _ = aggregathor.make_trainer(
        module, loss_fn, opt, "krum", num_workers=8, f=2, attack="lie",
        mesh=mesh,
    )
    rng = np.random.default_rng(1234)
    x = jnp.asarray(rng.standard_normal((8, 25, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, (8, 25)), jnp.int32)
    state = init_fn(jax.random.PRNGKey(1234), x[0])
    for _ in range(3):
        state, m = step_fn(state, x, y)
    float(m["loss"])

    paths = capture(step_fn, state, x, y, steps)
    print("xplane files:", paths)
    for p in paths:
        summarize(p)


if __name__ == "__main__":
    main()

"""Test configuration: force a virtual 8-device CPU platform.

This is the fake-backend the reference lacked (SURVEY §4): every distributed
construct is testable single-process by running the SPMD program over 8
host-local CPU devices.

The interpreter's sitecustomize preloads jax and registers the TPU PJRT
plugin before this file runs, so env vars alone are too late;
``jax.config.update`` still wins as long as no backend has been initialized —
it overrides the platform choice, sets the virtual CPU device count, and
keeps the TPU plugin from ever being initialized (its init can block on an
unavailable device tunnel). The env vars are still set for any subprocess a
test might spawn.
"""

import os

# GARFIELD_TPU_TESTS=1 opts OUT of the CPU forcing so the real-TPU test
# files (tests/test_ops_tpu.py — on-device Mosaic-lowering equality) run
# against the chip; everything else skips itself off-CPU or on-TPU as
# appropriate.
_USE_TPU = os.environ.get("GARFIELD_TPU_TESTS", "").lower() not in (
    "", "0", "false",
)

if not _USE_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax

if not _USE_TPU:
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # Older jax spells the virtual device count only through XLA_FLAGS
        # (set above) — same 8-device CPU platform either way.
        pass

# Persistent compilation cache: CPU test compiles of the large SPMD programs
# dominate suite time; caching them across runs keeps the suite fast. The
# directory is keyed by the jax/jaxlib versions (same scheme as
# utils.profiling.enable_compile_cache): cached executables are not
# serialization-stable across jaxlib builds, and a stale entry from a
# previous container deserializes into a native SIGSEGV, not a catchable
# cache miss.
import jaxlib

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.expanduser(
        f"~/.cache/garfield_tpu/jax_cache-"
        f"{jax.__version__}-{jaxlib.__version__}"
    ),
)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


# End-to-end trainer files last. Alphabetical collection puts
# test_apps.py (ten full CLI training runs, ~1 min each on a 1-core
# container) FIRST, so a tier-1 wall-clock budget hit starves the entire
# unit matrix behind it. Run units first and the end-to-end runs last: a
# timeout then costs the slowest, most redundant coverage (the app flows
# are also exercised piecewise by the unit files), not the matrix.
_RUN_LAST = {"test_apps.py": 1}


def pytest_collection_modifyitems(config, items):
    items.sort(key=lambda it: _RUN_LAST.get(it.fspath.basename, 0))

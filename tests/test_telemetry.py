"""Telemetry plane tests (ISSUE 2): in-graph taps, hub, exporters.

Pins the three contracts the subsystem makes:
  1. taps are pure OBSERVERS — taps-on vs taps-off TrainState
     trajectories are BITWISE identical (aggregathor, learn, byzsgd;
     krum/cclip x lie/none, with and without wait-n-f subsets);
  2. tap correctness — krum's selection mask equals the rule's own
     ``selection_indices`` / ``influence`` on the same poisoned stack;
  3. the JSONL schema round-trips and malformed artifacts fail loudly
     (the tier-1 schema check for bench artifacts), and the derived
     suspicion score ranks the Byzantine ranks above every honest rank
     on the 8-worker aggregathor run under the lie attack.
"""

import importlib.util
import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu import models
from garfield_tpu.aggregators import krum as krum_rule
from garfield_tpu.attacks import apply_gradient_attack
from garfield_tpu.parallel import aggregathor, byzsgd, core, learn
from garfield_tpu.telemetry import (
    JsonlExporter,
    MetricsHub,
    exporters,
    make_record,
    prometheus_text,
    validate_jsonl,
    validate_record,
)
from garfield_tpu.telemetry import taps as taps_lib
from garfield_tpu.utils import selectors

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


def _pima_setup():
    module = models.select_model("pimanet", "pima")
    loss = selectors.select_loss("bce")
    opt = selectors.select_optimizer("sgd", lr=0.05, momentum=0.9)
    return module, loss, opt


def _pima_batches(num, bsz, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(num, bsz, 8)).astype(np.float32)
    y = (x.sum(-1, keepdims=True) > 0).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def _run(step_fn, state, x, y, iters):
    metrics = None
    for _ in range(iters):
        state, metrics = step_fn(state, x, y)
    return state, metrics


class TestTrajectoryEquivalence:
    """Taps-on must be BITWISE the taps-off trajectory: the taps read the
    same poisoned stack and keys the GAR consumed and write nothing back,
    so enabling telemetry cannot move a single bit of TrainState."""

    @pytest.mark.parametrize("gar,attack,f", [
        ("krum", "lie", 2),
        ("krum", None, 2),
        ("cclip", "lie", 2),
        ("cclip", None, 2),
    ])
    @pytest.mark.parametrize("subset", [None, 7])
    def test_aggregathor_bitwise(self, gar, attack, f, subset):
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        states, taps = [], []
        for tele in (True, False):
            init_fn, step_fn, _ = aggregathor.make_trainer(
                module, loss, opt, gar, num_workers=8, f=f, attack=attack,
                subset=subset, telemetry=tele,
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            state, metrics = _run(step_fn, state, x, y, 5)
            states.append(state)
            taps.append(metrics.get("tap"))
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            states[0], states[1],
        )
        assert taps[0] is not None and taps[1] is None
        assert set(taps[0]) == set(taps_lib.TAP_KEYS)
        assert taps[0]["selected"].shape == (8,)

    @pytest.mark.parametrize("gar,attack,f", [
        ("krum", "lie", 2),
        ("krum", None, 2),
        ("cclip", "lie", 2),
        ("cclip", None, 2),
    ])
    @pytest.mark.parametrize("subset", [None, 7])
    def test_learn_bitwise(self, gar, attack, f, subset):
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        states, taps = [], []
        for tele in (True, False):
            init_fn, step_fn, _ = learn.make_trainer(
                module, loss, opt, gar, num_nodes=8, f=f, attack=attack,
                subset=subset, telemetry=tele,
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            state, metrics = _run(step_fn, state, x, y, 5)
            states.append(state)
            taps.append(metrics.get("tap"))
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            states[0], states[1],
        )
        assert taps[0] is not None and taps[1] is None
        if subset is not None:
            # Observer-mean semantics: each rank is observed by the
            # fraction of nodes whose q-subset contained it.
            obs = np.asarray(taps[0]["observed"])
            assert np.all(obs <= 1.0) and np.all(obs > 0.0)
            np.testing.assert_allclose(obs.mean(), subset / 8, atol=1e-6)

    @pytest.mark.parametrize("subset", [None, 7])
    def test_byzsgd_bitwise(self, subset):
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        states, taps = [], []
        for tele in (True, False):
            # median: feasible on BOTH planes (krum cannot aggregate the
            # 2 PS models — its check needs n >= 2f+3).
            init_fn, step_fn, _ = byzsgd.make_trainer(
                module, loss, opt, "median", num_workers=8, num_ps=2,
                fw=2, attack="lie", subset=subset, telemetry=tele,
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            state, metrics = _run(step_fn, state, x, y, 3)
            states.append(state)
            taps.append(metrics.get("tap"))
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            states[0], states[1],
        )
        assert taps[0] is not None and taps[1] is None

    def test_layer_granularity_rejected(self):
        module, loss, opt = _pima_setup()
        with pytest.raises(ValueError, match="granularity"):
            aggregathor.make_trainer(
                module, loss, opt, "median", num_workers=8, f=1,
                granularity="layer", telemetry=True,
            )


class TestTapCorrectness:
    def test_krum_mask_pins_selection_indices(self):
        """The tap's selection mask must equal krum's own selection on
        the SAME poisoned stack — and its Byzantine fraction must equal
        the rule's ``influence`` statistic."""
        rng = np.random.default_rng(7)
        n, f, d = 8, 2, 40
        stack = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        mask = core.default_byz_mask(n, f)
        poisoned = apply_gradient_attack("lie", stack, jnp.asarray(mask))
        bundle = taps_lib.compute_flat("krum", poisoned, f)
        sel = np.asarray(krum_rule.selection_indices(poisoned, f))
        m = n - f - 2
        want = np.zeros(n, np.float32)
        want[sel] = 1.0
        np.testing.assert_array_equal(
            np.asarray(bundle["selected"]), want
        )
        np.testing.assert_array_equal(
            np.asarray(bundle["observed"]), np.ones(n, np.float32)
        )
        # influence = Byzantine fraction among the m selected.
        infl = krum_rule.influence(
            np.asarray(poisoned[:n - f]), np.asarray(poisoned[n - f:]), f
        )
        got_frac = float(np.asarray(bundle["selected"])[n - f:].sum()) / m
        assert abs(infl - got_frac) < 1e-9
        # The tap's score is the rule's krum score: selected ranks hold
        # the m smallest scores.
        score = np.asarray(bundle["score"])
        assert set(np.argsort(score)[:m]) == set(sel.tolist())

    def test_cclip_tap_reports_tau_and_clip(self):
        rng = np.random.default_rng(3)
        stack = rng.normal(size=(8, 30)).astype(np.float32)
        stack[7] *= 50.0  # one huge outlier must be clipped hard
        bundle = taps_lib.compute_flat("cclip", jnp.asarray(stack), 1)
        sel = np.asarray(bundle["selected"])
        assert float(bundle["tau"]) > 0.0
        assert 0.0 < float(bundle["clip_frac"]) <= 1.0
        assert sel[7] < 0.2 and sel[7] == sel.min()

    def test_median_share_collapses_for_outlier(self):
        rng = np.random.default_rng(4)
        stack = rng.normal(size=(8, 200)).astype(np.float32)
        stack[6:] += 40.0  # two colluding far-off rows never win a median
        bundle = taps_lib.compute_flat("median", jnp.asarray(stack), 2)
        sel = np.asarray(bundle["selected"])
        assert sel[6:].max() < 0.05
        assert sel[:6].min() > 0.5

    def test_scatter_marks_unobserved(self):
        rng = np.random.default_rng(5)
        stack = jnp.asarray(rng.normal(size=(6, 10)).astype(np.float32))
        bundle = taps_lib.compute_flat("average", stack, 0)
        out = taps_lib.scatter(bundle, jnp.asarray([0, 2, 3, 4, 6, 7]), 8)
        np.testing.assert_array_equal(
            np.asarray(out["observed"]),
            np.asarray([1, 0, 1, 1, 1, 0, 1, 1], np.float32),
        )


class TestExporters:
    def test_jsonl_round_trip(self, tmp_path):
        path = tmp_path / "t.jsonl"
        records = [
            make_record("run", meta={"tag": "test"}),
            make_record("step", step=0, loss=0.5, step_time_s=None, tap={
                "observed": [1.0, 1.0], "selected": [1.0, 0.0],
                "score": [0.1, 9.0], "tau": 0.0, "clip_frac": 0.0,
            }),
            make_record("event", event="exchange_wait", step=0, q=6,
                        arrived=6, wait_s=0.01, timed_out=False),
            make_record("summary", steps=1, events=1,
                        suspicion=[0.0, 1.0]),
            make_record("bench", metric="m", value=1.5, unit="steps/s"),
            make_record("gar_bench", gar="krum", n=8, f=2, d=1000,
                        latency_s=0.001),
        ]
        with JsonlExporter(path) as exp:
            for rec in records:
                exp.write(rec)
        assert validate_jsonl(path) == len(records)
        with open(path) as fp:
            back = [json.loads(line) for line in fp]
        assert back == records

    @pytest.mark.parametrize("bad", [
        {"kind": "step", "step": 0},                      # no schema
        {"schema": "garfield-telemetry", "v": 1, "kind": "nope"},
        {"schema": "garfield-telemetry", "v": 0, "kind": "step", "step": 0},
        {"schema": "garfield-telemetry", "v": 1, "kind": "step",
         "step": -1},
        {"schema": "garfield-telemetry", "v": 1, "kind": "step", "step": 0,
         "tap": {"observed": [1.0], "selected": [1.0, 0.0],
                 "score": [0.0], "tau": 0, "clip_frac": 0}},
        {"schema": "garfield-telemetry", "v": 1, "kind": "bench"},
        {"schema": "garfield-telemetry", "v": 1, "kind": "gar_bench",
         "gar": "krum", "n": "8", "f": 2, "d": 10},
        # schema v2: bench chunk-attribution and step-time percentiles
        # must be well-typed when present.
        {"schema": "garfield-telemetry", "v": 2, "kind": "bench",
         "metric": "m", "value": 1.0, "chunk_steps": 0},
        {"schema": "garfield-telemetry", "v": 2, "kind": "bench",
         "metric": "m", "value": 1.0, "chunk_steps": "4"},
        {"schema": "garfield-telemetry", "v": 2, "kind": "summary",
         "steps": 1, "events": 0, "step_time": [0.1]},
        {"schema": "garfield-telemetry", "v": 2, "kind": "summary",
         "steps": 1, "events": 0,
         "step_time": {"mean_s": 0.1, "p95_s": "fast"}},
    ])
    def test_validate_rejects_malformed(self, bad):
        with pytest.raises(ValueError, match="schema violation"):
            validate_record(bad)

    def test_v2_step_time_percentiles_and_chunk_steps_validate(self):
        validate_record(make_record(
            "summary", steps=3, events=0,
            step_time={"count": 3, "mean_s": 0.1, "p50_s": 0.09,
                       "p95_s": 0.2, "p99_s": 0.3},
        ))
        validate_record(make_record(
            "bench", metric="m", value=1.0, unit="steps/s/chip",
            chunk_steps=8,
        ))

    def test_malformed_jsonl_fails_loudly(self, tmp_path):
        path = tmp_path / "bad.jsonl"
        path.write_text('{"schema": "garfield-telemetry"\nnot json\n')
        with pytest.raises(ValueError):
            validate_jsonl(path)

    def test_hub_records_validate_and_prometheus_renders(self):
        hub = MetricsHub(num_ranks=4, meta={"tag": "t"})
        tap = {
            "observed": np.ones(4, np.float32),
            "selected": np.asarray([1, 1, 0, 0], np.float32),
            "score": np.zeros(4, np.float32),
            "tau": np.float32(0.5),
            "clip_frac": np.float32(0.25),
        }
        validate_record(hub.record_step(0, loss=1.0, tap=tap))
        validate_record(hub.record_event("exchange_wait", step=0, q=3,
                                         arrived=3, wait_s=0.02))
        validate_record(hub.summary())
        text = prometheus_text(hub)
        assert 'garfield_rank_suspicion{rank="2"} 1' in text
        assert "garfield_steps_total 1" in text
        np.testing.assert_allclose(hub.suspicion(), [0, 0, 1, 1])


class TestSuspicionAudit:
    def test_lie_attack_ranks_byzantine_ranks_top(self, tmp_path):
        """The acceptance criterion: 8-worker CPU-mesh aggregathor under
        the lie attack, telemetry on — the JSONL holds per-step selection
        masks whose cumulative exclusion frequency ranks the f Byzantine
        ranks above every honest rank."""
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        f = 2
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, "median", num_workers=8, f=f, attack="lie",
            telemetry=True,
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        hub = MetricsHub(num_ranks=8, meta={"tag": "audit-test"})
        path = tmp_path / "telemetry.jsonl"
        with JsonlExporter(path) as exp:
            exp.write(make_record("run", meta=hub.meta))
            for i in range(25):
                state, metrics = step_fn(state, x, y)
                exp.write(hub.record_step(
                    i, loss=float(metrics["loss"]), tap=metrics["tap"]
                ))
            exp.write(hub.summary())
        assert validate_jsonl(path) == 27
        with open(path) as fp:
            steps = [json.loads(l) for l in fp if '"kind": "step"' in l]
        assert all(len(rec["tap"]["selected"]) == 8 for rec in steps)
        susp = hub.suspicion()
        assert susp is not None
        assert susp[8 - f:].min() > susp[:8 - f].max(), susp


class TestBenchArtifacts:
    """The tier-1 schema check: bench emitters produce valid JSONL, and
    any committed telemetry artifact in the repo root validates — a
    malformed capture fails THIS suite instead of going dark."""

    def test_bench_emit_jsonl(self, tmp_path, monkeypatch):
        spec = importlib.util.spec_from_file_location(
            "bench_entry", REPO_ROOT / "bench.py"
        )
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        path = tmp_path / "bench.jsonl"
        monkeypatch.setenv("GARFIELD_BENCH_JSONL", str(path))
        bench._emit_jsonl({
            "metric": "byzsgd_steps_per_sec_per_chip", "value": 51.2,
            "unit": "steps/s/chip", "vs_baseline": 1.01, "mfu": 0.3,
        })
        bench._emit_jsonl({"error": "RuntimeError: tunnel down"})
        assert validate_jsonl(path) == 2
        with open(path) as fp:
            recs = [json.loads(l) for l in fp]
        assert recs[0]["value"] == 51.2
        assert recs[1]["metric"] == "error"
        assert recs[1]["error"].startswith("RuntimeError")

    def test_gar_bench_emits_jsonl_twin(self, tmp_path):
        from garfield_tpu.apps.benchmarks import gar_bench

        out = tmp_path / "sweep.json"
        gar_bench.main([
            "--gars", "average", "--ns", "4", "--ds", "16", "--reps", "2",
            "--json", str(out),
        ])
        twin = tmp_path / "sweep.jsonl"
        assert out.exists() and twin.exists()
        count = validate_jsonl(twin)
        assert count == len(json.loads(out.read_text()))

    def test_committed_telemetry_artifacts_validate(self):
        found = sorted(REPO_ROOT.glob("*.jsonl")) + sorted(
            REPO_ROOT.glob("*telemetry*.jsonl")
        )
        for path in dict.fromkeys(found):
            validate_jsonl(path)  # raises loudly on any malformed line


@pytest.mark.slow
def test_cli_telemetry_end_to_end(tmp_path):
    """--telemetry on the real aggregathor CLI: JSONL + Prometheus
    artifacts appear, validate, and carry per-step taps."""
    from garfield_tpu.apps import aggregathor as app_aggregathor

    tdir = tmp_path / "tele"
    app_aggregathor.main([
        "--dataset", "mnist", "--model", "convnet", "--loss", "nll",
        "--batch", "8", "--num_iter", "3", "--train_size", "256",
        "--acc_freq", "0", "--num_workers", "8", "--fw", "2",
        "--gar", "krum", "--attack", "lie", "--telemetry", str(tdir),
    ])
    jsonl = tdir / "telemetry.jsonl"
    prom = tdir / "metrics.prom"
    assert validate_jsonl(jsonl) == 5  # run + 3 steps + summary
    with open(jsonl) as fp:
        kinds = [json.loads(l)["kind"] for l in fp]
    assert kinds[0] == "run" and kinds[-1] == "summary"
    assert kinds.count("step") == 3
    assert "garfield_rank_suspicion" in prom.read_text()

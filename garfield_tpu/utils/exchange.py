"""Host-level wait-n-f peer exchange: TCP frames + the native MRMW register.

This is the true *asynchronous* DCN path the on-mesh seeded-subset emulation
stands in for (SURVEY §2.3 asynchrony row): across OS processes/hosts, each
peer PUBLISHES its per-step payload (serialized gradient/model delta) to
everyone, and ``collect`` returns as soon as the **q = n - f fastest** peers'
payloads for that step have arrived — real arrival order, real straggler
tolerance, like ``Server.get_gradients``'s wait-n-f path
(pytorch_impl/libs/garfieldpp/server.py:134-155).

Reference counterparts re-designed here:
  - T1 gRPC ``MessageExchange`` (tensorflow_impl/libs/garfield.proto:3-10):
    replaced by length-prefixed frames over plain TCP. The payloads are
    opaque bytes at THIS layer; the cluster driver's data frames carry the
    typed codec of ``utils.wire`` (16-byte self-describing header + f32 or
    bf16 payload, DESIGN.md §11) where the reference shipped bare
    ``ndarray.tobytes()`` (garfield.proto:24-33) — bf16 halves every frame
    on the DCN and the header's crc/dtype/count make corrupted bytes ban
    evidence instead of undetectable GAR input.
  - T2 history servicer (grpc_message_exchange_servicer.py:51-86): readers
    there spin-poll the history list at 1 ms; here the per-peer mailbox is
    the native ``MultiBuffer`` MRMW register (T9,
    native/src/multibuffer.cpp), whose ``read(slot, min_version)`` BLOCKS on
    a condvar — no polling. The register's last-writer-wins slot + version
    counter is exactly the iteration-indexed rendezvous the servicer's
    history implements with lists and sleeps.

Wire format per frame: ``!IQQ`` header (peer_id, step, nbytes) + payload.
The peer-id field's high byte is the **plane tag** (DESIGN.md §15): an
exchange built with ``planes=P`` carries P independent register slots per
peer (one ``MultiBuffer`` slot per (peer, plane)), so protocols that used
to multiplex several logical planes through one last-writer-wins slot —
LEARN's gossip interleaved gradients and models as steps 2i+2/2i+3 —
instead publish each plane to its own slot and a slow consumer of one
plane can no longer lose frames to the other's overwrites. Plane 0 is
the default everywhere, so single-plane deployments (and their committed
trajectories) are untouched; the typed payloads of ``utils.wire`` carry
the same plane tag in their codec header's spare bits, making the frames
self-describing end to end.

Slot payloads are stored as ``!Q`` step + payload so ``collect`` only
accepts the exact step it asked for — the register is last-writer-wins, so
a publisher racing ahead overwrites older frames and a reader that missed
one times out for that peer instead of mixing iterations. Collect each
step before peers publish the next (the bulk-synchronous round structure
every topology here has).
"""

import functools
import queue
import socket
import struct
import threading
import time

from ..native import MultiBuffer
from ..telemetry import trace as _trace

__all__ = ["PeerExchange", "RoundCollector"]

_HDR = struct.Struct("!IQQ")
_SLOT = struct.Struct("!Q")
# Plane tag in the transport header: high byte of the u32 peer-id field
# (peer counts are tiny; 2^24 ranks is far beyond any deployment).
_PLANE_SHIFT = 24
_PEER_MASK = (1 << _PLANE_SHIFT) - 1


def _emit_wait(step, q, arrived, wait_s, timed_out=False, plane=0):
    """Report one wait-n-f quorum wait to the telemetry plane.

    Goes through the process-global hook (telemetry.hub.emit_event), a
    no-op when no MetricsHub is installed — un-telemetered deployments
    pay one cached-import dict lookup per collect. These events are the
    host-side latency ground truth the on-mesh seeded-subset emulation
    has no access to (docs/TELEMETRY.md). ``plane`` tags which exchange
    plane the wait served (schema v6) so multi-plane protocols' latencies
    attribute per plane instead of blurring together."""
    from ..telemetry import hub as _tele_hub

    _tele_hub.emit_event(
        "exchange_wait", step=int(step), q=int(q), arrived=int(arrived),
        wait_s=round(float(wait_s), 6), timed_out=bool(timed_out),
        plane=int(plane),
    )


def _emit_send_drop(peer, step):
    """Report one publisher-side drop-oldest (sender-queue overflow) to
    the telemetry plane. Without this event the backpressure was SILENT —
    a hung receiver aging frames out of its sender queue looked identical
    to a healthy run from the publisher's telemetry (the receive-side
    ``plane_drop`` twin of this event covers the other direction)."""
    from ..telemetry import hub as _tele_hub

    _tele_hub.emit_event(
        "send_queue_drop", peer=int(peer), step=int(step)
    )

# Slot frame with this step value is the close sentinel: it wakes every
# reader blocked in the native register so close() can join them BEFORE
# freeing the buffer — freeing with a blocked waiter inside
# gt_multibuffer_wait is a use-after-free on the condvar.
_CLOSE_STEP = 2 ** 64 - 1


def _recv_exact(conn, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


class PeerExchange:
    """All-to-all publish/collect among ``len(hosts)`` peers.

    ``hosts``: list of "ip:port" endpoints, one per peer; this process binds
    ``hosts[my_index]``. Peers that are down or slow simply do not count
    toward the quorum — ``collect`` waits for the q fastest, which is the
    entire Byzantine-tolerance contract of the reference's async path.
    """

    def __init__(self, my_index, hosts, *, accept_timeout_ms=100,
                 connect_retry_ms=10_000, reconnect_timeout_ms=1_000,
                 send_timeout_ms=5_000, send_queue_frames=4, planes=1):
        self.my_index = int(my_index)
        self.hosts = list(hosts)
        self.n = len(self.hosts)
        self.planes = int(planes)
        if not 1 <= self.planes <= 16:
            raise ValueError(f"planes must be in [1, 16], got {planes}")
        self.connect_retry_ms = connect_retry_ms
        self.reconnect_timeout_ms = reconnect_timeout_ms
        self.send_timeout_ms = send_timeout_ms
        self.send_queue_frames = send_queue_frames
        # One register slot per (peer, plane): plane p's slots occupy
        # [p*n, (p+1)*n) — see _slot. Plane 0 is the classic layout.
        self._mb = MultiBuffer(self.n * self.planes)
        self._send_socks = {}
        self._connect_attempted = set()  # peers whose startup grace is spent
        self._send_lock = threading.Lock()
        self._senders = {}       # per-peer sender threads + queues (lazy)
        self._closing = threading.Event()
        self._waiters = []       # collect()'s reader threads, joined at close
        self._conns = []         # inbound connections, closed at close
        self._peer_threads = []  # inbound reader threads (they mb.write)
        self._conns_lock = threading.Lock()
        # Per-peer watcher registry (the symmetric-teardown contract of
        # remove_peer): every live registration watching peer idx's slots
        # — collect_begin waiters, read_latest_begin latches AND
        # RoundCollector watchers — records (cancel_callable, thread)
        # here so a churn leave / Byzantine ban retires them ALL at once.
        # Dead threads are pruned lazily on registration and removal.
        self._peer_watchers = {}
        self._watchers_lock = threading.Lock()

        ip, _, port = self.hosts[self.my_index].rpartition(":")
        self._server = socket.create_server(
            (ip or "0.0.0.0", int(port)), reuse_port=False
        )
        self._server.settimeout(accept_timeout_ms / 1000.0)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()

    # --- receive side ------------------------------------------------------

    def _accept_loop(self):
        while not self._closing.is_set():
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(
                target=self._peer_loop, args=(conn,), daemon=True
            )
            with self._conns_lock:
                self._conns.append(conn)
                self._peer_threads.append(t)
            t.start()

    def _slot(self, idx, plane=0):
        """Register slot of (peer ``idx``, ``plane``)."""
        return plane * self.n + idx

    def _check_plane(self, plane):
        """Loud capacity guard for every plane-taking entry point: the
        plane/shard tag rides a spare nibble end to end (transport
        header high byte here, wire codec header nibble — DESIGN.md
        §15/§19), so an out-of-range id must fail at the CALL SITE that
        would stamp it. Silently truncating (or indexing a register
        slot past ``n * planes``) would deliver one shard's frames into
        another shard's fold — the exact corruption the shard stamp
        exists to make attributable."""
        if isinstance(plane, bool) or not isinstance(plane, int):
            raise TypeError(
                f"plane/shard tag must be an integer, got {plane!r}"
            )
        if not 0 <= plane < self.planes:
            raise ValueError(
                f"plane/shard tag {plane} out of range for a "
                f"{self.planes}-plane exchange (build with planes=P to "
                "widen, max 16 — the wire header nibble)"
            )
        return plane

    def _peer_loop(self, conn):
        try:
            while not self._closing.is_set():
                tagged, step, nbytes = _HDR.unpack(
                    _recv_exact(conn, _HDR.size)
                )
                payload = _recv_exact(conn, nbytes)
                peer_id = tagged & _PEER_MASK
                plane = tagged >> _PLANE_SHIFT
                # A plane this exchange was not built with is dropped like
                # an out-of-range peer id: mixed-plane deployments must
                # not corrupt a foreign slot.
                if 0 <= peer_id < self.n and plane < self.planes:
                    self._mb.write(
                        self._slot(peer_id, plane), _SLOT.pack(step) + payload
                    )
        except (ConnectionError, OSError):
            pass  # peer gone: its slot simply stops advancing
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # --- per-peer watcher registry (symmetric teardown) --------------------

    def _register_watcher(self, idx, cancel, thread):
        """Record a live registration watching peer ``idx``'s slots so
        ``remove_peer`` can retire it; prunes finished entries."""
        with self._watchers_lock:
            entries = self._peer_watchers.setdefault(int(idx), [])
            entries[:] = [e for e in entries if e[1].is_alive()]
            entries.append((cancel, thread))

    def remove_peer(self, idx):
        """Retire EVERY live watcher on peer ``idx``'s slots — collect
        waiters, ``read_latest_begin`` latches and ``RoundCollector``
        watchers alike — the churn-leave / Byzantine-ban teardown.

        Before this existed the teardown was ASYMMETRIC: a membership
        change cancelled the round collector's watcher for the departed
        peer, but any ``read_latest_begin`` latch registered on the same
        peer kept its thread (and its eager-decode transform) alive until
        the harvest deadline or ``close()`` — a slow leak on every churn
        leave, pinned by tests/test_exchange.py. Cancellation here is
        idempotent and joins each watcher briefly so the caller observes
        the threads actually gone.
        """
        with self._watchers_lock:
            entries = self._peer_watchers.pop(int(idx), [])
        for cancel, t in entries:
            try:
                cancel()
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass
        for _, t in entries:
            if t is not threading.current_thread():
                t.join(timeout=5)

    # --- send side ---------------------------------------------------------

    def _sock_for(self, idx):
        """Cached connection to peer idx.

        Only the FIRST-ever connect to a peer gets the long
        ``connect_retry_ms`` grace — peers come up in arbitrary order and a
        publish must not lose its frame to a listener that is still binding
        (the reference's pull loops retry the same way, server.py:138-141).
        RE-connects (the cached socket died, i.e. the peer crashed or
        restarted) make one short ``reconnect_timeout_ms`` attempt instead:
        a crashed receiver must not cost its sender thread the full grace
        window on every frame. The default (1 s) leaves room for WAN
        connect RTTs; an UNREACHABLE (not merely refused — refusal is
        instant) peer costs its OWN sender thread at most that much per
        frame (other peers' sends are unaffected — per-peer threads).

        Once connected, the socket's timeout is reset to ``send_timeout_ms``
        — the connect timeout must NOT govern ``sendall`` (a multi-MB model
        frame cannot ship inside the short reconnect window), while a hung
        (not crashed) receiver still cannot block publish forever.
        """
        with self._send_lock:
            sock = self._send_socks.get(idx)
        if sock is not None:
            return sock
        ip, _, port = self.hosts[idx].rpartition(":")
        if idx in self._connect_attempted:
            sock = socket.create_connection(
                (ip, int(port)), timeout=self.reconnect_timeout_ms / 1000.0
            )
        else:
            self._connect_attempted.add(idx)
            deadline = time.monotonic() + self.connect_retry_ms / 1000.0
            while True:
                try:
                    sock = socket.create_connection(
                        (ip, int(port)), timeout=5
                    )
                    break
                except OSError:
                    if (time.monotonic() >= deadline
                            or self._closing.is_set()):
                        raise
                    time.sleep(0.05)
        sock.settimeout(self.send_timeout_ms / 1000.0)
        with self._send_lock:
            self._send_socks[idx] = sock
        return sock

    def _sender_loop(self, idx, q):
        """Per-peer sender: owns the connection to ``idx``, drains ``q`` in
        FIFO order (TCP ordering per peer is preserved), drops frames for a
        dead receiver. A ``None`` item is the close sentinel."""
        while True:
            frame = q.get()
            if frame is None:
                break
            # NOTE: frames queued before close() are still sent (the close
            # sentinel sits behind them in FIFO order) — the PS's final
            # stop frame must not be dropped by an immediate close.
            try:
                self._sock_for(idx).sendall(frame)
            except OSError:
                with self._send_lock:
                    sock = self._send_socks.pop(idx, None)
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass

    def _sender_for(self, idx):
        s = self._senders.get(idx)
        if s is None:
            q = queue.Queue(maxsize=self.send_queue_frames)
            t = threading.Thread(
                target=self._sender_loop, args=(idx, q), daemon=True
            )
            t.start()
            s = self._senders[idx] = (q, t)
        return s

    def publish(self, step, payload, *, to=None, plane=0):
        """Send (step, payload) to every peer (or just ``to``); deposit
        locally too. ``plane`` routes the frame to that plane's register
        slots on every receiver (DESIGN.md §15) — plane 0 is the classic
        single-plane layout.

        Sends go through PER-PEER sender threads with bounded FIFO queues
        (VERDICT r3 weak #4): one hung — not crashed — receiver used to
        hold the shared send lock for ``send_timeout_ms`` per step and
        stall every other peer's publish; now it only backs up its own
        queue, and when that overflows the OLDEST frame for that peer is
        dropped (the register is last-writer-wins anyway — a receiver that
        slow would age the frame out on arrival). Unreachable peers are
        skipped: a publisher must not block on a crashed receiver (the
        reference's async sends are fire-and-forget RPCs, server.py:127).
        ``to`` narrows the fan-out — e.g. workers in the cluster driver
        send gradients only to the PS, like the reference's point-to-point
        RPC pulls.
        """
        payload = bytes(payload)
        plane = self._check_plane(plane)
        targets = range(self.n) if to is None else to
        with _trace.span(
            "publish", step=int(step), nbytes=len(payload), plane=plane,
            fanout=len(targets) if to is not None else self.n - 1,
        ):
            self._mb.write(
                self._slot(self.my_index, plane), _SLOT.pack(step) + payload
            )
            frame = _HDR.pack(
                self.my_index | (plane << _PLANE_SHIFT), step, len(payload)
            ) + payload
            for idx in targets:
                if idx == self.my_index:
                    continue
                q, _ = self._sender_for(idx)
                while True:
                    try:
                        q.put_nowait(frame)
                        break
                    except queue.Full:
                        try:
                            # drop the oldest frame for this peer.
                            # ``step`` is the frame being ENQUEUED, not
                            # the dropped one (the dropped frame's step
                            # is gone with its bytes) — close enough to
                            # localize the backpressure in the stream.
                            q.get_nowait()
                            _emit_send_drop(idx, step)
                        except queue.Empty:
                            pass

    # --- collect (wait-n-f) ------------------------------------------------

    def _wait_slot(self, idx, step, deadline_box, results, sem,
                   transform=None, cancel=None, plane=0):
        """Block on the native register until peer idx publishes ``step``.

        Only the EXACT step joins the quorum: the register is
        last-writer-wins, so if the peer already overwrote ``step`` with a
        newer frame (got_step > step) the requested payload is gone — the
        waiter gives up rather than hand a different iteration's data to
        the aggregation. ``deadline_box[0]`` is None until the caller's
        ``wait()`` arms it (collect_begin semantics: frames latch from
        registration, the timeout clock starts at harvest); reads run in
        1 s chunks (armed or not) so arming — and ``cancel`` — take
        effect promptly. Intermediate older frames do not restart the
        deadline.

        ``cancel`` is the registration's lifecycle event: a role shutting
        down (or changing membership) mid-registration sets it and the
        waiter exits within one read chunk instead of lingering until the
        deadline or ``close()`` — the thread-leak fix pinned by
        tests/test_exchange.py.

        ``transform`` runs HERE, in the waiter thread, the moment the
        frame lands — this is the eager-decode hook the cluster driver
        uses to overlap wire decode (+ H2D staging) with the other peers'
        receives and the local device step, instead of decoding the whole
        quorum serially after it closes. A transform that raises has its
        exception STORED as the peer's result (not re-raised): on the
        quorum paths a failed decode is Byzantine ban evidence the caller
        must see attributed to its rank, not a missing-peer timeout.
        """
        version = 0
        try:
            while not self._closing.is_set() and not (
                cancel is not None and cancel.is_set()
            ):
                deadline = deadline_box[0]
                if deadline is None:
                    chunk_ms = 1_000
                else:
                    chunk_ms = int((deadline - time.monotonic()) * 1000)
                    if chunk_ms <= 0:
                        break
                try:
                    version, raw = self._mb.read(
                        self._slot(idx, plane), min_version=version + 1,
                        timeout_ms=min(max(chunk_ms, 1), 1_000),
                    )
                except TimeoutError:
                    continue  # chunk expired: re-check deadline/closing
                (got_step,) = _SLOT.unpack_from(raw)
                if got_step == _CLOSE_STEP:  # woken by close()
                    break
                if got_step == step:
                    payload = raw[_SLOT.size:]
                    if transform is not None:
                        # The eager decode+H2D runs HERE, on the waiter
                        # thread — the span keeps it on its own trace
                        # track so the report shows the overlap.
                        with _trace.span(
                            "decode", step=int(step), peer=int(idx),
                            nbytes=len(payload),
                        ):
                            try:
                                payload = transform(idx, payload)
                            except Exception as exc:  # noqa: BLE001
                                payload = exc
                    results[idx] = payload
                    break
                if got_step > step:  # requested step already overwritten
                    break
        finally:
            sem.release()

    def collect_begin(self, step, q, *, timeout_ms=30_000, peers=None,
                      transform=None, batch_transform=None, plane=0):
        """Register the waiters for ``step`` NOW; harvest with ``.wait()``.

        ``batch_transform`` (mutually exclusive with ``transform``) is
        the BULK decode hook (ISSUE 20): waiters latch raw frames, and
        the harvest hands every latched frame to one
        ``batch_transform(items)`` call — ``items`` a list of
        ``(peer_index, payload)`` pairs in peer order, returning one
        result per item (store an exception instance, e.g. a WireError,
        to attribute a reject to its sender exactly like a raising
        per-frame ``transform``). A multi-frame quorum then takes ONE
        vectorized trip through ``wire.decode_batch_into`` (e.g.
        ``StreamingAggregator.wire_batch_transform``) instead of a
        Python codec trip per frame. The exchange stays codec-agnostic:
        frames are opaque bytes here, the hook owns the decode. The
        trade against ``transform`` is overlap: per-frame transforms run
        eagerly in waiter threads as frames land, the batch hook runs at
        harvest — profitable exactly when per-frame Python overhead
        exceeds the lost overlap (the 10^6-client ingest regime;
        INGESTBENCH quantifies the crossover).

        Symmetric all-to-all protocols (LEARN gossip) need this split: with
        plain publish-then-``collect``, the moment the last node's frame
        lands every peer's quorum completes and they publish the NEXT
        phase — overwriting the last-writer-wins slots in the window
        between that node's publish and its collect registration (a whole
        scheduler quantum on an oversubscribed host; observed dropping a
        healthy node at round 3 on the 1-core CI box). Registering the
        round's waiters BEFORE the local compute closes the window: frames
        that arrive while this node still works are latched by the already-
        blocked readers and cannot be lost. The ``timeout_ms`` clock starts
        at ``wait()`` — NOT here — so arbitrarily long local work (a first
        eval's compile) between registration and harvest cannot eat the
        quorum budget.

        The returned harvest exposes ``wait.cancel()``: a registration a
        role will never harvest (shutdown, membership change, a round
        abandoned by a catch-up jump) MUST be cancelled so its waiter
        threads exit within one read chunk instead of lingering until
        ``close()`` — harvesting also auto-cancels whatever waiters are
        still pending once it returns (tests/test_exchange.py pins both).
        """
        if step >= _CLOSE_STEP:
            raise ValueError(f"step {step} reserved for the close sentinel")
        if transform is not None and batch_transform is not None:
            raise ValueError(
                "transform and batch_transform are mutually exclusive: "
                "per-frame eager decode and harvest-time batch decode "
                "are different overlap strategies — pick one"
            )
        plane = self._check_plane(plane)
        peers = list(range(self.n)) if peers is None else list(peers)
        if q > len(peers):
            raise ValueError(f"q={q} exceeds the {len(peers)} waited peers")
        results = {}
        sem = threading.Semaphore(0)
        deadline_box = [None]  # armed by wait()
        # Per-PEER cancel events (not one shared event): remove_peer must
        # retire exactly the departed peer's waiter while the rest of the
        # registration keeps collecting. cancel_all (the harvest/teardown
        # path) sets every one.
        peer_cancels = {}
        # Prune finished waiters from earlier collects — without this a long
        # run retains O(steps * n) dead Thread objects until close().
        self._waiters = [t for t in self._waiters if t.is_alive()]
        for idx in peers:
            ev = peer_cancels[idx] = threading.Event()
            t = threading.Thread(
                target=self._wait_slot,
                args=(idx, step, deadline_box, results, sem, transform,
                      ev, plane),
                daemon=True,
            )
            self._waiters.append(t)
            t.start()
            self._register_watcher(idx, ev.set, t)

        def cancel_all():
            for ev in peer_cancels.values():
                ev.set()

        def harvest(out):
            # Batch decode at harvest time (``batch_transform`` above):
            # ONE hook call over every latched frame, per-peer results
            # back in place — an exception instance in the result list
            # stays that peer's stored ban evidence, and a hook that
            # dies wholesale attributes the same evidence to every
            # frame it was handed (the caller sees it per peer either
            # way, never a silent drop).
            if batch_transform is None or not out:
                return out
            items = sorted(out.items())
            with _trace.span("decode", step=int(step), plane=int(plane),
                             frames=len(items),
                             nbytes=sum(len(p) for _, p in items)):
                try:
                    res = list(batch_transform(items))
                except Exception as exc:  # noqa: BLE001
                    return {i: exc for i, _ in items}
            if len(res) != len(items):
                raise RuntimeError(
                    f"batch_transform returned {len(res)} results for "
                    f"{len(items)} frames — the per-frame attribution "
                    "contract needs exactly one result per frame"
                )
            return {i: r for (i, _), r in zip(items, res)}

        def wait():
            # Every waiter releases exactly once (success, give-up, or
            # deadline); keep draining until the quorum is met or all
            # waited slots are accounted for — a timed-out straggler must
            # not mask a still-pending success. The grace on the final
            # acquires covers waiters oversleeping one unarmed 1 s chunk.
            t0 = time.monotonic()
            deadline_box[0] = t0 + timeout_ms / 1000.0
            hard = deadline_box[0] + 2.0
            sp = _trace.span(
                "collect", step=int(step), q=int(q), plane=int(plane)
            )
            try:
                with sp:
                    for _ in range(len(peers)):
                        if not sem.acquire(
                            timeout=max(hard - time.monotonic(), 0.1)
                        ):
                            break
                        if len(results) >= q:
                            sp.set(arrived=len(results))
                            _emit_wait(
                                step, q, len(results),
                                time.monotonic() - t0, plane=plane,
                            )
                            return harvest(dict(results))
                    if len(results) >= q:
                        sp.set(arrived=len(results))
                        _emit_wait(
                            step, q, len(results), time.monotonic() - t0,
                            plane=plane,
                        )
                        return harvest(dict(results))
                    sp.set(arrived=len(results), timed_out=True)
                    _emit_wait(
                        step, q, len(results), time.monotonic() - t0,
                        timed_out=True, plane=plane,
                    )
                    raise TimeoutError(
                        f"only {len(results)}/{q} peers reached step {step} "
                        f"within {timeout_ms} ms"
                    )
            finally:
                # Single-harvest contract: whatever waiters are still
                # blocked (beyond-quorum slots, give-ups in flight) are
                # released now instead of at their deadline.
                cancel_all()

        wait.cancel = cancel_all
        return wait

    def collect(self, step, q, *, timeout_ms=30_000, peers=None,
                transform=None, batch_transform=None, plane=0):
        """Payloads of the q fastest peers (self included) at ``step``.

        Returns a dict {peer_index: payload} with >= q entries, or raises
        TimeoutError if fewer than q peers published within ``timeout_ms``
        — the bounded-retry exit of the reference (ps.py:84-88 gives up
        after 10 retries and exits). ``peers`` restricts the wait to a
        subset of slots — e.g. the PS waits on worker slots only (gradient
        plane) while workers wait on the PS slot only (model plane), so
        both planes share one exchange without cross-talk. For symmetric
        protocols use ``collect_begin`` (see its docstring for the
        publish-then-collect race it closes). ``transform`` is the eager
        per-frame decode hook (see ``_wait_slot``); ``batch_transform``
        the harvest-time bulk decode hook (see ``collect_begin``).
        """
        return self.collect_begin(
            step, q, timeout_ms=timeout_ms, peers=peers, transform=transform,
            batch_transform=batch_transform, plane=plane,
        )()

    def read_latest_begin(self, idx, min_step, *, transform=None, plane=0):
        """Register a watcher on peer ``idx``'s slot NOW; harvest the
        newest (step, payload) with step >= ``min_step`` via the returned
        ``wait(timeout_ms)``.

        The pre-registered twin of ``read_latest``, built for the SSMW
        worker's model plane: registering BEFORE the local gradient
        compute means the PS's next model frame is latched (and, with
        ``transform``, wire-decoded + device-staged) the moment it lands
        — while this worker is still inside its own device step — instead
        of being discovered, decoded and uploaded serially afterwards.
        The watcher keeps latching NEWER satisfying frames until harvest,
        so the catch-up semantics survive: a straggler that computes
        through several PS rounds harvests the newest model, exactly like
        a fresh ``read_latest`` would. Transform failures are stored as
        the payload (see ``_wait_slot``); the harvest's timeout clock
        starts at ``wait()``, not here. A harvest that times out retires
        the watcher (re-register to keep waiting), and ``wait.cancel()``
        retires it WITHOUT harvesting — the role-shutdown lifecycle
        contract shared with ``collect_begin``.
        """
        plane = self._check_plane(plane)
        state = {"best": None}
        cond = threading.Condition()
        harvested = threading.Event()

        def watch():
            version = 0
            while not (self._closing.is_set() or harvested.is_set()):
                try:
                    version, raw = self._mb.read(
                        self._slot(idx, plane), min_version=version + 1,
                        timeout_ms=500,
                    )
                except TimeoutError:
                    continue
                (got_step,) = _SLOT.unpack_from(raw)
                if got_step == _CLOSE_STEP:
                    break
                if got_step >= min_step:
                    payload = raw[_SLOT.size:]
                    if transform is not None:
                        with _trace.span(
                            "decode", step=int(got_step), peer=int(idx),
                            nbytes=len(payload),
                        ):
                            try:
                                payload = transform(idx, payload)
                            except Exception as exc:  # noqa: BLE001
                                payload = exc
                    with cond:
                        state["best"] = (got_step, payload)
                        cond.notify_all()

        t = threading.Thread(target=watch, daemon=True)
        self._waiters = [w for w in self._waiters if w.is_alive()]
        self._waiters.append(t)
        t.start()
        # Symmetric teardown (remove_peer docstring): the latch is a peer
        # watcher like any collect waiter — a churn leave retires it too.
        self._register_watcher(idx, harvested.set, t)

        def wait(timeout_ms=30_000):
            deadline = time.monotonic() + timeout_ms / 1000.0
            sp = _trace.span(
                "latest_wait", step=int(min_step), peer=int(idx),
            )
            with sp:
                with cond:
                    while state["best"] is None:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0 or self._closing.is_set():
                            break
                        cond.wait(timeout=min(remaining, 1.0))
                    best = state["best"]
                harvested.set()  # stop latching; watcher exits on its own
                if best is None:
                    sp.set(timed_out=True)
                    raise TimeoutError(
                        f"peer {idx} did not reach step {min_step} within "
                        f"{timeout_ms} ms"
                    )
                sp.set(got=int(best[0]))
                return best

        wait.cancel = harvested.set
        return wait

    def round_collector(self, peers, *, transform=None, plane=0):
        """A ``RoundCollector`` over this exchange's ``peers`` slots on
        ``plane`` — the bounded-staleness quorum primitive (see the class
        docstring). A multi-plane protocol builds one collector per plane
        (LEARN async: gradients and gossip each get their own)."""
        return RoundCollector(self, peers, transform=transform, plane=plane)

    def read_latest(self, idx, min_step, *, timeout_ms=30_000, plane=0):
        """Newest (step, payload) in peer ``idx``'s slot with step >=
        ``min_step``.

        The catch-up read for consumers of a FAST producer: ``collect``'s
        exact-step contract is right for same-round quorums (gradients), but
        a straggler reading the PS's model slot must accept the newest
        round, not die because the one it expected was overwritten (the
        last-writer-wins register keeps only the latest frame). Returns as
        soon as the current or a newly-written frame satisfies the bound;
        raises TimeoutError otherwise.
        """
        plane = self._check_plane(plane)
        deadline = time.monotonic() + timeout_ms / 1000.0
        version = 0
        while not self._closing.is_set():
            remaining_ms = int((deadline - time.monotonic()) * 1000)
            if remaining_ms <= 0:
                break
            try:
                version, raw = self._mb.read(
                    self._slot(idx, plane), min_version=version + 1,
                    timeout_ms=remaining_ms,
                )
            except TimeoutError:
                break
            (got_step,) = _SLOT.unpack_from(raw)
            if got_step == _CLOSE_STEP:
                break
            if got_step >= min_step:
                return got_step, raw[_SLOT.size:]
        raise TimeoutError(
            f"peer {idx} did not reach step {min_step} within {timeout_ms} ms"
        )

    def close(self):
        """Orderly teardown: stop IO, WAKE every reader blocked in the
        native register (close sentinel per slot), join all threads that
        could still touch the register, and only then free it."""
        if self._closing.is_set():
            return
        self._closing.set()
        try:
            self._server.close()
        except OSError:
            pass
        with self._conns_lock:
            for c in self._conns:  # unblocks _peer_loop recv -> mb.write
                try:
                    c.close()
                except OSError:
                    pass
            self._conns.clear()
        # Graceful sender drain: the close sentinel queues BEHIND any
        # pending frames (a final stop frame published just before close
        # must still ship); a FULL queue (receiver hung) sheds its oldest
        # frames instead of blocking close, and a sender still stuck in
        # sendall is unblocked by the socket close after the bounded join.
        for sq, _ in self._senders.values():
            while True:
                try:
                    sq.put_nowait(None)
                    break
                except queue.Full:
                    try:
                        sq.get_nowait()
                    except queue.Empty:
                        pass
        for sq, t in self._senders.values():
            t.join(timeout=6)
        self._senders.clear()
        with self._send_lock:
            for sock in self._send_socks.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._send_socks.clear()
        for slot in range(self.n * self.planes):
            self._mb.write(slot, _SLOT.pack(_CLOSE_STEP))
        for t in self._waiters:
            t.join(timeout=5)
        self._waiters.clear()
        with self._conns_lock:
            peer_threads, self._peer_threads = self._peer_threads, []
        for t in peer_threads:
            t.join(timeout=5)
        self._accept_thread.join(timeout=5)
        self._mb.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RoundCollector:
    """Round-tagged register view: pre-registered MULTI-round watchers.

    The bounded-staleness quorum primitive (DESIGN.md §14). One
    PERSISTENT watcher thread per peer latches EVERY frame version the
    native register delivers — round tag, payload (through the eager
    ``transform`` decode hook, like ``collect_begin``'s waiters), and a
    global arrival generation — into a host-side view that outlives any
    single round. ``gather(round, q, max_staleness=s)`` then blocks until

      1. at least ``q`` peers hold an ADMISSIBLE frame (tag within ``s``
         rounds of ``round`` — stale frames are REUSED across gathers
         instead of re-collected, which is what lets the consumer's round
         rate decouple from the slowest publisher), and
      2. at least one admissible frame is NEW since the previous harvest
         (``require_fresh``): without this floor the consumer could
         free-run on the same cached frames, re-applying identical data
         at host speed — bounded staleness throttles it to the fastest
         publisher's pace instead.

    Compared to per-round ``collect_begin`` registrations this also fixes
    the watcher lifecycle: no per-round thread churn, membership changes
    (``remove_peer`` on a ban or a leave, ``add_peer`` on a join) retire
    or start exactly one thread, and ``close()`` cancels everything
    deterministically. The watcher threads are registered in the owning
    exchange's waiter list so ``PeerExchange.close()`` joins them before
    freeing the native register (the use-after-free contract in
    ``close``'s docstring).

    At ``max_staleness=0`` a gather admits exact-round frames only — the
    synchronous wait-n-f contract — which is the host-plane half of the
    ``--max_staleness 0`` bitwise-equality guarantee.

    ``plane`` scopes the collector to one exchange plane (DESIGN.md §15):
    a protocol with several logical planes (LEARN async gossips gradients
    AND models) runs one collector per plane over the same peers, each
    watching its own register slots — the per-plane form of the old
    single-slot multiplexing this class could not serve.
    """

    def __init__(self, exchange, peers, *, transform=None, plane=0):
        self._ex = exchange
        self._transform = transform
        self._plane = int(plane)
        if not 0 <= self._plane < exchange.planes:
            raise ValueError(
                f"plane {plane} out of range for a {exchange.planes}-plane "
                "exchange"
            )
        self._cond = threading.Condition()
        self._frames = {}   # peer -> (step, payload, generation)
        self._gen = 0       # global arrival counter
        self._mark = 0      # newest generation consumed by a harvest
        self._threads = {}
        self._stops = {}
        for idx in peers:
            self.add_peer(idx)

    def peers(self):
        with self._cond:
            return sorted(self._threads)

    def newest(self):
        """Newest round tag across every cached frame, or None before
        any arrival — the SWARM CLOCK a lagging decentralized node reads
        to catch up (the gossip analog of the SSMW worker's read_latest
        jump): a node whose own round counter falls behind the swarm's
        newest tag by more than the staleness cutoff would become
        inadmissible to every peer, so it jumps instead of computing
        rounds nobody can use."""
        with self._cond:
            return max(
                (s for s, _, _ in self._frames.values()), default=None
            )

    def add_peer(self, idx):
        """Start (or restart) the watcher for peer ``idx`` — a JOIN in a
        churn scenario. Idempotent for already-watched peers."""
        idx = int(idx)
        with self._cond:
            if idx in self._threads and self._threads[idx].is_alive():
                return
            stop = threading.Event()
            t = threading.Thread(
                target=self._watch, args=(idx, stop), daemon=True
            )
            self._stops[idx] = stop
            self._threads[idx] = t
        # Same join-before-register-free contract as collect_begin waiters.
        self._ex._waiters = [
            w for w in self._ex._waiters if w.is_alive()
        ]
        self._ex._waiters.append(t)
        t.start()
        # Symmetric teardown: an exchange-level remove_peer (churn leave)
        # retires this watcher AND drops its cached frame, exactly like
        # the collector's own remove_peer.
        self._ex._register_watcher(
            idx, functools.partial(self._drop_peer, idx), t
        )

    def _drop_peer(self, idx):
        """Cancel + forget peer ``idx`` WITHOUT joining (the exchange's
        ``remove_peer`` joins after cancelling every registered watcher);
        returns the watcher thread, if any."""
        idx = int(idx)
        with self._cond:
            stop = self._stops.pop(idx, None)
            t = self._threads.pop(idx, None)
            self._frames.pop(idx, None)
            if stop is not None:
                # Under the lock: a watcher mid-decode re-checks this
                # before writing, so a removed peer's frame cannot be
                # resurrected by an in-flight arrival.
                stop.set()
        return t

    def remove_peer(self, idx):
        """Cancel peer ``idx``'s watcher and drop its cached frame — a
        LEAVE (or a Byzantine ban). The thread exits within one read
        chunk; joined here so membership changes never leak threads."""
        t = self._drop_peer(idx)
        if t is not None:
            t.join(timeout=5)

    def _watch(self, idx, stop):
        version = 0
        ex = self._ex
        slot = ex._slot(idx, self._plane)
        while not (stop.is_set() or ex._closing.is_set()):
            try:
                version, raw = ex._mb.read(
                    slot, min_version=version + 1, timeout_ms=200
                )
            except TimeoutError:
                continue
            (got_step,) = _SLOT.unpack_from(raw)
            if got_step == _CLOSE_STEP:
                break
            payload = raw[_SLOT.size:]
            if self._transform is not None:
                with _trace.span(
                    "decode", step=int(got_step), peer=int(idx),
                    nbytes=len(payload),
                ):
                    try:
                        payload = self._transform(idx, payload)
                    except Exception as exc:  # noqa: BLE001 — ban evidence
                        payload = exc
            with self._cond:
                if stop.is_set():
                    break  # removed while decoding: drop, don't resurrect
                self._gen += 1
                self._frames[idx] = (got_step, payload, self._gen)
                self._cond.notify_all()

    def gather(self, round_, q, *, max_staleness=0, timeout_ms=30_000,
               require_fresh=True):
        """Admissible frames for ``round_``: ``{peer: (tag, payload)}``.

        Blocks until >= ``q`` peers hold a frame tagged within
        ``max_staleness`` rounds of ``round_`` and (``require_fresh``) at
        least one of them arrived since the previous harvest; returns ALL
        admissible frames (the caller picks the freshest ``q`` — ties
        break on rank for deterministic composition). Payloads may be
        stored transform exceptions — Byzantine ban evidence the caller
        must attribute, exactly like ``collect``'s contract. Raises
        TimeoutError with the admissible count otherwise.
        """
        t0 = time.monotonic()
        deadline = t0 + timeout_ms / 1000.0
        lo = round_ - max_staleness
        sp = _trace.span(
            "gather", step=int(round_), q=int(q),
            max_staleness=int(max_staleness), plane=self._plane,
        )
        with sp, self._cond:
            while True:
                adm = {
                    p: f for p, f in self._frames.items() if f[0] >= lo
                }
                if len(adm) >= q:
                    newest = max(g for _, _, g in adm.values())
                    if not require_fresh or newest > self._mark:
                        self._mark = max(self._mark, newest)
                        sp.set(
                            arrived=len(adm),
                            reused=sum(
                                1 for s, _, _ in adm.values() if s < round_
                            ),
                        )
                        _emit_wait(
                            round_, q, len(adm), time.monotonic() - t0,
                            plane=self._plane,
                        )
                        return {p: (s, pl) for p, (s, pl, _) in adm.items()}
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._ex._closing.is_set():
                    sp.set(arrived=len(adm), timed_out=True)
                    _emit_wait(
                        round_, q, len(adm), time.monotonic() - t0,
                        timed_out=True, plane=self._plane,
                    )
                    raise TimeoutError(
                        f"only {len(adm)}/{q} peers within staleness "
                        f"{max_staleness} of round {round_} after "
                        f"{timeout_ms} ms"
                    )
                self._cond.wait(timeout=min(remaining, 1.0))

    def close(self):
        for idx in list(self.peers()):
            self.remove_peer(idx)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

"""VGG family (counterpart of garfieldpp/models/vgg.py; the reference also
pulls vgg16/vgg19 from torchvision, garfieldpp/tools.py:74-75). CIFAR-style:
conv+BN+ReLU stacks from the cfg table, 512-dim linear head."""

import flax.linen as nn
import jax.numpy as jnp

from ._layers import conv, max_pool, norm

cfg = {
    "VGG11": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "VGG13": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "VGG16": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M"],
    "VGG19": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Module):
    name_cfg: str = "VGG16"
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        for v in cfg[self.name_cfg]:
            if v == "M":
                x = max_pool(x, 2)
            else:
                x = nn.relu(norm(train, dtype=self.dtype)(
                    conv(v, 3, 1, padding=1, dtype=self.dtype)(x)))
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


def VGG11(num_classes=10, dtype=jnp.float32):
    return VGG("VGG11", num_classes, dtype)


def VGG13(num_classes=10, dtype=jnp.float32):
    return VGG("VGG13", num_classes, dtype)


def VGG16(num_classes=10, dtype=jnp.float32):
    return VGG("VGG16", num_classes, dtype)


def VGG19(num_classes=10, dtype=jnp.float32):
    return VGG("VGG19", num_classes, dtype)

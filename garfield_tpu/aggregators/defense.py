"""Closed-loop defense: suspicion-weighted GARs + rule escalation.

The counterpart of ``attacks/adaptive.py`` (DESIGN.md §16). The telemetry
plane already derives a per-rank suspicion score (exclusion frequency
under the active rule, ``telemetry/hub.py``); this module turns that
audit signal back into aggregation decisions, in ONE module deployed at
both scales — the ``utils/rounds.py`` pattern:

  - **Suspicion weighting** (``suspicion_weights``): a rank's rows enter
    the GAR scaled by ``max((1 - suspicion)^power, floor)``. The law is
    dual-backend (numpy on the host-plane PS, traced jnp for the
    in-graph emulation's carried suspicion EMA) and EXACTLY 1.0 at
    suspicion 0 — an all-clean history composes the unchanged stack, the
    defense-off bitwise contract's weighted half. The weights ride the
    SAME row-scale composition the bounded-staleness discount built
    (``fold.folded_tree_aggregate(row_weights=)`` on Gram rules, explicit
    row scaling elsewhere), so the folded-attack fast path survives.
  - **Escalation** (``EscalationPolicy``): a hysteresis state machine
    over an ordered ladder of rules — default ``krum`` (classic
    single-select) -> ``multi-krum`` (selective averaging) -> ``bulyan``
    (trimmed second phase, the strongest and costliest) — driven by the
    CONCENTRATION of suspicion (``suspicion_concentration``: how much
    the top-f ranks' suspicion exceeds the crowd's). Concentration above
    ``theta_up`` for ``patience`` consecutive rounds escalates one
    level; concentration below ``theta_down`` for ``clean_window``
    consecutive rounds de-escalates. ``theta_down < theta_up`` strictly,
    and a reading BETWEEN the thresholds resets both counters — a
    boundary-riding adversary cannot flap the rule (pinned in
    tests/test_defense.py).

Why both: an adaptive attacker that stays just under the exclusion
threshold (attacks/adaptive.py) is *selected*, so its suspicion stays
low — weighting alone cannot catch it. But its probing rounds and its
bursts ARE excluded, concentration rises, and escalation swaps in a rule
with a lower admission threshold (Bulyan's trimmed phase bounds exactly
the coordinate-wise excess the lie attack injects); the attacker's
bracket then re-closes at a smaller magnitude, and the accuracy bar is
restored (the committed DEFBENCH_r01 record).
"""

import dataclasses

import numpy as np

__all__ = [
    "DEFAULT_LEVELS",
    "LEVEL_RULES",
    "suspicion_weights",
    "suspicion_concentration",
    "EscalationConfig",
    "EscalationPolicy",
    "PlaneDefense",
    "DefensePlan",
    "resolve",
]

# The escalation ladder, weakest (cheapest) first. "krum" is the classic
# rule (select ONE best-scored gradient, m=1); "multi-krum" is this
# repo's krum default (average the m = n - f - 2 best); "bulyan" runs
# multi-krum phase 1 plus the coordinate-trimmed phase 2. Each level
# resolves to a registered rule + gar_params overlay via LEVEL_RULES.
DEFAULT_LEVELS = ("krum", "multi-krum", "bulyan")

LEVEL_RULES = {
    "krum": ("krum", {"m": 1}),
    "multi-krum": ("krum", {}),
    "bulyan": ("bulyan", {}),
    "median": ("median", {}),
    "tmean": ("tmean", {}),
    "cclip": ("cclip", {}),
}


def resolve_level(level):
    """(gar_name, gar_params) for an escalation-ladder level name."""
    if level not in LEVEL_RULES:
        raise ValueError(
            f"unknown escalation level {level!r}; available: "
            f"{sorted(LEVEL_RULES)}"
        )
    name, params = LEVEL_RULES[level]
    return name, dict(params)


def start_level(levels, gar_name, gar_params=None):
    """The ladder level an escalating defense STARTS at for a configured
    rule — matched by resolved SEMANTICS, never by name alone.

    The repo's default ``krum`` (m = n - f - 2) IS the ``multi-krum``
    level; starting the ladder at the name-matching ``krum`` level
    (classic, m = 1) would silently DOWNGRADE the deployed rule — and
    classic krum's single-select is categorically broken against a
    duplicate-cluster collusion fake (the f identical rows hand each
    other zero-distance neighbors, so one of them wins the score at
    essentially any magnitude; a floored adaptive-empire then stalls
    training from INSIDE the selection, DESIGN.md §17). An explicit
    ``gar_params {"m": 1}`` still starts at the classic level. Rules
    with no matching level start at 0 (the callers validate membership
    in LEVEL_RULES separately)."""
    m = dict(gar_params or {}).get("m")
    fallback = None
    for i, lv in enumerate(levels):
        name, params = resolve_level(lv)
        if name != gar_name:
            continue
        if params.get("m") == m:
            return i
        if fallback is None:
            fallback = i
    if fallback is not None:
        return fallback
    return 0


def suspicion_weights(suspicion, *, power=2.0, floor=0.1, relative=True):
    """Per-rank row weights from suspicion scores: ``max((1-s)^power,
    floor)`` with ``s`` the (by default RELATIVE) suspicion in [0, 1].

    ``relative=True`` (the default, and what the deployed defenses use)
    measures each rank's suspicion as its EXCESS over the crowd's
    median: ``s_rel = clip(s - median(s), 0, 1)``. Raw exclusion
    frequency is confounded under selective rules — krum at m of n
    refuses ``n - m`` rows EVERY round, and an adaptive cohort that
    stays admitted pushes its own exclusions onto honest ranks, so raw
    weighting would down-weight the victims and up-weight the attacker
    (the inversion hazard; DESIGN.md §16). Median-relative suspicion is
    translation-free: a uniformly-excluded crowd weighs 1.0, and only
    ranks refused MORE than their peers lose weight.

    Exactly 1.0 at relative suspicion 0 (``1.0 ** power`` is exact in
    IEEE), so a clean or uniform history leaves the stack bitwise
    unchanged through the row-scale composition — the same identity
    contract as ``rounds.staleness_weights`` at tau 0. ``floor > 0``
    keeps even a fully-suspect rank OBSERVABLE: a zero weight would
    freeze its suspicion (nothing left to select or exclude) and hand a
    rotation attack a permanent exit from the audit. Dual-backend like
    the staleness law: numpy in, numpy out (host PS); jnp/tracer in,
    jnp out (the in-graph carried-EMA emulation).
    """
    if not (0.0 <= floor <= 1.0):
        raise ValueError(f"floor must be in [0, 1], got {floor}")
    if power <= 0.0:
        raise ValueError(f"power must be > 0, got {power}")
    import jax
    import jax.numpy as jnp

    on_device = isinstance(suspicion, jax.Array)
    xp = jnp if on_device else np
    s = xp.clip(xp.asarray(suspicion, xp.float32), 0.0, 1.0)
    if relative:
        s = xp.clip(s - xp.median(s), 0.0, 1.0)
    w = xp.power(xp.float32(1.0) - s, xp.float32(power))
    return xp.maximum(w, xp.float32(floor)).astype(xp.float32)


def suspicion_concentration(suspicion, f):
    """How far the suspicion mass departs from uniform at the f-cohort
    scale: ``mean(top-f suspicion) - mean(bottom-f suspicion)``, in
    [0, 1]. ~0 for clean or uniformly noisy histories; high for BOTH
    Byzantine signatures — a pinned victim cohort (top-f -> 1 under a
    static attack the rule keeps refusing) AND a laundering/under-the-
    radar cohort (bottom-f conspicuously clean while the admitted fakes
    push the crowd's exclusions up, the adaptive-lie fingerprint). The
    escalation trigger. Works on numpy arrays/lists and jnp arrays
    (sort-based, no data-dependent shapes).
    """
    import jax
    import jax.numpy as jnp

    on_device = isinstance(suspicion, jax.Array)
    xp = jnp if on_device else np
    s = xp.clip(xp.asarray(suspicion, xp.float32), 0.0, 1.0)
    n = int(s.shape[0])
    f = int(f)
    if not (1 <= f < n):
        raise ValueError(f"need 1 <= f < n, got f={f}, n={n}")
    srt = xp.sort(s)  # ascending
    top = xp.mean(srt[n - f:])
    bottom = xp.mean(srt[:f])
    return (top - bottom).astype(xp.float32)


@dataclasses.dataclass(frozen=True)
class EscalationConfig:
    """Hysteresis parameters of the escalation state machine."""

    levels: tuple = DEFAULT_LEVELS
    theta_up: float = 0.5
    theta_down: float = 0.2
    patience: int = 3
    clean_window: int = 12

    def __post_init__(self):
        if len(self.levels) < 1:
            raise ValueError("need at least one escalation level")
        for lv in self.levels:
            resolve_level(lv)  # validates
        # A level change rebuilds the step around the SAME TrainState; a
        # ladder mixing stateful-center rules (cclip's carried v_0) with
        # stateless ones would change the state's structure mid-run.
        from . import gars

        stateful = {
            gars[resolve_level(lv)[0]].stateful_center for lv in self.levels
        }
        if len(stateful) > 1:
            raise ValueError(
                f"escalation ladder {self.levels} mixes stateful-center "
                "and stateless rules; the carried TrainState cannot "
                "change structure at a level transition"
            )
        if not (0.0 <= self.theta_down < self.theta_up):
            raise ValueError(
                "hysteresis needs 0 <= theta_down < theta_up, got "
                f"[{self.theta_down}, {self.theta_up}]"
            )
        if self.patience < 1 or self.clean_window < 1:
            raise ValueError("patience and clean_window must be >= 1")


class EscalationPolicy:
    """The defense's rule ladder: escalate under concentrated suspicion,
    de-escalate after a sustained clean window, never flap on a boundary.

    Counter semantics (the hysteresis contract, pinned in
    tests/test_defense.py): a concentration reading >= ``theta_up``
    increments the escalate counter and zeroes the clean counter; a
    reading <= ``theta_down`` does the reverse; a reading strictly
    BETWEEN the thresholds zeroes BOTH — sustained evidence on one side
    is required, so a value oscillating around either threshold (or
    parked between them) changes nothing. Every level change resets both
    counters: the new rule's steady state is measured, not the
    transient that triggered it (the cooldown idea of
    ``utils/autoscale.py``).
    """

    def __init__(self, config=None):
        self.config = config or EscalationConfig()
        self.level = 0
        self._hot = 0
        self._clean = 0
        self.escalations = 0
        self.deescalations = 0

    @property
    def level_name(self):
        return self.config.levels[self.level]

    def current(self):
        """(gar_name, gar_params) of the active level."""
        return resolve_level(self.level_name)

    def observe(self, concentration):
        """Fold one round's suspicion concentration; returns +1 on
        escalation, -1 on de-escalation, 0 otherwise."""
        c = float(concentration)
        cfg = self.config
        if c >= cfg.theta_up:
            self._hot += 1
            self._clean = 0
        elif c <= cfg.theta_down:
            self._clean += 1
            self._hot = 0
        else:
            # The hysteresis band: evidence for neither transition.
            self._hot = 0
            self._clean = 0
        if self._hot >= cfg.patience and self.level < len(cfg.levels) - 1:
            self.level += 1
            self._hot = 0
            self._clean = 0
            self.escalations += 1
            return 1
        if self._clean >= cfg.clean_window and self.level > 0:
            self.level -= 1
            self._hot = 0
            self._clean = 0
            self.deescalations += 1
            return -1
        return 0


class PlaneDefense:
    """Host-side closed-loop defense state for ONE aggregation plane.

    The SSMW PS derives its suspicion from its MetricsHub (it is the
    deployment's audit point); the other host planes — the MSMW replicas'
    gradient quorums, a LEARN node's gradient gather and model gossip —
    each see their own rank-attributed quorums and need their own
    independent history (DESIGN.md §17: "independent ladders per plane").
    One ``PlaneDefense`` carries, for one plane:

      - a decayed per-rank exclusion EMA (the MetricsHub windowed-
        suspicion law: ``obs``/``exc`` twins multiplied by
        ``0.5 ** (1/halflife)`` per fold — a rotation cannot launder it),
      - the ``suspicion_weights`` map (median-relative, floored), and
      - an optional per-plane ``EscalationPolicy`` whose ladder starts AT
        the plane's configured rule when that rule is a ladder level.

    ``fold(ranks, selected)`` ingests one round's audit: the quorum's
    rank ids plus the rule's per-row selection weights over exactly those
    rows (taps order). ``weights_for(ranks)`` returns the per-quorum-row
    weight vector (all-1.0 on a clean history — the caller dispatches
    the unweighted program then, preserving the bitwise contracts).
    ``observe()`` folds the current concentration into the ladder and
    returns the policy's action (0 when not escalating); the CALLER
    validates feasibility at its quorum size and calls ``revert`` on an
    infeasible level (the SSMW PS convention).
    """

    def __init__(self, plan, num_ranks, *, f, plane, base_gar,
                 base_params=None):
        self.plan = plan
        self.num_ranks = int(num_ranks)
        self.f = max(1, int(f))
        self.plane = str(plane)
        self.base_gar = base_gar
        self.base_params = dict(base_params or {})
        self._decay = 0.5 ** (1.0 / float(plan.halflife))
        self._obs = np.zeros(self.num_ranks, np.float64)
        self._exc = np.zeros(self.num_ranks, np.float64)
        self.policy = plan.policy()
        if self.policy is not None:
            levels = self.policy.config.levels
            if base_gar not in LEVEL_RULES:
                raise ValueError(
                    f"--defense escalate on the {self.plane!r} plane "
                    f"needs its rule to name an escalation-ladder level "
                    f"({sorted(LEVEL_RULES)}), got {base_gar!r}"
                )
            self.policy.level = start_level(
                levels, base_gar, self.base_params
            )

    def fold(self, ranks, selected):
        """One round's audit: ``ranks`` observed, ``selected`` the rule's
        per-row influence over exactly those rows."""
        ranks = np.asarray(ranks, np.int64)
        sel = np.asarray(selected, np.float64)
        obs_inc = np.zeros(self.num_ranks, np.float64)
        exc_inc = np.zeros(self.num_ranks, np.float64)
        np.add.at(obs_inc, ranks, 1.0)
        np.add.at(exc_inc, ranks, (sel <= 0.0).astype(np.float64))
        self._obs *= self._decay
        self._exc *= self._decay
        self._obs += obs_inc
        self._exc += exc_inc

    def suspicion(self):
        return self._exc / np.maximum(self._obs, 1e-9)

    def weights_full(self):
        """(num_ranks,) suspicion weights — exactly 1.0 pre-history."""
        return np.asarray(suspicion_weights(
            self.suspicion(), power=self.plan.power, floor=self.plan.floor
        ), np.float32)

    def weights_for(self, ranks):
        """Per-quorum-row weights for this round's rank composition, or
        None when every weight is exactly 1.0 (dispatch the unweighted
        program — the clean-history identity)."""
        w = self.weights_full()[np.asarray(ranks, np.int64)]
        if np.all(w == 1.0):
            return None
        return w.astype(np.float32)

    def concentration(self):
        return float(suspicion_concentration(self.suspicion(), self.f))

    def observe(self):
        """Fold this round's concentration into the per-plane ladder;
        returns the policy action (always 0 without escalation)."""
        if self.policy is None:
            return 0
        return self.policy.observe(self.concentration())

    def revert(self, action):
        """Undo an escalation the caller found infeasible at its quorum
        size (bulyan needs q >= 4f + 3)."""
        self.policy.level -= action

    def current(self):
        """(gar_name, gar_params) of the plane's active rule: the ladder
        level when escalating, else the configured base rule."""
        if self.policy is None:
            return self.base_gar, dict(self.base_params)
        name, lvl = resolve_level(self.policy.level_name)
        return name, {**self.base_params, **lvl}


@dataclasses.dataclass(frozen=True)
class DefensePlan:
    """Resolved ``--defense`` CLI intent (see ``resolve``)."""

    weighted: bool
    escalate: bool
    power: float = 2.0
    floor: float = 0.1
    halflife: float = 16.0
    escalation: EscalationConfig = None
    # Data-plane detectors (aggregators/dataplane.py, DESIGN.md §18):
    # the third plane of the closed loop — per-class head-gradient
    # fingerprints + spectral/2-means detection, their own EMA halflife.
    data: bool = False
    dp_tau: float = 2.0
    dp_power: float = 4.0
    dp_floor: float = 0.0
    dp_halflife: float = 8.0

    def policy(self):
        return EscalationPolicy(self.escalation) if self.escalate else None


# --defense mode table: (weighted, escalate, data). The GAR-side modes
# compose with the data plane via "+data" — the two defenses run
# SIMULTANEOUSLY (independent evidence, one row-weight algebra), which
# is how DEFBENCH_r03's backdoor bar is met without giving up the
# adaptive-lie coverage the ladder provides.
DEFENSE_MODES = {
    "weighted": (True, False, False),
    "escalate": (True, True, False),
    "data": (False, False, True),
    "weighted+data": (True, False, True),
    "escalate+data": (True, True, True),
}


def resolve(args):
    """``DefensePlan`` from the CLI flags, or None when ``--defense`` is
    off. ``--defense weighted`` enables suspicion weighting alone;
    ``--defense escalate`` enables weighting AND the rule ladder (the
    full closed loop); ``--defense data`` enables the DATA-plane
    detectors alone (fingerprints + spectral/2-means — the only plane
    that sees a backdoor); ``weighted+data``/``escalate+data`` compose
    them. ``--defense_params`` tunes ``power``/``floor``/``halflife``
    (the suspicion EMA), the escalation knobs (``levels``/``theta_up``/
    ``theta_down``/``patience``/``clean_window``), and the data-plane
    knobs (``dp_tau``/``dp_power``/``dp_floor``/``dp_halflife``)."""
    mode = getattr(args, "defense", None)
    if not mode or mode == "none":
        return None
    if mode not in DEFENSE_MODES:
        raise SystemExit(
            f"unknown --defense mode {mode!r}; use one of "
            f"{sorted(DEFENSE_MODES)}"
        )
    weighted, escalate, data = DEFENSE_MODES[mode]
    p = dict(getattr(args, "defense_params", None) or {})
    esc = EscalationConfig(
        levels=tuple(p.pop("levels", DEFAULT_LEVELS)),
        theta_up=float(p.pop("theta_up", 0.5)),
        theta_down=float(p.pop("theta_down", 0.2)),
        patience=int(p.pop("patience", 3)),
        clean_window=int(p.pop("clean_window", 12)),
    )
    plan = DefensePlan(
        weighted=weighted,
        escalate=escalate,
        power=float(p.pop("power", 2.0)),
        floor=float(p.pop("floor", 0.1)),
        halflife=float(p.pop("halflife", 16.0)),
        escalation=esc,
        data=data,
        dp_tau=float(p.pop("dp_tau", 2.0)),
        dp_power=float(p.pop("dp_power", 4.0)),
        dp_floor=float(p.pop("dp_floor", 0.0)),
        dp_halflife=float(p.pop("dp_halflife", 8.0)),
    )
    if p:
        raise SystemExit(f"unknown --defense_params keys {sorted(p)}")
    return plan

"""Time-to-target-accuracy vs Byzantine count f (the north-star's second
half, BASELINE.json).

ResNet-18 / CIFAR-10 (real files when present under GARFIELD_TPU_DATA_DIR —
see scripts/fetch_data.py — else the deterministic synthetic surrogate),
9 workers x batch 25, Multi-Krum under the lie attack for f in {1, 2, 3}
(n >= 2f+3 admits f <= 3 at n = 9) and fault-free average for f = 0,
mirroring the reference experiment grid (Aggregathor/run_exp.sh:5-14,
BASELINE.json configs).

Records (wall_seconds, accuracy) curves and the first crossing of each
target accuracy; writes the tracked artifact BASELINE_TTA.json and prints a
markdown table for BASELINE.md.

  python scripts/tta_bench.py [--iters 1200] [--eval_every 100] [--out FILE]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax
import jax.numpy as jnp
import numpy as np

TARGETS = (0.5, 0.7, 0.9)


def run_one(f, *, iters, eval_every, lr, gar=None, num_workers=9,
            batch=25, attack="lie", worker_momentum=None,
            gar_params=None, opt_momentum=0.9, topology="aggregathor"):
    from garfield_tpu import data, models, parallel
    from garfield_tpu.parallel import aggregathor, learn, mesh as mesh_lib
    from garfield_tpu.utils import selectors

    platform = jax.devices()[0].platform
    dtype = jnp.bfloat16 if platform == "tpu" else jnp.float32
    module = models.select_model("resnet18", "cifar10", dtype=dtype)
    loss_fn = selectors.select_loss("cross-entropy")
    opt = selectors.select_optimizer(
        "sgd", lr=lr, momentum=opt_momentum, weight_decay=5e-4
    )
    if gar is None:
        gar = "krum" if f else "average"
    attack = attack if f else None
    if topology == "learn":
        # Decentralized grid: every node worker+server on one chip; same
        # n/batch/rule axes as the PS grid (ClippedGossip-style evidence).
        mesh = mesh_lib.make_mesh({"nodes": 1}, devices=jax.devices()[:1])
        init_fn, step_fn, eval_fn = learn.make_trainer(
            module, loss_fn, opt, gar,
            num_nodes=num_workers, f=f, attack=attack, mesh=mesh,
            worker_momentum=worker_momentum, gar_params=gar_params,
        )
    else:
        mesh = mesh_lib.make_mesh({"workers": 1}, devices=jax.devices()[:1])
        init_fn, step_fn, eval_fn = aggregathor.make_trainer(
            module, loss_fn, opt, gar,
            num_workers=num_workers, f=f, attack=attack, mesh=mesh,
            worker_momentum=worker_momentum, gar_params=gar_params,
        )

    manager = data.DatasetManager("cifar10", batch, num_workers, num_workers, 0)
    manager.num_ps = 0
    xs_np, ys_np = manager.sharded_train_batches()
    # Bounded eval cost per point, scanned as ONE program (parallel.EvalSet).
    test = parallel.EvalSet(manager.get_test_set()[:40])
    xs, ys = jnp.asarray(xs_np), jnp.asarray(ys_np)
    num_batches = xs.shape[1]

    state = init_fn(jax.random.PRNGKey(1234), xs_np[0, 0])
    state, m = step_fn(state, xs[:, 0], ys[:, 0])  # compile before the clock
    jax.block_until_ready(m["loss"])

    curve = []
    t0 = time.time()
    for i in range(iters):
        state, m = step_fn(state, xs[:, i % num_batches], ys[:, i % num_batches])
        if (i + 1) % eval_every == 0 or i + 1 == iters:
            acc = parallel.compute_accuracy(state, eval_fn, test)
            curve.append({"wall_s": round(time.time() - t0, 3),
                          "step": i + 1, "accuracy": round(acc, 4)})
            print(f"  f={f} step={i + 1:5d} wall={curve[-1]['wall_s']:7.2f}s "
                  f"acc={acc:.4f}", flush=True)
    tta = {}
    for tgt in TARGETS:
        hit = next((c for c in curve if c["accuracy"] >= tgt), None)
        tta[str(tgt)] = None if hit is None else hit["wall_s"]
    return {"f": f, "gar": gar, "attack": attack,
            "num_workers": num_workers, "batch": batch,
            "worker_momentum": worker_momentum,
            "gar_params": gar_params or None,
            "opt_momentum": opt_momentum,
            "lr": lr,
            "topology": topology,
            "final_accuracy": curve[-1]["accuracy"] if curve else None,
            "time_to_target_s": tta, "curve": curve}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--iters", type=int, default=1200)
    p.add_argument("--eval_every", type=int, default=100)
    p.add_argument("--fs", nargs="*", type=int, default=[0, 1, 2, 3])
    p.add_argument("--gar", type=str, default=None,
                   help="Override the rule (default: krum for f>0, "
                        "average for f=0); e.g. bulyan needs n >= 4f+3.")
    p.add_argument("--workers", type=int, default=9)
    p.add_argument("--attack", type=str, default="lie",
                   help="Gradient attack for f > 0 rows (lie is the "
                        "literature's defense-breaking default; reverse/"
                        "random are the classic attacks robust rules beat).")
    p.add_argument("--topology", choices=["aggregathor", "learn"],
                   default="aggregathor",
                   help="PS grid (default) or the decentralized LEARN grid "
                        "(num_workers becomes num_nodes).")
    p.add_argument("--gar_params", type=json.loads, default=None,
                   help="Rule hyperparameters as JSON (e.g. cclip tau).")
    p.add_argument("--opt_momentum", type=float, default=0.9,
                   help="Server SGD momentum (0 = plain SGD, the "
                        "Karimireddy et al. server when workers carry "
                        "momentum).")
    p.add_argument("--worker_momentum", type=float, default=None,
                   help="Worker-momentum beta (Karimireddy et al. 2021); "
                        "pairs with --gar cclip.")
    p.add_argument("--lr", type=float, default=0.05,
                   help="SGD lr; the reference 0.2 makes krum-vs-lie at "
                   "f>=2 oscillate without converging on this task — "
                   "0.05 yields comparable convergence across f.")
    p.add_argument("--out", type=str, default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BASELINE_TTA.json"))
    args = p.parse_args(argv)

    from garfield_tpu import data as data_lib

    real = (data_lib.data_dir() / "cifar-10-batches-py").exists()
    results = []
    for f in args.fs:
        print(f"=== f={f} ===", flush=True)
        results.append(run_one(
            f, iters=args.iters, eval_every=args.eval_every, lr=args.lr,
            gar=args.gar, num_workers=args.workers, attack=args.attack,
            worker_momentum=args.worker_momentum,
            gar_params=args.gar_params, opt_momentum=args.opt_momentum,
            topology=args.topology,
        ))
    artifact = {
        "config": "resnet18/cifar10, batch 25/worker, SGD wd 5e-4; lr, "
                  "server momentum (opt_momentum), rule/attack/worker-count/"
                  "worker_momentum/gar_params/topology are PER ROW",
        "data": "real cifar10 files" if real else
                "deterministic synthetic surrogate (no dataset files; see "
                "scripts/fetch_data.py)",
        "device": str(jax.devices()[0]),
        "results": results,
    }
    # Merge with a prior artifact so the sweep can be built one f at a time
    # (each run is minutes on the shared chip).
    if os.path.exists(args.out):
        try:
            with open(args.out) as fp:
                prior = json.load(fp)
        except (OSError, ValueError) as exc:
            print(f"warning: cannot merge prior artifact ({exc}); "
                  f"overwriting {args.out}", file=sys.stderr)
        else:
            # .get defaults keep hand-edited / older-schema rows mergeable
            # instead of silently destroying them.
            # Rows from before --attack existed carry no attack field; they
            # were all lie (f>0) — normalize so re-running the default
            # sweep retires them instead of duplicating the config.
            key = lambda r: (
                r.get("f"), r.get("gar"), r.get("num_workers"),
                r.get("attack", "lie" if r.get("f") else None),
                r.get("worker_momentum"),
                json.dumps(r.get("gar_params") or None, sort_keys=True),
                r.get("opt_momentum", 0.9),
                r.get("topology", "aggregathor"),
                # lr is evidence, not tuning state: a re-run at a different
                # lr must ADD a row, never silently replace the published
                # measurement (rows predating the field were all lr 0.05).
                r.get("lr", 0.05),
            )
            seen = {key(r) for r in results}
            merged = list(results)
            for r in prior.get("results", []):
                if key(r) not in seen:  # also dedups prior-vs-prior
                    seen.add(key(r))
                    merged.append(r)
            artifact["results"] = sorted(
                merged,
                key=lambda r: (r.get("f", 0), str(r.get("gar")),
                               r.get("num_workers", 0)),
            )
    results = artifact["results"]
    with open(args.out, "w") as fp:
        json.dump(artifact, fp, indent=1)
    print(f"\nwrote {args.out}\n")
    print("| f | gar/attack | final acc | " +
          " | ".join(f"t(acc>={t})" for t in TARGETS) + " |")
    print("|---" * (3 + len(TARGETS)) + "|")
    for r in results:
        tta = r["time_to_target_s"]
        cells = " | ".join(
            "-" if tta[str(t)] is None else f"{tta[str(t)]:.1f}s"
            for t in TARGETS
        )
        wm = r.get("worker_momentum")
        attack = r.get("attack", "lie" if r.get("f") else None)
        cfg = r["gar"] + ("+" + attack if attack else "")
        if r.get("topology", "aggregathor") != "aggregathor":
            cfg = r["topology"] + ":" + cfg
        if wm is not None:
            cfg += f"+wm{wm:g}"
        srv_m = r.get("opt_momentum", 0.9)
        if wm is not None or srv_m != 0.9:
            cfg += f"/srv_m{srv_m:g}"
        cfg += f" lr{r.get('lr', 0.05):g}"
        if r.get("gar_params"):
            cfg += f" {r['gar_params']}"
        print(f"| {r['f']} (n={r['num_workers']}) | {cfg} | "
              f"{r['final_accuracy']:.4f} | {cells} |")
    return artifact


if __name__ == "__main__":
    main(sys.argv[1:])

"""Robustness matrix: every robust GAR vs every gradient attack.

The reference validates rules only implicitly (training runs + the
``upper_bound``/``influence`` formulas, SURVEY §4); here each (rule, attack)
cell is checked directly at the stack level: with n=11 workers, f=2 Byzantine
rows poisoned by the attack, the robust aggregate must stay near the honest
mean — and for the blatant attacks, beat plain averaging by an order of
magnitude. This is the Byzantine-tolerance contract the reference's paper
claims, as an executable test.
"""

import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu.aggregators import gars
from garfield_tpu.attacks import apply_gradient_attack

# n = 11 admits every rule's contract at f = 2 (bulyan needs n >= 4f+3).
N, F, D = 11, 2, 64
SIGMA = 0.01
RULES = ["krum", "median", "bulyan", "brute", "aksel", "condense", "tmean",
         "cclip"]
# reverse/empire shove the Byzantine rows far from the cluster; random
# replaces them with unit-scale noise (moderate displacement); lie/drop are
# designed to be subtle (stay within/near the honest spread).
STRONG = ["reverse", "empire"]
MODERATE = ["random"]
SUBTLE = ["lie", "drop"]


def _stack(seed):
    rng = np.random.default_rng(seed)
    mu = np.ones(D, np.float32)
    honest = mu + SIGMA * rng.standard_normal((N, D)).astype(np.float32)
    return jnp.asarray(honest), jnp.asarray(mu)


def _attacked(attack, g, seed):
    mask = jnp.arange(N) >= N - F  # last F rows Byzantine
    key = jax.random.PRNGKey(seed)
    return apply_gradient_attack(attack, g, mask, key=key), mask


def _err(agg, mu):
    return float(jnp.linalg.norm(agg - mu))


@pytest.mark.parametrize("attack", STRONG + MODERATE + SUBTLE)
@pytest.mark.parametrize("rule", RULES)
def test_rule_bounds_attack(rule, attack):
    g, mu = _stack(seed=zlib.crc32(f"{rule}-{attack}".encode()))
    attacked, _ = _attacked(attack, g, seed=7)
    agg = gars[rule].unchecked(attacked, f=F)
    err = _err(agg, mu)
    tol = 5 * SIGMA * np.sqrt(D)  # a few honest-noise lengths from the mean
    assert np.isfinite(err), f"{rule} vs {attack}: non-finite aggregate"
    assert err <= tol, f"{rule} vs {attack}: err {err:.4f} > tol {tol:.4f}"
    if attack in STRONG + MODERATE:
        ratio = 10 if attack in STRONG else 3
        err_avg = _err(jnp.mean(attacked, axis=0), mu)
        assert err <= err_avg / ratio, (
            f"{rule} vs {attack}: robust err {err:.4f} not << "
            f"average err {err_avg:.4f}"
        )


@pytest.mark.parametrize("attack", STRONG)
def test_average_is_broken_by_strong_attacks(attack):
    """Sanity: the non-robust baseline really is destroyed (otherwise the
    matrix above proves nothing)."""
    g, mu = _stack(seed=3)
    attacked, _ = _attacked(attack, g, seed=11)
    err_avg = _err(gars["average"].unchecked(attacked), mu)
    assert err_avg > 20 * 5 * SIGMA * np.sqrt(D)


@pytest.mark.parametrize("rule", [r for r in RULES if r != "condense"])
def test_permutation_invariant_under_attack(rule):
    """Shuffling worker rows must not change the aggregate (the mesh slot a
    Byzantine worker occupies is arbitrary). condense is excluded: it mixes
    the median with gradient 0 by design (condense.py), so it is
    order-dependent per the reference semantics."""
    g, _ = _stack(seed=5)
    attacked, _ = _attacked("reverse", g, seed=13)
    perm = np.random.default_rng(0).permutation(N)
    a1 = np.asarray(gars[rule].unchecked(attacked, f=F))
    a2 = np.asarray(gars[rule].unchecked(attacked[perm], f=F))
    np.testing.assert_allclose(a1, a2, rtol=2e-5, atol=2e-6)

"""GAR unit tests: golden values vs independent float64 numpy oracles,
plus the property tests the reference never had (SURVEY §4): permutation
invariance, Byzantine exclusion, NaN resilience, contract checks.

Oracles re-implement the reference rule semantics
(pytorch_impl/libs/aggregators/*.py) literally — direct pairwise-difference
norms, stable sorts — independent of the jax implementations under test.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from garfield_tpu.aggregators import gars


RNG = np.random.default_rng(1234)


def stack(n, d, scale=1.0):
    return RNG.normal(size=(n, d)).astype(np.float32) * scale


# ---------------------------------------------------------------------------
# Numpy oracles (float64, reference semantics)

def np_distances(g):
    g = np.asarray(g, dtype=np.float64)
    n = len(g)
    dist = np.full((n, n), np.inf)
    for i in range(n):
        for j in range(n):
            if i != j:
                dd = np.linalg.norm(g[i] - g[j])
                dist[i, j] = dd if np.isfinite(dd) else np.inf
    return dist


def np_krum(g, f, m=None):
    g = np.asarray(g, dtype=np.float64)
    n = len(g)
    if m is None:
        m = n - f - 2
    dist = np_distances(g)
    scores = np.array([np.sort(dist[i])[: n - f - 1].sum() for i in range(n)])
    order = np.argsort(scores, kind="stable")
    return g[order[:m]].mean(axis=0)


def np_median(g):
    g = np.asarray(g, dtype=np.float64)
    n = len(g)
    return np.sort(g, axis=0)[(n - 1) // 2]


def np_aksel(g, f, mode="mid"):
    g = np.asarray(g, dtype=np.float64)
    n = len(g)
    med = np_median(g)
    dist = ((g - med) ** 2).sum(axis=1)
    c = (n + 1) // 2 if mode == "mid" else n - f
    order = np.argsort(dist, kind="stable")
    return g[order[:c]].mean(axis=0)


def np_brute(g, f):
    import itertools

    g = np.asarray(g, dtype=np.float64)
    n = len(g)
    dist = np_distances(g)
    np.fill_diagonal(dist, 0.0)
    best, best_diam = None, np.inf
    for iset in itertools.combinations(range(n), n - f):
        diam = max(dist[x, y] for x in iset for y in iset)
        if diam < best_diam:
            best, best_diam = iset, diam
    return g[list(best)].mean(axis=0)


def np_bulyan(g, f, m=None):
    """Reference-intended Bulyan: per-round Multi-Krum over the active pool
    (scores recomputed each round — the fixed semantics, see bulyan.py)."""
    g = np.asarray(g, dtype=np.float64)
    n = len(g)
    m_max = n - f - 2
    if m is None:
        m = m_max
    dist = np_distances(g)
    active = list(range(n))
    rounds = n - 2 * f - 2
    selected = np.zeros((rounds, g.shape[1]))
    for i in range(rounds):
        m_i = min(m, m_max - i)
        scores = []
        for a in active:
            dd = np.sort([dist[a, b] for b in active if b != a])
            scores.append((dd[:m_i].sum(), a))
        order = sorted(scores, key=lambda t: t[0])
        chosen = [a for _, a in order[:m_i]]
        selected[i] = g[chosen].mean(axis=0)
        active.remove(order[0][1])
    beta = rounds - 2 * f
    med = np.sort(selected, axis=0)[(rounds - 1) // 2]
    out = np.zeros(g.shape[1])
    for j in range(g.shape[1]):
        devs = np.abs(selected[:, j] - med[j])
        idx = np.argsort(devs, kind="stable")[:beta]
        out[j] = selected[idx, j].mean()
    return out


# ---------------------------------------------------------------------------
# Golden tests

@pytest.mark.parametrize("n,f,d", [(7, 2, 16), (11, 3, 33), (15, 4, 8)])
def test_krum_golden(n, f, d):
    g = stack(n, d)
    got = np.asarray(gars["krum"](g, f=f))
    want = np_krum(g, f)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("n,d", [(5, 7), (8, 16), (9, 3)])
def test_median_golden(n, d):
    g = stack(n, d)
    got = np.asarray(gars["median"](g))
    np.testing.assert_allclose(got, np_median(g), rtol=1e-6)


@pytest.mark.parametrize("n,f,mode", [(7, 2, "mid"), (9, 3, "n-f"), (11, 2, "mid")])
def test_aksel_golden(n, f, mode):
    g = stack(n, 12)
    got = np.asarray(gars["aksel"](g, f=f, mode=mode))
    np.testing.assert_allclose(got, np_aksel(g, f, mode), rtol=2e-5, atol=1e-6)


@pytest.mark.parametrize("n,f", [(5, 1), (7, 2), (9, 3)])
def test_brute_golden(n, f):
    g = stack(n, 10)
    got = np.asarray(gars["brute"](g, f=f))
    np.testing.assert_allclose(got, np_brute(g, f), rtol=2e-5, atol=1e-6)


@pytest.mark.parametrize("n,f,d", [(7, 1, 9), (11, 2, 16), (12, 2, 5)])
def test_bulyan_golden(n, f, d):
    g = stack(n, d)
    got = np.asarray(gars["bulyan"](g, f=f))
    want = np_bulyan(g, f)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_average_golden():
    g = stack(6, 11)
    np.testing.assert_allclose(
        np.asarray(gars["average"](g)), g.astype(np.float64).mean(axis=0), rtol=1e-6
    )


def test_condense_p1_is_median():
    g = stack(8, 13)
    import jax

    got = np.asarray(gars["condense"](g, f=2, p=1.0, key=jax.random.key(0)))
    np.testing.assert_allclose(got, np_median(g), rtol=1e-6)


def test_condense_deterministic_per_key():
    import jax

    g = stack(8, 40)
    k = jax.random.key(7)
    a = np.asarray(gars["condense"](g, f=2, key=k))
    b = np.asarray(gars["condense"](g, f=2, key=k))
    np.testing.assert_array_equal(a, b)
    # Output coordinates come from median or g[0] only.
    med, g0 = np_median(g), g[0]
    assert all(
        np.isclose(x, m, atol=1e-6) or np.isclose(x, z, atol=1e-6)
        for x, m, z in zip(a, med, g0)
    )


def test_condense_keyless_replay_is_call_order_free():
    """No hidden global RNG: the keyless convenience path is a fixed key, so
    replaying the same call sequence — or reordering it — cannot change any
    result (VERDICT r1: the module-global counter coupled results to
    process-wide call order; it is gone)."""
    import jax

    g1, g2 = stack(8, 40), stack(8, 40)  # two distinct draws
    a1 = np.asarray(gars["condense"](g1, f=2))
    b1 = np.asarray(gars["condense"](g2, f=2))
    # Reversed order, same per-input results.
    b2 = np.asarray(gars["condense"](g2, f=2))
    a2 = np.asarray(gars["condense"](g1, f=2))
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(b1, b2)
    # Distinct explicit keys vary the mask (p=0.5 makes ties vanishingly
    # unlikely at d=40).
    c1 = np.asarray(gars["condense"](g1, f=2, p=0.5, key=jax.random.key(1)))
    c2 = np.asarray(gars["condense"](g1, f=2, p=0.5, key=jax.random.key(2)))
    assert not np.array_equal(c1, c2)


# ---------------------------------------------------------------------------
# Property tests

@pytest.mark.parametrize("name,kwargs", [
    ("krum", {"f": 2}),
    ("median", {}),
    ("brute", {"f": 2}),
    ("aksel", {"f": 2}),
    ("bulyan", {"f": 1}),
    ("average", {}),
])
def test_permutation_invariance(name, kwargs):
    g = stack(9, 14)
    perm = RNG.permutation(9)
    a = np.asarray(gars[name](g, **kwargs))
    b = np.asarray(gars[name](g[perm], **kwargs))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name,f,n", [("krum", 2, 9), ("brute", 2, 9),
                                      ("bulyan", 1, 8), ("aksel", 2, 9)])
def test_byzantine_exclusion(name, f, n):
    """f far-away Byzantine rows must not drag the output outside the honest
    coordinate envelope (robustness property the GARs exist to provide)."""
    g = stack(n, 10, scale=0.1)
    g[:f] = 1e4  # Byzantine rows
    out = np.asarray(gars[name](g, f=f))
    honest = g[f:]
    assert np.all(out <= honest.max(axis=0) + 1e-3)
    assert np.all(out >= honest.min(axis=0) - 1e-3)


@pytest.mark.parametrize("name,f", [("krum", 2), ("median", None), ("brute", 2)])
def test_nan_resilience(name, f):
    """A NaN-poisoned Byzantine row must not produce a NaN aggregate
    (median.py NaN-resilience; krum/brute isfinite guards)."""
    g = stack(9, 12)
    g[0] = np.nan
    kwargs = {} if f is None else {"f": f}
    out = np.asarray(gars[name](g, **kwargs))
    assert np.all(np.isfinite(out))


def test_checked_contracts():
    g = stack(5, 4)
    with pytest.raises(AssertionError):
        gars["krum"].checked(g, f=2)  # needs n >= 2f+3 = 7
    with pytest.raises(AssertionError):
        gars["bulyan"].checked(g, f=1)  # needs n >= 4f+3 = 7
    with pytest.raises(AssertionError):
        gars["brute"].checked(g, f=3)  # needs n >= 2f+1 = 7
    assert gars["krum"].check(stack(7, 4), f=2) is None


def test_upper_bounds_match_reference_formulas():
    import math

    n, f, d = 20, 4, 1000
    assert gars["median"].upper_bound(n, f, d) == pytest.approx(1 / math.sqrt(n - f))
    assert gars["krum"].upper_bound(n, f, d) == pytest.approx(
        1 / math.sqrt(2 * (n - f + f * (n + f * (n - f - 2) - 2) / (n - 2 * f - 2)))
    )
    assert gars["brute"].upper_bound(n, f, d) == pytest.approx((n - f) / (2 * f))


def test_influence_far_attacks_rejected():
    honest = stack(9, 8, scale=0.1)
    attacks = np.full((2, 8), 1e4, dtype=np.float32)
    assert gars["krum"].influence(list(honest), list(attacks), f=2) == 0.0
    assert gars["brute"].influence(list(honest), list(attacks), f=2) == 0.0
    assert gars["average"].influence(list(honest), list(attacks)) == pytest.approx(2 / 11)


def test_list_and_stack_inputs_agree():
    g = stack(7, 6)
    a = np.asarray(gars["krum"](g, f=2))
    b = np.asarray(gars["krum"]([jnp.asarray(row) for row in g], f=2))
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_jit_compatible():
    import functools
    import jax

    g = stack(9, 32)
    for name, kwargs in [("krum", {"f": 2}), ("median", {}), ("bulyan", {"f": 1}),
                         ("aksel", {"f": 2}), ("average", {})]:
        fn = jax.jit(functools.partial(gars[name].unchecked, **kwargs))
        eager = np.asarray(gars[name](g, **kwargs))
        jitted = np.asarray(fn(g))
        np.testing.assert_allclose(jitted, eager, rtol=1e-5, atol=1e-6)


def test_registry_contents():
    for name in ("average", "median", "tmean", "krum", "bulyan", "brute", "aksel",
                 "condense"):
        assert name in gars, f"GAR {name} missing from registry"


def test_tmean_golden():
    """Trimmed mean: drop f largest/smallest per coordinate, average rest."""
    g = np.array(
        [[0.0, 100.0], [1.0, 1.0], [2.0, 2.0], [3.0, 3.0], [-50.0, 4.0]],
        np.float32,
    )
    out = np.asarray(gars["tmean"](g, f=1))
    # col0 sorted: -50,0,1,2,3 -> mean(0,1,2)=1; col1: 1,2,3,4,100 -> 3
    np.testing.assert_allclose(out, [1.0, 3.0])
    assert gars["tmean"].check(g, f=2) is None
    assert gars["tmean"].check(g, f=3) is not None  # needs n >= 2f+1
    assert gars["tmean"].upper_bound(9, 2, 10) == pytest.approx(
        1 / np.sqrt(7)
    )


def test_tmean_nan_trimmed():
    g = np.ones((7, 4), np.float32)
    g[0] = np.nan  # sorts last per coordinate -> inside the trimmed tail
    out = np.asarray(gars["tmean"](g, f=1))
    np.testing.assert_allclose(out, np.ones(4))


def test_bf16_gram_flat_tree_agree():
    """ADVICE r2: both Gram paths accumulate in at-least-f32 (bf16 inputs
    no longer make the flat path compute a bf16 Gram), so under bf16
    gradients flat and tree Krum score the same candidates to within f32
    leaf-sum rounding and pick the SAME rows."""
    from garfield_tpu.aggregators import _common

    g16 = jnp.asarray(stack(8, 96)).astype(jnp.bfloat16)
    # Tree with two leaves whose flattened concat is the flat stack.
    tree = {"a": g16[:, :40].reshape(8, 5, 8), "b": g16[:, 40:]}
    gram_tree = _common.tree_gram(tree)
    d_flat = _common.pairwise_distances(g16)
    d_tree = _common.distances_from_gram(gram_tree)
    assert gram_tree.dtype == jnp.float32
    assert d_flat.dtype == jnp.float32
    # Per-leaf partial sums reorder the f32 accumulation, so the paths agree
    # to rounding, not bitwise — selections must still coincide.
    np.testing.assert_allclose(
        np.asarray(d_flat), np.asarray(d_tree), rtol=1e-5, atol=1e-5
    )
    k = 8 - 2 - 2  # n - f - 2 nearest neighbours, f=2
    sc_flat = np.sort(np.asarray(d_flat), axis=1)[:, :k].sum(axis=1)
    sc_tree = np.sort(np.asarray(d_tree), axis=1)[:, :k].sum(axis=1)
    assert np.argmin(sc_flat) == np.argmin(sc_tree)


# ---------------------------------------------------------------------------
# cclip — centered clipping (beyond-reference; aggregators/cclip.py,
# Karimireddy, He & Jaggi ICML'21). No reference oracle exists; the float64
# numpy oracle below re-implements the paper's fixed-point update literally.

def np_cclip(g, iters=3, tau=None):
    g = np.asarray(g, np.float64)
    n = len(g)
    # lower coordinate-wise median init (ops.coordinate_median semantics)
    v = np.sort(g, axis=0)[(n - 1) // 2]
    for _ in range(iters):
        dev = g - v
        norms = np.linalg.norm(dev, axis=1)
        t = np.median(norms) if tau is None else tau
        scale = np.minimum(1.0, t / np.maximum(norms, 1e-12))
        v = v + np.mean(dev * scale[:, None], axis=0)
    return v


@pytest.mark.parametrize("n,f,d", [(7, 2, 16), (9, 2, 33), (8, 3, 10)])
def test_cclip_golden(n, f, d):
    g = stack(n, d)
    got = np.asarray(gars["cclip"](g, f=f))
    np.testing.assert_allclose(got, np_cclip(g), rtol=1e-4, atol=1e-5)


def test_cclip_identical_rows_fixed_point():
    row = RNG.normal(size=12).astype(np.float32)
    g = np.tile(row, (9, 1))
    got = np.asarray(gars["cclip"](g, f=2))
    np.testing.assert_allclose(got, row, rtol=1e-6)


def test_cclip_huge_tau_is_mean():
    # With tau far above every radius nothing clips: one iteration from any
    # center lands on the mean, and the mean is the update's fixed point.
    g = stack(8, 20)
    got = np.asarray(gars["cclip"](g, f=2, tau=1e9))
    np.testing.assert_allclose(
        got, g.astype(np.float64).mean(axis=0), rtol=1e-4, atol=1e-5
    )


def test_cclip_bounded_influence():
    # The defining property (paper Lemma 1): an arbitrarily-placed row moves
    # the aggregate by at most ~iters * tau / n, NOT proportionally to its
    # magnitude. Selection-free analog of test_byzantine_exclusion.
    g = stack(9, 10, scale=0.1)
    honest_out = np.asarray(gars["cclip"](np.ascontiguousarray(g), f=2))
    radii = np.linalg.norm(
        g - np.sort(g, axis=0)[4], axis=1
    )
    tau = np.median(radii)
    for magnitude in (1e2, 1e6):
        bad = g.copy()
        bad[0] = magnitude
        out = np.asarray(gars["cclip"](bad, f=2))
        shift = np.linalg.norm(out - honest_out)
        # Generous constant (tau-median jitter + 3 iterations), but
        # magnitude-INdependent: the same bound must hold at 1e2 and 1e6.
        assert shift <= 2.0 * tau + 1e-6, (magnitude, shift, tau)


def test_cclip_nan_resilience():
    g = stack(9, 12)
    g[0] = np.nan
    out = np.asarray(gars["cclip"](g, f=2))
    assert np.all(np.isfinite(out))


def test_cclip_permutation_invariance():
    g = stack(9, 14)
    perm = RNG.permutation(9)
    a = np.asarray(gars["cclip"](g, f=2))
    b = np.asarray(gars["cclip"](g[perm], f=2))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_cclip_tree_matches_flat():
    # Tree-mode twin must agree with the flat path on a multi-leaf pytree.
    import jax

    leaves = {
        "w": RNG.normal(size=(9, 4, 3)).astype(np.float32),
        "b": RNG.normal(size=(9, 5)).astype(np.float32),
    }
    flat = np.concatenate(
        [np.asarray(l).reshape(9, -1) for l in jax.tree.leaves(leaves)],
        axis=1,
    )
    tree_out = gars["cclip"].tree_aggregate(
        jax.tree.map(jnp.asarray, leaves), f=2
    )
    flat_from_tree = np.concatenate(
        [np.asarray(l).reshape(-1) for l in jax.tree.leaves(tree_out)]
    )
    flat_out = np.asarray(gars["cclip"](flat, f=2))
    np.testing.assert_allclose(flat_from_tree, flat_out, rtol=1e-5, atol=1e-6)


def test_bulyan_tree_matches_flat():
    """r4 tree-mode Bulyan (concat-first: one axis-1 concat, one Gram, one
    fused phase-2) must agree with the flat path on a multi-leaf pytree."""
    import jax

    leaves = {
        "w": RNG.normal(size=(9, 4, 3)).astype(np.float32),
        "b": RNG.normal(size=(9, 5)).astype(np.float32),
    }
    flat = np.concatenate(
        [np.asarray(l).reshape(9, -1) for l in jax.tree.leaves(leaves)],
        axis=1,
    )
    tree_out = gars["bulyan"].tree_aggregate(
        jax.tree.map(jnp.asarray, leaves), f=1
    )
    flat_from_tree = np.concatenate(
        [np.asarray(l).reshape(-1) for l in jax.tree.leaves(tree_out)]
    )
    flat_out = np.asarray(gars["bulyan"](flat, f=1))
    np.testing.assert_allclose(flat_from_tree, flat_out, rtol=1e-5, atol=1e-6)


def test_cclip_checked_contract():
    with pytest.raises(AssertionError):
        gars["cclip"].checked(stack(5, 4), f=3)  # needs n >= 2f+1 = 7
    assert gars["cclip"].check(stack(7, 4), f=3) is None


@pytest.mark.parametrize("name,kwargs", [
    ("median", {}),
    ("tmean", {"f": 2}),
])
def test_coordinatewise_tree_matches_flat(name, kwargs):
    """r3 tree-mode twins of the coordinate-wise rules decompose per leaf;
    they must agree elementwise with the flat path."""
    import jax

    leaves = {
        "w": RNG.normal(size=(9, 4, 3)).astype(np.float32),
        "b": RNG.normal(size=(9, 5)).astype(np.float32),
    }
    flat = np.concatenate(
        [np.asarray(l).reshape(9, -1) for l in jax.tree.leaves(leaves)],
        axis=1,
    )
    tree_out = gars[name].tree_aggregate(
        jax.tree.map(jnp.asarray, leaves), **kwargs
    )
    flat_from_tree = np.concatenate(
        [np.asarray(l).reshape(-1) for l in jax.tree.leaves(tree_out)]
    )
    flat_out = np.asarray(gars[name](flat, **kwargs))
    np.testing.assert_allclose(flat_from_tree, flat_out, rtol=1e-6,
                               atol=1e-7)


# ---------------------------------------------------------------------------
# Sortnet-selection substitutability (PR 19): GARFIELD_SORTNET_SELECT
# defaults on, so the sortnet Gram paths must be BITWISE equal to the
# stable-argsort paths — not merely close. Tie-heavy stacks (duplicated
# rows give exactly equal pairwise distances, hence equal scores) are
# the cases where an unstable or differently-ordered pick would diverge.

class TestSortnetSelectSubstitutable:
    def _tie_stack(self, n, d, seed):
        g = np.random.default_rng(seed).normal(size=(n, d))
        g = g.astype(np.float32)
        g[n // 2] = g[0]  # duplicate row: tied distances + tied scores
        return g

    @pytest.mark.parametrize("n,f,m", [
        (7, 2, None), (11, 3, 4), (15, 4, 1),
        (40, 12, None),  # n > MAX_SORT_N: the top_k/argsort fallbacks
    ])
    def test_krum_bitwise_on_off(self, n, f, m):
        from garfield_tpu.aggregators import krum

        g = self._tie_stack(n, 24, seed=n)
        np.testing.assert_array_equal(
            np.asarray(krum.aggregate(g, f, m=m, use_sortnet=True)),
            np.asarray(krum.aggregate(g, f, m=m, use_sortnet=False)),
        )
        np.testing.assert_array_equal(
            np.asarray(krum.selection_indices(g, f, m=m,
                                              use_sortnet=True)),
            np.asarray(krum.selection_indices(g, f, m=m,
                                              use_sortnet=False)),
        )

    @pytest.mark.parametrize("n,f", [(7, 1), (12, 2), (35, 5)])
    def test_bulyan_bitwise_on_off(self, n, f):
        from garfield_tpu.aggregators import bulyan

        g = self._tie_stack(n, 16, seed=100 + n)
        np.testing.assert_array_equal(
            np.asarray(bulyan.aggregate(g, f, use_sortnet=True)),
            np.asarray(bulyan.aggregate(g, f, use_sortnet=False)),
        )

    def test_gram_select_bitwise_on_off(self):
        from garfield_tpu.aggregators import krum

        g = self._tie_stack(9, 12, seed=77)
        gram = jnp.matmul(jnp.asarray(g), jnp.asarray(g).T,
                          preferred_element_type=jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(krum.gram_select(gram, 2, use_sortnet=True)),
            np.asarray(krum.gram_select(gram, 2, use_sortnet=False)),
        )

    def test_env_knob_parses(self, monkeypatch):
        from garfield_tpu.aggregators import krum

        for raw, want in [("1", True), ("0", False), ("false", False),
                          ("", False), ("on", True)]:
            monkeypatch.setenv("GARFIELD_SORTNET_SELECT", raw)
            assert krum._sortnet_select(None) is want
        monkeypatch.delenv("GARFIELD_SORTNET_SELECT")
        assert krum._sortnet_select(None) is True  # default on
        assert krum._sortnet_select(False) is False  # explicit wins

"""DenseNet-BC family (counterpart of garfieldpp/models/densenet.py)."""

import math
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from ._layers import avg_pool, conv, conv1x1, global_avg_pool, norm


class Bottleneck(nn.Module):
    growth_rate: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        out = conv1x1(4 * self.growth_rate, dtype=self.dtype)(
            nn.relu(norm(train, dtype=self.dtype)(x)))
        out = conv(self.growth_rate, 3, 1, padding=1, dtype=self.dtype)(
            nn.relu(norm(train, dtype=self.dtype)(out)))
        return jnp.concatenate([out, x], axis=-1)


class Transition(nn.Module):
    out_planes: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        x = conv1x1(self.out_planes, dtype=self.dtype)(
            nn.relu(norm(train, dtype=self.dtype)(x)))
        return avg_pool(x, 2)


class DenseNet(nn.Module):
    nblocks: Sequence[int]
    growth_rate: int = 12
    reduction: float = 0.5
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        gr = self.growth_rate
        planes = 2 * gr
        x = conv(planes, 3, 1, padding=1, dtype=self.dtype)(x)
        for i, nb in enumerate(self.nblocks):
            for _ in range(nb):
                x = Bottleneck(gr, dtype=self.dtype)(x, train)
            planes += nb * gr
            if i != len(self.nblocks) - 1:
                planes = int(math.floor(planes * self.reduction))
                x = Transition(planes, dtype=self.dtype)(x, train)
        x = nn.relu(norm(train, dtype=self.dtype)(x))
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


def DenseNet121(num_classes=10, dtype=jnp.float32):
    return DenseNet((6, 12, 24, 16), 32, 0.5, num_classes, dtype)


def DenseNet169(num_classes=10, dtype=jnp.float32):
    return DenseNet((6, 12, 32, 32), 32, 0.5, num_classes, dtype)


def DenseNet201(num_classes=10, dtype=jnp.float32):
    return DenseNet((6, 12, 48, 32), 32, 0.5, num_classes, dtype)


def DenseNet161(num_classes=10, dtype=jnp.float32):
    return DenseNet((6, 12, 36, 24), 48, 0.5, num_classes, dtype)


def densenet_cifar(num_classes=10, dtype=jnp.float32):
    return DenseNet((6, 12, 24, 16), 12, 0.5, num_classes, dtype)

"""Hierarchical bucketed robust aggregation (DESIGN.md §13).

The flat GARs are built for tens of workers: every rule makes one pass over
an (n, d) stack, the coordinate kernels fall off the Pallas fast path past
``MAX_SORT_N`` = 32 (ops/coordinate.py), and GARBENCH_r3/r4 show the
single-shot rules stay graceful only to n ≈ 512. Federated scale — the
ROADMAP's "millions of users" — needs Byzantine resilience that COMPOSES:

  1. partition the n client gradients into buckets of ≤ ``bucket_size``
     (default MAX_SORT_N, the Pallas sorting-network sweet spot);
  2. robust-aggregate each bucket with a bucket GAR (vmapped over buckets:
     Gram rules batch their MXU matmuls, coordinate rules run the jnp
     sorting network ``ops.sortnet_median`` — the Pallas kernel's
     algorithm, batch-safe on every backend);
  3. robust-aggregate the bucket summaries with a (possibly different)
     top-level GAR — recursing while more than ``bucket_size`` summaries
     remain (``levels="auto"``), so memory and sort widths stay bounded.

This is the bucketing construction of Karimireddy et al. ("Byzantine-Robust
Learning on Heterogeneous Datasets via Bucketing") crossed with the
hierarchical aggregation of FL systems à la Bonawitz et al., expressed over
this repo's GAR registry.

f-composition
-------------
If every bucket at a level tolerates ``f_l`` Byzantine members, corrupting
one bucket summary costs the adversary ``f_l + 1`` clients — REGARDLESS of
placement. A global budget of ``f`` Byzantine clients therefore corrupts at
most ``f // (f_l + 1)`` summaries, which becomes the Byzantine budget of
the next level up; recursively, a hierarchy with per-level tolerances
``f_0, f_1, …, f_top`` withstands ``prod(f_l + 1) · (f_top + 1) − 1``
Byzantine clients. ``plan_hierarchy`` derives the per-level split (each
``f_l`` clamped into the level rule's contract at the smallest bucket of
that level), ``check``/``upper_bound`` expose the composed contract so the
``hier-*`` rules register in ``gars[...]`` like any flat rule, and the
adversarial-placement tests (tests/test_hierarchy.py) pin that concentrated
and spread cohorts both stay inside the tolerance.

Streaming ingest
----------------
``StreamingAggregator`` is the wave-based reducer for clients arriving in
order over the host plane: each pushed vector fills the current bucket;
completed buckets fold in vmapped waves the moment they close, and their
summaries cascade up the level states the same way. Peak memory is
O(wave · bucket_size · d) per level — O(log n) buffers, NOT O(n · d) — so
n = 2^17 clients at d = 1e5 fit the 1-core container (HIERBENCH_r01).
``push_frame``/``wire_transform`` accept typed wire frames (utils/wire.py);
the transform plugs straight into ``PeerExchange.collect_begin`` so decode +
bucket folding runs in the exchange's pre-registered waiter threads, and a
codec reject propagates as the sender's ban evidence exactly like the
cluster quorum paths. Streaming and batch aggregation are BITWISE equal
(pinned): both paths fold through the same jitted per-bucket programs, and
vmap width does not change per-element results.

Telemetry: with ``telemetry=True`` the reducer derives per-client
observed/selected weights (bucket-level ``gram_select`` exclusions composed
with the exclusion of whole bucket summaries above) and emits them as a
``hier_exclusion`` event, which ``telemetry.hub.MetricsHub`` folds into the
same per-client suspicion score the in-graph taps feed (docs/TELEMETRY.md).
"""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from . import gars, register
from ._common import as_stack, concat_stack, num_gradients, unflatten_vec
from ..ops import coordinate as _coord
from ..telemetry import trace as _trace
from ..utils import tools

__all__ = [
    "DEFAULT_BUCKET_SIZE",
    "SUPPORTED_RULES",
    "HierPlan",
    "plan_hierarchy",
    "max_tolerated_f",
    "aggregate",
    "aggregate_with_audit",
    "check",
    "upper_bound",
    "tree_aggregate",
    "StreamingAggregator",
    "make_hier_gar",
    "parse_hier_name",
]

DEFAULT_BUCKET_SIZE = _coord.MAX_SORT_N

# (min_f, max_f(n)) each rule's contract + breakdown point admits — the
# single source the f-composition derives from (mirrors each rule module's
# ``check``; ``average`` is contract-legal at any f but TOLERATES none, so
# it may only serve levels whose derived Byzantine budget is zero).
# ``condense`` (needs an rng key per call) and ``brute`` (exponential in n)
# are deliberately unsupported.
_TOLERANCE = {
    "krum": (1, lambda n: (n - 3) // 2),
    "median": (0, lambda n: (n - 1) // 2),
    "tmean": (1, lambda n: (n - 1) // 2),
    "bulyan": (1, lambda n: (n - 3) // 4),
    "aksel": (1, lambda n: (n - 1) // 2),
    "cclip": (0, lambda n: (n - 1) // 2),
    "average": (0, lambda n: 0),
}
SUPPORTED_RULES = tuple(sorted(_TOLERANCE))


def _tolerance(rule, n):
    """(min_f, max_f) the rule admits at n inputs; max < min means the
    bucket is too small for the rule at any tolerance."""
    lo, hi = _TOLERANCE[rule]
    return lo, hi(n)


def _min_n(rule, f):
    """Smallest input count at which ``rule`` admits tolerance ``f``."""
    lo, _ = _TOLERANCE[rule]
    f = max(f, lo)
    if rule == "krum":
        return 2 * f + 3
    if rule == "bulyan":
        return 4 * f + 3
    if rule in ("tmean", "aksel", "cclip"):
        return 2 * f + 1
    return 1  # median / average accept any n >= 1


def _balanced_into(n, num):
    """Partition n into exactly ``num`` contiguous buckets with sizes
    differing by at most 1 (larger buckets first) — no tiny remainder
    bucket for the adversary to overwhelm cheaply."""
    base, rem = divmod(n, num)
    return (base + 1,) * rem + (base,) * (num - rem)


class _Level:
    """One bucketing level: ``sizes[b]`` clients/summaries per bucket,
    every bucket aggregated by ``rule`` at tolerance ``f``."""

    __slots__ = ("sizes", "f", "rule")

    def __init__(self, sizes, f, rule):
        self.sizes = tuple(sizes)
        self.f = int(f)
        self.rule = rule

    def __repr__(self):
        return (f"<level {self.rule} x{len(self.sizes)} buckets "
                f"(sizes {min(self.sizes)}..{max(self.sizes)}) f={self.f}>")


class HierPlan:
    """Derived hierarchy: bucketing levels bottom-up, then the final fold.

    ``bucket_levels[0]`` consumes the n client gradients; each subsequent
    level consumes the previous level's bucket summaries; ``final_rule`` at
    tolerance ``final_f`` folds the last ``final_n`` summaries to (d,).
    """

    __slots__ = ("n", "f", "bucket_levels", "final_rule", "final_f",
                 "final_n")

    def __init__(self, n, f, bucket_levels, final_rule, final_f, final_n):
        self.n = n
        self.f = f
        self.bucket_levels = list(bucket_levels)
        self.final_rule = final_rule
        self.final_f = final_f
        self.final_n = final_n

    @property
    def num_levels(self):
        return len(self.bucket_levels) + 1

    @property
    def num_buckets(self):
        return len(self.bucket_levels[0].sizes) if self.bucket_levels else 1

    def __repr__(self):
        return (f"<HierPlan n={self.n} f={self.f} "
                f"levels={self.bucket_levels} "
                f"final={self.final_rule}@n={self.final_n},f={self.final_f}>")


def _resolve(bucket_gar, top_gar, bucket_size):
    top_gar = bucket_gar if top_gar is None else top_gar
    bucket_size = DEFAULT_BUCKET_SIZE if bucket_size is None else int(
        bucket_size)
    for rule in (bucket_gar, top_gar):
        if rule not in _TOLERANCE:
            raise ValueError(
                f"hierarchy supports rules {SUPPORTED_RULES}, got {rule!r} "
                "(condense needs an rng key per fold; brute is exponential)"
            )
    if bucket_size < 2:
        raise ValueError(f"bucket_size must be >= 2, got {bucket_size}")
    return bucket_gar, top_gar, bucket_size


def plan_hierarchy(n, f, bucket_gar="krum", top_gar=None, bucket_size=None,
                   levels="auto", _hint=True):
    """Derive the level structure and the per-level f split for (n, f).

    ``levels="auto"`` keeps bucketing while more than ``bucket_size``
    inputs remain (and the next level would still leave the top rule a
    viable final count); an int ``levels >= 2`` fixes the total depth
    (levels - 1 bucketing levels + the final fold, whatever count that
    leaves). Raises ValueError when f cannot be composed — the registered
    rules surface that message through ``check``. (``_hint`` is internal:
    ``max_tolerated_f`` probes with it off so failure messages do not
    recursively re-derive the capacity they are reporting.)
    """
    bucket_gar, top_gar, bucket_size = _resolve(
        bucket_gar, top_gar, bucket_size)
    n = int(n)
    if n < 1:
        raise ValueError(f"expected at least one gradient, got n={n}")
    if not isinstance(f, (int, np.integer)) or isinstance(f, bool) or f < 0:
        raise ValueError(
            f"invalid number of Byzantine clients to tolerate, got f={f!r}, "
            "expected an int >= 0"
        )
    f = int(f)
    if levels != "auto":
        levels = int(levels)
        if levels < 2:
            raise ValueError(f"levels must be >= 2 or 'auto', got {levels}")
    max_bucket_levels = None if levels == "auto" else levels - 1

    bucket_levels = []
    remaining = f
    count = n
    while count > bucket_size and (
        max_bucket_levels is None or len(bucket_levels) < max_bucket_levels
    ):
        num_nat = -(-count // bucket_size)
        is_last = (
            len(bucket_levels) == max_bucket_levels - 1
            if max_bucket_levels is not None
            else num_nat <= bucket_size
        )
        if not is_last:
            sizes = _balanced_into(count, num_nat)
            lo, hi = _tolerance(bucket_gar, min(sizes))
            if hi < lo:
                raise ValueError(
                    f"bucket rule {bucket_gar!r} cannot run on buckets of "
                    f"{min(sizes)} (needs n >= {_min_n(bucket_gar, lo)})"
                )
            f_l = min(hi, max(lo, remaining))
            bucket_levels.append(_Level(sizes, f_l, bucket_gar))
            remaining = remaining // (f_l + 1)
            count = num_nat
            continue
        # Last bucketing level: the bucket count B is ALSO the final fold's
        # input count, so grow B past ceil(count / bucket_size) until the
        # top rule's contract admits the budget B inherits (e.g. krum needs
        # >= 2f+3 summaries — 4 buckets of 32 can never feed a krum top;
        # 5 buckets of ~26 can). Smaller buckets only help the bucket rule,
        # so the search is monotone and bounded by 2-member buckets.
        chosen = None
        for num in range(num_nat, count // 2 + 1):
            lo, hi = _tolerance(bucket_gar, count // num)
            if hi < lo:
                break  # buckets now below the bucket rule's floor
            f_l = min(hi, max(lo, remaining))
            rem2 = remaining // (f_l + 1)
            lo_t, hi_t = _tolerance(top_gar, num)
            f_fin2 = max(lo_t, rem2)
            if num >= _min_n(top_gar, f_fin2) and f_fin2 <= hi_t:
                chosen = (num, f_l, rem2)
                break
        if chosen is None:
            hint = ""
            if _hint:
                cap = max_tolerated_f(n, bucket_gar, top_gar, bucket_size,
                                      levels)
                hint = f" (max composable f = {cap})"
            raise ValueError(
                f"f={f} does not compose: no bucket count over {count} "
                f"inputs gives the top rule {top_gar!r} a viable final "
                f"fold under bucket rule {bucket_gar!r}{hint}"
            )
        num, f_l, remaining = chosen
        bucket_levels.append(
            _Level(_balanced_into(count, num), f_l, bucket_gar))
        count = num
        break

    lo, hi = _tolerance(top_gar, count)
    f_fin = max(lo, remaining)
    if hi < lo or f_fin > hi:
        hint = ""
        if _hint:
            cap = max_tolerated_f(n, bucket_gar, top_gar, bucket_size,
                                  levels)
            hint = f" (max composable f = {cap})"
        raise ValueError(
            f"f={f} does not compose: after {len(bucket_levels)} bucketing "
            f"level(s) the top rule {top_gar!r} over {count} summaries must "
            f"tolerate {f_fin} corrupted summaries but admits at most "
            f"{max(hi, 0)}{hint}"
        )
    return HierPlan(n, f, bucket_levels, top_gar, f_fin, count)


def max_tolerated_f(n, bucket_gar="krum", top_gar=None, bucket_size=None,
                    levels="auto"):
    """Largest global f the hierarchy composes for, or None when even f=0
    is impossible (e.g. the final count is below the top rule's floor).
    The derivation is monotone in f, so binary search is exact."""
    def ok(f):
        try:
            plan_hierarchy(n, f, bucket_gar, top_gar, bucket_size, levels,
                           _hint=False)
            return True
        except ValueError:
            return False

    if not ok(0):
        return None
    lo, hi = 0, max(1, int(n))
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if ok(mid):
            lo = mid
        else:
            hi = mid - 1
    return lo


# --- per-bucket dispatch ----------------------------------------------------


def _rule_kwargs(rule, f):
    # Every supported rule accepts f via **kwargs; krum/tmean/bulyan/aksel
    # require it, median/average ignore it, cclip defaults it.
    return {"f": f}


def _bucket_call(rule, g, f):
    """(s, d) -> (d,) robust fold of one bucket — traced under vmap for the
    wave folds. Coordinate rules at s <= MAX_SORT_N take the jnp sorting
    network (batch-safe everywhere, 15x faster than XLA's variadic sort on
    CPU, bitwise-equal to the reference semantics); everything else runs
    the rule's own fast path (krum/average: the Gram matmul batches
    straight onto the MXU)."""
    s = g.shape[0]
    if s <= _coord.MAX_SORT_N:
        if rule == "median":
            return _coord.sortnet_median(g, axis=0)
        if rule == "tmean":
            return _coord.sortnet_trimmed_mean(g, f, axis=0)
    return gars[rule].unchecked(g, **_rule_kwargs(rule, f))


def _bucket_weights(rule, g, f):
    """(s,) selection weights of one bucket when the rule exposes its
    Gram-form selection (krum, average): the audit signal bucket-level
    exclusions are derived from. Rules without ``gram_select``
    (coordinate-wise medians) have no discrete selection — every member is
    'kept' and only whole-summary exclusions above are attributable."""
    r = gars[rule]
    if r.gram_select is None:
        return jnp.ones((g.shape[0],), jnp.float32)
    acc = jnp.promote_types(g.dtype, jnp.float32)
    gram = jnp.matmul(g, g.T, preferred_element_type=acc)
    return r.gram_select(gram, f)


_JIT_CACHE = {}
_JIT_LOCK = threading.Lock()


def _wave_jit(rule, f, audit):
    """Jitted (W, s, d) -> (W, d) [+ (W, s) weights] vmapped bucket fold.

    ONE callable per (rule, f, audit) — jax retraces per concrete shape, so
    the batch path (W = all buckets of a level) and the streaming path
    (W = wave) share the same program family; per-element results are
    identical across W (pinned by the streaming-vs-batch equality test)."""
    key = ("wave", rule, f, bool(audit))
    with _JIT_LOCK:
        fn = _JIT_CACHE.get(key)
        if fn is None:
            if audit:
                def fold(stack):
                    return (
                        jax.vmap(lambda g: _bucket_call(rule, g, f))(stack),
                        jax.vmap(lambda g: _bucket_weights(rule, g, f))(
                            stack),
                    )
            else:
                def fold(stack):
                    return jax.vmap(lambda g: _bucket_call(rule, g, f))(stack)
            fn = _JIT_CACHE[key] = jax.jit(fold)
    return fn


def _final_jit(rule, f, audit):
    """Jitted (m, d) -> (d,) [+ (m,) weights] final fold."""
    key = ("final", rule, f, bool(audit))
    with _JIT_LOCK:
        fn = _JIT_CACHE.get(key)
        if fn is None:
            if audit:
                def fold(stack):
                    return (_bucket_call(rule, stack, f),
                            _bucket_weights(rule, stack, f))
            else:
                def fold(stack):
                    return _bucket_call(rule, stack, f)
            fn = _JIT_CACHE[key] = jax.jit(fold)
    return fn


def _split_runs(sizes):
    """Contiguous (count, size) runs of equal bucket size — balanced
    partitions have at most two."""
    runs = []
    for s in sizes:
        if runs and runs[-1][1] == s:
            runs[-1][0] += 1
        else:
            runs.append([1, s])
    return [(c, s) for c, s in runs]


def _fold_level(x, level, audit):
    """(count_in, d) -> (num_buckets, d) batch fold of one level (pure jax,
    jit/trace-compatible — the registered hier rules run inside jit'd train
    steps like any flat rule). Returns (summaries, weights|None)."""
    outs, ws = [], []
    off = 0
    for count, size in _split_runs(level.sizes):
        chunk = jax.lax.slice_in_dim(x, off, off + count * size, axis=0)
        stack = chunk.reshape((count, size) + x.shape[1:])
        if audit:
            o, w = _wave_jit(level.rule, level.f, True)(stack)
            ws.append(w.reshape(-1))
        else:
            o = _wave_jit(level.rule, level.f, False)(stack)
        outs.append(o)
        off += count * size
    summaries = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
    weights = None
    if audit:
        weights = ws[0] if len(ws) == 1 else jnp.concatenate(ws)
    return summaries, weights


def aggregate(gradients, f, *, bucket_gar="krum", top_gar=None,
              bucket_size=None, levels="auto", **kwargs):
    """Batch hierarchical aggregation of an (n, d) stack (or vector list).

    Robust within buckets, robust across summaries; see the module
    docstring for the f-composition contract. Pure and jit-compatible with
    static n and f, like every flat rule.
    """
    stack = as_stack(gradients)
    plan = plan_hierarchy(stack.shape[0], f, bucket_gar, top_gar,
                          bucket_size, levels)
    x = stack
    for level in plan.bucket_levels:
        x, _ = _fold_level(x, level, audit=False)
    return _final_jit(plan.final_rule, plan.final_f, False)(x)


def aggregate_with_audit(gradients, f, *, bucket_gar="krum", top_gar=None,
                         bucket_size=None, levels="auto"):
    """(aggregate, audit): the batch fold plus per-client observed/selected
    weights — 'selected' is the product of the client's in-bucket selection
    (binary, from ``gram_select`` where the rule exposes one) and the
    survival of every summary above it. The streaming reducer emits the
    same signal as a ``hier_exclusion`` telemetry event."""
    stack = as_stack(gradients)
    n = stack.shape[0]
    plan = plan_hierarchy(n, f, bucket_gar, top_gar, bucket_size, levels)
    keep = np.ones(n, np.float32)
    spans = [(i, i + 1) for i in range(n)]
    x = stack
    for level in plan.bucket_levels:
        x, w = _fold_level(x, level, audit=True)
        w = np.asarray(w)
        new_spans, off = [], 0
        for size in level.sizes:
            members = spans[off:off + size]
            for j, (a, b) in enumerate(members):
                if w[off + j] == 0:
                    keep[a:b] = 0.0
            new_spans.append((members[0][0], members[-1][1]))
            off += size
        spans = new_spans
    agg, w_fin = _final_jit(plan.final_rule, plan.final_f, True)(x)
    w_fin = np.asarray(w_fin)
    for j, (a, b) in enumerate(spans):
        if w_fin[j] == 0:
            keep[a:b] = 0.0
    return agg, {
        "observed": np.ones(n, np.float32),
        "selected": keep,
        "plan": plan,
    }


def check(gradients, f, *, bucket_gar="krum", top_gar=None, bucket_size=None,
          levels="auto", **kwargs):
    """Registry-style check: None when (n, f) composes, else the message."""
    n = num_gradients(gradients)
    try:
        plan_hierarchy(n, f, bucket_gar, top_gar, bucket_size, levels)
    except (ValueError, TypeError) as e:
        return str(e)
    return None


def upper_bound(n, f, d, *, bucket_gar="krum", top_gar=None,
                bucket_size=None, levels="auto"):
    """Conservative composed variance/norm bound: the minimum of the
    per-level flat bounds (each level must hold for its own inputs, so the
    tightest level governs). None when no constituent exposes a bound."""
    plan = plan_hierarchy(n, f, bucket_gar, top_gar, bucket_size, levels)
    bounds = []
    for level in plan.bucket_levels:
        ub = gars[level.rule].upper_bound
        if ub is not None:
            bounds.append(ub(min(level.sizes), level.f, d))
    ub = gars[plan.final_rule].upper_bound
    if ub is not None:
        bounds.append(ub(plan.final_n, plan.final_f, d))
    return min(bounds) if bounds else None


def tree_aggregate(grads_tree, f, *, bucket_gar="krum", top_gar=None,
                   bucket_size=None, levels="auto", key=None, **kwargs):
    """Stacked-tree twin: concat-first (the Bulyan/cclip layout,
    _common.concat_stack) — one axis-1 concat, the flat hierarchy, one
    unflatten. At hierarchy scale the (n, d) stack dominates anyway; the
    twin exists so the hier rules slot into the topologies' tree dispatch
    like any registered rule."""
    leaves, treedef = jax.tree.flatten(grads_tree)
    stack, shapes = concat_stack(leaves)
    vec = aggregate(stack, f, bucket_gar=bucket_gar, top_gar=top_gar,
                    bucket_size=bucket_size, levels=levels)
    return unflatten_vec(vec, treedef, shapes)


# --- streaming ingest -------------------------------------------------------


class StreamingAggregator:
    """Wave-based streaming hierarchical reducer (see module docstring).

    Clients join buckets in ARRIVAL order: position k lands in the bucket
    covering k under the plan's contiguous balanced partition. Completed
    buckets fold in vmapped waves of ``wave_buckets`` (plus one
    smaller fold at each bucket-size run boundary), their summaries cascade
    into the next level's state immediately, and ``finalize`` flushes the
    levels and runs the final fold — so peak memory is
    O(levels · wave · bucket_size · d), never O(n · d).

    Thread-safe: ``push``/``push_frame``/``wire_transform`` may be called
    from ``PeerExchange`` waiter threads concurrently.
    """

    def __init__(self, n, f, *, bucket_gar="krum", top_gar=None,
                 bucket_size=None, levels="auto", wave_buckets=8,
                 audit=False, telemetry=False, d=None, double_buffer=None):
        self.plan = plan_hierarchy(n, f, bucket_gar, top_gar, bucket_size,
                                   levels)
        self.n = int(n)
        self.f = int(f)
        self.wave = max(1, int(wave_buckets))
        self._telemetry = bool(telemetry)
        self._audit = bool(audit) or self._telemetry
        # Double-buffered wave fold (GARFIELD_HIER_DOUBLE_BUFFER, default
        # on; ``double_buffer=`` overrides for the equality tests): each
        # level keeps TWO wave buffers, a dispatched wave folds on device
        # while ingest threads fill the other buffer, and the blocking
        # summary readback moves to the next wave's dispatch (the swap
        # point). Fold boundaries and cascade order are unchanged, so
        # streaming==batch bitwise equality is untouched; the cost is one
        # extra O(wave · bucket · d) buffer per level.
        if double_buffer is None:
            double_buffer = os.environ.get(
                "GARFIELD_HIER_DOUBLE_BUFFER", "1"
            ).lower() not in ("", "0", "false")
        self._double = bool(double_buffer)
        from ..utils import wire as _wire

        self._fused = _wire.wire_fused()
        self._lock = threading.RLock()
        self._arrived = 0
        # Row width: learned from the first ingested row, or pinned up
        # front via ``d``. Wire-facing deployments SHOULD pin it — it is
        # what lets push_frame bound a sparse frame's claimed dense size
        # BEFORE the scatter allocates (see push_frame).
        if d is not None and int(d) < 1:
            raise ValueError(f"row width d must be >= 1, got {d}")
        self._d = int(d) if d is not None else None
        self._keep = np.ones(self.n, np.float32) if self._audit else None
        # Per bucketing level: a PREALLOCATED contiguous wave buffer
        # (allocated lazily once d is known) + the pending rows' client
        # spans and the index of the next bucket to fold. Contiguity is a
        # measured 1.65x on the whole streaming path vs a list-of-rows +
        # np.stack design: each ingest is one row memcpy and each fold
        # hands XLA one contiguous (take, size, d) view. ing_t0/ing_dur
        # accumulate the wall start + duration of the row copies feeding
        # the level's NEXT wave (tracing on only), reported as ONE
        # hier_ingest span per dispatched wave (trace.emit) so ingest
        # attribution counts align 1:1 with hier_wave/hier_h2d.
        self._levels = [
            {"level": lv, "bufs": [None, None], "active": 0,
             "pending": None, "fill": 0, "spans": [], "cursor": 0,
             "ing_t0": None, "ing_dur": 0.0}
            for lv in self.plan.bucket_levels
        ]
        self._final_rows = []
        self._final_spans = []
        self._result = None

    # -- ingestion ----------------------------------------------------------

    def push(self, vec):
        """Ingest one client gradient (numpy/jax vector, any shape —
        raveled); returns the client's arrival index."""
        with self._lock:
            return self._push_one(vec)

    def push_many(self, rows, *, stable=False):
        """Ingest a (k, d) block of clients in row order (one lock
        acquisition; the bench's wave ingest path). Returns the arrival
        index of the first row.

        Bulk path: the block is copied into the level-0 wave buffer in
        contiguous chunks (arrival order IS bucket order, so a block
        lands as one or two memcpys per drain cycle) instead of the
        per-row ``_push_one`` loop — at federated-shard widths (d/S a
        few thousand) the per-row Python overhead otherwise dominates
        the fold and flattens the 1/S round-time scaling FEDBENCH
        measures. Fold boundaries are unchanged (``_drain`` triggers at
        the same cursor positions regardless of ingest granularity), so
        streaming-vs-batch bitwise equality holds verbatim.

        ``stable=True`` promises the caller's block is STABLE: it stays
        alive and unwritten until after the NEXT wave dispatch (or
        finalize) — e.g. an immutable round pool. Whole waves then fold
        directly on slices of ``rows`` (jnp.asarray is zero-copy for
        aligned C-contiguous f32 on the CPU backend), skipping the
        staging memcpy entirely — at 10^6 clients × d=10^4 that is
        ~10 MB/wave of pure overhead removed. Fold boundaries, cascade
        order and per-bucket programs are IDENTICAL, so the result
        stays bitwise equal to the copying path (pinned). Blocks that
        are not C-contiguous f32 (e.g. a sharded column slice) fall
        back to the copy path automatically; so do tail rows that do
        not complete a wave.
        """
        rows = np.asarray(rows, np.float32)
        if rows.ndim != 2:
            rows = rows.reshape(len(rows), -1)
        with self._lock:
            first = self._arrived
            k = rows.shape[0]
            if k == 0:
                return first
            if self._result is not None:
                raise RuntimeError("finalize() already ran")
            if self._arrived + k > self.n:
                raise ValueError(
                    f"pushing {k} rows past the {self.n}-client plan "
                    f"({self._arrived} already ingested)"
                )
            if self._d is None:
                self._d = rows.shape[1]
            elif rows.shape[1] != self._d:
                raise ValueError(
                    f"rows have {rows.shape[1]} elements, expected "
                    f"{self._d}"
                )
            if not self._levels:
                # n <= bucket_size: rows feed the final fold directly.
                for j in range(k):
                    idx = self._arrived
                    self._arrived += 1
                    self._final_rows.append(rows[j].copy())
                    self._final_spans.append((idx, idx + 1))
                return first
            state = self._levels[0]
            i = 0
            if (stable and state["fill"] == 0
                    and rows.dtype == np.float32
                    and rows.flags["C_CONTIGUOUS"]):
                # Zero-copy wave dispatch straight off the caller's
                # block. Only whole waves (as _ready would cut them off
                # an empty buffer) qualify; the tail falls through to
                # the copy loop below.
                while i < k:
                    take, size = self._ready(state, False, avail=k - i)
                    if take == 0:
                        break
                    used = take * size
                    base = self._arrived
                    if self._audit:
                        spans = [(base + j, base + j + 1)
                                 for j in range(used)]
                    else:
                        spans = base  # dense spans, see _drain
                    self._arrived += used
                    self._dispatch_wave(0, state, take, size,
                                        rows[i:i + used], spans,
                                        from_buf=False)
                    i += used
            while i < k:
                # Re-fetched EVERY iteration: the _drain below swaps the
                # active buffer in double-buffer mode, so a cached ``buf``
                # would keep writing rows into the buffer the in-flight
                # wave aliases (caught by the streaming==batch pin).
                buf = self._buf_for(state)
                take = min(k - i, buf.shape[0] - state["fill"])
                if take <= 0:  # full buffer with nothing drainable: bug
                    raise RuntimeError("level-0 wave buffer stalled")
                fill = state["fill"]
                if _trace.enabled():
                    t0w, t0 = time.time(), time.perf_counter()
                    buf[fill:fill + take] = rows[i:i + take]
                    if state["ing_t0"] is None:
                        state["ing_t0"] = t0w
                    state["ing_dur"] += time.perf_counter() - t0
                else:
                    buf[fill:fill + take] = rows[i:i + take]
                if self._audit:
                    base = self._arrived
                    state["spans"].extend(
                        (base + j, base + j + 1) for j in range(take)
                    )
                state["fill"] = fill + take
                self._arrived += take
                i += take
                self._drain(0, flush=False)
            return first

    def push_frame(self, buf, *, expect_plane=None, expect_epoch=None):
        """Ingest one typed wire frame (utils/wire.py). A frame that fails
        the codec raises WireError — ban evidence for the caller, exactly
        like the cluster quorum paths. ``expect_plane``/``expect_epoch``
        thread straight to the codec's header pins (a cross-plane or
        stale-epoch frame rejects before any payload work).

        Once the row width is known (the ctor's ``d``, or the first
        ingested row) it pins the frame's element count, so a sparse
        frame claiming a huge dense size rejects BEFORE the scatter
        allocates (wire.decode's expect_elems). Before the width is
        known, a sparse frame is refused outright: its dense size is a
        bare header claim nothing here can corroborate, i.e. a
        sender-controlled allocation — wire-facing deployments pass
        ``d=`` at construction to accept a sparse first frame.

        Fused path (GARFIELD_WIRE_FUSED_DECODE, default on): once the
        row width is known the frame dequantizes/scatters DIRECTLY into
        the level-0 wave buffer slot it will occupy (wire.decode_into)
        — no O(d) transient array per frame, one memory pass instead of
        decode + memcpy. Identical bytes, identical validation: a
        rejected frame raises BEFORE the first write, so the slot is
        never claimed nor scribbled on, and the arrival index commits
        only after the decode succeeds."""
        from ..utils import wire

        d = self._d
        if d is None and wire.frame_scheme(buf) == "topk":
            raise wire.WireError(
                "sparse frame arrived before the reducer's row width is "
                "known — its dense element count is an unverifiable "
                "header claim (sender-controlled allocation); construct "
                "the StreamingAggregator with d= to accept sparse first "
                "frames"
            )
        if d is not None and self._fused and self._levels:
            with self._lock:
                if self._result is not None:
                    raise RuntimeError("finalize() already ran")
                if self._arrived >= self.n:
                    raise ValueError(
                        f"already ingested all {self.n} clients"
                    )
                state = self._levels[0]
                row = self._buf_for(state)[state["fill"]]
                if _trace.enabled():
                    t0w, t0 = time.time(), time.perf_counter()
                    wire.decode_into(buf, row, expect_elems=d,
                                     expect_plane=expect_plane,
                                     expect_epoch=expect_epoch)
                    if state["ing_t0"] is None:
                        state["ing_t0"] = t0w
                    state["ing_dur"] += time.perf_counter() - t0
                else:
                    wire.decode_into(buf, row, expect_elems=d,
                                     expect_plane=expect_plane,
                                     expect_epoch=expect_epoch)
                idx = self._arrived
                self._arrived += 1
                state["fill"] += 1
                if self._audit:
                    state["spans"].append((idx, idx + 1))
                self._drain(0, flush=False)
                return idx
        return self.push(wire.decode(buf, expect_elems=d,
                                     expect_plane=expect_plane,
                                     expect_epoch=expect_epoch))

    def push_frames(self, bufs, *, expect_plane=None, expect_epoch=None):
        """Bulk wire ingest: decode a batch of frames DIRECTLY into
        consecutive level-0 wave-buffer rows via one
        ``wire.decode_batch_into`` pass (vectorized header screen,
        same-scheme slab dequant — see utils/wire.py), zero intermediate
        copies. Returns a list the length of ``bufs``: the frame's
        arrival index, or the ``WireError`` that rejected it.

        Per-frame isolation is the whole contract: one forged frame
        yields its indexed WireError (the sender's ban evidence) while
        every batchmate decodes bit-identically to a ``push_frame`` loop
        — rejected frames never claim an arrival index, never touch a
        buffer row that survives (accepted rows behind a reject are
        compacted down so the wave stays contiguous), and never shift a
        batchmate's fold boundary relative to the frames that actually
        landed.

        Falls back to a per-frame ``push_frame`` loop (same results
        list, exceptions caught per index) when the row width is not yet
        known, the fused path is off, there are no bucketing levels, or
        ``GARFIELD_WIRE_BATCH_DECODE`` disables batching. Raises
        ValueError up front if the batch could not fit the plan even
        with zero rejects (conservative: the caller sized the round)."""
        from ..utils import wire

        bufs = list(bufs)
        k = len(bufs)
        results = [None] * k
        if k == 0:
            return results
        if not (self._d is not None and self._fused and self._levels
                and wire.wire_batch_decode()):
            for i, b in enumerate(bufs):
                try:
                    results[i] = self.push_frame(
                        b, expect_plane=expect_plane,
                        expect_epoch=expect_epoch)
                except wire.WireError as err:
                    results[i] = err
            return results
        d = self._d
        with self._lock:
            if self._result is not None:
                raise RuntimeError("finalize() already ran")
            if self._arrived + k > self.n:
                raise ValueError(
                    f"pushing {k} frames past the {self.n}-client plan "
                    f"({self._arrived} already ingested)"
                )
            state = self._levels[0]
            i = 0
            while i < k:
                # Re-fetched every iteration (double-buffer swap), like
                # push_many.
                buf = self._buf_for(state)
                fill = state["fill"]
                take = min(k - i, buf.shape[0] - fill)
                if take <= 0:
                    raise RuntimeError("level-0 wave buffer stalled")
                if _trace.enabled():
                    t0w, t0 = time.time(), time.perf_counter()
                res = wire.decode_batch_into(
                    bufs[i:i + take], buf[fill:fill + take],
                    expect_elems=d, expect_plane=expect_plane,
                    expect_epoch=expect_epoch)
                # Compact accepted rows over rejected holes: row j only
                # moves DOWN (ngood <= j), each accepted frame's bytes
                # are already fully decoded, and rejected frames' target
                # rows were never written — so the surviving wave is
                # exactly what a push_frame loop over the accepted
                # frames would have staged.
                base = self._arrived
                ngood = 0
                for j, r in enumerate(res):
                    if isinstance(r, wire.WireError):
                        results[i + j] = r
                        continue
                    if ngood != j:
                        buf[fill + ngood] = buf[fill + j]
                    results[i + j] = base + ngood
                    ngood += 1
                if _trace.enabled():
                    if state["ing_t0"] is None:
                        state["ing_t0"] = t0w
                    state["ing_dur"] += time.perf_counter() - t0
                if self._audit:
                    state["spans"].extend(
                        (base + j, base + j + 1) for j in range(ngood)
                    )
                state["fill"] = fill + ngood
                self._arrived += ngood
                i += take
                self._drain(0, flush=False)
            return results

    def wire_transform(self, idx, payload):
        """``PeerExchange`` transform hook: decode + ingest in the waiter
        thread the moment the frame lands (collect/compute overlap), return
        the arrival index as the peer's collect result. A WireError
        propagates to the exchange, which stores it as the peer's
        attributable result."""
        return self.push_frame(payload)

    def wire_batch_transform(self, items):
        """``PeerExchange`` batch_transform hook (collect_begin): the
        harvest hands every latched ``(peer, frame)`` here at once and
        the whole quorum ingests through ONE ``push_frames`` /
        ``decode_batch_into`` pass. Returns one arrival index or
        WireError per item — the exchange stores an exception as that
        peer's ban evidence, same attribution as the per-frame
        ``wire_transform``."""
        return self.push_frames([p for _, p in items])

    def _push_one(self, vec):
        if self._result is not None:
            raise RuntimeError("finalize() already ran")
        if self._arrived >= self.n:
            raise ValueError(f"already ingested all {self.n} clients")
        vec = np.asarray(vec, np.float32).reshape(-1)
        if self._d is None:
            self._d = vec.size
        elif vec.size != self._d:
            raise ValueError(
                f"client {self._arrived} has {vec.size} elements, "
                f"expected {self._d}"
            )
        idx = self._arrived
        self._arrived += 1
        self._ingest(0, vec, (idx, idx + 1))
        return idx

    def _buf_for(self, state):
        i = state["active"]
        if state["bufs"][i] is None:
            # One wave of the level's largest buckets plus spill room for
            # the partially-filled next bucket — folds trigger the moment
            # a wave (or a size-run tail) completes, so fill never
            # exceeds this. The second buffer (double-buffer mode only)
            # allocates lazily on the first swap.
            cap = (self.wave + 1) * max(state["level"].sizes)
            state["bufs"][i] = np.empty((cap, self._d), np.float32)
        return state["bufs"][i]

    def _ingest(self, lvl_idx, row, span):
        if lvl_idx == len(self._levels):
            self._final_rows.append(row)
            self._final_spans.append(span)
            return
        state = self._levels[lvl_idx]
        buf = self._buf_for(state)
        if _trace.enabled():
            # Accumulate this slice into the level's per-wave ingest
            # span (drained by _dispatch_wave); zero clock reads when
            # tracing is off — the zero-cost contract.
            t0w, t0 = time.time(), time.perf_counter()
            buf[state["fill"]] = row
            if state["ing_t0"] is None:
                state["ing_t0"] = t0w
            state["ing_dur"] += time.perf_counter() - t0
        else:
            buf[state["fill"]] = row
        state["fill"] += 1
        if self._audit or lvl_idx > 0:
            # Level-0 spans with audit off are reconstructed
            # arithmetically in _drain — skip the tuple churn.
            state["spans"].append(span)
        self._drain(lvl_idx, flush=False)

    def reset(self):
        """Re-arm the reducer for a fresh pass over the SAME (n, f,
        rules) plan, keeping the allocated wave buffers and the cached
        fold programs — the federated round engine runs one pass per
        ROUND, and reallocating O(levels · wave · bucket · d) buffers
        every round is measurable at bench scale. Equivalent to a fresh
        construction bit for bit (the buffers are fully overwritten
        before any fold reads them)."""
        with self._lock:
            self._arrived = 0
            self._result = None
            if self._keep is not None:
                self._keep = np.ones(self.n, np.float32)
            for state in self._levels:
                state["fill"] = 0
                state["spans"] = []
                state["cursor"] = 0
                # A dropped in-flight wave only READS its buffer; its
                # result is never consumed, so the fresh round may refill
                # immediately.
                state["pending"] = None
                state["active"] = 0
                state["ing_t0"] = None
                state["ing_dur"] = 0.0
            self._final_rows = []
            self._final_spans = []

    # -- folding ------------------------------------------------------------

    def _ready(self, state, flush, avail=None):
        """(take, size): how many same-size complete buckets to fold now.

        Folds trigger at a full wave, at the end of an equal-size run (the
        balanced partition has at most one boundary per level — waiting for
        a wave that can never fill would grow the buffer unboundedly), or
        at flush time. ``avail`` overrides the buffered-row count for the
        zero-copy stable path, which folds straight out of the caller's
        block without staging rows in the wave buffer first.
        """
        sizes = state["level"].sizes
        cur = state["cursor"]
        if cur >= len(sizes):
            return 0, 0
        size = sizes[cur]
        if avail is None:
            avail = state["fill"]
        take, used = 0, 0
        while (cur + take < len(sizes) and sizes[cur + take] == size
               and used + size <= avail and take < self.wave):
            used += size
            take += 1
        if take == 0:
            return 0, 0
        run_ends = cur + take == len(sizes) or sizes[cur + take] != size
        if take == self.wave or run_ends or flush:
            return take, size
        return 0, 0

    def _dispatch_wave(self, lvl_idx, state, take, size, src, spans, *,
                       from_buf):
        """Dispatch one wave fold on ``src`` (a contiguous (take*size, d)
        f32 block: the level's wave buffer prefix, or — the zero-copy
        stable path — a slice of the caller's own block).

        jnp.asarray of an aligned f32 numpy array is ZERO-COPY on the CPU
        backend (the stack aliases ``src``) — safe ONLY because the
        ``np.asarray(out)`` readback blocks before ``src`` is written
        again. Sync mode blocks right here; double-buffer mode moves the
        block to the NEXT wave's dispatch (``_complete_pending`` below,
        the swap point), so the fold overlaps ingest filling the other
        buffer. ``from_buf=False`` (stable path) extends that contract to
        the CALLER: their block must stay alive and unwritten until the
        next wave's dispatch (or flush) reads this one back.

        Trace spans (schema v5/v12/v15): hier_h2d is the staging of one
        wave, hier_wave its dispatch (+ readback in sync mode), and the
        level's ingest accumulator drains here as ONE pre-timed
        hier_ingest record per wave — emitted even when the accumulated
        duration is zero (the stable path's whole point), so per-level
        span counts obey count(hier_ingest) == count(hier_wave) ==
        count(hier_h2d) exactly (the FEDBENCH_r02 undercount fix).
        """
        level = state["level"]
        if _trace.enabled():
            t0 = state["ing_t0"]
            _trace.emit("hier_ingest",
                        time.time() if t0 is None else t0,
                        state["ing_dur"], level=int(lvl_idx),
                        buckets=int(take), size=int(size))
            state["ing_t0"] = None
            state["ing_dur"] = 0.0
        with _trace.span("hier_wave", level=int(lvl_idx),
                         buckets=int(take), size=int(size)):
            with _trace.span("hier_h2d", level=int(lvl_idx),
                             buckets=int(take), size=int(size)):
                stack = jnp.asarray(src.reshape(take, size, -1))
            fn = _wave_jit(level.rule, level.f, self._audit)
            if self._audit:
                out, w = fn(stack)
            else:
                out, w = fn(stack), None
            if not self._double:
                # blocks: summaries host-side, frees src
                out = np.asarray(out)
                if w is not None:
                    w = np.asarray(w)
        del stack
        # The dispatched buckets leave the level's accounting NOW —
        # ``_ready`` must see the cursor past them whether or not their
        # summaries have landed host-side yet.
        state["cursor"] += take
        if self._double:
            # Swap point: the previous wave's readback must land before
            # the buffer it aliased is written again — the sync
            # invariant, one wave later. Completing FIRST also keeps the
            # cascade in bucket order, which is what pins
            # streaming==batch.
            self._complete_pending(lvl_idx)
            state["pending"] = {"out": out, "w": w, "spans": spans,
                                "take": take, "size": size}
            if from_buf:
                state["active"] ^= 1
        else:
            self._cascade(lvl_idx, out, w, spans, take, size)

    def _drain(self, lvl_idx, flush):
        state = self._levels[lvl_idx]
        while True:
            take, size = self._ready(state, flush)
            if take == 0:
                break
            used = take * size
            buf = self._buf_for(state)
            if lvl_idx == 0 and not self._audit:
                # Dense-span arithmetic: with audit off, level-0 spans
                # are ALWAYS width-1 consecutive rows, so the whole
                # tuple list collapses to one int — the arrival index of
                # pending row 0 (``_cascade`` rebuilds any bucket's span
                # from it). At 10^6 clients/round this skips building
                # 10^6 throwaway tuples on the hot ingest path.
                spans = self._arrived - state["fill"]
            else:
                spans = state["spans"][:used]
                del state["spans"][:used]
            self._dispatch_wave(lvl_idx, state, take, size, buf[:used],
                                spans, from_buf=True)
            left = state["fill"] - used
            if self._double:
                # ``active`` swapped inside _dispatch_wave: shift the
                # spill (the partially-filled next bucket) into the
                # OTHER buffer — the dispatched wave still aliases
                # ``buf``, which is only read from here on.
                other = self._buf_for(state)
                if left:
                    other[:left] = buf[used:state["fill"]]
                state["fill"] = left
            else:
                # Shift the spill to the buffer front; at most one
                # bucket's worth, so the copy is negligible next to the
                # fold it unblocks.
                if left:
                    buf[:left] = buf[used:state["fill"]].copy()
                state["fill"] = left
        if flush:
            self._complete_pending(lvl_idx)

    def _complete_pending(self, lvl_idx):
        """Block on the in-flight wave's summary readback and cascade it —
        the double-buffer swap point. The buffer the wave aliased is free
        for refill the moment this returns. No-op in sync mode (nothing is
        ever pending) or when no wave is in flight."""
        state = self._levels[lvl_idx]
        p, state["pending"] = state["pending"], None
        if p is None:
            return
        with _trace.span("hier_fold_wait", level=int(lvl_idx),
                         buckets=int(p["take"]), size=int(p["size"])):
            out = np.asarray(p["out"])
            w = np.asarray(p["w"]) if p["w"] is not None else None
        self._cascade(lvl_idx, out, w, p["spans"], p["take"], p["size"])

    def _cascade(self, lvl_idx, out, w, spans, take, size):
        """Host-side tail of one completed wave: audit bookkeeping and the
        summary cascade into the next level (identical for the sync and
        double-buffered paths — completion order is bucket order in both,
        so the upper levels see the exact same ingest sequence)."""
        excluded = 0
        if isinstance(spans, (int, np.integer)):
            # Dense level-0 spans (audit off — see _drain): bucket b
            # covers arrival indices [lo + b*size, lo + (b+1)*size).
            lo = int(spans)
            for b in range(take):
                self._ingest(lvl_idx + 1, out[b],
                             (lo + b * size, lo + (b + 1) * size))
        else:
            for b in range(take):
                members = spans[b * size:(b + 1) * size]
                if self._audit:
                    for j, (a, bb) in enumerate(members):
                        if w[b, j] == 0:
                            self._keep[a:bb] = 0.0
                            excluded += 1
                bspan = (members[0][0], members[-1][1])
                self._ingest(lvl_idx + 1, out[b], bspan)
        if self._telemetry:
            from ..telemetry import hub as _hub

            _hub.emit_event(
                "hier_wave", level=lvl_idx, buckets=int(take),
                size=int(size), excluded_members=int(excluded),
            )

    def finalize(self):
        """Flush every level, run the final fold, return the (d,) numpy
        aggregate (idempotent). Raises unless all n clients arrived."""
        with self._lock:
            if self._result is not None:
                return self._result
            if self._arrived != self.n:
                raise ValueError(
                    f"only {self._arrived}/{self.n} clients ingested"
                )
            with _trace.span("hier_finalize", levels=len(self._levels)):
                for lvl_idx in range(len(self._levels)):
                    self._drain(lvl_idx, flush=True)
                stack = jnp.asarray(np.stack(self._final_rows))
                fn = _final_jit(self.plan.final_rule, self.plan.final_f,
                                self._audit)
                if self._audit:
                    out, w_fin = fn(stack)
                    w_fin = np.asarray(w_fin)
                    for j, (a, b) in enumerate(self._final_spans):
                        if w_fin[j] == 0:
                            self._keep[a:b] = 0.0
                else:
                    out = fn(stack)
                self._result = np.asarray(out)
            self._final_rows = []
            if self._telemetry:
                from ..telemetry import hub as _hub

                _hub.emit_event(
                    "hier_exclusion",
                    observed=[1.0] * self.n,
                    selected=[float(v) for v in self._keep],
                    buckets=self.plan.num_buckets,
                    levels=self.plan.num_levels,
                )
            return self._result

    def audit(self):
        """Per-client observed/selected (after finalize) — the same signal
        ``aggregate_with_audit`` returns and the telemetry event carries."""
        if not self._audit:
            raise ValueError("reducer built without audit/telemetry")
        return {
            "observed": np.ones(self.n, np.float32),
            "selected": None if self._keep is None else self._keep.copy(),
        }


# --- registry ---------------------------------------------------------------


def parse_hier_name(name):
    """'hier-<bucket>[-<top>]' -> (bucket_gar, top_gar|None)."""
    parts = name.split("-")
    if len(parts) < 2 or parts[0] != "hier":
        raise ValueError(f"not a hierarchical rule name: {name!r}")
    if len(parts) == 2:
        return parts[1], None
    if len(parts) == 3:
        return parts[1], parts[2]
    raise ValueError(f"not a hierarchical rule name: {name!r}")


def make_hier_gar(bucket_gar, top_gar=None, *, bucket_size=None,
                  levels="auto", name=None):
    """Build + register one hierarchical GAR. Rule resolution is lazy (the
    registry auto-import reaches this module before krum/median register),
    so construction never touches ``gars``."""
    bucket_gar_r, top_gar_r, bucket_size = _resolve(
        bucket_gar, top_gar, bucket_size)
    if name is None:
        name = f"hier-{bucket_gar_r}" + (
            "" if top_gar is None or top_gar == bucket_gar_r
            else f"-{top_gar_r}"
        )
    cfg = dict(bucket_gar=bucket_gar_r, top_gar=top_gar_r,
               bucket_size=bucket_size, levels=levels)

    def _aggregate(gradients, f, **kwargs):
        return aggregate(gradients, f, **cfg)

    def _check(gradients, f, **kwargs):
        return check(gradients, f, **cfg)

    def _upper_bound(n, f, d):
        return upper_bound(n, f, d, **cfg)

    def _tree_aggregate(grads_tree, f, key=None, **kwargs):
        return tree_aggregate(grads_tree, f, **cfg)

    return register(name, _aggregate, _check, upper_bound=_upper_bound,
                    tree_aggregate=_tree_aggregate)


# Default instances: same-rule hierarchies for the bench grid plus the two
# cross combinations the composition tests exercise.
make_hier_gar("krum")
make_hier_gar("median")
make_hier_gar("tmean")
make_hier_gar("krum", "median")
make_hier_gar("median", "krum")

# ``hier`` alias: the deployment-picked hierarchy, configured as
# GARFIELD_HIER_GAR="<bucket>[:<top>]" (default krum at both levels).
_env = os.environ.get("GARFIELD_HIER_GAR", "krum").strip() or "krum"
try:
    _b, _, _t = _env.partition(":")
    make_hier_gar(_b, _t or None, name="hier")
except ValueError as _e:
    tools.warning(f"GARFIELD_HIER_GAR={_env!r} invalid ({_e}); "
                  "defaulting hier=krum")
    make_hier_gar("krum", name="hier")
del _env

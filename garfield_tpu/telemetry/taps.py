"""In-graph GAR audit taps: fixed-shape selection evidence per step.

A ``TapBundle`` is a plain dict pytree with one fixed layout for every
rule, so the host aggregator and the exporters never branch on the GAR:

  - ``observed``  (n,) f32 — 1 where the rank's row was inside the quorum
    the rule aggregated (the wait-n-f subset; all-ones without subsets);
  - ``selected``  (n,) f32 in [0, 1] — the rank's influence on the
    aggregate: a hard 0/1 selection indicator for selection rules
    (krum, bulyan, brute, aksel), the final clip weight for cclip, and
    the (clipped) share of coordinate wins for the coordinate-wise rules
    (median, tmean) — "median fall-through";
  - ``score``     (n,) f32 — the rule's own per-rank score (krum's
    distance score, aksel's distance-to-median, cclip's radii, the
    coordinate-win share for median/tmean). Semantics are per-rule; the
    suspicion statistic uses only ``observed``/``selected``;
  - ``tau``       () f32 — cclip's final clip threshold (0 elsewhere);
  - ``clip_frac`` () f32 — fraction of observed ranks cclip clipped
    (0 elsewhere).

Taps are recomputed from the SAME poisoned stack and PRNG keys the GAR
consumed, so they are pure observers: nothing they compute flows into
``TrainState``, which is what makes taps-on trajectories bitwise equal to
taps-off (asserted in tests/test_telemetry.py). On the flat aggregation
path XLA CSEs the recomputation against the rule's own; on the tree/fold
fast paths the tap pays one extra flatten + attack + selection pass —
only when telemetry is enabled (the topologies trace no tap code when it
is off).

Caveats (documented, deliberate): randomized attacks (random/drop) fold
their key per LEAF on the tree where-path, so the tap — computed on the
flat stack — sees a distributionally-identical but not bitwise-equal
poison there; cclip taps in the LEARN topology use a median-init center
(the per-node carried centers differ across observers); ``condense``'s
coordinate-Bernoulli mixing has no per-rank selection, so it reports the
uniform fallback bundle.
"""

import jax
import jax.numpy as jnp

__all__ = ["TAP_KEYS", "zeros", "compute_flat", "scatter"]

TAP_KEYS = ("observed", "selected", "score", "tau", "clip_frac")


def zeros(n):
    """All-zero TapBundle template for n logical ranks."""
    return {
        "observed": jnp.zeros((n,), jnp.float32),
        "selected": jnp.zeros((n,), jnp.float32),
        "score": jnp.zeros((n,), jnp.float32),
        "tau": jnp.zeros((), jnp.float32),
        "clip_frac": jnp.zeros((), jnp.float32),
    }


def _uniform(n):
    b = zeros(n)
    b["observed"] = jnp.ones((n,), jnp.float32)
    b["selected"] = jnp.ones((n,), jnp.float32)
    return b


def _coordinate_share(stack, member):
    """(n,) share of coordinate wins from a boolean membership matrix.

    ``member[i, d]`` marks row i's value as surviving at coordinate d
    (equal to the median / inside the trimmed window). Shares are
    normalized per coordinate (ties split the win) and averaged over d,
    then scaled by n so the uniform rule reports 1.0 — the "median
    fall-through" signal: an excluded rank's share collapses toward 0.
    """
    n = stack.shape[0]
    cnt = jnp.maximum(jnp.sum(member, axis=0, keepdims=True), 1)
    share = jnp.mean(member / cnt, axis=1)  # (n,), sums to ~1 over ranks
    return jnp.clip(n * share, 0.0, 1.0), share


def _tap_krum(stack, f, params):
    from ..aggregators import krum as _krum
    from ..aggregators._common import pairwise_distances

    n = stack.shape[0]
    m = params.get("m") or n - f - 2
    dist = pairwise_distances(stack)
    score = _krum._scores_from_dist(dist, n, f)
    w = _krum._selection_weights_from_dist(dist, n, f, m)
    b = _uniform(n)
    b["selected"] = (w > 0).astype(jnp.float32)
    b["score"] = jnp.nan_to_num(score, posinf=0.0).astype(jnp.float32)
    return b


def _tap_brute(stack, f, params):
    from ..aggregators import brute as _brute
    from ..aggregators._common import pairwise_distances

    n = stack.shape[0]
    w = _brute._selection_weights_from_dist(
        pairwise_distances(stack, exclude_self=False), n, f
    )
    b = _uniform(n)
    b["selected"] = (w > 0).astype(jnp.float32)
    b["score"] = b["selected"]
    return b


def _tap_bulyan(stack, f, params):
    from ..aggregators import bulyan as _bulyan
    from ..aggregators._common import pairwise_distances

    n = stack.shape[0]
    m = params.get("m") or n - f - 2
    dist = pairwise_distances(stack)
    weights = _bulyan._selection_weight_matrix(dist, n, f, m, jnp.float32)
    wsum = jnp.sum(weights, axis=0)  # total phase-1 influence per rank
    b = _uniform(n)
    b["selected"] = (wsum > 0).astype(jnp.float32)
    b["score"] = wsum
    return b


def _tap_aksel(stack, f, params):
    from ..aggregators import aksel as _aksel
    from ..aggregators._common import coordinate_median

    n = stack.shape[0]
    mode = params.get("mode", "mid")
    med = coordinate_median(stack)
    dist = jnp.sum(
        jnp.square((stack - med[None, :]).astype(jnp.float32)), axis=1
    )
    w = _aksel._weights(dist, n, _aksel._count(n, f, mode))
    b = _uniform(n)
    b["selected"] = (w > 0).astype(jnp.float32)
    b["score"] = jnp.nan_to_num(dist, posinf=0.0)
    return b


def _tap_cclip(stack, f, params, center):
    """Replays cclip's fixed-point iterations (aggregators/cclip.py
    ``_clip_step``) to expose the final radii, tau and clip weights."""
    from ..aggregators import cclip as _cclip
    from ..aggregators._common import coordinate_median

    n = stack.shape[0]
    iters = int(params.get("iters", _cclip.ITERS))
    tau_cfg = params.get("tau")
    eps = jnp.asarray(1e-12, jnp.float32)
    if center is None:
        center = coordinate_median(stack).astype(jnp.float32)
    else:
        center = center.astype(jnp.float32)
    norms = jnp.zeros((n,), jnp.float32)
    tau_l = jnp.zeros((), jnp.float32)
    scale = jnp.ones((n,), jnp.float32)
    for _ in range(iters):
        dev = stack - center[None, :]
        dev = jnp.nan_to_num(dev, nan=0.0, posinf=0.0, neginf=0.0)
        norms = jnp.sqrt(
            jnp.sum(jnp.square(dev.astype(jnp.float32)), axis=1)
        )
        tau_l = jnp.median(norms) if tau_cfg is None else jnp.asarray(
            tau_cfg, jnp.float32
        )
        scale = jnp.minimum(1.0, tau_l / jnp.maximum(norms, eps))
        center = center + jnp.mean(
            dev * scale[:, None].astype(dev.dtype), axis=0
        )
    b = _uniform(n)
    b["selected"] = scale
    b["score"] = norms
    b["tau"] = tau_l
    b["clip_frac"] = jnp.mean((scale < 1.0).astype(jnp.float32))
    return b


def _tap_median(stack, f, params):
    from ..aggregators._common import coordinate_median

    med = coordinate_median(stack)
    member = (stack == med[None, :]) & jnp.isfinite(stack)
    selected, share = _coordinate_share(stack, member)
    b = _uniform(stack.shape[0])
    b["selected"] = selected
    b["score"] = share
    return b


def _tap_tmean(stack, f, params):
    n = stack.shape[0]
    s = jnp.sort(stack.astype(jnp.float32), axis=0)  # NaN sorts last
    lo, hi = s[f], s[n - f - 1]
    member = (
        (stack >= lo[None, :]) & (stack <= hi[None, :])
        & jnp.isfinite(stack)
    )
    selected, share = _coordinate_share(stack, member)
    b = _uniform(n)
    b["selected"] = selected
    b["score"] = share
    return b


_TAP_FNS = {
    "krum": _tap_krum,
    "brute": _tap_brute,
    "bulyan": _tap_bulyan,
    "aksel": _tap_aksel,
    "median": _tap_median,
    "tmean": _tap_tmean,
}


def compute_flat(gar_name, stack, f, key=None, params=None, center=None):
    """TapBundle over the rows of the POISONED flat stack the GAR saw.

    ``stack`` is (q, d) in quorum-row order; use ``scatter`` to map a
    subset-quorum bundle back to the n logical ranks. ``center`` threads
    a stateful rule's carried v_0 (cclip) so the tap's radii match the
    rule's actual iteration. Unknown / selection-free rules (average,
    condense, native-*) report the uniform fallback bundle: everyone
    observed, everyone selected, zero scores.
    """
    params = dict(params or {})
    base = gar_name.split("native-")[-1]
    if base == "cclip":
        return _tap_cclip(stack, f, params, center)
    fn = _TAP_FNS.get(base)
    if fn is None:
        return _uniform(stack.shape[0])
    return fn(stack, f, params)


def scatter(bundle_q, sel, n):
    """Map a (q,)-rank TapBundle back to the n logical ranks.

    Ranks outside ``sel`` were never observed this step: observed = 0 and
    selected = 0 there (the hub counts exclusions only among observed
    ranks, so unobserved != suspicious)."""
    out = zeros(n)
    for k in ("observed", "selected", "score"):
        out[k] = out[k].at[sel].set(bundle_q[k])
    out["tau"] = bundle_q["tau"]
    out["clip_frac"] = bundle_q["clip_frac"]
    return out


def mean_bundles(bundles):
    """Average a leading observer axis away: (m, n) leaves -> (n,).

    The multi-observer topologies (LEARN per-node subsets, ByzSGD per-PS
    subsets) produce one bundle per observer; the exported tap is the
    observer MEAN — ``observed`` becomes the fraction of observers whose
    quorum contained the rank, ``selected`` the mean influence across the
    observers that saw it."""
    return jax.tree.map(lambda l: jnp.mean(l, axis=0), bundles)

"""AggregaThor topology: single trusted server, n workers, f Byzantine.

TPU-native re-design of ``pytorch_impl/applications/Aggregathor/trainer.py``
(train step :231-249) and the Server/Worker RPC machinery it drives
(server.py:112-159, worker.py:77-96). Per SURVEY §7, the whole PS round trip
collapses into one jit'd SPMD program over a "workers" mesh axis:

    grads  = vmap(worker_grad)(params, local_batches)     # worker.py:77-96
    stack  = lax.all_gather(grads, "workers")             # server.py:112-159
    stack  = attack(stack, byz_mask)                      # byzWorker.py:78-143
    stack  = stack[subset]                                # wait n-f, :134-155
    aggr   = gar(stack, f)                                # trainer.py:236
    params = optimizer(params, aggr)                      # server.py:277-287

The aggregation and update run redundantly on every shard (replicated
output), so there is no broadcast step: SPMD replication replaces
``write_model`` (server.py:289-297).

``granularity="layer"`` reproduces the Garfield_CC semantics of applying the
GAR per parameter tensor (Garfield_CC/trainer.py:55-204 loops over
``model.parameters()``) instead of over the whole flat gradient.

Centralized (pytorch_impl/applications/Centralized/trainer.py) is this
topology with num_workers=1, f=0, gar="average", attack=None.
"""

import functools

import jax
import jax.numpy as jnp
import optax
from jax.flatten_util import ravel_pytree
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import aggregators
from ..attacks import (
    adaptive as adaptive_lib,
    apply_gradient_attack,
    apply_gradient_attack_tree,
    gradient_attacks,
    note_attack_fallback,
    targeted as targeted_lib,
)
from ..telemetry import taps as taps_lib
from . import core, fold, mesh as mesh_lib

__all__ = ["make_trainer"]

# The data-plane defense (aggregators/dataplane.py, DESIGN.md §18) is
# deployed in-graph on THIS topology only: the SSMW gather holds the full
# per-rank stack every step, which is the quorum the fingerprints need.
# apps/common.py keys on this flag instead of growing a per-topology arg.
SUPPORTS_DATAPLANE = True


def _resolve_gar(gar):
    if isinstance(gar, str):
        return aggregators.gars[gar]
    return gar


def _check_gar(gar, n_effective, f, d=2):
    """Run the rule's contract check once at build time (the reference checks
    on every call under __debug__, aggregators/__init__.py:53-61; here n and f
    are static so once suffices)."""
    import numpy as np

    dummy = np.zeros((n_effective, d), dtype=np.float32)
    message = gar.check(dummy, f=f)
    if message is not None:
        raise AssertionError(
            f"aggregation rule {gar.name!r} cannot be used: {message}"
        )


def _tree_path_ok(tree_path, subset, num_slots, granularity, gar,
                  subset_gram_ok=False):
    """Shared tree-fast-path eligibility gate (aggregathor AND byzsgd).

    A true wait-n-f subset forces the flat path for most rules: row
    selection on a TREE is one dynamic gather per leaf (62 x per-PS at
    ResNet-18 scale), measured 3.5x slower than the flat path's single
    (n, d) gather (PERF.md). EXCEPT Gram-form rules when the caller
    implements the sub-Gram composition (``subset_gram_ok`` —
    aggregathor): their selection needs only the (q, q) gather of the
    tiny Gram plus a weight scatter, so the async emulation keeps the
    tree/fold fast path (VERDICT r4 #5). subset >= num_slots never
    selects rows, so it stays tree-eligible everywhere. Layer granularity
    and rules without tree aggregation use the flat path.
    """
    subset_ok = (
        subset is None or subset >= num_slots
        or (subset_gram_ok and gar.gram_select is not None)
    )
    return (
        tree_path
        and subset_ok
        and granularity != "layer"
        and gar.tree_aggregate is not None
    )


def _attack_then_aggregate(
    flat_stack, byz_mask, atk_key, sub_key, gar_key, *, attack,
    attack_params, gar, f, subset, gar_params, center=None,
    row_weights=None,
):
    """Poison rows, optionally subsample (wait n-f), aggregate. Pure.
    ``gar_key`` seeds randomized rules (condense's Bernoulli mask);
    ``center`` threads a stateful rule's carried v_0 (cclip);
    ``row_weights`` is the bounded-staleness discount composed AFTER the
    attack and the subset — the rows the rule consumes are exactly what
    the host-plane PS aggregates: poisoned, quorum-selected, then
    staleness-weighted (utils/rounds.py, DESIGN.md §14)."""
    n = flat_stack.shape[0]
    stack = apply_gradient_attack(
        attack, flat_stack, byz_mask, key=atk_key, **attack_params
    )
    if subset is not None and subset < n:
        sel = core.subset_indices(sub_key, n, subset)
        stack = stack[sel]
        if row_weights is not None:
            row_weights = row_weights[sel]
    if row_weights is not None:
        stack = (stack * row_weights[:, None]).astype(stack.dtype)
    extra = {} if center is None else {"center": center}
    return gar.unchecked(stack, f=f, key=gar_key, **gar_params, **extra)


def make_trainer(
    module,
    loss_fn,
    optimizer,
    gar,
    *,
    num_workers,
    f=0,
    attack=None,
    attack_params=None,
    byz_mask=None,
    mesh=None,
    axis="workers",
    subset=None,
    granularity="model",
    tree_path=True,
    gar_dtype=None,
    worker_momentum=None,
    gar_params=None,
    num_iter=None,
    telemetry=False,
    staleness=None,
    defense=None,
    wire=None,
):
    """Build ``(init_fn, step_fn, eval_fn)`` for the SSMW topology.

    ``telemetry`` (default off) makes ``step_fn`` return a fixed-shape
    ``TapBundle`` under ``metrics["tap"]`` — per-rank selection evidence
    recomputed from the same poisoned stack and keys the GAR consumed
    (telemetry/taps.py). Off means NOTHING tap-shaped is traced: the
    step program is byte-identical to the pre-telemetry one, and the
    taps never write into TrainState, so taps-on trajectories are
    bitwise equal to taps-off (tests/test_telemetry.py).

    Args mirror the reference CLI (Aggregathor/trainer.py:62-135): ``f`` is
    the declared tolerance passed to the GAR; ``attack``/``byz_mask`` control
    actual fault injection (byzWorker.py); ``subset=q`` emulates the
    asynchronous wait-for-q path (server.py:134-155); ``granularity`` picks
    whole-model (trainer.py:236) vs per-layer (Garfield_CC) aggregation.
    ``tree_path`` (default on) lets rules that support tree-mode aggregation
    (average, krum) skip the (n, d) flat stack entirely — measured ~5 ms/
    step at ResNet-18 scale (PERF.md); set False to force the flat path
    (A/B tests).

    ``gar_dtype`` (e.g. ``jnp.bfloat16``) casts the per-worker gradients to
    that dtype at the backward's epilogue (XLA fuses the cast into its final
    writes, so the f32 gradients never hit HBM) and runs the attack + gather
    + GAR phase entirely at the narrow width — halving the HBM traffic of
    the whole aggregation pipeline, which is bandwidth-bound (PERF.md
    "Known frontier"). Gram/selection arithmetic still accumulates in f32
    (aggregators/_common.py), and the aggregate is cast back to the param
    dtype at the optimizer boundary — the standard bf16-gradient-exchange
    design on TPU. None keeps full width.

    ``worker_momentum`` (float beta in [0, 1)) makes every worker submit an
    exponential moving average ``m_i = (1-beta) g_i + beta m_i`` of its
    gradients instead of the raw gradient — Karimireddy, He & Jaggi (ICML
    2021): momentum shrinks honest-gradient variance over time, which is
    exactly the quantity the "little is enough" lie attack hides inside, so
    robust rules (their cclip, but also krum/median) regain their guarantees
    under attacks that defeat them on raw gradients (see BASELINE.md's TTA
    grid). The per-worker momentum stack lives in ``TrainState.worker_mom``
    (same dtype as the aggregation pipeline, i.e. ``gar_dtype`` when set);
    Byzantine rows are re-poisoned by the attack every step, after the
    honest update — a real Byzantine worker submits whatever it wants
    regardless of its declared state.

    Pair worker momentum with a PLAIN-SGD server (no heavy-ball momentum in
    ``optimizer``), as the paper's algorithm does — the worker EMA *is* the
    momentum. Stacking it on a momentum server double-smooths the update
    (two poles at ~0.9) and destabilizes training: measured on the hardened
    ResNet-18 task, fault-free accuracy stalls at chance with server
    momentum 0.9 but trains normally with momentum 0 at the
    gain-compensated lr (BASELINE.md TTA grid, the worker-momentum rows).

    ``staleness`` is the in-graph EMULATION of the host plane's
    bounded-staleness async mode (DESIGN.md §14) — the asynchrony analog
    of the seeded ``subset`` emulation: a dict with ``max_staleness``
    (hard cutoff, rounds), ``decay`` (geometric discount), and optional
    ``taus`` (a FIXED per-rank staleness assignment — "rank r lags tau_r
    rounds"; omitted, each step draws per-rank staleness uniformly from
    ``[0, max_staleness]`` with a seeded key). The resulting weights
    (``utils.rounds.staleness_weights`` — the same function the host
    plane's PS applies) scale the post-attack rows before the GAR on
    every dispatch path, composed into the folded-attack row scales on
    Gram-form rules so ``fold.plan_for`` still applies. At
    ``max_staleness=0`` (or an all-zero ``taus``) the emulation is
    dropped entirely and the step program is the synchronous one —
    trajectories are BITWISE equal, the emulated half of the
    ``--max_staleness 0`` contract (tests/test_staleness.py).

    ``attack`` may also name an ADAPTIVE controller (``adaptive-lie`` /
    ``adaptive-empire``, attacks/adaptive.py, DESIGN.md §16): the lie/
    empire magnitude becomes a bisection bracket carried in
    ``TrainState.attack_state`` (and therefore through the chunk-scan
    carry), fed back each step by whether the active cohort entered the
    rule's selection; ``attack_params`` carries the controller knobs
    (``f_pool``/``rotation``/``mag_min``/``mag_max``/``burst``). With a
    static cohort on a Gram-form rule the traced magnitude composes into
    the folded-attack fake row (``adaptive_lib.traced_fold_plan``) so
    the fast path survives; rotation (``f_pool > f`` cohort laundering)
    keeps the where-path (the remap itself becomes dynamic) — reported
    once via the ``attack_fallback`` telemetry event. In-graph bursts
    key on the staleness emulation: a round whose draw hard-cuts an
    honest rank is a quorum-degradation window and the cohort plays
    ``burst`` magnitude (no staleness emulation -> no bursts).

    ``defense`` (aggregators/defense.py) is the closed-loop counterpart:
    a dict with ``power``/``floor``/``halflife`` enabling SUSPICION
    WEIGHTING — a per-rank exclusion EMA carried in
    ``TrainState.defense_state`` (the in-graph emulation of the host
    MetricsHub's decayed suspicion), mapped through
    ``defense.suspicion_weights`` and composed into the SAME row-weight
    algebra as the staleness discount (fold ``row_weights`` on Gram
    rules, explicit row scaling elsewhere). ``defense=None`` (default)
    traces nothing — trajectories are bitwise the undefended ones. Rule
    ESCALATION lives above the trainer (apps/common.py rebuilds the step
    on level changes, like the crash-schedule re-jit), so one policy
    module serves both deployment scales.

    A ``defense`` dict may additionally (or instead — ``weighted:
    False``) carry ``data`` (``tau``/``power``/``floor``/``halflife``):
    the DATA-plane detectors (aggregators/dataplane.py, DESIGN.md §18)
    — per-class classifier-head gradient fingerprints, spectral
    filtering + 2-means cohort clustering over the gathered stack, a
    carried dp exclusion EMA (``dp_obs``/``dp_exc`` in
    ``TrainState.defense_state``), composed by CENTER-PULL onto the
    trusted mean (row scaling hands a data poisoner krum centrality —
    the negative result §18 records). Per-step scores/flags/weights
    surface as ``dataplane_*`` metrics (schema-v9 ``data_defense``
    events in the app loop).

    ``wire`` is the in-graph EMULATION of the host wire codec's lossy
    schemes (parallel/compress.py, DESIGN.md §20): a dict with ``dtype``
    (one of ``wire.WIRE_DTYPES``), ``topk`` (sparsification divisor, 0 =
    off; nonzero replaces the dense scheme on the gradient rows, the
    cluster's gradient-plane policy) and ``error_feedback`` (default
    True; effective for the lossy int8/int4/topk schemes only — bf16
    stays EF-free like the PR 4 wire, f32 is lossless). The round trip
    is applied to the gathered rows AFTER the worker-momentum update
    (momentum accumulates the uncompressed honest signal, exactly like
    a host worker's local state) and BEFORE the attack (a Byzantine
    process controls its wire bytes — compression constrains honest
    senders only). The EF residual rows ride
    ``TrainState.wire_state["resid"]`` through the chunk-scan carry and
    the checkpoint tree, so chunked and resumed compressed runs are
    bitwise (tests/test_compress.py). ``wire=None`` or
    ``{"dtype": "f32", "topk": 0}`` traces NOTHING — trajectories are
    bitwise the uncompressed ones. The quantizer grid is pinned
    bit-identical to the host codec (``utils/wire.py``), so what the
    matrix measures here is what compressed frames do to the GARs.

    ``step_fn(state, x, y) -> (state, metrics)`` expects ``x``/``y`` with a
    leading ``num_workers`` axis, sharded over ``axis``; it is jit'd with
    replicated state output, so calling it in a loop keeps everything
    on-device.
    """
    gar = _resolve_gar(gar)
    attack_params = dict(attack_params or {})
    gar_params = dict(gar_params or {})
    # Targeted data poisoning (DESIGN.md §17): the Byzantine cohort's
    # BATCHES are rewritten (label flips / trigger stamps) and its
    # gradient rows stay HONEST gradients of the poisoned task — no row
    # transform exists for the GAR paths to see, which is exactly the
    # blindness the per-class eval telemetry measures.
    targeted_cfg = None
    if targeted_lib.is_targeted(attack):
        if f < 1:
            raise ValueError(
                f"targeted attack {attack!r} needs f >= 1 poisoning "
                "workers"
            )
        targeted_cfg = targeted_lib.configure(
            attack, attack_params,
            num_classes=getattr(module, "num_classes", 2),
        )
        if byz_mask is None:
            byz_mask = core.default_byz_mask(num_workers, f)
        attack = None  # the rows are honest; the poison is in the data
        attack_params = {}
    # Adaptive attacks (DESIGN.md §16): resolve the controller config and
    # strip it down to the BASE attack + cleaned params; the magnitude is
    # supplied per step from the carried bracket, never from params.
    adaptive_cfg = None
    if adaptive_lib.is_adaptive(attack):
        if byz_mask is not None:
            raise ValueError(
                "adaptive attacks derive their own Byzantine pool from "
                'attack_params ("f_pool"/"pool"); an explicit byz_mask '
                "would silently fight the rotation schedule — remove it"
            )
        if granularity == "layer":
            raise ValueError(
                "adaptive attacks need whole-model selection feedback; "
                'granularity="layer" runs an independent GAR per tensor '
                "with no single per-rank verdict"
            )
        adaptive_cfg = adaptive_lib.configure(
            attack, attack_params, num_workers=num_workers, f=f
        )
        attack = adaptive_cfg.base
        attack_params = adaptive_lib.base_params(attack_params)
        byz_mask = adaptive_cfg.pool_mask()
    if defense is not None and granularity == "layer":
        raise ValueError(
            "the suspicion-weighted defense needs whole-model selection "
            'evidence; granularity="layer" has no per-rank verdict'
        )
    if gar.stateful_center and "center" in gar_params:
        raise ValueError(
            f"{gar.name!r} carries its center across steps "
            "(TrainState.gar_state); a fixed gar_params 'center' would "
            "silently fight the carried state — remove it (standalone "
            "gars[...](stack, center=...) calls still accept one)"
        )
    if mesh is None:
        mesh = mesh_lib.make_mesh({axis: -1})
    if subset is not None and not (1 <= subset <= num_workers):
        raise ValueError(
            f"subset (wait-for-q) must be in [1, num_workers], got {subset}"
        )
    n_eff = subset if subset is not None else num_workers
    _check_gar(gar, n_eff, f)
    if telemetry and granularity == "layer":
        raise ValueError(
            "telemetry taps report one whole-model selection per rank; "
            'granularity="layer" runs an independent GAR per tensor, '
            "which has no single per-rank mask — run taps at model "
            "granularity"
        )
    if worker_momentum is not None and not (0.0 <= worker_momentum < 1.0):
        raise ValueError(
            f"worker_momentum must be in [0, 1), got {worker_momentum}"
        )
    axis_size = mesh.shape[axis]
    per_shard = mesh_lib.fold(num_workers, axis_size, "workers")
    if attack is not None and attack != "none" and attack not in gradient_attacks:
        raise ValueError(f"unknown attack {attack!r}")
    if byz_mask is None:
        byz_mask = core.default_byz_mask(num_workers, f if attack else 0)
    # Folded attack plan: static for deterministic attacks on
    # fold-capable rules (Gram-form krum/average/bulyan; coordinate-wise
    # median/tmean via remapped-row kernels); None keeps the where-path
    # (fold.plan_for). Adaptive attacks fold only their TRACED-magnitude
    # fake row (per-trace plan below) on Gram-form rules with a static
    # cohort — rotation makes the remap dynamic, and the feedback needs
    # the gram_select weights anyway.
    fold_plan = None
    adaptive_fold = False
    if adaptive_cfg is not None:
        import os

        adaptive_fold = (
            gar.gram_select is not None
            and adaptive_cfg.rotation_period == 0
            and not os.environ.get("GARFIELD_NO_FOLD")
        )
        if not adaptive_fold:
            note_attack_fallback(
                f"adaptive-{adaptive_cfg.base}", path="where",
                why=(
                    "cohort rotation makes the row remap dynamic"
                    if adaptive_cfg.rotation_period > 0
                    else "rule has no gram_select fold form"
                ),
            )
    else:
        fold_plan = fold.plan_for(gar, attack, byz_mask, attack_params)
    byz_mask = jnp.asarray(byz_mask, dtype=bool)
    # Closed-loop defense (see docstring): normalized EMA/weighting knobs.
    # ``weighted`` (default True) enables the GAR-suspicion weighting;
    # ``data`` enables the DATA-plane detectors (aggregators/dataplane.py,
    # DESIGN.md §18) — per-class head-gradient fingerprints, spectral
    # filtering + 2-means cohort flags, folded into their OWN carried
    # exclusion EMA and composed through the same row-weight algebra.
    d_power = d_floor = d_decay = None
    d_weighted = False
    dp_tau = dp_power = dp_floor = dp_decay = None
    if defense is not None:
        from ..aggregators import dataplane as dataplane_lib
        from ..aggregators import defense as defense_lib

        dd = dict(defense)
        d_weighted = bool(dd.pop("weighted", True))
        data_d = dd.pop("data", None)
        if d_weighted:
            d_power = float(dd.pop("power", 2.0))
            d_floor = float(dd.pop("floor", 0.1))
            halflife = float(dd.pop("halflife", 16.0))
            if halflife <= 0.0:
                raise ValueError(
                    f"defense halflife must be > 0, got {halflife}"
                )
            # Per-step multiplicative decay of the carried exclusion EMA:
            # the in-graph twin of MetricsHub(suspicion_halflife=).
            d_decay = float(0.5 ** (1.0 / halflife))
            defense_lib.suspicion_weights(
                [0.0], power=d_power, floor=d_floor
            )  # validate the knobs once, loudly
        if dd:
            raise ValueError(f"unknown defense keys {sorted(dd)}")
        if data_d is not None:
            dpd = dict(data_d)
            dp_tau = float(dpd.pop("tau", dataplane_lib.DEFAULT_TAU))
            dp_power = float(dpd.pop("power", 4.0))
            dp_floor = float(dpd.pop("floor", 0.0))
            dp_halflife = float(dpd.pop("halflife", 8.0))
            if dpd:
                raise ValueError(
                    f"unknown defense.data keys {sorted(dpd)}"
                )
            if dp_tau <= 0.0:
                raise ValueError(f"dp tau must be > 0, got {dp_tau}")
            if dp_halflife <= 0.0:
                raise ValueError(
                    f"dp halflife must be > 0, got {dp_halflife}"
                )
            dp_decay = float(0.5 ** (1.0 / dp_halflife))
            defense_lib.suspicion_weights(
                [0.0], power=dp_power, floor=dp_floor
            )
        if not d_weighted and dp_decay is None:
            raise ValueError(
                "defense enabled with neither suspicion weighting nor "
                "data-plane detectors; pass weighted and/or data"
            )

    # Bounded-staleness emulation (see docstring). Normalized here so the
    # trivially-synchronous configs drop the machinery at BUILD time: the
    # step program is then literally the synchronous one — the bitwise
    # half of the --max_staleness 0 contract.
    stale_ms = stale_decay = stale_weights_static = None
    if staleness is not None:
        import numpy as np

        from ..utils import rounds as rounds_lib

        st = dict(staleness)
        stale_ms = int(st.pop(
            "max_staleness", rounds_lib.DEFAULT_MAX_STALENESS
        ))
        stale_decay = float(st.pop("decay", rounds_lib.DEFAULT_DECAY))
        taus = st.pop("taus", None)
        if st:
            raise ValueError(f"unknown staleness keys {sorted(st)}")
        rounds_lib.StalenessPolicy(stale_ms, stale_decay)  # validate
        if stale_ms == 0:
            staleness = None  # all weights exactly 1: synchronous program
        elif taus is not None:
            taus = np.clip(np.asarray(taus, np.int64), 0, stale_ms)
            if taus.shape != (num_workers,):
                raise ValueError(
                    f"staleness taus must have shape ({num_workers},), "
                    f"got {taus.shape}"
                )
            stale_weights_static = rounds_lib.staleness_weights(
                taus, decay=stale_decay, max_staleness=stale_ms
            )
            if np.all(stale_weights_static == 1.0):
                staleness = None  # all-fresh schedule: same program
        if (staleness is not None and fold_plan is not None
                and gar.gram_select is None):
            # Row weights compose with the fold only through the Gram
            # (fold.folded_tree_aggregate row_weights); the other fold
            # forms consume row values — route through the where-path,
            # which weights rows explicitly.
            fold_plan = None
    if (defense is not None and fold_plan is not None
            and gar.gram_select is None):
        # Suspicion weights are row weights too (defense.suspicion_weights
        # composes through the same algebra as the staleness discount) —
        # same Gram-only fold constraint, same where-path fallback.
        fold_plan = None

    # Wire-compression emulation (see docstring): resolve the scheme at
    # build time so the no-compression configs trace NOTHING — the
    # bitwise contract every other optional feature here honors.
    wire_scheme = wire_div = None
    wire_ef = False
    if wire is not None:
        from ..utils import wire as wire_lib
        from . import compress as compress_lib

        wc = dict(wire)
        w_dtype = str(wc.pop("dtype", "f32")).lower()
        w_topk = int(wc.pop("topk", 0))
        w_ef = bool(wc.pop("error_feedback", True))
        if wc:
            raise ValueError(f"unknown wire keys {sorted(wc)}")
        if w_dtype not in wire_lib.WIRE_DTYPES:
            raise ValueError(
                f"wire dtype must be one of {wire_lib.WIRE_DTYPES}, "
                f"got {w_dtype!r}"
            )
        if w_topk < 0:
            raise ValueError(
                f"wire topk divisor must be >= 0 (0 = off), got {w_topk}"
            )
        if w_topk > 0:
            wire_scheme, wire_div = "topk", w_topk
        elif w_dtype != "f32":
            wire_scheme = w_dtype
        # EF is only sound (and only needed) for the biased lossy
        # schemes; bf16 stays EF-free like the PR 4 host wire.
        wire_ef = w_ef and wire_scheme in ("int8", "int4", "topk")

    init_worker, grad_fn, eval_apply = core.make_worker_fns(module, loss_fn)
    # Slot-fused gradient twin (models/slotfused.py) when eligible, else
    # run-length-aware unroll/vmap (core.select_slot_path).
    slot_fused_fn, force_unroll = core.select_slot_path(
        module, loss_fn, per_shard, num_iter, log_tag="aggregathor"
    )
    repl = NamedSharding(mesh, P())
    shard_w = NamedSharding(mesh, P(axis))

    def init_fn(key, example_x, seed_rng=None):
        params, model_state = init_worker(key, example_x)
        opt_state = optimizer.init(params)
        worker_mom = None
        if worker_momentum is not None:
            worker_mom = core.worker_mom_init(params, num_workers, gar_dtype)
        gar_state = None
        if gar.stateful_center:
            # cclip's carried center (v_0 = previous aggregate, the
            # paper's recipe); zeros at step 0 — that first aggregate is
            # tau-bounded from the origin (cclip.py docstring).
            gar_state = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )
        attack_state = None
        if adaptive_cfg is not None:
            # The bisection bracket starts wide open; the first rounds
            # ARE the controller's probes (attacks/adaptive.py).
            attack_state = adaptive_lib.init_state(adaptive_cfg)
        defense_state = None
        if defense is not None:
            # Carried exclusion EMAs: nothing observed yet, suspicion 0,
            # weights exactly 1.0 — the clean-history identity. The
            # data-plane detectors carry their OWN twins (independent
            # halflife; a GAR exclusion and a fingerprint flag are
            # different evidence).
            defense_state = {}
            if d_weighted:
                defense_state.update({
                    "obs": jnp.zeros((num_workers,), jnp.float32),
                    "exc": jnp.zeros((num_workers,), jnp.float32),
                })
            if dp_decay is not None:
                defense_state.update({
                    "dp_obs": jnp.zeros((num_workers,), jnp.float32),
                    "dp_exc": jnp.zeros((num_workers,), jnp.float32),
                })
        wire_state = None
        if wire_ef:
            # Zero EF residuals — checkpointed with the rest of the
            # state tree, so a resumed run carries them bitwise.
            d_flat = sum(
                int(l.size) for l in jax.tree.leaves(params)
            )
            wire_state = compress_lib.init_wire_state(num_workers, d_flat)
        state = core.TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            model_state=model_state,
            opt_state=opt_state,
            rng=key if seed_rng is None else seed_rng,
            worker_mom=worker_mom,
            gar_state=gar_state,
            attack_state=attack_state,
            defense_state=defense_state,
            wire_state=wire_state,
        )
        return jax.device_put(state, repl)

    def _local_step(state, x_local, y_local):
        """Body run per shard under shard_map."""
        params, ms = state.params, state.model_state
        base = jax.random.fold_in(state.rng, state.step)
        atk_key, sub_key, gar_key, drop_base = jax.random.split(base, 4)
        shard_idx = jax.lax.axis_index(axis)
        slot_ids = shard_idx * per_shard + jnp.arange(per_shard)
        drop_keys = jax.vmap(lambda i: jax.random.fold_in(drop_base, i))(slot_ids)

        if targeted_cfg is not None:
            # Targeted poisoning (DESIGN.md §17): rewrite the Byzantine
            # slots' batches BEFORE the gradient pass — label flips /
            # trigger stamps on their own data, honest gradients of the
            # poisoned task afterwards. Honest slots' batches are
            # selected back bitwise, and targeted_cfg None traces none
            # of this (the defense-off bitwise contract).
            byz_local = byz_mask[slot_ids]
            xs_p, ys_p = [], []
            for k in range(per_shard):
                xk, yk = targeted_lib.poison_batch(
                    targeted_cfg, x_local[k], y_local[k], seed=k,
                    step=state.step,
                )
                xs_p.append(xk)
                ys_p.append(yk)
            x_pois = jnp.stack(xs_p)
            y_pois = jnp.stack(ys_p)
            x_local = jnp.where(
                byz_local.reshape(
                    (per_shard,) + (1,) * (x_local.ndim - 1)
                ),
                x_pois, x_local,
            )
            y_local = jnp.where(
                byz_local.reshape(
                    (per_shard,) + (1,) * (y_local.ndim - 1)
                ),
                y_pois, y_local,
            )

        # Unrolled (not vmapped) per-slot gradients: kills the 5-D relayout
        # tax of the logical-worker fold (core.per_slot_grads docstring).
        # Keep the stacked TREE here and flatten after the gather — raveling
        # each slot inside the unroll (flat=True) measured 12% SLOWER
        # end-to-end (55 vs 62 steps/s): the 8 per-slot concats serialize
        # against the fwd+bwd graphs, while one vmapped ravel of the stacked
        # leaves fuses cleanly.
        grads_local, (loss_local, ms_local) = core.per_slot_grads(
            grad_fn, params, ms, x_local, y_local, drop_keys,
            fused_fn=slot_fused_fn, force_unroll=force_unroll,
        )
        # Narrow the aggregation pipeline (see make_trainer docstring); the
        # cast fuses into the backward's output writes. No-op when None.
        grads_local = core.cast_leaves(grads_local, gar_dtype)

        # all_gather over the mesh axis == Server.get_gradients (RPC gather).
        grads = jax.tree.map(
            lambda l: jax.lax.all_gather(l, axis, tiled=True), grads_local
        )
        losses = jax.lax.all_gather(loss_local, axis, tiled=True)
        new_ms = core.mean_model_state(ms_local, axis)

        # Worker momentum (see make_trainer docstring): every worker submits
        # its EMA instead of the raw gradient. Elementwise over the stacked
        # tree, so it composes with the tree-mode AND flat GAR paths below;
        # the honest update is stored, the attack poisons its rows after.
        new_mom = state.worker_mom
        if worker_momentum is not None:
            grads = core.worker_mom_update(
                worker_momentum, state.worker_mom, grads
            )
            new_mom = grads

        # Wire-compression emulation (see docstring): encode->decode the
        # rows every honest worker would put on the wire. AFTER momentum
        # (the EMA is worker-local host state, accumulated uncompressed),
        # BEFORE the attack (a Byzantine sender controls its bytes — the
        # attack overwrites its rows downstream, exactly as on the
        # cluster). The GARs then consume dense f32-dequantized rows, so
        # fold/row-weight algebra is untouched by construction.
        new_wire = state.wire_state
        if wire_scheme is not None:
            flat_w = core.flatten_rows(grads).astype(jnp.float32)
            w_k = (
                wire_lib.topk_k(flat_w.shape[1], wire_div)
                if wire_scheme == "topk" else None
            )
            if wire_ef:
                sent_w, resid_w = compress_lib.ef_roundtrip_rows(
                    flat_w, state.wire_state["resid"], wire_scheme, k=w_k
                )
                new_wire = {"resid": resid_w}
            else:
                sent_w = compress_lib.roundtrip_rows(
                    flat_w, wire_scheme, k=w_k
                )
            grads = jax.vmap(
                lambda r: core.unflatten_like(params, r)
            )(sent_w)
            grads = core.cast_leaves(grads, gar_dtype)

        honest = (~byz_mask).astype(losses.dtype)
        mean_loss = jnp.sum(losses * honest) / jnp.sum(honest)

        # Bounded-staleness weights (emulation; see docstring): fixed
        # per-rank schedule, or a fresh seeded draw each step. The key is
        # fold_in-derived (NOT an extra split) so synchronous configs'
        # key derivation — and therefore every pinned trajectory — is
        # untouched.
        stale_w = None
        if staleness is not None:
            if stale_weights_static is not None:
                stale_w = jnp.asarray(stale_weights_static)
            else:
                stale_taus = jax.random.randint(
                    jax.random.fold_in(base, 0x57A1E),
                    (num_workers,), 0, stale_ms + 1,
                )
                stale_w = rounds_lib.staleness_weights(
                    stale_taus, decay=stale_decay, max_staleness=stale_ms
                )

        # Adaptive controller (DESIGN.md §16): play the carried bracket's
        # midpoint, rotate the active cohort, and burst to full magnitude
        # when the staleness emulation opens a quorum-degradation window
        # (an honest rank hard-cut this round). All traced; nothing here
        # exists in the program when the attack is oblivious.
        act_mask = byz_mask
        eff_params = attack_params
        atk_mag = degraded = None
        a_lo = a_hi = None
        if adaptive_cfg is not None:
            a_lo = state.attack_state["lo"]
            a_hi = state.attack_state["hi"]
            atk_mag = adaptive_lib.played_magnitude(a_lo, a_hi)
            if stale_w is not None:
                # Quorum-degradation window (emulated): an HONEST rank at
                # the staleness cutoff's floor weight (or excluded
                # outright) — the emulation clips taus to the cutoff, so
                # the floor IS the hard-cut signature a host-plane
                # straggler/partition produces.
                floor_w = jnp.float32(
                    (stale_decay ** stale_ms) * (1.0 + 1e-5)
                )
                degraded = jnp.any((stale_w <= floor_w) & ~byz_mask)
                atk_mag = jnp.where(
                    degraded, jnp.float32(adaptive_cfg.burst_mag), atk_mag
                )
            act_mask = adaptive_lib.active_mask_traced(
                adaptive_cfg, state.step
            )
            eff_params = dict(attack_params)
            eff_params[
                adaptive_lib.magnitude_key(adaptive_cfg.base)
            ] = atk_mag

        # Closed-loop defense weights (aggregators/defense.py): suspicion
        # from the carried exclusion EMA, composed into the SAME row-
        # weight algebra as the staleness discount. Exactly 1.0 on a
        # clean history (the weighted identity contract).
        def_w = None
        if defense is not None and d_weighted:
            susp = state.defense_state["exc"] / jnp.maximum(
                state.defense_state["obs"], 1e-6
            )
            def_w = defense_lib.suspicion_weights(
                susp, power=d_power, floor=d_floor
            )

        # Data-plane defense (aggregators/dataplane.py, DESIGN.md §18):
        # fingerprint the classifier-head block of the SAME stacked tree
        # the rule consumes (post-momentum — the rows a data poisoner
        # actually submitted), run the spectral + 2-means detectors, map
        # the carried dp exclusion EMA through the suspicion-weight law,
        # and compose by CENTER-PULL: suspect rows collapse onto the
        # dp-weight-weighted TRUSTED mean instead of being scaled toward
        # the origin (toward-zero dampening hands a data poisoner krum
        # centrality — the inlier inversion measured in DEFBENCH; see
        # dataplane.center_pull_rows). The transform is per-leaf
        # elementwise like the momentum update, so every downstream path
        # (tree, fold, flat) is unchanged. Traced out entirely when off
        # (the TapBundle convention).
        dp_w = dp_scores = dp_flags = None
        if dp_decay is not None:
            head_k, head_b = dataplane_lib.head_leaves(grads)
            if head_k is None:
                raise ValueError(
                    "data-plane defense needs a classifier head (no "
                    "2-D parameter leaf in this model)"
                )
            dp_scores, flags_b = dataplane_lib.detect(
                head_k, head_b, f=max(1, f), tau=dp_tau
            )
            dp_flags = flags_b.astype(jnp.float32)
            dp_susp = state.defense_state["dp_exc"] / jnp.maximum(
                state.defense_state["dp_obs"], 1e-6
            )
            dp_w = defense_lib.suspicion_weights(
                dp_susp, power=dp_power, floor=dp_floor
            )
            grads = dataplane_lib.center_pull_tree(grads, dp_w)
        row_w = stale_w
        if def_w is not None:
            row_w = def_w if row_w is None else row_w * def_w

        # Selection feedback the two carries consume: the rule's (n,)
        # selection weights (sel_w) and the observation mask (obs_vec).
        need_sel = adaptive_cfg is not None or d_weighted
        sel_w = quorum_idx = None

        agg_kwargs = dict(
            attack=attack, attack_params=eff_params, gar=gar, f=f,
            subset=subset, gar_params=gar_params, row_weights=row_w,
        )
        center_kw = (
            {"center": state.gar_state} if gar.stateful_center else {}
        )
        if _tree_path_ok(tree_path, subset, num_workers, granularity, gar,
                         subset_gram_ok=True):
            # Tree-mode fast path: no (n, d) flat stack (PERF.md: the
            # flatten + unflatten round trip costs ~5 ms/step at ResNet-18
            # scale on one chip). True subsets stay here for Gram-form
            # rules (sub-Gram composition); others go flat —
            # see _tree_path_ok.
            sel = None
            if subset is not None and subset < num_workers:
                # SAME key derivation as the flat path's
                # _attack_then_aggregate, so tree and flat trajectories
                # sample identical wait-n-f subsets.
                sel = core.subset_indices(sub_key, num_workers, subset)
            if fold_plan is not None or adaptive_fold:
                # Folded attack: poison the Gram, never the rows — the raw
                # per-leaf Grams keep fusing into the backward epilogue
                # like the fault-free step (parallel/fold.py; 1.16x on the
                # krum+lie north-star). Staleness/suspicion weights
                # compose into the fold's row scales (row_weights), and
                # the adaptive magnitude into the shared fake row
                # (traced_fold_plan), so the fast path survives both the
                # async emulation and the adaptive adversary.
                plan_now = (
                    adaptive_lib.traced_fold_plan(adaptive_cfg, atk_mag)
                    if adaptive_fold else fold_plan
                )
                out = fold.folded_tree_aggregate(
                    gar, plan_now, grads, f=f, key=gar_key,
                    gar_params={**gar_params, **center_kw},
                    subset_sel=sel, row_weights=row_w,
                    return_weights=need_sel,
                )
                if need_sel:
                    aggr_tree, sel_w = out
                    quorum_idx = sel
                else:
                    aggr_tree = out
            else:
                poisoned = apply_gradient_attack_tree(
                    attack, grads, act_mask, key=atk_key, **eff_params
                )
                if row_w is not None:
                    # Weight the post-attack rows — what the host-plane
                    # PS aggregates (poisoned arrivals, then discounted).
                    poisoned = jax.tree.map(
                        lambda l: (l * row_w.reshape(
                            (num_workers,) + (1,) * (l.ndim - 1)
                        )).astype(l.dtype),
                        poisoned,
                    )
                if sel is not None:
                    # Wait-n-f on the Gram: select on the (q, q) sub-Gram,
                    # scatter the weights back — per-leaf row gathers never
                    # happen (the 3.5x regression _tree_path_ok documents).
                    from ..aggregators._common import (
                        tree_gram, tree_weighted_sum,
                    )

                    gram = tree_gram(poisoned)
                    w_sub = gar.gram_select(
                        gram[sel][:, sel], f=f, key=gar_key, **gar_params
                    )
                    w = jnp.zeros(
                        (num_workers,), jnp.float32
                    ).at[sel].set(w_sub)
                    aggr_tree = tree_weighted_sum(poisoned, w)
                    if need_sel:
                        sel_w = w
                        quorum_idx = sel
                else:
                    aggr_tree = gar.tree_aggregate(
                        poisoned, f=f, key=gar_key, **gar_params,
                        **center_kw
                    )
        elif granularity == "layer":
            # Garfield_CC per-parameter aggregation: independent GAR (and
            # attack statistics) per tensor, like the reference's per-layer
            # gather->GAR loop (Garfield_CC/trainer.py:91-127). Each leaf is
            # reshaped in place (free) — no flat stack is built. Stateful
            # rules (cclip) get their carried center per leaf, so layer
            # aggregation keeps the same v_0 semantics as whole-model.
            leaves, treedef = jax.tree.flatten(grads)
            c_leaves = (
                jax.tree.leaves(state.gar_state) if gar.stateful_center
                else [None] * len(leaves)
            )
            out_leaves = []
            for i, (leaf, c) in enumerate(zip(leaves, c_leaves)):
                n = leaf.shape[0]
                flat = leaf.reshape(n, -1)
                akey = jax.random.fold_in(atk_key, i)
                gkey = jax.random.fold_in(gar_key, i)
                aggr = _attack_then_aggregate(
                    flat, act_mask, akey, sub_key, gkey,
                    **agg_kwargs,
                    **({"center": c.reshape(-1)} if c is not None else {}),
                )
                out_leaves.append(aggr.reshape(leaf.shape[1:]))
            aggr_tree = jax.tree.unflatten(treedef, out_leaves)
        else:
            flat_stack = core.flatten_rows(grads)  # (n_w, d)
            flat_center = (
                {"center": ravel_pytree(state.gar_state)[0]}
                if gar.stateful_center else {}
            )
            aggr = _attack_then_aggregate(
                flat_stack, act_mask, atk_key, sub_key, gar_key,
                **agg_kwargs, **flat_center,
            )
            aggr_tree = core.unflatten_like(params, aggr)

        if need_sel and sel_w is None:
            # Feedback fallback: the aggregation path exposed no selection
            # weights (non-Gram rule, flat path, or full-participation
            # tree aggregate) — recompute the rule's verdict over the
            # SAME poisoned, weighted rows via the audit-tap machinery
            # (exactly the telemetry recomputation below; XLA CSEs the
            # shared subgraphs). Adaptive/defense-only cost.
            flat_fb = core.flatten_rows(grads)
            poisoned_fb = apply_gradient_attack(
                attack, flat_fb, act_mask, key=atk_key, **eff_params
            )
            if row_w is not None:
                poisoned_fb = (poisoned_fb * row_w[:, None]).astype(
                    poisoned_fb.dtype
                )
            fb_center = (
                ravel_pytree(state.gar_state)[0]
                if gar.stateful_center else None
            )
            if subset is not None and subset < num_workers:
                quorum_idx = core.subset_indices(
                    sub_key, num_workers, subset
                )
                bundle = taps_lib.compute_flat(
                    gar.name, poisoned_fb[quorum_idx], f, key=gar_key,
                    params=gar_params, center=fb_center,
                )
                sel_w = jnp.zeros((num_workers,), jnp.float32).at[
                    quorum_idx
                ].set(bundle["selected"])
            else:
                bundle = taps_lib.compute_flat(
                    gar.name, poisoned_fb, f, key=gar_key,
                    params=gar_params, center=fb_center,
                )
                sel_w = bundle["selected"]

        obs_vec = None
        if need_sel:
            if quorum_idx is not None:
                obs_vec = jnp.zeros((num_workers,), jnp.float32).at[
                    quorum_idx
                ].set(1.0)
            else:
                obs_vec = jnp.ones((num_workers,), jnp.float32)

        new_attack_state = state.attack_state
        detected = None
        if adaptive_cfg is not None:
            # Feedback = was the active cohort admitted? Majority-excluded
            # among the OBSERVED colluders counts as detected; a round
            # that observed none (whole cohort outside the quorum) and a
            # burst round (not the bracket's probe) hold the bracket.
            act_f = act_mask.astype(jnp.float32) * obs_vec
            cnt = jnp.sum(act_f)
            admitted = jnp.sum((sel_w > 0).astype(jnp.float32) * act_f)
            detected = admitted * 2.0 < cnt
            upd_lo, upd_hi = adaptive_lib.update_bracket(
                a_lo, a_hi, detected,
                mag_min=adaptive_cfg.mag_min,
                mag_max=adaptive_cfg.mag_max,
                regrow=adaptive_cfg.regrow,
            )
            hold = cnt == 0.0
            if degraded is not None:
                hold = hold | degraded
            new_attack_state = {
                "lo": jnp.where(hold, a_lo, upd_lo),
                "hi": jnp.where(hold, a_hi, upd_hi),
            }

        new_defense_state = state.defense_state
        if defense is not None:
            new_defense_state = dict(state.defense_state)
            if d_weighted:
                # The hub's exclusion law (observed minus admitted),
                # carried as an exponentially-decayed EMA — the in-graph
                # twin of MetricsHub(suspicion_halflife=).
                ind = (sel_w > 0).astype(jnp.float32) * obs_vec
                dec = jnp.float32(d_decay)
                new_defense_state["obs"] = (
                    state.defense_state["obs"] * dec + obs_vec
                )
                new_defense_state["exc"] = (
                    state.defense_state["exc"] * dec + (obs_vec - ind)
                )
            if dp_decay is not None:
                # Data-plane twins: the detectors observe the FULL
                # gathered stack every step (the subset emulation applies
                # at selection, after the gather), so every rank is
                # observed and a flag is an exclusion.
                dpdec = jnp.float32(dp_decay)
                ones = jnp.ones((num_workers,), jnp.float32)
                new_defense_state["dp_obs"] = (
                    state.defense_state["dp_obs"] * dpdec + ones
                )
                new_defense_state["dp_exc"] = (
                    state.defense_state["dp_exc"] * dpdec + dp_flags
                )

        new_gar_state = state.gar_state
        if gar.stateful_center:
            # Next step's v_0 = this step's aggregate (f32 — the carried
            # center should not round through the bf16 pipeline).
            new_gar_state = jax.tree.map(
                lambda l: l.astype(jnp.float32), aggr_tree
            )
        aggr_tree = core.cast_like(aggr_tree, params)  # no-op at f32
        updates, new_opt = optimizer.update(aggr_tree, state.opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            model_state=new_ms,
            opt_state=new_opt,
            worker_mom=new_mom,
            gar_state=new_gar_state,
            attack_state=new_attack_state,
            defense_state=new_defense_state,
            wire_state=new_wire,
        )
        metrics = {"loss": mean_loss}
        if wire_ef:
            # Per-rank EF residual L2 norms — the in-graph twin of the
            # wire event's ef_residual_norm field (schema v11).
            metrics["wire_resid_norm"] = jnp.sqrt(
                jnp.sum(new_wire["resid"] ** 2, axis=1)
            )
        if adaptive_cfg is not None:
            # Controller observability (the app loop surfaces these as
            # schema-v7 ``attack_adapt`` events): the magnitude actually
            # played and whether the rule caught it this round.
            metrics["attack_mag"] = jnp.asarray(atk_mag, jnp.float32)
            metrics["attack_detected"] = detected.astype(jnp.float32)
        if defense is not None and d_weighted:
            # The suspicion weights actually composed this step (the app
            # loop surfaces them as ``defense_weights`` events — the
            # summary's suspicion-weight digest at the on-mesh scale).
            metrics["defense_w"] = def_w
        if dp_decay is not None:
            # Data-plane observability (schema v9 ``data_defense``
            # events): the per-rank spectral outlier scores, this
            # round's detector flags, and the weights composed.
            metrics["dataplane_score"] = dp_scores.astype(jnp.float32)
            metrics["dataplane_flags"] = dp_flags
            metrics["dataplane_w"] = dp_w
        if telemetry:
            # In-graph audit tap (telemetry/taps.py): recompute the
            # poisoned flat stack with the SAME keys the aggregation used
            # — on the flat path XLA CSEs this against the rule's own
            # pass; on the tree/fold paths it is the enabled-only
            # overhead the docstring prices. Nothing here flows into
            # new_state, so the trajectory is untouched.
            flat_raw = core.flatten_rows(grads)
            poisoned = apply_gradient_attack(
                attack, flat_raw, act_mask, key=atk_key, **eff_params
            )
            if row_w is not None:
                # The tap audits the rule's selection over the SAME rows
                # the rule consumed — staleness- and suspicion-weighted
                # (and adaptively poisoned) included.
                poisoned = (poisoned * row_w[:, None]).astype(
                    poisoned.dtype
                )
            tap_center = (
                ravel_pytree(state.gar_state)[0]
                if gar.stateful_center else None
            )
            if subset is not None and subset < num_workers:
                tap_sel = core.subset_indices(sub_key, num_workers, subset)
                bundle = taps_lib.compute_flat(
                    gar.name, poisoned[tap_sel], f, key=gar_key,
                    params=gar_params, center=tap_center,
                )
                metrics["tap"] = taps_lib.scatter(
                    bundle, tap_sel, num_workers
                )
            else:
                metrics["tap"] = taps_lib.compute_flat(
                    gar.name, poisoned, f, key=gar_key, params=gar_params,
                    center=tap_center,
                )
        return new_state, metrics

    sharded_step = mesh_lib.shard_map(
        _local_step,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis)),
        out_specs=(P(), P()),
        check_vma=False,
    )

    @functools.partial(
        jax.jit,
        out_shardings=(repl, repl),
        donate_argnums=core.step_donation(),
    )
    def step_fn(state, x, y):
        return sharded_step(state, x, y)

    @jax.jit
    def eval_fn(state, x):
        return eval_apply(state.params, state.model_state, x)

    step_fn.mesh = mesh
    step_fn.batch_sharding = shard_w
    # The un-jitted shard_map body + this jit's output shardings, consumed
    # by core.make_chunked_step so a K-step chunk scans the SAME program
    # body instead of nesting jits (whose inner donation would be dropped).
    step_fn.inner = sharded_step
    step_fn.out_shardings = (repl, repl)
    return init_fn, step_fn, eval_fn

"""Adaptive (suspicion-aware) Byzantine attack controllers (DESIGN.md §16).

Every attack in ``attacks/__init__.py`` is OBLIVIOUS: a fixed lie/empire/
reverse schedule that ignores what the defense observes. But the defense's
own audit plane (telemetry taps -> per-rank suspicion, docs/TELEMETRY.md)
computes exactly the signal a worst-case adversary would exploit — and
"A Little Is Enough" (Baruch, Baruch & Goldberg, 2019; the source of our
``lie_attack``) showed that magnitudes tuned *just under* the detection
margin defeat Krum/Bulyan. This module closes the loop on the attacker's
side with three stateful behaviors, shared by BOTH deployment scales:

  1. **Magnitude modulation** — a bisection bracket ``[lo, hi]`` over the
     attack magnitude (lie's ``z``, empire's ``eps``). Each round the
     controller plays the midpoint; feedback is whether the Byzantine
     cohort appeared in the rule's selection (``selection_indices`` /
     audit taps in-graph, the broadcast model-delta probe on the host
     plane). Detected (excluded) -> ``hi`` drops to the played value;
     accepted -> ``lo`` rises to it. A bracket that collapses while still
     accepted RE-EXPANDS upward, so the controller tracks a moving
     threshold (the escalating defense of ``aggregators/defense.py``
     shifts it mid-run) instead of freezing at the first fixed point.
  2. **Cohort rotation** — the ``f`` ACTIVE attackers each round are a
     sliding window over a pool of ``f_pool >= f`` colluding ranks
     (inactive members behave honestly), so cumulative exclusion
     frequency is laundered across the pool: no single rank accumulates
     the suspicion a static cohort's victim does. The schedule is
     deterministic in (round, config) — colluders agree without
     communication, exactly like the reference's local-cohort trick.
  3. **Burst timing** — full-magnitude attacks are reserved for
     quorum-degradation windows, when the defense is weakest: in-graph,
     a round whose staleness emulation hard-cuts an honest rank; on the
     host plane, an inter-round gap blowout (soft-timeout / partition —
     the PR-4 ban path's evidence machinery finally gets an opponent
     that waits for it).

The in-graph half threads its state through ``TrainState.attack_state``
(and therefore through the ``lax.scan`` chunk carry for free); the
magnitude composes into ``fold.plan_gradient_attack_fold``'s shared-fake
row so the Gram fast path stays intact (parallel/aggregathor.py). The
host half (``HostController``) drives a REAL Byzantine worker process in
``apps/cluster.py`` (``--attack adaptive-lie``), reading its own
published-frame fate from the broadcast model delta (``delta_probe``)
or, when the operator leaks it, the PS's audit-tap stream
(``read_selected``).
"""

import dataclasses

import numpy as np

__all__ = [
    "ADAPTIVE_ATTACKS",
    "AdaptiveConfig",
    "is_adaptive",
    "configure",
    "base_params",
    "magnitude_key",
    "traced_fold_plan",
    "init_state",
    "played_magnitude",
    "update_bracket",
    "active_cohort",
    "active_mask_traced",
    "HostController",
    "delta_probe",
    "model_fake",
    "model_delta_probe",
    "read_selected",
]

# Registry of adaptive attack names -> the oblivious base attack whose
# row algebra they modulate. The base's shared-fake-row structure (ONE
# vector from every active colluder) is what keeps the folded Gram fast
# path applicable at a traced magnitude.
ADAPTIVE_ATTACKS = {
    "adaptive-lie": "lie",
    "adaptive-empire": "empire",
}

# Default magnitude search brackets. lie: z (the reference precomputes
# z_max = 1.035 for n=20, f=8 — the adaptive attacker searches far past
# it, because a duplicated fake cluster defeats Krum's score at much
# larger z). empire: eps (reference fixed eps = 10).
_DEFAULT_BRACKET = {
    "lie": (0.25, 6.0),
    "empire": (0.05, 12.0),
}
# Fraction of the full bracket below which an accepted bracket is
# considered collapsed and re-expands toward mag_max (threshold drift).
_COLLAPSE_FRAC = 0.02


def is_adaptive(attack):
    """True when ``attack`` names an adaptive controller."""
    return isinstance(attack, str) and attack in ADAPTIVE_ATTACKS


@dataclasses.dataclass(frozen=True)
class AdaptiveConfig:
    """Static plan of one adaptive attack (shared by both scales).

    ``pool`` holds the colluding ranks (``f_pool = len(pool) >= f``);
    each round the ``f`` ACTIVE attackers are a window into the pool
    advanced every ``rotation_period`` rounds (0 = static cohort =
    ``pool[:f]``). ``mag_min``/``mag_max`` bracket the bisection;
    ``burst_mag`` is the magnitude played inside degradation windows
    (default: ``mag_max``); ``regrow`` is the re-expansion step of a
    collapsed-but-accepted bracket.
    """

    base: str
    n: int
    f: int
    pool: tuple
    mag_min: float
    mag_max: float
    rotation_period: int = 0
    burst_mag: float = None
    regrow: float = 0.25

    def __post_init__(self):
        if self.base not in _DEFAULT_BRACKET:
            raise ValueError(
                f"unknown adaptive base attack {self.base!r}; "
                f"available: {sorted(_DEFAULT_BRACKET)}"
            )
        if not (1 <= self.f <= len(self.pool)):
            raise ValueError(
                f"active cohort f={self.f} must be in [1, f_pool="
                f"{len(self.pool)}]"
            )
        if len(set(self.pool)) != len(self.pool) or any(
            not (0 <= int(r) < self.n) for r in self.pool
        ):
            raise ValueError(
                f"pool {self.pool} must be distinct ranks in [0, {self.n})"
            )
        if not (0.0 < self.mag_min < self.mag_max):
            raise ValueError(
                f"need 0 < mag_min < mag_max, got "
                f"[{self.mag_min}, {self.mag_max}]"
            )
        if self.burst_mag is None:
            object.__setattr__(self, "burst_mag", float(self.mag_max))

    @property
    def f_pool(self):
        return len(self.pool)

    def pool_mask(self):
        """(n,) bool over all ranks: True for every pool member — the
        static superset of any round's active cohort (the trainers use
        it for honest-loss masking and the declared-f accounting)."""
        m = np.zeros(self.n, bool)
        m[np.asarray(self.pool, np.int64)] = True
        return m


def configure(attack, params, *, num_workers, f):
    """``AdaptiveConfig`` from an attack name + CLI ``attack_params``.

    Recognized params (all optional): ``f_pool`` (colluder pool size,
    default ``f``; the pool is the LAST f_pool ranks, matching
    ``core.default_byz_mask``'s last-f convention), ``pool`` (explicit
    rank list, overrides f_pool), ``mag_min``/``mag_max`` (bracket;
    ``z_min``/``z_max`` and ``eps_min``/``eps_max`` are accepted
    aliases), ``rotation`` (rounds per cohort window; 0 = static),
    ``burst`` (degradation-window magnitude), ``regrow``.
    """
    if not is_adaptive(attack):
        raise ValueError(f"{attack!r} is not an adaptive attack")
    base = ADAPTIVE_ATTACKS[attack]
    p = dict(params or {})
    if f < 1:
        raise ValueError(f"adaptive attacks need f >= 1 active ranks, got {f}")
    pool = p.get("pool")
    if pool is None:
        f_pool = int(p.get("f_pool", f))
        if f_pool < f:
            raise ValueError(f"f_pool {f_pool} < f {f}")
        pool = tuple(range(num_workers - f_pool, num_workers))
    else:
        pool = tuple(int(r) for r in pool)
    lo_d, hi_d = _DEFAULT_BRACKET[base]
    alias = "z" if base == "lie" else "eps"
    lo = float(p.get("mag_min", p.get(f"{alias}_min", lo_d)))
    hi = float(p.get("mag_max", p.get(f"{alias}_max", hi_d)))
    return AdaptiveConfig(
        base=base,
        n=num_workers,
        f=f,
        pool=pool,
        mag_min=lo,
        mag_max=hi,
        rotation_period=int(p.get("rotation", 0)),
        burst_mag=(None if p.get("burst") is None else float(p["burst"])),
        regrow=float(p.get("regrow", 0.25)),
    )


# Controller knobs that must NOT leak into the base attack functions'
# ``**params`` (they swallow unknown kwargs, so a leak would be silent) —
# plus the magnitude aliases themselves, which the controller OWNS.
CONTROLLER_KEYS = frozenset({
    "f_pool", "pool", "rotation", "burst", "regrow", "mag_min", "mag_max",
    "z_min", "z_max", "eps_min", "eps_max", "feedback", "feedback_taps",
    "threshold", "burst_factor", "burst_rounds", "cohort", "z", "eps",
})


def base_params(params):
    """Attack params with every controller knob stripped — what flows to
    the base attack function alongside the controller's magnitude."""
    return {k: v for k, v in (params or {}).items()
            if k not in CONTROLLER_KEYS}


def magnitude_key(base):
    """The base attack's magnitude kwarg name (lie's z, empire's eps)."""
    return "z" if base == "lie" else "eps"


def traced_fold_plan(cfg, magnitude):
    """``GradientAttackFold`` for the adaptive attack at a TRACED
    magnitude: the row remap is the base attack's static one (every
    active colluder publishes ONE shared fake row), only the fake-row
    transform closes over the traced scalar — so the Gram fast path of
    ``parallel/fold.py`` survives magnitude adaptation unchanged.
    Requires a static cohort (``rotation_period == 0``): rotation makes
    the remap itself dynamic, and those configs keep the where-path."""
    from . import GradientAttackFold, _shared_fake_builder

    if cfg.rotation_period > 0:
        raise ValueError("traced_fold_plan needs a static cohort")
    mask = active_cohort(cfg, 0)
    byz_idx = np.flatnonzero(mask)
    if cfg.base == "lie":
        transform = lambda mu, sigma: mu + magnitude * sigma  # noqa: E731
    else:
        transform = lambda mu, sigma: -magnitude * mu  # noqa: E731
    return GradientAttackFold(
        np.where(mask, cfg.n, np.arange(cfg.n)),
        np.ones(cfg.n),
        _shared_fake_builder(byz_idx, float(byz_idx.size), transform),
    )


# --- the bisection law (numpy scalars AND traced jnp, like rounds.py) ------


def init_state(cfg):
    """Initial controller state: the full bracket. In-graph this is the
    ``TrainState.attack_state`` pytree (two f32 scalars riding the scan
    carry); the host controller keeps the same two floats."""
    import jax.numpy as jnp

    return {
        "lo": jnp.asarray(cfg.mag_min, jnp.float32),
        "hi": jnp.asarray(cfg.mag_max, jnp.float32),
    }


def played_magnitude(lo, hi):
    """The magnitude the controller plays for a bracket: the midpoint."""
    return 0.5 * (lo + hi)


def update_bracket(lo, hi, detected, *, mag_min, mag_max, regrow=0.25):
    """One bisection step from this round's feedback.

    ``detected`` True means the active cohort was EXCLUDED by the rule at
    the played midpoint -> the threshold lies below it (``hi`` drops).
    False means the cohort was admitted -> the threshold lies above
    (``lo`` rises). A bracket narrower than ``_COLLAPSE_FRAC *
    (mag_max - mag_min)`` RE-EXPANDS by ``regrow`` of the remaining
    headroom in the direction the feedback points — up on acceptance,
    down on detection: the exclusion threshold is NOT stationary (the
    defense escalates mid-run, staleness weights shift), and a frozen
    bracket would keep under-attacking a weakened threshold — or, worse,
    keep over-attacking a TIGHTENED one forever (``lo`` only descends
    through the detection-side re-expansion). Accepts python/numpy
    scalars (host controller) or traced jnp values (the in-graph carry)
    and computes with the matching backend — the same dual-backend
    convention as ``utils.rounds.staleness_weights``. Returns
    ``(lo, hi)`` float32.
    """
    import jax
    import jax.numpy as jnp

    on_device = any(
        isinstance(v, jax.Array) for v in (lo, hi, detected)
    )
    xp = jnp if on_device else np
    lo = xp.asarray(lo, xp.float32)
    hi = xp.asarray(hi, xp.float32)
    det = xp.asarray(detected, bool)
    z = xp.float32(0.5) * (lo + hi)
    new_hi = xp.where(det, z, hi)
    new_lo = xp.where(det, lo, z)
    collapsed = (new_hi - new_lo) < _COLLAPSE_FRAC * (mag_max - mag_min)
    new_hi = xp.where(
        collapsed & ~det,
        xp.minimum(
            xp.float32(mag_max),
            new_hi + xp.float32(regrow) * (xp.float32(mag_max) - new_hi),
        ),
        new_hi,
    )
    new_lo = xp.where(
        collapsed & det,
        xp.maximum(
            xp.float32(mag_min),
            new_lo - xp.float32(regrow) * (new_lo - xp.float32(mag_min)),
        ),
        new_lo,
    )
    return new_lo.astype(xp.float32), new_hi.astype(xp.float32)


# --- cohort rotation --------------------------------------------------------


def _rotation_offset(cfg, rnd):
    if cfg.rotation_period <= 0:
        return 0
    return (int(rnd) // cfg.rotation_period) % cfg.f_pool


def active_cohort(cfg, rnd):
    """(n,) bool numpy mask of the ranks attacking at round ``rnd`` — the
    host-plane schedule every colluder derives independently."""
    mask = np.zeros(cfg.n, bool)
    off = _rotation_offset(cfg, rnd)
    for j in range(cfg.f):
        mask[cfg.pool[(off + j) % cfg.f_pool]] = True
    return mask


def pool_positions(cfg):
    """(n,) int32: each rank's position in the pool, -1 for honest ranks
    — the static half of the traced rotation mask."""
    pos = np.full(cfg.n, -1, np.int32)
    for j, r in enumerate(cfg.pool):
        pos[r] = j
    return pos


def active_mask_traced(cfg, step):
    """Traced (n,) bool active-cohort mask at (traced) ``step``: the
    in-graph twin of ``active_cohort``, identical for every concrete
    step value (pinned in tests/test_adaptive.py)."""
    import jax.numpy as jnp

    pos = jnp.asarray(pool_positions(cfg))
    if cfg.rotation_period <= 0:
        return jnp.asarray(active_cohort(cfg, 0))
    off = (step.astype(jnp.int32) // cfg.rotation_period) % cfg.f_pool
    rel = jnp.mod(pos - off, cfg.f_pool)
    return (pos >= 0) & (rel < cfg.f)


# --- host-plane controller (real Byzantine worker processes) ---------------


class HostController:
    """The attacker brain of a real Byzantine worker process
    (``apps/cluster.py --attack adaptive-lie``): bisection magnitude +
    deterministic rotation + gap-triggered bursts, fed by the worker's
    own observations (the broadcast model delta, its wall clock).

    Burst policy: the controller keeps an EMA of the inter-round gap (the
    cadence of model broadcasts it receives). A gap exceeding
    ``burst_factor`` x the EMA is a quorum-degradation window — a
    straggler, soft timeout or partition is slowing the PS — and the
    next ``burst_rounds`` attacked rounds play ``cfg.burst_mag`` instead
    of the bracket midpoint (and skip feedback updates: a burst is a
    smash-and-grab, not a probe).
    """

    def __init__(self, cfg, my_rank, *, burst_factor=3.0, burst_rounds=3):
        self.cfg = cfg
        self.my_rank = int(my_rank)
        self.lo = float(cfg.mag_min)
        self.hi = float(cfg.mag_max)
        self.burst_factor = float(burst_factor)
        self.burst_rounds = int(burst_rounds)
        self._burst_left = 0
        self._gap_ema = None
        self._last_t = None
        self.probes = 0
        self.detections = 0

    def is_active(self, rnd):
        """Whether THIS rank attacks at round ``rnd`` (rotation)."""
        return bool(active_cohort(self.cfg, rnd)[self.my_rank])

    def bursting(self):
        return self._burst_left > 0

    def magnitude(self):
        """The magnitude to play this round."""
        if self._burst_left > 0:
            return float(self.cfg.burst_mag)
        return float(played_magnitude(self.lo, self.hi))

    def feedback(self, detected):
        """Fold one selection observation into the bracket (no-op during
        a burst — its magnitude is not the bracket's probe)."""
        if self._burst_left > 0:
            self._burst_left -= 1
            return
        self.probes += 1
        self.detections += int(bool(detected))
        self.lo, self.hi = (
            float(v) for v in update_bracket(
                self.lo, self.hi, bool(detected),
                mag_min=self.cfg.mag_min, mag_max=self.cfg.mag_max,
                regrow=self.cfg.regrow,
            )
        )

    def observe_round(self, t_now):
        """Feed one model-broadcast arrival time; returns True when this
        arrival opened a degradation window (burst trigger)."""
        t_now = float(t_now)
        if self._last_t is None:
            self._last_t = t_now
            return False
        gap = max(t_now - self._last_t, 0.0)
        self._last_t = t_now
        if self._gap_ema is None:
            self._gap_ema = gap
            return False
        triggered = (
            self._gap_ema > 0.0 and gap > self.burst_factor * self._gap_ema
        )
        # The blown-out gap must not poison the baseline EMA (it IS the
        # anomaly); fold only ordinary gaps.
        if not triggered:
            self._gap_ema = 0.8 * self._gap_ema + 0.2 * gap
        if triggered:
            self._burst_left = self.burst_rounds
        return triggered

    def stats(self):
        return {
            "lo": round(self.lo, 6),
            "hi": round(self.hi, 6),
            "magnitude": round(self.magnitude(), 6),
            "probes": self.probes,
            "detections": self.detections,
            "bursting": self.bursting(),
        }


def delta_probe(prev_flat, new_flat, fake_excess, mu_est=None, *,
                threshold=0.05):
    """Published-frame fate from the broadcast model delta.

    The PS broadcasts its model every round; for a plain-SGD server the
    round delta is ``-lr * aggregate``. If the attacker's fake vector
    ``mu + z*sigma`` entered the selection with weight ``alpha``, the
    aggregate is ``(1-alpha) * mu_sel + alpha * fake`` and the delta
    carries an ``alpha * (fake - mu)`` component — the EXCESS direction
    ``u = z*sigma`` the attacker itself constructed. The probe projects
    the (negated) delta onto ``u`` after removing the component along
    the attacker's own honest-mean estimate ``mu_est`` (the honest part
    of the aggregate lies almost entirely along it, and ``<mu, sigma>``
    is not small in general): excluded rounds leave only honest noise in
    the residual, admitted rounds leave the ``alpha*u`` term.

    Returns ``(detected, score)``: ``detected`` True when the normalized
    residual projection falls below ``threshold`` — the cohort's rows
    did NOT reach the aggregate.
    """
    d = np.asarray(prev_flat, np.float64) - np.asarray(new_flat, np.float64)
    u = np.asarray(fake_excess, np.float64)
    un = np.linalg.norm(u)
    if un == 0.0 or not np.isfinite(un):
        return True, 0.0
    u = u / un
    if mu_est is not None:
        m = np.asarray(mu_est, np.float64)
        mn = np.linalg.norm(m)
        if mn > 0.0 and np.isfinite(mn):
            m = m / mn
            d = d - np.dot(d, m) * m
            u = u - np.dot(u, m) * m
            un2 = np.linalg.norm(u)
            if un2 < 1e-12:
                return True, 0.0  # fake excess lies along mu: unobservable
            u = u / un2
    dn = np.linalg.norm(d)
    if dn == 0.0 or not np.isfinite(dn):
        return True, 0.0
    score = float(np.dot(d / dn, u))
    return bool(score < threshold), score


def model_fake(base, stack, magnitude):
    """The model-plane collusion fake from an observed (k, d) replica/
    gossip stack: ``mu + z*sigma`` (lie) or ``-eps*mu`` (empire) at the
    controller's magnitude — the host twin of
    ``attacks.model_lie_attack_rows``/``model_empire_attack_rows``, fed
    by whatever stack the Byzantine publisher last GATHERED (a PS sees
    every replica model in the gather step; a LEARN node sees its gossip
    quorum). numpy in, numpy out (host roles only; the in-graph twins
    call the row attacks with a traced magnitude directly)."""
    stack = np.asarray(stack, np.float32)
    mu = stack.mean(axis=0)
    if base == "empire":
        return (-float(magnitude) * mu).astype(np.float32)
    sigma = stack.std(axis=0, ddof=1)  # NaN at k=1, like the gradient twin
    return (mu + float(magnitude) * sigma).astype(np.float32)


def model_delta_probe(prev_mean, new_mean, fake_excess, honest_delta=None,
                      *, threshold=0.05):
    """Published-MODEL fate from the next round's gathered plane.

    The model-plane mirror of ``delta_probe``: a Byzantine publisher that
    entered its peers' model aggregation pulls every honest replica's
    model TOWARD its fake — so across one round the mean of the honest
    peers' models moves by ``alpha * (fake - mu) + honest_drift``. The
    probe projects that forward delta onto the attacker's own excess
    direction after removing the honest-drift estimate (the attacker's
    honest loop knows its own round delta). Implemented by calling
    ``delta_probe`` with the arguments swapped — its ``prev - new``
    convention then yields the forward delta. Returns
    ``(detected, score)`` with the same semantics.
    """
    return delta_probe(
        new_mean, prev_mean, fake_excess, mu_est=honest_delta,
        threshold=threshold,
    )


def read_selected(path, rank, *, tail_bytes=262144):
    """Newest audit-tap verdict for ``rank`` from a PS telemetry JSONL.

    The leaked-audit feedback channel (DESIGN.md §16): when the operator
    exposes the PS's telemetry stream (or its /metrics endpoint), the
    attacker reads its own ``selected`` entry directly instead of
    probing the model delta. Tail-reads the last ``tail_bytes`` and
    returns ``(step, selected)`` of the newest step record carrying a
    tap, or None when none is found.
    """
    import json
    import os

    try:
        size = os.path.getsize(path)
        with open(path, "rb") as fp:
            fp.seek(max(0, size - tail_bytes))
            chunk = fp.read().decode("utf-8", "replace")
    except OSError:
        return None
    for line in reversed(chunk.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn first line of the tail window
        if rec.get("kind") != "step":
            continue
        tap = rec.get("tap")
        if not tap:
            continue
        sel = tap.get("selected") or []
        if rank < len(sel):
            return int(rec.get("step", -1)), float(sel[rank])
    return None

// Multi-reader/multi-writer atomic register with blocking
// producer-consumer semantics.
//
// Counterpart of the reference's multibuffer op
// (tensorflow_impl/.../rsrcs/native/op_multibuffer/op.cpp:11 — "MRMW
// read-only/write-only atomic register with blocking producer-consumer
// semantic", backed by include/multiregister.hpp) — re-designed: the
// reference uses a lock-free multi-buffer scheme to hand tensors between TF
// graph threads; here the register hands host-side payloads (serialized
// model/gradient blobs) between the driver thread and host-callback /
// multi-host RPC threads, so a seqlock-free mutex+condvar design is
// sufficient and formally simpler:
//
//   - write(slot, data): atomically replaces the slot's value and bumps its
//     version; never blocks (last-writer-wins, like a register — not a queue);
//   - read(slot, min_version): blocks until the slot's version is
//     >= min_version, then copies out a consistent snapshot. Version 1 is the
//     first write, so read(slot, 1) is "wait until somebody wrote" — the
//     same synchronization the TF servicer's history-polling loop provides
//     (grpc_message_exchange_servicer.py:58-65), without the 1 ms spin.
//
// C ABI for ctypes.

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

struct Slot {
  std::mutex mu;
  std::condition_variable cv;
  std::vector<uint8_t> data;
  int64_t version = 0;
};

struct MultiBuffer {
  explicit MultiBuffer(int64_t nslots) : slots(nslots) {}
  std::vector<Slot> slots;
};

}  // namespace

#define GT_EXPORT __attribute__((visibility("default")))

extern "C" {

GT_EXPORT void* gt_multibuffer_new(int64_t nslots) {
  return new MultiBuffer(nslots);
}

GT_EXPORT void gt_multibuffer_free(void* mb) { delete static_cast<MultiBuffer*>(mb); }

// Atomically replace slot contents; returns the new version.
GT_EXPORT int64_t gt_multibuffer_write(void* mb, int64_t slot, const uint8_t* data,
                             int64_t nbytes) {
  Slot& s = static_cast<MultiBuffer*>(mb)->slots[slot];
  int64_t v;
  {
    std::lock_guard<std::mutex> lk(s.mu);
    s.data.assign(data, data + nbytes);
    v = ++s.version;
  }
  s.cv.notify_all();
  return v;
}

// Current byte size once version >= min_version (blocking); use to size the
// read buffer. timeout_ms < 0 means wait forever; returns -1 on timeout.
GT_EXPORT int64_t gt_multibuffer_wait(void* mb, int64_t slot, int64_t min_version,
                            int64_t timeout_ms) {
  Slot& s = static_cast<MultiBuffer*>(mb)->slots[slot];
  std::unique_lock<std::mutex> lk(s.mu);
  const auto ready = [&] { return s.version >= min_version; };
  if (timeout_ms < 0) {
    s.cv.wait(lk, ready);
  } else if (!s.cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                            ready)) {
    return -1;
  }
  return static_cast<int64_t>(s.data.size());
}

// Copy out a consistent snapshot (caller sized the buffer via _wait).
// Writes the version to *out_version and returns the ACTUAL byte count
// copied (a concurrent write may have grown or shrunk the slot since the
// wait), or -1 if the current value no longer fits the caller's buffer.
GT_EXPORT int64_t gt_multibuffer_read(void* mb, int64_t slot, uint8_t* out,
                            int64_t capacity, int64_t* out_version) {
  Slot& s = static_cast<MultiBuffer*>(mb)->slots[slot];
  std::lock_guard<std::mutex> lk(s.mu);
  if (static_cast<int64_t>(s.data.size()) > capacity) return -1;
  std::memcpy(out, s.data.data(), s.data.size());
  *out_version = s.version;
  return static_cast<int64_t>(s.data.size());
}

GT_EXPORT int64_t gt_multibuffer_version(void* mb, int64_t slot) {
  Slot& s = static_cast<MultiBuffer*>(mb)->slots[slot];
  std::lock_guard<std::mutex> lk(s.mu);
  return s.version;
}

}  // extern "C"

"""Shared building blocks for the CNN zoo.

TPU-first conventions used across the zoo (counterpart of the torch zoo in
pytorch_impl/libs/garfieldpp/models/):
  - NHWC layout (XLA's native conv layout on TPU; torch is NCHW);
  - every module takes ``train: bool`` and routes BatchNorm through the
    ``batch_stats`` collection, dropout through the ``dropout`` rng;
  - ``dtype`` threads a compute dtype (bfloat16 on TPU for MXU-friendly
    convs) while parameters stay float32 (``param_dtype``).
"""

from functools import partial

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["conv", "conv1x1", "norm", "max_pool", "avg_pool", "global_avg_pool"]


def conv(features, kernel, stride=1, *, padding="SAME", groups=1, use_bias=False,
         dtype=jnp.float32, name=None):
    """3x3-style conv with torch-like defaults (no bias before BN)."""
    if isinstance(kernel, int):
        kernel = (kernel, kernel)
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    return nn.Conv(
        features, kernel, strides=stride, padding=padding,
        feature_group_count=groups, use_bias=use_bias, dtype=dtype, name=name,
    )


conv1x1 = partial(conv, kernel=1, padding="VALID")


def norm(train, *, dtype=jnp.float32, name=None):
    """BatchNorm with torch defaults (momentum 0.9, eps 1e-5)."""
    return nn.BatchNorm(
        use_running_average=not train, momentum=0.9, epsilon=1e-5,
        dtype=dtype, name=name,
    )


def max_pool(x, window=2, stride=None, padding="VALID"):
    stride = window if stride is None else stride
    if isinstance(window, int):
        window = (window, window)
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    return nn.max_pool(x, window, strides=stride, padding=padding)


def avg_pool(x, window=2, stride=None, padding="VALID"):
    stride = window if stride is None else stride
    if isinstance(window, int):
        window = (window, window)
    if isinstance(stride, int):
        stride = (stride, stride)
    return nn.avg_pool(x, window, strides=stride, padding=padding)


def global_avg_pool(x):
    """NHWC global average pool -> (N, C)."""
    return jnp.mean(x, axis=(1, 2))

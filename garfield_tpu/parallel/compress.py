"""In-graph twin of the wire codec's lossy schemes (DESIGN.md §20).

The cluster's compression happens on the host in ``utils/wire.py``; the
single-process trainers (``parallel/aggregathor``) emulate it INSIDE the
jitted step so the robustness matrix, DEFBENCH's adaptive-lie controller
and the convergence tests measure what compressed frames do to the GARs
without standing up a TCP cluster. Everything here is pure jnp — it
rides ``shard_map``/``lax.scan`` and differentiably does nothing (the
round trip sits on the data path after ``stop_gradient``-equivalent
gradient extraction).

What IS pinned against the host codec: the grid. ``_quant_rows`` uses
the same symmetric per-block scale (``max|x| / qmax``), the same
round-to-nearest-even, the same clip — so a quantized value here equals
``wire.decode(wire.encode(...))`` of the same f32 input bit-for-bit.

What is NOT pinned: top-k tie-breaking. ``jax.lax.top_k`` and
``np.argpartition`` may keep different coordinates when |values| tie at
the k-th magnitude; the emulation keeps AT LEAST k coordinates (every
coordinate >= the k-th magnitude survives the threshold mask). Ties are
measure-zero for real gradients; the host<->graph parity tests pin the
quantizers bitwise and the sparsifier on tie-free inputs only.

Error feedback (Karimireddy et al., EF-SGD) lives in
``TrainState.wire_state["resid"]`` — an (n_workers, d) f32 residual
carried through the scan chunk carry and the checkpoint tree, which is
what makes chunked and mid-run-resumed trainings bitwise-identical to
straight runs (tests/test_compress.py pins both). The residual
compensates the HEAD (gradient) segment only; see
``wire.ErrorFeedback`` for why model/gossip planes never get EF.
"""

import jax
import jax.numpy as jnp

from ..utils import wire

__all__ = ["roundtrip_rows", "ef_roundtrip_rows", "init_wire_state"]


def _quant_rows(rows, qmax, block):
    """Per-block symmetric linear quantize + dequantize of (n, d) rows.

    Bit-identical twin of the host ``wire._quant_payload`` ->
    ``wire._dequant`` round trip: pad each row to a block multiple,
    scale = max|x| / qmax per block, round-to-nearest-even
    (``jnp.rint`` == ``np.rint``), clip to [-qmax, qmax], multiply back.
    """
    n, d = rows.shape
    nblocks = -(-d // block)
    pad = nblocks * block - d
    x = jnp.pad(rows, ((0, 0), (0, pad))) if pad else rows
    xb = x.reshape(n, nblocks, block)
    scales = jnp.max(jnp.abs(xb), axis=2) / jnp.float32(qmax)
    safe = jnp.where(scales > 0, scales, jnp.float32(1.0))
    codes = jnp.clip(jnp.rint(xb / safe[:, :, None]), -qmax, qmax)
    out = (codes * scales[:, :, None]).reshape(n, nblocks * block)
    return out[:, :d].astype(jnp.float32)


def _topk_rows(rows, k):
    """Magnitude top-k mask of (n, d) rows via the k-th-magnitude
    threshold (``lax.top_k`` on |rows|). Keeps every coordinate whose
    magnitude >= the k-th largest — i.e. AT LEAST k survive on ties
    (see the module docstring for why that is acceptable drift from the
    host's exactly-k frames)."""
    d = rows.shape[-1]
    k = int(min(max(k, 1), d))
    kth = jax.lax.top_k(jnp.abs(rows), k)[0][:, -1]
    mask = jnp.abs(rows) >= kth[:, None]
    return jnp.where(mask, rows, jnp.float32(0.0))


def roundtrip_rows(rows, scheme, *, k=None, block=wire.QUANT_BLOCK):
    """Encode->decode emulation of one wire scheme over (n, d) f32 rows.

    ``scheme`` in ``wire.WIRE_SCHEMES``; "f32" is the identity, "bf16"
    the XLA convert round trip (same RNE the host codec uses), "topk"
    needs ``k`` (kept coordinates per row)."""
    rows = rows.astype(jnp.float32)
    if scheme == "f32":
        return rows
    if scheme == "bf16":
        return rows.astype(jnp.bfloat16).astype(jnp.float32)
    if scheme == "int8":
        return _quant_rows(rows, 127, int(block))
    if scheme == "int4":
        return _quant_rows(rows, 7, int(block))
    if scheme == "topk":
        if k is None:
            raise ValueError("topk roundtrip needs an explicit k")
        return _topk_rows(rows, k)
    raise ValueError(f"unknown wire scheme {scheme!r}")


def init_wire_state(num_workers, d):
    """Fresh error-feedback state for ``TrainState.wire_state``: one
    zero residual row per worker slot. Checkpointed with the rest of
    the state tree, so resume carries non-zero residuals bitwise."""
    return {"resid": jnp.zeros((int(num_workers), int(d)), jnp.float32)}


def ef_roundtrip_rows(rows, resid, scheme, *, k=None,
                      block=wire.QUANT_BLOCK):
    """Error-feedback compressed emulation of the gradient plane.

    Sends ``C(rows + resid)`` and returns ``(sent, new_resid)`` with
    ``new_resid = (rows + resid) - sent`` — the in-graph twin of
    ``wire.ErrorFeedback.compensate``/``update`` around the host
    encode/decode. The caller decides WHICH rows are honest senders;
    Byzantine rows overwrite ``sent`` afterwards (an attacker controls
    its wire bytes), and their residual rows are dead state.
    """
    comp = rows.astype(jnp.float32) + resid
    sent = roundtrip_rows(comp, scheme, k=k, block=block)
    return sent, comp - sent

"""Step timing, XLA profiler traces, and bandwidth accounting.

Counterpart of the reference's opt-in instrumentation (SURVEY §5):
  - per-step wall time: ``timeit(train_step, number=1)`` prints
    (Aggregathor/trainer.py:244-247) -> ``StepTimer``;
  - profiler: ``torch.autograd.profiler.profile(enabled=bench)``
    (Aggregathor/trainer.py:234-239) -> ``jax.profiler.trace`` (XLA/TPU
    timeline viewable in TensorBoard/Perfetto);
  - bandwidth: psutil NIC byte deltas (garfieldpp/tools.py:152-163, printed
    trainer.py:240-241). A TPU mesh has no NIC counters to poll; collective
    traffic is fully determined by the program, so we *derive* per-step bytes
    from the collective shapes instead (``collective_bytes``).
"""

import contextlib
import time

import jax
import numpy as np

__all__ = [
    "StepTimer",
    "paired_reps",
    "trace",
    "collective_bytes",
    "convert_to_gbit",
    "enable_compile_cache",
    "is_transient_backend_error",
    "probe_device_count",
]


def probe_device_count(timeout_s=None):
    """Device count of the DEFAULT backend, probed in a short-timeout
    subprocess — never initializes a backend in this process.

    The r5 outage post-mortem (VERDICT "Next round" #1a): with the TPU
    tunnel down, in-process ``jax.devices()`` blocks forever inside plugin
    init, so ``bench.py`` hung to rc=124 and ``dryrun_multichip`` died —
    the entry points must decide "is the backend alive?" WITHOUT betting
    the process on it. The subprocess inherits the environment (so it
    probes the same plugin this process would use); a hang is bounded by
    ``timeout_s`` (env ``GARFIELD_BACKEND_PROBE_TIMEOUT_S``, default 90 —
    tunneled TPU init takes tens of seconds when healthy).

    Returns the device count, or None when the probe times out or fails —
    callers fall back to the virtual CPU mesh / emit a diagnostic instead
    of hanging.
    """
    import os
    import subprocess
    import sys

    if timeout_s is None:
        timeout_s = float(
            os.environ.get("GARFIELD_BACKEND_PROBE_TIMEOUT_S", 90)
        )
    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; print('DEVICES=%d' % len(jax.devices()))",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            timeout=timeout_s,
        )
    except (subprocess.TimeoutExpired, OSError):
        return None
    if proc.returncode != 0:
        return None
    for line in (proc.stdout or "").splitlines():
        if line.startswith("DEVICES="):
            try:
                return int(line.split("=", 1)[1])
            except ValueError:
                return None
    return None


def enable_compile_cache(cache_dir=None):
    """Enable the persistent XLA compile cache (best-effort, never raises).

    Shared by ``bench.py`` and ``__graft_entry__.py``: the north-star step and
    the dryrun topologies are large SPMD programs (~30 s first compile on the
    tunneled chip); caching makes retries after transient tunnel failures and
    driver re-runs near-instant. Safe to call before any backend use.

    The default directory is keyed by the jax/jaxlib versions: cached
    executables are NOT serialization-stable across jaxlib builds, and a
    stale entry from a previous container deserializes into a native
    SIGSEGV (not a catchable miss) — a poisoned cache must never be
    reachable from a new runtime.
    """
    import os

    try:
        import jaxlib

        versioned = (
            f"~/.cache/garfield_tpu/jax_cache-"
            f"{jax.__version__}-{jaxlib.__version__}"
        )
        jax.config.update(
            "jax_compilation_cache_dir",
            cache_dir or os.path.expanduser(versioned),
        )
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass  # cache is an optimization; never fail the caller


# Substrings that mark a *transient* backend/tunnel failure worth retrying.
# Deterministic failures (lowering errors, shape errors, OOM) must surface
# immediately — see BENCH_r02.json for the motivating mid-compile drop.
_TRANSIENT_ERROR_MARKS = (
    "read body",
    "response body closed",
    "remote_compile",
    "connection",
    "unavailable",
    "deadline exceeded",
    "socket",
    "timed out",
    "timeout",
    "broken pipe",
    "reset by peer",
)


def is_transient_backend_error(exc):
    """True when ``exc`` looks like a transient tunnel/transport failure."""
    msg = f"{type(exc).__name__}: {exc}".lower()
    return any(mark in msg for mark in _TRANSIENT_ERROR_MARKS)


def paired_reps(timed_fn, reps, floor=1e-9, pairs=3, agg="median"):
    """Per-iteration latency via the paired-reps difference estimator.

    ``timed_fn(k)`` must run k *dependency-chained* iterations ended by a
    host-readback sync, and return the elapsed wall seconds. The chain is run
    at ``reps`` and ``2 * reps`` and the difference divided by ``reps`` —
    any constant per-run cost (queue flush, readback round trip) cancels.

    This is the only timing that holds up on tunneled/remote device
    backends, where ``jax.block_until_ready`` can return before the device
    finishes and a host readback (the one reliable sync) carries a large
    constant queue-flush cost; naive per-call block-and-subtract timing
    under-measures there by orders of magnitude (PERF.md "Timing
    methodology").

    Noise handling: on a shared chip a single (t1, t2) pair can come out
    with ``t2 - t1 <= 0``; flooring that would report ``1/floor`` as a
    plausible-looking throughput. Up to ``pairs`` independent pairs are
    measured, differences at or below ``floor`` are discarded as
    noise-dominated, and the chosen aggregate of the rest is returned.
    ``agg="median"`` (default) stops early once two pairs agree to be
    positive — the right choice for end-to-end steps, where the median
    tracks the typical shared-chip window. ``agg="min"`` runs ALL pairs
    and returns the minimum positive difference — the classic min-time
    latency methodology for MICRO-benchmarks, where co-tenant
    interference only ever adds time and the minimum is the best estimate
    of the kernel itself (VERDICT r4 weak #2: median-of-3 sub-ms grid
    cells bounced >1.3x between committed sweeps). Returns **None** when
    every pair is noise-dominated — the workload is below this host's
    measurement floor and no number would be honest; callers must treat
    None as "unmeasurable", not zero.
    """
    diffs = []
    for _ in range(max(1, pairs)):
        t1 = timed_fn(reps)
        t2 = timed_fn(2 * reps)
        d = (t2 - t1) / reps
        if d > floor:
            diffs.append(d)
        if agg == "median" and len(diffs) >= 2:
            break
    if not diffs:
        return None
    return float(np.min(diffs) if agg == "min" else np.median(diffs))


class StepTimer:
    """Wall-clock timer that blocks on device results for honest numbers.

    ``with timer.step(): ...`` records one step; ``summary()`` reports
    count/mean/min/max seconds, like the per-step prints at
    Aggregathor/trainer.py:244-247 but aggregated.
    """

    def __init__(self):
        self.times = []

    @contextlib.contextmanager
    def step(self, block_on=None):
        t0 = time.perf_counter()
        yield
        if block_on is not None:
            jax.block_until_ready(block_on)
        self.times.append(time.perf_counter() - t0)

    def record_chunk(self, total_s, k):
        """Fold one k-step chunked dispatch (``--chunk_steps``): the steps
        shared one dispatch + one sync, so the only honest per-step number
        is the mean ``total_s / k`` — recorded k times to keep ``last()``,
        ``summary()`` and the percentiles per-STEP shaped."""
        self.times.extend([total_s / k] * k)

    def last(self):
        return self.times[-1] if self.times else float("nan")

    def summary(self):
        if not self.times:
            return {"count": 0}
        a = np.asarray(self.times)
        return {
            "count": int(a.size),
            "mean_s": float(a.mean()),
            "min_s": float(a.min()),
            "max_s": float(a.max()),
            "total_s": float(a.sum()),
            # Tail percentiles: the mean hides the dispatch-tail spread
            # chunking exists to kill (the 130/s best-window vs 108/s
            # typical gap, PERF.md r8) — p50/p95/p99 make the fewer-fatter-
            # dispatches win visible in committed artifacts.
            "p50_s": float(np.percentile(a, 50)),
            "p95_s": float(np.percentile(a, 95)),
            "p99_s": float(np.percentile(a, 99)),
        }


@contextlib.contextmanager
def trace(log_dir=None):
    """``jax.profiler`` trace scope; no-op when ``log_dir`` is None."""
    if log_dir is None:
        yield
        return
    with jax.profiler.trace(str(log_dir)):
        yield


def collective_bytes(topology, *, num_workers, d, num_ps=1, rounds=1,
                     bytes_per_el=4, axis_size=None):
    """Per-step collective traffic (bytes) implied by the topology's program.

    Replaces NIC-counter polling (garfieldpp/tools.py:152-163): the SPMD
    program's communication volume is static. Counts the all_gather payloads
    per device (ring all-gather moves (k-1)/k of the gathered buffer over
    ICI, k = axis size):

      - aggregathor: one (n_w, d) gradient all_gather           (server.py:112-159)
      - byzsgd:      + one (n_ps, d) model all_gather           (server.py:161-184)
      - learn:       gradient gather x (1 + rounds) + model gather
                                                                (LEARN/trainer.py:208-257)
    """
    k = axis_size if axis_size else num_workers
    frac = (k - 1) / k if k > 1 else 0.0
    grad_gather = num_workers * d * bytes_per_el * frac
    model_gather = num_ps * d * bytes_per_el * frac
    if topology in ("centralized",):
        return 0
    if topology in ("aggregathor", "garfield_cc"):
        return int(grad_gather)
    if topology == "byzsgd":
        return int(grad_gather + model_gather)
    if topology == "learn":
        return int(grad_gather * (1 + rounds) + num_workers * d * bytes_per_el * frac)
    raise ValueError(f"unknown topology {topology!r}")


def convert_to_gbit(num_bytes):
    """Bytes -> Gbit (garfieldpp/tools.py:161-163)."""
    return num_bytes * 8 / (1024 ** 3)

"""Multi-host (DCN) integration: 2 real processes, one SPMD program.

The reference's multi-node story was ssh fan-out plus gRPC/RPC glue with no
way to test it without a cluster (SURVEY §4). Here the jax.distributed
multi-controller path — ClusterConfig bootstrap, cross-process all_gather,
GAR agreement — is exercised for real by spawning two OS processes that
form one 8-device global mesh (4 virtual CPU devices per "host") and must
print bit-identical Multi-Krum aggregates under a lie attack.
"""

import os
import socket
import subprocess
import sys


from garfield_tpu.utils import multihost

_CHILD = os.path.join(os.path.dirname(__file__), "multihost_child.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_cluster_agreement(tmp_path):
    port = _free_port()
    hosts = [f"127.0.0.1:{port}", f"127.0.0.1:{port + 1}"]
    procs = []
    env = {
        k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"
    }
    env["JAX_PLATFORMS"] = "cpu"
    # CPU-only children: PYTHONPATH is safe here (it breaks only the axon
    # TPU plugin registration — see .claude/skills/verify gotchas).
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(_CHILD))
    for i, _ in enumerate(hosts):
        cfg_path = tmp_path / f"task_{i}.json"
        multihost.generate_config(
            cfg_path, workers=hosts, task_type="worker", task_index=i,
            gar="krum", fw=2,
        )
        procs.append(subprocess.Popen(
            [sys.executable, _CHILD, str(cfg_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(os.path.dirname(_CHILD)),
        ))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=280)
            assert p.returncode == 0, f"child failed:\n{out[-3000:]}"
            agg_lines = [l for l in out.splitlines() if l.startswith("AGG ")]
            assert agg_lines, f"no AGG line:\n{out[-2000:]}"
            outs.append(agg_lines[-1].split()[2:])
    finally:
        for p in procs:  # never leak a blocked jax.distributed child
            if p.poll() is None:
                p.kill()
                p.wait()
    # Both hosts computed the identical replicated aggregate.
    assert outs[0] == outs[1], outs

"""Shard failure detection + checkpointed span handoff.

The Garfield paper tolerates f Byzantine OR CRASHED parameter servers
by replicating the full model (PAPER.md's f_ps axis); the sharded
federated plane (DESIGN.md §19) has no replicas — a shard owns its
span exclusively, so before this module a mid-round shard death cost
the whole run. With the per-span checkpoints already on disk
(federated/sharding.save_sharded, written every round by
``FedRoundEngine.save_checkpoint``), it should cost exactly ONE round,
and this module is the machinery:

- ``HeartbeatMonitor`` — failure detection: per-target probes (a TCP
  connect by default, ``tcp_probe``) on a fixed cadence with bounded
  in-probe retries and exponential backoff, so one dropped SYN is not
  a failover but ``retries`` consecutive losses are. Declaring death
  after R missed probes at interval T bounds detection latency at
  ~R*T + backoff — the knobs ride ``GARFIELD_HEARTBEAT_MS``.
- ``promote_standby`` — the handoff: replace a dead shard's server
  with a standby restored from the span's checkpoint. The standby gets
  the span's model bytes (bitwise — ``sharding.restore_span``), the
  round number it may serve (``ShardServer.mark_restored``: serving
  any other round is a loud refusal, the satellite-1 contract), the
  membership epoch BUMPED by one (stale-epoch frames from anyone still
  talking to the dead membership are attributable wire rejects), and
  the checkpointed per-client suspicion absorbed max-merge into the
  hub — an epoch-timed attacker cannot launder its exclusion history
  by crashing the shard that remembered it (DESIGN.md §22).

What is deliberately NOT restored: the wire ``ErrorFeedback``
residual. Zero-rebuild on restart is the recorded PR 14 decision
(utils/wire.ErrorFeedback docstring): the residual is a bounded
one-step correction, so dropping it costs one step of compensation —
cheaper and simpler than checkpointing a per-sender dict every round,
and pinned here (``EF_RESIDUAL_RESTORED = False`` + the controlplane
test) so a future round changes it explicitly or not at all.

The interrupted round is RE-RUN, not resumed: mid-round reducer state
(wave buffers, partial folds) is deliberately never checkpointed —
its arrival-order dependence would make a resumed fold bitwise
unverifiable. Re-running from the round-(R-1) checkpoint keeps the
S=1 bitwise anchor intact across the failure path (the fed test
suite pins a killed-and-handed-off round's aggregate bitwise equal to
an undisturbed run), which is the whole auditability point.
"""

import os
import socket
import time

from ..federated import sharding
from ..telemetry import hub as tele_hub

__all__ = [
    "EF_RESIDUAL_RESTORED",
    "heartbeat_interval_s",
    "standby_shards",
    "tcp_probe",
    "HeartbeatMonitor",
    "promote_standby",
]

# The PR 14 restart decision, pinned as data (see module docstring):
# wire ErrorFeedback residuals are rebuilt at zero on any restart or
# handoff — a handoff must NOT try to restore them.
EF_RESIDUAL_RESTORED = False

_DEFAULT_HEARTBEAT_MS = 100


def heartbeat_interval_s():
    """The probe cadence in seconds (``GARFIELD_HEARTBEAT_MS``, default
    100 ms — an order above a LAN RTT, an order under a round)."""
    v = os.environ.get("GARFIELD_HEARTBEAT_MS", "").strip()
    if not v:
        return _DEFAULT_HEARTBEAT_MS / 1000.0
    try:
        ms = float(v)
    except ValueError:
        raise ValueError(
            f"GARFIELD_HEARTBEAT_MS must be a number of milliseconds, "
            f"got {v!r}"
        )
    if ms <= 0:
        raise ValueError(
            f"GARFIELD_HEARTBEAT_MS must be > 0, got {ms}"
        )
    return ms / 1000.0


def standby_shards():
    """How many standby shard servers a deployment keeps warm
    (``GARFIELD_STANDBY_SHARDS``, default 1). Zero disables failover —
    a shard death is then terminal, the pre-control-plane behavior."""
    v = os.environ.get("GARFIELD_STANDBY_SHARDS", "1").strip()
    try:
        n = int(v)
    except ValueError:
        raise ValueError(
            f"GARFIELD_STANDBY_SHARDS must be a non-negative integer, "
            f"got {v!r}"
        )
    if n < 0:
        raise ValueError(
            f"GARFIELD_STANDBY_SHARDS must be >= 0, got {n}"
        )
    return n


def tcp_probe(host, port, timeout_s=0.25):
    """One liveness probe on the TCP plane: can the target's exchange
    listener accept a connection within ``timeout_s``? The connection
    is closed immediately — ``PeerExchange``'s accept loop tolerates
    a no-payload connection (reader sees EOF before a transport
    header), so probing is free for the probed."""
    try:
        with socket.create_connection((host, int(port)),
                                      timeout=float(timeout_s)):
            return True
    except OSError:
        return False


class HeartbeatMonitor:
    """Cadenced failure detection over a set of probe targets.

    ``targets`` maps a key (shard id, rank...) to whatever the
    ``probe`` callable takes — ``(host, port)`` for the default
    ``tcp_probe``. A target is DOWN after ``retries`` consecutive
    failed probes; within one ``poll`` the probe is retried up to
    ``retries`` times with exponential backoff (``backoff_s * 2**i``)
    before the miss is counted, so a single dropped SYN costs
    milliseconds, not a failover. ``poll()`` is synchronous and
    deterministic (tests drive it round-by-round); a deployment loop
    calls it once per ``interval_s`` (``run_once`` sleeps the
    remainder). ``on_down`` fires exactly once per death — a target
    revived via ``revive`` re-arms it.
    """

    def __init__(self, targets, *, probe=None, interval_s=None,
                 retries=3, backoff_s=0.01, on_down=None):
        self.targets = dict(targets)
        self.probe = tcp_probe if probe is None else probe
        self.interval_s = (
            heartbeat_interval_s() if interval_s is None
            else float(interval_s)
        )
        self.retries = int(retries)
        if self.retries < 1:
            raise ValueError(f"retries must be >= 1, got {retries}")
        self.backoff_s = float(backoff_s)
        self.on_down = on_down
        self.misses = {k: 0 for k in self.targets}
        self.down = set()
        self.probes = 0

    def _probe_with_retry(self, target):
        for i in range(self.retries):
            self.probes += 1
            try:
                if self.probe(*target) if isinstance(target, tuple) \
                        else self.probe(target):
                    return True
            except Exception:
                pass  # a raising probe is a failed probe, not a crash
            if i + 1 < self.retries and self.backoff_s > 0:
                time.sleep(self.backoff_s * (2 ** i))
        return False

    def poll(self):
        """One probe sweep; returns the keys newly declared down."""
        died = []
        for key, target in self.targets.items():
            if key in self.down:
                continue
            if self._probe_with_retry(target):
                self.misses[key] = 0
                continue
            self.misses[key] += 1
            if self.misses[key] >= 1:  # retried inside _probe_with_retry
                self.down.add(key)
                died.append(key)
                if self.on_down is not None:
                    self.on_down(key)
        return died

    def revive(self, key, target=None):
        """Re-arm a key after its standby took over (or the target was
        restarted) — the monitor watches the NEW incarnation."""
        if target is not None:
            self.targets[key] = target
        self.down.discard(key)
        self.misses[key] = 0

    def run_once(self):
        """One cadence tick: poll, then sleep out the interval."""
        t0 = time.perf_counter()
        died = self.poll()
        rest = self.interval_s - (time.perf_counter() - t0)
        if rest > 0 and not died:
            time.sleep(rest)
        return died


def promote_standby(engine, shard, *, step=None):
    """Hand a dead shard's span to a standby, mid-round.

    Restores span ``shard`` of ``engine`` from the newest complete
    checkpoint (or ``step``): a fresh ``ShardServer`` over the same
    span, the span's model bytes restored bitwise from disk
    (``sharding.restore_span`` — the engine's in-memory span may have
    been half-updated by the round in flight), the control record's
    suspicion absorbed max-merge, the membership epoch bumped (action
    ``failover``), and the standby pinned to the one round it may
    serve (``mark_restored`` — the interrupted round, which the caller
    re-runs). Returns ``(server, round_to_rerun)``.

    ErrorFeedback residuals are NOT restored — see the module
    docstring and ``EF_RESIDUAL_RESTORED``.
    """
    if engine._ckpt_dir is None:
        raise RuntimeError(
            "cannot promote a standby: the engine has no checkpoint_dir "
            "(per-span checkpoints are the handoff substrate)"
        )
    s = sharding.shard_plane(shard, engine.spec.num_shards)
    complete = set(sharding.sharded_steps(engine._ckpt_dir, engine.spec))
    complete &= set(engine.control_steps())
    if step is None:
        if not complete:
            raise FileNotFoundError(
                f"no complete checkpoint under {engine._ckpt_dir} to "
                f"hand shard {s} off from"
            )
        step = max(complete)
    elif int(step) not in complete:
        raise FileNotFoundError(
            f"round {step} has no complete checkpoint under "
            f"{engine._ckpt_dir}"
        )
    span_model = sharding.restore_span(
        engine._ckpt_dir, engine.spec, s, int(step)
    )
    ctl = engine.load_control(int(step))
    rerun = int(ctl["round"]) + 1
    lo, hi = engine.spec.spans[s]
    engine.model[lo:hi] = span_model  # bitwise: a pure span copy
    hub = tele_hub.current()
    if hub is not None and ctl.get("suspicion"):
        hub.absorb_client_suspicion({
            int(cid): (float(o), float(e))
            for cid, (o, e) in ctl["suspicion"].items()
        })
    engine.bump_epoch("failover", shard=s)
    server = engine.build_shard(s)
    server.mark_restored(rerun)
    engine.shards[s] = server
    return server, rerun

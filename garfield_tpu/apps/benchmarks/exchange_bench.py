"""Host-plane publish/collect round benchmark + cluster-mode steps/s.

The committed record for the ``apps/cluster.py`` path (VERDICT r5 item 4:
no step-time number existed for the host plane at all). Two modes:

**Micro** (default): for each (n, d, wire) cell, n localhost OS processes
— rank 0 in this process, ranks 1..n-1 spawned — run ``--rounds``
rank-0-paced publish/collect round trips per trial over a REAL
``PeerExchange`` (TCP frames + the native MRMW register), every frame
through the typed wire codec (``utils/wire.py``) with eager decode in the
collect waiter threads (the shipped cluster path; see ``_rank0_rounds``
for why the pacing is what makes the rounds loss-free on the
last-writer-wins register). Rank 0 records the median round latency per
trial and commits the MIN over ``--trials`` (gar_bench's min-over-k:
co-tenant noise only adds time). ``wire_bytes_per_step`` is the per-node
DCN fan-out: (n-1) frames of ``wire.frame_nbytes(d, w)`` — the number the
bf16 codec halves.

**--e2e**: additionally runs the SSMW cluster deployment end-to-end
(1 PS + ``--e2e_workers`` worker subprocesses, mnist/convnet,
JAX_PLATFORMS=cpu) once per wire dtype with ``--telemetry``, and derives
steps/s from the PS's per-step ``step_time_s`` records (median over the
post-warmup steps — the BASELINE.md cluster-mode row) plus wire
bytes/step from the summary's wire totals.

**--scenario** (round 11, DESIGN.md §14): the async-plane scenario
harness. ``straggler`` injects a delayed rank (``--straggler_ms``, or
10x the measured fault-free round when omitted — the EXCHBENCH_r02
acceptance shape) and measures the SYNC exact-round rate against the
bounded-staleness rate at matched (n, d): sync waits on the straggler
every round; async reuses its admissible stale frame
(``PeerExchange.round_collector``) and paces on the fast ranks, bounded
by ``--max_staleness``. ``churn`` kills the victim mid-run and relaunches
it (leave/join: the quorum q = n-2 flows around the gap; the rejoined
rank's fresh frames re-enter — re-admit is just re-appearing in the
admissible set). ``partition`` SIGSTOPs the victim for the middle third
and SIGCONTs it. Every scenario drives a MetricsHub: per-round
``staleness`` telemetry events fold the discount deficit into per-rank
SUSPICION, and each row records the victim ranking top. Every row (micro
cells included) carries ``peak_rss_bytes`` like HIERBENCH.

  python -m garfield_tpu.apps.benchmarks.exchange_bench \\
      --ns 4 --ds 100000 --wire f32 \\
      --scenario straggler churn partition --json EXCHBENCH_r02.json
"""

import argparse
import json
import os
import signal
import socket
import statistics
import subprocess
import sys
import time

import numpy as np

from ...utils import rounds as rounds_lib, wire
from ...utils.exchange import PeerExchange

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)))

# Follow-mode stop sentinel: a round tag no real round reaches.
_STOP_ROUND = 2 ** 40


def peak_rss_bytes():
    """High-water RSS of this process (bytes) — per-row accounting like
    HIERBENCH (gar_bench.peak_rss_bytes)."""
    import resource

    return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024


def _ports(k):
    socks = [socket.socket() for _ in range(k)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _decode_tf(idx, payload):
    return wire.decode(payload)


def _barrier(ex, n):
    """Startup barrier: everyone publishes a hello at step 0 and waits
    for every peer's — the micro rounds must time the exchange, not
    subprocess startup skew."""
    ex.publish(0, b"up")
    for r in range(n):
        if r != ex.my_index:
            ex.read_latest(r, 0, timeout_ms=120_000)


def _rank0_rounds(ex, n, d, wire_dtype, rounds, trials):
    """Rank 0 PACES the mesh, SSMW-style: publish the round's frame to
    every peer, collect every peer's typed response (eager decode in the
    waiter threads — the shipped cluster path). The pacing is the
    loss-freedom proof on the last-writer-wins register: a peer publishes
    round s only after reading rank 0's s, and rank 0 publishes s+1 only
    after collecting EVERY peer's s — so no round frame can be
    overwritten before its reader latched it. (A free-running symmetric
    protocol drops rounds here: two back-to-back writes from a fast peer
    land before the blocked reader is scheduled, and the register keeps
    only the newer — the exact race apps/cluster's role pacing closes.)
    Round latency = encode + fan-out + per-peer read/decode/re-encode/
    respond + collect + eager decode: two wire hops, the PS step's wire
    component. Returns the min-over-trials of the per-trial median."""
    rng = np.random.default_rng(1234)
    vec = rng.standard_normal(d).astype(np.float32)
    _barrier(ex, n)
    step = 1
    per_trial = []
    for _ in range(max(1, trials)):
        lats = []
        for _ in range(rounds):
            wait = ex.collect_begin(step, n, timeout_ms=120_000,
                                    transform=_decode_tf)
            t0 = time.perf_counter()
            ex.publish(step, wire.encode(vec, wire_dtype))
            got = wait()
            lats.append(time.perf_counter() - t0)
            assert len(got) == n and not any(
                isinstance(v, Exception) for v in got.values()
            )
            step += 1
        per_trial.append(statistics.median(lats))
    return min(per_trial) if per_trial else None


def _child_main(args):
    hosts = args.hosts.split(",")
    n = len(hosts)
    ex = PeerExchange(args.child, hosts, connect_retry_ms=120_000)
    rng = np.random.default_rng(1234 + args.child)
    vec = rng.standard_normal(args.d).astype(np.float32)
    try:
        if args.child_mode == "follow":
            return _child_follow(ex, args, vec)
        _barrier(ex, n)
        for step in range(1, 1 + args.rounds * max(1, args.trials)):
            got = ex.collect(step, 1, peers=[0], timeout_ms=120_000,
                             transform=_decode_tf)
            assert not isinstance(got[0], Exception)
            ex.publish(step, wire.encode(vec, args.child_wire), to=[0])
    finally:
        ex.close()


def _child_follow(ex, args, vec):
    """Scenario-mode child: respond to rank 0's NEWEST round (read_latest
    catch-up — a delayed child skips rounds exactly like a real straggling
    worker) with an optional injected delay before each publish. The
    rendezvous is with rank 0 only (not all-to-all): churn relaunches a
    child mid-run, and a full barrier would hang it on hellos the other
    children published before it existed."""
    ex.publish(0, b"up", to=[0])
    delay_s = max(0, args.child_delay_ms or 0) / 1e3
    last = 0
    while True:
        try:
            step, _ = ex.read_latest(0, last + 1, timeout_ms=180_000)
        except TimeoutError:
            return  # pacer gone (scenario harness was killed)
        if step >= _STOP_ROUND:
            return
        if delay_s:
            time.sleep(delay_s)  # the injected straggler
        ex.publish(step, wire.encode(vec, args.child_wire), to=[0])
        last = step


def _spawn_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        _REPO + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH") else _REPO
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep subprocesses off the TPU
    env["JAX_PLATFORMS"] = "cpu"
    return env


def bench_cell(n, d, wire_dtype, rounds, trials):
    """One micro cell: spawn ranks 1..n-1, run rank 0 here."""
    hosts = [f"127.0.0.1:{p}" for p in _ports(n)]
    env = _spawn_env()
    procs = [
        subprocess.Popen(
            [sys.executable, "-m",
             "garfield_tpu.apps.benchmarks.exchange_bench",
             "--child", str(k), "--hosts", ",".join(hosts),
             "--d", str(d), "--rounds", str(rounds),
             "--trials", str(trials), "--child_wire", wire_dtype],
            env=env,
        )
        for k in range(1, n)
    ]
    ex = PeerExchange(0, hosts, connect_retry_ms=120_000)
    try:
        round_s = _rank0_rounds(ex, n, d, wire_dtype, rounds, trials)
    finally:
        ex.close()
        for p in procs:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
    return {
        "mode": "micro", "n": n, "d": d, "wire": wire_dtype,
        "round_s": round_s,
        "wire_bytes_per_step": (n - 1) * wire.frame_nbytes(d, wire_dtype),
        "rounds": rounds, "trials": trials,
        "peak_rss_bytes": peak_rss_bytes(),
    }


def bench_e2e(wire_dtype, n_w, iters, tmpdir):
    """End-to-end SSMW cluster run (1 PS + n_w worker subprocesses) at
    ``wire_dtype``; steps/s from the PS's telemetry step records (median
    ``step_time_s`` over the post-warmup steps — compile-free, unlike
    wall_s / steps), wire bytes/step from the summary totals."""
    from ...utils import multihost

    pp = _ports(1 + n_w)
    cfg_path = os.path.join(tmpdir, f"cluster_{wire_dtype}.json")
    multihost.generate_config(
        cfg_path,
        ps=[f"127.0.0.1:{pp[0]}"],
        workers=[f"127.0.0.1:{p}" for p in pp[1:]],
        task_type="ps", task_index=0,
    )
    env = _spawn_env()
    env["GARFIELD_WIRE_DTYPE"] = wire_dtype
    env["GARFIELD_SURROGATE_MARGIN"] = "30"
    env["GARFIELD_SURROGATE_LABEL_NOISE"] = "0"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    tele_dir = os.path.join(tmpdir, f"tele_{wire_dtype}")

    def launch(role):
        return subprocess.Popen(
            [sys.executable, "-m", "garfield_tpu.apps.aggregathor",
             "--cluster", cfg_path, "--task", role,
             "--dataset", "mnist", "--model", "convnet", "--batch", "16",
             "--fw", "1", "--gar", "median", "--num_iter", str(iters),
             "--acc_freq", "0", "--train_size", "512",
             "--cluster_timeout_ms", "120000", "--telemetry", tele_dir],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )

    ps = launch("ps:0")
    workers = [launch(f"worker:{w}") for w in range(n_w)]
    try:
        out, _ = ps.communicate(timeout=600 + 10 * iters)
        if ps.returncode != 0:
            raise RuntimeError(f"e2e PS failed:\n{out[-2000:]}")
        summary = json.loads(
            [l for l in out.splitlines() if l.startswith("{")][-1]
        )
        for w in workers:
            w.communicate(timeout=120)
    finally:
        for p in [ps, *workers]:
            if p.poll() is None:
                p.kill()
    step_times, wire_totals = [], None
    with open(os.path.join(tele_dir, "cluster-ps.telemetry.jsonl")) as fp:
        for line in fp:
            rec = json.loads(line)
            if rec["kind"] == "step" and rec.get("step_time_s") is not None:
                step_times.append((rec["step"], rec["step_time_s"]))
            elif rec["kind"] == "summary":
                wire_totals = rec.get("wire")
    # Warmup excluded: the first steps pay grad/update compiles and the
    # exchange's cold-start connect grace.
    warm = [t for s, t in step_times if s >= 5]
    med = statistics.median(warm) if warm else None
    steps = summary["steps"]
    return {
        "mode": "cluster_e2e", "wire": wire_dtype, "workers": n_w,
        "iters": iters, "steps": steps,
        "wall_s": round(summary["wall_s"], 3),
        "step_s_median": None if med is None else round(med, 6),
        "steps_per_s": None if not med else round(1.0 / med, 3),
        "wire_bytes_per_step": (
            None if not (wire_totals and steps) else
            int((wire_totals["bytes_out"] + wire_totals["bytes_in"])
                / steps)
        ),
    }


def _spawn_follow(k, hosts, d, wire_dtype, delay_ms=0):
    return subprocess.Popen(
        [sys.executable, "-m",
         "garfield_tpu.apps.benchmarks.exchange_bench",
         "--child", str(k), "--hosts", ",".join(hosts),
         "--d", str(d), "--child_wire", wire_dtype,
         "--child_mode", "follow", "--child_delay_ms", str(delay_ms)],
        env=_spawn_env(),
    )


def _sync_follow_rounds(ex, peers, frame, n_rounds, step):
    """Exact-round pacing over follow children: publish round ``step``,
    wait for EVERY peer's response to that exact round — the synchronous
    wait-everyone contract whose pace a single straggler sets. Returns
    (median round_s, next step)."""
    lats = []
    for _ in range(n_rounds):
        wait = ex.collect_begin(
            step, len(peers), peers=peers, timeout_ms=180_000,
            transform=_decode_tf,
        )
        t0 = time.perf_counter()
        ex.publish(step, frame)
        got = wait()
        lats.append(time.perf_counter() - t0)
        assert not any(isinstance(v, Exception) for v in got.values())
        step += 1
    return statistics.median(lats), step


def _async_follow_rounds(ex, collector, q, frame, n_rounds, step, policy,
                         on_round=None, q_min=None, soft_timeout_ms=None):
    """Bounded-staleness pacing: publish, gather the admissible set
    (stale reuse + freshness floor — PeerExchange.round_collector), emit
    the per-round ``staleness`` telemetry event exactly like the cluster
    PS, so the scenario's MetricsHub derives suspicion from the discount
    deficits. ``q_min`` < ``q`` enables the liveness degrade the cluster
    plane applies: a quorum that cannot fill ``q`` inside
    ``soft_timeout_ms`` (a rank's frames expired past the cutoff — churn
    leave, partition) retries at ``q_min`` and flows around the outage;
    the excluded rank re-enters the admissible set the moment it
    publishes again (re-admission is just reappearance). Returns (median
    round_s, next step, max staleness seen, per-rank presence counts)."""
    from ...telemetry import hub as tele_hub_lib

    lats, tau_max = [], 0
    present = {}
    degraded = False  # sticky: pay the soft timeout once per outage
    for r in range(n_rounds):
        if on_round is not None:
            on_round(r)
        t0 = time.perf_counter()
        ex.publish(step, frame)
        if degraded:
            # gather returns ALL admissible frames: the moment the
            # excluded rank publishes again the count recovers past q
            # and the full quorum is restored (re-admission).
            got = collector.gather(
                step, q_min, max_staleness=policy.max_staleness,
                timeout_ms=180_000,
            )
            if len(got) >= q:
                degraded = False
        else:
            try:
                got = collector.gather(
                    step, q, max_staleness=policy.max_staleness,
                    timeout_ms=(
                        180_000 if q_min is None else soft_timeout_ms
                    ),
                )
            except TimeoutError:
                if q_min is None:
                    raise
                got = collector.gather(
                    step, q_min, max_staleness=policy.max_staleness,
                    timeout_ms=180_000,
                )
                degraded = True
        quorum = sorted(got, key=lambda k: (step - got[k][0], k))[:q]
        taus = [max(0, step - got[k][0]) for k in quorum]
        w = policy.weights(np.asarray(taus))
        lats.append(time.perf_counter() - t0)
        tau_max = max(tau_max, max(taus))
        for k in quorum:
            present[k] = present.get(k, 0) + 1
        tele_hub_lib.emit_event(
            "staleness", who="exchange-bench", step=int(step),
            ranks=[int(k) for k in quorum],
            staleness=[int(t) for t in taus],
            weights=[round(float(x), 6) for x in w],
            reused=int(sum(t > 0 for t in taus)),
        )
        step += 1
    return statistics.median(lats), step, tau_max, present


def bench_scenario(scenario, n, d, wire_dtype, rounds, trials,
                   straggler_ms, max_staleness, decay):
    """One async-plane scenario cell (docstring up top): returns the
    committed row. ``straggler`` A/Bs sync vs bounded-staleness round
    rate under an injected delay (auto: 10x the fault-free round);
    ``churn`` kills + relaunches the victim; ``partition`` SIGSTOPs it
    for the middle third. All drive suspicion through real telemetry."""
    from ...telemetry import hub as tele_hub_lib

    policy = rounds_lib.StalenessPolicy(max_staleness, decay)
    victim = n - 1
    rng = np.random.default_rng(1234)
    frame = wire.encode(
        rng.standard_normal(d).astype(np.float32), wire_dtype
    )

    def open_mesh(delay_ms=0):
        hosts = [f"127.0.0.1:{p}" for p in _ports(n)]
        procs = {
            k: _spawn_follow(
                k, hosts, d, wire_dtype,
                delay_ms if k == victim else 0,
            )
            for k in range(1, n)
        }
        ex = PeerExchange(0, hosts, connect_retry_ms=120_000)
        for r in range(1, n):  # follow children hello rank 0 only
            ex.read_latest(r, 0, timeout_ms=120_000)
        return hosts, procs, ex

    def close_mesh(procs, ex):
        try:
            ex.publish(_STOP_ROUND, b"", to=list(procs))
        except OSError:
            pass
        ex.close()
        for p in procs.values():
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGCONT)  # un-freeze partitions
                except OSError:
                    pass
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()

    # Fault-free baseline round (sync, no delay) — the '10x' anchor.
    hosts, procs, ex = open_mesh()
    try:
        baseline_s, step = _sync_follow_rounds(
            ex, list(range(1, n)), frame, max(5, rounds // 4), 1
        )
    finally:
        close_mesh(procs, ex)
    if not straggler_ms:
        straggler_ms = max(20, int(baseline_s * 1e4))  # 10x, >= 20 ms

    hub = tele_hub_lib.MetricsHub(num_ranks=n, meta={
        "tag": "exchange-bench-scenario", "scenario": scenario,
    })
    tele_hub_lib.install(hub)
    # Round tracing (schema v5): the scenario rows record per-phase
    # p50/p95 from the exchange spans (publish/collect/gather/decode) so
    # the committed artifact ATTRIBUTES its speedups — e.g. the async
    # win shows up as the gather phase shrinking while publish stays
    # flat — instead of just reporting them.
    from ...telemetry import trace as trace_lib

    trace_lib.enable(who=f"exchange-bench-{scenario}")
    sync_best = async_best = None
    tau_max = 0
    presence = {}
    try:
        if scenario == "straggler":
            hosts, procs, ex = open_mesh(delay_ms=straggler_ms)
            collector = ex.round_collector(
                list(range(1, n)), transform=_decode_tf
            )
            try:
                step = 1
                for _ in range(max(1, trials)):
                    # Few sync rounds: each costs ~straggler_ms by
                    # construction; the async segment then runs at the
                    # fast ranks' pace with the victim's frame reused.
                    sync_s, step = _sync_follow_rounds(
                        ex, list(range(1, n)), frame,
                        max(3, rounds // 6), step,
                    )
                    async_s, step, tmax, pres = _async_follow_rounds(
                        ex, collector, n - 1, frame, rounds, step, policy,
                    )
                    sync_best = min(sync_best or sync_s, sync_s)
                    async_best = min(async_best or async_s, async_s)
                    tau_max = max(tau_max, tmax)
                    for k, v in pres.items():
                        presence[k] = presence.get(k, 0) + v
            finally:
                collector.close()
                close_mesh(procs, ex)
        else:
            # churn / partition: async only, full q = n - 1 with the
            # degrade-to-q-2 fallback — the victim stays IN the quorum
            # while merely stale (its discount deficit feeds suspicion),
            # drops out when its frames expire past the cutoff, and
            # re-enters when it publishes again.
            hosts, procs, ex = open_mesh(delay_ms=0)
            collector = ex.round_collector(
                list(range(1, n)), transform=_decode_tf
            )

            # Pace the rounds at >= 20 ms so the fault windows span real
            # time: the victim's staleness must actually climb past the
            # cutoff (exclusion) and recover (re-admission) — at the raw
            # sub-ms gather pace the whole outage would fit in one frame.
            pace_s = max(0.02, baseline_s)

            def on_round(r):
                time.sleep(pace_s)
                if scenario == "churn":
                    if r == rounds // 3:
                        procs[victim].kill()
                        procs[victim].wait(timeout=30)
                    elif r == 2 * rounds // 3:
                        # JOIN: a fresh process on the same rank/port
                        # (re-admit = re-appearing in the admissible set;
                        # in the cluster driver the rejoined worker also
                        # re-reads its shard — re-admit becomes re-shard).
                        procs[victim] = _spawn_follow(
                            victim, hosts, d, wire_dtype
                        )
                elif scenario == "partition":
                    if r == rounds // 3:
                        procs[victim].send_signal(signal.SIGSTOP)
                    elif r == 2 * rounds // 3:
                        procs[victim].send_signal(signal.SIGCONT)

            try:
                async_best, step, tau_max, presence = _async_follow_rounds(
                    ex, collector, n - 1, frame, rounds, 1, policy,
                    on_round=on_round, q_min=n - 2,
                    soft_timeout_ms=int(
                        max(2_000, policy.max_staleness * pace_s * 1e3)
                    ),
                )
            finally:
                collector.close()
                close_mesh(procs, ex)
    finally:
        trace_lib.disable()
        tele_hub_lib.uninstall()
    susp = hub.suspicion()
    stale = hub.staleness_stats()
    phase_stats = hub.phase_stats() or {}
    phases = {
        k: {"p50_s": round(v["p50_s"], 6), "p95_s": round(v["p95_s"], 6)}
        for k, v in phase_stats.items()
    }
    row = {
        "mode": "scenario", "scenario": scenario, "n": n, "d": d,
        "wire": wire_dtype, "rounds": rounds, "trials": trials,
        "baseline_round_s": round(baseline_s, 6),
        "straggler_ms": int(straggler_ms),
        "sync_round_s": None if sync_best is None else round(sync_best, 6),
        "async_round_s": (
            None if async_best is None else round(async_best, 6)
        ),
        "speedup": (
            None if not (sync_best and async_best)
            else round(sync_best / async_best, 3)
        ),
        "max_staleness": policy.max_staleness, "decay": policy.decay,
        "max_staleness_seen": int(tau_max),
        "victim_rank": victim,
        "victim_quorums": int(presence.get(victim, 0)),
        "suspicion": (
            None if susp is None
            else [round(float(s), 6) for s in susp]
        ),
        "staleness_mean": None if stale is None else round(stale["mean"], 4),
        "phases": phases or None,
        "peak_rss_bytes": peak_rss_bytes(),
    }
    return row


def bench_trace_ab(n, d, wire_dtype, rounds, trials, tmpdir):
    """Tracing overhead A/B (ISSUE 8 acceptance): the same micro cell
    with tracing OFF then ON (spans streamed through a real MetricsHub
    + JSONL sink — the shipped cost, not a no-op hub), committed as one
    row so the <= 5% overhead claim lives in the artifact. The span hot
    path here is the worst case per byte moved: one publish + one
    collect + n decode spans per ~ms-scale round."""
    from ...telemetry import exporters, hub as tele_hub_lib
    from ...telemetry import trace as trace_lib

    off_row = bench_cell(n, d, wire_dtype, rounds, trials)
    sink = exporters.JsonlExporter(
        os.path.join(tmpdir, f"trace_ab_{n}_{d}_{wire_dtype}.jsonl")
    )
    hub = tele_hub_lib.MetricsHub(meta={"tag": "exchange-bench-trace-ab"})
    hub._sink = sink
    tele_hub_lib.install(hub)
    trace_lib.enable(who="exchange-bench")
    try:
        on_row = bench_cell(n, d, wire_dtype, rounds, trials)
    finally:
        trace_lib.disable()
        tele_hub_lib.uninstall()
        sink.close()
    phase_stats = hub.phase_stats() or {}
    off_s, on_s = off_row["round_s"], on_row["round_s"]
    return {
        "mode": "trace_ab", "n": n, "d": d, "wire": wire_dtype,
        "rounds": rounds, "trials": trials,
        "trace_off_round_s": off_s,
        "trace_on_round_s": on_s,
        "trace_overhead": (
            None if not (off_s and on_s) else round(on_s / off_s, 4)
        ),
        "spans": hub.counters()["spans"],
        "phases": {
            k: {"p50_s": round(v["p50_s"], 6),
                "p95_s": round(v["p95_s"], 6)}
            for k, v in phase_stats.items()
        } or None,
        "peak_rss_bytes": peak_rss_bytes(),
    }


def main(argv=None):
    p = argparse.ArgumentParser(
        description="host-plane exchange/wire-codec benchmark"
    )
    p.add_argument("--ns", nargs="*", type=int, default=[2, 4])
    p.add_argument("--ds", nargs="*", type=int,
                   default=[1_000, 100_000, 1_000_000])
    p.add_argument("--wire", nargs="*", default=list(wire.WIRE_DTYPES),
                   choices=wire.WIRE_DTYPES)
    p.add_argument("--rounds", type=int, default=20,
                   help="publish/collect rounds per trial")
    p.add_argument("--trials", type=int, default=3,
                   help="independent trials; the committed value is the "
                        "min of the per-trial medians (min-over-k)")
    p.add_argument("--e2e", action="store_true",
                   help="also run the SSMW cluster deployment end-to-end "
                        "per wire dtype (the BASELINE.md row)")
    p.add_argument("--e2e_workers", type=int, default=4)
    p.add_argument("--e2e_iters", type=int, default=40)
    p.add_argument("--scenario", nargs="*", default=None,
                   choices=["straggler", "churn", "partition"],
                   help="async-plane scenario harness cells (DESIGN.md "
                        "§14): per (n, d, wire) run the named scenarios "
                        "over follow-mode children — straggler A/Bs sync "
                        "vs bounded-staleness round rate, churn and "
                        "partition drive membership faults against "
                        "telemetry suspicion")
    p.add_argument("--trace_ab", action="store_true",
                   help="per (n, d, wire) also run the round-tracing "
                        "overhead A/B: the micro cell with spans off vs "
                        "on (real hub + JSONL sink), committed as a "
                        "trace_ab row — the ISSUE 8 <=5%% overhead "
                        "acceptance record")
    p.add_argument("--straggler_ms", type=int, default=0,
                   help="injected victim delay for --scenario straggler; "
                        "0 (default) auto-derives 10x the measured "
                        "fault-free round — the EXCHBENCH_r02 acceptance "
                        "shape")
    p.add_argument("--max_staleness", type=int, default=32,
                   help="bounded-staleness hard cutoff for the scenario "
                        "gathers (rounds)")
    p.add_argument("--decay", type=float, default=0.9,
                   help="per-round staleness discount for the scenario "
                        "gathers")
    p.add_argument("--json", type=str, default=None,
                   help="dump results (+ the schema-versioned telemetry "
                        "JSONL twin at the same path with a .jsonl "
                        "suffix)")
    # child-process plumbing (internal)
    p.add_argument("--child", type=int, default=None, help=argparse.SUPPRESS)
    p.add_argument("--hosts", type=str, default=None, help=argparse.SUPPRESS)
    p.add_argument("--d", type=int, default=None, help=argparse.SUPPRESS)
    p.add_argument("--child_wire", type=str, default="f32",
                   help=argparse.SUPPRESS)
    p.add_argument("--child_mode", type=str, default="paced",
                   choices=["paced", "follow"], help=argparse.SUPPRESS)
    p.add_argument("--child_delay_ms", type=int, default=0,
                   help=argparse.SUPPRESS)
    args = p.parse_args(argv)
    if args.child is not None:
        return _child_main(args)

    results = []
    for n in args.ns:
        for d in args.ds:
            for w in args.wire:
                row = bench_cell(n, d, w, args.rounds, args.trials)
                results.append(row)
                rs = row["round_s"]
                print(
                    f"n={n} d={d:<9} wire={w:<4} "
                    f"{'below noise floor' if rs is None else f'{rs * 1e3:9.3f} ms'}"
                    f"  {row['wire_bytes_per_step']:>12} B/step",
                    flush=True,
                )
    for scenario in args.scenario or ():
        for n in args.ns:
            for d in args.ds:
                for w in args.wire:
                    row = bench_scenario(
                        scenario, n, d, w, args.rounds, args.trials,
                        args.straggler_ms, args.max_staleness, args.decay,
                    )
                    results.append(row)
                    print(
                        f"scenario={scenario} n={n} d={d} wire={w} "
                        f"sync={row['sync_round_s']} "
                        f"async={row['async_round_s']} "
                        f"speedup={row['speedup']} "
                        f"tau_max={row['max_staleness_seen']} "
                        f"suspicion={row['suspicion']}",
                        flush=True,
                    )
    if args.trace_ab:
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            for n in args.ns:
                for d in args.ds:
                    for w in args.wire:
                        row = bench_trace_ab(
                            n, d, w, args.rounds, args.trials, td
                        )
                        results.append(row)
                        print(
                            f"trace_ab n={n} d={d} wire={w} "
                            f"off={row['trace_off_round_s']} "
                            f"on={row['trace_on_round_s']} "
                            f"overhead={row['trace_overhead']}x "
                            f"({row['spans']} spans)",
                            flush=True,
                        )
    if args.e2e:
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            for w in args.wire:
                row = bench_e2e(w, args.e2e_workers, args.e2e_iters, td)
                results.append(row)
                print(
                    f"e2e wire={w:<4} {row['steps_per_s']} steps/s "
                    f"({row['wire_bytes_per_step']} wire B/step)",
                    flush=True,
                )
    if args.json:
        with open(args.json, "w") as fp:
            json.dump(results, fp, indent=1)
        from ...telemetry import exporters

        jsonl_path = os.path.splitext(args.json)[0] + ".jsonl"
        with exporters.JsonlExporter(jsonl_path) as exp:
            for row in results:
                if row["mode"] == "micro":
                    exp.write(exporters.make_record(
                        "exchange_bench",
                        n=row["n"], d=row["d"], wire=row["wire"],
                        round_s=row["round_s"],
                        wire_bytes_per_step=row["wire_bytes_per_step"],
                        rounds=row["rounds"], trials=row["trials"],
                        peak_rss_bytes=row["peak_rss_bytes"],
                    ))
                elif row["mode"] == "scenario":
                    exp.write(exporters.make_record(
                        "exchange_bench",
                        n=row["n"], d=row["d"], wire=row["wire"],
                        scenario=row["scenario"],
                        straggler_ms=row["straggler_ms"],
                        sync_round_s=row["sync_round_s"],
                        async_round_s=row["async_round_s"],
                        speedup=row["speedup"],
                        max_staleness=row["max_staleness"],
                        max_staleness_seen=row["max_staleness_seen"],
                        victim_rank=row["victim_rank"],
                        suspicion=row["suspicion"],
                        phases=row["phases"],
                        rounds=row["rounds"], trials=row["trials"],
                        peak_rss_bytes=row["peak_rss_bytes"],
                    ))
                elif row["mode"] == "trace_ab":
                    exp.write(exporters.make_record(
                        "exchange_bench",
                        n=row["n"], d=row["d"], wire=row["wire"],
                        trace_off_round_s=row["trace_off_round_s"],
                        trace_on_round_s=row["trace_on_round_s"],
                        trace_overhead=row["trace_overhead"],
                        spans=row["spans"],
                        phases=row["phases"],
                        rounds=row["rounds"], trials=row["trials"],
                        peak_rss_bytes=row["peak_rss_bytes"],
                    ))
                else:
                    exp.write(exporters.make_record(
                        "bench",
                        metric=f"cluster_ssmw_steps_per_s_{row['wire']}",
                        value=row["steps_per_s"],
                        unit="steps/s",
                        wire_bytes_per_step=row["wire_bytes_per_step"],
                    ))
    return results


if __name__ == "__main__":
    main(sys.argv[1:])

"""Host-side distributed round tracing: lightweight spans (ISSUE 8).

The telemetry plane so far could COUNT a round (step times, event
totals) but not explain it: PRs 4-7 made a cluster round genuinely
concurrent — eager decode + H2D in exchange waiter threads,
pre-registered round watchers, async stale-frame reuse — and a scalar
``step_time_s`` cannot say where the wall clock went across those
PS/worker/waiter-thread boundaries. This module records *where*: each
instrumented phase of a round emits one **span** — wall-clock start,
monotonic duration, phase name, round/step tag, the owning role and a
per-thread track id — through the existing process-global MetricsHub
hook as a schema-v5 ``span`` JSONL record.

Contract (the taps' purity contract, host-side edition):

- **off by default, zero-cost when disabled**: ``span(...)`` checks one
  module-level flag and returns a shared no-op context manager — no
  clock reads, no allocation beyond the call itself. Nothing in-graph
  changes EVER (spans are host code only), so taps-on/off bitwise
  purity and the ``--chunk_steps`` trajectories are untouched; the
  tracing-on vs tracing-off trajectory pin in tests/test_trace.py
  asserts the host-side half.
- **crash-safe**: spans ride the hub's streaming JSONL sink (one
  flushed line per span), so a run that dies dark — the BENCH_r05
  post-mortem this plane exists for — keeps every span up to the
  crash.
- **thread-correct**: spans are emitted from exchange waiter threads
  (wire decode, H2D staging) concurrently with the role's main loop;
  the ``tid`` tag keeps them on separate tracks so the report's Chrome
  trace shows the collect/compute overlap instead of garbling it.

Enable with ``--trace`` on any app (implies ``--telemetry`` — spans
need the JSONL sink) or ``GARFIELD_TRACE=1``. Consume with
``python -m garfield_tpu.telemetry.report`` (cross-process merge,
causal timeline, critical-path attribution — see report.py).

Phase vocabulary (kept small and stable so the report can reason about
it; producers may add more):

  exchange:   publish, collect, decode, gather, latest_wait
  PS roles:   broadcast, quorum, gar_apply, bn_stats, model_gather
  worker:     model_wait, grad_compute, straggle
  LEARN node: grad_compute, quorum, update, gossip
  app loop:   dispatch (tag chunk=k), eval, checkpoint
  hierarchy:  hier_ingest, hier_wave, hier_h2d, hier_fold_wait,
              hier_finalize (hier_ingest is PRE-TIMED — one record per
              dispatched wave via ``emit``, accumulated from that
              wave's row copies/decodes, so per-wave counts align with
              hier_wave/hier_h2d exactly)
  federated:  fed_shard_fold, selection (ingest attribution rides the
              hierarchy's hier_ingest spans)
  soak:       soak_round (tag scenario=steady|rolling_restart|
              partition|churn — one span per sustained round; the
              SOAKBENCH SLO percentiles come from its phase stats)
"""

import itertools
import os
import threading
import time

from . import hub as _hub

__all__ = ["span", "emit", "enable", "disable", "enabled", "requested",
           "Span"]

# One mutable cell instead of rebindable module globals: ``span`` reads
# it on every call (the disabled fast path), and a cell read is as cheap
# as a global read while keeping enable/disable race-free under threads.
_STATE = {"enabled": False, "who": None}

# Small per-thread track ids for the report's Chrome-trace lanes: the
# main loop gets 0, waiter/watcher threads get 1, 2, ... in first-use
# order. OS thread ids are huge and unstable run-to-run; these are not.
_tid_counter = itertools.count(1)
_tids = threading.local()


def _tid():
    t = getattr(_tids, "id", None)
    if t is None:
        t = 0 if threading.current_thread() is threading.main_thread() \
            else next(_tid_counter)
        _tids.id = t
    return t


def requested(args=None):
    """Whether tracing was asked for: ``--trace`` or ``GARFIELD_TRACE``."""
    if args is not None and getattr(args, "trace", False):
        return True
    return os.environ.get("GARFIELD_TRACE", "").lower() not in (
        "", "0", "false",
    )


def enable(who=None):
    """Turn span recording on; ``who`` tags every span with the role
    (e.g. ``cluster-ps``, ``cluster-worker-2``) so the report can merge
    per-role streams without guessing from filenames."""
    _STATE["who"] = who
    _STATE["enabled"] = True


def disable():
    _STATE["enabled"] = False
    _STATE["who"] = None


def enabled():
    return _STATE["enabled"]


class _NullSpan:
    """The disabled path: a shared, reusable no-op context manager."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **tags):
        return self


_NULL = _NullSpan()


class Span:
    """One timed phase. Context-manager use only::

        with trace.span("quorum", step=i) as sp:
            got = collect(...)
            sp.set(arrived=len(got))

    The record is emitted at ``__exit__`` (through the process-global
    hub hook — a no-op if no hub is installed), stamped with the
    wall-clock START (``t_wall``, for cross-process alignment) and the
    monotonic DURATION (``dur_s``, immune to wall-clock steps). An
    exception inside the span still records it (tagged ``error``) and
    propagates — a phase that dies is exactly the one worth seeing.
    Nesting works: each span carries its own clocks; the report keeps
    outermost spans for attribution and all of them for the timeline.
    """

    __slots__ = ("phase", "tags", "_t_wall", "_t0")

    def __init__(self, phase, tags):
        self.phase = phase
        self.tags = tags

    def set(self, **tags):
        """Attach tags discovered mid-span (arrived counts, byte
        totals); later values win."""
        self.tags.update(tags)
        return self

    def __enter__(self):
        self._t_wall = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        tags = self.tags
        if exc_type is not None:
            tags = dict(tags, error=exc_type.__name__)
        who = _STATE["who"]
        if who is not None and "who" not in tags:
            tags = dict(tags, who=who)
        _hub.emit_span(
            self.phase, t_wall=self._t_wall, dur_s=dur, tid=_tid(), **tags
        )
        return False


def span(phase, **tags):
    """A span context manager for ``phase``, or the shared no-op when
    tracing is disabled (the zero-cost contract). ``step``/``round``
    tags are what the report keys rounds on — pass them whenever the
    phase belongs to one."""
    if not _STATE["enabled"]:
        return _NULL
    return Span(phase, tags)


def emit(phase, t_wall, dur_s, **tags):
    """Emit one PRE-TIMED span record (same shape as ``Span`` emits).

    For producers whose phase work is scattered across many small slices
    that only become one logical unit later — the hierarchy's per-wave
    ingest accounting (ISSUE 20) accumulates each row copy's duration
    and reports ONE ``hier_ingest`` span per dispatched wave, so span
    counts align 1:1 with the wave's ``hier_wave``/``hier_h2d`` records
    instead of undercounting attribution by whatever the ingest
    granularity happened to be. Callers time their own slices (and
    should skip the clock reads entirely when ``enabled()`` is False —
    the zero-cost contract is theirs to keep on this path)."""
    if not _STATE["enabled"]:
        return
    who = _STATE["who"]
    if who is not None and "who" not in tags:
        tags = dict(tags, who=who)
    _hub.emit_span(phase, t_wall=t_wall, dur_s=dur_s, tid=_tid(), **tags)

"""Load-driven autoscale controller (utils/autoscale.py, DESIGN.md §15).

Pure host-side unit coverage: the hysteresis + cooldown control law, the
mean-based (burst-proof) rate estimator, auto-calibration, the
quorum-margin scale-down gate, config validation, and the PS-argv ->
worker-argv command derivation. The multi-process e2e (a PS actually
spawning/retiring worker processes) lives in tests/test_async_cluster.py
(slow); the bench-harness form in exchange_bench --scenario
scaleup/scaledown.
"""

import sys

import pytest

from garfield_tpu.utils import autoscale


def _cfg(**kw):
    base = dict(target_rate=10.0, min_workers=2, max_workers=8,
                window=4, cooldown=2)
    base.update(kw)
    return autoscale.AutoscaleConfig(**base)


def _feed(ctl, round_s, k, active, margin=0):
    """Feed k identical rounds; return the list of non-zero actions."""
    actions = []
    for _ in range(k):
        a = ctl.observe(round_s, active=active, quorum_margin=margin)
        if a:
            actions.append(a)
    return actions


class TestConfig:
    def test_validation(self):
        with pytest.raises(ValueError):
            _cfg(min_workers=0)
        with pytest.raises(ValueError):
            _cfg(max_workers=1)  # < min_workers=2
        with pytest.raises(ValueError):
            _cfg(window=0)
        with pytest.raises(ValueError):
            _cfg(up_margin=1.2)
        with pytest.raises(ValueError):
            _cfg(down_margin=0.9)
        _cfg()  # valid baseline


class TestController:
    def test_no_decision_before_window_fills(self):
        ctl = autoscale.AutoscaleController(_cfg())
        assert _feed(ctl, 1.0, 3, active=4) == []  # window=4: 3 < 4
        assert ctl.rate() is None

    def test_rate_is_mean_not_median(self):
        # Bursty rounds: three ~instant harvests then one long stall.
        # Median would read ~1000/s; the throughput is 4 rounds / 1.003 s.
        # active at max so the low rate cannot trigger a spawn (which
        # would clear the window under measurement).
        ctl = autoscale.AutoscaleController(_cfg())
        for r in (0.001, 0.001, 0.001, 1.0):
            ctl.observe(r, active=8)
        assert ctl.rate() == pytest.approx(4 / 1.003, rel=1e-6)

    def test_scale_up_below_target(self):
        ctl = autoscale.AutoscaleController(_cfg())
        # rate = 5/s < 10 * 0.9 -> spawn exactly once the window fills.
        assert _feed(ctl, 0.2, 4, active=4) == [1]
        # The action cleared the window: the next decision waits for a
        # full window of the NEW membership (3 more rounds: nothing).
        assert ctl.rate() is None
        assert _feed(ctl, 0.2, 3, active=5) == []
        # 4th post-action round: window full again, cooldown (2) passed.
        assert _feed(ctl, 0.2, 1, active=5) == [1]

    def test_scale_up_capped_at_max(self):
        ctl = autoscale.AutoscaleController(_cfg())
        assert _feed(ctl, 0.2, 8, active=8) == []  # already at max

    def test_scale_down_above_target_with_clean_margin(self):
        ctl = autoscale.AutoscaleController(_cfg())
        # rate = 20/s > 10 * 1.3, margin clean -> retire.
        assert _feed(ctl, 0.05, 6, active=6, margin=1) == [-1]

    def test_scale_down_blocked_by_struggling_quorum(self):
        ctl = autoscale.AutoscaleController(_cfg())
        # Same rate, but one round in the window was SHORT an admissible
        # frame (negative margin): retiring into that is forbidden.
        for j in range(8):
            a = ctl.observe(
                0.05, active=6, quorum_margin=(-1 if j == 5 else 1)
            )
            assert a <= 0
            if j >= 5:
                assert a == 0

    def test_scale_down_floored_at_min(self):
        ctl = autoscale.AutoscaleController(_cfg())
        assert _feed(ctl, 0.05, 8, active=2, margin=1) == []

    def test_in_band_rate_holds(self):
        ctl = autoscale.AutoscaleController(_cfg())
        # 10/s is inside [0.9, 1.3] x target: no action, ever.
        assert _feed(ctl, 0.1, 20, active=4, margin=1) == []

    def test_auto_calibration_locks_first_window(self):
        ctl = autoscale.AutoscaleController(_cfg(target_rate=0.0))
        _feed(ctl, 0.04, 4, active=4)  # first full window: 25/s
        assert ctl.target == pytest.approx(25.0)
        # A later slowdown is measured AGAINST that service level: one
        # slow round drags the 4-round mean under 0.9 x 25 already.
        assert _feed(ctl, 0.2, 4, active=4) == [1]


class TestWorkerCommand:
    def test_rewrites_task_and_strips_ps_only_flags(self):
        argv = [
            "--cluster", "cfg.json", "--task", "ps:0", "--async",
            "--autoscale", "--target_rate", "12.5", "--autoscale_min",
            "2", "--autoscale_max=6", "--gar", "median", "--fw", "1",
        ]
        cmd = autoscale.worker_command(
            3, argv=argv, main_module="garfield_tpu.apps.aggregathor"
        )
        assert cmd[:3] == [
            sys.executable, "-m", "garfield_tpu.apps.aggregathor"
        ]
        rest = cmd[3:]
        assert rest[-2:] == ["--task", "worker:3"]
        assert "--autoscale" not in rest
        assert "--target_rate" not in rest
        assert "--autoscale_min" not in rest
        assert not any(a.startswith("--autoscale_max") for a in rest)
        assert "ps:0" not in rest
        # Deployment-shape flags the worker MUST share survive.
        for keep in ("--cluster", "cfg.json", "--async", "--gar",
                     "median", "--fw", "1"):
            assert keep in rest

    def test_requires_module_spec(self, monkeypatch):
        # A PS not launched via `python -m <app>` has no __main__ spec
        # to derive the worker command from — fail loudly, don't guess.
        monkeypatch.setattr(sys.modules["__main__"], "__spec__", None,
                            raising=False)
        with pytest.raises(RuntimeError, match="main_module"):
            autoscale.worker_command(0, argv=[])

    def test_main_dunder_suffix_stripped(self):
        cmd = autoscale.worker_command(
            1, argv=[], main_module="garfield_tpu.apps.learn"
        )
        assert cmd[2] == "garfield_tpu.apps.learn"


class TestRescind:
    """A refused action (capacity, wire caps, no standby) must be
    accounting-free: rescind() restores the measurement window, the
    cooldown clock and the action count — but only IMMEDIATELY after
    the advising observe, before the window moves on."""

    def test_rescind_restores_window_cooldown_and_count(self):
        ctl = autoscale.AutoscaleController(_cfg())
        # Slow rounds: the 4th observe fills the window and advises
        # a spawn (one more observe would expire the rescind snapshot).
        assert _feed(ctl, 1.0, 4, active=4) == [1]
        assert ctl.actions == 1 and ctl._since_action == 0
        assert ctl.rescind() is True
        assert ctl.actions == 0
        assert ctl.rate() is not None  # window NOT cleared by a refusal
        # The controller keeps advising on the unchanged membership:
        # the very next observe can act again (no consumed cooldown).
        assert ctl.observe(1.0, active=4, quorum_margin=0) == 1

    def test_rescind_without_action_is_noop(self):
        ctl = autoscale.AutoscaleController(_cfg())
        assert ctl.rescind() is False
        _feed(ctl, 1.0, 3, active=4)  # window not yet full: no action
        assert ctl.rescind() is False
        assert ctl.actions == 0

    def test_rescind_expires_after_any_later_observe(self):
        ctl = autoscale.AutoscaleController(_cfg())
        assert _feed(ctl, 1.0, 4, active=4) == [1]
        ctl.observe(1.0, active=5, quorum_margin=0)  # window moved on
        assert ctl.rescind() is False
        assert ctl.actions == 1  # the unrescinded action stands

"""Centered-clipping GAR (beyond-reference addition).

Karimireddy, He & Jaggi, "Learning from History for Byzantine Robust
Optimization" (ICML 2021): iteratively re-center on the clipped mean,

    v_{l+1} = v_l + (1/n) * sum_i  clip(x_i - v_l, tau_l),
    clip(z, tau) = z * min(1, tau / ||z||),

so every input's influence on the aggregate is bounded by ``tau_l / n``
regardless of its magnitude — the property selection rules (krum.py,
bulyan.py) lack, and the reason this rule (paired with worker momentum,
``worker_momentum=`` in the topology builders) survives the "little is
enough" attack that defeats Krum AND Bulyan on the round-3 TTA grid
(BASELINE.md). The reference library ships no clipping rule; this is the
standard modern baseline alongside its Krum/Median/Bulyan generation.

Defaults follow the paper's practical recipe: 3 fixed-point iterations;
``tau`` auto-scales to the median of the current radii ||x_i - v_l|| so
the rule is scale-free (no per-model tuning). ``center``: standalone
calls start at the coordinate-wise median (robust init); the AGGREGATHOR
topology threads the PREVIOUS step's aggregate through
``TrainState.gar_state`` as ``center`` — the paper's actual v_0 —
because the per-step median init costs a full coordinate-median pass
(~4 ms at ResNet-18 scale, the single largest piece of cclip's r4 22 ms
step; PERF.md r5). The first step runs from v_0 = 0, whose aggregate is
tau-bounded by construction. byzsgd/LEARN keep the per-step median init
(their per-PS/per-node state stacks would need one carried center per
slot; the cclip+momentum defense configs run on aggregathor/SSMW).

TPU form: the whole update is elementwise + row reductions — XLA fuses
each iteration into ~2 HBM passes over the (n, d) stack; no sort over d,
no gather. The tree-mode twin CONCATENATES the stacked tree once
(axis-1) and runs the flat iterations on it — a per-leaf formulation was
measured 7 ms/step slower (~600 small ops per aggregate; the Bulyan
concat-first layout lesson, PERF.md r5). ``fold_flat_aggregate`` gives
deterministic attacks a folded form (the remap applies to per-row
scalars of the iterations; parallel/fold.py).
"""

import math

import jax
import jax.numpy as jnp

from . import register
from ._common import (
    as_stack, coordinate_median, num_gradients, tree_coordinatewise,
)

ITERS = 3  # fixed-point iterations (paper §4: 1-3 suffice)


def _clip_step(stack, center, tau, eps):
    """One fixed-point iteration on the flat (n, d) stack."""
    dev = stack - center[None, :]
    # A NaN/Inf-poisoned row must not poison the aggregate (the same
    # resilience contract as krum/median's isfinite guards): its non-finite
    # entries become zero deviation, i.e. the row degenerates to a vote for
    # the current center — influence bounded like everyone else's.
    dev = jnp.nan_to_num(dev, nan=0.0, posinf=0.0, neginf=0.0)
    # Radii in f32: bf16 squared-norms overflow/underflow at d ~ 1e7.
    norms = jnp.sqrt(
        jnp.sum(jnp.square(dev.astype(jnp.float32)), axis=1)
    )
    tau_l = jnp.median(norms) if tau is None else jnp.asarray(
        tau, jnp.float32
    )
    scale = jnp.minimum(1.0, tau_l / jnp.maximum(norms, eps))
    return center + jnp.mean(
        dev * scale[:, None].astype(dev.dtype), axis=0
    )


def aggregate(gradients, f=0, key=None, center=None, tau=None,
              iters=ITERS, **kwargs):
    """Centered clipping around a robust center (see module docstring)."""
    stack = as_stack(gradients)
    eps = jnp.asarray(1e-12, jnp.float32)
    if center is None:
        # NaN-last lower median (jnp.median would propagate a poisoned
        # row's NaN into every coordinate of the init).
        center = coordinate_median(stack)
    # The center ALWAYS iterates at f32, however it arrived (median init,
    # carried TrainState.gar_state — f32 by construction — or a caller-
    # supplied v_0): _clip_step's subtraction must run at the SAME width
    # as the folded path's f32 deviations, or under a bf16 pipeline the
    # two paths round the tau median differently from the very first
    # step (ADVICE r5 #5; the fold-side twin cast lives in
    # fold_flat_aggregate).
    center = jnp.asarray(center).astype(jnp.float32)
    for _ in range(iters):
        center = _clip_step(stack, center, tau, eps)
    return center


def tree_aggregate(stacked_tree, f=0, key=None, center=None, tau=None,
                   iters=ITERS, **kwargs):
    """Tree-mode twin: CONCAT-FIRST (the Bulyan layout lesson, PERF.md r4).

    An earlier per-leaf formulation ran every iteration's subtract/normsq/
    update across all ~62 leaves (~600 small ops per aggregate) and made
    cclip the most expensive rule in the robustness matrix (22 ms/step vs
    krum's 12.6, VERDICT r4 #6). One axis-1 concat turns each iteration
    into two fused passes over a single (n, d) array — the exact flat-path
    math, so tree == flat by construction.
    """
    from ._common import concat_stack, unflatten_vec

    leaves, treedef = jax.tree.flatten(stacked_tree)
    stack, shapes = concat_stack(leaves)
    if center is not None:
        center = jnp.concatenate(
            [l.reshape(-1) for l in jax.tree.leaves(center)]
        )
    vec = aggregate(stack, f=f, key=key, center=center, tau=tau, iters=iters)
    return unflatten_vec(vec, treedef, shapes)


def fold_flat_aggregate(ext_stack, row_map, row_scale, f=0, key=None,
                        center=None, tau=None, iters=ITERS, **kwargs):
    """Folded-attack form: iterate on the EXTENDED raw stack (raw rows +
    the attack's shared fake row) under the static remap/scale — the
    poisoned (n, d) stack never materializes (parallel/fold.py).

    cclip consumes rows only through per-row scalars (radii) and one
    weighted row sum per iteration, both of which remap statically:

      radius_i    = || s_i * ext[m_i] - v ||     (s, m static)
      v          <- v * (1 - mean(c)) + (c * s / n) @ ext_rows

    Radii of unit-scale rows (honest + the shared lie/empire fake) come
    from a DIRECT fused ||row - v|| pass (no cancellation); scaled rows
    (reverse's -factor, crash's 0) use the expansion s^2*|row|^2 -
    2*s*<row, v> + |v|^2, clamped at 0, whose terms only add for the
    attacks that produce them.

    Non-finite guard is ROW-level here (a row with any non-finite entry
    gets clip weight 0, i.e. votes the current center wholesale — matching
    the where-path exactly for fully-poisoned rows like the fw=1 lie NaN
    fake; the flat path's entry-level guard differs only for PARTIALLY
    non-finite rows, a regime no deterministic attack produces).

    bf16 drift note (ADVICE r5 #5): both paths now SUBTRACT at f32 (the
    where-path casts its median init to f32, and carried centers are f32
    by construction), so the radii agree to f32 rounding — but the update
    reductions still associate differently (this path's weighted matvec
    accumulates bf16 rows into f32; the where-path means f32 deviations),
    so under a bf16 pipeline the two trajectories agree only to bf16
    rounding, not bitwise. Exact-parity tests pin f32; the bf16 row in
    tests/test_fold.py pins the agreed tolerance.
    """
    import numpy as np

    rows = ext_stack.shape[0]
    rmap = np.asarray(row_map)
    scales = np.asarray(row_scale, np.float32)
    n = rmap.size
    eps = jnp.asarray(1e-12, jnp.float32)
    finite = jnp.isfinite(ext_stack)
    x_safe = jnp.where(finite, ext_stack, 0)
    row_bad = jnp.any(~finite, axis=1)
    unit_np = scales == 1.0
    all_unit = bool(unit_np.all())  # static: lie/empire fold plans
    # Crash's zero scales degenerate the expansion to ||v||^2 — no stack
    # passes needed for them either (the general sq/dot algebra is only
    # for exotic scale values like reverse's -factor).
    zero_or_unit = bool((scales[~unit_np] == 0.0).all())
    sq = None
    if not (all_unit or zero_or_unit):
        sq = jnp.sum(
            jnp.square(x_safe.astype(jnp.float32)), axis=1
        )  # (rows,), iteration-invariant; only scaled rows need it
    unit = jnp.asarray(unit_np)
    s_log = jnp.asarray(scales)
    if center is None:
        # Remapped-row Pallas median: the robust init sees the POISONED
        # logical rows without them ever existing (ops row_map/row_scale).
        # f32, mirroring the where-path's init cast (see `aggregate`).
        from .. import ops

        center = ops.coordinate_median(
            ext_stack, row_map=rmap, row_scale=scales
        ).astype(jnp.float32)
    bad_log = row_bad[rmap] & (s_log != 0)
    # Shared-subtraction-dtype contract (ADVICE r5): BOTH paths iterate
    # the center at f32 regardless of how it arrived — `aggregate` casts
    # the where-path's center (median init or caller-supplied) and this
    # cast is its fold twin. Without it a bf16 caller-supplied center
    # would round through bf16 between iterations here while the
    # where-path kept f32, drifting the radii and tau per iteration. f32
    # (not quantize-to-stack-dtype) is the chosen direction because the
    # production carried center (TrainState.gar_state) is f32 by
    # construction and must not round through the narrow pipeline.
    v = jnp.asarray(center).astype(jnp.float32)
    for _ in range(iters):
        vf = v.astype(jnp.float32)
        # ONE fused read of the stack: ||row - v||^2 (and <row, v> only
        # when some scale != 1 — lie/empire plans are all-unit, statically).
        dev = x_safe.astype(jnp.float32) - vf[None, :]
        nsq_direct = jnp.sum(dev * dev, axis=1)
        if all_unit:
            nsq_log = nsq_direct[rmap]
        elif zero_or_unit:
            vsq = jnp.sum(vf * vf)
            nsq_log = jnp.where(unit, nsq_direct[rmap], vsq)
        else:
            vsq = jnp.sum(vf * vf)
            dot = jnp.sum(x_safe.astype(jnp.float32) * vf[None, :], axis=1)
            nsq_log = jnp.where(
                unit,
                nsq_direct[rmap],
                jnp.maximum(
                    s_log * s_log * sq[rmap] - 2.0 * s_log * dot[rmap]
                    + vsq,
                    0.0,
                ),
            )
        # Non-finite LOGICAL rows (a zero-scaled crash row is exactly the
        # zero vector — finite — whatever the raw row holds): the
        # where-path's nan_to_num gives them dev = 0, i.e. RADIUS 0 — the
        # zero must enter the tau median too, not ||v|| from the sanitized
        # buffer (ADVICE-of-record: confirmed tau shift otherwise).
        nsq_log = jnp.where(bad_log, 0.0, nsq_log)
        norms = jnp.sqrt(nsq_log)
        tau_l = jnp.median(norms) if tau is None else jnp.asarray(
            tau, jnp.float32
        )
        clip = jnp.minimum(1.0, tau_l / jnp.maximum(norms, eps))
        # clip = 0 for bad rows reproduces the where-path contribution
        # exactly: its clip * dev term is 0 either way.
        clip = jnp.where(bad_log, 0.0, clip)
        w_log = clip * s_log / n                     # logical row weights
        w_phys = jnp.zeros((rows,), jnp.float32).at[rmap].add(w_log)
        v = (
            v.astype(jnp.float32) * (1.0 - jnp.sum(clip) / n)
            + jnp.matmul(
                w_phys.astype(ext_stack.dtype), x_safe,
                preferred_element_type=jnp.float32,
            )
        ).astype(v.dtype)
    return v


def check(gradients, f=0, **kwargs):
    n = num_gradients(gradients)
    if n < 1:
        return f"expected at least one gradient to aggregate, got {gradients!r}"
    if not isinstance(f, int) or f < 0 or n < 2 * f + 1:
        return (
            f"invalid number of Byzantine gradients to tolerate, got f = "
            f"{f!r}, expected 0 <= f <= {(n - 1) // 2}"
        )
    return None


def upper_bound(n, f, d):
    """Paper Thm. III: aggregation error O(sqrt(delta)) at fraction
    delta = f/n of Byzantine inputs (radius-normalized)."""
    return math.sqrt(f / n) if f else 1 / math.sqrt(n)


register("cclip", aggregate, check, upper_bound=upper_bound,
         tree_aggregate=tree_aggregate,
         fold_flat_aggregate=fold_flat_aggregate,
         stateful_center=True)

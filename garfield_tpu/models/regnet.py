"""RegNetX (counterpart of garfieldpp/models/regnet.py): grouped bottleneck
stages with SE option."""

import flax.linen as nn
import jax.numpy as jnp

from ._layers import conv, conv1x1, global_avg_pool, norm


class RegNetBlock(nn.Module):
    w_out: int
    stride: int
    group_width: int
    bottleneck_ratio: int = 1
    se_ratio: float = 0.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        w_b = int(round(self.w_out / self.bottleneck_ratio))
        groups = w_b // self.group_width
        out = nn.relu(norm(train, dtype=d)(conv1x1(w_b, dtype=d)(x)))
        out = nn.relu(norm(train, dtype=d)(
            conv(w_b, 3, self.stride, padding=1, groups=groups, dtype=d)(out)))
        if self.se_ratio > 0:
            se = global_avg_pool(out)
            se = nn.relu(nn.Dense(int(x.shape[-1] * self.se_ratio), dtype=d)(se))
            se = nn.sigmoid(nn.Dense(w_b, dtype=d)(se))
            out = out * se[:, None, None, :]
        out = norm(train, dtype=d)(conv1x1(self.w_out, dtype=d)(out))
        if self.stride != 1 or x.shape[-1] != self.w_out:
            x = norm(train, dtype=d)(
                conv1x1(self.w_out, stride=self.stride, dtype=d)(x))
        return nn.relu(out + x)


class RegNet(nn.Module):
    depths: tuple
    widths: tuple
    strides: tuple
    group_width: int
    bottleneck_ratio: int = 1
    se_ratio: float = 0.0
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        x = nn.relu(norm(train, dtype=d)(conv(64, 3, 1, padding=1, dtype=d)(x)))
        for stage in range(len(self.depths)):
            for i in range(self.depths[stage]):
                stride = self.strides[stage] if i == 0 else 1
                x = RegNetBlock(self.widths[stage], stride, self.group_width,
                                self.bottleneck_ratio, self.se_ratio,
                                dtype=d)(x, train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=d)(x)


def RegNetX_200MF(num_classes=10, dtype=jnp.float32):
    return RegNet((1, 1, 4, 7), (24, 56, 152, 368), (1, 1, 2, 2), 8,
                  1, 0.0, num_classes, dtype)


def RegNetX_400MF(num_classes=10, dtype=jnp.float32):
    return RegNet((1, 2, 7, 12), (32, 64, 160, 384), (1, 1, 2, 2), 16,
                  1, 0.0, num_classes, dtype)


def RegNetY_400MF(num_classes=10, dtype=jnp.float32):
    return RegNet((1, 2, 7, 12), (32, 64, 160, 384), (1, 1, 2, 2), 16,
                  1, 0.25, num_classes, dtype)

"""North-star benchmark: Byzantine-resilient SGD steps/sec/chip.

Config (BASELINE.md measurement plan, mirroring Aggregathor/run_exp.sh:5-14):
ResNet-18 / CIFAR-10, 8 logical workers folded onto the available chip(s),
batch 25/worker, Multi-Krum with f=2 under the "little is enough" lie attack
(byzWorker.py:108-125) — i.e. the full hot path: per-worker fwd+bwd,
all_gather, on-device attack injection, O(n^2 d) Krum scoring, SGD update,
all inside one jit'd SPMD program.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu"}.
``vs_baseline`` divides by ``BASELINE.json.published.steps_per_sec_per_chip``
— the reference repo publishes no numbers (SURVEY §6), so that slot holds
this repo's own best driver-recorded measurement (BENCH_r01: 50.9139) and
acts as a ratchet: every round must beat the last. ``mfu`` is model-FLOPs
utilization: XLA-reported flops of the compiled step (fallback: analytic
ResNet-18 estimate) / measured step time / the chip's peak bf16 FLOP/s.

Env knobs: GARFIELD_BENCH_STEPS (timed steps, default 20),
GARFIELD_BENCH_WORKERS, GARFIELD_BENCH_F, GARFIELD_BENCH_BATCH,
GARFIELD_BENCH_GAR / GARFIELD_BENCH_ATTACK (rule/attack for off-default
table rows, e.g. average + none for the fault-free row; the official
metric name is emitted only for the default krum + lie config),
GARFIELD_BENCH_ATTEMPTS (transient-failure retries, default 5),
GARFIELD_BENCH_TRIALS (independent timed trials, default 4 — the shared
chip's run-to-run variance spikes 1.5-4x for stretches, so the reported
value is the BEST trial: closest to the machine's actual capability and
the standard guard against co-tenant noise),
GARFIELD_BENCH_F32_GAR (set to disable the default bf16 aggregation
pipeline on TPU and run the GAR phase at full width),
GARFIELD_BENCH_CHUNK (K steps scanned on device per dispatch via
core.make_chunked_step; per-step time = chunk_time / K; the JSON line
carries chunk_steps so BENCH rows stay attributable).

The tunneled backend can drop a single HTTP response mid-compile
("remote_compile: read body: response body closed" — see BENCH_r02.json);
compile + warmup + timing therefore run under a retry loop with exponential
backoff, and the persistent XLA compile cache is enabled so a retry (or a
driver re-run) does not pay the full ~30 s recompile window again.
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# Peak dense bf16 FLOP/s per chip by device kind (public spec sheets).
_PEAK_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def _step_flops(compiled, axis_size, num_workers, batch, chunk=1):
    """Global FLOPs of one train step (XLA cost model; analytic fallback).

    ``cost_analysis`` reports the partitioned per-device module, so the XLA
    number is scaled by ``axis_size`` to a global count — and divided by
    ``chunk`` when the compiled module is a K-step chunked program (the
    per-step quantity is what MFU needs). The fallback is the standard
    CIFAR-style ResNet-18 count: ~0.557 GMACs = 1.11 GFLOPs forward per
    32x32 image, x3 for fwd+bwd, x total images (already global, already
    per step).
    """
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        if flops > 0:
            return flops * axis_size / chunk
    except Exception:
        pass
    return 3 * 1.11e9 * num_workers * batch


def _measure(step_fn, init_fn, x, y, steps, chunk=1):
    """Compile, warm up, and time one configuration. Raises on any backend
    failure; the caller retries. Returns (dt_per_step, compiled).

    ``chunk > 1`` (GARFIELD_BENCH_CHUNK) times the CHUNKED program
    (core.make_chunked_step): each dispatch scans ``chunk`` steps on
    device, the readback syncs once per chunk, and the honest per-step
    time is chunk_time / chunk. The paired-reps estimator composes
    naturally — a chunk IS a dependency chain, so the k-dispatch chain it
    times is a k*chunk-step chain and the constant sync cost still
    cancels in the difference (PERF.md "Timing methodology")."""
    import numpy as np

    from garfield_tpu.parallel import core as core_lib
    from garfield_tpu.utils import profiling

    state = init_fn(jax.random.PRNGKey(1234), x[0])

    # AOT-compile once: the same executable serves warmup, timing, and the
    # cost-analysis read — no second compile after timing finishes.
    if chunk > 1:
        # One-slot batch axis: the bench reuses a single synthetic batch,
        # so the on-device index b = (i0 + k) % 1 always selects it.
        xs, ys = x[:, None], y[:, None]
        chunked = core_lib.make_chunked_step(step_fn, chunk, 1)
        compiled = chunked.lower(state, xs, ys, jnp.int32(0)).compile()
        call = lambda st: compiled(st, xs, ys, jnp.int32(0))
    else:
        compiled = step_fn.lower(state, x, y).compile()
        call = lambda st: compiled(st, x, y)

    for _ in range(3):  # warmup: stabilize clocks
        state, metrics = call(state)
    # host readback: drains the queue (on tunneled backends
    # block_until_ready can return before the device finishes; a readback
    # is the only reliable sync, at a constant queue-flush cost)
    float(np.asarray(metrics["loss"]).reshape(-1)[-1])

    state_box = [state]

    def timed(k):
        state = state_box[0]
        t0 = time.perf_counter()
        for _ in range(k):
            state, metrics = call(state)
        float(np.asarray(metrics["loss"]).reshape(-1)[-1])
        state_box[0] = state
        return time.perf_counter() - t0

    # Paired-reps timing: the constant sync cost cancels in the difference
    # (utils/profiling.paired_reps; see PERF.md "Timing methodology").
    dt = profiling.paired_reps(timed, steps)
    if dt is None:  # below noise floor at this rep count: lengthen the chain
        dt = profiling.paired_reps(timed, steps * 4)
    if dt is None:
        # Last resort: single-run wall time / steps. Includes the constant
        # sync cost, so it UNDER-reports throughput — conservative, never
        # the ~1/floor fantasy number the old clamp could produce.
        dt = timed(steps) / steps
    return dt / chunk, compiled


def _emit_jsonl(fields):
    """Append the schema-versioned JSONL twin of the stdout line
    (garfield_tpu.telemetry.exporters) — the format BENCH_r* artifacts
    adopt, validated by the tier-1 schema check so a malformed capture
    fails loudly instead of going dark. Path: GARFIELD_BENCH_JSONL
    (default ./bench_telemetry.jsonl; empty string disables). Best-effort:
    the stdout JSON contract stays total either way."""
    try:
        from garfield_tpu.telemetry import exporters

        path = os.environ.get("GARFIELD_BENCH_JSONL", "bench_telemetry.jsonl")
        if path:
            exporters.append_record(
                path,
                exporters.make_record(
                    "bench",
                    metric=fields.get("metric", "error"),
                    value=fields.get("value"),
                    unit=fields.get("unit"),
                    vs_baseline=fields.get("vs_baseline"),
                    mfu=fields.get("mfu"),
                    chunk_steps=fields.get("chunk_steps"),
                    error=fields.get("error"),
                    backend_outage=fields.get("backend_outage"),
                    t=time.time(),
                ),
            )
    except Exception as e:  # noqa: BLE001 — telemetry never fails the bench
        print(f"bench: JSONL emission failed: {e}", file=sys.stderr)


def main():
    """Entry point: run the benchmark, emitting ONE JSON line no matter
    what. A dead backend or any uncaught error becomes a parseable
    ``{"error": ...}`` object instead of a hang or a traceback (VERDICT r5
    #1a: BENCH_r05 died rc=1 with ``parsed: null`` when the TPU tunnel was
    down at capture time). Each line also lands as a schema-versioned
    JSONL record (``_emit_jsonl``)."""
    try:
        _main_impl()
    except Exception as e:  # noqa: BLE001 — the JSON contract is total
        err = {"error": f"{type(e).__name__}: {e}"}
        # Machine-readable outage stamp: BENCH_r05/MULTICHIP_r05 died to a
        # TPU-tunnel outage and the ratchet tooling had to be TOLD by a
        # human that those lines were environment, not regression. A
        # transient backend/tunnel failure now marks itself so future
        # ratchets filter outage captures mechanically (BASELINE.md).
        try:
            from garfield_tpu.utils import profiling as _prof

            err["backend_outage"] = bool(
                _prof.is_transient_backend_error(e)
                or "backend" in str(e).lower()
            )
        except Exception:  # noqa: BLE001 — stamping must not mask the error
            pass
        print(json.dumps(err))
        _emit_jsonl(err)
        sys.exit(0)


def _main_impl():
    import optax

    from garfield_tpu import models
    from garfield_tpu.parallel import aggregathor, mesh as mesh_lib
    from garfield_tpu.utils import profiling, selectors

    # Never initialize the default backend in-process first: with the TPU
    # tunnel down, jax.devices() blocks forever inside plugin init. Probe
    # the device count in a short-timeout subprocess and fall back to the
    # CPU platform on any failure — the run still emits a parseable line
    # (flagged non-official by the platform guard below).
    if os.environ.get("GARFIELD_FORCE_CPU_DRYRUN"):
        jax.config.update("jax_platforms", "cpu")
    elif profiling.probe_device_count() is None:
        print(
            "bench: backend probe failed or timed out; falling back to CPU",
            file=sys.stderr,
        )
        jax.config.update("jax_platforms", "cpu")

    # Persistent compile cache: a retry (or driver re-run) after a transient
    # tunnel failure must not re-enter the full-recompile flake window.
    profiling.enable_compile_cache()

    num_workers = int(os.environ.get("GARFIELD_BENCH_WORKERS", 8))
    f = int(os.environ.get("GARFIELD_BENCH_F", 2))
    gar_name = os.environ.get("GARFIELD_BENCH_GAR", "krum")
    attack_name = os.environ.get("GARFIELD_BENCH_ATTACK", "lie")
    if attack_name in ("", "none"):
        attack_name = None
    batch = int(os.environ.get("GARFIELD_BENCH_BATCH", 25))
    steps = max(1, int(os.environ.get("GARFIELD_BENCH_STEPS", 20)))
    # On-device step chunking (core.make_chunked_step): K steps per
    # dispatch, per-step time = chunk_time / K. 1 = the per-step program.
    chunk = max(1, int(os.environ.get("GARFIELD_BENCH_CHUNK", 1)))

    platform = jax.devices()[0].platform
    # bf16 compute routes conv/matmul onto the MXU; params stay f32.
    dtype = jnp.bfloat16 if platform == "tpu" else jnp.float32
    module = models.select_model("resnet18", "cifar10", dtype=dtype)
    loss_fn = selectors.select_loss("cross-entropy")
    # Reference AggregaThor defaults: SGD lr 0.2, momentum 0.9, wd 5e-4
    # (Aggregathor/run_exp.sh:39-40).
    opt = selectors.select_optimizer(
        "sgd", lr=0.2, momentum=0.9, weight_decay=5e-4
    )

    n_dev = len(jax.devices())
    axis_size = n_dev if num_workers % n_dev == 0 else 1
    mesh = mesh_lib.make_mesh(
        {"workers": axis_size}, devices=jax.devices()[:axis_size]
    )
    init_fn, step_fn, _ = aggregathor.make_trainer(
        module, loss_fn, opt, gar_name,
        num_workers=num_workers, f=f, attack=attack_name, mesh=mesh,
        # bf16 aggregation pipeline on TPU (half the HBM/ICI bytes through
        # attack+gather+GAR; Gram still accumulates f32): +~2% on one chip
        # (PERF.md r3), the honest TPU-first default. GARFIELD_BENCH_F32_GAR
        # restores the full-width pipeline.
        gar_dtype=(
            jnp.bfloat16
            if platform == "tpu"
            and not os.environ.get("GARFIELD_BENCH_F32_GAR")
            else None
        ),
    )

    rng = np.random.default_rng(1234)
    x = jnp.asarray(
        rng.standard_normal((num_workers, batch, 32, 32, 3)), jnp.float32
    )
    y = jnp.asarray(rng.integers(0, 10, (num_workers, batch)), jnp.int32)

    # Retry loop: the tunnel occasionally drops a response mid-compile or
    # mid-dispatch (BENCH_r02.json died exactly there). Each attempt runs a
    # fresh lower().compile(); the persistent cache makes that near-free when
    # the previous attempt got past compilation (and across driver re-runs).
    attempts = max(1, int(os.environ.get("GARFIELD_BENCH_ATTEMPTS", 5)))
    trials = max(1, int(os.environ.get("GARFIELD_BENCH_TRIALS", 4)))
    dt = compiled = None
    for trial in range(trials):
        trial_dt = None
        for attempt in range(attempts):
            try:
                trial_dt, compiled = _measure(
                    step_fn, init_fn, x, y, steps, chunk=chunk
                )
                break
            except Exception as e:
                # Only transient tunnel/transport failures earn a retry;
                # deterministic errors (lowering, shapes, OOM) surface at
                # once — UNLESS an earlier trial already measured, in which
                # case its number must survive (a later-trial failure must
                # never cost the run the record it already has).
                transient = profiling.is_transient_backend_error(e)
                if attempt == attempts - 1 or not transient:
                    if dt is not None:
                        print(
                            f"bench trial {trial + 1}/{trials} abandoned "
                            f"({type(e).__name__}: {e}); keeping best of "
                            f"{trial} completed trial(s)",
                            file=sys.stderr,
                        )
                        trial_dt = None
                        break
                    raise
                delay = 2.0 ** attempt
                print(
                    f"bench attempt {attempt + 1}/{attempts} failed "
                    f"({type(e).__name__}: {e}); retrying in {delay:.0f}s",
                    file=sys.stderr,
                )
                time.sleep(delay)
        if trial_dt is None:
            break  # a trial was abandoned with a prior record in hand
        print(
            f"bench trial {trial + 1}/{trials}: "
            f"{1.0 / trial_dt / axis_size:.2f} steps/s/chip",
            file=sys.stderr,
        )
        dt = trial_dt if dt is None else min(dt, trial_dt)

    steps_per_sec_per_chip = 1.0 / dt / axis_size
    flops = _step_flops(compiled, axis_size, num_workers, batch, chunk=chunk)
    peak = _PEAK_BF16.get(jax.devices()[0].device_kind)
    mfu = (flops / dt / (peak * axis_size)) if peak else None
    baseline = None
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as fp:
            baseline = json.load(fp).get("published", {}).get(
                "steps_per_sec_per_chip"
            )
    except OSError:
        pass
    vs = steps_per_sec_per_chip / baseline if baseline else None
    # One format string for every config: the official north-star name
    # ("...w8_f2_krum_lie") falls out of the defaults. vs_baseline is only
    # meaningful against the published krum/lie batch-25 record, so any
    # off-default knob (rule, attack, cohort, batch, f32 pipeline) reports
    # it as None instead of an apples-to-oranges ratio.
    metric = (
        f"byzsgd_steps_per_sec_per_chip_resnet18_cifar10_"
        f"w{num_workers}_f{f}_{gar_name}_{attack_name or 'none'}"
    )
    official = (
        (gar_name, attack_name, num_workers, f, batch)
        == ("krum", "lie", 8, 2, 25)
        and not os.environ.get("GARFIELD_BENCH_F32_GAR")
        and platform == "tpu"  # CPU fallback runs f32 — not the record's config
    )
    if not official:
        vs = None
    result = {
        "metric": metric,
        "value": round(steps_per_sec_per_chip, 4),
        "unit": "steps/s/chip",
        "vs_baseline": round(vs, 4) if vs is not None else None,
        "mfu": round(mfu, 4) if mfu is not None else None,
        # Attribution for BENCH_r06+ rows: how many steps each dispatch
        # scanned on device (1 = the classic per-step program).
        "chunk_steps": chunk,
    }
    print(json.dumps(result))
    _emit_jsonl(result)


if __name__ == "__main__":
    main()

#!/usr/bin/env bash
# Cross-process cluster fan-out: the TRUE wait-n-f deployment
# (apps/cluster.py over PeerExchange), one OS process per node.
#
# Counterpart of the reference's per-app run_exp.sh ssh loops
# (Aggregathor/run_exp.sh:41-60) for the host-driver mode: the FIRST host
# in <hosts_file> is the trusted PS (rank 0, AggregaThor SSMW), the rest
# are workers; each process binds its own "host:port" endpoint from the
# shared cluster config and exchanges models/gradients over TCP + the
# native MRMW register. Unlike run_exp.sh (one jax.distributed
# multi-controller program), processes here are INDEPENDENT — kill a
# worker and the PS keeps training on the q = n_w - fw fastest gradients.
#
# Usage:
#   scripts/run_cluster.sh <hosts_file> [app args...]
# e.g.
#   scripts/run_cluster.sh nodes --dataset cifar10 --model resnet18 \
#       --batch 25 --fw 2 --gar median --num_iter 10000
#
# Each line of <hosts_file> is "host[:port]" (default port 7600+rank).
# Requires passwordless ssh and this repo at the same path on every host.
set -euo pipefail

HOSTS_FILE=${1:?hosts file}
shift 1

mapfile -t HOSTS < <(grep -v '^#' "$HOSTS_FILE" | sed '/^$/d')
NUM=${#HOSTS[@]}
(( NUM >= 2 )) || { echo "need >= 2 hosts (1 PS + workers)"; exit 1; }
REPO_DIR=$(cd "$(dirname "$0")/.." && pwd)

# Normalize "host" -> "host:port" with a default per-rank port.
ENDPOINTS=()
for i in "${!HOSTS[@]}"; do
  H=${HOSTS[$i]}
  [[ "$H" == *:* ]] || H="$H:$((7600 + i))"
  ENDPOINTS+=("$H")
done

CONFIG_JSON=$(python3 - "${ENDPOINTS[@]}" <<'PY'
import json, sys
eps = sys.argv[1:]
print(json.dumps({
    "cluster": {"ps": eps[:1], "worker": eps[1:]},
    "task": {"type": "ps", "index": 0},
}))
PY
)

APP_ARGS=""
for arg in "$@"; do
  APP_ARGS+=$(printf ' %q' "$arg")
done

echo "launching cluster: PS on ${ENDPOINTS[0]}, $((NUM - 1)) workers"
for i in "${!ENDPOINTS[@]}"; do
  HOST=${ENDPOINTS[$i]%%:*}
  if (( i == 0 )); then TASK="ps:0"; else TASK="worker:$((i - 1))"; fi
  ssh -o StrictHostKeyChecking=no "$HOST" \
    "cd '$REPO_DIR' && printf '%s' '$CONFIG_JSON' > /tmp/garfield_cluster.json && \
     nohup python3 -m garfield_tpu.apps.aggregathor \
       --cluster /tmp/garfield_cluster.json --task $TASK$APP_ARGS \
     > run_cluster_${TASK/:/_}.log 2>&1 &" &
done
wait
echo "all ranks launched; logs: run_cluster_*.log on each host"

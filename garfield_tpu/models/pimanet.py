"""MLP for the Pima Indians Diabetes task (garfieldpp/models/pimanet.py:4-18):
8 -> 64 -> 64 -> num_classes with a sigmoid output, trained with BCE."""

import flax.linen as nn
import jax.numpy as jnp


class PimaNet(nn.Module):
    num_classes: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(64, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(64, dtype=self.dtype)(x))
        return nn.sigmoid(nn.Dense(self.num_classes, dtype=self.dtype)(x))

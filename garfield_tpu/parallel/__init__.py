"""SPMD parallel training core: mesh, roles-as-functions, and the three
Byzantine-resilient topologies of the reference (SURVEY §2.3):

  - ``aggregathor`` — single trusted PS, n workers (SSMW;
    pytorch_impl/applications/Aggregathor/); ``granularity="layer"`` gives
    the Garfield_CC per-parameter collective semantics; num_workers=1, f=0
    degenerates to the Centralized baseline.
  - ``byzsgd``      — replicated Byzantine PS (MSMW / GuanYu;
    pytorch_impl/applications/ByzSGD/).
  - ``learn``       — fully decentralized gossip (LEARN;
    pytorch_impl/applications/LEARN/).

Each exposes ``make_trainer(...) -> (init_fn, step_fn, eval_fn)`` with
``step_fn`` one jit'd SPMD program over the ICI mesh — the reference's
RPC / NCCL / gRPC round trips (SURVEY §2.3 comm-backend row) appear only as
XLA all_gather/psum collectives inside it.
"""

import jax
import jax.numpy as jnp
import numpy as np

from . import aggregathor, byzsgd, core, learn, mesh
from .core import TrainState, default_byz_mask, make_worker_fns
from .mesh import make_mesh

__all__ = [
    "aggregathor",
    "byzsgd",
    "learn",
    "core",
    "mesh",
    "TrainState",
    "default_byz_mask",
    "make_worker_fns",
    "make_mesh",
    "topologies",
    "EvalSet",
    "compute_accuracy",
    "compute_accuracy_async",
    "targeted_eval",
]

topologies = {
    "centralized": aggregathor,  # num_workers=1, f=0 (P16)
    "aggregathor": aggregathor,  # P17
    "byzsgd": byzsgd,  # P18
    "learn": learn,  # P19
    "garfield_cc": aggregathor,  # P20 — granularity="layer"
}


class EvalSet:
    """Device-stacked test set evaluated by ONE jitted scanned program.

    The list-of-batches eval path dispatches one program per test batch
    (hundreds for MNIST/CIFAR) — each dispatch costs real latency on a
    tunneled backend. EvalSet uploads the stacked (B, bsz, ...) arrays once
    and folds the whole accuracy count into a single ``lax.scan`` program
    per ``eval_fn``. Pass it anywhere ``test_batches`` is accepted.
    """

    def __init__(self, test_batches, *, binary=False):
        # DatasetManager keeps the ragged tail batch (data/__init__.py), so
        # stack the uniform prefix and keep differently-shaped stragglers on
        # a per-batch side path.
        batches = [
            (jnp.asarray(x), jnp.asarray(np.asarray(y).reshape(-1)))
            for x, y in test_batches
        ]
        if not batches:
            raise ValueError(
                "EvalSet needs at least one test batch (got an empty "
                "test_batches); check the dataset/test split configuration"
            )
        shape0 = batches[0][0].shape
        uniform = [b for b in batches if b[0].shape == shape0]
        self.ragged = [b for b in batches if b[0].shape != shape0]
        self.xs = jnp.stack([x for x, _ in uniform])
        self.ys = jnp.stack([y for _, y in uniform])
        self.binary = binary
        self.total = int(self.ys.size) + sum(
            int(y.size) for _, y in self.ragged
        )
        self._jitted = {}

    def _batch_hits(self, state, eval_fn, x, y):
        logits = eval_fn(state, x)
        if self.binary:
            pred = (logits.reshape(-1) > 0.5).astype(y.dtype)
            return jnp.sum(pred == y).astype(jnp.int32)
        return jnp.sum(logits.argmax(-1) == y).astype(jnp.int32)

    def counts(self, state, eval_fn):
        """(correct device scalar, total) — no host sync."""
        key = id(eval_fn)
        fn = self._jitted.get(key)
        if fn is None:

            def count(state, xs, ys):
                def body(correct, xy):
                    x, y = xy
                    return correct + self._batch_hits(state, eval_fn, x, y), None

                correct, _ = jax.lax.scan(
                    body, jnp.zeros((), jnp.int32), (xs, ys)
                )
                return correct

            fn = jax.jit(count)
            self._jitted[key] = fn
        correct = fn(state, self.xs, self.ys)
        for x, y in self.ragged:
            correct = correct + self._batch_hits(state, eval_fn, x, y)
        return correct, self.total


def _accuracy_counts(state, eval_fn, test_batches, *, binary=False):
    """Enqueue the full eval pass; return (correct, total) with ``correct``
    a DEVICE scalar — no host synchronization happens here.

    The per-batch compare+sum runs on device, so the caller decides when to
    pay the host readback (which on tunneled backends costs ~0.1 s per
    conversion — the old per-batch ``np.asarray`` made inline eval stall
    the step stream for seconds). ``test_batches`` may be an ``EvalSet``
    (one scanned program) or a list of (x, y) batches.
    """
    if isinstance(test_batches, EvalSet):
        return test_batches.counts(state, eval_fn)
    correct = jnp.zeros((), jnp.int32)
    total = 0
    for x, y in test_batches:
        logits = eval_fn(state, jnp.asarray(x))
        y_np = np.asarray(y).reshape(-1)
        yj = jnp.asarray(y_np)
        if binary:
            # pima path: sigmoid output, threshold 0.5 (demo.py accuracy).
            pred = (logits.reshape(-1) > 0.5).astype(yj.dtype)
            correct = correct + jnp.sum(pred == yj)
        else:
            correct = correct + jnp.sum(logits.argmax(-1) == yj)
        total += int(y_np.shape[0])
    return correct, total


def compute_accuracy(state, eval_fn, test_batches, *, binary=False):
    """Top-1 accuracy over a list of (x, y) test batches.

    Counterpart of ``Server.compute_accuracy`` (server.py:235-254) / the TF
    ``compute_accuracy`` (tensorflow_impl/libs/server.py:152-163). ``binary``
    follows the pima path (single sigmoid logit, byzWorker-era threshold 0.5).
    """
    correct, total = _accuracy_counts(
        state, eval_fn, test_batches, binary=binary
    )
    return int(correct) / max(total, 1)


def compute_accuracy_async(state, eval_fn, test_batches, *, binary=False,
                           on_done=None, after=None):
    """Overlapped accuracy: enqueue the eval pass now, pay the host readback
    in a side thread — the SPMD analog of the reference's accuracy thread
    (Aggregathor/trainer.py:251-264).

    All device work is dispatched AND completed (``block_until_ready``)
    in the caller's thread before returning: a donating ``step_fn(state)``
    call issued while eval consumers of ``state`` are still pending ABORTS
    the XLA:CPU runtime (observed as a Fatal Python error in the app test
    suite) — enqueue ordering alone is not a safety guarantee. What moves
    off the training thread is the device->host scalar readback, which on
    tunneled backends is the dominant cost (~0.1 s per conversion) and the
    one ``block_until_ready`` does not cover there.

    ``after``: a previous thread from this function; the new thread waits
    for it before reporting, so successive reports stay in request order.
    Returns the started (daemon) thread; its ``.exc`` attribute holds any
    exception the readback or ``on_done`` raised — join it and re-raise at
    exit, or the failure is silently dropped.
    """
    import threading

    correct, total = _accuracy_counts(
        state, eval_fn, test_batches, binary=binary
    )
    # Drain the eval's reads of `state` before the caller donates it.
    jax.block_until_ready(correct)
    acc_now = None
    if jax.default_backend() == "cpu":
        # XLA:CPU intermittently aborts when a background host readback
        # races the training thread's dispatches (seen as a Fatal Python
        # error in the app suite). A local readback is ~free, so complete
        # it inline on CPU and keep only the ordered reporting threaded;
        # the overlap matters on tunneled device backends, where the
        # readback is the ~0.1 s cost this function exists to move.
        acc_now = int(correct) / max(total, 1)

    def _finalize():
        try:
            if after is not None:
                after.join()
            acc = (int(correct) / max(total, 1)  # the one host readback
                   if acc_now is None else acc_now)
            if on_done is not None:
                on_done(acc)
        except BaseException as exc:  # surfaced by the caller at join
            t.exc = exc

    t = threading.Thread(target=_finalize, daemon=True)
    t.exc = None
    t.start()
    return t


def _eval_predictions(state, eval_fn, eval_set, x_transform=None):
    """Host-side (predictions, labels) over an ``EvalSet`` (uniform stack
    + ragged tail). ``x_transform`` optionally rewrites each input batch
    (the backdoor trigger stamp) before the forward pass. Eval-time only
    — one readback per call, never on the training path."""
    preds, labels = [], []

    def one(x, y):
        if x_transform is not None:
            x = x_transform(x)
        logits = eval_fn(state, x)
        if eval_set.binary:
            p = (np.asarray(logits).reshape(-1) > 0.5).astype(np.int64)
        else:
            p = np.asarray(logits).argmax(-1).astype(np.int64).reshape(-1)
        preds.append(p)
        labels.append(np.asarray(y).reshape(-1).astype(np.int64))

    for b in range(int(eval_set.xs.shape[0])):
        one(eval_set.xs[b], eval_set.ys[b])
    for x, y in eval_set.ragged:
        one(x, y)
    return np.concatenate(preds), np.concatenate(labels)


def targeted_eval(state, eval_fn, eval_set, *, source, target,
                  trigger_cfg=None):
    """Per-class accuracy + targeted attack-success-rate (DESIGN.md §17).

    The divergence-based audit plane is blind to a targeted attack —
    global accuracy barely moves — so success is measured where the
    adversary defined it:

      - ``per_class``: top-1 accuracy per true class (the v8 per-class
        eval digest; a labelflip shows up as a crater at ``source``);
      - ``confusion``: P(pred == target | true == source) — the
        labelflip attack-success-rate, whose CLEAN value is the baseline
        the DEFBENCH bar is measured against;
      - ``asr`` (only with ``trigger_cfg``, a ``targeted.TargetedConfig``
        for the backdoor): the trigger is stamped on every NON-target
        test input and ``asr`` is the fraction that flips to ``target``
        — the BadNets success metric, computed with the SAME
        ``apply_trigger`` the poisoned training batches used;
      - ``asr_baseline``: the clean-model trigger-rate baseline row —
        ``P(pred == target | true != target)`` over the UNtriggered
        eval. A model that never saw the trigger still emits the target
        class at this chance rate when the trigger is stamped, so a raw
        ASR cell overstates the attack by exactly this floor; DEFBENCH
        reports ``asr - asr_baseline`` as the attributable lift
        (schema v9, validated).

    Returns a dict with those fields plus ``accuracy`` (global top-1).
    ``eval_set`` must be a ``parallel.EvalSet``.
    """
    from ..attacks import targeted as targeted_lib

    preds, labels = _eval_predictions(state, eval_fn, eval_set)
    classes = sorted(int(c) for c in np.unique(labels))
    per_class = {
        int(c): float((preds[labels == c] == c).mean())
        for c in classes if (labels == c).any()
    }
    src_mask = labels == int(source)
    confusion = (
        float((preds[src_mask] == int(target)).mean())
        if src_mask.any() else None
    )
    base_mask = labels != int(target)
    asr_baseline = (
        float((preds[base_mask] == int(target)).mean())
        if base_mask.any() else None
    )
    asr = None
    if trigger_cfg is not None:
        t_preds, t_labels = _eval_predictions(
            state, eval_fn, eval_set,
            x_transform=lambda x: targeted_lib.apply_trigger(
                trigger_cfg, jnp.asarray(x)
            ),
        )
        non_target = t_labels != int(target)
        asr = (
            float((t_preds[non_target] == int(target)).mean())
            if non_target.any() else None
        )
    return {
        "accuracy": float((preds == labels).mean()),
        "per_class": per_class,
        "source": int(source),
        "target": int(target),
        "confusion": confusion,
        "asr": asr,
        "asr_baseline": asr_baseline,
    }

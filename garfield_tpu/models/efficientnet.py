"""EfficientNet-B0 (counterpart of garfieldpp/models/efficientnet.py):
MBConv blocks with SE, swish activation, CIFAR-scale stem."""

import flax.linen as nn
import jax.numpy as jnp

from ._layers import conv, conv1x1, global_avg_pool, norm

# (expansion, out_planes, num_blocks, kernel, stride)
cfg_b0 = [(1, 16, 1, 3, 1), (6, 24, 2, 3, 2), (6, 40, 2, 5, 2),
          (6, 80, 3, 3, 2), (6, 112, 3, 5, 1), (6, 192, 4, 5, 2),
          (6, 320, 1, 3, 1)]


class MBConv(nn.Module):
    expansion: int
    out_planes: int
    kernel: int
    stride: int
    se_ratio: float = 0.25
    drop_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        in_planes = x.shape[-1]
        planes = self.expansion * in_planes
        out = x
        if self.expansion != 1:
            out = nn.swish(norm(train, dtype=d)(conv1x1(planes, dtype=d)(out)))
        out = nn.swish(norm(train, dtype=d)(
            conv(planes, self.kernel, self.stride,
                 padding=(self.kernel - 1) // 2, groups=planes, dtype=d)(out)))
        # squeeze-excite
        se = global_avg_pool(out)
        se = nn.swish(nn.Dense(max(1, int(in_planes * self.se_ratio)), dtype=d)(se))
        se = nn.sigmoid(nn.Dense(planes, dtype=d)(se))
        out = out * se[:, None, None, :]
        out = norm(train, dtype=d)(conv1x1(self.out_planes, dtype=d)(out))
        if self.stride == 1 and in_planes == self.out_planes:
            out = out + x
        return out


class EfficientNet(nn.Module):
    cfg: tuple = tuple(cfg_b0)
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        x = nn.swish(norm(train, dtype=d)(conv(32, 3, 1, padding=1, dtype=d)(x)))
        for expansion, out_planes, num_blocks, kernel, stride in self.cfg:
            for i in range(num_blocks):
                s = stride if i == 0 else 1
                x = MBConv(expansion, out_planes, kernel, s, dtype=d)(x, train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=d)(x)


def EfficientNetB0(num_classes=10, dtype=jnp.float32):
    return EfficientNet(tuple(cfg_b0), num_classes, dtype)
